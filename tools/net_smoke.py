"""NET smoke gate — run by tools/t1.sh.

Drives a 2-replica PROCESS fleet (real serve-engine child processes
behind unix-domain sockets) over a trace derived from the wmt_sliver
fixture and asserts the promotion-to-processes contract end to end:

- zero dropped requests, with cross-process token output identical to
  the in-process fleet on the same seeded trace,
- a replica SIGKILL'd mid-stream is evacuated (zero drops), restarted
  by the supervisor, and READMITTED over its re-bound socket — after
  which it serves again,
- the merged Perfetto export still links cross-process flows: at least
  one trace_id has spans on more than one OS process.
"""

import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from deeplearning_cfn_tpu.fleet.replica import ReplicaState
from deeplearning_cfn_tpu.fleet.router import (
    FleetOverloadError,
    NoReplicasError,
)
from deeplearning_cfn_tpu.metrics.jsonl import MetricsWriter
from deeplearning_cfn_tpu.net.bench import (
    _reference_tokens,
    _teardown,
    spawn_process_fleet,
)
from deeplearning_cfn_tpu.net.router import NetRouter
from deeplearning_cfn_tpu.obs.export import export_fleet_trace
from deeplearning_cfn_tpu.obs.sinks import JsonlSink
from deeplearning_cfn_tpu.serve.queue import OverloadError

GEOMETRY = dict(slots=2, src_len=8, max_new_tokens=4, queue_depth=16,
                decode_window=4, seed=0)


def _submit(rt, trace, prefix, max_new_tokens):
    rids = []
    for i, src in enumerate(trace):
        while True:
            try:
                rids.append(rt.submit(src, max_new_tokens=max_new_tokens,
                                      request_id=f"{prefix}{i}"))
                break
            except (FleetOverloadError, OverloadError, NoReplicasError):
                rt.step()
                time.sleep(0.01)
    return rids


def main() -> int:
    sliver = os.path.join("tests", "data", "wmt_sliver.de")
    with open(sliver, "rb") as fh:
        lines = [ln for ln in fh.read().splitlines() if ln.strip()]
    trace = [[3 + (b % 93) for b in ln[:8]] for ln in lines][:6]
    assert len(trace) >= 2, "wmt_sliver fixture too small for the gate"

    with tempfile.TemporaryDirectory() as root:
        sup, remotes = spawn_process_fleet(
            root, ["both", "both"], trace=True, max_restarts=1,
            warmup_src=trace[0], **GEOMETRY)
        router_writer = MetricsWriter(
            os.path.join(root, "router.jsonl"), also_stdout=False)
        try:
            rt = NetRouter(remotes, supervisor=sup)
            rt.trace_sink = JsonlSink(router_writer)
            for r in remotes:
                r.trace_sink = JsonlSink(MetricsWriter(
                    os.path.join(root, r.id, "client.jsonl"),
                    also_stdout=False))

            # -- phase A: cross-process token parity, zero drops ------
            rids = _submit(rt, trace, "q", GEOMETRY["max_new_tokens"])
            rt.run_until_drained(idle_timeout_s=60.0)
            assert rt.dropped_requests == 0, rt.stats()
            got = {rid: list(rt.result(rid)["tokens"]) for rid in rids}
            ref = _reference_tokens(
                trace, GEOMETRY["max_new_tokens"], 1,
                slots=GEOMETRY["slots"], src_len=GEOMETRY["src_len"],
                queue_depth=GEOMETRY["queue_depth"],
                decode_window=GEOMETRY["decode_window"],
                seed=GEOMETRY["seed"])
            assert got == ref, {"got": got, "ref": ref}

            # -- phase B: SIGKILL mid-stream → evacuate, zero drops ---
            rids_b = _submit(rt, trace, "k", 8)
            sup._replicas[1].handle._procs[0].proc.kill()
            rt.run_until_drained(idle_timeout_s=60.0)
            assert rt.dropped_requests == 0, rt.stats()
            assert all(rt.result(rid)["state"] == "done"
                       for rid in rids_b), [rt.result(r) for r in rids_b]

            # -- phase C: supervisor restart → socket readmission -----
            # Wait for the condition we assert: readmitted AND currently
            # healthy. A readmission can flap (reconnect verified, then
            # the next RPC finds the child mid-restart) — the contract
            # is that tending CONVERGES, not that it never retries.
            deadline = time.monotonic() + 120.0
            while (rt.reconnects < 1
                   or remotes[1].state is not ReplicaState.HEALTHY) \
                    and time.monotonic() < deadline:
                rt.step()
                time.sleep(0.05)
            assert rt.reconnects >= 1, "restarted replica never readmitted"
            assert remotes[1].state is ReplicaState.HEALTHY, \
                remotes[1].state
            rids_c = _submit(rt, trace[:2], "p",
                             GEOMETRY["max_new_tokens"])
            rt.run_until_drained(idle_timeout_s=60.0)
            assert rt.dropped_requests == 0, rt.stats()
            evacuations = rt.stats()["evacuations"]
            reconnects = rt.reconnects
            assert len(rids_c) == 2
        finally:
            _teardown(sup, remotes)
            router_writer.close()

        # -- merged Perfetto export: flows still cross processes ------
        out = os.path.join(root, "trace.json")
        s = export_fleet_trace(root, out)
        assert not s["problems"], s
        assert s["flow_events"] >= 1, s
        with open(out) as fh:
            events = json.load(fh)["traceEvents"]
        by_trace = {}
        for e in events:
            if e.get("ph") != "X":
                continue
            tid = (e.get("args") or {}).get("trace_id")
            if isinstance(tid, str):
                by_trace.setdefault(tid, set()).add(e.get("pid"))
        crossed = [t for t, pids in by_trace.items() if len(pids) > 1]
        assert crossed, {t: sorted(p) for t, p in by_trace.items()}

    print(f"NET_SMOKE=OK parity_requests={len(trace)} "
          f"evacuations={evacuations} reconnects={reconnects} "
          f"flow_events={s['flow_events']} "
          f"cross_process_traces={len(crossed)}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
