"""CHAOS_FLEET_SMOKE gate — run by tools/t1.sh.

Drives the committed chaos plans (tests/fixtures/chaos/) through tiny
fleets over the wmt_sliver fixture and asserts the fleet chaos contract
for EVERY injected fault class:

- co-located: an injected transient submit, a classified hang, a slow
  tick, and a mid-tick crash on replica-0 — zero dropped requests,
  exact token parity vs the single-engine baseline, balanced goodput
  ledger, and the record proves every fault class actually fired,
- disaggregated: a corrupted and a lost handoff artifact — the importer
  detects and REJECTS both, the exporter stays parked, the retried hop
  lands, and the same zero-drop/parity/ledger contract holds,
- brownout: a prefill-heavy adversarial trace with ``--degrade`` —
  the controller engages (at least one audited ``degrade`` transition),
  recovers once pressure clears, and the degradation stays
  token-preserving,
- full determinism: a second identical run of each scenario reproduces
  the fault fire counts and the token outputs (no wall-clock in any
  fault or degrade decision).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from deeplearning_cfn_tpu.fleet.bench import run_fleet_bench

PLAN_DIR = os.path.join("tests", "fixtures", "chaos")


def _trace():
    sliver = os.path.join("tests", "data", "wmt_sliver.de")
    with open(sliver, "rb") as fh:
        lines = [ln for ln in fh.read().splitlines() if ln.strip()]
    # Byte-derived token ids in the bench vocab (>= 3 skips the
    # pad/bos/eos reserved ids), capped to the smoke src_len.
    trace = [[3 + (b % 93) for b in ln[:8]] for ln in lines][:6]
    assert len(trace) >= 3, "wmt_sliver fixture too small for the gate"
    return trace


def _assert_contract(rec, tag):
    assert rec["dropped_requests"] == 0, (tag, rec)
    assert rec["token_identical"] is True, (tag, rec)
    assert rec["goodput_sum_ok"] is True, (tag, rec)


def main() -> int:
    trace = _trace()

    # -- co-located fault classes: transient / hang / latency / crash_mid
    plan = os.path.join(PLAN_DIR, "fleet_colocated.json")
    colo = [run_fleet_bench(smoke=True, trace=trace, chaos_plan=plan)
            for _ in range(2)]
    r = colo[0]
    _assert_contract(r, "colocated")
    assert r["chaos_plan"] == plan, r
    for kind in ("transient", "hang", "latency", "crash_mid"):
        assert r["faults_injected"].get(kind, 0) >= 1, \
            (kind, r["faults_injected"])
    # Deterministic replay: the same plan bites identically twice.
    assert colo[0]["faults_injected"] == colo[1]["faults_injected"]

    # -- disaggregated handoff faults: corruption + loss, both rejected
    plan = os.path.join(PLAN_DIR, "fleet_disagg.json")
    dis = [run_fleet_bench(smoke=True, trace=trace,
                           prefill_replicas=1, decode_replicas=1,
                           chaos_plan=plan)
           for _ in range(2)]
    r = dis[0]
    _assert_contract(r, "disagg")
    for kind in ("corrupt", "drop"):
        assert r["faults_injected"].get(kind, 0) >= 1, \
            (kind, r["faults_injected"])
    assert dis[0]["faults_injected"] == dis[1]["faults_injected"]

    # -- brownout: engage AND recover under the prefill-heavy adversary.
    # The smoke fleet is tiny, so the gate hands the controller a
    # pressure-sensitive policy — the LEVELS and their knobs are the
    # production ones, only the thresholds are scaled to smoke depth.
    from deeplearning_cfn_tpu.fleet.degrade import DegradePolicy

    def _policy():
        return DegradePolicy(up_queue_depth=0.5, down_queue_depth=0.25,
                             up_stable_ticks=1, down_stable_ticks=1,
                             cooldown_ticks=0)

    deg = [run_fleet_bench(smoke=True, trace_mix="prefill-heavy",
                           decode_window=1, degrade=True,
                           degrade_policy=_policy())
           for _ in range(2)]
    r = deg[0]
    _assert_contract(r, "degrade")
    actions = [e["action"] for e in r["degrade_events"]]
    assert "degrade" in actions, r["degrade_events"]
    assert "recover" in actions, r["degrade_events"]
    assert r["degrade_events"][-1]["level"] == 0, r["degrade_events"]
    assert [e["action"] for e in deg[1]["degrade_events"]] == actions

    print(f"CHAOS_FLEET_SMOKE=OK "
          f"colocated_faults={colo[0]['faults_injected']} "
          f"disagg_faults={dis[0]['faults_injected']} "
          f"degrade_transitions={deg[0]['degrade_transitions']}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
