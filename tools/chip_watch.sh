#!/usr/bin/env bash
# Backend-recovery watcher, round-4 edition: poll the TPU backend; the
# moment it answers, run the full measurement session with its log INSIDE
# the repo (bench_artifacts/) and git-commit the capture immediately, so a
# later container death cannot lose the evidence (round-3 verdict, Weak #1:
# /tmp artifacts die with the container).
#
# Usage: tools/chip_watch.sh [MAX_POLLS] [POLL_INTERVAL_S]
# Runs in the foreground; callers background it themselves.

set -u
MAX_POLLS="${1:-400}"
INTERVAL="${2:-90}"
cd "$(dirname "$0")/.."
STAMP() { date -u +%Y%m%dT%H%M%SZ; }
ART=bench_artifacts
PROBE_LOG="$ART/probe_$(STAMP).log"
mkdir -p "$ART"

# Record this watcher's PID at arm time so rearm_watch.sh can wait on the
# exact process instead of pattern-matching command lines (pgrep -f matches
# any process whose argv mentions the script — including the re-armer).
# Kept out of $ART so commit_artifacts never sweeps transient state into
# the committed evidence.
PIDFILE="${CHIP_WATCH_PIDFILE:-/tmp/chip_watch.pid}"
echo "$$" > "$PIDFILE"

# The probe must assert a real accelerator: in the r01 failure mode the TPU
# plugin RAISES and jax silently falls back to CPU, where a bare matmul
# succeeds — that must not trigger (and thereby spend) the one-shot session.
PROBE='import jax, jax.numpy as jnp
d = jax.devices()[0]
assert d.platform != "cpu", f"cpu fallback: {d}"
x = jnp.ones((256, 256)); print(d.platform, float((x @ x).sum()))'

commit_artifacts() {
  # Pathspec'd commit so concurrently-staged unrelated work is never swept
  # in; retried because a 10 h watch window can race another git operation
  # (stale index.lock). Returns nonzero if the evidence is NOT durable.
  local msg="$1"
  for try in 1 2 3; do
    if git add -- "$ART" >> "$PROBE_LOG" 2>&1 \
       && git commit -m "$msg" -- "$ART" >> "$PROBE_LOG" 2>&1; then
      return 0
    fi
    # "nothing to commit" (all artifacts already committed) is success.
    if git diff --quiet HEAD -- "$ART" 2>/dev/null \
       && [ -z "$(git status --porcelain -- "$ART")" ]; then
      return 0
    fi
    echo "$(STAMP) commit attempt $try failed, retrying in 10s" >> "$PROBE_LOG"
    sleep 10
  done
  echo "$(STAMP) ERROR: artifacts NOT committed" >> "$PROBE_LOG"
  return 1
}

echo "$(STAMP) watcher armed (max $MAX_POLLS polls @ ${INTERVAL}s)" >> "$PROBE_LOG"
for i in $(seq 1 "$MAX_POLLS"); do
  if timeout 120 python -c "$PROBE" >> "$PROBE_LOG" 2>&1; then
    # Capture-time one-shot guard: two watchers can be armed across a
    # session boundary and both probes can succeed in the same window, so
    # a bare existence check races (check-then-create is not atomic). The
    # guard IS the lock: noclobber (set -C) creation of a fixed-name lock
    # file succeeds for exactly one watcher; the loser stands down. A
    # capture from an earlier window leaves the lock behind, preserving
    # the old "already ran — stand down" behaviour.
    if ! ( set -C; echo "pid=$$ $(STAMP)" > "$ART/chip_session.lock" ) 2>/dev/null; then
      echo "$(STAMP) TPU OK (poll $i) but the session lock is already held ($(cat "$ART/chip_session.lock" 2>/dev/null)) — standing down" >> "$PROBE_LOG"
      exit 0
    fi
    echo "$(STAMP) TPU OK (poll $i) — launching chip session" >> "$PROBE_LOG"
    SESSION_LOG="$ART/chip_session_$(STAMP).log"
    # Same-stamp double-create is impossible past the lock, but create the
    # session log noclobber too so a clobber can never destroy evidence.
    ( set -C; : > "$SESSION_LOG" ) 2>/dev/null || {
      echo "$(STAMP) session log $SESSION_LOG already exists — standing down" >> "$PROBE_LOG"
      exit 0
    }
    bash tools/chip_session.sh "$SESSION_LOG"
    echo "$(STAMP) chip session finished" >> "$PROBE_LOG"
    commit_artifacts "bench_artifacts: real-chip measurement session $(STAMP)"
    exit $?
  fi
  echo "$(STAMP) still hung (poll $i)" >> "$PROBE_LOG"
  sleep "$INTERVAL"
done
echo "$(STAMP) watcher exhausted without a live backend" >> "$PROBE_LOG"
commit_artifacts "bench_artifacts: probe log — backend never recovered"
exit 1
