"""QOS smoke gate — run by tools/t1.sh.

Routes a two-tenant trace (tenant-a latency-class interactive traffic
interleaved with tenant-b batch-class bulk work, sources drawn from the
wmt_sliver fixture) through the fleet bench and asserts the multi-tenant
contract end to end:

- zero dropped requests (fair-share admission sheds with retry-after
  hints instead of silently losing work),
- at least one audited preemption: a latency-class arrival evicted a
  running batch stream, whose replayed continuation is token-identical
  (``qos_token_loss == 0``),
- token parity vs the single-engine baseline (QoS scheduling must be
  invisible in outputs),
- the goodput ledger still balances (``goodput + wasted == decoded``),
- latency-class decode p95 stays within a generous bound of the
  no-adversary baseline the same invocation measures (the batch flood
  must not starve the latency tenant),
- full determinism: a second run produces identical per-class p95s and
  the identical preemption count.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from deeplearning_cfn_tpu.fleet.bench import run_fleet_bench


def main() -> int:
    sliver = os.path.join("tests", "data", "wmt_sliver.de")
    with open(sliver, "rb") as fh:
        lines = [ln for ln in fh.read().splitlines() if ln.strip()]
    # Byte-derived token ids in the bench vocab (>= 3 skips the
    # pad/bos/eos reserved ids), capped to the smoke src_len.
    trace = [[3 + (b % 93) for b in ln[:8]] for ln in lines][:6]
    assert len(trace) >= 3, "wmt_sliver fixture too small for the gate"

    # decode_window=1 keeps the batch flood mid-decode for several fleet
    # steps, so the staggered latency arrivals land while every slot is
    # held by an evictable stream.
    runs = [run_fleet_bench(smoke=True, trace_mix="tenants", trace=trace,
                            decode_window=1)
            for _ in range(2)]
    r = runs[0]
    assert r["dropped_requests"] == 0, r
    assert r["token_identical"] is True, r
    assert r["goodput_sum_ok"] is True, r
    assert r["preemptions"] >= 1, r
    assert r["qos_token_loss"] == 0, r
    by_cls = r["qos_p95_by_class"]
    assert by_cls and "latency" in by_cls and "batch" in by_cls, r
    lat_p95 = by_cls["latency"]
    noadv = r["qos_decode_p95_no_adversary"]
    assert lat_p95 is not None and noadv is not None, r
    # The latency tenant must not be starved by the batch flood. The
    # bound is deliberately loose (CPU smoke timings are noisy at this
    # scale) — it exists to catch order-of-magnitude starvation, which
    # is what a broken fair-share scheduler produces.
    assert lat_p95 <= 5.0 * noadv + 0.5, (lat_p95, noadv)
    # Determinism: the same trace yields the same per-class latencies
    # under the virtual clock and the same preemption decisions.
    assert runs[0]["preemptions"] == runs[1]["preemptions"]
    assert runs[0]["qos_token_loss"] == runs[1]["qos_token_loss"]
    print(f"QOS_SMOKE=OK preemptions={r['preemptions']} "
          f"replayed={r['preempted_tokens_replayed']} "
          f"token_loss={r['qos_token_loss']} "
          f"latency_p95={lat_p95:.4f} no_adversary_p95={noadv:.4f} "
          f"fair_share_violation_max={r['fair_share_violation_max']}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
