"""AUTOSCALE smoke gate — run by tools/t1.sh.

Replays the seeded ``burst`` trace (open-loop loadgen on the virtual
clock) against a 1-replica fleet with the closed-loop autoscaler on and
asserts the contract end to end:

- at least one scale-up fires at burst onset,
- at least one scale-down completes via drain (``drained`` is True —
  the victim went idle before removal, never evacuated mid-flight),
- zero dropped requests (retry-after admission + drain-based removal
  means scaling never loses work),
- token parity vs a FIXED fleet of ``max_replicas`` replaying the same
  schedule (elasticity must be invisible in outputs),
- full determinism: a second run produces the identical arrival
  schedule AND the identical scale-event sequence, byte for byte.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from deeplearning_cfn_tpu.fleet.bench import run_fleet_bench


def main() -> int:
    sliver = os.path.join("tests", "data", "wmt_sliver.de")
    with open(sliver, "rb") as fh:
        lines = [ln for ln in fh.read().splitlines() if ln.strip()]
    # Byte-derived token ids in the bench vocab (>= 3 skips the
    # pad/bos/eos reserved ids), capped to the smoke src_len.
    trace = [[3 + (b % 93) for b in ln[:8]] for ln in lines][:6]
    assert len(trace) >= 2, "wmt_sliver fixture too small for the gate"

    runs = [run_fleet_bench(smoke=True, autoscale=True, trace_spec="burst",
                            policy="round_robin", trace=trace)
            for _ in range(2)]
    r = runs[0]
    assert r["scale_ups"] >= 1, r["scale_events"]
    downs = [e for e in r["scale_events"] if e["action"] == "scale_down"]
    assert len(downs) >= 1, r["scale_events"]
    assert all(e["drained"] is True for e in downs), downs
    assert r["dropped_requests"] == 0, r
    assert r["token_identical"] is True, r
    assert r["replicas_final"] == r["min_replicas"], r
    # Determinism: both runs replay the same arrivals and make the same
    # scaling decisions at the same virtual timestamps.
    assert runs[0]["arrival_schedule"] == runs[1]["arrival_schedule"]
    assert runs[0]["scale_events"] == runs[1]["scale_events"]
    print(f"AUTOSCALE_SMOKE=OK ups={r['scale_ups']} "
          f"downs={r['scale_downs']} "
          f"time_to_scale_s={r['time_to_scale_s']} "
          f"p95_during_burst={r['p95_during_burst']}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
