#!/usr/bin/env bash
# Round-5 watcher re-armer: the round-4 chip_watch.sh was launched with a
# poll budget that expires mid-round-5. This waits for the running watcher
# to exit and, if it exhausted WITHOUT capturing a chip session, arms a
# fresh chip_watch.sh sized to cover the remainder of the round — so the
# one-shot measurement session fires no matter when the backend recovers.
#
# Usage: tools/rearm_watch.sh [NEW_MAX_POLLS] [POLL_INTERVAL_S]

set -u
NEW_POLLS="${1:-320}"
INTERVAL="${2:-90}"
cd "$(dirname "$0")/.."

# Wait for the armed watcher to finish its budget (or its capture).
# chip_watch.sh records its PID at arm time; waiting on that exact PID
# replaces the old `pgrep -f 'chip_watch.sh'` loop, which pattern-matched
# ANY process whose command line mentioned the script (this re-armer, an
# editor, a grep) and could therefore spin forever or return early.
PIDFILE="${CHIP_WATCH_PIDFILE:-/tmp/chip_watch.pid}"
if [ -f "$PIDFILE" ]; then
  WATCH_PID="$(cat "$PIDFILE" 2>/dev/null)"
  while [ -n "$WATCH_PID" ] && kill -0 "$WATCH_PID" 2>/dev/null; do
    sleep 60
  done
fi

# If a session was already captured, the evidence exists — do not re-arm
# (chip_session.sh is a one-shot full measurement; a second run would just
# duplicate it and race git).
if ls bench_artifacts/chip_session_*.log > /dev/null 2>&1; then
  echo "$(date -u +%Y%m%dT%H%M%SZ) capture exists; not re-arming" \
    >> bench_artifacts/rearm.log
  exit 0
fi

echo "$(date -u +%Y%m%dT%H%M%SZ) re-arming watcher ($NEW_POLLS polls @ ${INTERVAL}s)" \
  >> bench_artifacts/rearm.log
exec bash tools/chip_watch.sh "$NEW_POLLS" "$INTERVAL"
