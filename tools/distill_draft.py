#!/usr/bin/env python
"""Distill the committed "tiny-distilled" draft for speculative serving.

`dlcfn-tpu bench --serve --speculate γ` defaults to SELF-draft: the draft
IS the target, so every proposal is accepted and the reported accept rate
is a ceiling (1.0) rather than a measurement. This tool produces the real
shrunk draft the bench (and serve/loader.py ``draft_cfg="tiny-distilled"``)
loads instead: a quarter-size transformer_nmt_tiny distilled against the
EXACT teacher the bench builds — the random-init tiny preset at seed 0 —
by teacher-logit (KL) distillation over the teacher's own greedy
trajectories.

Training sources mix the WMT sliver fixture sentences (bytes folded into
the tiny vocab, ``3 + (b % 93)`` — the reserved-id framing data/text.py
uses) with draws from the bench's seeded `_fixed_trace` family, so the
measured accept rate on the bench trace reflects in-distribution
distillation, not memorization of the eval trace itself (the bench trace
seed is excluded from training).

Run from the repo root (CPU, ~a minute):

    python tools/distill_draft.py

Writes deeplearning_cfn_tpu/serve/data/draft_tiny_distilled.npz — a flat
{"a/b/c": array} params tree (see serve/loader.py distilled_draft) —
and prints the held-out greedy agreement rate (≈ the accept rate the
bench will measure).
"""

import os
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import optax  # noqa: E402
from flax import traverse_util  # noqa: E402

from deeplearning_cfn_tpu.models.decoding import BOS_ID, EOS_ID  # noqa: E402
from deeplearning_cfn_tpu.models.transformer_nmt import \
    transformer_nmt_tiny  # noqa: E402
from deeplearning_cfn_tpu.serve.bench import _fixed_trace  # noqa: E402
from deeplearning_cfn_tpu.serve.loader import DRAFT_PRESETS  # noqa: E402

VOCAB, MAX_LEN, SRC_LEN, TRAJ_LEN = 96, 64, 12, 16
OUT = os.path.join(REPO, "deeplearning_cfn_tpu", "serve", "data",
                   "draft_tiny_distilled.npz")


def sliver_sources():
    """WMT sliver sentences → tiny-vocab id sequences, the byte-fold
    framing: ids 0..2 are reserved (PAD/BOS/EOS)."""
    out = []
    data = os.path.join(REPO, "tests", "data")
    for lang in ("en", "de"):
        with open(os.path.join(data, f"wmt_sliver.{lang}"), "rb") as fh:
            for ln in fh:
                ln = ln.strip()
                if not ln:
                    continue
                ids = [3 + (b % 93) for b in ln][:SRC_LEN]
                if len(ids) >= 2:
                    out.append(ids)
    return out


def pad_batch(srcs):
    src = np.zeros((len(srcs), SRC_LEN), np.int32)
    for i, s in enumerate(srcs):
        src[i, :len(s)] = s
    mask = (src != 0).astype(np.int32)
    return src, mask


def main():
    # The teacher is byte-for-byte what run_serve_bench builds: the tiny
    # preset, random-init at the bench's default seed.
    teacher = transformer_nmt_tiny(vocab_size=VOCAB, max_len=MAX_LEN)
    t_vars = teacher.init(
        jax.random.PRNGKey(0), np.zeros((1, SRC_LEN), np.int32),
        np.ones((1, SRC_LEN), np.int32), np.zeros((1, SRC_LEN), np.int32),
        train=False)
    t_vars = {"params": t_vars["params"]}

    kwargs, _ = DRAFT_PRESETS["tiny-distilled"]
    draft = transformer_nmt_tiny(**kwargs)
    d_params = draft.init(
        jax.random.PRNGKey(7), np.zeros((1, SRC_LEN), np.int32),
        np.ones((1, SRC_LEN), np.int32), np.zeros((1, SRC_LEN), np.int32),
        train=False)["params"]

    # Training sources: sliver byte-folds + seeded trace family draws.
    # Seed 0 is the bench's default eval trace — held out of training.
    srcs = sliver_sources()
    for seed in range(1, 9):
        srcs.extend(_fixed_trace(16, SRC_LEN, VOCAB, seed=seed))
    src, mask = pad_batch(srcs)

    @jax.jit
    def teacher_traj(src, mask):
        """Teacher greedy trajectories + per-position teacher logits:
        tgt_in[:, 0] = BOS (the engine's greedy framing), logits[:, t]
        scores position t+1. Full-sequence `decode` per step — O(T²) but
        the preset is tiny and this runs once."""
        enc = teacher.apply(t_vars, src, mask, method=type(teacher).encode)
        b = src.shape[0]
        tgt = jnp.full((b, TRAJ_LEN + 1), 0, jnp.int32).at[:, 0].set(BOS_ID)
        for t in range(TRAJ_LEN):
            logits = teacher.apply(t_vars, tgt[:, :t + 1], enc, mask,
                                   method=type(teacher).decode)
            tgt = tgt.at[:, t + 1].set(jnp.argmax(logits[:, -1], axis=-1)
                                       .astype(jnp.int32))
        full = teacher.apply(t_vars, tgt[:, :-1], enc, mask,
                             method=type(teacher).decode)
        return tgt, full

    tgt, t_logits = teacher_traj(src, mask)
    # Distill only up to (and including) the first EOS: the engine never
    # decodes past it, and post-EOS teacher behavior is noise.
    is_eos = np.asarray(tgt[:, 1:]) == EOS_ID
    first_eos = np.where(is_eos.any(1), is_eos.argmax(1), TRAJ_LEN)
    valid = (np.arange(TRAJ_LEN)[None, :]
             <= first_eos[:, None]).astype(np.float32)

    tx = optax.adam(3e-3)
    opt_state = tx.init(d_params)

    @jax.jit
    def step(params, opt_state, src, mask, tgt, t_logits, valid):
        def loss_fn(p):
            enc = draft.apply({"params": p}, src, mask,
                              method=type(draft).encode)
            d_logits = draft.apply({"params": p}, tgt[:, :-1], enc, mask,
                                   method=type(draft).decode)
            t_lp = jax.nn.log_softmax(t_logits.astype(jnp.float32))
            d_lp = jax.nn.log_softmax(d_logits.astype(jnp.float32))
            kl = jnp.sum(jnp.exp(t_lp) * (t_lp - d_lp), axis=-1)
            return jnp.sum(kl * valid) / jnp.maximum(jnp.sum(valid), 1.0)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state)
        return optax.apply_updates(params, updates), opt_state, loss

    rng = np.random.RandomState(0)
    n, bsz = src.shape[0], 64
    for it in range(1500):
        idx = rng.randint(0, n, size=bsz)
        d_params, opt_state, loss = step(
            d_params, opt_state, src[idx], mask[idx], tgt[idx],
            t_logits[idx], valid[idx])
        if it % 250 == 0:
            print(f"step {it:4d}  kl {float(loss):.4f}")

    # Held-out agreement: the bench's actual seed-0 trace, teacher-forced
    # on the TEACHER trajectory — exactly the accept test speculation
    # applies to each proposed token.
    ev_src, ev_mask = pad_batch(_fixed_trace(16, SRC_LEN, VOCAB, seed=0))
    ev_tgt, ev_logits = teacher_traj(ev_src, ev_mask)
    enc = draft.apply({"params": d_params}, ev_src, ev_mask,
                      method=type(draft).encode)
    d_logits = draft.apply({"params": d_params}, np.asarray(ev_tgt)[:, :-1],
                           enc, ev_mask, method=type(draft).decode)
    agree = np.asarray(jnp.argmax(d_logits, -1)
                       == jnp.argmax(ev_logits, -1))
    is_eos = np.asarray(ev_tgt[:, 1:]) == EOS_ID
    first = np.where(is_eos.any(1), is_eos.argmax(1), TRAJ_LEN)
    ev_valid = np.arange(TRAJ_LEN)[None, :] <= first[:, None]
    rate = float(agree[ev_valid].mean())
    print(f"held-out greedy agreement (≈ accept rate): {rate:.3f}")

    flat = {"/".join(k): np.asarray(v) for k, v in
            traverse_util.flatten_dict(d_params).items()}
    os.makedirs(os.path.dirname(OUT), exist_ok=True)
    np.savez_compressed(OUT, **flat)
    size = os.path.getsize(OUT)
    print(f"wrote {OUT} ({size / 1024:.0f} KiB, {len(flat)} arrays)")


if __name__ == "__main__":
    main()
