#!/usr/bin/env bash
# On-chip measurement session: run everything worth measuring on the real
# TPU in one unattended pass, appending JSON lines + markers to a log.
# Usage: tools/chip_session.sh [LOGFILE]
#   (default bench_artifacts/chip_session_<UTC>.log — committed evidence)
#
# Designed for the flaky-backend reality: every stage is its own process
# with a hard timeout, failures don't stop later stages, and the log
# records wall-clock per stage. Order: cheapest/highest-value first, so a
# mid-session backend death still leaves the headline numbers.

set -u
cd "$(dirname "$0")/.."
mkdir -p bench_artifacts
LOG="${1:-bench_artifacts/chip_session_$(date -u +%Y%m%dT%H%M%SZ).log}"

stage() {
  local name="$1" tmo="$2"; shift 2
  echo "=== [$(date +%H:%M:%S)] $name (timeout ${tmo}s) ===" >> "$LOG"
  timeout "$tmo" "$@" >> "$LOG" 2>&1
  echo "--- rc=$? [$(date +%H:%M:%S)] $name done" >> "$LOG"
}

echo "==== chip session start $(date) ====" >> "$LOG"

# 0. Preflight: is the backend even alive? (doctor exits 1 on failure —
#    later stages still run, in case the hang was transient.)
stage doctor            180 python -m deeplearning_cfn_tpu.cli doctor

# 1. Headline driver bench (ResNet-50, full contract line). Timeout must
#    exceed bench.py's worst-case wall: 40 s probe + 540 s attempt budget.
stage bench_headline    630 python bench.py

# 2. ResNet batch sweep around the shipped 512 default.
stage sweep_resnet      900 python -m deeplearning_cfn_tpu.cli bench \
    --preset imagenet_resnet50 --steps 20 --sweep-batches 384,512,640

# 3. Stem A/B: classic 7x7 vs space-to-depth, full fwd+bwd at 224/b512.
stage ops_resnet        900 python -m deeplearning_cfn_tpu.cli bench \
    --ops resnet --steps 10 --global-batch 512

# 4. Detection step breakdown (the 0.05-MFU diagnosis).
stage ops_detection    1500 python -m deeplearning_cfn_tpu.cli bench \
    --ops detection --steps 5

# 4b. Detection batch sweep: the preset trains at 64/chip-group but the
#     single-number bench ran at 4, under-filling the chip (r03 Weak #5).
stage sweep_detection  1200 python -m deeplearning_cfn_tpu.cli bench \
    --preset maskrcnn_coco --steps 8 --sweep-batches 4,8,16

# 5. Per-preset step benches not covered above.
for p in bert_base_wikipedia transformer_nmt_wmt maskrcnn_coco \
         bert_moe_wikipedia bert_long_wikipedia gpt_small_lm \
         gpt_long_lm imagenet_vit_s16; do
  stage "bench_$p"      700 python -m deeplearning_cfn_tpu.cli bench \
      --preset "$p" --steps 20
done

# 6. Feed-included flagship number (trained throughput).
stage bench_with_input  700 python -m deeplearning_cfn_tpu.cli bench \
    --preset imagenet_resnet50 --steps 20 --with-input

echo "==== chip session end $(date) ====" >> "$LOG"
