"""DISAGG smoke gate — run by tools/t1.sh.

Drives a tiny disaggregated fleet (1 prefill + 1 decode replica) over a
trace derived from the wmt_sliver fixture and asserts the three
contract properties end to end:

- zero dropped requests,
- token parity vs the single-engine oracle AND vs a co-located fleet on
  the same trace (the disagg split must be invisible in outputs),
- the KV handoff shows up as a cross-process flow link in the merged
  Perfetto export: at least one trace_id has ``serve.request`` spans on
  BOTH the prefill-0 and decode-0 processes.

A second pass reruns the same trace with ``kv_quant="int8"``: the
handoff then ships int8 block codes + per-block scale sidecars, and the
zero-drop / parity contract must hold unchanged (parity is against the
int8-KV single-engine oracle — int8 KV is bounded-divergence vs fp32,
not bit-identical).
"""

import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from deeplearning_cfn_tpu.fleet.bench import run_fleet_bench
from deeplearning_cfn_tpu.obs.export import export_fleet_trace

_REQUEST = "serve.request"


def main() -> int:
    sliver = os.path.join("tests", "data", "wmt_sliver.de")
    with open(sliver, "rb") as fh:
        lines = [ln for ln in fh.read().splitlines() if ln.strip()]
    # Byte-derived token ids in the bench vocab (>= 3 skips the
    # pad/bos/eos reserved ids), capped to the smoke src_len.
    trace = [[3 + (b % 93) for b in ln[:8]] for ln in lines][:6]
    assert len(trace) >= 2, "wmt_sliver fixture too small for the gate"
    with tempfile.TemporaryDirectory() as d:
        r = run_fleet_bench(smoke=True, prefill_replicas=1,
                            decode_replicas=1, trace=trace, trace_dir=d)
        assert r["dropped_requests"] == 0, r
        assert r["token_identical"] is True, r
        assert r["token_identical_colocated"] is True, r
        assert r["handoffs"] >= 1, r
        out = os.path.join(d, "trace.json")
        s = export_fleet_trace(d, out)
        assert not s["problems"], s
        assert s["flow_events"] >= 1, s
        with open(out) as fh:
            events = json.load(fh)["traceEvents"]
        # pid → shard label via the process_name metadata events.
        label = {e["pid"]: e["args"]["name"] for e in events
                 if e.get("ph") == "M" and e.get("name") == "process_name"}
        by_trace = {}
        for e in events:
            if e.get("ph") != "X" \
                    or not str(e.get("name", "")).startswith(_REQUEST):
                continue
            tid = (e.get("args") or {}).get("trace_id")
            if isinstance(tid, str):
                by_trace.setdefault(tid, set()).add(
                    label.get(e["pid"], ""))
        hopped = [t for t, shards in by_trace.items()
                  if any(n.startswith("prefill-0") for n in shards)
                  and any(n.startswith("decode-0") for n in shards)]
        assert hopped, {t: sorted(v) for t, v in by_trace.items()}
    rq = run_fleet_bench(smoke=True, prefill_replicas=1,
                         decode_replicas=1, trace=trace,
                         kv_quant="int8")
    assert rq["dropped_requests"] == 0, rq
    assert rq["token_identical"] is True, rq
    assert rq["token_identical_colocated"] is True, rq
    assert rq["handoffs"] >= 1, rq
    print(f"DISAGG_SMOKE=OK handoffs={r['handoffs']} "
          f"hopped_traces={len(hopped)} int8kv_handoffs={rq['handoffs']}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
