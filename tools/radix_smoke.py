"""RADIX smoke gate — run by tools/t1.sh.

Routes a prefix-heavy trace (repeated sources drawn from the wmt_sliver
fixture) through a radix-cached fleet under prefix-affinity routing and
asserts the radix contract end to end:

- token parity vs the single-engine COLD-cache baseline (cached reuse
  must be invisible in outputs — the cache only ever supplies tokens a
  cold decode would have produced),
- zero dropped requests and a balanced goodput ledger, where the radix
  invariant is ``goodput + wasted == decoded + radix_hit_tokens``
  (cache-supplied tokens are goodput that no engine step decoded),
- a real hit rate (> 0) with real tokens saved
  (``prefill_tokens_saved_ratio > 0``),
- the sharing sweep: decoded work per request falls monotonically as
  distinct sources collapse (``radix_prefill_monotonic``),
- routing evidence: prefix-affinity beats round-robin on hit rate for
  the same trace (scattering a group across replicas cold-misses every
  replica once),
- determinism: a second run reproduces the hit rate and the sweep.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from deeplearning_cfn_tpu.fleet.bench import run_fleet_bench


def main() -> int:
    sliver = os.path.join("tests", "data", "wmt_sliver.de")
    with open(sliver, "rb") as fh:
        lines = [ln for ln in fh.read().splitlines() if ln.strip()]
    # Byte-derived token ids in the bench vocab (>= 3 skips the
    # pad/bos/eos reserved ids), capped to the smoke src_len.
    corpus = [[3 + (b % 93) for b in ln[:8]] for ln in lines][:4]
    assert len(corpus) >= 2, "wmt_sliver fixture too small for the gate"

    runs = [run_fleet_bench(smoke=True, radix=True,
                            trace_mix="prefix-heavy", trace=corpus,
                            policy="prefix_affinity")
            for _ in range(2)]
    r = runs[0]
    assert r["radix"] is True, r
    assert r["dropped_requests"] == 0, r
    assert r["token_identical"] is True, r
    assert r["goodput_sum_ok"] is True, r
    assert r["radix_hit_rate"] is not None and r["radix_hit_rate"] > 0, r
    assert r["radix_hit_tokens_per_request"] > 0, r
    assert r["prefill_tokens_saved_ratio"] > 0, r
    sweep = r["radix_sweep"]
    assert sweep and len(sweep) >= 2, r
    assert r["radix_prefill_monotonic"] is True, r
    aff = r["radix_hit_rate_prefix_affinity"]
    rr = r["radix_hit_rate_round_robin"]
    assert aff is not None and rr is not None and aff > rr, (aff, rr)
    # Determinism: same trace, same sharing, same routing decisions.
    assert runs[0]["radix_hit_rate"] == runs[1]["radix_hit_rate"]
    assert runs[0]["radix_sweep"] == runs[1]["radix_sweep"]
    print(f"RADIX_SMOKE=OK hit_rate={r['radix_hit_rate']} "
          f"hit_tokens_per_request={r['radix_hit_tokens_per_request']} "
          f"saved_ratio={r['prefill_tokens_saved_ratio']} "
          f"sweep={[row['decoded_tokens_per_request'] for row in sweep]} "
          f"affinity={aff} round_robin={rr}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
