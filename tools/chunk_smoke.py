"""CHUNK smoke gate — run by tools/t1.sh.

Routes a prefill-heavy adversarial trace (long-prompt/short-decode
adversaries interleaved with short-prompt latency streams, sources drawn
from the wmt_sliver fixture) through one co-located chunked fleet and
asserts the stall-free chunked-prefill contract end to end:

- zero dropped requests (chunking defers encode work, it never sheds
  admitted requests),
- exact token parity vs the UNCHUNKED fleet the same invocation runs
  (the completion tick re-runs the full-width prefill, so chunking must
  be invisible in outputs) AND vs the cold single-engine baseline,
- the goodput ledger still balances (``goodput + wasted == decoded``),
- decode p95 under the adversary stays within a generous bound of the
  no-adversary baseline the same invocation measures (the long prompts
  must not stall co-resident decode streams),
- chunked prefill actually engaged: the per-request chunk-tick p50
  shows multi-tick encodes,
- full determinism: a second run produces identical p95s.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from deeplearning_cfn_tpu.fleet.bench import run_fleet_bench


def main() -> int:
    sliver = os.path.join("tests", "data", "wmt_sliver.de")
    with open(sliver, "rb") as fh:
        lines = [ln for ln in fh.read().splitlines() if ln.strip()]
    # Byte-derived token ids in the bench vocab (>= 3 skips the
    # pad/bos/eos reserved ids), capped to the smoke src_len.
    trace = [[3 + (b % 93) for b in ln[:8]] for ln in lines][:6]
    assert len(trace) >= 3, "wmt_sliver fixture too small for the gate"

    # chunk=3 against src_len=8 makes every adversary prompt a 3-tick
    # encode; decode_window=1 keeps latency streams surfacing tokens
    # between chunks, which is the stall the gate is about.
    runs = [run_fleet_bench(smoke=True, trace_mix="prefill-heavy",
                            trace=trace, decode_window=1,
                            prefill_chunk=3)
            for _ in range(2)]
    r = runs[0]
    assert r["dropped_requests"] == 0, r
    assert r["token_identical"] is True, r
    assert r["token_identical_unchunked"] is True, r
    assert r["goodput_sum_ok"] is True, r
    ticks_p50 = r["chunk_ticks_per_prefill_p50"]
    assert ticks_p50 is not None and ticks_p50 >= 2, r
    chunked = r["chunked_decode_p95"]
    noadv = r["decode_p95_no_adversary"]
    assert chunked is not None and noadv is not None, r
    # The latency streams must not be stalled by the adversary prompts.
    # The bound is deliberately loose (CPU smoke timings are noisy at
    # this scale) — it exists to catch order-of-magnitude decode stall,
    # which is what an unchunked admission encode produces.
    assert chunked <= 5.0 * noadv + 0.5, (chunked, noadv)
    # Determinism: same trace, same chunk schedule, same tokens.
    assert (runs[0]["chunk_ticks_per_prefill_p50"]
            == runs[1]["chunk_ticks_per_prefill_p50"])
    assert runs[0]["token_identical_unchunked"] \
        and runs[1]["token_identical_unchunked"]
    print(f"CHUNK_SMOKE=OK chunk={r['prefill_chunk']} "
          f"ticks_per_prefill_p50={ticks_p50} "
          f"chunked_p95={chunked:.4f} no_adversary_p95={noadv:.4f} "
          f"unchunked_p95={r['unchunked_decode_p95']:.4f}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
