"""GcsStore unit tests against a faked google-cloud-storage client.

Real GCS is unreachable offline, but GcsStore's own logic — url parsing,
key↔blob-name mapping under a prefix, recursive list, delimiter-based
one-level list_subdirs, delete_prefix, the NotFound→FileNotFoundError
contract translation — is pure client choreography, so a dict-backed fake
client covers it without network. The fake mimics the google API shapes
GcsStore touches: Client.bucket / Client.list_blobs (with the delimiter
iterator whose .prefixes only populates after the iterator is drained,
exactly the real HTTPIterator behavior GcsStore relies on), Bucket.blob,
Blob.upload_from_string / download_as_bytes / exists / delete, and
google.cloud.exceptions.NotFound.

GcsStore then runs the same Store-interface suite PosixStore and
MemoryObjectStore pass (tests/test_checkpoint.py) plus the full two-phase
checkpoint protocol.
"""

import sys
import types

import numpy as np
import pytest


class _FakeNotFound(Exception):
    pass


class _FakeBlob:
    def __init__(self, objects, name):
        self._objects = objects
        self.name = name

    def upload_from_string(self, data):
        if isinstance(data, str):
            data = data.encode("utf-8")
        self._objects[self.name] = bytes(data)

    def download_as_bytes(self):
        try:
            return self._objects[self.name]
        except KeyError:
            raise _FakeNotFound(f"404 blob {self.name!r} not found")

    def exists(self):
        return self.name in self._objects

    def delete(self):
        if self.name not in self._objects:
            raise _FakeNotFound(f"404 blob {self.name!r} not found")
        del self._objects[self.name]


class _FakeListIterator:
    """Mimics google.api_core.page_iterator.HTTPIterator: ``prefixes`` is
    empty until the pages have actually been consumed — GcsStore must drain
    the iterator before reading it (store.py pins that with a list(it))."""

    def __init__(self, blobs, prefixes):
        self._blobs = blobs
        self._final_prefixes = prefixes
        self.prefixes = set()

    def __iter__(self):
        for b in self._blobs:
            yield b
        self.prefixes = set(self._final_prefixes)


class _FakeBucket:
    def __init__(self, objects, name):
        self._objects = objects
        self.name = name

    def blob(self, name):
        return _FakeBlob(self._objects, name)


class _FakeClient:
    # One object namespace shared by every client in the process, like a
    # real bucket; reset per-test by the fixture.
    objects = {}

    def bucket(self, name):
        return _FakeBucket(self.objects, name)

    def list_blobs(self, bucket, prefix="", delimiter=None):
        names = sorted(n for n in bucket._objects if n.startswith(prefix))
        if delimiter is None:
            return _FakeListIterator(
                [_FakeBlob(bucket._objects, n) for n in names], set())
        direct, prefixes = [], set()
        for n in names:
            rest = n[len(prefix):]
            if delimiter in rest:
                prefixes.add(prefix + rest.split(delimiter, 1)[0] + delimiter)
            else:
                direct.append(n)
        return _FakeListIterator(
            [_FakeBlob(bucket._objects, n) for n in direct], prefixes)


@pytest.fixture
def gcs(monkeypatch):
    """Install the fake google.cloud.storage modules; returns the shared
    object dict for white-box assertions on blob names."""
    fake_storage = types.ModuleType("google.cloud.storage")
    fake_storage.Client = _FakeClient
    fake_exceptions = types.ModuleType("google.cloud.exceptions")
    fake_exceptions.NotFound = _FakeNotFound
    fake_cloud = types.ModuleType("google.cloud")
    fake_cloud.storage = fake_storage
    fake_cloud.exceptions = fake_exceptions
    if "google" not in sys.modules:
        monkeypatch.setitem(sys.modules, "google", types.ModuleType("google"))
    monkeypatch.setitem(sys.modules, "google.cloud", fake_cloud)
    monkeypatch.setitem(sys.modules, "google.cloud.storage", fake_storage)
    monkeypatch.setitem(sys.modules, "google.cloud.exceptions",
                        fake_exceptions)
    _FakeClient.objects = {}
    return _FakeClient.objects


def _make(url="gs://bkt/ckpts/run1"):
    from deeplearning_cfn_tpu.ckpt.store import GcsStore

    return GcsStore(url)


def test_url_parsing_rejects_bad_urls(gcs):
    from deeplearning_cfn_tpu.ckpt.store import GcsStore

    with pytest.raises(ValueError):
        GcsStore("/posix/path")
    with pytest.raises(ValueError):
        GcsStore("gs://")


def test_key_to_blob_name_mapping(gcs):
    """Keys map under the url prefix; a bare-bucket url maps identity; the
    prefix never doubles or drops slashes."""
    store = _make("gs://bkt/ckpts/run1")
    store.put_bytes("step_00000001/COMMIT", b"x")
    assert list(gcs) == ["ckpts/run1/step_00000001/COMMIT"]

    gcs.clear()
    bare = _make("gs://bkt")
    bare.put_bytes("a/b.txt", b"y")
    assert list(gcs) == ["a/b.txt"]

    gcs.clear()
    slashed = _make("gs://bkt/pre/")  # trailing slash must not double up
    slashed.put_bytes("k", b"z")
    assert list(gcs) == ["pre/k"]


def test_store_interface_suite(gcs):
    """The exact interface suite PosixStore/MemoryObjectStore pass
    (tests/test_checkpoint.py::test_store_interface_posix_and_memory)."""
    store = _make()
    store.put_bytes("a/b/c.txt", b"hello")
    assert store.exists("a/b/c.txt")
    assert store.get_bytes("a/b/c.txt") == b"hello"
    store.put_npz("a/x.npz", {"w": np.arange(4.0)})
    z = store.get_npz("a/x.npz")
    np.testing.assert_array_equal(z["w"], np.arange(4.0))
    z.close()
    assert sorted(store.list("a/")) == ["a/b/c.txt", "a/x.npz"]
    assert store.list_subdirs("") == ["a"]
    assert store.list_subdirs("a/") == ["b"]
    store.delete_prefix("a/b/")
    assert store.list("a/") == ["a/x.npz"]
    assert not store.exists("a/b/c.txt")


def test_missing_key_raises_filenotfound(gcs):
    """The Store contract: a missing key is FileNotFoundError, not the
    google NotFound (restore_or_none and friends key on it)."""
    store = _make()
    with pytest.raises(FileNotFoundError):
        store.get_bytes("nope")


def test_list_subdirs_is_one_level(gcs):
    """Delimiter listing returns immediate children only — deep shard
    objects must not surface grandchildren as subdirs."""
    store = _make()
    store.put_bytes("step_00000001/shards/p0/data.npz", b"x")
    store.put_bytes("step_00000001/COMMIT", b"x")
    store.put_bytes("step_00000002/COMMIT", b"x")
    store.put_bytes("rootfile", b"x")
    assert store.list_subdirs("") == ["step_00000001", "step_00000002"]
    assert store.list_subdirs("step_00000001/") == ["shards"]
    assert store.list_subdirs("step_00000001/shards/") == ["p0"]


def test_checkpoint_protocol_against_gcs(gcs, devices):
    """The full two-phase commit protocol (save → DONE/COMMIT → GC →
    latest → restore; uncommitted invisible) runs against GcsStore exactly
    as it does against MemoryObjectStore."""
    import jax.numpy as jnp

    from deeplearning_cfn_tpu.ckpt.checkpoint import (
        latest_checkpoint,
        restore_checkpoint,
        save_checkpoint,
    )

    store = _make()
    state = {"params": {"w": jnp.arange(6.0).reshape(2, 3)},
             "step": jnp.asarray(0, jnp.int32)}
    for step in [1, 2, 3]:
        save_checkpoint(store, step, state, keep=2)
    assert sorted(
        int(k.split("/")[0][len("step_"):])
        for k in store.list("") if k.endswith("/COMMIT")) == [2, 3]
    assert latest_checkpoint(store) == 3

    target = {"params": {"w": jnp.zeros((2, 3))},
              "step": jnp.asarray(0, jnp.int32)}
    restored, step = restore_checkpoint(store, target)
    assert step == 3
    np.testing.assert_array_equal(np.asarray(restored["params"]["w"]),
                                  np.arange(6.0).reshape(2, 3))

    store.delete_prefix("step_00000003/COMMIT")
    assert latest_checkpoint(store) == 2
