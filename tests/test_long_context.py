"""Long-context model integration (models/bert_long.py, 'seq' axis).

ops/ring_attention.py and ops/ulysses.py are op-level proven in
tests/test_ops.py (vs a single-device oracle, forward + backward); these
tests prove the MODEL-level integration: bert_long trained on a
(data, seq) mesh reproduces pure-DP numerics through the full trainer for
both strategies, while the sequence dim of the activations is actually
sharded.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning_cfn_tpu.config import (
    DataConfig,
    ExperimentConfig,
    MeshConfig,
    ModelConfig,
    OptimizerConfig,
    ScheduleConfig,
    TrainConfig,
)
from deeplearning_cfn_tpu.parallel.mesh import build_mesh


def _run_long(mesh_cfg, impl, steps=6, num_heads=4):
    from deeplearning_cfn_tpu.data import build_pipeline
    from deeplearning_cfn_tpu.train import create_train_state
    from deeplearning_cfn_tpu.train.optim import build_optimizer, \
        build_schedule
    from deeplearning_cfn_tpu.train.task import build_task
    from deeplearning_cfn_tpu.train.trainer import Trainer

    cfg = ExperimentConfig(
        model=ModelConfig(name="bert_long", num_classes=2,
                          kwargs=dict(vocab_size=64, hidden_size=32,
                                      num_layers=2, num_heads=num_heads,
                                      mlp_dim=64, max_len=32,
                                      seq_impl=impl)),
        data=DataConfig(name="wikipedia_mlm", seq_len=32, vocab_size=64,
                        num_train_examples=128, prefetch=0),
        train=TrainConfig(global_batch=16, dtype="float32"),
        optimizer=OptimizerConfig(name="adamw", weight_decay=0.01),
        schedule=ScheduleConfig(name="constant", base_lr=3e-3,
                                warmup_steps=0),
        mesh=mesh_cfg,
    )
    mesh = build_mesh(cfg.mesh)
    task = build_task(cfg, mesh=mesh)
    sched = build_schedule(cfg.schedule, 100, 16, 8)
    tx = build_optimizer(cfg.optimizer, sched)
    state = create_train_state(jax.random.PRNGKey(0), task.init, tx, mesh,
                               param_rules=task.param_rules)
    trainer = Trainer(cfg, task.loss_fn, tx, mesh=mesh, donate=False)
    pipe = build_pipeline(cfg.data, 16, 2, seed=0, train=True)
    it = pipe.epochs()
    losses = []
    for _ in range(steps):
        batch = trainer.device_batch(next(it))
        state, m = trainer.train_step(state, batch, jax.random.PRNGKey(1))
        losses.append(float(m["loss"]))
    return state, losses


@pytest.mark.parametrize("impl", ["ring", "ulysses"])
def test_seq_parallel_matches_data_parallel(impl, devices):
    """bert_long trained 8 steps on a (data=2, seq=4) mesh reproduces the
    pure-DP (data=8) run for both sequence-parallel strategies."""
    state_sp, loss_sp = _run_long(MeshConfig(data=2, seq=4), impl)
    state_dp, loss_dp = _run_long(MeshConfig(data=8), impl)
    np.testing.assert_allclose(loss_sp, loss_dp, rtol=3e-4)
    # Params: atol 5e-3 — the blockwise online softmax reduces in a very
    # different order from the monolithic one, and optimizer steps amplify
    # that float32 noise; the op itself is oracle-tested bit-tight in
    # test_ops.py, and the loss check above pins the trajectory.
    for a, b in zip(jax.tree_util.tree_leaves(state_sp.params),
                    jax.tree_util.tree_leaves(state_dp.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-3)


_RING_PPERMUTE_OLD_JAXLIB = pytest.mark.skipif(
    tuple(map(int, jax.__version__.split(".")[:2])) < (0, 5),
    reason="jaxlib 0.4.x SPMD partitioner fails on the ring op's jaxpr with "
           "'UNIMPLEMENTED: PartitionId instruction is not supported for "
           "SPMD partitioning'. Environmental — see PARITY.md (tier-1 "
           "triage); the ulysses case still runs.")


@pytest.mark.parametrize("impl,collective", [
    pytest.param("ring", "ppermute", marks=_RING_PPERMUTE_OLD_JAXLIB),
    ("ulysses", "all_to_all"),
])
def test_seq_attention_actually_parallel(impl, collective, devices):
    """The forward on a (data=2, seq=4) mesh really runs the
    sequence-parallel op — its collective primitive must appear in the
    jaxpr. Guards the silent-fallback path in SeqParallelAttention (mesh
    unthreaded → plain dense attention, correct numerics, zero
    parallelism)."""
    from deeplearning_cfn_tpu.models import build_model

    mesh = build_mesh(MeshConfig(data=2, seq=4))
    model = build_model("bert_long", 2, jnp.float32, vocab_size=64,
                        hidden_size=32, num_layers=1, num_heads=4,
                        mlp_dim=64, max_len=32, seq_impl=impl, mesh=mesh,
                        batch_axes="data")
    ids = jnp.zeros((8, 32), jnp.int32)
    pos = jnp.zeros((8, 4), jnp.int32)
    variables = model.init(jax.random.PRNGKey(0), ids, jnp.ones_like(ids),
                           ids, pos, train=False)

    fwd = lambda v: model.apply(v, ids, jnp.ones_like(ids), ids, pos,
                                train=False)
    jaxpr_text = str(jax.make_jaxpr(fwd)(variables))
    assert collective in jaxpr_text, \
        f"{impl} attention fell back to dense: no {collective} in jaxpr"
    out = jax.jit(fwd)(variables)
    assert bool(jnp.all(jnp.isfinite(out["mlm_logits"])))


def test_ulysses_head_divisibility_error(devices):
    """num_heads not divisible by the seq ways must fail loudly (the
    ulysses op's own contract), not silently mis-shard."""
    with pytest.raises(ValueError, match="divisible"):
        _run_long(MeshConfig(data=2, seq=4), "ulysses", steps=1,
                  num_heads=2)


def test_seq_impl_unknown_raises(devices):
    with pytest.raises(KeyError):
        _run_long(MeshConfig(data=2, seq=4), "nope", steps=1)


def _run_gpt_long(mesh_cfg, impl, steps=6):
    from deeplearning_cfn_tpu.data import build_pipeline
    from deeplearning_cfn_tpu.train import create_train_state
    from deeplearning_cfn_tpu.train.optim import build_optimizer, \
        build_schedule
    from deeplearning_cfn_tpu.train.task import build_task
    from deeplearning_cfn_tpu.train.trainer import Trainer

    cfg = ExperimentConfig(
        model=ModelConfig(name="gpt_long",
                          kwargs=dict(vocab_size=64, hidden_size=32,
                                      num_layers=2, num_heads=4,
                                      mlp_dim=64, max_len=32,
                                      seq_impl=impl)),
        data=DataConfig(name="lm_text", seq_len=32, vocab_size=64,
                        num_train_examples=128, prefetch=0),
        train=TrainConfig(global_batch=16, dtype="float32"),
        optimizer=OptimizerConfig(name="adamw", weight_decay=0.01),
        schedule=ScheduleConfig(name="constant", base_lr=3e-3,
                                warmup_steps=0),
        mesh=mesh_cfg,
    )
    mesh = build_mesh(cfg.mesh)
    task = build_task(cfg, mesh=mesh)
    sched = build_schedule(cfg.schedule, 100, 16, 8)
    tx = build_optimizer(cfg.optimizer, sched)
    state = create_train_state(jax.random.PRNGKey(0), task.init, tx, mesh,
                               param_rules=task.param_rules)
    trainer = Trainer(cfg, task.loss_fn, tx, mesh=mesh, donate=False)
    pipe = build_pipeline(cfg.data, 16, 0, seed=0, train=True)
    it = pipe.epochs()
    losses = []
    for _ in range(steps):
        batch = trainer.device_batch(next(it))
        state, m = trainer.train_step(state, batch, jax.random.PRNGKey(1))
        losses.append(float(m["loss"]))
    return state, losses


@pytest.mark.parametrize("impl", ["ring", "ulysses"])
def test_gpt_long_seq_parallel_matches_data_parallel(impl, devices):
    """The CAUSAL long-context trunk: gpt_long on (data=2, seq=4)
    reproduces pure-DP numerics — proving the sequence-parallel ops'
    causal masking composes correctly with global block offsets."""
    state_sp, loss_sp = _run_gpt_long(MeshConfig(data=2, seq=4), impl)
    state_dp, loss_dp = _run_gpt_long(MeshConfig(data=8), impl)
    np.testing.assert_allclose(loss_sp, loss_dp, rtol=3e-4)
    for a, b in zip(jax.tree_util.tree_leaves(state_sp.params),
                    jax.tree_util.tree_leaves(state_dp.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-3)


@pytest.mark.parametrize("impl,collective", [("ring", "ppermute"),
                                             ("ulysses", "all_to_all")])
def test_gpt_long_attention_actually_parallel(impl, collective, devices):
    from deeplearning_cfn_tpu.models import build_model

    mesh = build_mesh(MeshConfig(data=2, seq=4))
    model = build_model("gpt_long", 0, jnp.float32, vocab_size=64,
                        hidden_size=32, num_layers=1, num_heads=4,
                        mlp_dim=64, max_len=32, seq_impl=impl, mesh=mesh,
                        batch_axes="data")
    ids = jnp.zeros((8, 32), jnp.int32)
    variables = model.init(jax.random.PRNGKey(0), ids, train=False)
    fwd = lambda v: model.apply(v, ids, train=False)
    jaxpr_text = str(jax.make_jaxpr(fwd)(variables))
    assert collective in jaxpr_text, \
        f"{impl} attention fell back to dense: no {collective} in jaxpr"
    out = jax.jit(fwd)(variables)
    assert bool(jnp.all(jnp.isfinite(out)))


@pytest.mark.parametrize("impl", ["ring"])
def test_three_axis_composition_matches_data_parallel(impl, devices):
    """DP × TP × SP composed on one mesh: bert_long on
    (data=2, seq=2, model=2) — batch sharded, block kernels sharded over
    'model' (PARAM_RULES), sequence sharded with ring attention — must
    reproduce the pure-DP (data=8) trajectory. The strongest composition
    claim a fake-device mesh can prove."""
    state_3ax, loss_3ax = _run_long(MeshConfig(data=2, seq=2, model=2),
                                    impl, num_heads=4)
    state_dp, loss_dp = _run_long(MeshConfig(data=8), impl, num_heads=4)
    np.testing.assert_allclose(loss_3ax, loss_dp, rtol=3e-4)
    # Param atol 1e-2 (vs 5e-3 for the single-axis tests): THREE distinct
    # reduction orders (TP psum, ring online-softmax, DP grad psum) each
    # contribute f32 noise the optimizer amplifies over the steps; the
    # rtol-tight loss trajectory above is the equivalence pin.
    for a, b in zip(jax.tree_util.tree_leaves(state_3ax.params),
                    jax.tree_util.tree_leaves(state_dp.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-2)
    # The 3-axis run really sharded kernels over 'model'.
    n_tp = sum(
        1 for leaf in jax.tree_util.tree_leaves(state_3ax.params)
        if (spec := getattr(leaf.sharding, "spec", None))
        and any(ax == "model" for ax in spec if ax))
    assert n_tp >= 6, n_tp
