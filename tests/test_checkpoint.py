"""Checkpoint round-trip, atomicity, GC, cross-topology restore, and the
pluggable store layer (POSIX + object-store semantics)."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning_cfn_tpu.ckpt import (
    CheckpointManager,
    MemoryObjectStore,
    PosixStore,
    latest_checkpoint,
    open_store,
    restore_checkpoint,
    save_checkpoint,
)
from deeplearning_cfn_tpu.config import MeshConfig
from deeplearning_cfn_tpu.parallel import batch_sharding, build_mesh, replicated


def _tree():
    return {
        "params": {"w": jnp.arange(12.0).reshape(3, 4),
                   "b": jnp.ones((4,))},
        "step": jnp.asarray(7, jnp.int32),
    }


def test_roundtrip(tmp_workdir):
    state = _tree()
    save_checkpoint(tmp_workdir, 7, state)
    assert latest_checkpoint(tmp_workdir) == 7
    zeros = jax.tree_util.tree_map(jnp.zeros_like, state)
    restored, step = restore_checkpoint(tmp_workdir, zeros)
    assert step == 7
    np.testing.assert_array_equal(np.asarray(restored["params"]["w"]),
                                  np.asarray(state["params"]["w"]))
    assert int(restored["step"]) == 7


def test_uncommitted_invisible(tmp_workdir):
    state = _tree()
    path = save_checkpoint(tmp_workdir, 3, state)
    os.remove(os.path.join(path, "COMMIT"))
    assert latest_checkpoint(tmp_workdir) is None
    with pytest.raises(FileNotFoundError):
        restore_checkpoint(tmp_workdir, state)


def test_keep_k_gc(tmp_workdir):
    for step in [1, 2, 3, 4]:
        save_checkpoint(tmp_workdir, step, _tree(), keep=2)
    steps = sorted(
        int(d[len("step_"):]) for d in os.listdir(tmp_workdir)
        if d.startswith("step_")
    )
    assert steps == [3, 4]


def test_sharded_save_restore(tmp_workdir, devices):
    """A data-sharded array round-trips: each fake device's shard is written
    and the global array is reassembled with the current shardings."""
    mesh = build_mesh(MeshConfig(data=-1))
    x = np.arange(8 * 4, dtype=np.float32).reshape(8, 4)
    sharded = jax.device_put(x, batch_sharding(mesh, 2))
    state = {"x": sharded, "scalar": jnp.asarray(1.5)}
    save_checkpoint(tmp_workdir, 1, state)

    target = {"x": jnp.zeros((8, 4)), "scalar": jnp.asarray(0.0)}
    shardings = {"x": batch_sharding(mesh, 2), "scalar": replicated(mesh)}
    restored, _ = restore_checkpoint(tmp_workdir, target, shardings=shardings)
    np.testing.assert_array_equal(np.asarray(restored["x"]), x)
    assert restored["x"].sharding.spec == batch_sharding(mesh, 2).spec


def test_cross_topology_restore(tmp_workdir, devices):
    """Save sharded over 8 devices, restore replicated (topology change —
    the resize-via-resume story, SURVEY.md §4.5)."""
    mesh = build_mesh(MeshConfig(data=-1))
    x = np.arange(16, dtype=np.float32).reshape(8, 2)
    state = {"x": jax.device_put(x, batch_sharding(mesh, 2))}
    save_checkpoint(tmp_workdir, 5, state)
    restored, _ = restore_checkpoint(
        tmp_workdir, {"x": jnp.zeros((8, 2))},
        shardings={"x": replicated(mesh)},
    )
    np.testing.assert_array_equal(np.asarray(restored["x"]), x)


def test_manager_async_and_resume(tmp_workdir):
    mgr = CheckpointManager(tmp_workdir, every_steps=2, keep=2,
                            async_write=True)
    state = _tree()
    for step in [1, 2, 3, 4]:
        mgr.save(step, state)
    mgr.wait()
    assert latest_checkpoint(tmp_workdir) == 4
    restored, step = mgr.restore_or_none(
        jax.tree_util.tree_map(jnp.zeros_like, state)
    )
    assert step == 4
    none_mgr = CheckpointManager(os.path.join(tmp_workdir, "empty"))
    assert none_mgr.restore_or_none(state) == (None, None)


def test_manager_restore_explicit_step(tmp_workdir):
    """restore_or_none(step=N) restores an exact committed step read-only;
    a missing step errors instead of silently falling back to latest."""
    mgr = CheckpointManager(tmp_workdir, every_steps=2, keep=3,
                            async_write=False)
    for step in [2, 4, 6]:
        mgr.save(step, {"w": jnp.full((4,), float(step))})
    target = {"w": jnp.zeros((4,))}
    restored, step = mgr.restore_or_none(target, step=4)
    assert step == 4
    np.testing.assert_array_equal(np.asarray(restored["w"]),
                                  np.full((4,), 4.0))
    # Read-only: the later checkpoint is untouched.
    assert latest_checkpoint(tmp_workdir) == 6
    with pytest.raises(FileNotFoundError, match="available"):
        mgr.restore_or_none(target, step=3)


def test_rollback_checkpoints(tmp_workdir):
    """rollback_checkpoints deletes the whole timeline past the target —
    committed AND uncommitted dirs — so auto-resume picks the rollback
    point and re-saves start from empty directories."""
    from deeplearning_cfn_tpu.ckpt import rollback_checkpoints

    mgr = CheckpointManager(tmp_workdir, every_steps=2, keep=5,
                            async_write=False)
    for step in [2, 4, 6]:
        mgr.save(step, {"w": jnp.full((4,), float(step))})
    # An uncommitted (crashed) step dir past the rollback point must also
    # go: its stale manifests would poison a future re-save at that step.
    os.makedirs(os.path.join(tmp_workdir, "step_00000008"))
    with open(os.path.join(tmp_workdir, "step_00000008", "manifest_p7.json"),
              "w") as f:
        f.write("{}")

    deleted = rollback_checkpoints(tmp_workdir, 4)
    assert deleted == [6, 8]
    assert latest_checkpoint(tmp_workdir) == 4
    assert not os.path.exists(os.path.join(tmp_workdir, "step_00000006"))
    assert not os.path.exists(os.path.join(tmp_workdir, "step_00000008"))
    with pytest.raises(FileNotFoundError, match="available"):
        rollback_checkpoints(tmp_workdir, 3)


def test_missing_leaf_raises(tmp_workdir):
    save_checkpoint(tmp_workdir, 1, {"a": jnp.ones(3)})
    with pytest.raises(KeyError):
        restore_checkpoint(tmp_workdir, {"b": jnp.ones(3)})


def test_multiprocess_shard_files_restore_correctly(tmp_workdir, devices):
    """Regression (review finding): two processes saving shards with the same
    leaf names must not collide — restore merges per-process manifests."""
    import json

    ckpt_dir = os.path.join(tmp_workdir, "step_00000001")
    os.makedirs(ckpt_dir)
    full = np.arange(8, dtype=np.float32).reshape(4, 2)
    # Hand-write the on-disk format as two processes would produce it:
    # p0 owns rows 0:2, p1 owns rows 2:4, identical npz keys "w::0".
    with open(os.path.join(ckpt_dir, "manifest.json"), "w") as fh:
        json.dump({"step": 1, "processes": 2, "leaves": {
            "w": {"kind": "array", "shape": [4, 2], "dtype": "float32"}}}, fh)
    for p, rows in [(0, (0, 2)), (1, (2, 4))]:
        np.savez(os.path.join(ckpt_dir, f"shards_p{p}.tmp.npz"),
                 **{"w::0": full[rows[0]:rows[1]]})
        os.replace(os.path.join(ckpt_dir, f"shards_p{p}.tmp.npz"),
                   os.path.join(ckpt_dir, f"shards_p{p}.npz"))
        with open(os.path.join(ckpt_dir, f"manifest_p{p}.json"), "w") as fh:
            json.dump({"process": p, "leaves": {"w": [
                {"key": "w::0", "index": [[rows[0], rows[1]], [0, 2]]}]}}, fh)
        with open(os.path.join(ckpt_dir, f"DONE_p{p}"), "w") as fh:
            fh.write("1")
    with open(os.path.join(ckpt_dir, "COMMIT"), "w") as fh:
        fh.write("1")

    restored, step = restore_checkpoint(tmp_workdir, {"w": jnp.zeros((4, 2))})
    assert step == 1
    np.testing.assert_array_equal(np.asarray(restored["w"]), full)


def test_store_interface_posix_and_memory(tmp_workdir):
    """Both store backends satisfy the atomic-object contract the commit
    protocol relies on."""
    for store in (PosixStore(os.path.join(tmp_workdir, "s")),
                  MemoryObjectStore()):
        store.put_bytes("a/b/c.txt", b"hello")
        assert store.exists("a/b/c.txt")
        assert store.get_bytes("a/b/c.txt") == b"hello"
        store.put_npz("a/x.npz", {"w": np.arange(4.0)})
        z = store.get_npz("a/x.npz")
        np.testing.assert_array_equal(z["w"], np.arange(4.0))
        z.close()
        assert sorted(store.list("a/")) == ["a/b/c.txt", "a/x.npz"]
        assert store.list_subdirs("") == ["a"]
        assert store.list_subdirs("a/") == ["b"]
        store.delete_prefix("a/b/")
        assert store.list("a/") == ["a/x.npz"]
        assert not store.exists("a/b/c.txt")


def test_open_store_dispatch(tmp_workdir):
    assert isinstance(open_store(tmp_workdir), PosixStore)
    mem = MemoryObjectStore()
    assert open_store(mem) is mem


def test_roundtrip_against_object_store(devices):
    """The full two-phase checkpoint protocol — sharded save, DONE/COMMIT,
    GC, restore with current-mesh shardings — runs against an object store
    (no rename, no directories): the GCS-role contract of SURVEY §6."""
    store = MemoryObjectStore()
    mesh = build_mesh(MeshConfig(data=-1))
    x = np.arange(8 * 4, dtype=np.float32).reshape(8, 4)
    state = {"x": jax.device_put(x, batch_sharding(mesh, 2)),
             "step": jnp.asarray(3, jnp.int32)}
    for step in [1, 2, 3]:
        save_checkpoint(store, step, state, keep=2)
    # GC kept the newest 2; COMMIT objects gate visibility.
    assert sorted(
        int(k.split("/")[0][len("step_"):])
        for k in store.list("") if k.endswith("/COMMIT")) == [2, 3]
    assert latest_checkpoint(store) == 3

    target = {"x": jnp.zeros((8, 4)), "step": jnp.asarray(0, jnp.int32)}
    shardings = {"x": batch_sharding(mesh, 2), "step": replicated(mesh)}
    restored, step = restore_checkpoint(store, target, shardings=shardings)
    assert step == 3
    np.testing.assert_array_equal(np.asarray(restored["x"]), x)
    assert restored["x"].sharding.spec == batch_sharding(mesh, 2).spec


def test_object_store_uncommitted_invisible(devices):
    store = MemoryObjectStore()
    save_checkpoint(store, 4, _tree())
    store.delete_prefix("step_00000004/COMMIT")
    assert latest_checkpoint(store) is None
    with pytest.raises(FileNotFoundError):
        restore_checkpoint(store, _tree())


def test_manager_against_object_store(devices):
    store = MemoryObjectStore()
    mgr = CheckpointManager(store, every_steps=2, keep=2, async_write=True)
    state = _tree()
    for step in [1, 2, 3, 4]:
        mgr.save(step, state)
    mgr.wait()
    assert latest_checkpoint(store) == 4
    restored, step = mgr.restore_or_none(
        jax.tree_util.tree_map(jnp.zeros_like, state))
    assert step == 4
    np.testing.assert_array_equal(np.asarray(restored["params"]["w"]),
                                  np.asarray(state["params"]["w"]))


def test_incomplete_shard_coverage_raises(tmp_workdir, devices):
    """A checkpoint whose shard files don't cover the full array must raise,
    not silently restore zeros."""
    import json

    ckpt_dir = os.path.join(tmp_workdir, "step_00000001")
    os.makedirs(ckpt_dir)
    with open(os.path.join(ckpt_dir, "manifest.json"), "w") as fh:
        json.dump({"step": 1, "processes": 1, "leaves": {
            "w": {"kind": "array", "shape": [4, 2], "dtype": "float32"}}}, fh)
    np.savez(os.path.join(ckpt_dir, "shards_p0.tmp.npz"),
             **{"w::0": np.ones((2, 2), np.float32)})
    os.replace(os.path.join(ckpt_dir, "shards_p0.tmp.npz"),
               os.path.join(ckpt_dir, "shards_p0.npz"))
    with open(os.path.join(ckpt_dir, "manifest_p0.json"), "w") as fh:
        json.dump({"process": 0, "leaves": {"w": [
            {"key": "w::0", "index": [[0, 2], [0, 2]]}]}}, fh)
    with open(os.path.join(ckpt_dir, "COMMIT"), "w") as fh:
        fh.write("1")
    with pytest.raises(ValueError, match="cover only"):
        restore_checkpoint(tmp_workdir, {"w": jnp.zeros((4, 2))})
