"""Model zoo: shapes, param counts, dtype policy."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning_cfn_tpu.models import build_model


def _param_count(params):
    return sum(np.prod(p.shape) for p in jax.tree_util.tree_leaves(params))


def test_resnet20_shapes_and_params():
    model = build_model("resnet20", num_classes=10, dtype=jnp.float32)
    x = jnp.zeros((2, 32, 32, 3))
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    logits = model.apply(variables, x, train=False)
    assert logits.shape == (2, 10)
    assert logits.dtype == jnp.float32
    # He et al. ResNet-20 is ~0.27M params.
    n = _param_count(variables["params"])
    assert 0.2e6 < n < 0.35e6, n


def test_resnet50_shapes_and_params():
    model = build_model("resnet50", num_classes=1000, dtype=jnp.bfloat16)
    x = jnp.zeros((1, 64, 64, 3))  # small spatial for test speed
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    n = _param_count(variables["params"])
    # Canonical ResNet-50 ≈ 25.6M params.
    assert 24e6 < n < 27e6, n
    logits = model.apply(variables, x, train=False)
    assert logits.shape == (1, 1000)
    assert logits.dtype == jnp.float32  # head forced to f32


def test_space_to_depth_exact():
    from deeplearning_cfn_tpu.models.resnet import space_to_depth

    x = jnp.arange(2 * 8 * 8 * 3, dtype=jnp.float32).reshape(2, 8, 8, 3)
    y = space_to_depth(x, 2)
    assert y.shape == (2, 4, 4, 12)
    # Block (i,j) of the output must hold the 2×2 input block row-major:
    # channels [0:3]=(2i,2j), [3:6]=(2i,2j+1), [6:9]=(2i+1,2j), [9:12]=(2i+1,2j+1).
    np.testing.assert_array_equal(y[0, 1, 2, 0:3], x[0, 2, 4, :])
    np.testing.assert_array_equal(y[0, 1, 2, 3:6], x[0, 2, 5, :])
    np.testing.assert_array_equal(y[0, 1, 2, 6:9], x[0, 3, 4, :])
    np.testing.assert_array_equal(y[0, 1, 2, 9:12], x[0, 3, 5, :])


def test_resnet50_s2d_stem():
    # The s2d variant must produce the same output shape as the classic
    # stem (downstream stages are identical) with a 4×4×12 stem kernel.
    model = build_model("resnet50_s2d", num_classes=1000, dtype=jnp.bfloat16)
    x = jnp.zeros((1, 64, 64, 3))
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    stem_kernel = variables["params"]["conv_init_s2d"]["kernel"]
    assert stem_kernel.shape == (4, 4, 12, 64), stem_kernel.shape
    logits = model.apply(variables, x, train=False)
    assert logits.shape == (1, 1000)
    n = _param_count(variables["params"])
    assert 24e6 < n < 27e6, n  # same ballpark as classic resnet50


def test_batchnorm_stats_update():
    model = build_model("resnet20", num_classes=10, dtype=jnp.float32)
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 32, 32, 3))
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    _, mutated = model.apply(variables, x, train=True,
                             mutable=["batch_stats"])
    before = jax.tree_util.tree_leaves(variables["batch_stats"])
    after = jax.tree_util.tree_leaves(mutated["batch_stats"])
    assert any(not np.allclose(np.asarray(b), np.asarray(a))
               for b, a in zip(before, after))


def test_bn_params_stay_f32_under_bf16():
    model = build_model("resnet50", num_classes=10, dtype=jnp.bfloat16)
    x = jnp.zeros((1, 32, 32, 3))
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    flat = jax.tree_util.tree_leaves_with_path(variables["params"])
    for path, leaf in flat:
        assert leaf.dtype == jnp.float32, path


def test_unknown_model_raises():
    with pytest.raises(KeyError):
        build_model("nonexistent", num_classes=2, dtype=jnp.float32)
