"""Large-batch recipe validation (r03 verdict, Next #3): the cheapest
available de-risking of the north-star claims. The 75.9%-top-1 recipe risk
is LARS + warmup at pod-scale global batches (SURVEY §8 hard-part #3) —
untestable at pod scale here, but its failure mode (trust-ratio/warmup
mis-tuned → large-batch training stalls while small-batch converges) is
fully visible at CPU scale through gradient accumulation, which emulates
the device count (trainer.py's documented DP-equivalent averaging).

Two checks, both slow-marked:
- CIFAR ResNet-20: LARS at effective batch 1024 (accum 8) + warmup must
  optimize comparably to the small-batch momentum baseline in 8x fewer
  steps.
- BERT-tiny: LAMB at effective batch 256 (accum 8) must match the
  small-batch AdamW loss-curve drop (the BERT-recipe analogue).
"""

import jax
import numpy as np
import pytest

from deeplearning_cfn_tpu.config import (
    DataConfig,
    ExperimentConfig,
    MeshConfig,
    ModelConfig,
    OptimizerConfig,
    ScheduleConfig,
    TrainConfig,
)
from deeplearning_cfn_tpu.data import build_pipeline
from deeplearning_cfn_tpu.parallel.mesh import build_mesh, local_batch_size
from deeplearning_cfn_tpu.train import create_train_state
from deeplearning_cfn_tpu.train.optim import build_optimizer, build_schedule
from deeplearning_cfn_tpu.train.task import build_task
from deeplearning_cfn_tpu.train.trainer import Trainer


def _train(cfg, steps):
    """Run ``steps`` train steps; return (first_loss, last_metrics)."""
    mesh = build_mesh(cfg.mesh)
    task = build_task(cfg)
    tx = build_optimizer(
        cfg.optimizer,
        build_schedule(cfg.schedule, steps, cfg.train.global_batch, 0))
    state = create_train_state(
        jax.random.PRNGKey(0), task.init, tx, mesh,
        param_rules=getattr(task, "param_rules", ()))
    trainer = Trainer(cfg, task.loss_fn, tx, mesh=mesh, donate=False)
    pipe = build_pipeline(cfg.data,
                          local_batch_size(cfg.train.global_batch, mesh),
                          cfg.model.num_classes, seed=0, train=True)
    it = pipe.epochs()
    first = None
    m = {}
    for _ in range(steps):
        state, m = trainer.train_step(
            state, trainer.device_batch(next(it)), jax.random.PRNGKey(1))
        if first is None:
            first = float(m["loss"])
    return first, {k: float(v) for k, v in jax.device_get(m).items()}


def _cifar_cfg(gb, accum, opt, sched):
    return ExperimentConfig(
        model=ModelConfig(name="resnet20", num_classes=10),
        data=DataConfig(name="cifar10", image_size=32,
                        num_train_examples=2048, prefetch=0),
        train=TrainConfig(global_batch=gb, grad_accum_steps=accum,
                          dtype="float32"),
        optimizer=opt, schedule=sched, mesh=MeshConfig(data=-1))


@pytest.mark.slow
def test_lars_large_accum_matches_small_batch_momentum(devices):
    """LARS + warmup at effective batch 1024 (16x the baseline's 64,
    emulated via accum 8 — the pod-device-count emulation) must optimize
    the same task to comparable train accuracy in 8x fewer steps. A
    mis-tuned trust ratio or missing warmup fails exactly this check —
    the small-scale shadow of the 75.9% recipe risk."""
    base_first, base = _train(
        _cifar_cfg(64, 1,
                   OptimizerConfig(name="momentum", momentum=0.9,
                                   weight_decay=1e-4),
                   ScheduleConfig(name="cosine", base_lr=0.1,
                                  warmup_steps=0)),
        steps=160)
    # The baseline must itself converge hard, or the comparison is vacuous.
    assert base["loss"] < 0.15 and base["accuracy"] > 0.95, base

    lars_first, lars = _train(
        _cifar_cfg(1024, 8,
                   OptimizerConfig(name="lars", momentum=0.9,
                                   weight_decay=1e-4),
                   ScheduleConfig(name="cosine", base_lr=5.0,
                                  warmup_steps=4)),
        steps=20)
    assert np.isfinite(lars["loss"]), "LARS diverged at large batch"
    # Tuned r04 reference point: loss 0.82 / acc 0.80 at these settings.
    # Thresholds leave noise margin while still failing a broken recipe
    # (an untuned run at the same budget sits at loss ~2.2 / acc ~0.14).
    assert lars["loss"] < 1.4, (lars_first, lars)
    assert lars["accuracy"] > base["accuracy"] - 0.35, (base, lars)


def _bert_cfg(gb, accum, opt, sched):
    return ExperimentConfig(
        model=ModelConfig(name="bert_tiny", num_classes=2,
                          kwargs=dict(vocab_size=64, hidden_size=32,
                                      num_layers=2, num_heads=2,
                                      mlp_dim=64, max_len=32)),
        data=DataConfig(name="wikipedia_mlm", seq_len=32, vocab_size=64,
                        num_train_examples=2048, prefetch=0),
        train=TrainConfig(global_batch=gb, grad_accum_steps=accum,
                          dtype="float32"),
        optimizer=opt, schedule=sched, mesh=MeshConfig(data=-1))


@pytest.mark.slow
def test_lamb_large_accum_matches_adamw_loss_curve(devices):
    """The BERT-recipe analogue: LAMB at effective batch 256 (accum 8)
    must reproduce a comparable MLM loss-curve drop to the small-batch
    AdamW baseline (r04 tuning: adamw 4.81->3.95, lamb 4.90->4.10)."""
    a_first, a = _train(
        _bert_cfg(32, 1,
                  OptimizerConfig(name="adamw", weight_decay=0.01),
                  ScheduleConfig(name="cosine", base_lr=3e-3,
                                 warmup_steps=15)),
        steps=120)
    adamw_drop = a_first - a["loss"]
    assert adamw_drop > 0.5, (a_first, a)

    l_first, l = _train(
        _bert_cfg(256, 8,
                  OptimizerConfig(name="lamb", weight_decay=0.01),
                  ScheduleConfig(name="cosine", base_lr=2e-2,
                                 warmup_steps=10)),
        steps=80)
    lamb_drop = l_first - l["loss"]
    assert np.isfinite(l["loss"]), "LAMB diverged at large batch"
    assert lamb_drop > 0.6 * adamw_drop, (
        f"LAMB large-batch drop {lamb_drop:.3f} vs AdamW {adamw_drop:.3f}")
