"""Pipeline parallelism (ops/pipeline.py, models/pipelined.py, 'pipe' axis).

The reference has no pipeline parallelism (SURVEY.md §3.2 lists PP as
absent); these tests hold the rebuild's extension to the same bar as
TP/EP: the SPMD GPipe schedule is proven EXACT against a sequential
application of the same stacked layers (forward and gradients), and the
pipelined model is proven numerically invisible vs pure DP while its
trunk params are asserted actually sharded over 'pipe'.
"""

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning_cfn_tpu.config import (
    DataConfig,
    ExperimentConfig,
    MeshConfig,
    ModelConfig,
    OptimizerConfig,
    ScheduleConfig,
    TrainConfig,
)
from deeplearning_cfn_tpu.ops.pipeline import gpipe, scan_layers
from deeplearning_cfn_tpu.parallel.mesh import build_mesh


def _toy():
    l, b, f = 8, 16, 4
    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(0), 3)
    params = {"w": jax.random.normal(k1, (l, f, f)) * 0.5,
              "b": jax.random.normal(k2, (l, f)) * 0.1}
    x = jax.random.normal(k3, (b, f))
    stage = scan_layers(lambda lp, h: jnp.tanh(h @ lp["w"] + lp["b"]))
    return params, x, stage


def test_gpipe_forward_matches_sequential(devices):
    """4 stages x 2 layers each over (pipe=4, data=2): bit-level same
    result as scanning all 8 layers on one device."""
    mesh = build_mesh(MeshConfig(data=2, pipe=4))
    params, x, stage = _toy()
    y_ref = stage(params, x)
    y_pipe = jax.jit(lambda p, x: gpipe(
        stage, p, x, mesh=mesh, n_microbatches=4))(params, x)
    np.testing.assert_allclose(np.asarray(y_pipe), np.asarray(y_ref),
                               atol=1e-6)


def test_gpipe_gradients_match_sequential(devices):
    """AD through the schedule (scan + ppermute transposes) reproduces the
    sequential gradients for params AND inputs."""
    mesh = build_mesh(MeshConfig(data=2, pipe=4))
    params, x, stage = _toy()
    ref = jax.grad(lambda p, x: jnp.sum(stage(p, x) ** 2),
                   argnums=(0, 1))(params, x)
    piped = jax.jit(jax.grad(
        lambda p, x: jnp.sum(gpipe(stage, p, x, mesh=mesh,
                                   n_microbatches=4) ** 2),
        argnums=(0, 1)))(params, x)
    for a, b in zip(jax.tree_util.tree_leaves(ref),
                    jax.tree_util.tree_leaves(piped)):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a), atol=1e-5)


def test_gpipe_passthrough_state(devices):
    """Non-computed leaves (the attention-bias role) ride the pipeline
    unchanged and come back intact."""
    mesh = build_mesh(MeshConfig(data=2, pipe=4))
    params, x, _ = _toy()
    aux = jnp.arange(16.0).reshape(16, 1)

    def stage(lp, st):
        def step(s, layer_params):
            h = jnp.tanh(s["h"] @ layer_params["w"] + layer_params["b"])
            return {"h": h + 0.0 * s["aux"], "aux": s["aux"]}, None
        out, _ = jax.lax.scan(step, st, lp)
        return out

    out = jax.jit(lambda p, xs: gpipe(stage, p, xs, mesh=mesh,
                                      n_microbatches=4))(
        params, {"h": x, "aux": aux})
    np.testing.assert_allclose(np.asarray(out["aux"]), np.asarray(aux))


def _run_pipelined(mesh_cfg, steps=10):
    from deeplearning_cfn_tpu.data import build_pipeline
    from deeplearning_cfn_tpu.train import create_train_state
    from deeplearning_cfn_tpu.train.optim import build_optimizer, \
        build_schedule
    from deeplearning_cfn_tpu.train.task import build_task
    from deeplearning_cfn_tpu.train.trainer import Trainer

    cfg = ExperimentConfig(
        model=ModelConfig(name="bert_pipelined", num_classes=2,
                          kwargs=dict(vocab_size=64, hidden_size=32,
                                      num_layers=4, num_heads=2,
                                      mlp_dim=64, max_len=32,
                                      n_microbatches=4)),
        data=DataConfig(name="wikipedia_mlm", seq_len=32, vocab_size=64,
                        num_train_examples=256, prefetch=0),
        train=TrainConfig(global_batch=32, dtype="float32"),
        optimizer=OptimizerConfig(name="adamw", weight_decay=0.01),
        schedule=ScheduleConfig(name="constant", base_lr=3e-3,
                                warmup_steps=0),
        mesh=mesh_cfg,
    )
    mesh = build_mesh(cfg.mesh)
    task = build_task(cfg, mesh=mesh)
    sched = build_schedule(cfg.schedule, 100, 32, 8)
    tx = build_optimizer(cfg.optimizer, sched)
    state = create_train_state(jax.random.PRNGKey(0), task.init, tx, mesh,
                               param_rules=task.param_rules)
    trainer = Trainer(cfg, task.loss_fn, tx, mesh=mesh, donate=False)
    pipe = build_pipeline(cfg.data, 32, 2, seed=0, train=True)
    it = pipe.epochs()
    losses = []
    for _ in range(steps):
        batch = trainer.device_batch(next(it))
        state, m = trainer.train_step(state, batch, jax.random.PRNGKey(1))
        losses.append(float(m["loss"]))
    return state, losses


def test_pipeline_parallel_matches_data_parallel(devices):
    """bert_pipelined trained 10 steps on a (pipe=2, data=4) mesh
    reproduces the pure-DP (data=8) run — same loss trajectory, same final
    params — while the stacked trunk weights are actually sharded over
    'pipe'."""
    state_pp, loss_pp = _run_pipelined(MeshConfig(data=4, pipe=2))
    state_dp, loss_dp = _run_pipelined(MeshConfig(data=8))

    n_sharded = 0
    for leaf in jax.tree_util.tree_leaves(state_pp.params):
        spec = getattr(getattr(leaf, "sharding", None), "spec", None)
        if spec is not None and len(spec) and spec[0] == "pipe":
            n_sharded += 1
            assert leaf.addressable_shards[0].data.shape[0] \
                == leaf.shape[0] // 2
    assert n_sharded == 16, \
        f"expected all 16 stacked trunk params pipe-sharded, {n_sharded}"

    np.testing.assert_allclose(loss_pp, loss_dp, rtol=2e-4)
    # Params: atol 2e-3 — the pipelined trunk reduces attention/microbatch
    # sums in a different order and 10 adamw steps accumulate that float32
    # noise; anything semantic (wrong stage wiring, a dropped microbatch)
    # is orders of magnitude larger AND caught by the loss check above and
    # the bit-exact single-call tests further up.
    for a, b in zip(jax.tree_util.tree_leaves(state_pp.params),
                    jax.tree_util.tree_leaves(state_dp.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-3)
    assert loss_pp[-1] < loss_pp[0]
