"""data prepare-coco: real COCO JSON + images → the detection npz contract,
checked on a generated 3-image mini-COCO (known geometry), including the
mask paste round-trip against metrics/coco_map's PastedMask convention and
a short end-to-end train from the converted shards."""

import json
import os

import numpy as np
import pytest

from deeplearning_cfn_tpu.data.coco import prepare_coco


def _mini_coco(tmp_path):
    """3 images: (a) 100x80 with a centered axis-aligned square object and
    one iscrowd ann, (b) 60x60 with a triangle + 3 extra tiny objects (to
    trip max_boxes=3), (c) 40x120 with no annotations."""
    from PIL import Image

    img_dir = tmp_path / "imgs"
    img_dir.mkdir()
    rng = np.random.RandomState(0)
    sizes = {"a.jpg": (100, 80), "b.jpg": (60, 60), "c.jpg": (120, 40)}
    for name, (w, h) in sizes.items():
        arr = (rng.rand(h, w, 3) * 255).astype(np.uint8)
        Image.fromarray(arr).save(img_dir / name, quality=95)

    square = [20.0, 10.0, 40.0, 40.0]  # x, y, w, h
    square_poly = [20.0, 10.0, 60.0, 10.0, 60.0, 50.0, 20.0, 50.0]
    triangle = [5.0, 5.0, 30.0, 40.0]
    triangle_poly = [5.0, 45.0, 35.0, 45.0, 20.0, 5.0]
    anns = [
        {"id": 1, "image_id": 1, "category_id": 7, "bbox": square,
         "area": 1600.0, "segmentation": [square_poly], "iscrowd": 0},
        {"id": 2, "image_id": 1, "category_id": 3, "bbox": [0, 0, 50, 60],
         "area": 3000.0, "segmentation": {"counts": "rle"}, "iscrowd": 1},
        {"id": 3, "image_id": 2, "category_id": 11, "bbox": triangle,
         "area": 525.0, "segmentation": [triangle_poly], "iscrowd": 0},
    ]
    # 3 tiny extra objects on image b → with max_boxes=3 one must drop
    # (largest-first keeps the triangle + 2 of these).
    for k in range(3):
        anns.append({"id": 10 + k, "image_id": 2, "category_id": 2,
                     "bbox": [2.0 * k, 50.0, 4.0, 4.0], "area": 16.0 - k,
                     "segmentation": [], "iscrowd": 0})
    coco = {
        "images": [
            {"id": 1, "file_name": "a.jpg", "width": 100, "height": 80},
            {"id": 2, "file_name": "b.jpg", "width": 60, "height": 60},
            {"id": 3, "file_name": "c.jpg", "width": 120, "height": 40},
        ],
        "annotations": anns,
        "categories": [{"id": i, "name": str(i)} for i in (2, 3, 7, 11)],
    }
    ann_path = tmp_path / "instances.json"
    ann_path.write_text(json.dumps(coco))
    return str(ann_path), str(img_dir)


def test_prepare_coco_geometry_and_contract(tmp_path):
    ann, imgs = _mini_coco(tmp_path)
    out = str(tmp_path / "npz")
    info = prepare_coco(ann, imgs, out, "train", image_size=64, max_boxes=3)
    # Objects kept: 1 on image a (square; crowd skipped) + 3 on image b
    # (triangle + 2 of the 3 tinies under max_boxes=3).
    assert info == {"images": 3, "objects": 4, "skipped_crowd": 1,
                    "skipped_degenerate": 0, "dropped_over_max": 1,
                    "image_size": 64, "max_boxes": 3}
    with np.load(os.path.join(out, "train.npz")) as z:
        image, boxes = z["image"], z["boxes"]
        labels, masks = z["labels"], z["masks"]
    assert image.shape == (3, 64, 64, 3) and image.dtype == np.uint8
    assert boxes.shape == (3, 3, 4) and masks.shape == (3, 3, 28, 28)

    # Image a: 100x80 → scale 64/100 = 0.64; square bbox (x20,y10,40x40) →
    # (y0,x0,y1,x1) = (6.4, 12.8, 32.0, 38.4).
    np.testing.assert_allclose(boxes[0, 0], [6.4, 12.8, 32.0, 38.4],
                               atol=1e-5)
    assert labels[0, 0] == 7
    # The crowd ann was skipped entirely — slot 1 stays padding.
    assert labels[0, 1] == 0 and np.all(boxes[0, 1] == 0)
    # Square polygon fills its own bbox: box-aligned mask ≈ all ones.
    assert masks[0, 0].mean() > 0.97
    # Image b kept 3 of 4 anns, largest (triangle, category 11) first.
    assert labels[1, 0] == 11 and (labels[1] > 0).sum() == 3
    # Triangle mask ≈ half its box, and the apex row is mostly empty.
    tri = masks[1, 0]
    assert 0.3 < tri.mean() < 0.7
    assert tri[-1].mean() > 0.8 and tri[0].mean() < 0.2
    # Image c: no objects; letterboxed region (height 40*64/120≈21) has
    # content, the padding below is zeros.
    assert labels[2].sum() == 0
    assert image[2, :21].any() and not image[2, 22:].any()


def test_prepare_coco_mask_pastes_back(tmp_path):
    """The stored box-aligned mask, pasted with PastedMask, must reproduce
    the polygon's image-space area (the same convention the mAP metric
    uses — converter and metric agree end to end)."""
    from deeplearning_cfn_tpu.metrics.coco_map import PastedMask

    ann, imgs = _mini_coco(tmp_path)
    out = str(tmp_path / "npz")
    prepare_coco(ann, imgs, out, "eval", image_size=64, max_boxes=3)
    with np.load(os.path.join(out, "eval.npz")) as z:
        boxes, masks = z["boxes"], z["masks"]
    # Triangle on image b: true area = 0.5 * 30 * 40 * (64/60)^2 scaled.
    scale = 64 / 60
    true_area = 0.5 * 30 * 40 * scale * scale
    pasted = PastedMask(masks[1, 0], boxes[1, 0], 64, 64)
    assert abs(pasted.count - true_area) / true_area < 0.15


def test_prepare_coco_errors(tmp_path):
    ann, imgs = _mini_coco(tmp_path)
    with pytest.raises(ValueError, match="split"):
        prepare_coco(ann, imgs, str(tmp_path / "x"), "test")
    empty = tmp_path / "empty.json"
    empty.write_text(json.dumps({"images": [], "annotations": []}))
    with pytest.raises(ValueError, match="no images"):
        prepare_coco(str(empty), imgs, str(tmp_path / "x"), "train")
    # The one-npz RAM guard: a projected >8 GiB split must refuse with
    # actionable guidance, before allocating anything.
    many = tmp_path / "many.json"
    many.write_text(json.dumps({
        "images": [{"id": i, "file_name": "a.jpg", "width": 10,
                    "height": 10} for i in range(20000)],
        "annotations": [],
    }))
    with pytest.raises(ValueError, match="GiB"):
        prepare_coco(str(many), imgs, str(tmp_path / "x"), "train",
                     image_size=1024)


def test_prepare_coco_degenerate_does_not_steal_slots(tmp_path):
    """A sub-pixel-after-scaling ann must be filtered BEFORE the max_boxes
    cap (and counted), so it can never waste a slot a real object needed."""
    from PIL import Image

    img_dir = tmp_path / "imgs"
    img_dir.mkdir()
    Image.fromarray(np.zeros((100, 100, 3), np.uint8)).save(
        img_dir / "z.jpg")
    anns = [
        # Degenerate: 0.5px wide at scale 16/100 — huge area claim so it
        # would have out-ranked the real objects under the cap.
        {"id": 1, "image_id": 1, "category_id": 5,
         "bbox": [0.0, 0.0, 0.5, 90.0], "area": 99999.0,
         "segmentation": [], "iscrowd": 0},
        {"id": 2, "image_id": 1, "category_id": 6,
         "bbox": [10.0, 10.0, 60.0, 60.0], "area": 3600.0,
         "segmentation": [], "iscrowd": 0},
        {"id": 3, "image_id": 1, "category_id": 7,
         "bbox": [30.0, 30.0, 50.0, 50.0], "area": 2500.0,
         "segmentation": [], "iscrowd": 0},
    ]
    ann_path = tmp_path / "inst.json"
    ann_path.write_text(json.dumps({
        "images": [{"id": 1, "file_name": "z.jpg", "width": 100,
                    "height": 100}],
        "annotations": anns,
    }))
    info = prepare_coco(str(ann_path), str(img_dir), str(tmp_path / "o"),
                        "train", image_size=16, max_boxes=2)
    assert info["skipped_degenerate"] == 1
    assert info["objects"] == 2 and info["dropped_over_max"] == 0
    with np.load(os.path.join(str(tmp_path / "o"), "train.npz")) as z:
        # Both REAL objects kept, contiguous from slot 0.
        assert list(z["labels"][0]) == [6, 7]


@pytest.mark.slow
def test_converted_coco_trains(tmp_path, devices):
    """Converted npz → maskrcnn train for a few steps via the real-data
    path (BASELINE.md tracking row 5's last gap: real COCO ingestion)."""
    from deeplearning_cfn_tpu.config import (
        CheckpointConfig,
        DataConfig,
        ExperimentConfig,
        MeshConfig,
        ModelConfig,
        OptimizerConfig,
        ScheduleConfig,
        TrainConfig,
    )
    from deeplearning_cfn_tpu.train.run import run_experiment

    ann, imgs = _mini_coco(tmp_path)
    out = str(tmp_path / "npz")
    for split in ("train", "eval"):
        prepare_coco(ann, imgs, out, split, image_size=64, max_boxes=4)
    cfg = ExperimentConfig(
        model=ModelConfig(
            name="maskrcnn_resnet50", num_classes=12,
            kwargs=dict(image_size=64, pre_nms_topk=64, post_nms_topk=16,
                        num_mask_rois=4, anchor_scale=4.0)),
        data=DataConfig(name="coco", image_size=64, data_dir=out,
                        synthetic=False, max_boxes=4),
        train=TrainConfig(global_batch=2, steps=2, dtype="float32",
                          eval_batch=2, log_every_steps=1,
                          eval_every_steps=1000),
        optimizer=OptimizerConfig(name="momentum", momentum=0.9),
        schedule=ScheduleConfig(name="constant", base_lr=0.01,
                                warmup_steps=0),
        mesh=MeshConfig(data=2, model=4),
        checkpoint=CheckpointConfig(async_write=False),
        workdir=str(tmp_path / "run"),
    )
    final = run_experiment(cfg)
    assert np.isfinite(final.get("loss", np.nan))
