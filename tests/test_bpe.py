"""Byte-level BPE (data/bpe.py) and the real-text converters
(data prepare-wikipedia / prepare-wmt): training determinism, round trips,
npz contract conformance, and real-file -> shards -> train end-to-end."""

import json
import os

import numpy as np
import pytest

from deeplearning_cfn_tpu.data.bpe import (
    Bpe,
    MLM_SPECIALS,
    NMT_SPECIALS,
    train_bpe,
)
from deeplearning_cfn_tpu.data.text import prepare_mlm_text, prepare_nmt_text

CORPUS = [
    "the quick brown fox jumps over the lazy dog",
    "the quick brown fox is quick and brown",
    "pack my box with five dozen liquor jugs",
    "the lazy dog sleeps while the quick fox jumps",
] * 8


def test_bpe_roundtrip_and_compression():
    bpe = train_bpe(CORPUS, vocab_size=4 + 256 + 50, specials=MLM_SPECIALS)
    text = "the quick brown dog"
    ids = bpe.encode(text)
    assert bpe.decode(ids) == text
    # Merges must actually compress: fewer tokens than raw bytes+spaces.
    assert 0 < len(ids) < len(text.encode()) + 1
    # All ids in range, none colliding with specials.
    assert all(len(MLM_SPECIALS) <= i < bpe.vocab_size for i in ids)
    # Unseen-but-encodable text (byte fallback) round-trips too.
    weird = "zebra ünïcode"
    assert bpe.decode(bpe.encode(weird)) == weird


def test_bpe_training_is_deterministic():
    a = train_bpe(CORPUS, 4 + 256 + 30, MLM_SPECIALS)
    b = train_bpe(list(CORPUS), 4 + 256 + 30, MLM_SPECIALS)
    assert a.merges == b.merges


def test_bpe_save_load(tmp_path):
    bpe = train_bpe(CORPUS, 4 + 256 + 20, NMT_SPECIALS)
    path = str(tmp_path / "vocab.json")
    bpe.save(path)
    loaded = Bpe.load(path)
    assert loaded.merges == bpe.merges
    assert loaded.specials == bpe.specials
    s = "the quick fox"
    assert loaded.encode(s) == bpe.encode(s)


def test_bpe_decode_skips_specials_and_unknown():
    bpe = train_bpe(CORPUS, 4 + 256 + 5, MLM_SPECIALS)
    ids = [1] + bpe.encode("the fox") + [2, 10 ** 6]
    out = bpe.decode(ids)
    assert "[CLS]" in out and "[SEP]" in out and "the fox" in out


def test_prepare_wikipedia_contract(tmp_path):
    src = tmp_path / "corpus.txt"
    src.write_text("\n".join(CORPUS))
    out = str(tmp_path / "mlm")
    info = prepare_mlm_text(str(src), out, seq_len=32,
                            vocab_size=4 + 256 + 40, eval_fraction=0.2)
    assert os.path.exists(os.path.join(out, "vocab.json"))
    with np.load(os.path.join(out, "train.npz")) as z:
        keys = set(z.files)
        assert {"input_ids", "input_mask", "segment_ids", "mlm_positions",
                "mlm_ids", "mlm_weights", "nsp_label"} <= keys
        ii = z["input_ids"]
        assert ii.shape[1] == 32
        assert (ii[:, 0] == 1).all()          # [CLS]
        assert (ii < info["vocab_size"]).all() and (ii >= 0).all()
        # Masked positions exist and carry weights.
        assert z["mlm_weights"].sum() > 0
    assert info["train_examples"] > 0 and info["eval_examples"] > 0


def test_prepare_wmt_contract(tmp_path):
    src = tmp_path / "en.txt"
    tgt = tmp_path / "de.txt"
    pairs = [("the quick fox", "der schnelle fuchs"),
             ("a lazy dog", "ein fauler hund"),
             ("the dog sleeps", "der hund schlaeft"),
             ("", ""),  # empty pair -> skipped
             ("x " * 200, "y " * 200)] * 4  # over-length -> skipped
    src.write_text("\n".join(p[0] for p in pairs))
    tgt.write_text("\n".join(p[1] for p in pairs))
    out = str(tmp_path / "nmt")
    info = prepare_nmt_text(str(src), str(tgt), out, seq_len=24,
                            vocab_size=3 + 256 + 30, eval_fraction=0.25)
    assert info["skipped_pairs"] == 8
    with np.load(os.path.join(out, "train.npz")) as z:
        assert {"src_ids", "src_mask", "tgt_in_ids", "tgt_out_ids",
                "tgt_mask"} <= set(z.files)
        si, ti, to = z["src_ids"], z["tgt_in_ids"], z["tgt_out_ids"]
        assert si.shape[1] == 24
        assert (ti[:, 0] == 1).all()  # [BOS]
        for row_s, row_o, m in zip(si, to, z["tgt_mask"]):
            n = int(m.sum())
            assert row_o[n - 1] == 2          # EOS ends target
            assert 2 in row_s                 # EOS in source
    # Mismatched parallel files must be rejected.
    (tmp_path / "short.txt").write_text("one line")
    with pytest.raises(ValueError, match="parallel files differ"):
        prepare_nmt_text(str(src), str(tmp_path / "short.txt"), out, 24)


@pytest.mark.slow
def test_prepared_text_trains_bert_and_nmt(tmp_path, devices):
    """The full VERDICT #4 loop: real text file -> BPE shards -> BERT/NMT
    train via the real-data npz path, loss decreasing."""
    from deeplearning_cfn_tpu.config import (
        DataConfig,
        ExperimentConfig,
        MeshConfig,
        ModelConfig,
        OptimizerConfig,
        ScheduleConfig,
        TrainConfig,
    )
    from deeplearning_cfn_tpu.train.run import run_experiment

    src = tmp_path / "corpus.txt"
    src.write_text("\n".join(CORPUS * 8))
    mlm_dir = str(tmp_path / "mlm")
    info = prepare_mlm_text(str(src), mlm_dir, seq_len=32,
                            vocab_size=4 + 256 + 40, eval_fraction=0.2)

    cfg = ExperimentConfig(
        model=ModelConfig(name="bert_tiny", num_classes=2,
                          kwargs=dict(vocab_size=info["vocab_size"],
                                      hidden_size=32, num_layers=1,
                                      num_heads=2, mlp_dim=64, max_len=32)),
        data=DataConfig(name="wikipedia_mlm", seq_len=32,
                        vocab_size=info["vocab_size"], data_dir=mlm_dir,
                        synthetic=False),
        train=TrainConfig(global_batch=16, steps=12, dtype="float32",
                          eval_batch=16, log_every_steps=4),
        optimizer=OptimizerConfig(name="adamw", weight_decay=0.01),
        schedule=ScheduleConfig(name="constant", base_lr=3e-3,
                                warmup_steps=0),
        mesh=MeshConfig(data=-1),
        workdir=str(tmp_path / "bert_run"),
    )
    final = run_experiment(cfg)
    assert np.isfinite(final["loss"])

    en = tmp_path / "en.txt"
    de = tmp_path / "de.txt"
    lines = [("the quick fox runs", "der schnelle fuchs rennt"),
             ("a dog sleeps here", "ein hund schlaeft hier"),
             ("the fox and the dog", "der fuchs und der hund")] * 32
    en.write_text("\n".join(p[0] for p in lines))
    de.write_text("\n".join(p[1] for p in lines))
    nmt_dir = str(tmp_path / "nmt")
    ninfo = prepare_nmt_text(str(en), str(de), nmt_dir, seq_len=16,
                             vocab_size=3 + 256 + 20, eval_fraction=0.2)
    cfg2 = ExperimentConfig(
        model=ModelConfig(name="transformer_nmt_tiny",
                          kwargs=dict(vocab_size=ninfo["vocab_size"],
                                      hidden_size=32, num_layers=1,
                                      num_heads=2, mlp_dim=64, max_len=16)),
        data=DataConfig(name="wmt_en_de", seq_len=16,
                        vocab_size=ninfo["vocab_size"], data_dir=nmt_dir,
                        synthetic=False),
        train=TrainConfig(global_batch=16, steps=12, dtype="float32",
                          eval_batch=16, label_smoothing=0.0,
                          log_every_steps=4),
        optimizer=OptimizerConfig(name="adamw", b1=0.9, b2=0.98),
        schedule=ScheduleConfig(name="constant", base_lr=3e-3,
                                warmup_steps=0),
        mesh=MeshConfig(data=-1),
        workdir=str(tmp_path / "nmt_run"),
    )
    final2 = run_experiment(cfg2)
    assert np.isfinite(final2["loss"])
