"""Tests for L2: multi-host fan-out, log aggregation, failure detection and
auto-restart (the fault-injection tier SURVEY.md §6 specifies — the reference
had no equivalent: its Horovod jobs hung on node loss)."""

import os
import sys

from deeplearning_cfn_tpu.launch import JobLauncher, LocalTransport
from deeplearning_cfn_tpu.runtime.cluster import (
    ClusterSpec,
    ENV_PROCESS_ID,
    ENV_WORKERS_COUNT,
)


def _spec(n):
    return ClusterSpec(hosts=["127.0.0.1"] * n)


def _py(code: str):
    return [sys.executable, "-c", code]


def test_fanout_per_rank_env(tmp_path):
    """Every host gets the same argv but its own rank env (the reference's
    mpirun -np N semantics)."""
    out_dir = tmp_path / "out"
    out_dir.mkdir()
    code = (
        "import os; open(os.path.join(r'%s', "
        f"os.environ['{ENV_PROCESS_ID}']), 'w')"
        f".write(os.environ['{ENV_WORKERS_COUNT}'])" % out_dir
    )
    launcher = JobLauncher(transport=LocalTransport(), tail_rank0=False)
    result = launcher.run(_spec(3), _py(code), str(tmp_path / "logs"))
    assert result.success
    assert result.restarts == 0
    assert sorted(os.listdir(out_dir)) == ["0", "1", "2"]
    for i in range(3):
        assert (out_dir / str(i)).read_text() == "3"


def test_per_host_logs_aggregated(tmp_path):
    code = "import os; print('hello from rank', os.environ['%s'])" % \
        ENV_PROCESS_ID
    launcher = JobLauncher(transport=LocalTransport(), tail_rank0=False)
    result = launcher.run(_spec(2), _py(code), str(tmp_path / "logs"))
    assert result.success
    logs = sorted(os.listdir(result.log_dir))
    assert logs == ["attempt0-host0.log", "attempt0-host1.log"]
    text0 = (tmp_path / "logs" / logs[0]).read_text()
    assert "hello from rank 0" in text0


def test_failure_kills_survivors_fast(tmp_path):
    """One host dies → the launcher kills the rest instead of letting them
    hang in collectives (the reference's failure mode)."""
    # Rank 1 exits 1 immediately; rank 0 would sleep for an hour.
    code = (
        "import os, sys, time\n"
        f"rank = int(os.environ['{ENV_PROCESS_ID}'])\n"
        "sys.exit(1) if rank == 1 else time.sleep(3600)\n"
    )
    launcher = JobLauncher(transport=LocalTransport(), max_restarts=0,
                           tail_rank0=False)
    import time
    t0 = time.time()
    result = launcher.run(_spec(2), _py(code), str(tmp_path / "logs"))
    assert not result.success
    assert time.time() - t0 < 30  # did not wait for the sleeper
    assert result.exit_codes[1] == 1


def test_fault_injection_restart_resumes(tmp_path):
    """Kill-a-host fault injection: rank 1 crashes on the first attempt;
    the launcher restarts the whole job and the second attempt 'resumes'
    (observes prior attempt's marker) and succeeds."""
    # Per-rank markers: a shared marker would race — if rank 0 wrote it
    # before rank 1's interpreter started, rank 1 would skip the injected
    # crash and the job would succeed with restarts=0.
    marker = tmp_path / "attempt0_rank"
    code = (
        "import os, sys\n"
        f"rank = int(os.environ['{ENV_PROCESS_ID}'])\n"
        f"marker = r'{marker}' + str(rank)\n"
        "if not os.path.exists(marker):\n"
        "    open(marker, 'w').write('x')\n"
        "    sys.exit(7) if rank == 1 else sys.exit(0)\n"
        "print('RESUMED rank', rank)\n"
    )
    failures = []
    launcher = JobLauncher(transport=LocalTransport(), max_restarts=2,
                           tail_rank0=False)
    result = launcher.run(
        _spec(2), _py(code), str(tmp_path / "logs"),
        on_failure=lambda idx, host: failures.append(idx),
    )
    assert result.success
    assert result.restarts == 1
    assert failures == [1]
    # Attempt-1 logs show the resumed run.
    log = (tmp_path / "logs" / "attempt1-host1.log").read_text()
    assert "RESUMED rank 1" in log


def test_restart_budget_exhausted(tmp_path):
    launcher = JobLauncher(transport=LocalTransport(), max_restarts=1,
                           tail_rank0=False)
    result = launcher.run(_spec(2), _py("import sys; sys.exit(3)"),
                          str(tmp_path / "logs"))
    assert not result.success
    assert result.restarts == 1
    assert set(result.exit_codes) == {3}
