"""Tests for L2: multi-host fan-out, log aggregation, failure detection and
auto-restart (the fault-injection tier SURVEY.md §6 specifies — the reference
had no equivalent: its Horovod jobs hung on node loss)."""

import json
import os
import sys

import pytest

from deeplearning_cfn_tpu.launch import (
    JobLauncher,
    LocalTransport,
    SshTransport,
)
from deeplearning_cfn_tpu.runtime.cluster import (
    ClusterSpec,
    ENV_PROCESS_ID,
    ENV_WORKERS_COUNT,
)


def _spec(n):
    return ClusterSpec(hosts=["127.0.0.1"] * n)


def _py(code: str):
    return [sys.executable, "-c", code]


def test_fanout_per_rank_env(tmp_path):
    """Every host gets the same argv but its own rank env (the reference's
    mpirun -np N semantics)."""
    out_dir = tmp_path / "out"
    out_dir.mkdir()
    code = (
        "import os; open(os.path.join(r'%s', "
        f"os.environ['{ENV_PROCESS_ID}']), 'w')"
        f".write(os.environ['{ENV_WORKERS_COUNT}'])" % out_dir
    )
    launcher = JobLauncher(transport=LocalTransport(), tail_rank0=False)
    result = launcher.run(_spec(3), _py(code), str(tmp_path / "logs"))
    assert result.success
    assert result.restarts == 0
    assert sorted(os.listdir(out_dir)) == ["0", "1", "2"]
    for i in range(3):
        assert (out_dir / str(i)).read_text() == "3"


def test_per_host_logs_aggregated(tmp_path):
    code = "import os; print('hello from rank', os.environ['%s'])" % \
        ENV_PROCESS_ID
    launcher = JobLauncher(transport=LocalTransport(), tail_rank0=False)
    result = launcher.run(_spec(2), _py(code), str(tmp_path / "logs"))
    assert result.success
    logs = sorted(os.listdir(result.log_dir))
    assert logs == ["attempt0-host0.log", "attempt0-host1.log",
                    "launch.jsonl"]
    text0 = (tmp_path / "logs" / logs[0]).read_text()
    assert "hello from rank 0" in text0
    # Attempt lifecycle events land next to the host logs (obs report feed),
    # alongside the launch.attempt span the trace exporter draws as a bar.
    records = [json.loads(line) for line in
               (tmp_path / "logs" / "launch.jsonl").read_text().splitlines()]
    (event,) = [r for r in records if r.get("event") == "launch_attempt"]
    assert event["event"] == "launch_attempt"
    assert event["attempt"] == 0 and event["outcome"] == "ok"
    assert event["success"] is True and event["exit_codes"] == [0, 0]
    (span_rec,) = [r for r in records if r.get("span") == "launch.attempt"]
    assert span_rec["attempt"] == 0 and span_rec["outcome"] == "ok"
    assert span_rec["dur_s"] >= 0 and "ts" in span_rec


def test_failure_kills_survivors_fast(tmp_path):
    """One host dies → the launcher kills the rest instead of letting them
    hang in collectives (the reference's failure mode)."""
    # Rank 1 exits 1 immediately; rank 0 would sleep for an hour.
    code = (
        "import os, sys, time\n"
        f"rank = int(os.environ['{ENV_PROCESS_ID}'])\n"
        "sys.exit(1) if rank == 1 else time.sleep(3600)\n"
    )
    launcher = JobLauncher(transport=LocalTransport(), max_restarts=0,
                           tail_rank0=False)
    import time
    t0 = time.time()
    result = launcher.run(_spec(2), _py(code), str(tmp_path / "logs"))
    assert not result.success
    assert time.time() - t0 < 30  # did not wait for the sleeper
    assert result.exit_codes[1] == 1


def test_fault_injection_restart_resumes(tmp_path):
    """Kill-a-host fault injection: rank 1 crashes on the first attempt;
    the launcher restarts the whole job and the second attempt 'resumes'
    (observes prior attempt's marker) and succeeds."""
    # Per-rank markers: a shared marker would race — if rank 0 wrote it
    # before rank 1's interpreter started, rank 1 would skip the injected
    # crash and the job would succeed with restarts=0.
    marker = tmp_path / "attempt0_rank"
    code = (
        "import os, sys\n"
        f"rank = int(os.environ['{ENV_PROCESS_ID}'])\n"
        f"marker = r'{marker}' + str(rank)\n"
        "if not os.path.exists(marker):\n"
        "    open(marker, 'w').write('x')\n"
        "    sys.exit(7) if rank == 1 else sys.exit(0)\n"
        "print('RESUMED rank', rank)\n"
    )
    failures = []
    launcher = JobLauncher(transport=LocalTransport(), max_restarts=2,
                           tail_rank0=False)
    result = launcher.run(
        _spec(2), _py(code), str(tmp_path / "logs"),
        on_failure=lambda idx, host: failures.append(idx),
    )
    assert result.success
    assert result.restarts == 1
    assert failures == [1]
    # Attempt-1 logs show the resumed run.
    log = (tmp_path / "logs" / "attempt1-host1.log").read_text()
    assert "RESUMED rank 1" in log


def test_restart_budget_exhausted(tmp_path):
    launcher = JobLauncher(transport=LocalTransport(), max_restarts=1,
                           tail_rank0=False)
    result = launcher.run(_spec(2), _py("import sys; sys.exit(3)"),
                          str(tmp_path / "logs"))
    assert not result.success
    assert result.restarts == 1
    assert set(result.exit_codes) == {3}
    assert result.attempt_outcomes == ["crash", "crash"]


def test_hang_vs_crash_classified_per_attempt(tmp_path):
    """The watchdog's deliberate exit 89 is recorded as 'hang', anything
    else nonzero as 'crash' — per attempt, so operators can tell a wedged
    collective from a real fault without reading rank logs."""
    from deeplearning_cfn_tpu.launch.launcher import classify_attempt
    from deeplearning_cfn_tpu.runtime.watchdog import HANG_EXIT_CODE

    assert classify_attempt([0, 0]) == "ok"
    assert classify_attempt([0, 1]) == "crash"
    assert classify_attempt([HANG_EXIT_CODE, 0]) == "hang"
    # A hang wins over a concurrent crash: the watchdog exit is the
    # diagnosis, the other rank's death is collateral.
    assert classify_attempt([HANG_EXIT_CODE, 1]) == "hang"

    launcher = JobLauncher(transport=LocalTransport(), max_restarts=0,
                           tail_rank0=False)
    result = launcher.run(
        _spec(1), _py(f"import sys; sys.exit({HANG_EXIT_CODE})"),
        str(tmp_path / "logs"))
    assert not result.success
    assert result.attempt_outcomes == ["hang"]


def test_launcher_exports_attempt_index(tmp_path):
    """Workers see DLCFN_ATTEMPT per attempt (the chaos harness keys its
    fault arming off it): here the worker hangs-exits only on attempt 0,
    so outcomes read hang → ok."""
    from deeplearning_cfn_tpu.runtime.watchdog import HANG_EXIT_CODE

    code = (
        "import os, sys\n"
        "sys.exit(%d if os.environ['DLCFN_ATTEMPT'] == '0' else 0)\n"
        % HANG_EXIT_CODE
    )
    launcher = JobLauncher(transport=LocalTransport(), max_restarts=2,
                           tail_rank0=False)
    result = launcher.run(_spec(1), _py(code), str(tmp_path / "logs"))
    assert result.success
    assert result.restarts == 1
    assert result.attempt_outcomes == ["hang", "ok"]


# -- SshTransport through a fake-ssh PATH shim ------------------------------
#
# The production multi-host path (the `mpirun -hostfile` replacement,
# SURVEY.md §4.2) fans out over real `ssh`. These tests intercept the `ssh`
# binary with a PATH script that records its exact argv (so the option/host/
# remote-command contract is asserted) and then execs the remote command
# locally — driving the full launcher watch/restart machinery through
# SshTransport's quoting, env-export, and cwd plumbing.

_SSH_SHIM = r"""#!/usr/bin/env bash
# Fake ssh for tests: record argv, then run the remote command locally.
rec=$(mktemp "$FAKE_SSH_DIR/call_XXXXXX.argv")
printf '%s\n' "$@" > "$rec"
# Skip ssh options (value-taking ones consume two args) to find the host.
while [ $# -gt 0 ]; do
  case "$1" in
    -o|-p|-i|-l|-F|-E) shift 2 ;;
    -*) shift ;;
    *) break ;;
  esac
done
host="$1"; shift
exec bash -c "$*"
"""


@pytest.fixture
def fake_ssh(tmp_path, monkeypatch):
    """Install the fake `ssh` at the front of PATH; returns the directory
    where every invocation's argv is recorded."""
    bindir = tmp_path / "fake_bin"
    bindir.mkdir()
    calls = tmp_path / "ssh_calls"
    calls.mkdir()
    shim = bindir / "ssh"
    shim.write_text(_SSH_SHIM)
    shim.chmod(0o755)
    monkeypatch.setenv("PATH", f"{bindir}{os.pathsep}{os.environ['PATH']}")
    monkeypatch.setenv("FAKE_SSH_DIR", str(calls))
    return calls


def _recorded_calls(calls_dir):
    return [p.read_text().splitlines()
            for p in sorted(calls_dir.iterdir())]


def test_ssh_transport_argv_env_quoting_and_cwd(fake_ssh, tmp_path):
    """One fan-out over SshTransport: the ssh argv carries -tt/BatchMode/
    host, the per-rank env contract arrives ONLY via the exported remote
    command string (hostile values survive the quoting), and cwd is applied
    remotely."""
    workdir = tmp_path / "remote_cwd"
    workdir.mkdir()
    tricky = "sp ace 'quo\"te' $HOME ;&|*"
    code = (
        "import os; print('rank', os.environ['%s'], "
        "'tricky', repr(os.environ['TRICKY']), "
        "'cwd', os.getcwd())" % ENV_PROCESS_ID
    )
    launcher = JobLauncher(transport=SshTransport(), tail_rank0=False)
    spec = ClusterSpec(hosts=["worker-a", "worker-b"])
    result = launcher.run(spec, _py(code), str(tmp_path / "logs"),
                          extra_env={"TRICKY": tricky},
                          cwd=str(workdir))
    assert result.success

    for rank, host in enumerate(spec.hosts):
        log = (tmp_path / "logs" / f"attempt0-host{rank}.log").read_text()
        assert f"rank {rank}" in log
        assert f"tricky {tricky!r}" in log  # quoting survived verbatim
        assert f"cwd {workdir}" in log

    argvs = _recorded_calls(fake_ssh)
    assert len(argvs) == 2
    hosts_seen = set()
    for argv in argvs:
        assert argv[0] == "-tt"  # remote-teardown-on-kill contract flag
        assert "BatchMode=yes" in argv
        assert "StrictHostKeyChecking=accept-new" in argv
        host, remote = argv[-2], argv[-1]
        hosts_seen.add(host)
        assert remote.startswith("export ")  # env rides the command string
        assert "export TRICKY=" in remote
        assert f"cd {workdir}" in remote
    assert hosts_seen == {"worker-a", "worker-b"}


def test_ssh_transport_extra_ssh_args_precede_host(fake_ssh, tmp_path):
    launcher = JobLauncher(
        transport=SshTransport(ssh_args=["-p", "2222"]), tail_rank0=False)
    result = launcher.run(ClusterSpec(hosts=["worker-x"]),
                          _py("print('ok')"), str(tmp_path / "logs"))
    assert result.success
    (argv,) = _recorded_calls(fake_ssh)
    p_at = argv.index("-p")
    assert argv[p_at + 1] == "2222"
    assert p_at < argv.index("worker-x")  # options before the host operand


def test_ssh_transport_failure_kills_remote_survivors(fake_ssh, tmp_path):
    """Host death over SSH: the launcher must tear down the surviving
    remote workers (locally: the whole ssh process group) instead of
    waiting out their sleep."""
    import time
    code = (
        "import os, sys, time\n"
        f"rank = int(os.environ['{ENV_PROCESS_ID}'])\n"
        "sys.exit(1) if rank == 1 else time.sleep(3600)\n"
    )
    launcher = JobLauncher(transport=SshTransport(), max_restarts=0,
                           tail_rank0=False)
    t0 = time.time()
    result = launcher.run(ClusterSpec(hosts=["worker-a", "worker-b"]),
                          _py(code), str(tmp_path / "logs"))
    assert not result.success
    assert time.time() - t0 < 30
    assert result.exit_codes[1] == 1


def test_ssh_transport_fault_injection_restart_resumes(fake_ssh, tmp_path):
    """The full kill-a-host → restart → resume cycle through SshTransport:
    rank 1 crashes once, the relaunched attempt observes the prior marker
    and succeeds — the auto-restart contract on the production transport."""
    marker = tmp_path / "attempt0_rank"
    code = (
        "import os, sys\n"
        f"rank = int(os.environ['{ENV_PROCESS_ID}'])\n"
        f"marker = r'{marker}' + str(rank)\n"
        "if not os.path.exists(marker):\n"
        "    open(marker, 'w').write('x')\n"
        "    sys.exit(7) if rank == 1 else sys.exit(0)\n"
        "print('RESUMED rank', rank)\n"
    )
    failures = []
    launcher = JobLauncher(transport=SshTransport(), max_restarts=2,
                           tail_rank0=False)
    result = launcher.run(
        ClusterSpec(hosts=["worker-a", "worker-b"]), _py(code),
        str(tmp_path / "logs"),
        on_failure=lambda idx, host: failures.append(idx),
    )
    assert result.success
    assert result.restarts == 1
    assert failures == [1]
    log = (tmp_path / "logs" / "attempt1-host1.log").read_text()
    assert "RESUMED rank 1" in log
    # Two attempts x two hosts = four ssh fan-outs recorded.
    assert len(_recorded_calls(fake_ssh)) == 4


# -- non-blocking start/poll (JobHandle) -------------------------------------


def test_start_returns_pollable_handle(tmp_path):
    """start() never blocks: the handle reports per-host liveness while
    the job runs and the classified outcome once every host exits."""
    import time as _time

    gate = tmp_path / "gate"
    code = (
        "import os, time\n"
        f"while not os.path.exists(r'{gate}'): time.sleep(0.01)\n"
    )
    launcher = JobLauncher(transport=LocalTransport(), tail_rank0=False)
    handle = launcher.start(_spec(2), _py(code), str(tmp_path / "logs"))
    try:
        assert handle.poll() == [None, None]
        assert handle.alive() == [True, True]
        assert not handle.done()
        assert handle.outcome() is None
        # The launcher-level poll() mirrors the current handle.
        assert launcher.poll() == [None, None]
        gate.write_text("go")
        deadline = _time.time() + 30
        while not handle.done() and _time.time() < deadline:
            _time.sleep(0.02)
        assert handle.poll() == [0, 0]
        assert handle.outcome() == "ok"
    finally:
        handle.terminate()


def test_handle_wait_and_crash_outcome(tmp_path):
    launcher = JobLauncher(transport=LocalTransport(), tail_rank0=False)
    handle = launcher.start(
        _spec(2),
        _py("import os, sys; "
            "sys.exit(5 if os.environ['%s'] == '1' else 0)"
            % ENV_PROCESS_ID),
        str(tmp_path / "logs"))
    codes = handle.wait(timeout_s=30)
    handle.close()
    assert codes == [0, 5]
    assert handle.outcome() == "crash"
    # Per-host logs landed in the usual attemptN-hostI layout.
    assert sorted(p.name for p in (tmp_path / "logs").iterdir()) == [
        "attempt0-host0.log", "attempt0-host1.log"]


def test_handle_terminate_kills_running_hosts(tmp_path):
    launcher = JobLauncher(transport=LocalTransport(), tail_rank0=False)
    handle = launcher.start(
        _spec(1), _py("import time; time.sleep(600)"),
        str(tmp_path / "logs"))
    assert handle.alive() == [True]
    handle.terminate()
    codes = handle.wait(timeout_s=30)
    assert codes[0] is not None and codes[0] != 0


def test_poll_without_start_returns_none(tmp_path):
    assert JobLauncher(transport=LocalTransport()).poll() is None
