"""Fault injection + retrying store I/O: the storage half of the recovery
contract. Covers the deterministic FaultPlan/FaultInjectionStore layer, the
RetryingStore policy (transient absorbed, permanent fails fast, budgets
bounded), and the two-phase commit protocol's behavior against torn-commit
states on both PosixStore and MemoryObjectStore."""

import os
import time

import jax.numpy as jnp
import pytest

from deeplearning_cfn_tpu.ckpt import (
    CheckpointManager,
    MemoryObjectStore,
    PosixStore,
    RetryPolicy,
    RetryingStore,
    is_retriable,
    latest_checkpoint,
    open_store,
    restore_checkpoint,
    retry_policy_from_config,
    rollback_checkpoints,
    save_checkpoint,
    sweep_uncommitted,
)
from deeplearning_cfn_tpu.config import CheckpointConfig
from deeplearning_cfn_tpu.runtime.faults import (
    FaultInjectionStore,
    FaultPlan,
    FaultSpec,
    InjectedFatalError,
    InjectedTransientError,
    StoreCrashed,
)


def _tree():
    return {"params": {"w": jnp.arange(6.0).reshape(2, 3)},
            "step": jnp.asarray(5, jnp.int32)}


def _store_factories(tmp_path):
    return {
        "posix": lambda: PosixStore(str(tmp_path / "posix")),
        "memory": MemoryObjectStore,
    }


# -- FaultPlan / FaultInjectionStore ----------------------------------------


def test_fault_spec_first_n_is_per_site():
    plan = FaultPlan([FaultSpec(op="put", kind="transient", first_n=2)])
    store = FaultInjectionStore(MemoryObjectStore(), plan)
    for key in ("a", "b"):  # each key is its own site: 2 failures each
        for _ in range(2):
            with pytest.raises(InjectedTransientError):
                store.put_bytes(key, b"x")
        store.put_bytes(key, b"x")  # third call succeeds
    assert store.injected == {"transient": 4}
    assert store.inner.get_bytes("a") == b"x"


def test_fault_spec_at_calls_and_key_substring():
    plan = FaultPlan([FaultSpec(op="put", key="DONE", kind="transient",
                                at_calls=(1,))])
    store = FaultInjectionStore(MemoryObjectStore(), plan)
    store.put_bytes("step_1/DONE_p0", b"1")  # site call 0: passes
    with pytest.raises(InjectedTransientError):
        store.put_bytes("step_1/DONE_p0", b"1")  # site call 1: fires
    store.put_bytes("step_1/COMMIT", b"1")  # key mismatch: never fires


def test_probability_faults_are_seeded_deterministic():
    def run(seed):
        plan = FaultPlan([FaultSpec(op="put", kind="transient",
                                    probability=0.5)], seed=seed)
        store = FaultInjectionStore(MemoryObjectStore(), plan)
        fired = []
        for i in range(20):
            try:
                store.put_bytes(f"k{i}", b"x")
                fired.append(False)
            except InjectedTransientError:
                fired.append(True)
        return fired

    assert run(7) == run(7)  # same seed → identical schedule
    assert any(run(7)) and not all(run(7))


def test_latency_fault_calls_sleep_then_delegates():
    slept = []
    plan = FaultPlan([FaultSpec(op="get", kind="latency", latency_s=1.5)])
    store = FaultInjectionStore(MemoryObjectStore(), plan,
                                sleep=slept.append)
    store.inner.put_bytes("k", b"v")
    assert store.get_bytes("k") == b"v"
    assert slept == [1.5]


def test_crash_fault_kills_the_store_permanently():
    plan = FaultPlan.crash_before_commit()
    store = FaultInjectionStore(MemoryObjectStore(), plan)
    store.put_bytes("step_1/DONE_p0", b"1")
    with pytest.raises(StoreCrashed):
        store.put_bytes("step_1/COMMIT", b"1")
    assert store.crashed
    # A dead process never writes again — even non-matching ops raise.
    with pytest.raises(StoreCrashed):
        store.get_bytes("step_1/DONE_p0")
    assert not store.inner.exists("step_1/COMMIT")


def test_unknown_fault_kind_rejected():
    with pytest.raises(ValueError):
        FaultSpec(kind="gremlins")


# -- retry classification / policy ------------------------------------------


def test_retriable_classification():
    assert is_retriable(OSError("io"))
    assert is_retriable(ConnectionResetError())
    assert is_retriable(TimeoutError())
    assert is_retriable(InjectedTransientError("injected"))
    # Fatal beats the OSError base class: FileNotFoundError IS an OSError.
    assert not is_retriable(FileNotFoundError("gone"))
    assert not is_retriable(ValueError("corrupt"))
    assert not is_retriable(InjectedFatalError("injected"))
    assert not is_retriable(KeyError("leaf"))

    class Gcs503(Exception):
        code = 503

    class Gcs404(Exception):
        code = 404

    class ServiceUnavailable(Exception):  # name-based fallback
        pass

    assert is_retriable(Gcs503())
    assert not is_retriable(Gcs404())
    assert is_retriable(ServiceUnavailable())


def test_backoff_is_deterministic_capped_and_jittered():
    p = RetryPolicy(backoff_s=1.0, backoff_max_s=4.0, jitter=0.1)
    assert p.backoff(2, salt=9) == p.backoff(2, salt=9)
    for i in range(8):
        base = min(2.0 ** i, 4.0)
        assert base <= p.backoff(i, salt=3) <= base * 1.1
    # Different salts decorrelate concurrent retriers.
    assert p.backoff(0, salt=1) != p.backoff(0, salt=2)


def test_retry_policy_from_config():
    assert retry_policy_from_config(CheckpointConfig(retry_attempts=1)) is None
    p = retry_policy_from_config(CheckpointConfig(retry_attempts=5,
                                                  retry_backoff_s=0.25))
    assert p.max_attempts == 5 and p.backoff_s == 0.25


# -- RetryingStore ----------------------------------------------------------


def test_retrying_store_absorbs_transients_with_visible_counts():
    faulty = FaultInjectionStore(
        MemoryObjectStore(), FaultPlan.transient_puts(failures_per_put=2))
    slept = []
    store = RetryingStore(faulty, RetryPolicy(max_attempts=3),
                          sleep=slept.append)
    store.put_bytes("a", b"1")
    store.put_bytes("b", b"2")
    assert store.inner.inner.get_bytes("a") == b"1"
    assert store.retries_total == 4 and len(slept) == 4
    assert store.retries_by_op == {"put_bytes": 4}
    assert store.gave_up == 0


def test_retrying_store_fails_fast_on_permanent_errors():
    faulty = FaultInjectionStore(MemoryObjectStore(),
                                 FaultPlan.permanent_puts())
    slept = []
    store = RetryingStore(faulty, RetryPolicy(max_attempts=5),
                          sleep=slept.append)
    with pytest.raises(InjectedFatalError):
        store.put_bytes("a", b"1")
    assert slept == []  # no backoff burned on a classified-fatal error
    assert store.retries_total == 0
    assert faulty.op_counts["put_bytes"] == 1  # exactly one attempt


def test_retrying_store_exhausts_budget_then_reraises():
    faulty = FaultInjectionStore(
        MemoryObjectStore(),
        FaultPlan([FaultSpec(op="put", kind="transient")]))  # always fails
    store = RetryingStore(faulty, RetryPolicy(max_attempts=3),
                          sleep=lambda d: None)
    with pytest.raises(InjectedTransientError):
        store.put_bytes("a", b"1")
    assert faulty.op_counts["put_bytes"] == 3
    assert store.retries_total == 2 and store.gave_up == 1


def test_retrying_store_per_op_deadline():
    clock = {"t": 0.0}
    faulty = FaultInjectionStore(
        MemoryObjectStore(),
        FaultPlan([FaultSpec(op="put", kind="transient")]))
    store = RetryingStore(
        faulty, RetryPolicy(max_attempts=100, op_timeout_s=5.0),
        sleep=lambda d: clock.__setitem__("t", clock["t"] + d),
        clock=lambda: clock["t"])
    with pytest.raises(InjectedTransientError):
        store.put_bytes("a", b"1")
    # Bounded by the deadline, far below the 100-attempt budget.
    assert faulty.op_counts["put_bytes"] < 20


def test_open_store_wraps_once():
    inner = MemoryObjectStore()
    wrapped = open_store(inner, retry=RetryPolicy())
    assert isinstance(wrapped, RetryingStore)
    again = open_store(wrapped, retry=RetryPolicy())
    assert again is wrapped  # no double wrap
    assert open_store(inner) is inner  # no policy → untouched


def test_checkpoint_commits_through_flaky_store_with_retry_metrics():
    """The acceptance scenario: 2 transient failures per put, a full
    two-phase checkpoint save commits anyway, retry counts visible."""
    faulty = FaultInjectionStore(
        MemoryObjectStore(), FaultPlan.transient_puts(failures_per_put=2))
    manager = CheckpointManager(
        faulty, every_steps=1, async_write=False,
        retry=RetryPolicy(max_attempts=3, backoff_s=0.0, jitter=0.0))
    state = _tree()
    manager.save(5, state)
    assert latest_checkpoint(manager.store) == 5
    assert manager.store_retries() >= 2  # ≥2 per faulted put, surfaced
    restored, step = manager.restore_or_none(state)
    assert step == 5


def test_checkpoint_fails_fast_through_permanently_broken_store():
    faulty = FaultInjectionStore(MemoryObjectStore(),
                                 FaultPlan.permanent_puts())
    manager = CheckpointManager(faulty, every_steps=1, async_write=False,
                                retry=RetryPolicy(max_attempts=5))
    t0 = time.monotonic()
    with pytest.raises(InjectedFatalError):
        manager.save(5, _tree())
    assert time.monotonic() - t0 < 2.0  # no retry backoff was burned
    assert manager.store_retries() == 0


# -- torn-commit states (both store kinds) ----------------------------------


@pytest.mark.parametrize("kind", ["posix", "memory"])
def test_crash_before_done_leaves_invisible_sweepable_state(tmp_path, kind):
    inner = _store_factories(tmp_path)[kind]()
    faulty = FaultInjectionStore(inner, FaultPlan.crash_before_done())
    with pytest.raises(StoreCrashed):
        save_checkpoint(faulty, 3, _tree(), async_write=False)
    # Shards + manifests are durable, no DONE, no COMMIT.
    assert any("shards_p0" in k for k in inner.list("step_00000003/"))
    assert not inner.exists("step_00000003/DONE_p0")
    assert latest_checkpoint(inner) is None
    with pytest.raises(FileNotFoundError):
        restore_checkpoint(inner, _tree())
    assert sweep_uncommitted(inner) == [3]
    assert inner.list("step_00000003/") == []


@pytest.mark.parametrize("kind", ["posix", "memory"])
def test_crash_before_commit_rolls_back_cleanly(tmp_path, kind):
    inner = _store_factories(tmp_path)[kind]()
    save_checkpoint(inner, 1, _tree(), async_write=False)  # healthy commit
    faulty = FaultInjectionStore(inner, FaultPlan.crash_before_commit())
    with pytest.raises(StoreCrashed):
        save_checkpoint(faulty, 2, _tree(), async_write=False)
    # Step 2 has every per-process object and marker except COMMIT.
    assert inner.exists("step_00000002/DONE_p0")
    assert not inner.exists("step_00000002/COMMIT")
    assert latest_checkpoint(inner) == 1
    # rollback to the committed step deletes the torn one too.
    assert rollback_checkpoints(inner, 1) == [2]
    assert inner.list("step_00000002/") == []
    restored, step = restore_checkpoint(inner, _tree())
    assert step == 1


@pytest.mark.parametrize("kind", ["posix", "memory"])
def test_partial_ranks_torn_state(tmp_path, kind):
    """Emulate a 2-process save where rank 1 died before its DONE marker:
    the checkpoint must stay invisible and sweepable."""
    inner = _store_factories(tmp_path)[kind]()
    key = "step_00000004"
    faulty = FaultInjectionStore(
        inner, FaultPlan([FaultSpec(op="put", key="DONE_p1", kind="crash")]))
    # Rank 0's full contribution...
    faulty.put_bytes(f"{key}/manifest.json", b"{}")
    faulty.put_bytes(f"{key}/manifest_p0.json", b"{}")
    faulty.put_bytes(f"{key}/DONE_p0", b"4")
    # ...rank 1 dies on its marker; COMMIT is never reached.
    faulty.put_bytes(f"{key}/manifest_p1.json", b"{}")
    with pytest.raises(StoreCrashed):
        faulty.put_bytes(f"{key}/DONE_p1", b"4")
    assert latest_checkpoint(inner) is None
    assert sweep_uncommitted(inner) == [4]
    assert inner.list(f"{key}/") == []


# -- PosixStore tmp hygiene (satellite) --------------------------------------


def test_posix_tmp_names_are_writer_unique(tmp_path):
    store = PosixStore(str(tmp_path))
    suffix = store._tmp_suffix()
    assert str(os.getpid()) in suffix and suffix.endswith(".tmp")
    store.put_bytes("step_1/COMMIT", b"1")
    store.put_npz("step_1/shards.npz", {"w": jnp.zeros(2)})
    # No tmp debris after successful puts, and list() never shows any.
    leftovers = [n for _, _, fs in os.walk(tmp_path) for n in fs
                 if ".tmp" in n]
    assert leftovers == []


def test_posix_stale_tmp_swept_on_open_fresh_kept(tmp_path):
    root = tmp_path / "ckpt"
    sub = root / "step_00000001"
    sub.mkdir(parents=True)
    stale = sub / "shards_p0.npz.123.456.tmp"
    stale.write_bytes(b"half-written")
    old = time.time() - 7200
    os.utime(stale, (old, old))
    fresh = sub / "COMMIT.789.1011.tmp"  # young: maybe a live writer
    fresh.write_bytes(b"inflight")

    store = PosixStore(str(root))
    assert not stale.exists()
    assert fresh.exists()
    # tmp files are invisible to the protocol either way.
    assert all(not store._is_tmp(k.rsplit("/", 1)[-1])
               for k in store.list(""))
