"""Tests for the BERT and NMT workloads: data-source invariants, forward
shapes, and short-horizon convergence through the full trainer (the
loss-curve acceptance SURVEY.md §8 prescribes for the text workloads)."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning_cfn_tpu.config import (
    DataConfig,
    ExperimentConfig,
    MeshConfig,
    ModelConfig,
    OptimizerConfig,
    ScheduleConfig,
    TrainConfig,
)
from deeplearning_cfn_tpu.data.text import make_mlm_source, make_nmt_source
from deeplearning_cfn_tpu.metrics import read_metrics
from deeplearning_cfn_tpu.models import build_model
from deeplearning_cfn_tpu.train.run import run_experiment


# -- data sources -----------------------------------------------------------


def test_mlm_source_invariants():
    src = make_mlm_source(64, seq_len=32, vocab_size=128, seed=0)
    a = src.arrays
    assert a["input_ids"].shape == (64, 32)
    assert a["mlm_positions"].shape[1] == int(32 * 0.2)
    # CLS/SEP framing; positions point inside the sequence body.
    assert (a["input_ids"][:, 0] == 1).all()
    assert (a["input_ids"][:, -1] == 2).all()
    live = a["mlm_weights"] > 0
    assert live.any()
    pos = a["mlm_positions"][live]
    assert pos.min() >= 1 and pos.max() <= 30
    # Original ids recorded for masked slots; most inputs actually masked.
    assert (a["mlm_ids"][live] >= 3).all()
    masked_frac = (np.take_along_axis(a["input_ids"], a["mlm_positions"],
                                      1)[live] == 3).mean()
    assert 0.6 < masked_frac < 0.95
    # Deterministic.
    src2 = make_mlm_source(64, seq_len=32, vocab_size=128, seed=0)
    np.testing.assert_array_equal(a["input_ids"], src2.arrays["input_ids"])


def test_nmt_source_invariants():
    src = make_nmt_source(32, seq_len=24, vocab_size=64, seed=0)
    a = src.arrays
    # BOS-shifted decoder input: tgt_in[t+1] == tgt_out[t] on real positions.
    assert (a["tgt_in_ids"][:, 0] == 1).all()
    lengths = a["tgt_mask"].sum(1).astype(int)
    for i in range(8):
        n = lengths[i] - 1  # last real position is EOS
        np.testing.assert_array_equal(a["tgt_in_ids"][i, 1:n + 1],
                                      a["tgt_out_ids"][i, :n])
        # Target is the documented transform: reverse + offset 7.
        s = a["src_ids"][i, :n] - 3
        t = a["tgt_out_ids"][i, :n] - 3
        np.testing.assert_array_equal(t, (s[::-1] + 7) % 61)


# -- forward shapes ---------------------------------------------------------


def test_bert_tiny_forward_shapes():
    model = build_model("bert_tiny", num_classes=2, dtype=jnp.float32)
    s, p = 32, 6
    ids = jnp.zeros((2, s), jnp.int32)
    variables = model.init(jax.random.PRNGKey(0), ids,
                           jnp.ones((2, s), jnp.int32), ids,
                           jnp.zeros((2, p), jnp.int32), train=False)
    out = model.apply(variables, ids, jnp.ones((2, s), jnp.int32), ids,
                      jnp.zeros((2, p), jnp.int32), train=False)
    assert out["mlm_logits"].shape == (2, p, 512)
    assert out["nsp_logits"].shape == (2, 2)


def test_nmt_tiny_forward_shapes():
    model = build_model("transformer_nmt_tiny", num_classes=0,
                        dtype=jnp.float32)
    s = 16
    ids = jnp.zeros((2, s), jnp.int32)
    variables = model.init(jax.random.PRNGKey(0), ids,
                           jnp.ones((2, s), jnp.int32), ids, train=False)
    logits = model.apply(variables, ids, jnp.ones((2, s), jnp.int32), ids,
                         train=False)
    assert logits.shape == (2, s, 128)


def test_nmt_causality():
    """Future target tokens must not influence earlier logits."""
    model = build_model("transformer_nmt_tiny", num_classes=0,
                        dtype=jnp.float32)
    s = 12
    rng = np.random.RandomState(0)
    src = jnp.asarray(rng.randint(3, 100, (1, s)), jnp.int32)
    tgt = jnp.asarray(rng.randint(3, 100, (1, s)), jnp.int32)
    variables = model.init(jax.random.PRNGKey(0), src,
                           jnp.ones((1, s), jnp.int32), tgt, train=False)
    base = model.apply(variables, src, jnp.ones((1, s), jnp.int32), tgt,
                       train=False)
    tgt2 = tgt.at[0, -1].set((tgt[0, -1] + 13) % 100)
    pert = model.apply(variables, src, jnp.ones((1, s), jnp.int32), tgt2,
                       train=False)
    np.testing.assert_allclose(np.asarray(base)[:, :-1],
                               np.asarray(pert)[:, :-1], atol=1e-5)
    assert not np.allclose(np.asarray(base)[:, -1], np.asarray(pert)[:, -1])


def test_bert_dropout_trains():
    """dropout_rate > 0 must work through the task rng plumbing."""
    import optax

    from deeplearning_cfn_tpu.train.task import build_task

    cfg = ExperimentConfig(
        model=ModelConfig(name="bert_tiny", num_classes=2,
                          kwargs=dict(vocab_size=64, hidden_size=32,
                                      num_layers=1, num_heads=2,
                                      mlp_dim=64, max_len=32,
                                      dropout_rate=0.1)),
        data=DataConfig(name="wikipedia_mlm", seq_len=32, vocab_size=64),
        train=TrainConfig(dtype="float32"),
    )
    task = build_task(cfg)
    variables = task.init(jax.random.PRNGKey(0))
    src = make_mlm_source(8, 32, 64, seed=0)
    batch = {k: jnp.asarray(v) for k, v in src.arrays.items()}
    loss, aux = task.loss_fn(variables["params"], {}, batch,
                             jax.random.PRNGKey(1), True)
    assert jnp.isfinite(loss)


# -- tensor parallelism -----------------------------------------------------


def test_tensor_parallel_matches_data_parallel(devices):
    """The Megatron rules (models/transformer.TRANSFORMER_PARAM_RULES) must
    be numerically invisible: bert_tiny trained 20 steps on a
    (model=2, data=4) mesh reproduces the pure-DP (data=8) run — same loss
    trajectory, same final params — while the QKV/MLP kernels are actually
    sharded over 'model' (not silently replicated)."""
    import re

    import jax.tree_util as jtu

    from deeplearning_cfn_tpu.data import build_pipeline
    from deeplearning_cfn_tpu.parallel.mesh import build_mesh
    from deeplearning_cfn_tpu.train import create_train_state
    from deeplearning_cfn_tpu.train.optim import (
        build_optimizer,
        build_schedule,
    )
    from deeplearning_cfn_tpu.train.task import build_task
    from deeplearning_cfn_tpu.train.trainer import Trainer
    from deeplearning_cfn_tpu.utils.trees import path_str

    def run(mesh_cfg, steps=20):
        cfg = ExperimentConfig(
            model=ModelConfig(name="bert_tiny", num_classes=2,
                              kwargs=dict(vocab_size=64, hidden_size=32,
                                          num_layers=2, num_heads=2,
                                          mlp_dim=64, max_len=32)),
            data=DataConfig(name="wikipedia_mlm", seq_len=32, vocab_size=64,
                            num_train_examples=256, prefetch=0),
            train=TrainConfig(global_batch=32, dtype="float32"),
            optimizer=OptimizerConfig(name="adamw", weight_decay=0.01),
            schedule=ScheduleConfig(name="constant", base_lr=3e-3,
                                    warmup_steps=0),
            mesh=mesh_cfg,
        )
        mesh = build_mesh(cfg.mesh)
        task = build_task(cfg)
        sched = build_schedule(cfg.schedule, 100, 32, 8)
        tx = build_optimizer(cfg.optimizer, sched)
        state = create_train_state(jax.random.PRNGKey(0), task.init, tx,
                                   mesh, param_rules=task.param_rules)
        trainer = Trainer(cfg, task.loss_fn, tx, mesh=mesh, donate=False)
        pipe = build_pipeline(cfg.data, 32, 2, seed=0, train=True)
        it = pipe.epochs()
        losses = []
        for _ in range(steps):
            batch = trainer.device_batch(next(it))
            state, m = trainer.train_step(state, batch,
                                          jax.random.PRNGKey(1))
            losses.append(float(m["loss"]))
        return state, losses

    state_tp, loss_tp = run(MeshConfig(data=4, model=2))
    state_dp, loss_dp = run(MeshConfig(data=8))

    # The TP kernels must actually be sharded (a wrong regex would leave
    # them replicated and this test would prove nothing).
    sharded_names = []
    for path, leaf in jtu.tree_leaves_with_path(state_tp.params):
        name = path_str(path)
        if re.search(r"(query|key|value|mlp_in|mlp_out|attn_out)/kernel",
                     name):
            shard_shape = leaf.addressable_shards[0].data.shape
            assert shard_shape != leaf.shape, (
                f"{name} not sharded: shard {shard_shape} == global")
            sharded_names.append(name)
    assert len(sharded_names) >= 12, sharded_names  # 6 kernels × 2 layers

    np.testing.assert_allclose(loss_tp, loss_dp, rtol=2e-4, atol=2e-4)

    flat_tp = {path_str(p): np.asarray(v) for p, v in
               jtu.tree_leaves_with_path(state_tp.params)}
    flat_dp = {path_str(p): np.asarray(v) for p, v in
               jtu.tree_leaves_with_path(state_dp.params)}
    assert flat_tp.keys() == flat_dp.keys()
    for name in flat_tp:
        if re.search(r"key/bias", name):
            # Gauge direction: softmax(q·(k+b)) == softmax(q·k) — a key
            # bias shifts every logit in a row equally, so its true
            # gradient is zero and AdamW normalizes pure float-rounding
            # noise into O(lr) drift that legitimately differs per mesh.
            continue
        np.testing.assert_allclose(
            flat_tp[name], flat_dp[name], rtol=2e-3, atol=2e-4,
            err_msg=f"param {name} diverged between TP and DP")


# -- end-to-end convergence -------------------------------------------------


def _run(cfg, tmp, steps):
    cfg.workdir = os.path.join(tmp, "work")
    cfg.train.steps = steps
    cfg.train.log_every_steps = 5
    cfg.data.prefetch = 0
    cfg.checkpoint.async_write = False
    return run_experiment(cfg)


def test_bert_trains_end_to_end(tmp_workdir):
    cfg = ExperimentConfig(
        model=ModelConfig(name="bert_tiny", num_classes=2,
                          kwargs=dict(vocab_size=64, hidden_size=32,
                                      num_layers=2, num_heads=2,
                                      mlp_dim=64, max_len=32)),
        data=DataConfig(name="wikipedia_mlm", seq_len=32, vocab_size=64,
                        num_train_examples=256, num_eval_examples=64),
        train=TrainConfig(global_batch=32, dtype="float32", eval_batch=32),
        optimizer=OptimizerConfig(name="adamw", weight_decay=0.01,
                                  grad_clip_norm=1.0),
        schedule=ScheduleConfig(name="constant", base_lr=3e-3,
                                warmup_steps=5),
        mesh=MeshConfig(data=-1),
    )
    _run(cfg, tmp_workdir, steps=40)
    records = [r for r in read_metrics(
        os.path.join(cfg.workdir, "bert_tiny", "metrics.jsonl"))
        if "loss" in r]
    first, last = records[0], records[-1]
    # MLM over a 64-token vocab starts near ln(61)≈4.1; the Markov structure
    # must pull it well below unigram entropy within 40 steps.
    assert last["loss"] < first["loss"] - 0.5, (first, last)


def test_nmt_trains_end_to_end(tmp_workdir):
    cfg = ExperimentConfig(
        model=ModelConfig(name="transformer_nmt_tiny",
                          kwargs=dict(vocab_size=32, hidden_size=32,
                                      num_layers=1, num_heads=2,
                                      mlp_dim=64, max_len=16)),
        data=DataConfig(name="wmt_en_de", seq_len=16, vocab_size=32,
                        num_train_examples=256, num_eval_examples=64),
        train=TrainConfig(global_batch=32, dtype="float32", eval_batch=32,
                          label_smoothing=0.0),
        optimizer=OptimizerConfig(name="adamw", b1=0.9, b2=0.98),
        schedule=ScheduleConfig(name="constant", base_lr=3e-3,
                                warmup_steps=5),
        mesh=MeshConfig(data=-1),
    )
    final = _run(cfg, tmp_workdir, steps=300)
    records = [r for r in read_metrics(
        os.path.join(cfg.workdir, "transformer_nmt_tiny", "metrics.jsonl"))
        if "loss" in r]
    first, last = records[0], records[-1]
    assert last["loss"] < first["loss"] - 0.5, (first, last)
    # Acceptance metric: the final eval beam-decodes the eval set and scores
    # corpus BLEU (the Sockeye workload's yardstick). The target transform
    # (reverse + offset) is deterministic, so a model that learned anything
    # scores well above a random decoder's ~0 BLEU — and the number must
    # land in metrics.jsonl as final_eval_bleu.
    assert "bleu" in final, final
    assert 0.0 <= final["bleu"] <= 1.0
    assert final["bleu"] > 0.05, final["bleu"]
    logged = [r for r in read_metrics(
        os.path.join(cfg.workdir, "transformer_nmt_tiny", "metrics.jsonl"))
        if "final_eval_bleu" in r]
    assert logged and logged[-1]["final_eval_bleu"] == \
        pytest.approx(final["bleu"])
