"""Crash-recovery: resume must be step-exact and torn state must be swept.

Two tiers:
- the default-suite tests run the recovery logic in process (interrupt a
  real training run, plant a torn checkpoint dir, resume, compare per-step
  metrics float-exactly) — tier-1-safe, no subprocesses;
- the ``chaos``-marked test drives the full launcher harness
  (launch/chaos.py): a worker subprocess SIGKILLs itself mid-run at the
  planned step, JobLauncher restarts it, and the resumed trajectory must
  match an uninterrupted control run exactly.
"""

import json
import os

import pytest

from deeplearning_cfn_tpu.config import ExperimentConfig, apply_overrides
from deeplearning_cfn_tpu.presets import get_preset

# Cheap deterministic CPU config (the test_trainer tiny-cfg recipe).
TINY_OVERRIDES = [
    "train.global_batch=32",
    "train.log_every_steps=1",
    "train.eval_every_steps=1000000",
    "data.num_train_examples=256",
    "data.num_eval_examples=64",
    "train.eval_batch=32",
    "data.prefetch=0",
    "schedule.name=constant",
    "schedule.base_lr=0.1",
    "schedule.warmup_epochs=0",
]


def _cfg(workdir, steps=6, ckpt_every=2) -> ExperimentConfig:
    cfg = get_preset("cifar10_resnet20")
    apply_overrides(cfg, [
        f"workdir={workdir}",
        f"train.steps={steps}",
        f"checkpoint.every_steps={ckpt_every}",
        "checkpoint.async_write=false",
        *TINY_OVERRIDES,
    ])
    return cfg


def _step_losses(workdir):
    """step → [loss, ...] from every per-step record in metrics.jsonl."""
    path = os.path.join(workdir, "cifar10_resnet20", "metrics.jsonl")
    out = {}
    with open(path) as fh:
        for line in fh:
            rec = json.loads(line)
            if "step" in rec and "loss" in rec:
                out.setdefault(int(rec["step"]), []).append(rec["loss"])
    return out


def test_inprocess_interrupt_resume_is_step_exact(tmp_workdir, devices):
    """Interrupt a run at a committed step, plant a torn checkpoint dir,
    resume: the orphan is swept, the trajectory matches an uninterrupted
    control run float-exactly, and retry counts appear in metrics."""
    from deeplearning_cfn_tpu.train.run import run_experiment

    base_dir = os.path.join(tmp_workdir, "base")
    chaos_dir = os.path.join(tmp_workdir, "chaos")

    run_experiment(_cfg(base_dir))  # uninterrupted control

    # "Crash" at step 4: the interrupted run stops there with step 4
    # committed (the cadence save), like a worker dying right after a
    # checkpoint boundary.
    run_experiment(_cfg(chaos_dir), max_steps=4)

    # Plant the torn debris a real mid-save death leaves behind: a step
    # dir with shard objects but no COMMIT.
    ckpt_dir = os.path.join(chaos_dir, "cifar10_resnet20", "ckpt")
    torn = os.path.join(ckpt_dir, "step_00000099")
    os.makedirs(torn)
    with open(os.path.join(torn, "shards_p0.npz"), "wb") as fh:
        fh.write(b"half-written garbage")

    run_experiment(_cfg(chaos_dir))  # restart: sweep, resume 4 → 6

    assert not os.path.exists(torn), "orphaned uncommitted dir not swept"
    base = _step_losses(base_dir)
    chaos = _step_losses(chaos_dir)
    assert set(chaos) == set(base)
    for step, losses in sorted(chaos.items()):
        for loss in losses:  # overlap steps recorded by both attempts
            assert loss == base[step][0], \
                f"step {step}: resumed loss {loss!r} != control " \
                f"{base[step][0]!r}"
    # Step 4 was committed before the interrupt; 6 by the resumed run.
    from deeplearning_cfn_tpu.ckpt import committed_steps

    assert 6 in committed_steps(ckpt_dir)
    # The retry counter rides the final metrics record (0 here — no faults).
    path = os.path.join(chaos_dir, "cifar10_resnet20", "metrics.jsonl")
    finals = [json.loads(line) for line in open(path)
              if "ckpt_store_retries" in line]
    assert finals and all(r["ckpt_store_retries"] == 0 for r in finals)


def test_chaos_hook_arming_contract(monkeypatch):
    """The SIGKILL hook only arms on attempt 0 with the env set — a
    restarted attempt must run to completion."""
    from deeplearning_cfn_tpu.runtime.faults import (
        ATTEMPT_ENV,
        CHAOS_KILL_ENV,
        chaos_kill_hook_from_env,
    )

    monkeypatch.delenv(CHAOS_KILL_ENV, raising=False)
    monkeypatch.delenv(ATTEMPT_ENV, raising=False)
    assert chaos_kill_hook_from_env() is None  # unarmed by default

    monkeypatch.setenv(CHAOS_KILL_ENV, "4")
    assert chaos_kill_hook_from_env() is not None  # armed, attempt 0

    monkeypatch.setenv(ATTEMPT_ENV, "1")
    assert chaos_kill_hook_from_env() is None  # restarted attempt: never


@pytest.mark.chaos
@pytest.mark.slow
def test_sigkill_restart_resumes_step_exact(tmp_path):
    """The full contract, end to end: a real worker subprocess SIGKILLs
    itself right after the step-4 checkpoint dispatch, JobLauncher
    restarts it, and the resumed run is step-exact vs. the control."""
    from deeplearning_cfn_tpu.launch.chaos import run_crash_recovery

    report = run_crash_recovery(
        str(tmp_path),
        preset="cifar10_resnet20",
        overrides=TINY_OVERRIDES,
        total_steps=8,
        kill_at_step=4,
        ckpt_every=2,
        max_restarts=2,
    )
    assert report.baseline_result.success, report.baseline_result
    assert report.chaos_result.success, report.chaos_result
    assert report.chaos_result.restarts >= 1  # the kill really happened
    assert report.chaos_result.attempt_outcomes[0] == "crash"
    assert report.chaos_result.attempt_outcomes[-1] == "ok"
    assert report.resumed_from is not None  # restart announced its resume
    assert report.parity_ok, report.mismatches
    assert report.uncommitted_after == []  # torn dirs swept on resume
    assert report.ok
