"""Tests for the Mask R-CNN workload: detection-op numerics (IoU, box
codec, static NMS, ROI-align), data-source invariants, and short-horizon
end-to-end training (SURVEY.md §8 hard-part #1 made testable on CPU)."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning_cfn_tpu.config import (
    CheckpointConfig,
    DataConfig,
    ExperimentConfig,
    MeshConfig,
    ModelConfig,
    OptimizerConfig,
    ScheduleConfig,
    TrainConfig,
)
from deeplearning_cfn_tpu.data.detection import make_detection_source
from deeplearning_cfn_tpu.metrics import read_metrics
from deeplearning_cfn_tpu.ops.detection import (
    decode_boxes,
    encode_boxes,
    generate_anchors,
    iou_matrix,
    multilevel_roi_align,
    nms_static,
    roi_align,
)
from deeplearning_cfn_tpu.train.run import run_experiment


# -- box math ---------------------------------------------------------------


def test_iou_matrix_basics():
    a = jnp.asarray([[0, 0, 10, 10], [0, 0, 5, 5]], jnp.float32)
    b = jnp.asarray([[0, 0, 10, 10], [5, 5, 10, 10], [20, 20, 30, 30]],
                    jnp.float32)
    iou = np.asarray(iou_matrix(a, b))
    np.testing.assert_allclose(iou[0], [1.0, 0.25, 0.0], atol=1e-6)
    np.testing.assert_allclose(iou[1, 0], 0.25, atol=1e-6)
    assert iou[1, 1] == 0.0  # touching corners, no overlap


def test_box_codec_roundtrip():
    rng = np.random.RandomState(0)
    anchors = jnp.asarray(
        np.stack([rng.uniform(0, 50, 32), rng.uniform(0, 50, 32),
                  rng.uniform(60, 100, 32), rng.uniform(60, 100, 32)], 1),
        jnp.float32)
    boxes = anchors + jnp.asarray(rng.uniform(-5, 5, (32, 4)), jnp.float32)
    deltas = encode_boxes(boxes, anchors)
    back = decode_boxes(deltas, anchors)
    np.testing.assert_allclose(np.asarray(back), np.asarray(boxes),
                               atol=1e-3, rtol=1e-4)


def test_nms_static_suppresses():
    boxes = jnp.asarray([
        [0, 0, 10, 10],      # score .9 — kept
        [1, 1, 11, 11],      # heavy overlap with 0 — suppressed
        [50, 50, 60, 60],    # disjoint — kept
        [0, 0, 10.5, 10.5],  # overlap with 0 — suppressed
    ], jnp.float32)
    scores = jnp.asarray([0.9, 0.8, 0.7, 0.6])
    idx, keep = nms_static(boxes, scores, iou_threshold=0.5, max_outputs=4)
    kept = set(np.asarray(idx)[np.asarray(keep)].tolist())
    assert kept == {0, 2}


def test_nms_static_padding_sentinels_and_valid_mask():
    """Padded candidates must never appear in the output — whether marked
    by the finite -1e30 sentinel convention or by an explicit validity
    mask (regression: exact -inf was the only recognized padding)."""
    boxes = jnp.asarray([
        [0, 0, 10, 10],
        [50, 50, 60, 60],
        [0, 0, 0, 0],      # padding
        [0, 0, 0, 0],      # padding
    ], jnp.float32)
    scores = jnp.asarray([0.9, 0.8, -1e30, -1e30])
    idx, keep = nms_static(boxes, scores, iou_threshold=0.5, max_outputs=4)
    kept = set(np.asarray(idx)[np.asarray(keep)].tolist())
    assert kept == {0, 1}

    # Explicit validity mask overrides scores: box 1 is masked out even
    # though its score is high.
    valid = jnp.asarray([True, False, False, False])
    idx, keep = nms_static(boxes, scores, iou_threshold=0.5, max_outputs=4,
                           valid=valid)
    kept = set(np.asarray(idx)[np.asarray(keep)].tolist())
    assert kept == {0}


def test_roi_align_identity_crop():
    """Aligning a box that covers exactly the feature map reproduces it
    (up to bilinear smoothing at the bin centers)."""
    feat = jnp.arange(16, dtype=jnp.float32).reshape(4, 4, 1)
    out = roi_align(feat, jnp.asarray([[0.0, 0.0, 4.0, 4.0]]), out_size=4,
                    sampling_ratio=1)
    np.testing.assert_allclose(np.asarray(out)[0, :, :, 0],
                               np.asarray(feat)[:, :, 0], atol=1e-5)


def test_roi_align_constant_region():
    feat = jnp.ones((8, 8, 3)) * 5.0
    out = roi_align(feat, jnp.asarray([[2.0, 2.0, 6.0, 6.0]]), out_size=2)
    np.testing.assert_allclose(np.asarray(out), 5.0, atol=1e-5)


def test_multilevel_roi_align_routes_by_size():
    feats = {2: jnp.ones((32, 32, 1)) * 2.0, 3: jnp.ones((16, 16, 1)) * 3.0}
    strides = {2: 4, 3: 8}
    # Small box → level 2, huge box → clipped to level 3.
    boxes = jnp.asarray([[0, 0, 8, 8], [0, 0, 120, 120]], jnp.float32)
    out = multilevel_roi_align(feats, boxes, out_size=2, strides=strides,
                               canonical_level=2, canonical_size=16.0)
    assert np.allclose(np.asarray(out)[0], 2.0)
    assert np.allclose(np.asarray(out)[1], 3.0)


def test_multilevel_roi_align_matches_dense_reference():
    """The flat-pyramid single-gather formulation must equal the dense
    reference (align every box on every level, one-hot select by target
    level) bit-for-bit in f32 — including boxes hanging off the map edge
    and degenerate boxes."""
    import jax

    rng = np.random.RandomState(0)
    strides = {2: 4, 3: 8, 4: 16}
    feats = {
        lvl: jnp.asarray(rng.randn(64 // (2 ** i), 64 // (2 ** i), 8),
                         jnp.float32)
        for i, lvl in enumerate(sorted(strides))
    }
    boxes = jnp.asarray(np.concatenate([
        rng.uniform(0, 256, (12, 4)),
        [[(-8.0), -8.0, 20.0, 20.0],     # off the top-left edge
         [200.0, 200.0, 400.0, 400.0],   # off the bottom-right edge
         [17.0, 17.0, 17.0, 17.0]],      # degenerate (zero-area)
    ]), jnp.float32)
    boxes = jnp.stack([
        jnp.minimum(boxes[:, 0], boxes[:, 2]),
        jnp.minimum(boxes[:, 1], boxes[:, 3]),
        jnp.maximum(boxes[:, 0], boxes[:, 2]),
        jnp.maximum(boxes[:, 1], boxes[:, 3]),
    ], axis=1)

    def dense_reference(feats, boxes, out_size):
        levels = sorted(feats)
        from deeplearning_cfn_tpu.ops.detection import EPS, box_area
        sqrt_area = jnp.sqrt(jnp.maximum(box_area(boxes), EPS))
        target = jnp.floor(4 + jnp.log2(sqrt_area / 224.0 + EPS))
        target = jnp.clip(target, levels[0], levels[-1]).astype(jnp.int32)
        outs = [roi_align(feats[lvl], boxes, out_size,
                          spatial_scale=1.0 / strides[lvl])
                for lvl in levels]
        stacked = jnp.stack(outs, axis=0)
        sel = (target[None, :] == jnp.asarray(
            levels, jnp.int32)[:, None]).astype(stacked.dtype)
        return jnp.einsum("lnhwc,ln->nhwc", stacked, sel)

    for out_size in (7, 14):
        got = multilevel_roi_align(feats, boxes, out_size, strides)
        want = dense_reference(feats, boxes, out_size)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-5)

    # Gradients must match too (the align sits inside the train step).
    def loss_new(f):
        return jnp.sum(multilevel_roi_align(f, boxes, 7, strides) ** 2)

    def loss_ref(f):
        return jnp.sum(dense_reference(f, boxes, 7) ** 2)

    g_new = jax.grad(loss_new)(feats)
    g_ref = jax.grad(loss_ref)(feats)
    for lvl in feats:
        np.testing.assert_allclose(np.asarray(g_new[lvl]),
                                   np.asarray(g_ref[lvl]),
                                   rtol=1e-4, atol=1e-4)


def test_generate_anchors_layout():
    anchors = generate_anchors((32, 32), strides=[8, 16], scales=[16, 32])
    # 4*4*3 + 2*2*3 anchors, all finite, centers inside the image.
    assert anchors.shape == (60, 4)
    assert np.isfinite(np.asarray(anchors)).all()
    centers = np.asarray((anchors[:, :2] + anchors[:, 2:]) / 2)
    assert (centers >= 0).all() and (centers <= 32).all()


# -- data -------------------------------------------------------------------


def test_detection_source_invariants():
    src = make_detection_source(16, image_size=64, num_classes=7,
                                max_boxes=8, seed=0)
    a = src.arrays
    assert a["image"].shape == (16, 64, 64, 3)
    assert a["boxes"].shape == (16, 8, 4)
    assert a["masks"].shape == (16, 8, 28, 28)
    valid = a["labels"] > 0
    assert valid.any() and (a["labels"] < 7).all()
    b = a["boxes"][valid]
    assert (b[:, 2] > b[:, 0]).all() and (b[:, 3] > b[:, 1]).all()
    assert (b >= 0).all() and (b <= 64).all()
    # Masks nontrivial for valid objects, empty for padding.
    assert a["masks"][valid].max() == 1.0
    assert a["masks"][~valid].sum() == 0.0


# -- end-to-end -------------------------------------------------------------


def _tiny_cfg():
    return ExperimentConfig(
        model=ModelConfig(
            name="maskrcnn_resnet50", num_classes=7,
            kwargs=dict(image_size=64, pre_nms_topk=64, post_nms_topk=16,
                        num_mask_rois=4, anchor_scale=4.0)),
        data=DataConfig(name="coco", image_size=64, num_train_examples=32,
                        num_eval_examples=4, max_boxes=4),
        train=TrainConfig(global_batch=4, dtype="float32", eval_batch=4,
                          log_every_steps=2),
        optimizer=OptimizerConfig(name="momentum", momentum=0.9,
                                  weight_decay=1e-4, grad_clip_norm=10.0),
        schedule=ScheduleConfig(name="constant", base_lr=0.01,
                                warmup_steps=5),
        # data=4 × model=2 fills the 8 fake devices at global_batch 4
        # (the idle 'model' axis just replicates — params have no TP rules).
        mesh=MeshConfig(data=4, model=2),
        checkpoint=CheckpointConfig(async_write=False),
    )


def test_detect_one_postprocessing():
    """Per-class NMS + global top-K on hand-crafted head outputs: duplicate
    boxes of the same class are suppressed, same-position boxes of distinct
    classes both survive, sub-threshold and invalid proposals drop out."""
    from deeplearning_cfn_tpu.train.detection_task import DetectionTask

    task = DetectionTask(_tiny_cfg())
    p, c = 6, 3  # 6 proposals, background + 2 foreground classes
    props = jnp.asarray(np.array([
        [0, 0, 10, 10],
        [0, 1, 10, 11],    # heavy overlap with 0 → NMS victim (class 1)
        [30, 30, 40, 40],  # distinct location, class 2
        [0, 0, 10, 10],    # same place as 0 but class 2 → must survive
        [50, 50, 60, 60],  # below score threshold
        [70, 70, 80, 80],  # invalid proposal
    ], np.float32))
    valid = jnp.asarray([True, True, True, True, True, False])
    probs = np.full((p, c), 0.01, np.float32)
    probs[0, 1] = 0.9
    probs[1, 1] = 0.8
    probs[2, 2] = 0.7
    probs[3, 2] = 0.6
    probs[4, 1] = 0.04  # below the 0.05 floor
    probs[5, 1] = 0.9   # invalid → ignored
    deltas = jnp.zeros((p, c, 4), np.float32)
    boxes, scores, classes = task._detect_one(
        jnp.asarray(probs), deltas, props, valid,
        topk=4, score_thr=0.05, nms_iou=0.5)
    boxes, scores, classes = map(np.asarray, (boxes, scores, classes))
    kept = [(int(c_), float(s)) for c_, s in zip(classes, scores) if c_ > 0]
    assert kept == [(1, pytest.approx(0.9)), (2, pytest.approx(0.7)),
                    (2, pytest.approx(0.6))], kept
    # Survivor boxes: 0 (cls 1), 2 and 3 (cls 2) — deltas were zero so the
    # output boxes equal the proposals.
    np.testing.assert_allclose(boxes[0], props[0])
    np.testing.assert_allclose(boxes[1], props[2])
    np.testing.assert_allclose(boxes[2], props[3])


def test_deconv_to_upsample_conversion():
    """Pin the pre-round-4 checkpoint conversion: a 2×2/stride-2
    SAME ConvTranspose and Dense(converted weights) + MaskHead's
    depth-to-space must agree to f32 rounding. flax ConvTranspose puts
    kernel tap (a, b) at output offset (1-a, 1-b), so the conversion must
    flip both spatial axes — the unflipped formula swaps every 2×2 block
    (ADVICE r4)."""
    import flax.linen as nn

    from deeplearning_cfn_tpu.models.maskrcnn import convert_deconv_to_upsample

    c, c_out = 5, 7
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(2, 3, 3, c)), jnp.float32)
    w_convt = jnp.asarray(rng.normal(size=(2, 2, c, c_out)), jnp.float32)

    deconv = nn.ConvTranspose(c_out, (2, 2), strides=(2, 2), padding="SAME",
                              use_bias=False)
    ref = deconv.apply({"params": {"kernel": w_convt}}, x)

    w_dense = convert_deconv_to_upsample(np.asarray(w_convt))
    y = x @ jnp.asarray(w_dense)  # [B, s, s, 4*Cout]
    b, s = x.shape[0], x.shape[1]
    y = y.reshape(b, s, s, 2, 2, c_out)
    y = y.transpose(0, 1, 3, 2, 4, 5).reshape(b, 2 * s, 2 * s, c_out)
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref), atol=1e-6)

    # The unflipped formula must NOT match — guards against the doc bug
    # silently coming back.
    w_bad = np.asarray(w_convt).transpose(2, 0, 1, 3).reshape(c, 4 * c_out)
    y_bad = x @ jnp.asarray(w_bad)
    y_bad = y_bad.reshape(b, s, s, 2, 2, c_out)
    y_bad = y_bad.transpose(0, 1, 3, 2, 4, 5).reshape(b, 2 * s, 2 * s, c_out)
    assert np.abs(np.asarray(y_bad) - np.asarray(ref)).max() > 0.1

    with pytest.raises(ValueError):
        convert_deconv_to_upsample(np.zeros((3, 3, c, c_out)))


def test_maskrcnn_trains_end_to_end(tmp_workdir):
    """Full pipeline: synthetic COCO → RPN/RoI/mask losses all finite and
    the total improving over a short horizon."""
    cfg = _tiny_cfg()
    cfg.workdir = os.path.join(tmp_workdir, "work")
    cfg.train.steps = 6  # CPU detection steps are ~40s; keep the horizon short
    cfg.train.eval_every_steps = 1000  # skip mid-run eval (compile cost)
    cfg.data.prefetch = 0
    cfg.eval.detect_topk = 8  # keep the inference compile small on CPU
    final = run_experiment(cfg)
    records = [r for r in read_metrics(
        os.path.join(cfg.workdir, "maskrcnn_resnet50", "metrics.jsonl"))
        if "loss" in r]
    assert records, "no train metrics logged"
    for r in records:
        for key in ["rpn_cls_loss", "rpn_box_loss", "roi_cls_loss",
                    "roi_box_loss", "mask_loss", "proposal_recall"]:
            assert key in r and np.isfinite(r[key]), (key, r)
    first, last = records[0], records[-1]
    assert last["loss"] < first["loss"], (first["loss"], last["loss"])
    # Acceptance metric: the final eval runs the static-shape inference path
    # (per-class NMS → fixed-K boxes + masks) and scores COCO-style mAP —
    # 6 steps won't produce detections that match GT, but the full pipeline
    # must execute and land final_eval_map / final_eval_mask_map in
    # metrics.jsonl (BASELINE.md tracking row 5).
    for key in ("map", "map50", "mask_map"):
        assert key in final and np.isfinite(final[key]) \
            and 0.0 <= final[key] <= 1.0, (key, final)
    logged = [r for r in read_metrics(
        os.path.join(cfg.workdir, "maskrcnn_resnet50", "metrics.jsonl"))
        if "final_eval_map" in r]
    assert logged and "final_eval_mask_map" in logged[-1]


def test_maskrcnn_spatial_shard_compiles(devices, tmp_workdir):
    """The data+spatial shard (SURVEY.md §3.2's one beyond-DP strategy):
    mesh data=4 × spatial=2, image H sharded — one step must compile and
    produce finite losses."""
    cfg = _tiny_cfg()
    cfg.workdir = os.path.join(tmp_workdir, "work")
    cfg.mesh = MeshConfig(data=4, spatial=2)
    cfg.train.steps = 2
    cfg.train.eval_every_steps = 1000
    cfg.data.prefetch = 0
    # Keep final eval ON: the inference path (predict_fn's NMS/top-k/
    # roi-align) must also compile and run with the image spatially sharded
    # — a production multichip run hits it at the very end of training.
    cfg.eval.detect_topk = 4
    final = run_experiment(cfg)
    assert np.isfinite(final["loss"])
    assert "map" in final and np.isfinite(final["map"])
