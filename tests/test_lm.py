"""Decoder-only causal LM: data source, causality, KV-cache decode
consistency, and short-horizon convergence through the full trainer."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning_cfn_tpu.config import (
    DataConfig,
    ExperimentConfig,
    MeshConfig,
    ModelConfig,
    OptimizerConfig,
    ScheduleConfig,
    TrainConfig,
)
from deeplearning_cfn_tpu.data.text import make_lm_source
from deeplearning_cfn_tpu.metrics import read_metrics
from deeplearning_cfn_tpu.models import build_model
from deeplearning_cfn_tpu.train.run import run_experiment


def test_lm_source_invariants():
    src = make_lm_source(64, seq_len=16, vocab_size=32, seed=0)
    batch = src.gather(np.arange(64))
    assert batch["tokens"].shape == (64, 17)  # seq_len + 1
    assert batch["loss_mask"].shape == (64, 16)
    assert batch["tokens"].min() >= 0 and batch["tokens"].max() < 32
    # Deterministic across constructions.
    again = make_lm_source(64, seq_len=16, vocab_size=32, seed=0)
    np.testing.assert_array_equal(batch["tokens"],
                                  again.gather(np.arange(64))["tokens"])


def test_prepare_lm_text_roundtrip(tmp_path):
    """prepare-text → real-data lm_text pipeline → a training step: the
    fully-offline byte-level path."""
    from deeplearning_cfn_tpu.data.text import build_text_source, \
        prepare_lm_text

    src = tmp_path / "corpus.txt"
    src.write_bytes(bytes(range(256)) * 40)  # 10240 bytes
    out = str(tmp_path / "tok")
    info = prepare_lm_text(str(src), out, seq_len=31)
    assert info["train_examples"] + info["eval_examples"] == 10240 // 32
    assert info["vocab_size"] == 260

    cfg = DataConfig(name="lm_text", seq_len=31, vocab_size=260,
                     data_dir=out, synthetic=False)
    train_src = build_text_source(cfg, train=True)
    batch = train_src.gather(np.arange(4))
    assert batch["tokens"].shape == (4, 32)
    # Byte values shifted past the 4 reserved specials.
    assert batch["tokens"].min() >= 4 and batch["tokens"].max() < 260

    with pytest.raises(ValueError, match="at least"):
        tiny = tmp_path / "tiny.txt"
        tiny.write_bytes(b"x" * 10)
        prepare_lm_text(str(tiny), out, seq_len=31)
    with pytest.raises(ValueError, match="eval_fraction"):
        prepare_lm_text(str(src), out, seq_len=31, eval_fraction=1.5)


def test_lm_is_causal():
    """Changing a future token must not change past logits."""
    model = build_model("gpt_tiny", 0, jnp.float32, vocab_size=32,
                        max_len=16, dropout_rate=0.0)
    ids = jnp.arange(12, dtype=jnp.int32)[None, :] % 32
    variables = model.init(jax.random.PRNGKey(0), ids, train=False)
    base = model.apply(variables, ids, train=False)
    bumped = ids.at[0, 8].set((ids[0, 8] + 7) % 32)
    out = model.apply(variables, bumped, train=False)
    np.testing.assert_allclose(np.asarray(base[0, :8]),
                               np.asarray(out[0, :8]), atol=1e-5)
    assert not np.allclose(np.asarray(base[0, 8:]), np.asarray(out[0, 8:]))


@pytest.mark.parametrize("num_experts", [0, 2])
def test_lm_kv_cache_decode_matches_full_forward(num_experts):
    """Incremental decode through the KV cache must reproduce the full
    forward's logits position by position — the correctness claim behind
    cached generation (including through MoE FFN layers, whose routing
    is per-token and so decode-invariant)."""
    # capacity_factor high enough that the full-sequence pass drops no
    # tokens — per-position decode never drops (1 token vs capacity>=1),
    # so drop-free routing is a precondition for exact parity.
    model = build_model("gpt_tiny", 0, jnp.float32, vocab_size=32,
                        max_len=16, dropout_rate=0.0,
                        num_experts=num_experts, moe_capacity_factor=4.0)
    T = 10
    ids = (jax.random.randint(jax.random.PRNGKey(1), (1, T), 0, 32)
           .astype(jnp.int32))
    variables = model.init(jax.random.PRNGKey(0), ids, train=False)
    full = model.apply(variables, ids, train=False)
    if num_experts:
        full = full[0]  # (logits, moe_aux) when MoE layers exist

    # Create the cache via a decode_step init (the documented contract).
    from deeplearning_cfn_tpu.models.lm import TransformerCausalLm

    dec_vars = model.init(jax.random.PRNGKey(0), ids[:, :1], 0,
                          method=TransformerCausalLm.decode_step)
    cache = dec_vars["cache"]
    step_logits = []
    for t in range(T):
        logits, mutated = model.apply(
            {"params": variables["params"], "cache": cache},
            ids[:, t:t + 1], t, method=TransformerCausalLm.decode_step,
            mutable=["cache"])
        cache = mutated["cache"]
        step_logits.append(np.asarray(logits[0, 0]))
    np.testing.assert_allclose(np.stack(step_logits), np.asarray(full[0]),
                               atol=1e-4)


def test_lm_trains_end_to_end(tmp_workdir):
    cfg = ExperimentConfig(
        model=ModelConfig(name="gpt_tiny",
                          kwargs=dict(vocab_size=64, max_len=32,
                                      dropout_rate=0.0)),
        data=DataConfig(name="lm_text", seq_len=32, vocab_size=64,
                        num_train_examples=256, num_eval_examples=64),
        train=TrainConfig(global_batch=32, dtype="float32", eval_batch=32),
        optimizer=OptimizerConfig(name="adamw", weight_decay=0.01,
                                  grad_clip_norm=1.0),
        schedule=ScheduleConfig(name="constant", base_lr=3e-3,
                                warmup_steps=5),
        mesh=MeshConfig(data=-1),
    )
    cfg.workdir = os.path.join(tmp_workdir, "work")
    cfg.train.steps = 40
    cfg.train.log_every_steps = 5
    cfg.data.prefetch = 0
    cfg.checkpoint.async_write = False
    final = run_experiment(cfg)
    records = [r for r in read_metrics(
        os.path.join(cfg.workdir, "gpt_tiny", "metrics.jsonl"))
        if "loss" in r]
    first, last = records[0], records[-1]
    # Next-token CE over a 64-vocab Markov chain starts near ln(60)≈4.1;
    # the fixed transitions must pull it well below within 40 steps.
    assert last["loss"] < first["loss"] - 0.5, (first, last)
    assert "perplexity" in final and "token_accuracy" in final
    assert final["perplexity"] < np.exp(first["loss"])
    # Derived post-aggregation, so it must be exactly exp of the exact
    # token-weighted eval CE (not a mean of per-batch exps; without MoE
    # layers ce_loss == loss).
    assert final["perplexity"] == pytest.approx(np.exp(final["ce_loss"]))
    assert final["ce_loss"] == pytest.approx(final["loss"])


def test_lm_generate_greedy_matches_manual_rollout():
    """lm_generate(temperature=0) must equal the brute-force rollout that
    re-runs the FULL forward and takes argmax of the last position each
    step — the cached scan is an optimization, not a different sampler."""
    from deeplearning_cfn_tpu.models.decoding import lm_generate

    model = build_model("gpt_tiny", 0, jnp.float32, vocab_size=32,
                        max_len=16, dropout_rate=0.0)
    prompt = jnp.array([[5, 9, 3], [1, 2, 7]], jnp.int32)
    variables = model.init(jax.random.PRNGKey(0), prompt, train=False)

    out = lm_generate(model, variables, prompt, max_new_tokens=6)
    assert out.shape == (2, 9)
    np.testing.assert_array_equal(np.asarray(out[:, :3]),
                                  np.asarray(prompt))

    manual = prompt
    for _ in range(6):
        logits = model.apply(variables, manual, train=False)
        nxt = jnp.argmax(logits[:, -1, :], -1).astype(jnp.int32)
        manual = jnp.concatenate([manual, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(manual))


def test_lm_generate_recompute_fallback_for_gpt_long():
    """Models without decode_step (gpt_long) take the recompute drive
    mode — greedy output must still equal the brute-force rollout."""
    from deeplearning_cfn_tpu.models.decoding import lm_generate

    model = build_model("gpt_long", 0, jnp.float32, vocab_size=32,
                        hidden_size=32, num_layers=1, num_heads=2,
                        mlp_dim=64, max_len=16)
    assert not hasattr(type(model), "decode_step")
    prompt = jnp.array([[3, 7, 1]], jnp.int32)
    variables = model.init(jax.random.PRNGKey(0), prompt, train=False)
    out = lm_generate(model, variables, prompt, max_new_tokens=5)
    manual = prompt
    for _ in range(5):
        logits = model.apply(variables, manual, train=False)
        nxt = jnp.argmax(logits[:, -1, :], -1).astype(jnp.int32)
        manual = jnp.concatenate([manual, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(manual))


def test_lm_generate_sampling_is_seeded_and_in_vocab():
    from deeplearning_cfn_tpu.models.decoding import lm_generate

    model = build_model("gpt_tiny", 0, jnp.float32, vocab_size=32,
                        max_len=16, dropout_rate=0.0)
    prompt = jnp.array([[4, 8]], jnp.int32)
    variables = model.init(jax.random.PRNGKey(0), prompt, train=False)
    a = lm_generate(model, variables, prompt, 5, temperature=1.0,
                    top_k=8, rng=jax.random.PRNGKey(7))
    b = lm_generate(model, variables, prompt, 5, temperature=1.0,
                    top_k=8, rng=jax.random.PRNGKey(7))
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert int(a.min()) >= 0 and int(a.max()) < 32
    with pytest.raises(ValueError, match="rng"):
        lm_generate(model, variables, prompt, 5, temperature=1.0)
    # Generating past max_len would silently clamp the cache writes —
    # it must refuse instead.
    with pytest.raises(ValueError, match="max_len"):
        lm_generate(model, variables, prompt, 15)


def test_generate_verb_end_to_end(tmp_path, capsys):
    """train (byte-level corpus) → `generate` verb continues the learned
    text from a prompt — the LM family's full user loop via the CLI."""
    from deeplearning_cfn_tpu.cli.main import main
    from deeplearning_cfn_tpu.data.text import prepare_lm_text

    src = tmp_path / "c.txt"
    src.write_bytes(b"abcdefgh" * 600)
    tok = str(tmp_path / "tok")
    prepare_lm_text(str(src), tok, seq_len=15)
    common = [
        "--preset", "gpt_small_lm", "--accelerator", "cpu",
        f"workdir={tmp_path}", "model.name=gpt_tiny",
        'model.kwargs={"vocab_size": 260, "max_len": 16}',
        "data.name=lm_text", f"data.data_dir={tok}",
        "data.synthetic=false", "data.vocab_size=260", "data.seq_len=15",
        "train.global_batch=16", "train.dtype=float32",
        "train.eval_batch=16", "schedule.name=constant",
        "schedule.base_lr=3e-3", "schedule.warmup_steps=5",
        "train.shard_opt_state=false", "checkpoint.async_write=false",
        "data.prefetch=0",
    ]
    assert main(["train", *common, "train.steps=40",
                 "train.log_every_steps=10"]) == 0
    capsys.readouterr()
    assert main(["generate", *common, "--prompt", "abcd",
                 "--max-new-tokens", "8"]) == 0
    out = capsys.readouterr().out
    # The corpus is the 8-cycle "abcdefgh": a model at ~100% token
    # accuracy must continue it exactly.
    assert "abcdefghabcd" in out, out
    # --vocab plumbing (load → prompt encode → output decode, no crash):
    # a zero-merge BPE over MLM_SPECIALS maps bytes to the same ids as the
    # byte tokenizer EXCEPT it appends an end-of-word space token (36) the
    # space-free corpus never saw — so the continuation after it is
    # arbitrary and only the decoded prompt echo is asserted. Continuation
    # QUALITY is covered by the byte-path assertion above.
    from deeplearning_cfn_tpu.data.bpe import Bpe, MLM_SPECIALS

    vocab_path = str(tmp_path / "vocab.json")
    Bpe([], MLM_SPECIALS).save(vocab_path)
    capsys.readouterr()
    assert main(["generate", *common, "--prompt", "abcd",
                 "--vocab", vocab_path, "--max-new-tokens", "4"]) == 0
    out = capsys.readouterr().out
    assert "abcd" in out, out
    # A prompt that BPE-encodes to nothing (pure whitespace) exits 1.
    assert main(["generate", *common, "--prompt", "   ",
                 "--vocab", vocab_path]) == 1
    # Misuse exits 1 with an error, not a traceback: wrong preset/workdir
    # (no checkpoint), and an explicit step that was never committed.
    assert main(["generate", "--preset", "cifar10_resnet20",
                 "--accelerator", "cpu", f"workdir={tmp_path}",
                 "--prompt", "x"]) == 1
    assert main(["generate", *common, "--prompt", "abcd",
                 "--step", "999"]) == 1


def test_lm_moe_trains_and_shards_experts(tmp_workdir, devices):
    """gpt with num_experts: MoE aux losses thread into the objective and
    expert weights shard over the 'expert' mesh axis (the GShard
    convention the bert_moe flagship uses)."""
    from deeplearning_cfn_tpu.parallel import build_mesh
    from deeplearning_cfn_tpu.train import create_train_state
    from deeplearning_cfn_tpu.train.optim import build_optimizer, build_schedule
    from deeplearning_cfn_tpu.train.task import build_task
    from deeplearning_cfn_tpu.train.trainer import Trainer

    cfg = ExperimentConfig(
        model=ModelConfig(name="gpt_tiny",
                          kwargs=dict(vocab_size=64, max_len=32,
                                      num_experts=2)),
        data=DataConfig(name="lm_text", seq_len=32, vocab_size=64,
                        num_train_examples=64, num_eval_examples=32,
                        prefetch=0),
        train=TrainConfig(global_batch=16, dtype="float32"),
        mesh=MeshConfig(data=4, expert=2),
    )
    mesh = build_mesh(cfg.mesh)
    task = build_task(cfg)
    sched = build_schedule(cfg.schedule, 4, 16, 4)
    tx = build_optimizer(cfg.optimizer, sched)
    state = create_train_state(jax.random.PRNGKey(0), task.init, tx, mesh,
                               param_rules=task.param_rules)
    n_expert_sharded = 0
    for leaf in jax.tree_util.tree_leaves(state.params):
        spec = getattr(leaf.sharding, "spec", None)
        if spec and any(ax == "expert" for ax in spec if ax):
            n_expert_sharded += 1
    assert n_expert_sharded >= 2, n_expert_sharded  # 1 MoE layer's w1/w2

    from deeplearning_cfn_tpu.data import build_pipeline

    trainer = Trainer(cfg, task.loss_fn, tx, mesh=mesh)
    pipe = build_pipeline(cfg.data, 16, 0, seed=0, train=True)
    batch = trainer.device_batch(next(iter(pipe.one_epoch(0))))
    state, metrics = trainer.train_step(state, batch, jax.random.PRNGKey(1))
    assert np.isfinite(float(metrics["loss"]))
    assert "moe_load_balance" in metrics


def test_lm_tensor_parallel_shards_kernels(tmp_workdir, devices):
    """gpt models carry the transformer PARAM_RULES: on a data×model mesh
    the block kernels must actually shard over 'model'."""
    from deeplearning_cfn_tpu.parallel import build_mesh
    from deeplearning_cfn_tpu.train import create_train_state
    from deeplearning_cfn_tpu.train.optim import build_optimizer, build_schedule
    from deeplearning_cfn_tpu.train.task import build_task

    cfg = ExperimentConfig(
        model=ModelConfig(name="gpt_tiny",
                          kwargs=dict(vocab_size=64, max_len=32)),
        data=DataConfig(name="lm_text", seq_len=32, vocab_size=64,
                        num_train_examples=64, num_eval_examples=32),
        train=TrainConfig(global_batch=16, dtype="float32"),
        mesh=MeshConfig(data=4, model=2),
    )
    mesh = build_mesh(cfg.mesh)
    task = build_task(cfg)
    sched = build_schedule(cfg.schedule, 4, 16, 4)
    tx = build_optimizer(cfg.optimizer, sched)
    state = create_train_state(jax.random.PRNGKey(0), task.init, tx, mesh,
                               param_rules=task.param_rules)
    n_sharded = 0
    for leaf in jax.tree_util.tree_leaves(state.params):
        spec = getattr(leaf.sharding, "spec", None)
        if spec and any(ax == "model" for ax in spec if ax):
            n_sharded += 1
    assert n_sharded >= 6, n_sharded  # 2 layers × (qkv/out/mlp kernels)
