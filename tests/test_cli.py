"""Tests for L5: the `stack create → train` CLI flow — the reference's
user-facing contract (SURVEY.md §4.1/§4.4), exercised end-to-end against the
dry-run control plane."""

import json
import os
import sys

import pytest

from deeplearning_cfn_tpu.cli import main


def test_presets_lists_all_five(capsys):
    assert main(["presets"]) == 0
    out = capsys.readouterr().out
    for name in ["cifar10_resnet20", "imagenet_resnet50",
                 "bert_base_wikipedia", "maskrcnn_coco",
                 "transformer_nmt_wmt"]:
        assert name in out


def test_config_shows_resolved_preset_with_overrides(capsys):
    assert main(["config", "--preset", "cifar10_resnet20",
                 "train.global_batch=64"]) == 0
    cfg = json.loads(capsys.readouterr().out)
    assert cfg["model"]["name"] == "resnet20"
    assert cfg["train"]["global_batch"] == 64


def test_doctor_passes_on_cpu(capsys, devices):
    assert main(["doctor"]) == 0
    out = capsys.readouterr().out
    for check in ["presets: ok", "native-loader: ok", "backend-init: ok",
                  "device-exec: ok", "mesh: ok", "all checks passed"]:
        assert check in out, out


def test_doctor_skip_backend(capsys):
    assert main(["doctor", "--skip-backend"]) == 0
    out = capsys.readouterr().out
    assert "backend: ok — skipped on request" in out
    assert "device-exec" not in out


def test_config_rejects_unknown_override():
    with pytest.raises(KeyError):
        main(["config", "--preset", "cifar10_resnet20", "train.nope=1"])


def test_bench_collectives_verb(capsys, devices):
    """`bench --collectives` is the nccl-tests role: one JSON record per
    collective with a positive bus bandwidth over the 8-device mesh."""
    assert main(["bench", "--collectives", "--size-mb", "2"]) == 0
    lines = [json.loads(l) for l in
             capsys.readouterr().out.strip().splitlines()]
    assert {r["op"] for r in lines} == \
        {"psum", "all_gather", "psum_scatter", "ppermute"}
    for r in lines:
        assert r["ranks"] == 8
        assert r["busbw_gbps"] > 0


def test_stack_lifecycle(tmp_path, capsys):
    state_dir = str(tmp_path)
    assert main(["stack", "create", "--name", "clitest",
                 "--slice-type", "v5p-8", "--provisioner", "dryrun",
                 "--state-dir", state_dir]) == 0
    out = capsys.readouterr().out
    assert "CREATE_COMPLETE" in out

    assert main(["stack", "status", "clitest",
                 "--state-dir", state_dir]) == 0
    status = json.loads(capsys.readouterr().out)
    assert status["status"] == "CREATE_COMPLETE"
    assert len(status["hosts"]) == 2

    assert main(["stack", "list", "--state-dir", state_dir]) == 0
    assert "clitest" in capsys.readouterr().out

    assert main(["stack", "delete", "clitest",
                 "--state-dir", state_dir]) == 0
    assert main(["stack", "status", "clitest",
                 "--state-dir", state_dir]) == 1


def test_stack_resize(tmp_path, capsys):
    """`stack resize` is the reference's change-the-ASG-worker-count flow:
    delete + recreate under the same name with the new topology (SURVEY
    §4.5), training state carried by checkpoints."""
    state_dir = str(tmp_path)
    assert main(["stack", "create", "--name", "rz",
                 "--slice-type", "v5p-8", "--provisioner", "dryrun",
                 "--state-dir", state_dir]) == 0
    capsys.readouterr()
    assert main(["stack", "resize", "rz", "--slice", "v5p-16",
                 "--state-dir", state_dir]) == 0
    out = capsys.readouterr().out
    assert "resized to v5p-16" in out

    assert main(["stack", "status", "rz", "--state-dir", state_dir]) == 0
    status = json.loads(capsys.readouterr().out)
    assert status["status"] == "CREATE_COMPLETE"
    assert status["slice_type"] == "v5p-16"
    assert len(status["hosts"]) == 4  # v5p-16 = 4 hosts (vs 2 for v5p-8)
    # Every create-time knob except the slice type carried over into the
    # recreated stack's recorded config.
    cc = status["create_config"]
    assert cc["slice_type"] == "v5p-16"
    assert cc["provisioner"] == "dryrun"
    assert cc["runtime_version"] == "tpu-ubuntu2204-base"

    # No-op resize is an error, and the stack survives untouched.
    assert main(["stack", "resize", "rz", "--slice", "v5p-16",
                 "--state-dir", state_dir]) == 1
    assert main(["stack", "resize", "ghost", "--slice", "v5p-16",
                 "--state-dir", state_dir]) == 1
    assert main(["stack", "delete", "rz", "--state-dir", state_dir]) == 0


def test_eval_verb_standalone(tmp_path, capsys):
    """`eval` re-judges a finished run from its checkpoint: same weighted
    metrics machinery, no training step."""
    common = [
        "--preset", "cifar10_resnet20", "--accelerator", "cpu",
        f"workdir={tmp_path}", "train.global_batch=32",
        "data.num_train_examples=64", "data.num_eval_examples=32",
        "train.eval_batch=32", "schedule.warmup_epochs=0",
        "checkpoint.async_write=false", "data.prefetch=0",
    ]
    assert main(["train", *common, "train.steps=4",
                 "train.log_every_steps=2"]) == 0
    capsys.readouterr()
    assert main(["eval", *common]) == 0
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert {"loss", "accuracy", "accuracy_top5",
            "checkpoint_step"} <= set(rec)
    assert rec["checkpoint_step"] == 4

    # Evaluating a workdir with no checkpoints errors loudly.
    assert main(["eval", "--preset", "cifar10_resnet20",
                 "--accelerator", "cpu", f"workdir={tmp_path}/empty"]) == 1


def test_metrics_summary_verb(tmp_path, capsys):
    """`metrics` summarizes a run's JSONL: last train step, best eval,
    throughput, and the final acceptance metrics."""
    common = [
        "--preset", "cifar10_resnet20", "--accelerator", "cpu",
        f"workdir={tmp_path}", "train.global_batch=32", "train.steps=8",
        "train.log_every_steps=2", "train.eval_every_steps=4",
        "data.num_train_examples=64", "data.num_eval_examples=32",
        "train.eval_batch=32", "schedule.warmup_epochs=0",
        "checkpoint.async_write=false", "data.prefetch=0",
    ]
    assert main(["train", *common]) == 0
    capsys.readouterr()
    rundir = os.path.join(str(tmp_path), "cifar10_resnet20")
    assert main(["metrics", rundir]) == 0
    rec = json.loads(capsys.readouterr().out)
    assert rec["last_step"] == 8
    assert rec["mean_examples_per_sec"] > 0
    assert "final_eval_accuracy" in rec["final"]
    assert "best_eval_accuracy" in rec

    assert main(["metrics", str(tmp_path / "nope")]) == 1


def test_ckpt_list_and_rollback_verbs(tmp_path, capsys):
    import jax.numpy as jnp

    from deeplearning_cfn_tpu.ckpt import save_checkpoint

    d = str(tmp_path)
    for step in [2, 4, 6]:
        save_checkpoint(d, step, {"w": jnp.zeros((2,))})

    assert main(["ckpt", "list", d]) == 0
    rec = json.loads(capsys.readouterr().out)
    assert rec["committed_steps"] == [2, 4, 6]

    assert main(["ckpt", "rollback", d, "--step", "4"]) == 0
    assert "deleted 1 later checkpoint(s): [6]" in capsys.readouterr().out
    assert main(["ckpt", "list", d]) == 0
    assert json.loads(capsys.readouterr().out)["committed_steps"] == [2, 4]

    assert main(["ckpt", "rollback", d, "--step", "5"]) == 1
    # A mistyped directory is an error, not an empty-but-successful list.
    assert main(["ckpt", "list", d + "-typo"]) == 1


def test_stack_status_missing(tmp_path):
    assert main(["stack", "status", "nope",
                 "--state-dir", str(tmp_path)]) == 1


def test_train_requires_existing_ready_stack(tmp_path):
    assert main(["train", "--preset", "cifar10_resnet20",
                 "--stack", "ghost", "--state-dir", str(tmp_path)]) == 1


def test_train_local_inprocess(tmp_path, capsys):
    """`train` without a stack runs single-host in-process — the 'run the
    example script directly' path."""
    rc = main([
        "train", "--preset", "cifar10_resnet20",
        "--max-steps", "2",
        "--state-dir", str(tmp_path),
        f"workdir={tmp_path}/work",
        "train.global_batch=32",
        "data.num_train_examples=64",
        "data.num_eval_examples=32",
        "data.prefetch=0",
        "checkpoint.async_write=false",
        "train.log_every_steps=1",
    ])
    assert rc == 0
    assert "final metrics" in capsys.readouterr().out


def test_train_on_dryrun_stack_fans_out_worker(tmp_path, capsys):
    """Full `stack create → train` flow: a 1-host dry-run stack, the worker
    module fanned out as a real subprocess via LocalTransport."""
    state_dir = str(tmp_path / "stacks")
    assert main(["stack", "create", "--name", "trainstack",
                 "--slice-type", "v5p-4", "--provisioner", "dryrun",
                 "--state-dir", state_dir]) == 0
    capsys.readouterr()
    rc = main([
        "train", "--preset", "cifar10_resnet20",
        "--stack", "trainstack",
        "--state-dir", state_dir,
        "--max-steps", "2",
        f"workdir={tmp_path}/work",
        "train.global_batch=32",
        "data.num_train_examples=64",
        "data.num_eval_examples=32",
        "data.prefetch=0",
        "checkpoint.async_write=false",
        "train.log_every_steps=1",
    ])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "job finished" in out
    logs = list((tmp_path / "work" / "cifar10_resnet20" / "logs").iterdir())
    assert any("attempt0-host0.log" == p.name for p in logs)


@pytest.mark.skipif(
    tuple(map(int, __import__("jax").__version__.split(".")[:2])) < (0, 5),
    reason="jaxlib 0.4.x CPU backend rejects multi-process SPMD: workers die "
           "with 'INVALID_ARGUMENT: Multiprocess computations aren't "
           "implemented on the CPU backend' once both ranks join the mesh. "
           "Environmental, not a repo bug — see PARITY.md (tier-1 triage).")
def test_train_on_multihost_dryrun_stack(tmp_path, capsys):
    """The keystone cluster simulation: a 2-host dry-run stack (v5p-8),
    `train --stack` fans TWO worker processes that rendezvous over loopback
    via jax.distributed and run real data-parallel steps across 16 fake
    devices — the whole L0→L4 stack with zero real TPUs."""
    state_dir = str(tmp_path / "stacks")
    assert main(["stack", "create", "--name", "mh",
                 "--slice-type", "v5p-8", "--provisioner", "dryrun",
                 "--state-dir", state_dir]) == 0
    capsys.readouterr()
    rc = main([
        "train", "--preset", "cifar10_resnet20",
        "--stack", "mh",
        "--state-dir", state_dir,
        "--max-steps", "2",
        f"workdir={tmp_path}/work",
        "train.global_batch=32",
        "data.num_train_examples=64",
        "data.num_eval_examples=32",
        "train.eval_batch=32",
        "data.prefetch=0",
        "checkpoint.async_write=false",
        "train.log_every_steps=1",
    ])
    out = capsys.readouterr().out
    assert rc == 0, out
    log_dir = tmp_path / "work" / "cifar10_resnet20" / "logs"
    host0 = (log_dir / "attempt0-host0.log").read_text()
    assert "2 processes" in host0, host0  # both ranks joined the mesh
    assert (log_dir / "attempt0-host1.log").exists()


def test_entry_point_matches_pyproject():
    # pyproject [project.scripts] points at cli.main:main — keep them wired.
    from deeplearning_cfn_tpu.cli.main import main as m
    assert callable(m)
