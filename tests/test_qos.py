"""Multi-tenant QoS tests: weighted fair-share admission (deficit
round-robin over per-class sub-queues), per-tenant rate limits with
class-specific retry-after hints, and the engine's preemptive eviction
path — a latency-class arrival that cannot place evicts a running
batch-class stream, which later resumes and must finish with EXACTLY the
tokens it would have produced unpreempted (restart-from-scratch resume is
a pure scheduling event, invisible in outputs).

The back-compat contract rides along: untagged single-tenant traffic
must behave — and serialize — byte-identically to the pre-QoS engine
(FIFO pop order, no qos_* metric keys, unchanged submit call shapes).
"""

import dataclasses
import importlib.util
import json
import os

import numpy as np
import pytest

from deeplearning_cfn_tpu.serve.queue import (
    DEFAULT_QOS_CLASS,
    OverloadError,
    QosSpec,
    RateLimitError,
    RequestQueue,
    RequestState,
    default_qos_classes,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def _q(max_depth=200, clock=None, classes=True):
    kw = {}
    if clock is not None:
        kw["clock"] = clock
    if classes:
        kw["qos_classes"] = default_qos_classes()
    return RequestQueue(max_depth=max_depth, **kw)


# -- queue: fair-share admission ---------------------------------------------


def test_untagged_traffic_pops_in_exact_fifo_and_stays_qos_inactive():
    q = RequestQueue(max_depth=8)
    rids = [q.submit([5, 2, 1], 4, request_id=f"r{i}").id
            for i in range(6)]
    assert not q.qos_active
    assert [q.pop_ready().id for _ in range(6)] == rids
    assert q.fair_share_violation_max() is None


def test_tagged_submit_flips_qos_active():
    q = RequestQueue(max_depth=8)
    q.submit([5, 2, 1], 4)
    assert not q.qos_active
    q.submit([5, 2, 1], 4, qos_class="latency")
    assert q.qos_active


def test_drr_is_weighted_starvation_free_and_fifo_within_class():
    q = _q()
    lat = [q.submit([5, 2, 1], 8, qos_class="latency", tenant="a",
                    request_id=f"l{i}").id for i in range(40)]
    bat = [q.submit([5, 2, 1], 8, qos_class="batch", tenant="b",
                    request_id=f"b{i}").id for i in range(40)]
    order = [q.pop_ready().id for _ in range(80)]
    assert q.pop_ready() is None
    # FIFO within each class, whatever the interleave.
    assert [o for o in order if o.startswith("l")] == lat
    assert [o for o in order if o.startswith("b")] == bat
    # Starvation-free: batch is served while latency still has backlog
    # (weight 8 vs 1 → roughly one batch round per 8 latency rounds,
    # NOT "batch only after latency drains").
    first_batch = order.index("b0")
    assert first_batch < len(lat), "batch starved until latency drained"
    # Weighted: latency dominates the contended prefix ~8:1.
    prefix = order[:48]
    n_lat = sum(1 for o in prefix if o.startswith("l"))
    assert n_lat > 2 * (len(prefix) - n_lat)


def test_drr_blocked_class_skipped_without_losing_its_claim():
    q = _q()
    big = q.submit([5, 2, 1], 8, qos_class="latency", request_id="big")
    q.submit([5, 2, 1], 8, qos_class="batch", request_id="small")
    # The latency head cannot place: its class blocks (FIFO — nothing
    # behind it may jump), but batch keeps draining.
    got = q.pop_ready(can_place=lambda r: r.id != "big")
    assert got is not None and got.id == "small"
    # Once placeable, the blocked head is served before anything else.
    assert q.pop_ready().id == "big"
    assert big.state is RequestState.QUEUED  # engine flips it on placement
    assert q.pop_ready() is None


def test_pop_returns_none_when_every_head_is_unplaceable():
    q = _q()
    q.submit([5, 2, 1], 8, qos_class="latency")
    q.submit([5, 2, 1], 8, qos_class="batch")
    assert q.pop_ready(can_place=lambda r: False) is None
    assert q.depth == 2


def test_fair_share_violation_tracks_contended_shortfall():
    q = _q()
    for i in range(8):
        q.submit([5, 2, 1], 8, qos_class="latency", request_id=f"l{i}")
        q.submit([5, 2, 1], 8, qos_class="batch", request_id=f"b{i}")
    for _ in range(16):
        q.pop_ready()
    v = q.fair_share_violation_max()
    assert v is not None and 0.0 <= v <= 1.0


# -- queue: rate limits and per-class hints ----------------------------------


def test_rate_limit_is_per_tenant_and_hint_is_rate_derived():
    clock = FakeClock()
    classes = default_qos_classes()
    classes["batch"] = dataclasses.replace(classes["batch"],
                                           rate_per_s=2.0, burst=2.0)
    q = RequestQueue(max_depth=500, clock=clock, qos_classes=classes)
    q.submit([5, 2, 1], 4, qos_class="batch", tenant="noisy")
    q.submit([5, 2, 1], 4, qos_class="batch", tenant="noisy")
    with pytest.raises(RateLimitError) as ei:
        q.submit([5, 2, 1], 4, qos_class="batch", tenant="noisy")
    # IS-A OverloadError: every existing shed/backoff path handles it.
    assert isinstance(ei.value, OverloadError)
    assert ei.value.rate_limited and ei.value.tenant == "noisy"
    assert ei.value.retry_after_s == pytest.approx(0.5)
    # A different tenant in the same class has its own bucket.
    q.submit([5, 2, 1], 4, qos_class="batch", tenant="quiet")
    # The bucket refills on the clock.
    clock.advance(0.5)
    q.submit([5, 2, 1], 4, qos_class="batch", tenant="noisy")


def test_batch_overload_hint_exceeds_latency_hint_under_backlog():
    clock = FakeClock()
    classes = default_qos_classes()
    classes["batch"] = dataclasses.replace(classes["batch"],
                                           rate_per_s=2.0, burst=100.0)
    q = RequestQueue(max_depth=10, clock=clock, qos_classes=classes)
    for i in range(10):
        q.submit([5, 2, 1], 4, qos_class="batch", request_id=f"b{i}")
    with pytest.raises(OverloadError) as bat:
        q.submit([5, 2, 1], 4, qos_class="batch")
    with pytest.raises(OverloadError) as lat:
        q.submit([5, 2, 1], 4, qos_class="latency")
    # Batch is told to wait out its own backlog (10 pending / 2 per s);
    # latency gets the base (cold-start floor) estimate.
    assert bat.value.retry_after_s == pytest.approx(5.0)
    assert lat.value.retry_after_s == \
        RequestQueue.DEFAULT_RETRY_AFTER_FLOOR_S
    assert bat.value.retry_after_s > lat.value.retry_after_s


def test_qos_spec_validation():
    with pytest.raises(ValueError):
        QosSpec("bad", weight=0)
    with pytest.raises(ValueError):
        QosSpec("bad", rate_per_s=-1.0)
    with pytest.raises(ValueError):
        _q().submit([5, 2, 1], 4, qos_class="no-such-class")


def test_default_class_is_standard():
    q = _q()
    req = q.submit([5, 2, 1], 4)
    assert req.qos_class == DEFAULT_QOS_CLASS == "standard"
    assert req.tenant is None


# -- engine: preemptive eviction + token-identical resume --------------------


SRC_LEN = 8
MAX_NEW = 6


@pytest.fixture(scope="module")
def qos_model():
    import jax

    from deeplearning_cfn_tpu.models.transformer_nmt import (
        transformer_nmt_tiny,
    )

    model = transformer_nmt_tiny(vocab_size=96, hidden_size=32,
                                 num_layers=1, num_heads=2, mlp_dim=64,
                                 max_len=32)
    init = model.init(
        jax.random.PRNGKey(0), np.zeros((1, SRC_LEN), np.int32),
        np.ones((1, SRC_LEN), np.int32),
        np.zeros((1, SRC_LEN), np.int32), train=False)
    return model, {"params": init["params"]}


def _mk_engine(qos_model, **kw):
    from deeplearning_cfn_tpu.serve.engine import Engine

    model, variables = qos_model
    kw.setdefault("capacity", 2)
    kw.setdefault("max_src_len", SRC_LEN)
    kw.setdefault("queue_depth", 16)
    kw.setdefault("default_max_new_tokens", MAX_NEW)
    kw.setdefault("decode_window", 2)
    return Engine(model, variables, **kw)


def _srcs(n):
    rng = np.random.RandomState(7)
    return [[int(t) for t in rng.randint(3, 96, size=SRC_LEN)]
            for _ in range(n)]


def _drain_tokens(engine, rids):
    engine.run_until_drained()
    out = {}
    for rid in rids:
        req = engine.poll(rid)
        assert req.state is RequestState.DONE
        out[rid] = list(req.tokens)
    return out


@pytest.mark.parametrize("beam,kv", [(1, 0), (1, 4), (2, 0), (2, 4)],
                         ids=["greedy-dense", "greedy-paged",
                              "beam-dense", "beam-paged"])
def test_preempt_resume_token_parity(qos_model, beam, kv):
    """A batch-class stream evicted mid-decode by a latency arrival must
    resume and finish token-identical to an unpreempted run — greedy and
    beam, dense and paged caches alike."""
    srcs = _srcs(3)
    kw = dict(kv_block_size=kv)

    # Baseline: same requests, untagged, no contention-driven eviction.
    base = _mk_engine(qos_model, **kw)
    b1 = base.submit(srcs[0], max_new_tokens=MAX_NEW, beam_size=beam)
    b2 = None
    if beam == 1:
        b2 = base.submit(srcs[1], max_new_tokens=MAX_NEW)
    b3 = base.submit(srcs[2], max_new_tokens=2)
    base_rids = [r.id for r in (b1, b2, b3) if r is not None]
    baseline = _drain_tokens(base, base_rids)

    eng = _mk_engine(qos_model, **kw)
    # Fill every row with preemptible batch work: one beam-2 group (two
    # rows) or two greedy streams.
    r1 = eng.submit(srcs[0], max_new_tokens=MAX_NEW, beam_size=beam,
                    tenant="tenant-b", qos_class="batch")
    r2 = None
    if beam == 1:
        r2 = eng.submit(srcs[1], max_new_tokens=MAX_NEW,
                        tenant="tenant-b", qos_class="batch")
    for _ in range(2):      # let the batch work decode a bit first
        eng.step()
    # The latency arrival cannot place → evicts a batch stream.
    r3 = eng.submit(srcs[2], max_new_tokens=2, tenant="tenant-a",
                    qos_class="latency")
    rids = [r.id for r in (r1, r2, r3) if r is not None]
    tokens = _drain_tokens(eng, rids)

    assert eng.metrics.preemptions >= 1
    assert eng.metrics.qos_token_loss == 0
    snap = eng.metrics.snapshot()
    assert snap["serve_preemptions"] == eng.metrics.preemptions
    # Every decoded token is goodput or audited waste — preemption
    # replay never double-counts.
    assert snap["serve_goodput_tokens"] + snap["serve_wasted_tokens"] \
        == snap["serve_tokens_generated"]
    preempted = [rid for rid in rids
                 if eng.poll(rid).preemptions > 0]
    assert preempted, "no request recorded a preemption"
    for rid in preempted:
        assert eng.poll(rid).preempted_s >= 0.0
    # The contract: preemption is invisible in outputs.
    assert len(base_rids) == len(rids)
    for brid, rid in zip(base_rids, rids):
        assert tokens[rid] == baseline[brid], \
            f"preempted run diverged on {rid}"


def test_preemption_needs_qos_traffic(qos_model):
    """Untagged traffic never preempts — the engine stays byte-for-byte
    the pre-QoS scheduler, including its metrics snapshot keys."""
    eng = _mk_engine(qos_model)
    srcs = _srcs(3)
    rids = [eng.submit(s, max_new_tokens=3).id for s in srcs]
    tokens = _drain_tokens(eng, rids)
    assert all(len(t) > 0 for t in tokens.values())
    assert eng.metrics.preemptions == 0
    snap = eng.metrics.snapshot()
    assert "serve_preemptions" not in snap
    assert "serve_qos_by_class" not in snap
    assert not eng.queue.qos_active


def test_qos_snapshot_surfaces_by_class(qos_model):
    eng = _mk_engine(qos_model)
    srcs = _srcs(2)
    rids = [
        eng.submit(srcs[0], max_new_tokens=3, tenant="a",
                   qos_class="latency").id,
        eng.submit(srcs[1], max_new_tokens=3, tenant="b",
                   qos_class="batch").id,
    ]
    _drain_tokens(eng, rids)
    snap = eng.metrics.snapshot()
    by_cls = snap["serve_qos_by_class"]
    assert by_cls["latency"]["completed"] == 1
    assert by_cls["batch"]["completed"] == 1
    assert by_cls["latency"]["latency_p95_s"] is not None


@pytest.mark.parametrize("beam", [1, 2], ids=["greedy", "beam"])
def test_preempt_resume_parity_across_disagg_handoff(qos_model, beam):
    """Preemption composes with disaggregation: a batch-class stream
    imported onto a decode engine via the KV handoff is evicted by a
    direct latency submit, re-prefills locally, and still finishes
    token-identical to a co-located run of the same trace."""
    srcs = _srcs(3)

    co = _mk_engine(qos_model, kv_block_size=4)
    c1 = co.submit(srcs[0], max_new_tokens=MAX_NEW, beam_size=beam)
    c2 = None
    if beam == 1:
        c2 = co.submit(srcs[1], max_new_tokens=MAX_NEW)
    c3 = co.submit(srcs[2], max_new_tokens=2)
    co_rids = [r.id for r in (c1, c2, c3) if r is not None]
    baseline = _drain_tokens(co, co_rids)

    pre = _mk_engine(qos_model, kv_block_size=4, phase="prefill")
    dec = _mk_engine(qos_model, kv_block_size=4, phase="decode")
    parked = [pre.submit(srcs[0], max_new_tokens=MAX_NEW,
                         beam_size=beam, tenant="tenant-b",
                         qos_class="batch")]
    if beam == 1:
        parked.append(pre.submit(srcs[1], max_new_tokens=MAX_NEW,
                                 tenant="tenant-b", qos_class="batch"))
    pre.run_until_drained()
    imported = []
    for req in parked:
        assert pre.handoff_ready(req.id)
        art = pre.export_handoff(req.id)
        imported.append(dec.import_handoff(
            art, request_id=req.id + "#a1", tenant="tenant-b",
            qos_class="batch"))
        pre.release_handoff(req.id)
    assert dec.queue.qos_active
    for _ in range(2):
        dec.step()
    lat = dec.submit(srcs[2], max_new_tokens=2, tenant="tenant-a",
                     qos_class="latency")
    rids = [r.id for r in imported] + [lat.id]
    tokens = _drain_tokens(dec, rids)

    assert dec.metrics.preemptions >= 1
    assert dec.metrics.qos_token_loss == 0
    for brid, rid in zip(co_rids, rids):
        assert tokens[rid] == baseline[brid], \
            f"handoff+preempt run diverged on {rid}"


# -- fleet: router threading + ledger ----------------------------------------


def test_router_ledger_tags_tenant_class_and_preemptions(qos_model):
    from deeplearning_cfn_tpu.fleet import EngineReplica, Router

    eng = _mk_engine(qos_model, capacity=1, kv_block_size=4)
    router = Router([EngineReplica("replica-0", eng)])
    b = router.submit(_srcs(1)[0], max_new_tokens=MAX_NEW,
                      tenant="tenant-b", qos_class="batch")
    router.step()
    lat = router.submit(_srcs(2)[1], max_new_tokens=2,
                        tenant="tenant-a", qos_class="latency")
    plain = router.submit(_srcs(3)[2], max_new_tokens=2)
    router.run_until_drained()
    for rid in (b, lat, plain):
        assert router.result(rid)["state"] == "done"
    entry = router.ledger[b]
    assert entry["tenant"] == "tenant-b"
    assert entry["qos_class"] == "batch"
    assert entry["preemptions"] >= 1
    assert entry["phases"]["preempted_s"] >= 0.0
    assert router.ledger[lat]["qos_class"] == "latency"
    # Untagged requests keep the exact pre-QoS ledger key set.
    assert "tenant" not in router.ledger[plain]
    assert "qos_class" not in router.ledger[plain]
    assert "preempted_s" not in router.ledger[plain]["phases"]


# -- loadgen: tenant mixes ---------------------------------------------------


def test_tenants_mix_classes_carry_tags():
    from deeplearning_cfn_tpu.loadgen import parse_trace_spec

    spec = parse_trace_spec("poisson:mix=tenants", src_len=12,
                            max_new_tokens=16, requests=12)
    by_name = {c.name: c for c in spec.classes}
    assert by_name["interactive"].tenant == "tenant-a"
    assert by_name["interactive"].qos_class == "latency"
    assert by_name["bulk"].tenant == "tenant-b"
    assert by_name["bulk"].qos_class == "batch"
    # The uniform mix stays untagged.
    uni = parse_trace_spec("poisson", src_len=12, max_new_tokens=16)
    assert all(c.tenant is None and c.qos_class is None
               for c in uni.classes)


class _CaptureRouter:
    def __init__(self):
        self.ledger = {}
        self.calls = []

    def submit(self, src_ids, max_new_tokens, request_id, **kw):
        self.calls.append((request_id, dict(kw)))
        self.ledger[request_id] = {"phases": {}}
        return request_id

    def step(self):
        return False

    def pending(self):
        return 0


@pytest.mark.parametrize("mix,tagged", [("tenants", True),
                                        ("uniform", False)])
def test_replay_submits_tenant_tags_through_router(mix, tagged):
    from deeplearning_cfn_tpu.loadgen import (
        LoadGenerator,
        VirtualClock,
        parse_trace_spec,
        replay,
    )

    spec = parse_trace_spec(f"poisson:duration=0.5,mix={mix}",
                            src_len=8, max_new_tokens=4, requests=8)
    gen = LoadGenerator(spec, seed=0)
    router = _CaptureRouter()
    replay(gen, router, VirtualClock(), tick_s=0.05)
    assert router.calls
    if tagged:
        by_cls = {s.request_id: s.qos_class for s in gen.schedule}
        for rid, kw in router.calls:
            assert kw["qos_class"] == by_cls[rid]
            assert kw["tenant"] in ("tenant-a", "tenant-b")
    else:
        # Back-compat call shape: untagged replay must not even pass
        # the kwargs (pre-QoS router fakes reject unknown keys).
        for _, kw in router.calls:
            assert "tenant" not in kw and "qos_class" not in kw


# -- obs: SLO rules, report, tail --------------------------------------------


def test_slo_rule_class_field_reads_nested_qos_section():
    from deeplearning_cfn_tpu.obs.slo import Rule, RuleError

    rule = Rule({"metric": "latency_p95_s", "class": "latency",
                 "kind": "threshold", "max": 0.5})
    ok = {"serve_qos_by_class": {
        "latency": {"latency_p95_s": 0.4},
        "batch": {"latency_p95_s": 9.0}}}
    assert rule.observe(ok) is None
    bad = {"serve_qos_by_class": {"latency": {"latency_p95_s": 0.7}}}
    alert = rule.observe(bad)
    assert alert is not None and alert["class"] == "latency"
    # A top-level key of the same name is NOT the per-class value.
    rule2 = Rule({"metric": "latency_p95_s", "class": "latency",
                  "kind": "threshold", "max": 0.5})
    assert rule2.observe({"latency_p95_s": 0.7}) is None
    with pytest.raises(RuleError):
        Rule({"metric": "latency_p95_s", "class": "", "max": 1.0})


def test_summarize_reports_per_tenant_sections(tmp_path):
    from deeplearning_cfn_tpu.obs.report import render_report, summarize

    p = tmp_path / "metrics.jsonl"
    snap = {"serve_completed": 3, "serve_submitted": 3,
            "serve_preemptions": 2, "serve_preempted_tokens_replayed": 7,
            "serve_qos_token_loss": 0,
            "serve_fair_share_violation_max": 0.1,
            "serve_qos_by_class": {
                "latency": {"completed": 1, "latency_p50_s": 0.01,
                            "latency_p95_s": 0.02},
                "batch": {"completed": 2, "latency_p50_s": 0.5,
                          "latency_p95_s": 0.9}}}
    p.write_text(json.dumps(snap) + "\n")
    out = summarize(str(p))
    qos = out["serve"]["qos"]
    assert qos["preemptions"] == 2
    assert qos["by_class"]["batch"]["completed"] == 2
    text = render_report(out)
    assert "qos latency" in text and "qos batch" in text
    assert "preemptions" in text
    # Single-tenant snapshots keep the exact pre-QoS section shape.
    p2 = tmp_path / "plain.jsonl"
    p2.write_text(json.dumps({"serve_completed": 1}) + "\n")
    out2 = summarize(str(p2))
    assert "qos" not in out2["serve"]
    assert "qos" not in render_report(out2)


def test_tail_status_line_shows_preemptions():
    from deeplearning_cfn_tpu.obs.tail import FleetTailState, TailState

    st = TailState()
    st.update({"serve_submitted": 2, "serve_completed": 1})
    assert "preempt" not in st.status_line()
    st.update({"serve_submitted": 3, "serve_preemptions": 2})
    assert "preempt 2" in st.status_line()
    fst = FleetTailState(["replica-0", "replica-1"])
    fst.update("replica-0", {"serve_submitted": 2, "serve_preemptions": 1})
    fst.update("replica-1", {"serve_submitted": 2, "serve_preemptions": 3})
    assert "preempt 4" in fst.status_line()
    fplain = FleetTailState(["replica-0"])
    fplain.update("replica-0", {"serve_submitted": 2})
    assert "preempt" not in fplain.status_line()


# -- root bench wrapper: null-over-zero for qos fields -----------------------


def test_finalize_green_nulls_qos_fields_when_unmeasured(monkeypatch):
    spec = importlib.util.spec_from_file_location(
        "root_bench_qos", os.path.join(REPO_ROOT, "bench.py"))
    w = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(w)
    monkeypatch.delenv("JAX_PLATFORMS", raising=False)
    rec = w._finalize_green(
        {"measured": False, "value": 9.9, "device_kind": "TPU v5e",
         "error": "x", "qos_p95_by_class": {"latency": 0.1},
         "preemptions": 3, "preempted_tokens_replayed": 12,
         "fair_share_violation_max": 0.2,
         "qos_decode_p95_no_adversary": 0.05},
        alive=True, probe_note="probe: tpu alive")
    for key in ("qos_p95_by_class", "preemptions",
                "preempted_tokens_replayed", "fair_share_violation_max",
                "qos_decode_p95_no_adversary"):
        assert rec[key] is None
    rec2 = w._finalize_green(
        {"measured": False, "value": 1.0, "device_kind": "TPU v5e",
         "error": "x"}, alive=True, probe_note="probe: tpu alive")
    assert "preemptions" not in rec2   # key set untouched when absent
