"""Config system + presets: the training-tier flag surface (SURVEY.md §6)."""

import dataclasses

import pytest

from deeplearning_cfn_tpu.config import ExperimentConfig, apply_overrides
from deeplearning_cfn_tpu.presets import get_preset, list_presets

BASELINE_PRESETS = [
    "cifar10_resnet20",
    "imagenet_resnet50",
    "bert_base_wikipedia",
    "maskrcnn_coco",
    "transformer_nmt_wmt",
]


def test_all_baseline_presets_registered():
    assert set(BASELINE_PRESETS) <= set(list_presets())


@pytest.mark.parametrize("name", BASELINE_PRESETS)
def test_presets_construct_and_serialize(name):
    cfg = get_preset(name)
    assert cfg.preset == name
    d = cfg.to_dict()
    assert d["model"]["name"]
    assert cfg.to_json()


def test_preset_isolation():
    a = get_preset("cifar10_resnet20")
    a.train.global_batch = 999
    b = get_preset("cifar10_resnet20")
    assert b.train.global_batch != 999


def test_overrides_scalar_types():
    cfg = ExperimentConfig()
    apply_overrides(cfg, [
        "train.global_batch=256",
        "schedule.base_lr=0.5",
        "train.remat=true",
        "model.name=resnet50",
        "mesh.model=2",
    ])
    assert cfg.train.global_batch == 256
    assert cfg.schedule.base_lr == 0.5
    assert cfg.train.remat is True
    assert cfg.model.name == "resnet50"
    assert cfg.mesh.model == 2


def test_overrides_tuple_and_dict():
    cfg = ExperimentConfig()
    apply_overrides(cfg, ["schedule.step_boundaries=0.5,0.75"])
    assert cfg.schedule.step_boundaries == (0.5, 0.75)
    apply_overrides(cfg, ["model.kwargs.depth=20"])
    assert cfg.model.kwargs["depth"] == 20


def test_overrides_unknown_key_raises():
    cfg = ExperimentConfig()
    with pytest.raises(KeyError):
        apply_overrides(cfg, ["train.nonexistent=1"])
    with pytest.raises(KeyError):
        apply_overrides(cfg, ["nosection.x=1"])
    with pytest.raises(ValueError):
        apply_overrides(cfg, ["no_equals_sign"])


def test_config_is_dataclass_tree():
    cfg = ExperimentConfig()
    assert dataclasses.is_dataclass(cfg.train)
    assert dataclasses.is_dataclass(cfg.stack)
