"""Numerics tests for the kernel layer (ops/): flash attention vs the jnp
oracle (kernel run in Pallas interpreter mode — CPU-runnable), gradients
through the custom VJP, and ring attention vs full attention on the fake
8-device mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from deeplearning_cfn_tpu.ops.ulysses import ulysses_attention_sharded
from deeplearning_cfn_tpu.ops import (
    attention_reference,
    fused_attention,
    ring_attention_sharded,
)


def _qkv(b=2, h=2, sq=64, sk=64, d=32, seed=0, dtype=jnp.float32):
    rng = np.random.RandomState(seed)
    mk = lambda s: jnp.asarray(rng.normal(0, 1, (b, h, s, d)), dtype)
    return mk(sq), mk(sk), mk(d * 0 + sk)[:, :, :sk, :]


def test_reference_matches_naive_softmax():
    q, k, v = _qkv()
    out = attention_reference(q, k, v)
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k) / jnp.sqrt(32.0)
    naive = jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(logits, -1), v)
    np.testing.assert_allclose(out, naive, atol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("sq,sk", [(64, 64), (64, 128), (100, 100)])
def test_flash_kernel_matches_reference(causal, sq, sk):
    """The Pallas kernel (interpreter mode) must agree with the oracle,
    including non-block-multiple lengths (padding path) and causal masks."""
    if causal and sq != sk and sq == 64 and sk == 128:
        pass  # cross-length causal aligns ends — covered below too
    q, k, v = _qkv(sq=sq, sk=sk)
    ref = attention_reference(q, k, v, causal=causal)
    out = fused_attention(q, k, v, causal=causal,
                          implementation="interpret")
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_flash_kernel_with_padding_bias():
    """Additive -inf padding bias (BERT padding mask shape [B,1,1,Sk])."""
    q, k, v = _qkv(sq=64, sk=64)
    kv_len = 40
    bias = jnp.where(jnp.arange(64) < kv_len, 0.0, -1e30)
    bias = bias[None, None, None, :]
    ref = attention_reference(q, k, v, bias=bias)
    out = fused_attention(q, k, v, bias=bias, implementation="interpret")
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)
    # Masked-out keys truly don't contribute.
    v2 = v.at[:, :, kv_len:, :].set(999.0)
    out2 = fused_attention(q, k, v2, bias=bias, implementation="interpret")
    np.testing.assert_allclose(np.asarray(out), np.asarray(out2), atol=1e-5)


def test_fused_attention_grads_match_reference():
    q, k, v = _qkv(sq=32, sk=32, d=16)

    def loss_ref(q, k, v):
        return jnp.sum(attention_reference(q, k, v, causal=True) ** 2)

    def loss_fused(q, k, v):
        return jnp.sum(fused_attention(q, k, v, causal=True,
                                       implementation="interpret") ** 2)

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_fused = jax.grad(loss_fused, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ref, g_fused):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-4, rtol=5e-4)


def test_fused_attention_bfloat16():
    q, k, v = _qkv(dtype=jnp.bfloat16)
    out = fused_attention(q, k, v, implementation="interpret")
    ref = attention_reference(q, k, v)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32),
        atol=3e-2, rtol=3e-2)


def test_auto_dispatch_short_seq_window():
    """The 'auto' dispatch (ops/attention._auto_use_pallas): flash kernel
    on TPU except the hardware-measured short-seq window (S<1024) where
    XLA's fused attention is faster — and only while its quadratic
    backward intermediate fits the cap (at big batch, flash's O(S)
    memory wins regardless)."""
    from deeplearning_cfn_tpu.ops.attention import _auto_use_pallas

    # Never pallas off-TPU.
    assert _auto_use_pallas("cpu", 8, 12, 512, 512) is False
    # Short seq on TPU within the memory cap -> XLA path.
    assert _auto_use_pallas("tpu", 32, 12, 512, 512) is False
    # Long seq -> flash (the measured 1.4x/35x regime).
    assert _auto_use_pallas("tpu", 8, 12, 2048, 2048) is True
    assert _auto_use_pallas("tpu", 2, 12, 8192, 8192) is True
    # Short seq but the f32 [B,H,Sq,Sk] backward intermediate exceeds
    # the 512 MiB cap -> flash for memory: 512*12*512*512*4 B = 6.0 GiB.
    assert _auto_use_pallas("tpu", 512, 12, 512, 512) is True
    # Near-cap case (60*16*512*512*4 B ≈ 0.94 GiB > 512 MiB): must stay
    # flash — the XLA backward holds 2-3 such buffers live at once.
    assert _auto_use_pallas("tpu", 60, 16, 512, 512) is True
    # The r03 bench shape (32*12*512*512*4 B ≈ 402 MiB) stays eligible.
    assert _auto_use_pallas("tpu", 32, 12, 512, 512) is False


def test_fused_attention_shape_validation():
    with pytest.raises(ValueError, match="B,H,S,D"):
        fused_attention(jnp.zeros((4, 8, 16)), jnp.zeros((4, 8, 16)),
                        jnp.zeros((4, 8, 16)))
    with pytest.raises(ValueError, match="implementation"):
        q, k, v = _qkv(sq=8, sk=8, d=8)
        fused_attention(q, k, v, implementation="cuda")


@pytest.mark.parametrize("sq,sk", [(192, 192), (300, 300), (40, 72)])
def test_flash_causal_with_block_padding(sq, sk):
    """Shapes where Q and K pad by DIFFERENT amounts: the causal diagonal
    must still align to the true lengths (regression: padded lengths used
    to shift the mask, leaking future positions)."""
    q, k, v = _qkv(b=1, h=1, sq=sq, sk=sk, d=16, seed=3)
    ref = attention_reference(q, k, v, causal=True)
    out = fused_attention(q, k, v, causal=True, implementation="interpret")
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_flash_bias_broadcast_k_dim():
    """Bias with K dim == 1 (broadcast over keys, e.g. a per-query additive
    term): the contract is 'broadcastable to [B,H,Sq,Sk]' and the reference
    path accepts it, so the kernel path must agree (regression: used to
    raise ValueError)."""
    q, k, v = _qkv(b=1, h=2, sq=64, sk=72, d=16, seed=5)
    bias = jnp.asarray(
        np.random.RandomState(6).normal(0, 1, (1, 1, 64, 1)), jnp.float32)
    ref = attention_reference(q, k, v, bias=bias)
    out = fused_attention(q, k, v, bias=bias, implementation="interpret")
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)
    # And combined with causal masking (kv block padding in play: sk=72).
    ref_c = attention_reference(q, k, v, bias=bias, causal=True)
    out_c = fused_attention(q, k, v, bias=bias, causal=True,
                            implementation="interpret")
    np.testing.assert_allclose(np.asarray(out_c), np.asarray(ref_c),
                               atol=2e-5, rtol=2e-5)


def test_flash_bias_with_kv_padding():
    """User bias [B,1,1,sk] where sk needs block padding (regression: used
    to crash on shape mismatch when adding the pad bias)."""
    sk = 200
    q, k, v = _qkv(b=1, h=2, sq=64, sk=sk, d=16, seed=4)
    bias = jnp.where(jnp.arange(sk) < 150, 0.0, -1e30)[None, None, None, :]
    ref = attention_reference(q, k, v, bias=bias)
    out = fused_attention(q, k, v, bias=bias, implementation="interpret")
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("sq,sk", [(64, 64), (100, 100), (64, 128),
                                   (40, 72)])
def test_flash_backward_matches_reference(causal, sq, sk):
    """The blocked flash backward (dq/dk/dv kernels, interpret mode) must
    agree with the reference VJP — including block-padded lengths where the
    causal diagonal and padded rows/columns need masking in the recompute."""
    q, k, v = _qkv(b=2, h=2, sq=sq, sk=sk, d=16, seed=8)
    g = jnp.asarray(
        np.random.RandomState(9).normal(0, 1, q.shape[:-1] + (16,)),
        jnp.float32)

    def loss_ref(q, k, v):
        return jnp.vdot(attention_reference(q, k, v, causal=causal), g)

    def loss_flash(q, k, v):
        return jnp.vdot(
            fused_attention(q, k, v, causal=causal,
                            implementation="interpret"), g)

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", g_ref, g_flash):
        np.testing.assert_allclose(
            np.asarray(b), np.asarray(a), atol=5e-4, rtol=5e-4,
            err_msg=f"d{name} mismatch")


def test_flash_backward_bf16():
    q, k, v = _qkv(sq=128, sk=128, d=32, dtype=jnp.bfloat16, seed=10)

    def loss(impl):
        def f(q, k, v):
            return jnp.sum(fused_attention(q, k, v, causal=True,
                                           implementation=impl) ** 2)
        return jax.grad(f, argnums=(0, 1, 2))(q, k, v)

    for a, b in zip(loss("reference"), loss("interpret")):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32),
                                   atol=5e-2, rtol=5e-2)


def test_flash_backward_no_full_score_matrix():
    """The point of the flash backward: no [Sq,Sk] intermediate anywhere in
    the grad computation (walk the jaxpr, including pallas kernel bodies —
    block tiles are fine, full S×S is not)."""
    sq = sk = 512  # well above both block sizes
    q, k, v = _qkv(b=1, h=1, sq=sq, sk=sk, d=16, seed=11)

    def loss(q, k, v):
        return jnp.sum(fused_attention(q, k, v, causal=True,
                                       implementation="interpret") ** 2)

    jaxpr = jax.make_jaxpr(jax.grad(loss, argnums=(0, 1, 2)))(q, k, v)

    offenders = []

    def walk(jx):
        for eqn in jx.eqns:
            for var in list(eqn.invars) + list(eqn.outvars):
                aval = getattr(var, "aval", None)
                shape = getattr(aval, "shape", ())
                if len(shape) >= 2 and shape[-1] == sk and \
                        shape[-2] == sq:
                    offenders.append((eqn.primitive.name, shape))
            for param in eqn.params.values():
                inner = getattr(param, "jaxpr", param)
                if hasattr(inner, "eqns"):
                    walk(inner)

    walk(jaxpr.jaxpr)
    assert not offenders, f"full score-matrix tensors found: {offenders}"


# -- ring attention ---------------------------------------------------------


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_full(devices, causal):
    """Sequence sharded 8 ways over the mesh: the ring result must equal
    single-device full attention — it is exact, not approximate."""
    mesh = Mesh(np.asarray(devices), ("data",))
    q, k, v = _qkv(b=2, h=2, sq=128, sk=128, d=32)
    ref = attention_reference(q, k, v, causal=causal)
    out = ring_attention_sharded(q, k, v, mesh, axis_name="data",
                                 causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_grads_flow(devices, causal):
    mesh = Mesh(np.asarray(devices), ("data",))
    q, k, v = _qkv(b=1, h=1, sq=64, sk=64, d=16)

    def loss(q, k, v):
        return jnp.sum(ring_attention_sharded(q, k, v, mesh,
                                              axis_name="data",
                                              causal=causal) ** 2)

    grads = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)

    def loss_ref(q, k, v):
        return jnp.sum(attention_reference(q, k, v, causal=causal) ** 2)

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(grads, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-4, rtol=5e-4)


def test_ring_attention_composed_data_seq_shard(devices):
    """Composed parallelism on a (data=2, seq=4) mesh: batch sharded over
    'data', sequence ring over 'seq' — forward and backward both match the
    single-device oracle."""
    mesh = Mesh(np.asarray(devices).reshape(2, 4), ("data", "seq"))
    q, k, v = _qkv(b=4, h=2, sq=128, sk=128, d=16, seed=12)

    out = ring_attention_sharded(q, k, v, mesh, axis_name="seq",
                                 causal=True, batch_axis="data")
    ref = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)

    def loss(q, k, v):
        return jnp.sum(ring_attention_sharded(
            q, k, v, mesh, axis_name="seq", causal=True,
            batch_axis="data") ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(attention_reference(q, k, v, causal=True) ** 2)

    grads = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(grads, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-4, rtol=5e-4)


def test_ring_attention_backward_no_stacked_rotations(devices):
    """The custom VJP must not save every K/V rotation as scan residuals —
    that per-device memory would grow with the axis size, defeating
    sequence parallelism. Walk the grad jaxpr for stacked [axis_size-1,...]
    K/V-shaped tensors."""
    mesh = Mesh(np.asarray(devices), ("data",))
    b, h, s, d = 1, 1, 64, 16
    q, k, v = _qkv(b=b, h=h, sq=s, sk=s, d=d)

    def loss(q, k, v):
        return jnp.sum(ring_attention_sharded(q, k, v, mesh,
                                              axis_name="data") ** 2)

    jaxpr = jax.make_jaxpr(jax.grad(loss, argnums=(0, 1, 2)))(q, k, v)
    n_rot = 7  # axis_size - 1
    s_local = s // 8
    offenders = []

    def walk(jx):
        for eqn in jx.eqns:
            for var in list(eqn.invars) + list(eqn.outvars):
                shape = getattr(getattr(var, "aval", None), "shape", ())
                if len(shape) == 5 and shape[0] == n_rot and \
                        shape[-2:] == (s_local, d):
                    offenders.append((eqn.primitive.name, shape))
            for param in eqn.params.values():
                inner = getattr(param, "jaxpr", param)
                if hasattr(inner, "eqns"):
                    walk(inner)

    walk(jaxpr.jaxpr)
    assert not offenders, f"stacked per-rotation residuals: {offenders}"


# -- ulysses (all-to-all) sequence parallelism ------------------------------


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_attention_matches_full(devices, causal):
    """Sequence sharded 8 ways, heads reswizzled via all_to_all: the result
    must equal single-device full attention — exact, like the ring."""
    mesh = Mesh(np.asarray(devices), ("data",))
    q, k, v = _qkv(b=2, h=8, sq=128, sk=128, d=32)
    ref = attention_reference(q, k, v, causal=causal)
    out = ulysses_attention_sharded(q, k, v, mesh, axis_name="data",
                                    causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_attention_grads_match(devices, causal):
    mesh = Mesh(np.asarray(devices), ("data",))
    q, k, v = _qkv(b=1, h=8, sq=64, sk=64, d=16)

    def loss(q, k, v):
        return jnp.sum(ulysses_attention_sharded(
            q, k, v, mesh, axis_name="data", causal=causal) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(attention_reference(q, k, v, causal=causal) ** 2)

    grads = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(grads, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-4, rtol=5e-4)


def test_ulysses_composed_data_seq_shard(devices):
    """Composed (data=2, seq=4) mesh: batch over 'data', sequence all-to-all
    over 'seq' — forward and backward match the single-device oracle."""
    mesh = Mesh(np.asarray(devices).reshape(2, 4), ("data", "seq"))
    q, k, v = _qkv(b=4, h=4, sq=128, sk=128, d=16, seed=12)

    out = ulysses_attention_sharded(q, k, v, mesh, axis_name="seq",
                                    causal=True, batch_axis="data")
    ref = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)

    def loss(q, k, v):
        return jnp.sum(ulysses_attention_sharded(
            q, k, v, mesh, axis_name="seq", causal=True,
            batch_axis="data") ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(attention_reference(q, k, v, causal=True) ** 2)

    grads = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(grads, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-4, rtol=5e-4)


def test_ulysses_rejects_indivisible_heads(devices):
    mesh = Mesh(np.asarray(devices), ("data",))
    q, k, v = _qkv(b=1, h=6, sq=64, sk=64, d=16)  # 6 % 8 != 0
    with pytest.raises(ValueError, match="divisible"):
        ulysses_attention_sharded(q, k, v, mesh, axis_name="data")


def test_ulysses_agrees_with_ring(devices):
    """The two sequence-parallel strategies are interchangeable: same
    inputs, same mesh → same attention output."""
    mesh = Mesh(np.asarray(devices), ("data",))
    q, k, v = _qkv(b=2, h=8, sq=128, sk=128, d=16, seed=5)
    a = ulysses_attention_sharded(q, k, v, mesh, axis_name="data",
                                  causal=True)
    b = ring_attention_sharded(q, k, v, mesh, axis_name="data", causal=True)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                               atol=2e-5, rtol=2e-5)
