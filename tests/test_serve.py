"""serve/ subsystem tests: request lifecycle, scheduler invariants, engine
parity against the offline searchers, metrics, and the CLI driver.

Parity is the load-bearing guarantee: continuous batching must be a pure
scheduling optimization, token-identical to models/decoding.py's greedy and
beam searchers for every request — whatever slot churn happened around it.
The WMT sliver fixtures (tests/data/wmt_sliver.{de,en}) provide real
sentences for that check via a BPE vocabulary trained on them.
"""

import json
import os

import jax
import numpy as np
import pytest

from deeplearning_cfn_tpu.data.bpe import NMT_SPECIALS, train_bpe
from deeplearning_cfn_tpu.models import decoding
from deeplearning_cfn_tpu.models.transformer_nmt import transformer_nmt_tiny
from deeplearning_cfn_tpu.serve import (
    BlockAllocator,
    BlockPoolExhausted,
    Engine,
    OverloadError,
    PrefixCache,
    RequestQueue,
    RequestState,
    ServeMetrics,
    percentile,
)

DATA_DIR = os.path.join(os.path.dirname(__file__), "data")


def _sliver_lines(lang):
    with open(os.path.join(DATA_DIR, f"wmt_sliver.{lang}")) as fh:
        return [ln.strip() for ln in fh if ln.strip()]


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


# -- queue ------------------------------------------------------------------


def test_queue_lifecycle_and_fifo():
    q = RequestQueue(max_depth=4)
    a = q.submit([5, 2], 8)
    b = q.submit([6, 2], 8, beam_size=2)
    assert a.state is RequestState.QUEUED and q.depth == 2
    assert q.pop_ready() is a  # FIFO
    q.requeue_front(a)
    assert q.pop_ready() is a  # requeue preserves order
    assert q.pop_ready() is b
    assert q.poll(a.id) is a
    with pytest.raises(KeyError):
        q.poll("nope")


def test_queue_overload_is_explicit():
    q = RequestQueue(max_depth=2)
    q.submit([5, 2], 4)
    q.submit([5, 2], 4)
    with pytest.raises(OverloadError) as ei:
        q.submit([5, 2], 4)
    assert ei.value.depth == 2 and ei.value.max_depth == 2
    # No admissions yet → no wait history → the cold-start floor stands
    # in (a fleet router sheds on this number; None is not an answer).
    assert ei.value.retry_after_s == RequestQueue.DEFAULT_RETRY_AFTER_FLOOR_S
    assert "~0.050s" in str(ei.value)
    # Draining makes room again — bounded, not closed.
    q.pop_ready()
    q.submit([5, 2], 4)


def test_queue_cold_start_retry_floor_is_configurable():
    q = RequestQueue(max_depth=1, retry_after_floor_s=1.5)
    q.submit([5, 2], 4)
    with pytest.raises(OverloadError) as ei:
        q.submit([5, 2], 4)
    assert ei.value.retry_after_s == 1.5
    # None disables the floor: the old hint-less cold start.
    q2 = RequestQueue(max_depth=1, retry_after_floor_s=None)
    q2.submit([5, 2], 4)
    with pytest.raises(OverloadError) as ei:
        q2.submit([5, 2], 4)
    assert ei.value.retry_after_s is None
    assert "retry later" in str(ei.value)
    with pytest.raises(ValueError):
        RequestQueue(retry_after_floor_s=-0.1)


def test_overload_carries_retry_after_hint_from_queue_waits():
    """Once the queue has admission history, a rejection tells the caller
    HOW LONG to back off: the p50 of recent submit→admit waits."""
    t = {"now": 0.0}
    q = RequestQueue(max_depth=1, clock=lambda: t["now"])
    q.submit([5, 2], 4)
    t["now"] = 2.0  # the request waited 2s before admission
    assert q.pop_ready() is not None
    q.submit([5, 2], 4)
    with pytest.raises(OverloadError) as ei:
        q.submit([5, 2], 4)
    assert ei.value.retry_after_s == 2.0
    assert "~2.000s" in str(ei.value)
    # The engine-side metrics record the hint on reject.
    m = ServeMetrics(capacity=4)
    m.record_reject(ei.value.retry_after_s)
    snap = m.snapshot()
    assert snap["serve_retry_after_hint_s"] == 2.0
    assert snap["serve_rejected"] == 1
    assert "serve_ckpt_load_retries" in snap


def test_queue_rejects_bad_requests():
    q = RequestQueue(max_depth=2)
    with pytest.raises(ValueError):
        q.submit([], 4)
    with pytest.raises(ValueError):
        q.submit([5, 2], 0)
    with pytest.raises(ValueError):
        q.submit([5, 2], 4, beam_size=0)
    q.submit([5, 2], 4, request_id="dup")
    with pytest.raises(ValueError):
        q.submit([5, 2], 4, request_id="dup")


def test_pop_ready_can_place_keeps_fifo():
    """A non-placeable head parks the queue: pop_ready returns None
    WITHOUT popping, so a big request is never starved by smaller ones
    sneaking past it."""
    q = RequestQueue(max_depth=4)
    big = q.submit([5, 2], 8, beam_size=4)
    small = q.submit([6, 2], 8)
    assert q.pop_ready(can_place=lambda r: r.beam_size == 1) is None
    assert q.depth == 2  # nothing popped, nothing reordered
    assert q.pop_ready(can_place=lambda r: True) is big
    assert q.pop_ready(can_place=lambda r: True) is small


def test_queued_cancel_and_deadline_finalize_at_pop():
    clock = FakeClock()
    q = RequestQueue(max_depth=4, clock=clock)
    a = q.submit([5, 2], 4)
    b = q.submit([6, 2], 4, deadline_s=1.0)
    c = q.submit([7, 2], 4)
    assert q.cancel(a.id) is True
    clock.advance(2.0)  # b's deadline passes while queued
    assert q.pop_ready() is c  # a and c skipped AND finalized
    assert a.state is RequestState.CANCELLED and a.finished
    assert b.state is RequestState.EXPIRED and b.finished
    assert q.cancel(a.id) is False  # already finished


# -- metrics ----------------------------------------------------------------


def test_percentile_null_over_zero():
    assert percentile([], 50) is None
    assert percentile([3.0], 95) == 3.0
    assert percentile([1.0, 2.0, 3.0], 50) == 2.0


def test_serve_metrics_snapshot_and_emit(tmp_path):
    from deeplearning_cfn_tpu.metrics.jsonl import MetricsWriter

    clock = FakeClock()
    m = ServeMetrics(capacity=4, clock=clock)
    m.record_submit()
    m.record_admit()
    m.record_first_token(0.5)
    m.record_step(2, 3, 2, 0.1)
    m.record_finish("done", 1.5)
    snap = m.snapshot()
    assert snap["serve_submitted"] == 1 and snap["serve_completed"] == 1
    assert snap["serve_queue_depth"] == 3
    assert snap["serve_slot_occupancy"] == 0.5
    assert snap["serve_tokens_per_sec"] == pytest.approx(20.0)
    assert snap["serve_ttft_p50_s"] == 0.5
    path = str(tmp_path / "m.jsonl")
    with MetricsWriter(path, also_stdout=False) as w:
        m.emit(w, drained=True)
    rec = json.loads(open(path).read().strip())
    assert rec["drained"] is True and rec["serve_admitted"] == 1


def test_serve_metrics_empty_distributions_are_null():
    snap = ServeMetrics(capacity=2, clock=FakeClock()).snapshot()
    assert snap["serve_ttft_p50_s"] is None
    assert snap["serve_latency_p95_s"] is None
    assert snap["serve_tokens_per_sec"] is None
    assert snap["serve_slot_occupancy"] is None
    assert snap["serve_queue_wait_p50_s"] is None
    assert snap["serve_step_latency_p95_s"] is None
    assert snap["serve_steps_per_window"] is None


def test_serve_metrics_queue_wait_and_window_accounting():
    """Admission latency (submit→admit) is a first-class distribution, and
    step accounting splits decode steps from device calls (windows)."""
    m = ServeMetrics(capacity=4, clock=FakeClock())
    m.record_admit(0.2)
    m.record_admit(0.4)
    m.record_admit()  # wait unknown — counted, not distributed
    assert m.admitted == 3
    snap = m.snapshot()
    assert snap["serve_queue_wait_p50_s"] == pytest.approx(0.3)
    assert snap["serve_queue_wait_p95_s"] == pytest.approx(0.39)
    # One 4-step window on 2 rows: 8 active row-steps, 8 tokens, 0.2 s.
    m.record_step(8, 0, 8, 0.2, steps=4)
    snap = m.snapshot()
    assert snap["serve_steps"] == 4
    assert snap["serve_decode_windows"] == 1
    assert snap["serve_steps_per_window"] == 4.0
    assert snap["serve_slot_occupancy"] == pytest.approx(0.5)
    assert snap["serve_step_latency_p50_s"] == pytest.approx(0.05)
    assert snap["serve_tokens_per_sec"] == pytest.approx(40.0)


# -- KV block allocator -----------------------------------------------------


def test_block_allocator_alloc_free_reuse():
    a = BlockAllocator(num_blocks=5, block_size=4)
    assert a.usable_blocks == 4 and a.free_blocks == 4
    b1, b2 = a.alloc(), a.alloc()
    assert 0 not in (b1, b2), "null sentinel must never be handed out"
    assert a.blocks_in_use == 2 and a.is_allocated(b1)
    a.free(b1)
    assert not a.is_allocated(b1) and a.free_blocks == 3
    b3 = a.alloc()  # freed blocks return to the pool
    assert a.blocks_in_use == 2
    for b in (b2, b3):
        a.free(b)
    assert a.free_blocks == 4
    with pytest.raises(ValueError):
        a.free(b3)  # double free
    assert a.blocks_for_tokens(1) == 1
    assert a.blocks_for_tokens(4) == 1
    assert a.blocks_for_tokens(5) == 2


def test_block_allocator_refcounted_sharing():
    """Beam prefix sharing: a block freed by one row survives while a
    sibling still references it."""
    a = BlockAllocator(num_blocks=4, block_size=2)
    b = a.alloc()
    a.ref(b)
    assert a.refcount(b) == 2
    a.free(b)
    assert a.is_allocated(b), "one ref left — must stay allocated"
    a.free(b)
    assert not a.is_allocated(b)
    with pytest.raises(ValueError):
        a.ref(b)  # ref on a returned block is a bug, not a revival


def test_block_allocator_exhaustion_is_overload():
    """Pool exhaustion is backpressure, not a crash or a silent clamp:
    BlockPoolExhausted IS an OverloadError, raised by both the admission
    ledger (commit) and a bare alloc on an empty free list."""
    a = BlockAllocator(num_blocks=3, block_size=4)
    a.commit(2)
    assert not a.can_commit(1)
    with pytest.raises(BlockPoolExhausted) as ei:
        a.commit(1)
    assert isinstance(ei.value, OverloadError)
    a.uncommit(2)
    with pytest.raises(ValueError):
        a.uncommit(1)  # over-uncommit is a ledger bug
    a.alloc(), a.alloc()
    with pytest.raises(BlockPoolExhausted):
        a.alloc()


# -- encoder prefix cache ---------------------------------------------------


def test_prefix_cache_hit_miss_and_lru_eviction():
    c = PrefixCache(max_entries=2)
    assert c.get(("a",)) is None and c.misses == 1
    assert c.put(("a",), 1) == 0
    assert c.put(("b",), 2) == 0
    assert c.get(("a",)) == 1 and c.hits == 1  # refreshes "a"
    assert c.put(("c",), 3) == 1  # evicts "b", the least recent
    assert ("b",) not in c and ("a",) in c and ("c",) in c
    assert c.evictions == 1
    assert c.get(("b",)) is None
    assert c.hit_rate == pytest.approx(1 / 3)
    with pytest.raises(ValueError):
        PrefixCache(0)


def test_unpadded_key_strips_trailing_pad_only():
    """The encoder LRU key ignores pad WIDTH, not pad POSITION: the same
    sentence padded to any width maps to one key, while interior pads
    (a different sentence) stay significant."""
    from deeplearning_cfn_tpu.serve.prefix import unpadded_key

    pad = decoding.PAD_ID
    base = [5, 9, 2]
    keys = {unpadded_key(base + [pad] * w, pad) for w in (0, 1, 3)}
    assert keys == {(5, 9, 2)}
    assert unpadded_key([5, pad, 2], pad) == (5, pad, 2)
    assert unpadded_key([pad, pad], pad) == ()


# -- engine: shared tiny model ----------------------------------------------

SCHED_VOCAB = 64
SCHED_SRC_LEN = 8


@pytest.fixture(scope="module")
def sched_model():
    model = transformer_nmt_tiny(vocab_size=SCHED_VOCAB, hidden_size=32,
                                 num_layers=1, num_heads=2, mlp_dim=64,
                                 max_len=32)
    variables = model.init(
        jax.random.PRNGKey(0), np.zeros((1, SCHED_SRC_LEN), np.int32),
        np.ones((1, SCHED_SRC_LEN), np.int32),
        np.zeros((1, SCHED_SRC_LEN), np.int32), train=False)
    return model, {"params": variables["params"]}


def _mk_engine(sched_model, clock=None, **kw):
    model, variables = sched_model
    kw.setdefault("capacity", 2)
    kw.setdefault("max_src_len", SCHED_SRC_LEN)
    if clock is not None:
        kw["clock"] = clock
    return Engine(model, variables, **kw)


def _src(seed, n=5):
    rng = np.random.RandomState(seed)
    return [int(t) for t in rng.randint(3, SCHED_VOCAB, size=n - 1)] + \
        [decoding.EOS_ID]


# -- engine: scheduler invariants -------------------------------------------


def test_slot_exclusivity_under_churn(sched_model):
    """No row ever serves two requests, across a run with constant slot
    turnover (mixed budgets, more requests than capacity)."""
    eng = _mk_engine(sched_model, capacity=3, queue_depth=32)
    reqs = [eng.submit(_src(i), max_new_tokens=2 + i % 4)
            for i in range(10)]
    steps = 0
    while eng.queue.depth > 0 or eng.active_requests:
        eng.step()
        steps += 1
        owners = eng.slot_view()
        running = {g.req.id: g.rows for g in eng._groups}
        # Every owned row belongs to exactly the group that claims it.
        claimed = [r for rows in running.values() for r in rows]
        assert len(claimed) == len(set(claimed)), "row in two groups"
        for rid, rows in running.items():
            assert all(owners[r] == rid for r in rows)
        for r, owner in enumerate(owners):
            assert owner is None or r in running[owner]
        assert steps < 200
    assert all(eng.poll(r.id).state is RequestState.DONE for r in reqs)


def test_admission_is_fifo_and_only_into_free_rows(sched_model):
    """A beam group that doesn't fit blocks later requests (no sneak-in),
    and admission happens strictly into free rows."""
    eng = _mk_engine(sched_model, capacity=2, queue_depth=8)
    big = eng.submit(_src(1), max_new_tokens=4, beam_size=2)
    small = eng.submit(_src(2), max_new_tokens=2)
    eng.step()
    # The beam group took both rows; small must wait (FIFO would be
    # violated if it half-admitted or jumped ahead of a later submit).
    assert eng.poll(big.id).state is RequestState.RUNNING
    assert eng.poll(small.id).state is RequestState.QUEUED
    assert eng.active_rows == 2
    eng.run_until_drained()
    assert eng.poll(big.id).state is RequestState.DONE
    assert eng.poll(small.id).state is RequestState.DONE


def test_overload_rejection_at_engine_submit(sched_model):
    eng = _mk_engine(sched_model, queue_depth=2)
    eng.submit(_src(1), max_new_tokens=2)
    eng.submit(_src(2), max_new_tokens=2)
    with pytest.raises(OverloadError):
        eng.submit(_src(3), max_new_tokens=2)
    assert eng.metrics.rejected == 1
    eng.run_until_drained()


def test_engine_rejects_unplaceable_requests(sched_model):
    eng = _mk_engine(sched_model, capacity=2)
    with pytest.raises(ValueError):
        eng.submit(_src(1), beam_size=3)  # wider than the slot table
    with pytest.raises(ValueError):
        eng.submit([5] * (SCHED_SRC_LEN + 1), max_new_tokens=2)


def test_cancel_frees_slot_within_one_step(sched_model):
    clock = FakeClock()
    eng = _mk_engine(sched_model, clock=clock, capacity=1)
    a = eng.submit(_src(1), max_new_tokens=30)
    eng.step()
    assert eng.poll(a.id).state is RequestState.RUNNING
    b = eng.submit(_src(2), max_new_tokens=2)
    assert eng.cancel(a.id) is True
    eng.step()  # reap a, admit b, decode — one step
    assert eng.poll(a.id).state is RequestState.CANCELLED
    assert eng.poll(b.id).state is RequestState.RUNNING
    assert eng.slot_view() == [b.id]
    assert eng.poll(a.id).tokens, "partial output is kept"
    eng.run_until_drained()
    assert eng.poll(b.id).state is RequestState.DONE


def test_deadline_expires_running_request_within_one_step(sched_model):
    clock = FakeClock()
    eng = _mk_engine(sched_model, clock=clock, capacity=1)
    a = eng.submit(_src(1), max_new_tokens=30, deadline_s=5.0)
    eng.step()
    assert eng.poll(a.id).state is RequestState.RUNNING
    clock.advance(10.0)
    b = eng.submit(_src(2), max_new_tokens=2)
    eng.step()
    assert eng.poll(a.id).state is RequestState.EXPIRED
    assert eng.slot_view() == [b.id]
    assert eng.metrics.expired == 1
    eng.run_until_drained()


def test_rows_recycle_without_stalling_neighbours(sched_model):
    """A short request finishing must not disturb a long in-flight one:
    the long request's output equals its solo-run output."""
    eng_solo = _mk_engine(sched_model, capacity=2)
    long_solo = eng_solo.submit(_src(7), max_new_tokens=12)
    eng_solo.run_until_drained()

    eng = _mk_engine(sched_model, capacity=2, queue_depth=16)
    long_req = eng.submit(_src(7), max_new_tokens=12)
    shorts = [eng.submit(_src(20 + i), max_new_tokens=2) for i in range(4)]
    eng.run_until_drained()
    assert eng.poll(long_req.id).tokens == eng_solo.poll(long_solo.id).tokens
    assert all(eng.poll(s.id).state is RequestState.DONE for s in shorts)


# -- engine: parity with models/decoding.py over the sliver fixtures --------

PARITY_SRC_LEN = 20
PARITY_NEW_TOKENS = 12


@pytest.fixture(scope="module")
def sliver_bpe():
    lines = _sliver_lines("de") + _sliver_lines("en")
    return train_bpe(lines, vocab_size=300, specials=NMT_SPECIALS)


@pytest.fixture(scope="module")
def parity_setup(sliver_bpe):
    model = transformer_nmt_tiny(vocab_size=sliver_bpe.vocab_size,
                                 hidden_size=32, num_layers=1, num_heads=2,
                                 mlp_dim=64, max_len=32)
    variables = model.init(
        jax.random.PRNGKey(1), np.zeros((1, PARITY_SRC_LEN), np.int32),
        np.ones((1, PARITY_SRC_LEN), np.int32),
        np.zeros((1, PARITY_SRC_LEN), np.int32), train=False)
    variables = {"params": variables["params"]}
    # Real sliver sentences → BPE ids (+EOS), truncated to the serving
    # source length, data/text.py's source framing.
    srcs = []
    for line in _sliver_lines("de")[:6]:
        ids = sliver_bpe.encode(line)[:PARITY_SRC_LEN - 1]
        srcs.append(ids + [decoding.EOS_ID])
    return model, variables, srcs


def _direct_decode(model, variables, src_ids, beam_size):
    src = np.zeros((1, PARITY_SRC_LEN), np.int32)
    src[0, :len(src_ids)] = src_ids
    mask = (src != decoding.PAD_ID).astype(np.int32)
    if beam_size == 1:
        out = decoding.greedy_decode_cached(model, variables, src, mask,
                                            PARITY_NEW_TOKENS)
        return decoding.strip_special(np.asarray(out[0]))
    out, _ = decoding.beam_decode_cached(model, variables, src, mask,
                                         PARITY_NEW_TOKENS,
                                         beam_size=beam_size)
    return decoding.strip_special(np.asarray(out[0]))


def test_greedy_parity_with_offline_decoder(parity_setup):
    """Engine output is token-identical to greedy_decode_cached for every
    sliver sentence, despite slot churn (capacity < request count)."""
    model, variables, srcs = parity_setup
    direct = [_direct_decode(model, variables, s, 1) for s in srcs]
    eng = Engine(model, variables, capacity=2, max_src_len=PARITY_SRC_LEN,
                 default_max_new_tokens=PARITY_NEW_TOKENS)
    reqs = [eng.submit(s) for s in srcs]
    eng.run_until_drained()
    got = [decoding.strip_special(eng.poll(r.id).tokens) for r in reqs]
    assert got == direct


def test_beam_parity_with_offline_decoder(parity_setup):
    """Beam groups (2 rows/request) reproduce beam_decode_cached exactly,
    including the GNMT length-norm final pick and cache-row reordering."""
    model, variables, srcs = parity_setup
    direct = [_direct_decode(model, variables, s, 2) for s in srcs]
    eng = Engine(model, variables, capacity=4, max_src_len=PARITY_SRC_LEN,
                 default_max_new_tokens=PARITY_NEW_TOKENS)
    reqs = [eng.submit(s, beam_size=2) for s in srcs]
    eng.run_until_drained()
    got = [decoding.strip_special(eng.poll(r.id).tokens) for r in reqs]
    assert got == direct


def test_mixed_greedy_and_beam_parity(parity_setup):
    """Greedy and beam requests sharing the slot table stay parity-exact —
    the modes must not interfere through the shared cache."""
    model, variables, srcs = parity_setup
    eng = Engine(model, variables, capacity=3, max_src_len=PARITY_SRC_LEN,
                 default_max_new_tokens=PARITY_NEW_TOKENS)
    reqs = [eng.submit(s, beam_size=1 + (i % 2))
            for i, s in enumerate(srcs)]
    eng.run_until_drained()
    for i, (r, s) in enumerate(zip(reqs, srcs)):
        want = _direct_decode(model, variables, s, 1 + (i % 2))
        assert decoding.strip_special(eng.poll(r.id).tokens) == want


# -- engine: device-resident fast path (fused steps, windows, donation) -----


@pytest.mark.parametrize("window", [1, 4, PARITY_NEW_TOKENS + 20])
def test_windowed_greedy_parity(parity_setup, window):
    """The fused/windowed greedy path is token-identical to
    greedy_decode_cached for window sizes 1, 4, and > the decode budget —
    windows are a dispatch optimization, never a search change."""
    model, variables, srcs = parity_setup
    direct = [_direct_decode(model, variables, s, 1) for s in srcs]
    eng = Engine(model, variables, capacity=2, max_src_len=PARITY_SRC_LEN,
                 default_max_new_tokens=PARITY_NEW_TOKENS,
                 decode_window=window)
    reqs = [eng.submit(s) for s in srcs]
    eng.run_until_drained()
    got = [decoding.strip_special(eng.poll(r.id).tokens) for r in reqs]
    assert got == direct


def test_windowed_engine_keeps_beam_parity(parity_setup):
    """A windowed engine drops to the single-step logits path for beam
    groups — beam output is unchanged by the decode_window knob."""
    model, variables, srcs = parity_setup
    direct = [_direct_decode(model, variables, s, 2) for s in srcs]
    eng = Engine(model, variables, capacity=4, max_src_len=PARITY_SRC_LEN,
                 default_max_new_tokens=PARITY_NEW_TOKENS, decode_window=8)
    reqs = [eng.submit(s, beam_size=2) for s in srcs]
    eng.run_until_drained()
    got = [decoding.strip_special(eng.poll(r.id).tokens) for r in reqs]
    assert got == direct


def test_greedy_path_never_materializes_logits(sched_model):
    """The acceptance contract: greedy traffic must not ship the
    [capacity, V] logits matrix to the host per token. The logits-returning
    step is reserved for beam rows, so on all-greedy traffic it is never
    invoked — whatever the window size."""
    for window in (1, 4):
        eng = _mk_engine(sched_model, capacity=2, queue_depth=16,
                         decode_window=window)

        def _boom(*a, **k):
            raise AssertionError(
                "logits step ran on an all-greedy trace")

        eng._step_fn = _boom
        reqs = [eng.submit(_src(i), max_new_tokens=3) for i in range(5)]
        eng.run_until_drained()
        assert all(eng.poll(r.id).state is RequestState.DONE for r in reqs)


def test_cache_is_donated_into_the_step(sched_model):
    """The KV cache is donated into every decode call: after a step, the
    previous cache buffers are consumed (updated in place), not left as a
    live full-size copy."""
    eng = _mk_engine(sched_model, capacity=2, decode_window=2)
    eng.submit(_src(1), max_new_tokens=6)
    eng.step()
    stale = jax.tree_util.tree_leaves(eng.cache)
    eng.step()
    fresh = jax.tree_util.tree_leaves(eng.cache)
    assert any(l.is_deleted() for l in stale if getattr(l, "ndim", 0) >= 4)
    # The engine itself never holds a deleted buffer: stepping twice more
    # works and the live cache is fully readable.
    eng.run_until_drained()
    assert all(not l.is_deleted() for l in
               jax.tree_util.tree_leaves(eng.cache))
    del fresh


def test_budget_clamps_below_cache_size_and_terminates(sched_model):
    """A request asking for more tokens than the KV cache holds is clamped
    to max_len - 1 at submit and terminates at cache exhaustion — it never
    silently re-writes the last cache slot forever."""
    model, _ = sched_model
    eng = _mk_engine(sched_model, capacity=1, decode_window=4)
    req = eng.submit(_src(1), max_new_tokens=10**6)
    assert req.max_new_tokens == model.max_len - 1
    ticks = eng.run_until_drained(max_steps=5 * model.max_len)
    assert eng.poll(req.id).state is RequestState.DONE
    assert len(req.tokens) <= model.max_len - 1
    assert ticks < 5 * model.max_len  # drained, not max_steps-capped


def test_cancel_eviction_lands_within_one_window(sched_model):
    clock = FakeClock()
    eng = _mk_engine(sched_model, clock=clock, capacity=1, decode_window=4)
    a = eng.submit(_src(1), max_new_tokens=30)
    eng.step()
    assert eng.poll(a.id).state is RequestState.RUNNING
    assert eng.cancel(a.id) is True
    eng.step()  # the very next window reaps it
    assert eng.poll(a.id).state is RequestState.CANCELLED
    assert eng.slot_view() == [None]
    assert eng.poll(a.id).tokens, "partial output is kept"


def test_deadline_eviction_lands_within_one_window(sched_model):
    """A running deadline forces the scheduler to window size 1, so expiry
    is detected within one step — a large decode_window must not defer it."""
    clock = FakeClock()
    eng = _mk_engine(sched_model, clock=clock, capacity=1, decode_window=8)
    a = eng.submit(_src(1), max_new_tokens=30, deadline_s=5.0)
    eng.step()
    n_before = len(eng.poll(a.id).tokens)
    assert eng._plan_window() == 1  # deadline pending → per-step ticks
    clock.advance(10.0)
    eng.step()
    assert eng.poll(a.id).state is RequestState.EXPIRED
    # The expiring tick reaped before decoding: no token generated past
    # the deadline.
    assert len(eng.poll(a.id).tokens) == n_before


def test_windowed_slot_churn_keeps_invariants(sched_model):
    """The slot-exclusivity and parity-of-neighbours guarantees survive
    multi-step windows under constant turnover."""
    eng_solo = _mk_engine(sched_model, capacity=2, decode_window=4)
    long_solo = eng_solo.submit(_src(7), max_new_tokens=12)
    eng_solo.run_until_drained()

    eng = _mk_engine(sched_model, capacity=3, queue_depth=32,
                     decode_window=4)
    long_req = eng.submit(_src(7), max_new_tokens=12)
    shorts = [eng.submit(_src(20 + i), max_new_tokens=2 + i % 3)
              for i in range(8)]
    steps = 0
    while eng.queue.depth > 0 or eng.active_requests:
        eng.step()
        steps += 1
        owners = eng.slot_view()
        running = {g.req.id: g.rows for g in eng._groups}
        claimed = [r for rows in running.values() for r in rows]
        assert len(claimed) == len(set(claimed)), "row in two groups"
        for rid, rows in running.items():
            assert all(owners[r] == rid for r in rows)
        assert steps < 200
    assert eng.poll(long_req.id).tokens == \
        eng_solo.poll(long_solo.id).tokens
    assert all(eng.poll(s.id).state is RequestState.DONE for s in shorts)


# -- engine: paged KV cache -------------------------------------------------


def test_engine_submit_rejects_empty_src(sched_model):
    eng = _mk_engine(sched_model)
    with pytest.raises(ValueError):
        eng.submit([], max_new_tokens=2)


def test_paged_engine_validates_block_size(sched_model):
    with pytest.raises(ValueError):
        _mk_engine(sched_model, kv_block_size=5)  # 5 does not divide 32


def test_paged_submit_rejects_never_placeable(sched_model):
    """A request whose worst-case block need exceeds the whole pool is
    rejected at submit — it could never be admitted."""
    eng = _mk_engine(sched_model, kv_block_size=4, kv_blocks=3)
    with pytest.raises(ValueError):
        eng.submit(_src(1), max_new_tokens=12)  # 3 blocks > 2 usable


@pytest.mark.parametrize("window", [1, 4])
def test_paged_greedy_parity(parity_setup, window):
    """Paged attention is a memory-layout change, token-identical to the
    dense slot engine AND the offline greedy searcher at every window."""
    model, variables, srcs = parity_setup
    direct = [_direct_decode(model, variables, s, 1) for s in srcs]
    dense = Engine(model, variables, capacity=2,
                   max_src_len=PARITY_SRC_LEN,
                   default_max_new_tokens=PARITY_NEW_TOKENS,
                   decode_window=window)
    paged = Engine(model, variables, capacity=2,
                   max_src_len=PARITY_SRC_LEN,
                   default_max_new_tokens=PARITY_NEW_TOKENS,
                   decode_window=window, kv_block_size=4)
    outs = []
    for eng in (dense, paged):
        reqs = [eng.submit(s) for s in srcs]
        eng.run_until_drained()
        outs.append([decoding.strip_special(eng.poll(r.id).tokens)
                     for r in reqs])
    assert outs[0] == direct
    assert outs[1] == direct


@pytest.mark.parametrize("window", [1, 4])
def test_paged_beam_parity(parity_setup, window):
    """Beam groups on the paged cache — copy-on-write block forks instead
    of whole-row permutation — reproduce beam_decode_cached exactly."""
    model, variables, srcs = parity_setup
    direct = [_direct_decode(model, variables, s, 2) for s in srcs]
    eng = Engine(model, variables, capacity=4, max_src_len=PARITY_SRC_LEN,
                 default_max_new_tokens=PARITY_NEW_TOKENS,
                 decode_window=window, kv_block_size=4)
    reqs = [eng.submit(s, beam_size=2) for s in srcs]
    eng.run_until_drained()
    got = [decoding.strip_special(eng.poll(r.id).tokens) for r in reqs]
    assert got == direct


def test_paged_mixed_traffic_parity(parity_setup):
    model, variables, srcs = parity_setup
    eng = Engine(model, variables, capacity=3, max_src_len=PARITY_SRC_LEN,
                 default_max_new_tokens=PARITY_NEW_TOKENS,
                 decode_window=4, kv_block_size=8, prefix_cache_size=4)
    reqs = [eng.submit(s, beam_size=1 + (i % 2))
            for i, s in enumerate(srcs)]
    eng.run_until_drained()
    for i, (r, s) in enumerate(zip(reqs, srcs)):
        want = _direct_decode(model, variables, s, 1 + (i % 2))
        assert decoding.strip_special(eng.poll(r.id).tokens) == want


def test_paged_greedy_path_never_materializes_logits(sched_model):
    """The paged fast path keeps the no-logits contract: all-greedy
    traffic never invokes the logits-returning step."""
    for window in (1, 4):
        eng = _mk_engine(sched_model, capacity=2, queue_depth=16,
                         decode_window=window, kv_block_size=4)

        def _boom(*a, **k):
            raise AssertionError("logits step ran on an all-greedy trace")

        eng._step_fn = _boom
        reqs = [eng.submit(_src(i), max_new_tokens=3) for i in range(5)]
        eng.run_until_drained()
        assert all(eng.poll(r.id).state is RequestState.DONE for r in reqs)


def test_paged_cache_is_donated_into_the_step(sched_model):
    """Donation survives paging: the block pool is consumed by each decode
    call, not copied beside itself."""
    eng = _mk_engine(sched_model, capacity=2, decode_window=2,
                     kv_block_size=4)
    eng.submit(_src(1), max_new_tokens=6)
    eng.step()
    stale = jax.tree_util.tree_leaves(eng.cache)
    eng.step()
    assert any(l.is_deleted() for l in stale if getattr(l, "ndim", 0) >= 4)
    eng.run_until_drained()
    assert all(not l.is_deleted() for l in
               jax.tree_util.tree_leaves(eng.cache))


def test_paged_block_accounting_under_churn(sched_model):
    """Allocator/table invariants across constant turnover with mixed
    greedy+beam traffic: every nonzero table entry is a live block, a
    greedy row's blocks are exclusively its own, and a drained engine
    returns every block and every commitment."""
    eng = _mk_engine(sched_model, capacity=3, queue_depth=32,
                     decode_window=4, kv_block_size=4)
    reqs = [eng.submit(_src(i), max_new_tokens=2 + i % 5,
                       beam_size=1 + (i % 3 == 0))
            for i in range(10)]
    steps = 0
    while eng.queue.depth > 0 or eng.active_requests:
        eng.step()
        steps += 1
        alloc = eng.allocator
        for g in eng._groups:
            for r in g.rows:
                bound = eng._blocks_bound[r]
                table = eng._block_tables[r]
                assert list(table[:len(bound)]) == bound
                assert (table[len(bound):] == 0).all()
                for b in bound:
                    assert alloc.is_allocated(b), "row reads a freed block"
                if g.req.beam_size == 1:
                    assert all(alloc.refcount(b) == 1 for b in bound)
        assert alloc.blocks_in_use <= alloc.usable_blocks
        assert alloc.committed_blocks <= alloc.usable_blocks
        assert steps < 300
    assert all(eng.poll(r.id).state is RequestState.DONE for r in reqs)
    assert eng.allocator.blocks_in_use == 0
    assert eng.allocator.committed_blocks == 0


def test_paged_token_budget_admission_defers_not_clamps(sched_model):
    """When the pool cannot cover a request's token budget, the request
    WAITS (and runs with its full budget later) — admission control, never
    a silent budget clamp."""
    eng = _mk_engine(sched_model, capacity=2, kv_block_size=4, kv_blocks=3)
    a = eng.submit(_src(1), max_new_tokens=8)  # 2 blocks = whole pool
    b = eng.submit(_src(2), max_new_tokens=8)
    eng.step()
    assert eng.poll(a.id).state is RequestState.RUNNING
    assert eng.poll(b.id).state is RequestState.QUEUED, \
        "pool is fully committed — b must wait despite a free row"
    eng.run_until_drained()
    assert eng.poll(a.id).state is RequestState.DONE
    assert eng.poll(b.id).state is RequestState.DONE
    # Full-budget outputs, identical to an engine with a roomy pool — the
    # tight pool delayed b, it did not shrink it.
    roomy = _mk_engine(sched_model, capacity=2, kv_block_size=4)
    ra = roomy.submit(_src(1), max_new_tokens=8)
    rb = roomy.submit(_src(2), max_new_tokens=8)
    roomy.run_until_drained()
    assert eng.poll(a.id).tokens == roomy.poll(ra.id).tokens
    assert eng.poll(b.id).tokens == roomy.poll(rb.id).tokens


def test_paged_coresidency_beats_dense_at_equal_memory(sched_model):
    """The headline win: at the SAME KV memory (dense capacity x max_len
    = pool blocks x block size), short-budget traffic co-resides >= 1.5x
    more requests on the paged engine."""
    model, _ = sched_model

    def peak_coresident(**kw):
        eng = _mk_engine(sched_model, queue_depth=64, **kw)
        for i in range(12):
            eng.submit(_src(30 + i), max_new_tokens=3)
        peak, steps = 0, 0
        while eng.queue.depth > 0 or eng.active_requests:
            eng.step()
            peak = max(peak, eng.active_requests)
            steps += 1
            assert steps < 300
        return peak

    dense_peak = peak_coresident(capacity=4)
    # Equal KV memory: 4 rows x 32 positions = 128 positions = 32 blocks
    # of 4 (+1 null). The paged engine spends it on 8 slim rows instead.
    paged_peak = peak_coresident(capacity=8, kv_block_size=4, kv_blocks=33)
    assert dense_peak <= 4
    assert paged_peak >= 1.5 * dense_peak


def test_paged_prefix_cache_reuses_encoder_outputs(sched_model):
    """Repeated sources hit the prefix cache (fewer logical encodes than
    admissions) and hit requests decode the exact same tokens as a cold
    engine."""
    cold = _mk_engine(sched_model, capacity=2, queue_depth=16)
    eng = _mk_engine(sched_model, capacity=2, queue_depth=16,
                     kv_block_size=4, prefix_cache_size=8)
    srcs = [_src(1), _src(2), _src(1), _src(2), _src(1)]
    outs = {}
    for e in (cold, eng):
        reqs = [e.submit(s, max_new_tokens=4) for s in srcs]
        e.run_until_drained()
        outs[e] = [e.poll(r.id).tokens for r in reqs]
    assert outs[cold] == outs[eng]
    assert eng.metrics.prefix_hits >= 2
    assert eng.encoder_invocations < eng.metrics.admitted
    assert eng.metrics.prefix_hit_rate > 0
    snap = eng.metrics.snapshot()
    assert snap["serve_prefix_hits"] == eng.metrics.prefix_hits
    assert snap["serve_kv_blocks_total"] == eng.allocator.usable_blocks


def test_prefix_cache_hits_across_pad_widths(sched_model):
    """One sentence submitted at two pad widths is ONE cache entry: the
    LRU is keyed on the unpadded token tuple, so client-side padding
    differences can't split (and silently cold-miss) the cache."""
    eng = _mk_engine(sched_model, capacity=1, queue_depth=16,
                     prefix_cache_size=8)
    s = _src(1, n=5)
    for padded in (s, s + [decoding.PAD_ID], s + [decoding.PAD_ID] * 3):
        eng.submit(padded, max_new_tokens=4)
        eng.run_until_drained()
    assert eng.metrics.prefix_hits == 2
    assert eng.metrics.prefix_misses == 1
    assert eng._prefix.hits == 2 and len(eng._prefix) == 1


def test_prefix_cache_eviction_keeps_correctness(sched_model):
    """A 1-entry cache under alternating sources evicts constantly and
    must still be output-identical to the uncached engine."""
    cold = _mk_engine(sched_model, capacity=1, queue_depth=16)
    eng = _mk_engine(sched_model, capacity=1, queue_depth=16,
                     prefix_cache_size=1)
    srcs = [_src(1), _src(2), _src(1), _src(2)]
    outs = {}
    for e in (cold, eng):
        reqs = [e.submit(s, max_new_tokens=4) for s in srcs]
        e.run_until_drained()
        outs[e] = [e.poll(r.id).tokens for r in reqs]
    assert outs[cold] == outs[eng]
    assert eng.metrics.snapshot()["serve_prefix_evictions"] >= 1


def test_fused_window_records_active_row_steps(sched_model):
    """record_step's occupancy numerator is row-steps of real decode work
    (each row counted until it finished), derived from the window's done
    mask — not rows x window and not a token-count stand-in."""
    eng = _mk_engine(sched_model, capacity=4, decode_window=4)
    calls = []
    real = eng.metrics.record_step

    def spy(active_rows, queue_depth, new_tokens, dt, **kw):
        calls.append((active_rows, new_tokens, kw.get("steps", 1)))
        return real(active_rows, queue_depth, new_tokens, dt, **kw)

    eng.metrics.record_step = spy
    reqs = [eng.submit(_src(i), max_new_tokens=2) for i in range(2)]
    eng.step()
    assert all(eng.poll(r.id).state is RequestState.DONE for r in reqs)
    (active_row_steps, new_tokens, steps), = calls
    assert steps == 4
    # 2 rows, each active for exactly its 2-token budget inside the
    # 4-step window: 4 row-steps, NOT 2 rows x 4 steps = 8.
    assert active_row_steps == 4
    assert new_tokens == 4
    # Occupancy: 4 row-steps over a 4-step window on 4 slots = 0.25.
    assert eng.metrics.mean_slot_occupancy == pytest.approx(0.25)


def test_serve_metrics_paged_keys_are_conditional():
    """An unconfigured ServeMetrics snapshot has NO paged/prefix keys (the
    pinned obs contract); configuring the surfaces adds them."""
    base = ServeMetrics(capacity=2, clock=FakeClock())
    snap = base.snapshot()
    assert not any(k.startswith(("serve_kv_", "serve_prefix_",
                                 "serve_radix_"))
                   for k in snap)
    m = ServeMetrics(capacity=2, clock=FakeClock())
    m.configure_kv_pool(usable_blocks=8, block_size=4)
    m.configure_prefix_cache(max_entries=16)
    m.record_prefix(True)
    m.record_prefix(False)
    m.record_step(2, 0, 2, 0.1, kv_blocks_in_use=4)
    snap = m.snapshot()
    assert snap["serve_kv_blocks_total"] == 8
    assert snap["serve_kv_block_size"] == 4
    assert snap["serve_kv_blocks_in_use"] == 4
    assert snap["serve_kv_block_utilization"] == pytest.approx(0.5)
    assert snap["serve_prefix_cache_size"] == 16
    assert snap["serve_prefix_hits"] == 1
    assert snap["serve_prefix_misses"] == 1
    assert snap["serve_prefix_hit_rate"] == pytest.approx(0.5)
    assert snap["serve_prefix_evictions"] == 0


# -- CLI + bench ------------------------------------------------------------

CLI_OVERRIDES = [
    "model.kwargs.hidden_size=32", "model.kwargs.num_layers=1",
    "model.kwargs.num_heads=2", "model.kwargs.mlp_dim=64",
    "model.kwargs.max_len=64", "data.seq_len=48",
]


def test_cli_serve_offline_driver(tmp_path, capsys, sliver_bpe):
    """End-to-end `dlcfn-tpu serve`: restore a committed checkpoint, drive
    a JSONL trace (text + src_ids requests), emit serve_* metrics."""
    from deeplearning_cfn_tpu.cli.main import main
    from deeplearning_cfn_tpu.ckpt import CheckpointManager
    from deeplearning_cfn_tpu.config import apply_overrides
    from deeplearning_cfn_tpu.presets import get_preset
    from deeplearning_cfn_tpu.train.run import _workdir_and_ckpt_dir
    from deeplearning_cfn_tpu.train.task import build_task

    overrides = CLI_OVERRIDES + [
        f"model.kwargs.vocab_size={sliver_bpe.vocab_size}",
        f"workdir={tmp_path}",
    ]
    cfg = apply_overrides(get_preset("transformer_nmt_wmt"), overrides)
    task = build_task(cfg)
    variables = task.init(jax.random.PRNGKey(3))
    _, ckpt_dir = _workdir_and_ckpt_dir(cfg)
    CheckpointManager(ckpt_dir, async_write=False).save(
        7, {"params": variables["params"]}, force=True)

    vocab_path = str(tmp_path / "vocab.json")
    sliver_bpe.save(vocab_path)
    reqs_path = str(tmp_path / "reqs.jsonl")
    sentence = _sliver_lines("de")[0]
    with open(reqs_path, "w") as fh:
        fh.write(json.dumps({"text": sentence, "id": "txt",
                             "max_new_tokens": 4}) + "\n")
        fh.write(json.dumps({"src_ids": [5, 9, 2], "id": "raw",
                             "beam_size": 2}) + "\n")
        # Unplaceable (source longer than data.seq_len): rejected with a
        # diagnostic, must not sink the rest of the trace.
        fh.write(json.dumps({"src_ids": [5] * 60, "id": "toolong"}) + "\n")
    metrics_path = str(tmp_path / "serve.jsonl")
    rc = main(["serve", "--preset", "transformer_nmt_wmt",
               "--accelerator", "cpu", "--requests", reqs_path,
               "--slots", "2", "--max-new-tokens", "4", "--vocab",
               vocab_path, "--metrics-path", metrics_path, *overrides])
    captured = capsys.readouterr()
    assert rc == 0
    results = {r["id"]: r
               for r in map(json.loads, captured.out.strip().splitlines())}
    assert results["txt"]["state"] == "done"
    assert results["raw"]["state"] == "done"
    assert "toolong" not in results
    assert "line 3 rejected" in captured.err
    assert "text" in results["txt"]  # BPE-decoded output
    assert results["txt"]["ttft_s"] is not None
    # The drained metrics record carries the headline serving signals.
    records = [json.loads(ln) for ln in open(metrics_path)]
    final = records[-1]
    assert final["drained"] is True
    for key in ("serve_queue_depth", "serve_ttft_p50_s",
                "serve_tokens_per_sec", "serve_slot_occupancy"):
        assert key in final
    assert final["serve_completed"] == 2


def test_cli_serve_requires_checkpoint_unless_allow_init(tmp_path, capsys):
    from deeplearning_cfn_tpu.cli.main import main

    args = ["serve", "--preset", "transformer_nmt_wmt", "--accelerator",
            "cpu", "--requests", str(tmp_path / "nope.jsonl"),
            *CLI_OVERRIDES, "model.kwargs.vocab_size=64",
            f"workdir={tmp_path}"]
    assert main(args) == 1  # no checkpoint, no --allow-init
    capsys.readouterr()


def test_cli_bench_serve_flag_exclusive(capsys):
    from deeplearning_cfn_tpu.cli.main import main

    assert main(["bench", "--serve", "--collectives"]) == 2
    assert main(["bench", "--smoke"]) == 2  # smoke is a --serve mode


def test_serve_bench_record_contract():
    """The serving scenario emits the BENCH_* schema shape with real
    latency percentiles and the diagnostics the perf trajectory needs to
    attribute wins (decode window, per-step decode latency)."""
    from deeplearning_cfn_tpu.serve.bench import run_serve_bench

    rec = run_serve_bench(num_requests=4, slots=2, max_new_tokens=4,
                          src_len=8)
    assert {"metric", "value", "unit", "vs_baseline", "mfu",
            "measured"} <= set(rec)
    assert rec["measured"] is True
    assert rec["unit"] == "tokens/sec"
    assert rec["value"] is not None and rec["value"] > 0
    assert rec["p50_latency_s"] is not None
    assert rec["ttft_p95_s"] is not None
    assert rec["engine_steps"] > 0
    assert rec["decode_window"] >= 1
    assert rec["decode_steps"] > 0
    assert rec["step_latency_p50_s"] is not None
    assert rec["step_latency_p95_s"] is not None
    assert rec["queue_wait_p50_s"] is not None
    # Paged-cache + prefix diagnostics joined the record contract.
    assert rec["kv_block_size"] == 16
    assert rec["kv_blocks"] > 0
    assert rec["kv_block_utilization"] is not None
    assert rec["encoder_invocations"] > 0
    assert rec["admitted"] > 0


def test_serve_bench_prefix_dup_exercises_the_cache():
    """`--prefix-dup 0.5`-style traces must show real prefix reuse: a
    positive hit rate and fewer logical encoder invocations than
    admissions."""
    from deeplearning_cfn_tpu.serve.bench import run_serve_bench

    rec = run_serve_bench(num_requests=8, slots=2, max_new_tokens=4,
                          src_len=8, prefix_dup=0.6)
    assert rec["prefix_dup"] == 0.6
    assert rec["prefix_hit_rate"] is not None
    assert rec["prefix_hit_rate"] > 0
    assert rec["encoder_invocations"] < rec["admitted"]


def test_cli_bench_serve_smoke_emits_contract_record(capsys):
    """`bench --serve --smoke` is the CI fast mode: it must always emit a
    valid BENCH-contract record, so the serving bench cannot silently rot."""
    from deeplearning_cfn_tpu.cli.main import main

    assert main(["bench", "--serve", "--smoke"]) == 0
    out = capsys.readouterr().out.strip()
    rec = json.loads(out.splitlines()[-1])
    assert {"metric", "value", "unit", "vs_baseline", "mfu",
            "measured"} <= set(rec)
    assert rec["metric"] == "serve_tiny_nmt_tokens_per_sec"
    assert rec["measured"] is True
    assert rec["smoke"] is True
    assert rec["value"] is not None and rec["value"] > 0
    assert rec["decode_window"] >= 1
    assert rec["step_latency_p50_s"] is not None


# -- speculative decoding ---------------------------------------------------


@pytest.mark.parametrize("paged", [False, True])
@pytest.mark.parametrize("window", [1, 4])
@pytest.mark.parametrize("gamma", [1, 2, 4])
def test_speculative_greedy_parity(parity_setup, gamma, window, paged):
    """Speculative greedy is token-identical to greedy_decode_cached for
    every sliver sentence, across draft depths, decode-window settings,
    and both cache layouts — speculation is a scheduling optimization,
    never a search change. Self-draft, so acceptance is total and
    tokens-per-target-step is the γ+1 upper bound."""
    model, variables, srcs = parity_setup
    direct = [_direct_decode(model, variables, s, 1) for s in srcs]
    eng = Engine(model, variables, capacity=2, max_src_len=PARITY_SRC_LEN,
                 default_max_new_tokens=PARITY_NEW_TOKENS,
                 decode_window=window, speculate_gamma=gamma,
                 kv_block_size=4 if paged else 0)
    reqs = [eng.submit(s) for s in srcs]
    eng.run_until_drained()
    got = [decoding.strip_special(eng.poll(r.id).tokens) for r in reqs]
    assert got == direct
    assert eng.metrics.spec_accept_rate == pytest.approx(1.0)
    tpts = eng.metrics.spec_tokens_per_target_step
    assert tpts is not None and tpts > 1.0


@pytest.fixture(scope="module")
def shrunk_draft(sliver_bpe):
    """A genuinely smaller draft sharing the target's vocab and max_len —
    different random weights, so acceptance is partial and the reject/
    correct path is exercised for real."""
    draft = transformer_nmt_tiny(vocab_size=sliver_bpe.vocab_size,
                                 hidden_size=16, num_layers=1, num_heads=2,
                                 mlp_dim=32, max_len=32)
    dvars = draft.init(
        jax.random.PRNGKey(7), np.zeros((1, PARITY_SRC_LEN), np.int32),
        np.ones((1, PARITY_SRC_LEN), np.int32),
        np.zeros((1, PARITY_SRC_LEN), np.int32), train=False)
    return draft, {"params": dvars["params"]}


@pytest.mark.parametrize("paged", [False, True])
def test_speculative_distinct_draft_parity(parity_setup, shrunk_draft,
                                           paged):
    """With a shrunk (disagreeing) draft, acceptance is partial — and the
    output must STILL be token-identical to plain greedy: rejected windows
    fall back to the target's correction token, never the draft's. In
    paged mode the block tables advance by the per-row accepted length,
    and the pool drains leak-free."""
    model, variables, srcs = parity_setup
    draft, dvars = shrunk_draft
    direct = [_direct_decode(model, variables, s, 1) for s in srcs]
    eng = Engine(model, variables, capacity=2, max_src_len=PARITY_SRC_LEN,
                 default_max_new_tokens=PARITY_NEW_TOKENS,
                 speculate_gamma=3, draft_model=draft,
                 draft_variables=dvars,
                 kv_block_size=4 if paged else 0)
    reqs = [eng.submit(s) for s in srcs]
    eng.run_until_drained()
    got = [decoding.strip_special(eng.poll(r.id).tokens) for r in reqs]
    assert got == direct
    rate = eng.metrics.spec_accept_rate
    assert rate is not None and rate < 1.0  # the draft really disagrees
    tpts = eng.metrics.spec_tokens_per_target_step
    assert tpts is not None and tpts >= 1.0  # every verify emits >= 1
    if paged:
        assert eng.allocator.blocks_in_use == 0  # full release on drain


@pytest.mark.parametrize("paged", [False, True])
def test_spec_acceptance_crosses_budget_boundary(parity_setup, paged):
    """γ=4 against a 3-token budget: the accepted window would overrun the
    budget, so emission must truncate token-by-token exactly like the
    fused window body — same tokens as a plain engine at the same
    budget."""
    model, variables, srcs = parity_setup
    kw = dict(capacity=2, max_src_len=PARITY_SRC_LEN,
              default_max_new_tokens=3, kv_block_size=4 if paged else 0)
    plain = Engine(model, variables, **kw)
    plain_reqs = [plain.submit(s) for s in srcs]
    plain.run_until_drained()
    spec = Engine(model, variables, speculate_gamma=4, **kw)
    spec_reqs = [spec.submit(s) for s in srcs]
    spec.run_until_drained()
    for pr, sr in zip(plain_reqs, spec_reqs):
        assert spec.poll(sr.id).tokens == plain.poll(pr.id).tokens
        assert len(spec.poll(sr.id).tokens) <= 3


def test_spec_draft_eos_mid_window(sched_model):
    """An accepted EOS mid-window ends the request right there: later
    window positions are discarded, the row releases, and positions
    advance only past the emitted tokens. Driven through a stubbed device
    fn so the EOS lands deterministically."""
    eng = _mk_engine(sched_model, speculate_gamma=4, queue_depth=4)
    req = eng.submit(_src(3), max_new_tokens=8)
    cap, g = eng.capacity, eng.speculate_gamma

    def fake(*args):
        cache, dcache = args[2], args[3]
        props = np.full((cap, g), 7, np.int32)
        tgt = np.full((cap, g + 1), 7, np.int32)
        props[:, 1] = decoding.EOS_ID
        tgt[:, 1] = decoding.EOS_ID
        return props, tgt, cache, dcache

    eng._spec_fn_cached = fake
    eng.step()
    assert eng.poll(req.id).tokens == [7, decoding.EOS_ID]
    assert eng.poll(req.id).state is RequestState.DONE
    assert eng.active_rows == 0
    assert int(eng._pos[0]) == 0  # row released and reset
    assert eng.metrics.spec_tokens_per_target_step == pytest.approx(2.0)


def test_spec_gamma_zero_degenerates_to_plain_window(sched_model):
    """speculate_gamma=0 is exactly the pre-speculation engine: no draft
    state, no spec jit, no serve_spec_ metric keys, same tokens."""
    eng = _mk_engine(sched_model, speculate_gamma=0, decode_window=4)
    assert eng.draft_model is None and eng.draft_variables is None
    r = eng.submit(_src(5), max_new_tokens=6)
    eng.run_until_drained()
    assert eng._spec_fn_cached is None
    assert not any(k.startswith("serve_spec_")
                   for k in eng.metrics.snapshot())
    ref = _mk_engine(sched_model, decode_window=4)
    r2 = ref.submit(_src(5), max_new_tokens=6)
    ref.run_until_drained()
    assert eng.poll(r.id).tokens == ref.poll(r2.id).tokens


def test_spec_falls_back_for_deadlines_and_beams(parity_setup):
    """A pending deadline (or a beam group) must drop the tick to the
    non-speculative path — expiry lands within one plain step — and the
    trace stays parity-exact across the path flips."""
    model, variables, srcs = parity_setup
    eng = Engine(model, variables, capacity=3, max_src_len=PARITY_SRC_LEN,
                 default_max_new_tokens=PARITY_NEW_TOKENS,
                 speculate_gamma=2)
    reqs = []
    for i, s in enumerate(srcs):
        kw = {"deadline_s": 60.0} if i % 2 else {}
        kw["beam_size"] = 2 if i == 3 else 1
        reqs.append(eng.submit(s, **kw))
    eng.run_until_drained()
    for i, (r, s) in enumerate(zip(reqs, srcs)):
        want = _direct_decode(model, variables, s, 2 if i == 3 else 1)
        assert decoding.strip_special(eng.poll(r.id).tokens) == want


def test_spec_engine_validates_draft(sched_model):
    model, variables = sched_model
    with pytest.raises(ValueError):
        Engine(model, variables, speculate_gamma=-1)
    with pytest.raises(ValueError):  # draft model without variables
        Engine(model, variables, speculate_gamma=2, draft_model=model)
    short = transformer_nmt_tiny(vocab_size=SCHED_VOCAB, hidden_size=16,
                                 num_layers=1, num_heads=2, mlp_dim=32,
                                 max_len=16)
    svars = short.init(
        jax.random.PRNGKey(2), np.zeros((1, SCHED_SRC_LEN), np.int32),
        np.ones((1, SCHED_SRC_LEN), np.int32),
        np.zeros((1, SCHED_SRC_LEN), np.int32), train=False)
    with pytest.raises(ValueError):  # draft max_len < target max_len
        Engine(model, variables, speculate_gamma=2, draft_model=short,
               draft_variables={"params": svars["params"]})


def test_serve_metrics_spec_keys_are_conditional():
    """serve_spec_* keys exist only once speculation is configured — the
    same conditional-surface contract as the paged/prefix keys."""
    base = ServeMetrics(capacity=2, clock=FakeClock())
    assert not any(k.startswith("serve_spec_") for k in base.snapshot())
    m = ServeMetrics(capacity=2, clock=FakeClock())
    m.configure_speculation(2)
    m.record_spec(proposed=4, accepted=3, target_row_steps=2, emitted=5,
                  rates=[1.0, 0.5])
    snap = m.snapshot()
    assert snap["serve_spec_gamma"] == 2
    assert snap["serve_spec_proposed"] == 4
    assert snap["serve_spec_accepted"] == 3
    assert snap["serve_spec_accept_rate"] == pytest.approx(0.75)
    assert 0.5 <= snap["serve_spec_accept_rate_p50"] <= 1.0
    assert 0.5 <= snap["serve_spec_accept_rate_p95"] <= 1.0
    assert snap["serve_spec_tokens_per_target_step"] == pytest.approx(2.5)


def test_overload_hint_falls_back_to_decode_window():
    """With no admission waits observed yet, the retry-after hint comes
    from the measured decode-window latency (the post-speculation rate),
    not the static floor."""
    q = RequestQueue(max_depth=1, clock=FakeClock())
    q.note_decode_window(0.2)
    q.note_decode_window(0.2)
    q.submit([5], max_new_tokens=2)
    with pytest.raises(OverloadError) as ei:
        q.submit([6], max_new_tokens=2)
    assert ei.value.retry_after_s == pytest.approx(0.2)


# -- int8 weight-only quantization ------------------------------------------


def test_quantize_variables_int8_ratio_and_structure(sched_model):
    from deeplearning_cfn_tpu.serve import quantize_variables, \
        variables_bytes

    model, variables = sched_model
    q = quantize_variables(variables)
    ratio = variables_bytes(q) / variables_bytes(variables)
    # This 32-hidden scheduler model keeps a larger share of its bytes in
    # the unquantized position tables / LayerNorms than the bench model
    # does, so the bound here is looser than the 0.35 serving contract
    # (asserted on the bench model in the record-fields test below).
    assert ratio <= 0.40
    leaves = jax.tree_util.tree_leaves(q)
    assert any(np.asarray(l).dtype == np.int8 for l in leaves)
    # The fp32 source tree is untouched (quantization is a pure function).
    assert all(np.asarray(l).dtype != np.int8
               for l in jax.tree_util.tree_leaves(variables))
    with pytest.raises(ValueError):
        quantize_variables(variables, dtype="int4")


def test_quantized_serving_divergence_bounded(sched_model):
    """One fp32-vs-int8 forward pass stays inside the relative logits
    bound the bench gates on."""
    from deeplearning_cfn_tpu.serve.bench import _quant_divergence

    model, variables = sched_model
    diff, bound, ok = _quant_divergence(model, variables, SCHED_SRC_LEN,
                                        SCHED_VOCAB, seed=0)
    assert ok is True and diff <= bound


def test_quantized_engine_serves_and_spec_parity(sched_model):
    """An int8 engine serves end-to-end, and speculation on top of it is
    token-identical to the plain int8 engine (parity is within the
    quantized model, not across precisions)."""
    plain = _mk_engine(sched_model, quantize="int8")
    spec = _mk_engine(sched_model, quantize="int8", speculate_gamma=2)
    srcs = [_src(i) for i in range(4)]
    p_reqs = [plain.submit(s, max_new_tokens=8) for s in srcs]
    plain.run_until_drained()
    s_reqs = [spec.submit(s, max_new_tokens=8) for s in srcs]
    spec.run_until_drained()
    for pr, sr in zip(p_reqs, s_reqs):
        assert plain.poll(pr.id).state is RequestState.DONE
        assert spec.poll(sr.id).tokens == plain.poll(pr.id).tokens


def test_swap_variables_requantizes_for_quantized_engine(sched_model):
    """Fleet rollout against a --quantize int8 fleet: swap receives the
    fp32 checkpoint, the engine re-quantizes it (and re-points the
    self-draft alias), and serving continues with identical output."""
    model, variables = sched_model
    eng = _mk_engine(sched_model, quantize="int8", speculate_gamma=2)
    r1 = eng.submit(_src(4), max_new_tokens=6)
    eng.run_until_drained()
    before = eng.poll(r1.id).tokens
    eng.swap_variables(variables)  # fp32 in → int8 inside
    assert any(np.asarray(l).dtype == np.int8
               for l in jax.tree_util.tree_leaves(eng.variables))
    assert eng.draft_variables is eng.variables  # self-draft re-aliased
    r2 = eng.submit(_src(4), max_new_tokens=6)
    eng.run_until_drained()
    assert eng.poll(r2.id).tokens == before


def test_serve_bench_speculate_and_quantize_record_fields():
    """The bench record carries the speculation/quantization perf fields
    (and their contracts) the t1 gates assert on."""
    from deeplearning_cfn_tpu.serve.bench import run_serve_bench

    rec = run_serve_bench(num_requests=4, slots=2, max_new_tokens=4,
                          src_len=8, speculate=2, quantize="int8",
                          smoke=True)
    assert rec["spec_gamma"] == 2
    assert rec["token_identical"] is True
    assert rec["spec_accept_rate"] == pytest.approx(1.0)
    assert rec["tokens_per_target_step"] > 1.0
    assert rec["weight_bytes"] <= 0.35 * rec["weight_bytes_fp32"]
    assert rec["kv_bytes"] > 0
    assert rec["divergence_ok"] is True
    assert rec["logits_divergence"] <= rec["divergence_bound"]


# -- device-resident spec chains + int8 KV cache -----------------------------


@pytest.fixture(scope="module")
def parity_direct(parity_setup):
    model, variables, srcs = parity_setup
    return [_direct_decode(model, variables, s, 1) for s in srcs]


@pytest.fixture(scope="module")
def int8_kv_baseline(parity_setup):
    """Plain (non-speculative, window-1) int8-KV tokens — the reference
    the int8 speculative parity checks compare against: int8 KV is
    bounded-divergence vs fp32, so parity is WITHIN the quantized
    engine, exactly like the --quantize contract."""
    model, variables, srcs = parity_setup
    eng = Engine(model, variables, capacity=2, max_src_len=PARITY_SRC_LEN,
                 default_max_new_tokens=PARITY_NEW_TOKENS,
                 kv_block_size=4, kv_quant="int8")
    reqs = [eng.submit(s) for s in srcs]
    eng.run_until_drained()
    return [decoding.strip_special(eng.poll(r.id).tokens) for r in reqs]


@pytest.mark.parametrize("kv", ["fp32", "int8"])
@pytest.mark.parametrize("chain", [1, 4])
@pytest.mark.parametrize("gamma", [2, 4])
@pytest.mark.parametrize("paged", [False, True])
def test_spec_device_chain_parity(parity_setup, parity_direct,
                                  int8_kv_baseline, paged, gamma, chain,
                                  kv):
    """The tentpole grid: device-resident accept/advance is
    token-identical to plain greedy across draft depths, chain lengths
    (--decode-window), cache layouts, and KV precisions. fp32 compares
    against the offline searcher; int8 against the plain int8-KV engine
    (bounded-divergence contract, same as --quantize)."""
    if kv == "int8" and not paged:
        pytest.skip("int8 KV requires the paged pool")
    model, variables, srcs = parity_setup
    eng = Engine(model, variables, capacity=2, max_src_len=PARITY_SRC_LEN,
                 default_max_new_tokens=PARITY_NEW_TOKENS,
                 decode_window=chain, speculate_gamma=gamma,
                 speculate_device=True,
                 kv_block_size=4 if paged else 0,
                 kv_quant="int8" if kv == "int8" else "")
    reqs = [eng.submit(s) for s in srcs]
    eng.run_until_drained()
    got = [decoding.strip_special(eng.poll(r.id).tokens) for r in reqs]
    assert got == (parity_direct if kv == "fp32" else int8_kv_baseline)
    if kv == "fp32":
        # Self-draft on an unquantized pool: acceptance is total.
        assert eng.metrics.spec_accept_rate == pytest.approx(1.0)
    syncs = eng.metrics.spec_host_syncs_per_token
    assert syncs is not None and syncs > 0
    assert eng.metrics.spec_windows_per_chain >= 1.0
    if paged:
        assert eng.allocator.blocks_in_use == 0  # leak-free drain


@pytest.mark.parametrize("paged", [False, True])
def test_spec_device_chain_budget_truncation(parity_setup, paged):
    """γ=4 chained 4 windows deep against a 3-token budget: the replay
    must truncate mid-chain exactly like the host path — same tokens as
    a plain engine at the same budget, never a token past it."""
    model, variables, srcs = parity_setup
    kw = dict(capacity=2, max_src_len=PARITY_SRC_LEN,
              default_max_new_tokens=3, kv_block_size=4 if paged else 0)
    plain = Engine(model, variables, **kw)
    plain_reqs = [plain.submit(s) for s in srcs]
    plain.run_until_drained()
    dev = Engine(model, variables, speculate_gamma=4,
                 speculate_device=True, decode_window=4, **kw)
    dev_reqs = [dev.submit(s) for s in srcs]
    dev.run_until_drained()
    for pr, dr in zip(plain_reqs, dev_reqs):
        assert dev.poll(dr.id).tokens == plain.poll(pr.id).tokens
        assert len(dev.poll(dr.id).tokens) <= 3


def test_spec_device_chain_eos_mid_chain(sched_model):
    """An accepted EOS in a LATER window of the chain ends the request
    there: the replay discards the remaining window positions, the row
    releases, and the chain accounting records one sync for the whole
    chain. Driven through a stubbed chain fn so the EOS lands
    deterministically mid-chain."""
    eng = _mk_engine(sched_model, speculate_gamma=2,
                     speculate_device=True, decode_window=2, queue_depth=4)
    req = eng.submit(_src(3), max_new_tokens=8)
    cap, g, chain = eng.capacity, eng.speculate_gamma, eng.decode_window

    def fake(*args):
        cache, dcache = args[2], args[3]
        tgts = np.full((chain, cap, g + 1), 7, np.int32)
        accs = np.zeros((chain, cap), np.int32)
        # Window 0: reject all → emit one correction token. Window 1:
        # accept one draft token, whose target token is EOS.
        accs[1, :] = 1
        tgts[1, :, 1] = decoding.EOS_ID
        return tgts, accs, cache, dcache

    eng._spec_chain_fns[chain] = fake
    eng.step()
    assert eng.poll(req.id).tokens == [7, 7, decoding.EOS_ID]
    assert eng.poll(req.id).state is RequestState.DONE
    assert eng.active_rows == 0
    assert eng.metrics.spec_windows_per_chain == pytest.approx(2.0)
    assert eng.metrics.spec_host_syncs_per_token == pytest.approx(1 / 3)


def test_spec_device_chain_fewer_syncs_than_host_path(parity_setup):
    """The acceptance criterion, at engine level: at γ=4/chain=4 the
    device path pays strictly fewer host syncs per emitted token than
    the host accept loop on the same trace (same tokens, fewer
    round-trips)."""
    model, variables, srcs = parity_setup
    kw = dict(capacity=2, max_src_len=PARITY_SRC_LEN,
              default_max_new_tokens=PARITY_NEW_TOKENS,
              speculate_gamma=4, decode_window=4, kv_block_size=4)
    host = Engine(model, variables, **kw)
    h_reqs = [host.submit(s) for s in srcs]
    host.run_until_drained()
    dev = Engine(model, variables, speculate_device=True, **kw)
    d_reqs = [dev.submit(s) for s in srcs]
    dev.run_until_drained()
    for hr, dr in zip(h_reqs, d_reqs):
        assert dev.poll(dr.id).tokens == host.poll(hr.id).tokens
    h = host.metrics.spec_host_syncs_per_token
    d = dev.metrics.spec_host_syncs_per_token
    assert h is not None and d is not None
    assert d < h


def test_spec_device_and_kv_quant_validation(sched_model):
    model, variables = sched_model
    with pytest.raises(ValueError, match="speculate_gamma"):
        Engine(model, variables, speculate_device=True)
    with pytest.raises(ValueError, match="kv_block_size"):
        Engine(model, variables, kv_quant="int8")  # dense layout
    with pytest.raises(ValueError):
        Engine(model, variables, kv_quant="int4", kv_block_size=4)


def test_kv_quant_pool_structure_and_bytes(sched_model):
    """The int8 pool is int8 codes + per-block/per-head fp32 scale
    sidecars, its as-stored footprint meets the ≤0.30× contract, and the
    serve_kv_quant_bytes gauge reports exactly that footprint."""
    from deeplearning_cfn_tpu.serve.quant import kv_pool_bytes

    eng = _mk_engine(sched_model, kv_block_size=4, kv_quant="int8")
    nb = eng.kv_blocks
    leaves = [np.asarray(l) for l in jax.tree_util.tree_leaves(eng.cache)]
    codes = [l for l in leaves if l.ndim == 4 and l.shape[0] == nb]
    scales = [l for l in leaves if l.ndim == 2 and l.shape[0] == nb]
    assert codes and len(codes) == len(scales)  # every pool has a sidecar
    assert all(l.dtype == np.int8 for l in codes)
    assert all(l.dtype == np.float32 for l in scales)
    assert all(s.shape[1] == c.shape[1]  # one scale per (block, head)
               for c, s in zip(codes, scales))
    stored, fp32 = kv_pool_bytes(eng.cache, nb)
    assert 0 < stored <= 0.30 * fp32
    assert eng.metrics.snapshot()["serve_kv_quant_bytes"] == stored


def test_kv_quant_window_invariance(sched_model):
    """Int8 KV serving is decode-window invariant: the requantize write
    path and dequant gather commute with window fusion."""
    srcs = [_src(i) for i in range(4)]
    outs = []
    for w in (1, 2):
        eng = _mk_engine(sched_model, kv_block_size=4, kv_quant="int8",
                         decode_window=w, queue_depth=8)
        reqs = [eng.submit(s, max_new_tokens=8) for s in srcs]
        eng.run_until_drained()
        assert all(eng.poll(r.id).state is RequestState.DONE for r in reqs)
        outs.append([eng.poll(r.id).tokens for r in reqs])
    assert outs[0] == outs[1]


def test_kv_quant_divergence_bounded(sched_model):
    """Teacher-forced paged decode fp32-vs-int8-KV stays inside the same
    relative logits bound the bench gates on (the --quantize contract,
    applied to the cache)."""
    from deeplearning_cfn_tpu.serve.bench import _kv_quant_divergence

    model, variables = sched_model
    diff, bound, ok = _kv_quant_divergence(model, variables,
                                           SCHED_SRC_LEN, SCHED_VOCAB,
                                           seed=0)
    assert ok is True and diff <= bound


def test_kv_quant_beam_cow_preserves_scales(sched_model):
    """Beam forks COW tail blocks WITH their scale sidecars: an int8
    beam run matches the fp32-KV beam choice on this trace and is
    decode-window invariant — a fork that dropped scales would misdecode
    the copied block and diverge on both counts."""
    def run(kv_quant, w):
        eng = _mk_engine(sched_model, kv_block_size=2, kv_quant=kv_quant,
                         decode_window=w, queue_depth=4)
        r = eng.submit(_src(9), max_new_tokens=6, beam_size=2)
        eng.run_until_drained()
        assert eng.poll(r.id).state is RequestState.DONE
        assert eng.allocator.blocks_in_use == 0
        return eng.poll(r.id).tokens

    fp32 = run("", 1)
    assert run("int8", 1) == fp32
    assert run("int8", 2) == fp32


def test_kv_quant_composes_with_weight_quant_and_spec_device(sched_model):
    """All three knobs at once — int8 weights, int8 KV, device-resident
    speculation — serve token-identically to the plain engine with the
    same two quantizers (parity within the quantized pair)."""
    kw = dict(kv_block_size=4, kv_quant="int8", quantize="int8",
              queue_depth=8)
    plain = _mk_engine(sched_model, **kw)
    spec = _mk_engine(sched_model, speculate_gamma=2,
                      speculate_device=True, decode_window=2, **kw)
    srcs = [_src(i) for i in range(4)]
    p_reqs = [plain.submit(s, max_new_tokens=8) for s in srcs]
    plain.run_until_drained()
    s_reqs = [spec.submit(s, max_new_tokens=8) for s in srcs]
    spec.run_until_drained()
    for pr, sr in zip(p_reqs, s_reqs):
        assert spec.poll(sr.id).tokens == plain.poll(pr.id).tokens
    assert spec.metrics.spec_host_syncs_per_token is not None


def test_distilled_draft_preset_loads():
    from deeplearning_cfn_tpu.serve.loader import (
        DRAFT_PRESETS,
        distilled_draft,
    )

    assert "tiny-distilled" in DRAFT_PRESETS
    draft, dvars = distilled_draft("tiny-distilled")
    leaves = jax.tree_util.tree_leaves(dvars)
    assert leaves and all(np.asarray(l).size > 0 for l in leaves)
    with pytest.raises(ValueError, match="tiny-distilled"):
        distilled_draft("no-such-preset")


def test_distilled_draft_real_accept_rate_with_parity():
    """The committed distilled draft against the exact bench teacher it
    was distilled from (random-init tiny NMT, seed 0): token parity with
    the plain engine AND a real (non-ceiling) accept rate — the draft
    genuinely predicts the teacher instead of merely aliasing it."""
    from deeplearning_cfn_tpu.serve.bench import _fixed_trace
    from deeplearning_cfn_tpu.serve.loader import distilled_draft

    src_len = 8
    model = transformer_nmt_tiny(vocab_size=96, max_len=64)
    init = model.init(
        jax.random.PRNGKey(0), np.zeros((1, src_len), np.int32),
        np.ones((1, src_len), np.int32),
        np.zeros((1, src_len), np.int32), train=False)
    variables = {"params": init["params"]}
    draft, dvars = distilled_draft()
    trace = _fixed_trace(4, src_len, 96, seed=0)
    kw = dict(capacity=2, max_src_len=src_len, queue_depth=8,
              default_max_new_tokens=8)
    plain = Engine(model, variables, **kw)
    p_ids = [plain.submit(s).id for s in trace]
    plain.run_until_drained()
    spec = Engine(model, variables, speculate_gamma=4, draft_model=draft,
                  draft_variables=dvars, **kw)
    s_ids = [spec.submit(s).id for s in trace]
    spec.run_until_drained()
    assert [spec.poll(i).tokens for i in s_ids] == \
        [plain.poll(i).tokens for i in p_ids]
    rate = spec.metrics.spec_accept_rate
    assert rate is not None and 0.5 <= rate <= 1.0


def test_serve_bench_spec_device_kv_quant_record_fields():
    """The bench record carries the chain/sync and KV-footprint fields
    (and their contracts) the new t1 gates assert on."""
    from deeplearning_cfn_tpu.serve.bench import run_serve_bench

    rec = run_serve_bench(num_requests=4, slots=2, max_new_tokens=4,
                          src_len=8, speculate=2, speculate_device=True,
                          kv_quant="int8", smoke=True)
    assert rec["speculate_device"] is True
    assert rec["kv_quant"] == "int8"
    assert rec["token_identical"] is True
    assert rec["spec_chain_len_p50"] is not None
    assert rec["host_syncs_per_token"] is not None
    assert rec["host_syncs_per_token_host_path"] is not None
    assert rec["kv_cache_bytes"] <= 0.30 * rec["kv_cache_bytes_fp32"]
    assert rec["kv_divergence_ok"] is True
    assert rec["kv_divergence"] <= rec["kv_divergence_bound"]


def test_serve_metrics_chain_and_kv_quant_keys_are_conditional():
    """serve_spec_chain_* / serve_kv_quant_bytes exist only once their
    feature is configured — the same conditional-surface contract as the
    spec/paged/prefix keys."""
    base = ServeMetrics(capacity=2, clock=FakeClock())
    snap = base.snapshot()
    assert "serve_kv_quant_bytes" not in snap
    assert not any(k.startswith("serve_spec_chain") for k in snap)
    m = ServeMetrics(capacity=2, clock=FakeClock())
    m.configure_speculation(4)
    m.configure_spec_chain(True)
    m.record_spec_chain(windows=4, syncs=1, emitted=6)
    snap = m.snapshot()
    assert snap["serve_spec_device"] is True
    assert snap["serve_spec_chain_windows"] == 4
    assert snap["serve_spec_chain_syncs"] == 1
    assert snap["serve_spec_windows_per_chain"] == pytest.approx(4.0)
    assert snap["serve_spec_host_syncs_per_token"] == pytest.approx(1 / 6)
    assert snap["serve_spec_chain_len_p50"] == pytest.approx(4.0)
    mq = ServeMetrics(capacity=2, clock=FakeClock())
    mq.configure_kv_quant(1234)
    assert mq.snapshot()["serve_kv_quant_bytes"] == 1234
