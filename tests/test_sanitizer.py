"""Sanitizer tier (SURVEY.md §6): the cheapest class-of-bug net.

Runs training smokes under JAX's strictest runtime checks —
``jax_debug_nans`` / ``jax_debug_infs`` abort the program at the first
non-finite intermediate (instead of letting it launder through the loss),
and ``jax_check_tracer_leaks`` catches side-effecting host code inside
traced functions. The reference's analogue was running the examples under
framework debug flags; here it is one marked pytest tier:

    pytest -m sanitizer

The marker gives CI a dedicated job handle; the tests also run (and pass)
as part of the plain suite — deselect with `-m "not sanitizer"` if the
extra ~1 min matters.
"""

import contextlib
import os

import jax
import numpy as np
import pytest

from deeplearning_cfn_tpu.config import apply_overrides
from deeplearning_cfn_tpu.presets import get_preset
from deeplearning_cfn_tpu.train.run import run_experiment


@contextlib.contextmanager
def strict_numerics():
    flags = {"jax_debug_nans": True, "jax_debug_infs": True,
             "jax_check_tracer_leaks": True}
    old = {k: getattr(jax.config, k) for k in flags}
    try:
        for k, v in flags.items():
            jax.config.update(k, v)
        yield
    finally:
        for k, v in old.items():
            jax.config.update(k, v)


def _smoke_cfg(tmp_workdir, preset="cifar10_resnet20"):
    cfg = get_preset(preset)
    apply_overrides(cfg, [
        f"workdir={tmp_workdir}",
        "train.global_batch=16",
        "train.steps=4",
        "train.log_every_steps=2",
        "train.eval_every_steps=1000000",
        "train.dtype=float32",  # debug_nans is exact in f32
        "data.num_train_examples=64",
        "data.num_eval_examples=16",
        "train.eval_batch=16",
        "data.prefetch=0",
        "schedule.name=constant",
        "schedule.base_lr=0.05",
        "schedule.warmup_epochs=0",
        "checkpoint.async_write=false",
    ])
    return cfg


@pytest.mark.sanitizer
def test_cifar_smoke_under_debug_nans(tmp_workdir, devices):
    with strict_numerics():
        final = run_experiment(_smoke_cfg(tmp_workdir))
    assert np.isfinite(final["loss"])


@pytest.mark.sanitizer
def test_nmt_smoke_under_debug_nans(tmp_workdir, devices):
    cfg = _smoke_cfg(tmp_workdir, "transformer_nmt_wmt")
    apply_overrides(cfg, [
        "data.seq_len=16", "data.vocab_size=32",
        "data.num_train_examples=64", "data.num_eval_examples=16",
        "model.kwargs.hidden_size=32", "model.kwargs.num_layers=1",
        "model.kwargs.num_heads=2", "model.kwargs.mlp_dim=64",
        "model.kwargs.max_len=16", "eval.beam_size=2",
    ])
    with strict_numerics():
        final = run_experiment(cfg)
    assert np.isfinite(final["loss"])
    assert 0.0 <= final["bleu"] <= 1.0


@pytest.mark.sanitizer
def test_lm_smoke_under_debug_nans(tmp_workdir, devices):
    cfg = _smoke_cfg(tmp_workdir, "gpt_small_lm")
    apply_overrides(cfg, [
        "model.name=gpt_tiny",
        'model.kwargs={"vocab_size": 32, "max_len": 16}',
        "data.seq_len=16", "data.vocab_size=32",
        "data.num_train_examples=64", "data.num_eval_examples=16",
        "train.shard_opt_state=false",
    ])
    with strict_numerics():
        final = run_experiment(cfg)
    assert np.isfinite(final["loss"])
    assert np.isfinite(final["perplexity"])


@pytest.mark.sanitizer
def test_vit_smoke_under_debug_nans(tmp_workdir, devices):
    cfg = _smoke_cfg(tmp_workdir, "imagenet_vit_s16")
    apply_overrides(cfg, [
        "model.name=vit_tiny", "model.num_classes=10",
        'model.kwargs={"dropout_rate": 0.1}',
        "data.name=cifar10", "data.image_size=32",
        "data.num_train_examples=64", "data.num_eval_examples=16",
        "train.shard_opt_state=false",
    ])
    with strict_numerics():
        final = run_experiment(cfg)
    assert np.isfinite(final["loss"])


@pytest.mark.sanitizer
def test_debug_nans_actually_fires(devices):
    """The tier is only a net if the flag really aborts on NaN — prove the
    config plumbing works by tripping it on purpose."""
    with strict_numerics():
        with pytest.raises(FloatingPointError):
            jax.jit(lambda x: jnp_log_neg(x))(np.ones(4, np.float32))


def jnp_log_neg(x):
    import jax.numpy as jnp

    return jnp.log(-jnp.abs(x))  # log of a negative → NaN
