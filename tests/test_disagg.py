"""Disaggregated prefill/decode serving tests: the KV handoff codec
(export → store → import round trip, partial tail blocks, beam prefix
sharing, free-list-independent remap), engine-pair token parity (greedy,
beam, speculation), the router's phase-aware placement and handoff hop,
phase-aware rollout/evacuation, and the bench/report surfaces.

The contract under test everywhere: splitting the fleet into prefill
and decode replicas must be invisible in outputs — token-identical to a
co-located run of the same trace — while zero requests drop.
"""

import json
import os

import numpy as np
import pytest

from deeplearning_cfn_tpu.ckpt.store import MemoryObjectStore
from deeplearning_cfn_tpu.fleet import (
    EngineReplica,
    ReplicaState,
    Router,
    rolling_upgrade,
)
from deeplearning_cfn_tpu.runtime.faults import FaultPlan, FaultSpec
from deeplearning_cfn_tpu.serve.handoff import (
    drop_handoff,
    load_handoff,
    save_handoff,
    validate_artifact,
)
from deeplearning_cfn_tpu.serve.queue import OverloadError


@pytest.fixture(scope="module")
def tiny_disagg_setup():
    """One tiny NMT init shared by every engine in this module, a fixed
    trace, and paged single-engine baselines (greedy per-request token
    lists, plus a beam baseline for trace[1])."""
    import jax

    from deeplearning_cfn_tpu.models.transformer_nmt import (
        transformer_nmt_tiny,
    )
    from deeplearning_cfn_tpu.serve.bench import _fixed_trace
    from deeplearning_cfn_tpu.serve.engine import Engine

    src_len, max_new = 8, 4
    model = transformer_nmt_tiny(vocab_size=96, max_len=64)
    init = model.init(
        jax.random.PRNGKey(0),
        np.zeros((1, src_len), np.int32), np.ones((1, src_len), np.int32),
        np.zeros((1, src_len), np.int32), train=False)
    variables = {"params": init["params"]}
    trace = _fixed_trace(6, src_len, 96, seed=0)

    def make_engine(phase, kv_block_size=4, speculate_gamma=0, **kw):
        return Engine(model, variables, capacity=2, max_src_len=src_len,
                      queue_depth=len(trace),
                      default_max_new_tokens=max_new, decode_window=2,
                      kv_block_size=kv_block_size,
                      speculate_gamma=speculate_gamma, phase=phase, **kw)

    baseline_engine = make_engine("both")
    ids = [baseline_engine.submit(src, max_new_tokens=max_new).id
           for src in trace]
    baseline_engine.run_until_drained()
    baseline = [list(baseline_engine.poll(i).tokens) for i in ids]
    beam_req = make_engine("both")
    rb = beam_req.submit(trace[1], max_new_tokens=max_new, beam_size=2)
    beam_req.run_until_drained()
    beam_baseline = list(beam_req.poll(rb.id).tokens)

    return {"trace": trace, "baseline": baseline,
            "beam_baseline": beam_baseline, "variables": variables,
            "max_new": max_new, "src_len": src_len,
            "make_engine": make_engine}


def _park_one(engine, src, max_new, **submit_kwargs):
    req = engine.submit(src, max_new_tokens=max_new, **submit_kwargs)
    engine.run_until_drained()
    assert engine.handoff_ready(req.id)
    return req


def _route_all(router, trace, max_new):
    rids = []
    for src in trace:
        while True:
            try:
                rids.append(router.submit(src, max_new_tokens=max_new))
                break
            except OverloadError:
                router.step()
    return rids


# -- handoff codec -----------------------------------------------------------


def test_handoff_codec_round_trips_through_store(tiny_disagg_setup):
    """Every artifact array survives save → load byte-identically, and
    drop removes the object."""
    s = tiny_disagg_setup
    pre = s["make_engine"]("prefill")
    req = _park_one(pre, s["trace"][0], s["max_new"])
    art = pre.export_handoff(req.id)
    store = MemoryObjectStore()
    nbytes = save_handoff(store, "handoff/t0", art)
    assert nbytes > 0
    loaded = load_handoff(store, "handoff/t0")
    assert set(loaded) == set(art)
    for k in art:
        np.testing.assert_array_equal(np.asarray(loaded[k]),
                                      np.asarray(art[k]), err_msg=k)
    validate_artifact(loaded)
    drop_handoff(store, "handoff/t0")
    with pytest.raises(FileNotFoundError):
        load_handoff(store, "handoff/t0")
    pre.release_handoff(req.id)


def test_handoff_codec_round_trips_bfloat16_leaves():
    """A bfloat16 cache (the wmt preset on TPU) must survive the npz
    transport: numpy reloads raw ml_dtypes arrays as void records, so
    the codec ships them as byte views with a dtype tag."""
    import ml_dtypes

    from deeplearning_cfn_tpu.serve.handoff import pack_meta

    rng = np.random.default_rng(0)
    kv = rng.standard_normal((2, 2, 4, 3)).astype(ml_dtypes.bfloat16)
    enc = rng.standard_normal((8, 16)).astype(ml_dtypes.bfloat16)
    art = {
        "meta": pack_meta(version=1, width=1, steps=1, budget=4,
                          kv_block_size=4, model_max_len=64,
                          max_src_len=8, enc_hid=16),
        "row_block_index": np.array([[0, 1]], np.int32),
        "kv_0": kv, "enc": enc,
        "src_mask": np.ones((8,), np.int32),
        "src_ids": np.arange(3, 11, dtype=np.int32),
        "tokens": np.array([7], np.int32),
        "prev": np.array([7], np.int32),
        "pos": np.array([1], np.int32),
        "deadline": np.array([np.nan], np.float64),
    }
    store = MemoryObjectStore()
    save_handoff(store, "handoff/bf16", art)
    loaded = load_handoff(store, "handoff/bf16")
    assert loaded["kv_0"].dtype == ml_dtypes.bfloat16
    assert loaded["enc"].dtype == ml_dtypes.bfloat16
    np.testing.assert_array_equal(loaded["kv_0"].view(np.uint16),
                                  kv.view(np.uint16))
    np.testing.assert_array_equal(loaded["enc"].view(np.uint16),
                                  enc.view(np.uint16))
    assert loaded["src_mask"].dtype == np.int32


def test_handoff_artifact_partial_tail_block(tiny_disagg_setup):
    """Prefill parks after exactly one decode step, so with block size 4
    the exported tail block is partial: the artifact still carries whole
    blocks, indexed per row, with pos marking the real fill level."""
    s = tiny_disagg_setup
    pre = s["make_engine"]("prefill")
    req = _park_one(pre, s["trace"][2], s["max_new"])
    art = pre.export_handoff(req.id)
    meta = validate_artifact(art)
    assert meta["steps"] == 1 and meta["width"] == 1
    assert meta["kv_block_size"] == 4
    rbi = np.asarray(art["row_block_index"])
    # One partially-filled block bound for the single row.
    assert (rbi[0] >= 0).sum() == 1
    assert art["kv_0"].shape[0] == 1          # n_unique blocks
    assert art["kv_0"].shape[2] == 4          # whole block exported
    assert list(art["pos"]) == [1]            # ...but only 1 position live
    pre.release_handoff(req.id)


def test_handoff_beam_shared_prefix_reshared_by_refcount(tiny_disagg_setup):
    """Beam rows sharing a prefix block export ONE copy (same artifact
    index in both rows) and the importer re-shares it: both decode-side
    rows bind the same remapped block at refcount 2."""
    s = tiny_disagg_setup
    # Block size 1: the first step fills a whole block, so the beam fork
    # shares it by refcount instead of copying the tail.
    pre = s["make_engine"]("prefill", kv_block_size=1)
    dec = s["make_engine"]("decode", kv_block_size=1)
    req = _park_one(pre, s["trace"][1], s["max_new"], beam_size=2)
    art = pre.export_handoff(req.id)
    rbi = np.asarray(art["row_block_index"])
    assert rbi[0, 0] == rbi[1, 0]             # shared artifact index
    assert art["kv_0"].shape[0] == 1          # exported once
    new = dec.import_handoff(art, request_id=req.id + "#a1")
    g = dec._groups[-1]
    bounds = [dec._blocks_bound[r] for r in g.rows]
    assert bounds[0][0] == bounds[1][0]
    assert dec.allocator.refcount(bounds[0][0]) == 2
    pre.release_handoff(req.id)
    dec.run_until_drained()
    assert dec.poll(new.id).state.value == "done"


def test_import_remaps_block_ids_through_importer_free_list(
        tiny_disagg_setup):
    """The artifact carries pool-independent indices: an importer whose
    free list is in a different order maps them onto different physical
    block ids and still resumes to identical tokens."""
    s = tiny_disagg_setup
    pre = s["make_engine"]("prefill")
    dec = s["make_engine"]("decode")
    # Scramble the importer's free list: cycle a few blocks through
    # alloc/free so the next pops return different ids than a fresh pool.
    held = [dec.allocator.alloc() for _ in range(3)]
    for b in held:
        dec.allocator.free(b)
    req = _park_one(pre, s["trace"][0], s["max_new"])
    art = pre.export_handoff(req.id)
    n_unique = int(art["kv_0"].shape[0])
    new = dec.import_handoff(art, request_id=req.id + "#a1")
    assert dec.allocator.blocks_in_use == n_unique
    pre.release_handoff(req.id)
    dec.run_until_drained()
    assert list(dec.poll(new.id).tokens) == s["baseline"][0]


def test_import_rejects_mismatched_geometry(tiny_disagg_setup):
    s = tiny_disagg_setup
    pre = s["make_engine"]("prefill")
    req = _park_one(pre, s["trace"][0], s["max_new"])
    art = pre.export_handoff(req.id)
    other = s["make_engine"]("decode", kv_block_size=2)
    with pytest.raises(ValueError, match="kv_block_size"):
        other.import_handoff(art, request_id="x#a1")
    # The exporter's parked state is untouched — a later retry works.
    dec = s["make_engine"]("decode")
    new = dec.import_handoff(art, request_id=req.id + "#a1")
    pre.release_handoff(req.id)
    dec.run_until_drained()
    assert list(dec.poll(new.id).tokens) == s["baseline"][0]


# -- engine-pair parity ------------------------------------------------------


def test_disagg_pair_token_parity_greedy(tiny_disagg_setup):
    """Prefill engine → store codec → decode engine, whole trace: the
    split is invisible — token-identical to the co-located baseline."""
    s = tiny_disagg_setup
    pre = s["make_engine"]("prefill")
    dec = s["make_engine"]("decode")
    store = MemoryObjectStore()
    out = []
    for i, src in enumerate(s["trace"]):
        req = _park_one(pre, src, s["max_new"])
        save_handoff(store, f"handoff/{req.id}", pre.export_handoff(req.id))
        new = dec.import_handoff(load_handoff(store, f"handoff/{req.id}"),
                                 request_id=f"{req.id}#a1",
                                 trace_id=req.id)
        pre.release_handoff(req.id)
        drop_handoff(store, f"handoff/{req.id}")
        dec.run_until_drained()
        out.append(list(dec.poll(new.id).tokens))
    assert out == s["baseline"]


def test_disagg_pair_token_parity_beam(tiny_disagg_setup):
    s = tiny_disagg_setup
    pre = s["make_engine"]("prefill")
    dec = s["make_engine"]("decode")
    req = _park_one(pre, s["trace"][1], s["max_new"], beam_size=2)
    art = pre.export_handoff(req.id)
    new = dec.import_handoff(art, request_id=req.id + "#a1")
    pre.release_handoff(req.id)
    dec.run_until_drained()
    assert list(dec.poll(new.id).tokens) == s["beam_baseline"]


def test_disagg_int8_kv_handoff_round_trip(tiny_disagg_setup):
    """Int8 pools on both sides of the split: the artifact ships int8
    block codes plus their per-block scale sidecars as paired kv_*
    leaves, and the whole trace resumes token-identically to a
    co-located int8 engine (bounded-divergence parity is within the
    quantized pair, like --quantize)."""
    s = tiny_disagg_setup
    both = s["make_engine"]("both", kv_quant="int8")
    ids = [both.submit(src, max_new_tokens=s["max_new"]).id
           for src in s["trace"]]
    both.run_until_drained()
    baseline = [list(both.poll(i).tokens) for i in ids]
    pre = s["make_engine"]("prefill", kv_quant="int8")
    dec = s["make_engine"]("decode", kv_quant="int8")
    store = MemoryObjectStore()
    out = []
    for src in s["trace"]:
        req = _park_one(pre, src, s["max_new"])
        art = pre.export_handoff(req.id)
        kv = [np.asarray(art[k]) for k in sorted(art)
              if k.startswith("kv_")]
        assert any(a.ndim == 4 and a.dtype == np.int8 for a in kv)
        assert any(a.ndim == 2 and a.dtype == np.float32 for a in kv)
        save_handoff(store, f"handoff/{req.id}", art)
        new = dec.import_handoff(load_handoff(store, f"handoff/{req.id}"),
                                 request_id=f"{req.id}#a1")
        pre.release_handoff(req.id)
        drop_handoff(store, f"handoff/{req.id}")
        dec.run_until_drained()
        out.append(list(dec.poll(new.id).tokens))
    assert out == baseline


def test_disagg_import_rejects_cross_precision(tiny_disagg_setup):
    """An fp32 artifact must not land in an int8 pool (or vice versa):
    the importer refuses before committing any state, and the exporter's
    parked group survives for a matched retry."""
    s = tiny_disagg_setup
    pre_fp = s["make_engine"]("prefill")
    req = _park_one(pre_fp, s["trace"][0], s["max_new"])
    art = pre_fp.export_handoff(req.id)
    dec_q = s["make_engine"]("decode", kv_quant="int8")
    with pytest.raises(ValueError, match="kv-quant"):
        dec_q.import_handoff(art, request_id="x#a1")
    # Parked state intact — a matched-precision decode still resumes.
    dec_fp = s["make_engine"]("decode")
    new = dec_fp.import_handoff(art, request_id=req.id + "#a1")
    pre_fp.release_handoff(req.id)
    dec_fp.run_until_drained()
    assert list(dec_fp.poll(new.id).tokens) == s["baseline"][0]
    pre_q = s["make_engine"]("prefill", kv_quant="int8")
    req2 = _park_one(pre_q, s["trace"][0], s["max_new"])
    art2 = pre_q.export_handoff(req2.id)
    with pytest.raises(ValueError, match="kv-quant"):
        s["make_engine"]("decode").import_handoff(art2, request_id="y#a1")
    pre_q.release_handoff(req2.id)


def test_disagg_int8_decode_replica_spec_device_parity(tiny_disagg_setup):
    """Device-resident speculation on an int8 decode replica: the import
    warms the draft's dense fp cache from the DEQUANTIZED blocks, the
    chain resumes mid-stream, and the tokens match the co-located int8
    engine."""
    s = tiny_disagg_setup
    both = s["make_engine"]("both", kv_quant="int8")
    r0 = both.submit(s["trace"][0], max_new_tokens=s["max_new"])
    both.run_until_drained()
    base = list(both.poll(r0.id).tokens)
    pre = s["make_engine"]("prefill", kv_quant="int8")
    dec = s["make_engine"]("decode", kv_quant="int8", speculate_gamma=2,
                           speculate_device=True)
    req = _park_one(pre, s["trace"][0], s["max_new"])
    new = dec.import_handoff(pre.export_handoff(req.id),
                             request_id=req.id + "#a1")
    pre.release_handoff(req.id)
    dec.run_until_drained()
    assert list(dec.poll(new.id).tokens) == base
    assert dec.metrics.spec_host_syncs_per_token is not None


def test_disagg_decode_replica_speculation_parity(tiny_disagg_setup):
    """Self-draft speculation on the decode replica: the import warms the
    draft cache from the artifact's blocks, and the accept-prefix rule
    keeps the resumed stream exact — same tokens as the plain baseline."""
    s = tiny_disagg_setup
    pre = s["make_engine"]("prefill")
    dec = s["make_engine"]("decode", speculate_gamma=2)
    req = _park_one(pre, s["trace"][0], s["max_new"])
    art = pre.export_handoff(req.id)
    new = dec.import_handoff(art, request_id=req.id + "#a1")
    pre.release_handoff(req.id)
    dec.run_until_drained()
    assert list(dec.poll(new.id).tokens) == s["baseline"][0]


# -- router: phase-aware placement and the handoff hop -----------------------


def test_router_places_submissions_on_prefill_only(tiny_disagg_setup):
    s = tiny_disagg_setup
    pre = EngineReplica("prefill-0", s["make_engine"]("prefill"))
    dec = EngineReplica("decode-0", s["make_engine"]("decode"))
    router = Router([pre, dec], policy="least_loaded")
    assert router.disaggregated
    for src in s["trace"][:2]:
        router.submit(src, max_new_tokens=s["max_new"])
    assert pre.engine.queue.depth + pre.engine.active_requests == 2
    assert dec.engine.queue.depth + dec.engine.active_requests == 0


def test_router_disagg_hop_parity_and_ledger(tiny_disagg_setup):
    """End-to-end through the router: every stream prefills on
    prefill-0, hops through the store codec, finishes on decode-0 —
    zero drops, token parity, and the phase ledger records the hop as
    its own ``handoff_s`` phase (co-located entries keep the plain
    five-phase shape)."""
    s = tiny_disagg_setup
    router = Router([EngineReplica("prefill-0", s["make_engine"]("prefill")),
                     EngineReplica("decode-0", s["make_engine"]("decode"))],
                    policy="least_loaded")
    rids = _route_all(router, s["trace"], s["max_new"])
    router.run_until_drained()
    results = [router.result(rid) for rid in rids]
    assert all(r["state"] == "done" for r in results)
    assert [r["tokens"] for r in results] == s["baseline"]
    stats = router.stats()
    assert stats["dropped_requests"] == 0
    assert stats["handoffs"] == len(rids)
    assert stats["handoff_bytes"] > 0
    assert stats["replicas"]["prefill-0"]["phase"] == "prefill"
    assert stats["replicas"]["decode-0"]["phase"] == "decode"
    for rid in rids:
        entry = router.ledger[rid]
        assert entry["replicas"] == ["prefill-0", "decode-0"]
        assert entry["phases"]["handoff_s"] >= 0.0
        assert entry["phases"]["prefill_s"] is not None
    # Co-located control: same trace, no hop, no handoff_s key.
    co = Router([EngineReplica("replica-0", s["make_engine"]("both"))],
                policy="least_loaded")
    co_rids = _route_all(co, s["trace"], s["max_new"])
    co.run_until_drained()
    assert [co.result(r)["tokens"] for r in co_rids] == s["baseline"]
    for rid in co_rids:
        assert set(co.ledger[rid]["phases"]) == {
            "queue_wait_s", "prefill_s", "decode_s", "stall_s", "emit_s"}


def test_router_disagg_chaos_kill_decode_replica(tiny_disagg_setup):
    """A decode replica dies mid-decode: its streams are evacuated,
    re-prefilled, and hop to the surviving decode replica — zero drops
    and the aggregate stays token-identical."""
    s = tiny_disagg_setup
    plan = FaultPlan([FaultSpec(op="step", key="decode-0", kind="crash",
                                at_calls=(3,))])
    reps = [
        EngineReplica("prefill-0", s["make_engine"]("prefill"),
                      fault_plan=plan),
        EngineReplica("decode-0", s["make_engine"]("decode"),
                      fault_plan=plan),
        EngineReplica("decode-1", s["make_engine"]("decode"),
                      fault_plan=plan),
    ]
    router = Router(reps, policy="least_loaded")
    rids = _route_all(router, s["trace"], s["max_new"])
    router.run_until_drained()
    assert reps[1].state is ReplicaState.DOWN
    assert router.evacuations >= 1
    results = [router.result(rid) for rid in rids]
    assert all(r["state"] == "done" for r in results)
    assert router.stats()["dropped_requests"] == 0
    assert [r["tokens"] for r in results] == s["baseline"]
    # The evacuated streams re-prefilled and hopped a second time.
    assert router.stats()["handoffs"] > len(rids) - 1


def test_rolling_upgrade_disagg_drains_decode_first(tiny_disagg_setup):
    """Phase-aware rollout: decode replicas upgrade before prefill ones
    (new weights are probed on the decode path before prefill produces
    new-weight artifacts), probes release parked prefill state, and the
    fleet keeps serving with token parity afterwards."""
    s = tiny_disagg_setup
    router = Router([EngineReplica("prefill-0", s["make_engine"]("prefill")),
                     EngineReplica("decode-0", s["make_engine"]("decode"))],
                    policy="least_loaded")
    report = rolling_upgrade(router, s["variables"])
    assert report.ok and len(report.upgraded) == 2
    assert [r.replica for r in report.results] == \
        ["decode-0", "prefill-0"]
    assert [r.phase for r in report.results] == ["decode", "prefill"]
    assert all(r.swapped and r.probe_ok for r in report.results)
    for rid in router.replica_ids():
        assert router.replica(rid).state is ReplicaState.HEALTHY
    rids = _route_all(router, s["trace"], s["max_new"])
    router.run_until_drained()
    assert [router.result(r)["tokens"] for r in rids] == s["baseline"]
    assert router.stats()["dropped_requests"] == 0


# -- bench, CLI, report surfaces ---------------------------------------------


def test_fleet_bench_rejects_lopsided_disagg_and_bad_mix():
    from deeplearning_cfn_tpu.fleet.bench import run_fleet_bench

    with pytest.raises(ValueError, match="prefill"):
        run_fleet_bench(prefill_replicas=1, decode_replicas=0, smoke=True)
    with pytest.raises(ValueError, match="trace mix"):
        run_fleet_bench(trace_mix="decode-heavy", smoke=True)


@pytest.mark.slow
def test_fleet_bench_disagg_smoke_contract():
    """The bench contract run: a 1+1 disagg fleet is token-identical to
    both the single-engine oracle and a co-located fleet on the same
    trace, drops nothing, and reports the handoff economics."""
    from deeplearning_cfn_tpu.fleet.bench import run_fleet_bench

    r = run_fleet_bench(smoke=True, prefill_replicas=1, decode_replicas=1)
    assert r["prefill_replicas"] == 1 and r["decode_replicas"] == 1
    assert r["dropped_requests"] == 0
    assert r["token_identical"] is True
    assert r["token_identical_colocated"] is True
    assert r["goodput_sum_ok"] is True
    assert r["handoffs"] >= 1 and r["handoff_bytes"] > 0
    assert r["handoff_latency_p50_s"] is not None
    assert {row["phase"] for row in r["per_replica"]} == \
        {"prefill", "decode"}


def test_cli_disagg_flags_parse():
    from deeplearning_cfn_tpu.cli.main import build_parser, main

    parser = build_parser()
    up = parser.parse_args(["fleet", "up", "--preset", "p",
                            "--requests", "r.jsonl",
                            "--prefill", "2", "--decode", "3",
                            "--kv-block-size", "8"])
    assert up.fn.__name__ == "_cmd_fleet_up"
    assert up.prefill == 2 and up.decode == 3 and up.kv_block_size == 8
    be = parser.parse_args(["bench", "--fleet", "--smoke",
                            "--fleet-prefill", "1", "--fleet-decode", "1",
                            "--trace-mix", "prefill-heavy"])
    assert be.fleet_prefill == 1 and be.fleet_decode == 1
    assert be.trace_mix == "prefill-heavy"
    # A prefill pool without a decode pool is refused up front.
    assert main(["fleet", "up", "--preset", "p", "--requests", "r.jsonl",
                 "--prefill", "2"]) == 2


def test_summarize_fleet_reports_phase_and_queue_depth(tmp_path):
    """obs summarize --fleet over a disagg run dir: per-replica phase
    roles and the per-phase queue depth aggregate, both in the dict and
    in the rendered report."""
    from deeplearning_cfn_tpu.obs.report import (
        render_fleet_report,
        summarize_fleet,
    )

    root = tmp_path / "run"
    for name, phase, depth in (("prefill-0", "prefill", 3),
                               ("decode-0", "decode", 1)):
        d = root / name
        d.mkdir(parents=True)
        rec = {"serve_submitted": 4, "serve_admitted": 4,
               "serve_completed": 4, "serve_tokens_generated": 16,
               "serve_tokens_per_sec": 8.0, "serve_queue_depth": depth,
               "phase": phase, "replica": name}
        (d / "metrics.jsonl").write_text(json.dumps(rec) + "\n")
    summary = summarize_fleet(str(root))
    assert summary["replicas"]["prefill-0"]["serve"]["phase"] == "prefill"
    assert summary["replicas"]["decode-0"]["serve"]["phase"] == "decode"
    assert summary["fleet"]["queue_depth_by_phase"] == \
        {"prefill": 3, "decode": 1}
    text = render_fleet_report(summary)
    assert "queue depth by phase: decode=1  prefill=3" in text
    assert "phase prefill (q 3)" in text
    assert "phase decode (q 1)" in text


def test_fleet_status_cli_on_disagg_run(tmp_path, capsys):
    from deeplearning_cfn_tpu.cli.main import main

    root = tmp_path / "run"
    for name, phase in (("prefill-0", "prefill"), ("decode-0", "decode")):
        d = root / name
        d.mkdir(parents=True)
        rec = {"serve_submitted": 2, "serve_completed": 2,
               "serve_tokens_generated": 8, "serve_queue_depth": 0,
               "phase": phase}
        (d / "metrics.jsonl").write_text(json.dumps(rec) + "\n")
    assert main(["fleet", "status", str(root)]) == 0
    out = capsys.readouterr().out
    assert "fleet 2 replica(s)" in out
    assert "phase prefill" in out and "phase decode" in out
