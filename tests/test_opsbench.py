"""Op-level microbench harness: sync contract + suite smoke.

The suites' real purpose is chip diagnosis (the 0.05-MFU detection-step
breakdown); these tests pin the harness mechanics so the module stays
exercised — timing sanity on CPU, not performance claims.
"""

import jax
import jax.numpy as jnp

from deeplearning_cfn_tpu.opsbench import suite_resnet, timed_scalar


def test_timed_scalar_measures_work():
    # A jitted matmul chain: timing must be positive and scale roughly
    # with the step count's work (not collapse to dispatch-only time).
    x = jnp.ones((128, 128))

    @jax.jit
    def f(x, tok):
        y = x + tok
        for _ in range(4):
            y = y @ x
        return jnp.sum(y * 1e-9)

    ms = timed_scalar(f, x, steps=3, warmup=1)
    assert ms > 0.0


def test_timed_scalar_orders_by_cost():
    # The timing must reflect actual device work: a 50-matmul chain over
    # 512² must measure slower than a single 64² matmul. Contrast is ~3
    # orders of magnitude, so this is robust to scheduler noise.
    small = jnp.ones((64, 64))
    big = jnp.ones((512, 512))

    @jax.jit
    def f_small(x, tok):
        return jnp.sum((x + tok) @ x) * 1e-9

    @jax.jit
    def f_big(x, tok):
        y = x + tok
        for _ in range(50):
            y = y @ x * 1e-3
        return jnp.sum(y) * 1e-9

    ms_small = timed_scalar(f_small, small, steps=3, warmup=1)
    ms_big = timed_scalar(f_big, big, steps=3, warmup=1)
    assert ms_big > ms_small


def test_suite_resnet_smoke():
    # Tiny shapes: both stem variants build, run fwd+bwd, and report
    # positive times. (CPU; the A/B question itself is a TPU matter.)
    results = suite_resnet(batch=2, steps=1, image_size=64)
    assert set(results) == {"resnet50", "resnet50_s2d"}
    assert all(v > 0 for v in results.values())
