"""Tests for L0/L1: topology catalog, stack store, provisioner, runtime
contract (SURVEY.md §5 tiers 1–2 — the provisioner fixture strategy)."""

import os
import socket
import subprocess
import sys
import textwrap

import numpy as np
import pytest

from deeplearning_cfn_tpu.config import StackConfig
from deeplearning_cfn_tpu.provision import (
    DryRunProvisioner,
    ProvisionError,
    StackStatus,
    StackStore,
    create_stack,
    delete_stack,
    slice_topology,
)
from deeplearning_cfn_tpu.runtime import cluster as rt


# -- topology ---------------------------------------------------------------


def test_slice_topology_v5p():
    t = slice_topology("v5p-256")
    assert t.num_chips == 256
    assert t.chips_per_host == 4
    assert t.num_hosts == 64
    assert len(t.ici_mesh) == 3
    prod = 1
    for d in t.ici_mesh:
        prod *= d
    assert prod == 256


def test_slice_topology_generations():
    assert slice_topology("v4-8").num_hosts == 2
    assert slice_topology("v5e-16").chips_per_host == 8
    assert slice_topology("v5e-16").num_hosts == 2
    # v2/v3 suffix counts TensorCores (2/chip).
    assert slice_topology("v3-8").num_chips == 4
    assert slice_topology("v3-8").num_hosts == 1


@pytest.mark.parametrize("bad", ["v5p", "x5-8", "v5p-0", "v99-8", "v5e-9999"])
def test_slice_topology_rejects(bad):
    with pytest.raises(ValueError):
        slice_topology(bad)


# -- stack store ------------------------------------------------------------


def test_stack_store_roundtrip(tmp_path):
    store = StackStore(str(tmp_path))
    cfg = StackConfig(name="t1", slice_type="v5p-8", provisioner="dryrun")
    state = DryRunProvisioner().create(cfg)
    store.save(state)
    loaded = store.load("t1")
    assert loaded.name == "t1"
    assert loaded.slice_type == "v5p-8"
    assert loaded.status == StackStatus.CREATE_IN_PROGRESS
    assert len(loaded.hosts) == 2
    assert [s.name for s in store.list()] == ["t1"]
    store.delete("t1")
    assert store.load_or_none("t1") is None


def test_stack_store_rejects_bad_names(tmp_path):
    store = StackStore(str(tmp_path))
    for bad in ["", "../evil", ".hidden"]:
        with pytest.raises(ValueError):
            store._path(bad)


# -- provisioner flows ------------------------------------------------------


def _mk_cfg(tmp_path, **kw):
    defaults = dict(name="demo", slice_type="v5p-8", provisioner="dryrun",
                    state_dir=str(tmp_path), create_timeout_s=60)
    defaults.update(kw)
    return StackConfig(**defaults)


def test_create_stack_happy_path(tmp_path):
    cfg = _mk_cfg(tmp_path)
    seen = []
    state = create_stack(cfg, provisioner=DryRunProvisioner(ready_after_polls=3),
                         on_status=lambda s: seen.append(
                             {h.state for h in s.hosts}),
                         _sleep=lambda s: None)
    assert state.status == StackStatus.CREATE_COMPLETE
    assert state.ready
    assert {h.state for h in state.hosts} == {"READY"}
    # Staged readiness was observed (CREATING before READY).
    assert {"CREATING"} in seen
    # Hostfile written with one address per host — the reference's
    # $DEEPLEARNING_WORKERS_PATH contract.
    hosts = rt.read_hostfile(state.hostfile)
    assert len(hosts) == 2
    # Store agrees.
    assert StackStore(str(tmp_path)).load("demo").ready


def test_create_stack_duplicate_rejected(tmp_path):
    cfg = _mk_cfg(tmp_path)
    create_stack(cfg, provisioner=DryRunProvisioner(), _sleep=lambda s: None)
    with pytest.raises(ProvisionError, match="already exists"):
        create_stack(cfg, provisioner=DryRunProvisioner(),
                     _sleep=lambda s: None)


def test_create_stack_partial_failure(tmp_path):
    """A host that never becomes healthy fails the stack — the
    WaitCondition-timeout contract: no partial cluster is ever handed out."""
    cfg = _mk_cfg(tmp_path)
    with pytest.raises(ProvisionError, match="failed to assemble"):
        create_stack(cfg, provisioner=DryRunProvisioner(fail_hosts=[1]),
                     _sleep=lambda s: None)
    assert StackStore(str(tmp_path)).load("demo").status == \
        StackStatus.CREATE_FAILED


def test_create_stack_timeout(tmp_path):
    cfg = _mk_cfg(tmp_path, create_timeout_s=0)
    with pytest.raises(ProvisionError, match="timed out"):
        create_stack(cfg, provisioner=DryRunProvisioner(ready_after_polls=99),
                     _sleep=lambda s: None)


def test_delete_stack(tmp_path):
    cfg = _mk_cfg(tmp_path)
    state = create_stack(cfg, provisioner=DryRunProvisioner(),
                         _sleep=lambda s: None)
    hostfile = state.hostfile
    assert os.path.exists(hostfile)
    delete_stack("demo", store=StackStore(str(tmp_path)))
    assert not os.path.exists(hostfile)
    assert StackStore(str(tmp_path)).load_or_none("demo") is None


# -- runtime contract -------------------------------------------------------


def test_hostfile_roundtrip(tmp_path):
    path = str(tmp_path / "hosts")
    rt.write_hostfile(path, ["10.0.0.1", "10.0.0.2"])
    assert rt.read_hostfile(path) == ["10.0.0.1", "10.0.0.2"]


def test_cluster_env_and_back(tmp_path):
    hostfile = rt.write_hostfile(str(tmp_path / "hosts"),
                                 ["10.0.0.1", "10.0.0.2", "10.0.0.3"])
    spec = rt.ClusterSpec(hosts=["10.0.0.1", "10.0.0.2", "10.0.0.3"],
                          chips_per_host=4, hostfile=hostfile)
    env = rt.cluster_env(spec, process_id=2)
    assert env[rt.ENV_WORKERS_COUNT] == "3"
    assert env[rt.ENV_COORDINATOR] == "10.0.0.1:8476"
    assert env[rt.ENV_PROCESS_ID] == "2"
    # A worker process reconstructs the same spec from its environment.
    spec2 = rt.current_cluster(env)
    assert spec2 is not None
    assert spec2.hosts == spec.hosts
    assert spec2.process_id == 2
    assert spec2.coordinator == "10.0.0.1:8476"
    assert spec2.is_multi_host


def test_current_cluster_absent_contract():
    assert rt.current_cluster({}) is None


def test_initialize_single_host_noop():
    spec = rt.initialize(rt.ClusterSpec(hosts=["localhost"]))
    assert not spec.is_multi_host


def test_cluster_spec_validation():
    with pytest.raises(ValueError):
        rt.ClusterSpec(hosts=[]).validate()
    with pytest.raises(ValueError):
        rt.ClusterSpec(hosts=["a"], process_id=1).validate()


# -- real multi-process rendezvous -----------------------------------------


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_rendezvous(tmp_path):
    """Two real OS processes join through the env contract and see each
    other's devices — jax.distributed over the launcher's env block, the
    rebuild's MPI-rendezvous replacement, minus TPUs."""
    port = _free_port()
    spec = rt.ClusterSpec(hosts=["127.0.0.1", "127.0.0.1"],
                          coordinator_port=port)
    script = textwrap.dedent("""
        import jax
        # The image's sitecustomize pre-registers a TPU plugin; env var alone
        # is too late (same workaround as tests/conftest.py).
        jax.config.update("jax_platforms", "cpu")
        from deeplearning_cfn_tpu.runtime import initialize
        spec = initialize(timeout_s=60)
        assert spec.is_multi_host, spec
        assert jax.process_count() == 2, jax.process_count()
        total = jax.device_count()
        local = jax.local_device_count()
        assert total == 2 * local, (total, local)
        print("RENDEZVOUS_OK", jax.process_index(), total)
    """)
    env_base = {k: v for k, v in os.environ.items()}
    env_base["JAX_PLATFORMS"] = "cpu"
    # One fake device per process keeps startup fast.
    env_base["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    procs = []
    try:
        for pid in range(2):
            env = {**env_base, **rt.cluster_env(spec, pid)}
            procs.append(subprocess.Popen(
                [sys.executable, "-c", script], env=env,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
                cwd=os.path.dirname(
                    os.path.dirname(os.path.abspath(__file__))),
            ))
        outs = [p.communicate(timeout=120)[0] for p in procs]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for p, out in zip(procs, outs):
        assert p.returncode == 0, f"worker failed:\n{out}"
        assert "RENDEZVOUS_OK" in out


# ONE config for both sides of the 1-proc vs 2-proc equivalence (the
# comparison is vacuous if the two runs can drift apart): built from this
# override list by `_two_proc_cfg` in-test and by the worker (which
# receives it via DLCFN_TEST_CFG).
_TWO_PROC_OVERRIDES = [
    "model.num_classes=10", "data.image_size=16",
    "data.num_train_examples=32", "data.prefetch=0",
    "train.global_batch=32", "train.dtype=float32",
    "optimizer.name=momentum", "optimizer.momentum=0.9",
    "schedule.name=constant", "schedule.base_lr=0.05",
    "schedule.warmup_steps=0",
]


def _two_proc_cfg(overrides):
    from deeplearning_cfn_tpu.config import (
        DataConfig, ExperimentConfig, ModelConfig, apply_overrides)

    cfg = ExperimentConfig(
        model=ModelConfig(name="resnet20"),
        data=DataConfig(name="imagenet"))
    return apply_overrides(cfg, overrides)


_TRAIN_WORKER = """
import json, os, sys
import jax
jax.config.update("jax_platforms", "cpu")
from deeplearning_cfn_tpu.runtime import initialize
spec = initialize(timeout_s=60)
assert jax.process_count() == 2

import numpy as np
from deeplearning_cfn_tpu.config import (DataConfig, ExperimentConfig,
    ModelConfig, apply_overrides)
from deeplearning_cfn_tpu.data import build_pipeline
from deeplearning_cfn_tpu.parallel.mesh import build_mesh, local_batch_size
from deeplearning_cfn_tpu.train import create_train_state
from deeplearning_cfn_tpu.train.optim import build_optimizer, build_schedule
from deeplearning_cfn_tpu.train.task import build_task
from deeplearning_cfn_tpu.train.trainer import Trainer

out_dir = os.environ["DLCFN_TEST_OUT"]
GB, STEPS = 32, 3
cfg = apply_overrides(
    ExperimentConfig(model=ModelConfig(name="resnet20"),
                     data=DataConfig(name="imagenet")),
    json.loads(os.environ["DLCFN_TEST_CFG"]))
assert cfg.train.global_batch == GB
mesh = build_mesh(cfg.mesh)
lb = local_batch_size(GB, mesh)
assert lb == GB // 2, lb  # each host feeds exactly half

pipe = build_pipeline(cfg.data, lb, 10, seed=0, train=True)
pidx = jax.process_index()
with open(os.path.join(out_dir, f"idx_{pidx}.json"), "w") as f:
    json.dump([int(i) for i in pipe._epoch_indices(0)], f)

task = build_task(cfg)
tx = build_optimizer(cfg.optimizer, build_schedule(cfg.schedule, 100, GB, 0))
state = create_train_state(jax.random.PRNGKey(0), task.init, tx, mesh)
tr = Trainer(cfg, task.loss_fn, tx, mesh=mesh, donate=False)
it = pipe.epochs()
for _ in range(STEPS):
    state, m = tr.train_step(state, tr.device_batch(next(it)),
                             jax.random.PRNGKey(1))
loss = float(m["loss"])
if pidx == 0:
    leaves = jax.tree_util.tree_leaves(jax.device_get(state.params))
    np.savez(os.path.join(out_dir, "params_2proc.npz"),
             **{str(i): np.asarray(a) for i, a in enumerate(leaves)})
print("TRAIN2P_OK", pidx, loss)
"""


@pytest.mark.slow
def test_two_process_train_shards_and_matches_single(tmp_path):
    """The launcher→trainer seam end to end (r03 verdict, Next #7): two
    real processes train CIFAR-shaped ResNet-20 for 3 steps and must (a)
    each feed ONLY their addressable half of the shared epoch permutation,
    (b) cover the global batch exactly once between them, and (c) land on
    the same final params as the same run on one 8-device process — the
    multi-HOST analogue of the in-process DP equivalence tests."""
    port = _free_port()
    spec = rt.ClusterSpec(hosts=["127.0.0.1", "127.0.0.1"],
                          coordinator_port=port)
    env_base = dict(os.environ)
    env_base["JAX_PLATFORMS"] = "cpu"
    env_base["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    env_base["DLCFN_TEST_OUT"] = str(tmp_path)
    import json as _json

    env_base["DLCFN_TEST_CFG"] = _json.dumps(_TWO_PROC_OVERRIDES)
    procs = []
    try:
        for pid in range(2):
            env = {**env_base, **rt.cluster_env(spec, pid)}
            procs.append(subprocess.Popen(
                [sys.executable, "-c", _TRAIN_WORKER], env=env,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
                cwd=os.path.dirname(
                    os.path.dirname(os.path.abspath(__file__))),
            ))
        outs = [p.communicate(timeout=560)[0] for p in procs]
    finally:
        # A deadlocked rendezvous must not orphan workers spinning in the
        # collective client (and holding the coordinator port).
        for p in procs:
            if p.poll() is None:
                p.kill()
    for p, out in zip(procs, outs):
        assert p.returncode == 0, f"worker failed:\n{out[-3000:]}"
        assert "TRAIN2P_OK" in out

    # (a)+(b): disjoint halves covering the dataset exactly once.
    import json as _json

    idx0 = _json.load(open(tmp_path / "idx_0.json"))
    idx1 = _json.load(open(tmp_path / "idx_1.json"))
    assert len(idx0) == len(idx1) == 16
    assert set(idx0).isdisjoint(idx1)
    assert set(idx0) | set(idx1) == set(range(32))

    # (c): the same run, single process on the in-test 8-device mesh —
    # the SAME config object both sides (shared override list).
    import jax

    from deeplearning_cfn_tpu.data import build_pipeline
    from deeplearning_cfn_tpu.parallel.mesh import build_mesh, \
        local_batch_size
    from deeplearning_cfn_tpu.train import create_train_state
    from deeplearning_cfn_tpu.train.optim import build_optimizer, \
        build_schedule
    from deeplearning_cfn_tpu.train.task import build_task
    from deeplearning_cfn_tpu.train.trainer import Trainer

    cfg = _two_proc_cfg(_TWO_PROC_OVERRIDES)
    mesh = build_mesh(cfg.mesh)
    task = build_task(cfg)
    tx = build_optimizer(cfg.optimizer,
                         build_schedule(cfg.schedule, 100, 32, 0))
    state = create_train_state(jax.random.PRNGKey(0), task.init, tx, mesh)
    tr = Trainer(cfg, task.loss_fn, tx, mesh=mesh, donate=False)
    pipe = build_pipeline(cfg.data, local_batch_size(32, mesh), 10,
                          seed=0, train=True)
    it = pipe.epochs()
    for _ in range(3):
        state, m = tr.train_step(state, tr.device_batch(next(it)),
                                 jax.random.PRNGKey(1))
    ref_leaves = jax.tree_util.tree_leaves(jax.device_get(state.params))
    with np.load(tmp_path / "params_2proc.npz") as z:
        got = [z[str(i)] for i in range(len(ref_leaves))]
    for i, (a, b) in enumerate(zip(ref_leaves, got)):
        np.testing.assert_allclose(
            np.asarray(a), b, rtol=1e-4, atol=1e-6,
            err_msg=f"leaf {i} diverged between 1-proc and 2-proc runs")


# -- GCP provisioner (offline: gcloud invocations pinned, not run) ----------


class _FakeGcloud:
    """Capture GcpProvisioner._run invocations and script its outputs."""

    def __init__(self, outputs=()):
        self.calls = []
        self.outputs = list(outputs)

    def __call__(self, *args):
        self.calls.append(args)
        return self.outputs.pop(0) if self.outputs else "{}"


def _gcp(monkeypatch, outputs=()):
    from deeplearning_cfn_tpu.provision.provisioner import GcpProvisioner

    monkeypatch.setattr("shutil.which", lambda name: "/usr/bin/gcloud")
    prov = GcpProvisioner()
    fake = _FakeGcloud(outputs)
    prov._run = fake
    return prov, fake


def test_gcp_create_command_line(monkeypatch):
    """The create call must carry every config knob — this is the CFN
    template-parameters contract, TPU-shaped."""
    prov, fake = _gcp(monkeypatch)
    cfg = StackConfig(name="prod", slice_type="v5p-16", zone="us-east5-a",
                      project="my-proj", runtime_version="tpu-vm-custom",
                      preemptible=True, provisioner="gcp")
    state = prov.create(cfg)
    (args,) = fake.calls
    assert args[:5] == ("compute", "tpus", "tpu-vm", "create", "prod")
    assert "--zone=us-east5-a" in args
    assert "--version=tpu-vm-custom" in args
    assert "--project=my-proj" in args
    assert "--preemptible" in args
    assert "--async" in args
    assert any(a.startswith("--accelerator-type=") for a in args)
    assert state.status == StackStatus.CREATE_IN_PROGRESS
    assert len(state.hosts) == 4  # v5p-16 = 4 hosts


def test_gcp_refresh_parses_describe(monkeypatch):
    import json as _json

    desc = _json.dumps({
        "state": "READY",
        "networkEndpoints": [
            {"ipAddress": "10.0.0.2",
             "accessConfig": {"externalIp": "34.1.2.3"}},
            {"ipAddress": "10.0.0.3", "accessConfig": {}},
        ],
    })
    prov, fake = _gcp(monkeypatch, outputs=[desc])
    from deeplearning_cfn_tpu.provision import StackState

    state = StackState(name="prod", slice_type="v5p-8", zone="z")
    state = prov.refresh(state)
    assert [h.internal_ip for h in state.hosts] == ["10.0.0.2", "10.0.0.3"]
    assert [h.external_ip for h in state.hosts] == ["34.1.2.3", ""]
    assert all(h.state == "READY" for h in state.hosts)
    assert fake.calls[0][:5] == ("compute", "tpus", "tpu-vm", "describe",
                                 "prod")


def test_gcp_delete_command_line(monkeypatch):
    from deeplearning_cfn_tpu.provision import StackState

    prov, fake = _gcp(monkeypatch)
    state = StackState(name="prod", slice_type="v5p-8", zone="z",
                       project="my-proj")
    prov.delete(state)
    (args,) = fake.calls
    assert args[:5] == ("compute", "tpus", "tpu-vm", "delete", "prod")
    assert "--quiet" in args and "--project=my-proj" in args


def test_gcp_run_raises_on_failure(monkeypatch):
    from deeplearning_cfn_tpu.provision.provisioner import GcpProvisioner

    monkeypatch.setattr("shutil.which", lambda name: "/usr/bin/gcloud")
    prov = GcpProvisioner()

    class Proc:
        returncode = 1
        stderr = "quota exceeded"
        stdout = ""

    monkeypatch.setattr("subprocess.run", lambda *a, **k: Proc())
    with pytest.raises(ProvisionError, match="quota exceeded"):
        prov._run("compute", "tpus", "list")
