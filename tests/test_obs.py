"""obs/ subsystem: metrics registry, span tracer, sinks, run reports.

Parity tests pin the migration contracts: ServeMetrics keeps its exact
attribute surface and snapshot keys/values after moving onto the registry,
and percentile() keeps its interpolation semantics. The report tests run
against COMMITTED fixture logs generated from real train/serve/launcher
runs (tests/fixtures/obs/), so `obs summarize` is tested on the actual
byte shapes the runners emit.
"""

import json
import os

import pytest

from deeplearning_cfn_tpu.metrics.jsonl import MetricsWriter
from deeplearning_cfn_tpu.obs import (
    AlertingWriter,
    JsonlFollower,
    JsonlSink,
    MemorySink,
    MetricsRegistry,
    SloEngine,
    TailState,
    Tracer,
    build_trace,
    check_run,
    configured,
    diff_runs,
    exponential_buckets,
    export_trace,
    get_tracer,
    load_rules,
    obs_enabled,
    percentile,
    render_diff,
    render_prometheus,
    render_report,
    set_enabled,
    span,
    summarize,
    tail,
    validate_trace,
    write_prometheus,
)
from deeplearning_cfn_tpu.obs.diff import direction
from deeplearning_cfn_tpu.obs.slo import Rule, RuleError
from deeplearning_cfn_tpu.serve.metrics import ServeMetrics

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures", "obs")


# -- percentile edge cases (satellite: never raise, never NaN) ---------------


def test_percentile_empty_returns_none():
    assert percentile([], 50) is None
    assert percentile([], 95) is None


def test_percentile_single_sample_is_that_sample():
    for q in (0, 50, 95, 100):
        assert percentile([0.25], q) == 0.25


def test_percentile_all_ties_no_nan():
    p = percentile([2.0] * 7, 95)
    assert p == 2.0
    assert p == p  # not NaN


def test_percentile_interpolates():
    # rank = (n-1) * q/100; for [1..5], p50 = 3.0, p95 = 4.8
    xs = [5.0, 1.0, 4.0, 2.0, 3.0]
    assert percentile(xs, 50) == 3.0
    assert percentile(xs, 95) == pytest.approx(4.8)


def test_percentile_does_not_mutate_input():
    xs = [3.0, 1.0, 2.0]
    percentile(xs, 50)
    assert xs == [3.0, 1.0, 2.0]


# -- registry + instruments --------------------------------------------------


def test_counter_inc_and_monotonicity():
    reg = MetricsRegistry()
    c = reg.counter("reqs", "requests")
    assert c.value() == 0
    c.inc()
    c.inc(3)
    assert c.value() == 4
    with pytest.raises(ValueError):
        c.inc(-1)


def test_counter_labels_are_independent_series():
    reg = MetricsRegistry()
    c = reg.counter("reqs", "requests")
    c.inc(2, state="ok")
    c.inc(5, state="err")
    assert c.value(state="ok") == 2
    assert c.value(state="err") == 5
    assert c.labels(state="ok").value() == 2
    assert c.series()[(("state", "ok"),)] == 2


def test_gauge_set_and_inc():
    reg = MetricsRegistry()
    g = reg.gauge("depth", "queue depth")
    assert g.value() is None
    g.set(7)
    assert g.value() == 7
    g.inc(-2)
    assert g.value() == 5


def test_registry_get_or_create_returns_same_instrument():
    reg = MetricsRegistry()
    assert reg.counter("x", "d") is reg.counter("x", "d")


def test_registry_kind_mismatch_raises_typeerror():
    reg = MetricsRegistry()
    reg.counter("x", "d")
    with pytest.raises(TypeError):
        reg.gauge("x", "d")
    with pytest.raises(TypeError):
        reg.histogram("x", "d")


def test_exponential_buckets_shape():
    assert exponential_buckets(start=1e-3, factor=2.0, count=4) == \
        (1e-3, 2e-3, 4e-3, 8e-3)
    with pytest.raises(ValueError):
        exponential_buckets(start=0)


def test_histogram_buckets_and_exact_percentiles():
    reg = MetricsRegistry()
    h = reg.histogram("lat", "latency", buckets=(0.1, 1.0))
    for v in (0.05, 0.5, 5.0):
        h.observe(v)
    assert h.count() == 3
    assert h.sum() == pytest.approx(5.55)
    # exact percentiles come from retained samples, not bucket edges
    assert h.percentile(50) == 0.5
    assert h.samples() == [0.05, 0.5, 5.0]
    ((_, series),) = h.series().items()
    assert series.bucket_counts == [1, 1, 1]  # per-bucket incl +Inf


def test_histogram_empty_percentile_is_none():
    reg = MetricsRegistry()
    h = reg.histogram("lat", "latency")
    assert h.percentile(50) is None
    assert h.mean() is None
    assert h.count() == 0 and h.sum() == 0.0 and h.samples() == []


def test_histogram_keep_samples_false_drops_raw_series():
    reg = MetricsRegistry()
    h = reg.histogram("hot", "hot path", keep_samples=False)
    h.observe(0.2)
    assert h.count() == 1
    assert h.samples() == []
    assert h.percentile(50) is None  # no raw series -> no exact percentile


def test_histogram_labelled_series():
    reg = MetricsRegistry()
    h = reg.histogram("span_dur_s", "d")
    h.observe(0.1, name="a")
    h.observe(0.2, name="a")
    h.observe(9.0, name="b")
    assert h.count(name="a") == 2
    assert h.percentile(50, name="a") == pytest.approx(0.15)
    assert h.count(name="b") == 1


def test_registry_snapshot_is_json_able():
    reg = MetricsRegistry()
    reg.counter("c", "c").inc(2, state="ok")
    reg.gauge("g", "g").set(1.5)
    reg.histogram("h", "h").observe(0.2)
    snap = json.loads(json.dumps(reg.snapshot()))
    assert snap["c"]["kind"] == "counter"
    assert snap["c"]["series"]["state=ok"] == 2
    assert snap["h"]["series"][""]["count"] == 1
    assert snap["h"]["series"][""]["p50"] == 0.2


# -- span tracer -------------------------------------------------------------


@pytest.fixture()
def fresh_tracer():
    t = Tracer()
    configured(t)
    try:
        yield t
    finally:
        configured(None)
        set_enabled(None)


def test_span_ids_deterministic_from_one(fresh_tracer):
    sink = MemorySink()
    fresh_tracer.add_sink(sink)
    with span("a"):
        pass
    with span("b"):
        pass
    assert [r["span_id"] for r in sink.records] == [1, 2]
    assert all(r["parent_id"] is None for r in sink.records)


def test_span_nesting_sets_parent_id(fresh_tracer):
    sink = MemorySink()
    fresh_tracer.add_sink(sink)
    with span("outer"):
        with span("inner", step=3):
            pass
    inner, outer = sink.records  # inner closes (and is recorded) first
    assert inner["span"] == "inner"
    assert inner["parent_id"] == outer["span_id"]
    assert inner["step"] == 3
    assert outer["parent_id"] is None
    assert inner["dur_s"] <= outer["dur_s"]
    assert inner["t0_s"] >= outer["t0_s"]


def test_span_records_failure_and_reraises(fresh_tracer):
    sink = MemorySink()
    fresh_tracer.add_sink(sink)
    with pytest.raises(RuntimeError):
        with span("boom"):
            raise RuntimeError("x")
    (rec,) = sink.records
    assert rec["ok"] is False


def test_span_annotate_adds_attrs(fresh_tracer):
    sink = MemorySink()
    fresh_tracer.add_sink(sink)
    with span("ckpt.save", step=4) as sp:
        sp.annotate(retries=2)
    (rec,) = sink.records
    assert rec["step"] == 4
    assert rec["retries"] == 2


def test_spans_feed_duration_histogram(fresh_tracer):
    with span("work"):
        pass
    h = fresh_tracer.registry.histogram("span_dur_s", "span durations by name")
    assert h.count(name="work") == 1


def test_memory_sink_by_span(fresh_tracer):
    sink = MemorySink()
    fresh_tracer.add_sink(sink)
    with span("a"):
        pass
    with span("b"):
        pass
    assert [r["span"] for r in sink.by_span("a")] == ["a"]


def test_env_gate_disables_spans(fresh_tracer, monkeypatch):
    sink = MemorySink()
    fresh_tracer.add_sink(sink)
    monkeypatch.setenv("DLCFN_OBS_OFF", "1")
    assert not obs_enabled()
    with span("a") as sp:
        sp.annotate(ignored=True)  # null span: no-op, no raise
    assert sink.records == []
    monkeypatch.delenv("DLCFN_OBS_OFF")
    assert obs_enabled()
    with span("a"):
        pass
    assert len(sink.records) == 1


def test_set_enabled_overrides_env(fresh_tracer, monkeypatch):
    sink = MemorySink()
    fresh_tracer.add_sink(sink)
    monkeypatch.setenv("DLCFN_OBS_OFF", "1")
    set_enabled(True)  # programmatic override beats the env var
    with span("a"):
        pass
    assert len(sink.records) == 1
    set_enabled(False)
    with span("b"):
        pass
    assert len(sink.records) == 1


def test_get_tracer_returns_configured_default():
    t = Tracer()
    configured(t)
    try:
        assert get_tracer() is t
    finally:
        configured(None)
    assert get_tracer() is not t


def test_remove_sink_stops_delivery(fresh_tracer):
    sink = MemorySink()
    fresh_tracer.add_sink(sink)
    fresh_tracer.remove_sink(sink)
    fresh_tracer.remove_sink(sink)  # idempotent
    with span("a"):
        pass
    assert sink.records == []


# -- sinks -------------------------------------------------------------------


def test_jsonl_sink_writes_span_records(fresh_tracer, tmp_path):
    path = str(tmp_path / "m.jsonl")
    sink = JsonlSink(MetricsWriter(path, also_stdout=False))
    fresh_tracer.add_sink(sink)
    with span("a", step=1):
        pass
    sink.close()
    (line,) = open(path).read().splitlines()
    rec = json.loads(line)
    assert rec["span"] == "a" and rec["span_id"] == 1 and "ts" in rec


def test_render_prometheus_text_format():
    reg = MetricsRegistry()
    reg.counter("reqs_total", "requests").inc(3, state="ok")
    reg.gauge("depth", "queue depth").set(2)
    h = reg.histogram("lat_s", "latency", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    text = render_prometheus(reg)
    assert "# TYPE reqs_total counter" in text
    assert 'reqs_total{state="ok"} 3' in text
    assert "# TYPE depth gauge" in text
    assert "depth 2" in text
    assert 'lat_s_bucket{le="0.1"} 1' in text
    assert 'lat_s_bucket{le="1"} 2' in text
    assert 'lat_s_bucket{le="+Inf"} 2' in text
    assert "lat_s_count 2" in text
    assert "lat_s_sum 0.55" in text


def test_render_prometheus_escapes_label_values():
    reg = MetricsRegistry()
    reg.counter("c", "d").inc(1, msg='a"b\nc\\d')
    text = render_prometheus(reg)
    assert 'msg="a\\"b\\nc\\\\d"' in text


def test_write_prometheus_atomic(tmp_path):
    reg = MetricsRegistry()
    reg.counter("c", "d").inc()
    path = str(tmp_path / "metrics.prom")
    text = write_prometheus(reg, path)
    assert open(path).read() == text
    assert os.listdir(str(tmp_path)) == ["metrics.prom"]  # no tmp leftover


# -- ServeMetrics parity after the registry migration ------------------------


def _drive(m: ServeMetrics):
    m.record_submit()
    m.record_submit()
    m.record_admit(queue_wait_s=0.5)
    m.record_admit(queue_wait_s=1.5)
    m.record_first_token(0.2)
    m.record_finish("done", 2.0)
    m.record_step(active_rows=2, queue_depth=3, new_tokens=5,
                  step_time_s=0.01)
    m.record_step(active_rows=1, queue_depth=3, new_tokens=3,
                  step_time_s=0.03)
    m.record_reject(retry_after_s=0.25)


def test_serve_metrics_attribute_surface_parity():
    m = ServeMetrics(capacity=4, clock=lambda: 0.0)
    _drive(m)
    # the exact pre-migration attribute surface, live values
    assert m.submitted == 2 and isinstance(m.submitted, int)
    assert m.admitted == 2
    assert m.completed == 1
    assert m.rejected == 1
    assert m.cancelled == 0 and m.expired == 0
    assert m.tokens_generated == 8
    assert m.steps == 2 and m.windows == 2
    assert m.queue_wait_s == [0.5, 1.5]
    assert m.ttft_s == [0.2]
    assert m.latency_s == [2.0]
    assert m.step_latency_s == [0.01, 0.03]
    assert m.busy_time_s == pytest.approx(0.04)
    assert m.last_queue_depth == 3
    assert m.last_retry_after_s == 0.25
    assert m.mean_slot_occupancy == pytest.approx(0.375)
    assert m.mean_steps_per_window == 1.0
    assert m.tokens_per_sec == pytest.approx(8 / 0.04)
    assert m.ckpt_load_retries == 0


def test_serve_metrics_snapshot_keys_and_values_parity():
    m = ServeMetrics(capacity=4, clock=lambda: 0.0)
    _drive(m)
    snap = m.snapshot()
    # key set is the pre-migration JSONL contract
    assert set(snap) == {
        "serve_submitted", "serve_rejected", "serve_admitted",
        "serve_completed", "serve_cancelled", "serve_expired",
        "serve_steps", "serve_decode_windows", "serve_steps_per_window",
        "serve_queue_depth", "serve_slot_capacity", "serve_slot_occupancy",
        "serve_tokens_generated", "serve_tokens_per_sec",
        "serve_ckpt_load_retries", "serve_retry_after_hint_s",
        "serve_queue_wait_p50_s", "serve_queue_wait_p95_s",
        "serve_ttft_p50_s", "serve_ttft_p95_s",
        "serve_latency_p50_s", "serve_latency_p95_s",
        "serve_step_latency_p50_s", "serve_step_latency_p95_s",
        "serve_uptime_s",
    }
    # counters serialize as ints (1 not 1.0) — the byte-compat contract
    for k in ("serve_submitted", "serve_admitted", "serve_completed",
              "serve_rejected", "serve_tokens_generated", "serve_steps",
              "serve_decode_windows", "serve_queue_depth",
              "serve_ckpt_load_retries"):
        assert isinstance(snap[k], int), k
    # percentiles are the exact list-based values, not bucket estimates
    assert snap["serve_queue_wait_p50_s"] == percentile([0.5, 1.5], 50)
    assert snap["serve_queue_wait_p95_s"] == percentile([0.5, 1.5], 95)
    assert snap["serve_step_latency_p50_s"] == percentile([0.01, 0.03], 50)
    assert snap["serve_ttft_p50_s"] == 0.2
    assert snap["serve_latency_p95_s"] == 2.0


def test_serve_metrics_empty_percentiles_are_none():
    snap = ServeMetrics(capacity=2).snapshot()
    assert snap["serve_queue_wait_p50_s"] is None
    assert snap["serve_ttft_p95_s"] is None
    assert snap["serve_tokens_per_sec"] is None


def test_serve_metrics_ckpt_load_retries_settable():
    m = ServeMetrics(capacity=2)
    m.ckpt_load_retries = 3  # serve/loader.py assigns this directly
    assert m.ckpt_load_retries == 3
    assert m.snapshot()["serve_ckpt_load_retries"] == 3


def test_serve_metrics_registry_is_queryable():
    m = ServeMetrics(capacity=2)
    _drive(m)
    c = m.registry.counter("serve_requests_total",
                           "request lifecycle events by state")
    assert c.value(state="submitted") == 2
    assert c.value(state="admitted") == 2


def test_serve_metrics_instances_do_not_share_state():
    a, b = ServeMetrics(capacity=2), ServeMetrics(capacity=2)
    a.record_submit()
    assert a.submitted == 1 and b.submitted == 0


# -- StepTimer on the registry ----------------------------------------------


def _fake_clock(monkeypatch, ticks):
    from deeplearning_cfn_tpu.runtime import profiling

    it = iter(ticks)
    monkeypatch.setattr(profiling.time, "perf_counter", lambda: next(it))


def test_step_timer_summary_has_percentiles(monkeypatch):
    from deeplearning_cfn_tpu.runtime.profiling import StepTimer

    _fake_clock(monkeypatch, [0.0, 1.0, 1.0, 2.0, 2.0, 3.5, 3.5, 4.0])
    t = StepTimer(warmup=1)
    for _ in range(4):
        t.start()
        t.stop()
    s = t.summary()
    assert t.steps == 3
    assert s["steps"] == 3
    assert s["mean_step_s"] == pytest.approx(1.0)
    assert s["p50_step_s"] == 1.0
    assert s["p95_step_s"] == pytest.approx(1.45)
    assert s["min_step_s"] == 0.5 and s["max_step_s"] == 1.5


def test_step_timer_feeds_registry_histogram(monkeypatch):
    from deeplearning_cfn_tpu.runtime.profiling import StepTimer

    _fake_clock(monkeypatch, [0.0, 1.0])
    reg = MetricsRegistry()
    t = StepTimer(warmup=0, registry=reg)
    t.start()
    t.stop()
    h = reg.histogram("step_time_s", "synced per-step wall time")
    assert h.count() == 1 and h.samples() == [1.0]


def test_step_timer_empty_summary():
    from deeplearning_cfn_tpu.runtime.profiling import StepTimer

    assert StepTimer().summary() == {"steps": 0}


# -- trace_steps hardening ---------------------------------------------------


def test_trace_steps_body_error_not_masked_by_stop(monkeypatch, tmp_path):
    from deeplearning_cfn_tpu.runtime import profiling

    monkeypatch.setattr(profiling.jax.profiler, "start_trace",
                        lambda d: None)

    def bad_stop():
        raise OSError("flush failed")

    monkeypatch.setattr(profiling.jax.profiler, "stop_trace", bad_stop)
    # body error wins; stop_trace's secondary failure is swallowed
    with pytest.raises(ValueError, match="body"):
        with profiling.trace_steps(str(tmp_path)):
            raise ValueError("body")
    # body succeeded -> stop_trace failure must surface
    with pytest.raises(OSError, match="flush"):
        with profiling.trace_steps(str(tmp_path)):
            pass


# -- lazy MetricsWriter (satellite: no jax at construction) ------------------


def test_metrics_writer_construction_is_side_effect_free(tmp_path):
    path = str(tmp_path / "sub" / "m.jsonl")
    w = MetricsWriter(path, also_stdout=False)
    # no file, no directory until the first write
    assert not os.path.exists(os.path.dirname(path))
    w.write({"a": 1})
    w.close()
    assert json.loads(open(path).read())["a"] == 1


def test_metrics_writer_all_processes_never_asks_jax(tmp_path):
    w = MetricsWriter(str(tmp_path / "m.jsonl"), also_stdout=False,
                      all_processes=True)
    assert w.enabled  # resolved without touching jax.process_index()


# -- run reports over committed fixture logs ---------------------------------


def test_summarize_train_fixture_dir():
    s = summarize(os.path.join(FIXTURES, "train"))
    assert s["source"]["files"] == 2
    assert s["source"]["records"] == 25
    assert s["source"]["skipped_lines"] == 0
    tr = s["train"]
    assert tr["last_step"] == 6
    assert 0.2 < tr["step_time_s"]["p50"] < 0.31
    assert tr["step_time_s"]["p95"] >= tr["step_time_s"]["p50"]
    assert tr["examples_per_sec"]["last"] == pytest.approx(115.15, abs=0.01)
    assert tr["examples_per_sec"]["peak"] == pytest.approx(118.36, abs=0.01)
    assert tr["loss"]["first"] == pytest.approx(2.3026, abs=1e-3)
    assert tr["compile_s"] == pytest.approx(5.258, abs=1e-2)
    assert tr["ckpt_store_retries"] == 0
    assert tr["eval"]["final_eval_accuracy"] == 0.125
    sp = s["spans"]
    assert sp["ckpt.save"]["count"] == 4  # steps 2,4,6 + final forced save
    assert "failed" not in sp["ckpt.save"]  # no failures recorded
    assert sp["train.dispatch"]["count"] == 6
    assert sp["train.realize"]["count"] == 6
    la = s["launch"]
    assert la["attempts"] == 2
    assert la["outcomes"] == ["crash", "ok"]
    assert la["success"] is True and la["restarts"] == 1


def test_summarize_serve_fixture_file():
    s = summarize(os.path.join(FIXTURES, "serve", "metrics.jsonl"))
    assert s["source"]["files"] == 1
    sv = s["serve"]
    assert sv["submitted"] == 4 and sv["admitted"] == 4
    assert sv["completed"] == 4 and sv["rejected"] == 0
    assert sv["tokens_generated"] == 16
    assert sv["tokens_per_sec"] > 0
    assert sv["queue_wait_s"]["p50"] > 0
    assert sv["ttft_s"]["p95"] >= sv["ttft_s"]["p50"]
    assert s["spans"]["serve.decode"]["count"] == 4
    assert s["spans"]["serve.admit"]["count"] == 4
    assert "train" not in s


def test_render_report_is_human_text():
    s = summarize(os.path.join(FIXTURES, "train"))
    text = render_report(s)
    assert "run report:" in text
    assert "last step" in text
    assert "launch:" in text and "crash, ok" in text


def test_summarize_skips_malformed_lines(tmp_path):
    p = tmp_path / "m.jsonl"
    p.write_text('{"step": 1, "loss": 2.0}\nnot json\n{"step": 2}\n')
    s = summarize(str(p))
    assert s["source"]["records"] == 2
    assert s["source"]["skipped_lines"] == 1
    assert s["train"]["last_step"] == 2


def test_summarize_empty_input(tmp_path):
    p = tmp_path / "m.jsonl"
    p.write_text("")
    s = summarize(str(p))
    assert s["source"]["records"] == 0
    assert "no train" in render_report(s)  # renders, no raise


# -- CLI verb ----------------------------------------------------------------


def test_cli_obs_summarize(capsys):
    from deeplearning_cfn_tpu.cli.main import main

    rc = main(["obs", "summarize", os.path.join(FIXTURES, "train")])
    assert rc == 0
    out = capsys.readouterr().out
    assert "run report:" in out and "last step" in out


def test_cli_obs_summarize_json(capsys):
    from deeplearning_cfn_tpu.cli.main import main

    rc = main(["obs", "summarize", "--json",
               os.path.join(FIXTURES, "serve", "metrics.jsonl")])
    assert rc == 0
    s = json.loads(capsys.readouterr().out)
    assert s["serve"]["completed"] == 4


def test_cli_obs_summarize_missing_path(capsys):
    from deeplearning_cfn_tpu.cli.main import main

    assert main(["obs", "summarize", "/nonexistent/m.jsonl"]) == 1


# -- trace export (tentpole: spans -> Perfetto trace events) -----------------


def test_build_trace_round_trip_nesting(fresh_tracer):
    sink = MemorySink()
    fresh_tracer.add_sink(sink)
    with span("train.step", step=1):
        with span("train.dispatch"):
            pass
        with span("train.realize"):
            pass
    trace = build_trace(sink.records)
    assert validate_trace(trace) == []
    xs = {e["name"]: e for e in trace["traceEvents"] if e.get("ph") == "X"}
    outer = xs["train.step"]
    for name in ("train.dispatch", "train.realize"):
        inner = xs[name]
        # Same track, child interval inside the parent's.
        assert (inner["pid"], inner["tid"]) == (outer["pid"], outer["tid"])
        assert inner["ts"] >= outer["ts"]
        assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"] + 0.5
    assert outer["args"]["step"] == 1
    assert outer["cat"] == "train"


def test_build_trace_request_spans_tagged(fresh_tracer):
    sink = MemorySink()
    fresh_tracer.add_sink(sink)
    e = fresh_tracer._epoch
    parent = fresh_tracer.record_span("serve.request", e + 1.0, 2.0,
                                      request_id="r1", state="done")
    fresh_tracer.record_span("serve.request.queue", e + 1.0, 0.5,
                             parent_id=parent, request_id="r1")
    fresh_tracer.record_span("serve.request.decode", e + 1.5, 1.5,
                             parent_id=parent, request_id="r1",
                             ttft_s=0.8)
    trace = build_trace(sink.records)
    assert validate_trace(trace) == []
    xs = [ev for ev in trace["traceEvents"] if ev.get("ph") == "X"]
    assert len(xs) == 3
    # Request lifecycles live on their own process group, tagged by id.
    assert all(ev["pid"] == 2 for ev in xs)
    assert all(ev["args"]["request_id"] == "r1" for ev in xs)
    decode = next(ev for ev in xs if ev["name"] == "serve.request.decode")
    assert decode["args"]["ttft_s"] == 0.8


def test_record_request_trace_emits_lifecycle_spans(fresh_tracer):
    from types import SimpleNamespace

    sink = MemorySink()
    fresh_tracer.add_sink(sink)
    sm = ServeMetrics(capacity=2)
    req = SimpleNamespace(id="req-7", submitted_at=10.0, admitted_at=10.4,
                          finished_at=12.0, state="done", beam_size=2,
                          tokens=[1, 2, 3], ttft_s=0.9)
    sm.record_request_trace(req)
    by_name = {r["span"]: r for r in sink.records}
    assert set(by_name) == {"serve.request", "serve.request.queue",
                            "serve.request.decode"}
    parent = by_name["serve.request"]
    assert parent["request_id"] == "req-7"
    assert parent["tokens"] == 3
    assert parent["dur_s"] == pytest.approx(2.0)
    assert by_name["serve.request.queue"]["parent_id"] == parent["span_id"]
    assert by_name["serve.request.queue"]["dur_s"] == pytest.approx(0.4)
    decode = by_name["serve.request.decode"]
    assert decode["parent_id"] == parent["span_id"]
    assert decode["ttft_s"] == 0.9


def test_record_request_trace_skips_unfinished(fresh_tracer):
    from types import SimpleNamespace

    sink = MemorySink()
    fresh_tracer.add_sink(sink)
    sm = ServeMetrics(capacity=2)
    sm.record_request_trace(SimpleNamespace(id="r", submitted_at=1.0,
                                            finished_at=None))
    assert sink.records == []


def test_export_trace_train_fixture(tmp_path):
    out = str(tmp_path / "trace.json")
    summary = export_trace(os.path.join(FIXTURES, "train"), out)
    assert summary["problems"] == []
    assert summary["spans"] == 16
    assert summary["records"] == 25
    with open(out) as fh:
        trace = json.load(fh)
    assert validate_trace(trace) == []
    instants = sorted(e["name"] for e in trace["traceEvents"]
                      if e.get("ph") == "i")
    assert instants == ["launch_attempt:crash", "launch_attempt:ok"]
    counters = {e["name"] for e in trace["traceEvents"]
                if e.get("ph") == "C"}
    assert {"loss", "examples_per_sec"} <= counters


def test_build_trace_deterministic():
    from deeplearning_cfn_tpu.obs.report import collect

    records, _, _ = collect(os.path.join(FIXTURES, "train"))
    assert json.dumps(build_trace(records)) == \
        json.dumps(build_trace(records))


def test_validate_trace_flags_bad_shapes():
    assert validate_trace([]) != []
    assert validate_trace({"traceEvents": [{"ph": "X"}]}) != []  # no name
    bad_ts = {"traceEvents": [
        {"ph": "X", "name": "a", "ts": -1.0, "dur": 1.0}]}
    assert any("bad ts" in p for p in validate_trace(bad_ts))
    overlap = {"traceEvents": [
        {"ph": "X", "name": "a", "pid": 1, "tid": 0, "ts": 0.0,
         "dur": 10.0},
        {"ph": "X", "name": "b", "pid": 1, "tid": 0, "ts": 5.0,
         "dur": 10.0}]}
    assert any("overlaps" in p for p in validate_trace(overlap))


def test_cli_obs_export(tmp_path, capsys):
    from deeplearning_cfn_tpu.cli.main import main

    out = str(tmp_path / "trace.json")
    rc = main(["obs", "export", os.path.join(FIXTURES, "train"),
               "-o", out])
    assert rc == 0
    assert "ui.perfetto.dev" in capsys.readouterr().out
    with open(out) as fh:
        assert json.load(fh)["traceEvents"]


def test_cli_obs_export_missing_path(capsys):
    from deeplearning_cfn_tpu.cli.main import main

    assert main(["obs", "export", "/nonexistent/run"]) == 1


# -- SLO rules ---------------------------------------------------------------


def test_threshold_exactly_at_limit_does_not_fire():
    r = Rule({"metric": "lat", "kind": "threshold", "max": 1.0})
    assert r.observe({"lat": 1.0}) is None      # at the limit: contract, ok
    alert = r.observe({"lat": 1.0001})          # strictly above: breach
    assert alert is not None
    assert alert["event"] == "alert"
    assert alert["value"] == pytest.approx(1.0001)
    assert alert["limit"] == 1.0
    r2 = Rule({"metric": "tps", "kind": "threshold", "min": 2.0})
    assert r2.observe({"tps": 2.0}) is None
    assert r2.observe({"tps": 1.9}) is not None


def test_threshold_edge_triggered_rearms():
    r = Rule({"metric": "lat", "kind": "threshold", "max": 1.0})
    assert r.observe({"lat": 2.0}) is not None   # ok -> breach: fires
    assert r.observe({"lat": 3.0}) is None       # still breached: latched
    assert r.observe({"lat": 0.5}) is None       # recovery re-arms
    assert r.observe({"lat": 2.0}) is not None   # second edge fires
    assert r.fired == 2


def test_percentile_rule_min_count_gate():
    r = Rule({"metric": "step_time_s", "kind": "percentile", "q": 95,
              "max": 1.0, "min_count": 3})
    assert r.observe({"step_time_s": 2.0}) is None   # gated: n=1
    assert r.observe({"step_time_s": 2.0}) is None   # gated: n=2
    alert = r.observe({"step_time_s": 2.0})          # n=3: p95=2.0 > 1.0
    assert alert is not None and alert["kind"] == "percentile"
    assert alert["value"] == pytest.approx(2.0)


def test_drop_rule_warmup_and_peak():
    r = Rule({"metric": "eps", "kind": "drop", "max_drop_frac": 0.5,
              "warmup": 2})
    assert r.observe({"eps": 100.0}) is None    # establishing the peak
    assert r.observe({"eps": 100.0}) is None    # warmup
    alert = r.observe({"eps": 40.0})            # 60% below peak: fires
    assert alert is not None
    assert "dropped" in alert["detail"]
    assert r.observe({"eps": 45.0}) is None     # latched
    assert r.observe({"eps": 90.0}) is None     # recovered, re-armed
    assert r.observe({"eps": 30.0}) is not None


def test_rule_ignores_missing_and_non_numeric():
    r = Rule({"metric": "lat", "kind": "threshold", "max": 1.0})
    assert r.observe({"other": 5.0}) is None
    assert r.observe({"lat": "fast"}) is None
    assert r.observe({"lat": True}) is None


def test_rule_alert_carries_step():
    r = Rule({"metric": "loss", "kind": "threshold", "max": 1.0})
    alert = r.observe({"step": 12, "loss": 3.0})
    assert alert["step"] == 12


def test_load_rules_rejects_bad_specs(tmp_path):
    def _load(doc):
        p = tmp_path / "r.json"
        p.write_text(doc if isinstance(doc, str) else json.dumps(doc))
        return load_rules(str(p))

    with pytest.raises(RuleError):
        _load("{not json")
    with pytest.raises(RuleError):
        _load({"no_rules": []})
    with pytest.raises(RuleError):
        _load({"rules": [{"metric": "x", "kind": "wat", "max": 1}]})
    with pytest.raises(RuleError):
        _load({"rules": [{"metric": "x", "kind": "threshold"}]})  # no limit
    with pytest.raises(RuleError):
        _load({"rules": [{"metric": "x", "kind": "drop"}]})  # no frac
    with pytest.raises(RuleError):
        _load({"rules": [{"kind": "threshold", "max": 1}]})  # no metric
    rules = _load({"rules": [{"metric": "x", "max": 1}]})  # kind defaults
    assert rules[0].kind == "threshold"
    assert rules[0].name == "x-threshold"


def test_check_run_clean_fixtures():
    rules = os.path.join(FIXTURES, "rules.json")
    for run in ("train", "serve"):
        result = check_run(os.path.join(FIXTURES, run), rules)
        assert result["ok"], result["alerts"]
        assert result["alerts"] == []


def test_check_run_breach_fixture_fires_and_tolerates_torn_line():
    result = check_run(os.path.join(FIXTURES, "breach"),
                       os.path.join(FIXTURES, "rules.json"))
    assert not result["ok"]
    assert result["skipped_lines"] >= 1  # the deliberately torn last line
    assert sorted(a["rule"] for a in result["alerts"]) == [
        "serve-queue-wait-p95",
        "serve-tokens-per-sec-floor",
        "train-step-time-p95",
        "train-throughput-drop",
    ]


def test_check_run_skips_preexisting_alert_records(tmp_path):
    p = tmp_path / "m.jsonl"
    rules = tmp_path / "r.json"
    rules.write_text(json.dumps({"rules": [
        {"name": "lat", "metric": "value", "kind": "threshold",
         "max": 1.0}]}))
    with p.open("w") as fh:
        # An alert line from a previous live run: its "value" field must
        # not be re-fed into the rules.
        fh.write(json.dumps({"event": "alert", "rule": "lat",
                             "value": 9.0, "limit": 1.0}) + "\n")
        fh.write(json.dumps({"ts": 1.0, "value": 0.5}) + "\n")
    result = check_run(str(p), str(rules))
    assert result["ok"]
    assert result["records"] == 2


def test_alerting_writer_emits_alert_inline(tmp_path):
    p = tmp_path / "m.jsonl"
    engine = SloEngine([Rule({"metric": "loss", "kind": "threshold",
                              "max": 1.0})])
    w = AlertingWriter(MetricsWriter(str(p)), engine)
    w.write({"step": 1, "loss": 0.5})
    w.write({"step": 2, "loss": 3.0})
    w.close()
    recs = [json.loads(l) for l in p.read_text().splitlines()]
    assert len(recs) == 3
    assert recs[2]["event"] == "alert"       # right after its trigger
    assert recs[2]["step"] == 2
    assert len(engine.alerts) == 1


def test_cli_obs_check_rc_contract(capsys):
    from deeplearning_cfn_tpu.cli.main import main

    rules = os.path.join(FIXTURES, "rules.json")
    assert main(["obs", "check", os.path.join(FIXTURES, "train"),
                 "--rules", rules]) == 0
    assert "obs check OK" in capsys.readouterr().out
    assert main(["obs", "check", os.path.join(FIXTURES, "breach"),
                 "--rules", rules]) == 1
    out = capsys.readouterr().out
    assert "obs check BREACH" in out and "ALERT " in out
    assert main(["obs", "check", "/nonexistent/run",
                 "--rules", rules]) == 2
    assert main(["obs", "check", os.path.join(FIXTURES, "train"),
                 "--rules", "/nonexistent/rules.json"]) == 2


def test_cli_obs_check_json(capsys):
    from deeplearning_cfn_tpu.cli.main import main

    rc = main(["obs", "check", os.path.join(FIXTURES, "breach"),
               "--rules", os.path.join(FIXTURES, "rules.json"),
               "--json"])
    assert rc == 1
    result = json.loads(capsys.readouterr().out)
    assert result["ok"] is False
    assert len(result["alerts"]) == 4


# -- cross-run diff ----------------------------------------------------------


def test_diff_identical_runs_zero_deltas():
    train = os.path.join(FIXTURES, "train")
    report = diff_runs(train, train)
    assert report["ok"]
    assert report["regressions"] == []
    assert report["common_metrics"] > 0
    assert report["only_a"] == report["only_b"] == []
    for m in report["metrics"].values():
        assert not m["regressed"]
        assert m["delta_p50"] in (None, 0.0)
        assert m["delta_p95"] in (None, 0.0)
    assert "regressions: 0" in render_diff(report)


def test_diff_flags_injected_regression(tmp_path):
    src = os.path.join(FIXTURES, "train", "metrics.jsonl")
    slow = tmp_path / "metrics.jsonl"
    with open(src) as fh, slow.open("w") as out:
        for line in fh:
            rec = json.loads(line)
            if isinstance(rec.get("step_time_s"), (int, float)):
                rec["step_time_s"] *= 3.0
            out.write(json.dumps(rec) + "\n")
    report = diff_runs(src, str(slow))
    assert not report["ok"]
    assert "step_time_s" in report["regressions"]
    m = report["metrics"]["step_time_s"]
    assert m["direction"] == "lower"
    assert m["rel_p50"] == pytest.approx(2.0)
    # The same 3x slowdown read the other way is an improvement, not a
    # regression.
    assert diff_runs(str(slow), src)["ok"]


def test_diff_direction_awareness():
    assert direction("examples_per_sec") == "higher"
    assert direction("serve_tokens_per_sec") == "higher"
    assert direction("loss") == "lower"
    assert direction("step_time_s") == "lower"
    assert direction("serve_queue_wait_p95_s") == "lower"
    assert direction("serve_latency_p95_s") == "lower"
    assert direction("span:serve.decode") == "lower"
    assert direction("accuracy") is None


def test_diff_bench_records_gate():
    from deeplearning_cfn_tpu.obs.diff import diff_bench_records

    prior = {"metric": "examples_per_sec", "value": 100.0,
             "mean_step_s": 0.1, "measured": True}
    worse = {"metric": "examples_per_sec", "value": 50.0,
             "mean_step_s": 0.2, "measured": True}
    verdict = diff_bench_records(prior, worse)
    assert not verdict["ok"]
    assert set(verdict["regressions"]) == {"value", "mean_step_s"}
    assert diff_bench_records(prior, prior)["ok"]
    # Unmeasured (fallback) records never gate.
    unmeasured = dict(worse, measured=False)
    v = diff_bench_records(prior, unmeasured)
    assert v["ok"] and "skipped" in v


def test_load_bench_record(tmp_path):
    from deeplearning_cfn_tpu.obs.diff import load_bench_record

    assert load_bench_record("/nonexistent.json") is None
    p = tmp_path / "r.json"
    p.write_text(json.dumps({"metric": "examples_per_sec", "value": 9.0}))
    assert load_bench_record(str(p))["value"] == 9.0
    jl = tmp_path / "r.jsonl"
    jl.write_text('{"other": 1}\n{"metric": "m", "value": 1.0}\n'
                  '{"metric": "m", "value": 2.0}\n')
    assert load_bench_record(str(jl))["value"] == 2.0  # last wins


def test_cli_obs_diff_self_and_regression(tmp_path, capsys):
    from deeplearning_cfn_tpu.cli.main import main

    train = os.path.join(FIXTURES, "train")
    assert main(["obs", "diff", train, train]) == 0
    assert "regressions: 0" in capsys.readouterr().out
    src = os.path.join(train, "metrics.jsonl")
    slow = tmp_path / "metrics.jsonl"
    with open(src) as fh, slow.open("w") as out:
        for line in fh:
            rec = json.loads(line)
            if isinstance(rec.get("step_time_s"), (int, float)):
                rec["step_time_s"] *= 3.0
            out.write(json.dumps(rec) + "\n")
    assert main(["obs", "diff", src, str(slow)]) == 1
    assert main(["obs", "diff", src, "/nonexistent"]) == 2
    rc = main(["obs", "diff", train, train, "--json"])
    capsys.readouterr()
    assert rc == 0


# -- live tail ---------------------------------------------------------------


def test_follower_buffers_partial_lines(tmp_path):
    p = tmp_path / "m.jsonl"
    f = JsonlFollower(str(p))
    assert f.poll() == []                        # missing file: no raise
    with p.open("w") as fh:
        fh.write('{"step": 1}\n{"step": 2, "lo')
        fh.flush()
    assert f.poll() == [{"step": 1}]             # torn tail held back
    with p.open("a") as fh:
        fh.write('ss": 2.5}\n')
    assert f.poll() == [{"step": 2, "loss": 2.5}]  # completed on next poll
    assert f.skipped == 0


def test_follower_resets_on_truncation(tmp_path):
    p = tmp_path / "m.jsonl"
    p.write_text('{"step": 1}\n{"step": 2}\n')
    f = JsonlFollower(str(p))
    assert len(f.poll()) == 2
    p.write_text('{"step": 9}\n')                # rotated/truncated
    assert f.poll() == [{"step": 9}]


def test_tail_state_status_line():
    s = TailState()
    s.update({"step": 4, "step_time_s": 0.25, "examples_per_sec": 128.0,
              "loss": 2.1})
    line = s.status_line()
    assert "step 4" in line and "4 steps/s" in line and "loss 2.1" in line
    s.update({"event": "alert", "rule": "loss-ceiling"})
    assert "alerts 1 (last: loss-ceiling)" in s.status_line()
    s.update({"span": "ckpt.save", "ok": False})
    assert "span-failures 1" in s.status_line()


def test_tail_once_renders_fixture_status():
    import io

    buf = io.StringIO()
    rc = tail(os.path.join(FIXTURES, "serve"), once=True, out=buf)
    assert rc == 0
    assert "serve q=0 25.41 tok/s done 4/4" in buf.getvalue()
    assert "alerts 0" in buf.getvalue()


def test_tail_live_slo_engine_prints_alerts(tmp_path):
    import io

    p = tmp_path / "metrics.jsonl"
    p.write_text('{"ts": 1.0, "step": 1, "loss": 99.0}\n')
    engine = SloEngine([Rule({"name": "loss-cap", "metric": "loss",
                              "kind": "threshold", "max": 10.0})])
    buf = io.StringIO()
    tail(str(p), once=True, slo_engine=engine, out=buf)
    assert "ALERT loss-cap:" in buf.getvalue()


def test_cli_obs_tail_once(capsys):
    from deeplearning_cfn_tpu.cli.main import main

    rc = main(["obs", "tail", os.path.join(FIXTURES, "train"), "--once"])
    assert rc == 0
    assert "step 6" in capsys.readouterr().out


# -- bounded histogram retention (satellite) ---------------------------------


def test_histogram_exact_below_cap():
    reg = MetricsRegistry()
    h = reg.histogram("h", max_samples=8)
    for i in range(8):
        h.observe(float(i))
    assert h.samples() == [float(i) for i in range(8)]  # byte-identical
    assert h.count() == 8
    assert h.percentile(50) == percentile([float(i) for i in range(8)], 50)


def test_histogram_reservoir_bounds_retention():
    reg = MetricsRegistry()
    h = reg.histogram("h", max_samples=8)
    for i in range(1000):
        h.observe(float(i))
    assert len(h.samples()) == 8            # retention bounded
    assert h.count() == 1000                # count stays exact
    assert h.sum() == float(sum(range(1000)))  # sum stays exact
    assert all(0.0 <= v < 1000.0 for v in h.samples())
    assert h.percentile(50) is not None


def test_histogram_reservoir_deterministic():
    def _fill():
        reg = MetricsRegistry()
        h = reg.histogram("h", max_samples=16)
        for i in range(500):
            h.observe(float(i))
        return h.samples()

    assert _fill() == _fill()               # seeded: no run-to-run drift


def test_histogram_max_samples_validated():
    reg = MetricsRegistry()
    with pytest.raises(ValueError):
        reg.histogram("bad", max_samples=0)


def test_histogram_default_cap_unchanged_for_short_runs():
    # Default-config histograms behave exactly as before the cap for any
    # realistic test-sized series.
    reg = MetricsRegistry()
    h = reg.histogram("h")
    xs = [0.1 * i for i in range(100)]
    for v in xs:
        h.observe(v)
    assert h.samples() == xs


# -- summarize: --since-step and empty dirs (satellite) ----------------------


def test_summarize_since_step_filters_train_records():
    train = os.path.join(FIXTURES, "train")
    full = summarize(train)
    late = summarize(train, since_step=4)
    assert late["source"]["since_step"] == 4
    assert late["source"]["records"] < full["source"]["records"]
    assert late["train"]["records"] < full["train"]["records"]
    assert late["train"]["last_step"] == full["train"]["last_step"]


def test_cli_obs_summarize_since_step(capsys):
    from deeplearning_cfn_tpu.cli.main import main

    rc = main(["obs", "summarize", "--json", "--since-step", "4",
               os.path.join(FIXTURES, "train")])
    assert rc == 0
    s = json.loads(capsys.readouterr().out)
    assert s["source"]["since_step"] == 4


def test_cli_obs_summarize_empty_dir(tmp_path, capsys):
    from deeplearning_cfn_tpu.cli.main import main

    rc = main(["obs", "summarize", str(tmp_path)])
    assert rc == 1
    assert "empty run dir" in capsys.readouterr().err


# -- phase-budget SLO rules --------------------------------------------------


_PHASE_RULE = {
    "name": "request-p95", "kind": "phase_budget",
    "metric": "serve_latency_p95_s", "max": 1.0,
    "phases": {
        "prefill": {"metric": "serve_phase_prefill_p95_s", "budget": 0.2},
        "decode": {"metric": "serve_phase_decode_p95_s", "budget": 0.7},
    },
}


def test_phase_budget_attributes_breach_to_worst_phase():
    r = Rule(dict(_PHASE_RULE))
    # Within SLO: phases are remembered, nothing fires.
    assert r.observe({"serve_latency_p95_s": 0.9,
                      "serve_phase_prefill_p95_s": 0.1,
                      "serve_phase_decode_p95_s": 0.6}) is None
    alert = r.observe({"serve_latency_p95_s": 1.4,
                       "serve_phase_prefill_p95_s": 0.1,
                       "serve_phase_decode_p95_s": 1.2})
    assert alert is not None and alert["kind"] == "phase_budget"
    assert alert["phase"] == "decode"        # 1.2/0.7 beats 0.1/0.2
    assert "decode" in alert["detail"]
    assert alert["value"] == pytest.approx(1.4)
    assert alert["limit"] == 1.0


def test_phase_budget_attribution_survives_split_records():
    # Total and phase metrics arrive in SEPARATE records (snapshot
    # streams interleave); the last phase observation still attributes.
    r = Rule(dict(_PHASE_RULE))
    assert r.observe({"serve_phase_prefill_p95_s": 0.5}) is None
    alert = r.observe({"serve_latency_p95_s": 2.0})
    assert alert is not None and alert["phase"] == "prefill"


def test_phase_budget_unattributed_when_phases_within_budget():
    r = Rule(dict(_PHASE_RULE))
    alert = r.observe({"serve_latency_p95_s": 1.5,
                       "serve_phase_prefill_p95_s": 0.1,
                       "serve_phase_decode_p95_s": 0.5})
    assert alert is not None and alert["phase"] == "unattributed"
    assert "within budget" in alert["detail"]


def test_phase_budget_edge_triggered_like_threshold():
    r = Rule(dict(_PHASE_RULE))
    rec = {"serve_latency_p95_s": 2.0, "serve_phase_decode_p95_s": 1.5}
    assert r.observe(rec) is not None       # ok -> breach fires
    assert r.observe(rec) is None           # latched
    assert r.observe({"serve_latency_p95_s": 0.5}) is None  # re-arms
    assert r.observe(rec) is not None
    assert r.fired == 2


def test_phase_budget_spec_validation():
    with pytest.raises(RuleError):   # needs max
        Rule({"metric": "m", "kind": "phase_budget",
              "phases": {"p": {"metric": "x", "budget": 1.0}}})
    with pytest.raises(RuleError):   # needs non-empty phases
        Rule({"metric": "m", "kind": "phase_budget", "max": 1.0})
    with pytest.raises(RuleError):
        Rule({"metric": "m", "kind": "phase_budget", "max": 1.0,
              "phases": {}})
    with pytest.raises(RuleError):   # phase needs a positive budget
        Rule({"metric": "m", "kind": "phase_budget", "max": 1.0,
              "phases": {"p": {"metric": "x", "budget": 0}}})
    with pytest.raises(RuleError):   # bool budget is not a number here
        Rule({"metric": "m", "kind": "phase_budget", "max": 1.0,
              "phases": {"p": {"metric": "x", "budget": True}}})
    with pytest.raises(RuleError):   # phase needs a metric string
        Rule({"metric": "m", "kind": "phase_budget", "max": 1.0,
              "phases": {"p": {"budget": 1.0}}})


# -- histogram snapshot honesty fields (satellite) ---------------------------


def test_histogram_snapshot_reports_window_and_retention():
    reg = MetricsRegistry()
    h = reg.histogram("h", max_samples=4)
    for i in range(10):
        h.observe(float(i), ts=100.0 + i)
    snap = reg.snapshot()["h"]["series"][""]
    assert snap["count"] == 10
    assert snap["samples_retained"] == 4     # reservoir cap bites
    assert snap["window_start_ts"] == 100.0
    assert snap["window_end_ts"] == 109.0


def test_histogram_snapshot_window_none_without_timestamps():
    reg = MetricsRegistry()
    h = reg.histogram("h")
    h.observe(1.0)
    h.observe(2.0)
    snap = reg.snapshot()["h"]["series"][""]
    assert snap["samples_retained"] == snap["count"] == 2
    assert snap["window_start_ts"] is None
    assert snap["window_end_ts"] is None


# -- the fleet signal bus ----------------------------------------------------


def test_rolling_window_prunes_to_record_time():
    from deeplearning_cfn_tpu.obs.signals import RollingWindow

    w = RollingWindow(window_s=10.0)
    w.add(0.0, 1.0)
    w.add(5.0, 2.0)
    w.add(14.0, 3.0)              # cutoff 4.0: drops the t=0 sample
    snap = w.snapshot()
    assert snap["samples"] == 2
    assert snap["window_start_ts"] == 5.0
    assert snap["window_end_ts"] == 14.0
    assert snap["last"] == 3.0
    with pytest.raises(ValueError):
        RollingWindow(window_s=0)


def test_signal_bus_fleet_aggregate_and_replay_determinism():
    from deeplearning_cfn_tpu.obs.signals import SignalBus

    def _fold():
        bus = SignalBus(names=["replica-0", "replica-1"])
        bus.observe("replica-0", {"ts": 1.0, "serve_tokens_per_sec": 10.0,
                                  "serve_queue_depth": 1,
                                  "serve_latency_p95_s": 0.2})
        bus.observe("replica-1", {"ts": 2.0, "serve_tokens_per_sec": 5.0,
                                  "serve_queue_depth": 0,
                                  "serve_latency_p95_s": 0.6})
        bus.observe("replica-1", {"event": "alert", "rule": "lat"})
        return bus.snapshot()

    a, b = _fold(), _fold()
    assert a == b                 # the bus never reads a clock
    assert a["event"] == "signal_snapshot"
    f = a["fleet"]
    assert f["replicas"] == 2 and f["replicas_live"] == 2
    assert f["tokens_per_sec"] == 15.0
    assert f["queue_depth"] == 1
    assert f["worst_latency_p95_s"] == 0.6
    assert f["alerts"] == 1
    assert a["replicas"]["replica-1"]["last_alert"] == "lat"
    assert json.dumps(a)          # one JSONL line, the autoscaler wire


def test_signal_bus_sequences_records_without_timestamps():
    from deeplearning_cfn_tpu.obs.signals import SignalBus

    bus = SignalBus()
    bus.observe("r", {"serve_queue_depth": 3})      # no ts anywhere
    win = bus.snapshot()["replicas"]["r"]["windowed"]["queue_depth"]
    assert win["samples"] == 1
    assert win["window_start_ts"] == 1.0            # seq counter stands in
    assert win["last"] == 3


def test_signal_bus_membership_churn_mid_window():
    """The autoscaler adds/removes replicas while the bus is live: a
    joiner registers on first observe and lands in the aggregate
    immediately, without disturbing the incumbents' rolling windows; a
    leaver simply stops reporting (its last values persist — the bus is
    an observer, not the membership authority, which is the router)."""
    from deeplearning_cfn_tpu.obs.signals import SignalBus

    bus = SignalBus(names=["replica-0"])
    bus.observe("replica-0", {"ts": 1.0, "serve_queue_depth": 3,
                              "serve_tokens_per_sec": 10.0})
    before = bus.replica("replica-0").snapshot()["windowed"]["queue_depth"]
    # Join mid-window: unknown name auto-registers on first observe.
    bus.observe("auto-both-0", {"ts": 1.5, "serve_queue_depth": 2,
                                "serve_tokens_per_sec": 4.0})
    f = bus.fleet()
    assert f["replicas"] == 2 and f["replicas_live"] == 2
    assert f["queue_depth"] == 5          # joiner counted immediately
    assert f["tokens_per_sec"] == 14.0
    after = bus.replica("replica-0").snapshot()["windowed"]["queue_depth"]
    assert after == before                # incumbent fold untouched
    # The incumbent keeps folding into the SAME window after the join.
    bus.observe("replica-0", {"ts": 2.0, "serve_queue_depth": 1})
    win = bus.replica("replica-0").snapshot()["windowed"]["queue_depth"]
    assert win["samples"] == before["samples"] + 1
    assert win["last"] == 1
    # Leave: the joiner drains away and stops reporting; the aggregate
    # still sums its last-known values (staleness is visible in ts, not
    # silently zeroed) and stays JSON-serializable.
    bus.observe("replica-0", {"ts": 3.0, "serve_queue_depth": 0})
    f = bus.fleet()
    assert f["queue_depth"] == 2          # 0 + joiner's last 2
    assert json.dumps(bus.snapshot())


def test_signal_bus_churn_replay_determinism():
    """Folding the same churn sequence twice — registration order,
    joins, and all — yields identical snapshots (the autoscaler's
    decisions replay from the seed only if its inputs do)."""
    from deeplearning_cfn_tpu.obs.signals import SignalBus

    def _fold():
        bus = SignalBus(names=["replica-0"])
        bus.observe("replica-0", {"ts": 1.0, "serve_queue_depth": 4})
        bus.observe("auto-both-0", {"ts": 1.2, "serve_queue_depth": 1})
        bus.observe("auto-both-1", {"ts": 1.4, "serve_queue_depth": 1})
        bus.observe("replica-0", {"ts": 2.0, "serve_queue_depth": 2})
        return bus.snapshot()

    assert _fold() == _fold()


def test_fleet_tail_state_autoscale_membership_and_state():
    """`obs tail --fleet` folds scale events into live membership and a
    controller state; a fleet that never scales keeps the legacy status
    line byte for byte."""
    from deeplearning_cfn_tpu.obs.tail import FleetTailState

    fixed = FleetTailState(["replica-0"])
    fixed.update("replica-0", {"ts": 1.0, "serve_queue_depth": 0,
                               "serve_submitted": 2,
                               "serve_completed": 2})
    legacy = fixed.status_line()
    assert "members" not in legacy and "scale" not in legacy

    st = FleetTailState(["replica-0", "#autoscale"])
    st.update("replica-0", {"ts": 1.0, "serve_queue_depth": 4,
                            "phase": "both"})
    st.update("#autoscale", {"event": "scale_event", "action": "scale_up",
                             "ts": 1.1, "phase": "both",
                             "replica": "auto-both-0",
                             "reason": "queue_depth 4 > 1.5"})
    assert st.scale_state() == "scaling-up"
    assert st.members == {"replica-0": "both", "auto-both-0": "both"}
    line = st.status_line()
    assert "members auto-both-0:both,replica-0:both" in line
    assert "scale scaling-up" in line and "queue_depth 4 > 1.5" in line
    # The control stream never pollutes the replica bus.
    assert "#autoscale" not in st.bus.replicas
    st.update("#autoscale", {"event": "scale_event",
                             "action": "drain_begin", "ts": 2.0,
                             "phase": "both", "replica": "auto-both-0",
                             "reason": "pool calm"})
    assert st.scale_state() == "draining"
    st.update("#autoscale", {"event": "scale_event",
                             "action": "scale_down", "ts": 2.1,
                             "phase": "both", "replica": "auto-both-0",
                             "reason": "drained idle", "drained": True})
    assert st.scale_state() == "steady"
    assert st.members == {"replica-0": "both"}
    assert st.scale_ups == 1 and st.scale_downs == 1


def test_fleet_tail_follows_autoscale_jsonl_and_new_replicas(tmp_path):
    """End to end over a fleet root on disk: the tail discovers the
    autoscale.jsonl control stream AND a replica dir created after the
    follow started (autoscaled membership is not fixed at startup)."""
    import io

    from deeplearning_cfn_tpu.obs.tail import (
        FleetTailState,
        _fleet_followers,
    )

    root = tmp_path / "fleet"
    (root / "replica-0").mkdir(parents=True)
    (root / "replica-0" / "metrics.jsonl").write_text(json.dumps(
        {"ts": 1.0, "serve_queue_depth": 1, "serve_submitted": 1,
         "serve_completed": 0}) + "\n")
    pairs = _fleet_followers(str(root))
    names = [n for n, _ in pairs]
    assert "#autoscale" in names
    # A replica dir that appears later is picked up by a re-discovery.
    (root / "auto-both-0").mkdir()
    (root / "auto-both-0" / "metrics.jsonl").write_text(json.dumps(
        {"ts": 2.0, "serve_queue_depth": 0, "serve_submitted": 1,
         "serve_completed": 1}) + "\n")
    (root / "autoscale.jsonl").write_text(json.dumps(
        {"event": "scale_event", "action": "scale_up", "ts": 1.5,
         "phase": "both", "replica": "auto-both-0",
         "reason": "queue_depth 3 > 1.5"}) + "\n")
    known = {f.path for _, f in pairs}
    for name, f in _fleet_followers(str(root)):
        if f.path not in known:
            pairs.append((name, f))
    assert {n for n, _ in pairs if not n.startswith("#")} \
        == {"auto-both-0", "replica-0"}
    st = FleetTailState([n for n, _ in pairs])
    for name, f in pairs:
        for rec in f.poll():
            st.update(name, rec)
    line = st.status_line()
    assert "scale scaling-up" in line
    assert "auto-both-0" in line

    from deeplearning_cfn_tpu.obs.tail import tail
    buf = io.StringIO()
    assert tail(str(root), once=True, fleet=True, out=buf) == 0
    assert "scale scaling-up" in buf.getvalue()


def test_fold_autoscale_report_section():
    """summarize --fleet's autoscale fold: counts, drained-vs-forced
    split, and the steady/scaling-up/draining state derivation."""
    from deeplearning_cfn_tpu.obs.report import fold_autoscale

    up = {"event": "scale_event", "action": "scale_up", "ts": 1.0,
          "phase": "both", "replica": "auto-both-0", "reason": "q"}
    drain = {"event": "scale_event", "action": "drain_begin", "ts": 2.0,
             "phase": "both", "replica": "auto-both-0", "reason": "calm"}
    down = {"event": "scale_event", "action": "scale_down", "ts": 3.0,
            "phase": "both", "replica": "auto-both-0",
            "reason": "drained idle", "drained": True}
    assert fold_autoscale([up])["state"] == "scaling-up"
    assert fold_autoscale([up, drain])["state"] == "draining"
    full = fold_autoscale([up, drain, down])
    assert full["state"] == "steady"
    assert full["scale_ups"] == 1 and full["scale_downs"] == 1
    assert full["drained_scale_downs"] == 1
    assert full["last_action"] == "scale_down"
    assert full["last_reason"] == "drained idle"
