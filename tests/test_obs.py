"""obs/ subsystem: metrics registry, span tracer, sinks, run reports.

Parity tests pin the migration contracts: ServeMetrics keeps its exact
attribute surface and snapshot keys/values after moving onto the registry,
and percentile() keeps its interpolation semantics. The report tests run
against COMMITTED fixture logs generated from real train/serve/launcher
runs (tests/fixtures/obs/), so `obs summarize` is tested on the actual
byte shapes the runners emit.
"""

import json
import os

import pytest

from deeplearning_cfn_tpu.metrics.jsonl import MetricsWriter
from deeplearning_cfn_tpu.obs import (
    JsonlSink,
    MemorySink,
    MetricsRegistry,
    Tracer,
    configured,
    exponential_buckets,
    get_tracer,
    obs_enabled,
    percentile,
    render_prometheus,
    render_report,
    set_enabled,
    span,
    summarize,
    write_prometheus,
)
from deeplearning_cfn_tpu.serve.metrics import ServeMetrics

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures", "obs")


# -- percentile edge cases (satellite: never raise, never NaN) ---------------


def test_percentile_empty_returns_none():
    assert percentile([], 50) is None
    assert percentile([], 95) is None


def test_percentile_single_sample_is_that_sample():
    for q in (0, 50, 95, 100):
        assert percentile([0.25], q) == 0.25


def test_percentile_all_ties_no_nan():
    p = percentile([2.0] * 7, 95)
    assert p == 2.0
    assert p == p  # not NaN


def test_percentile_interpolates():
    # rank = (n-1) * q/100; for [1..5], p50 = 3.0, p95 = 4.8
    xs = [5.0, 1.0, 4.0, 2.0, 3.0]
    assert percentile(xs, 50) == 3.0
    assert percentile(xs, 95) == pytest.approx(4.8)


def test_percentile_does_not_mutate_input():
    xs = [3.0, 1.0, 2.0]
    percentile(xs, 50)
    assert xs == [3.0, 1.0, 2.0]


# -- registry + instruments --------------------------------------------------


def test_counter_inc_and_monotonicity():
    reg = MetricsRegistry()
    c = reg.counter("reqs", "requests")
    assert c.value() == 0
    c.inc()
    c.inc(3)
    assert c.value() == 4
    with pytest.raises(ValueError):
        c.inc(-1)


def test_counter_labels_are_independent_series():
    reg = MetricsRegistry()
    c = reg.counter("reqs", "requests")
    c.inc(2, state="ok")
    c.inc(5, state="err")
    assert c.value(state="ok") == 2
    assert c.value(state="err") == 5
    assert c.labels(state="ok").value() == 2
    assert c.series()[(("state", "ok"),)] == 2


def test_gauge_set_and_inc():
    reg = MetricsRegistry()
    g = reg.gauge("depth", "queue depth")
    assert g.value() is None
    g.set(7)
    assert g.value() == 7
    g.inc(-2)
    assert g.value() == 5


def test_registry_get_or_create_returns_same_instrument():
    reg = MetricsRegistry()
    assert reg.counter("x", "d") is reg.counter("x", "d")


def test_registry_kind_mismatch_raises_typeerror():
    reg = MetricsRegistry()
    reg.counter("x", "d")
    with pytest.raises(TypeError):
        reg.gauge("x", "d")
    with pytest.raises(TypeError):
        reg.histogram("x", "d")


def test_exponential_buckets_shape():
    assert exponential_buckets(start=1e-3, factor=2.0, count=4) == \
        (1e-3, 2e-3, 4e-3, 8e-3)
    with pytest.raises(ValueError):
        exponential_buckets(start=0)


def test_histogram_buckets_and_exact_percentiles():
    reg = MetricsRegistry()
    h = reg.histogram("lat", "latency", buckets=(0.1, 1.0))
    for v in (0.05, 0.5, 5.0):
        h.observe(v)
    assert h.count() == 3
    assert h.sum() == pytest.approx(5.55)
    # exact percentiles come from retained samples, not bucket edges
    assert h.percentile(50) == 0.5
    assert h.samples() == [0.05, 0.5, 5.0]
    ((_, series),) = h.series().items()
    assert series.bucket_counts == [1, 1, 1]  # per-bucket incl +Inf


def test_histogram_empty_percentile_is_none():
    reg = MetricsRegistry()
    h = reg.histogram("lat", "latency")
    assert h.percentile(50) is None
    assert h.mean() is None
    assert h.count() == 0 and h.sum() == 0.0 and h.samples() == []


def test_histogram_keep_samples_false_drops_raw_series():
    reg = MetricsRegistry()
    h = reg.histogram("hot", "hot path", keep_samples=False)
    h.observe(0.2)
    assert h.count() == 1
    assert h.samples() == []
    assert h.percentile(50) is None  # no raw series -> no exact percentile


def test_histogram_labelled_series():
    reg = MetricsRegistry()
    h = reg.histogram("span_dur_s", "d")
    h.observe(0.1, name="a")
    h.observe(0.2, name="a")
    h.observe(9.0, name="b")
    assert h.count(name="a") == 2
    assert h.percentile(50, name="a") == pytest.approx(0.15)
    assert h.count(name="b") == 1


def test_registry_snapshot_is_json_able():
    reg = MetricsRegistry()
    reg.counter("c", "c").inc(2, state="ok")
    reg.gauge("g", "g").set(1.5)
    reg.histogram("h", "h").observe(0.2)
    snap = json.loads(json.dumps(reg.snapshot()))
    assert snap["c"]["kind"] == "counter"
    assert snap["c"]["series"]["state=ok"] == 2
    assert snap["h"]["series"][""]["count"] == 1
    assert snap["h"]["series"][""]["p50"] == 0.2


# -- span tracer -------------------------------------------------------------


@pytest.fixture()
def fresh_tracer():
    t = Tracer()
    configured(t)
    try:
        yield t
    finally:
        configured(None)
        set_enabled(None)


def test_span_ids_deterministic_from_one(fresh_tracer):
    sink = MemorySink()
    fresh_tracer.add_sink(sink)
    with span("a"):
        pass
    with span("b"):
        pass
    assert [r["span_id"] for r in sink.records] == [1, 2]
    assert all(r["parent_id"] is None for r in sink.records)


def test_span_nesting_sets_parent_id(fresh_tracer):
    sink = MemorySink()
    fresh_tracer.add_sink(sink)
    with span("outer"):
        with span("inner", step=3):
            pass
    inner, outer = sink.records  # inner closes (and is recorded) first
    assert inner["span"] == "inner"
    assert inner["parent_id"] == outer["span_id"]
    assert inner["step"] == 3
    assert outer["parent_id"] is None
    assert inner["dur_s"] <= outer["dur_s"]
    assert inner["t0_s"] >= outer["t0_s"]


def test_span_records_failure_and_reraises(fresh_tracer):
    sink = MemorySink()
    fresh_tracer.add_sink(sink)
    with pytest.raises(RuntimeError):
        with span("boom"):
            raise RuntimeError("x")
    (rec,) = sink.records
    assert rec["ok"] is False


def test_span_annotate_adds_attrs(fresh_tracer):
    sink = MemorySink()
    fresh_tracer.add_sink(sink)
    with span("ckpt.save", step=4) as sp:
        sp.annotate(retries=2)
    (rec,) = sink.records
    assert rec["step"] == 4
    assert rec["retries"] == 2


def test_spans_feed_duration_histogram(fresh_tracer):
    with span("work"):
        pass
    h = fresh_tracer.registry.histogram("span_dur_s", "span durations by name")
    assert h.count(name="work") == 1


def test_memory_sink_by_span(fresh_tracer):
    sink = MemorySink()
    fresh_tracer.add_sink(sink)
    with span("a"):
        pass
    with span("b"):
        pass
    assert [r["span"] for r in sink.by_span("a")] == ["a"]


def test_env_gate_disables_spans(fresh_tracer, monkeypatch):
    sink = MemorySink()
    fresh_tracer.add_sink(sink)
    monkeypatch.setenv("DLCFN_OBS_OFF", "1")
    assert not obs_enabled()
    with span("a") as sp:
        sp.annotate(ignored=True)  # null span: no-op, no raise
    assert sink.records == []
    monkeypatch.delenv("DLCFN_OBS_OFF")
    assert obs_enabled()
    with span("a"):
        pass
    assert len(sink.records) == 1


def test_set_enabled_overrides_env(fresh_tracer, monkeypatch):
    sink = MemorySink()
    fresh_tracer.add_sink(sink)
    monkeypatch.setenv("DLCFN_OBS_OFF", "1")
    set_enabled(True)  # programmatic override beats the env var
    with span("a"):
        pass
    assert len(sink.records) == 1
    set_enabled(False)
    with span("b"):
        pass
    assert len(sink.records) == 1


def test_get_tracer_returns_configured_default():
    t = Tracer()
    configured(t)
    try:
        assert get_tracer() is t
    finally:
        configured(None)
    assert get_tracer() is not t


def test_remove_sink_stops_delivery(fresh_tracer):
    sink = MemorySink()
    fresh_tracer.add_sink(sink)
    fresh_tracer.remove_sink(sink)
    fresh_tracer.remove_sink(sink)  # idempotent
    with span("a"):
        pass
    assert sink.records == []


# -- sinks -------------------------------------------------------------------


def test_jsonl_sink_writes_span_records(fresh_tracer, tmp_path):
    path = str(tmp_path / "m.jsonl")
    sink = JsonlSink(MetricsWriter(path, also_stdout=False))
    fresh_tracer.add_sink(sink)
    with span("a", step=1):
        pass
    sink.close()
    (line,) = open(path).read().splitlines()
    rec = json.loads(line)
    assert rec["span"] == "a" and rec["span_id"] == 1 and "ts" in rec


def test_render_prometheus_text_format():
    reg = MetricsRegistry()
    reg.counter("reqs_total", "requests").inc(3, state="ok")
    reg.gauge("depth", "queue depth").set(2)
    h = reg.histogram("lat_s", "latency", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    text = render_prometheus(reg)
    assert "# TYPE reqs_total counter" in text
    assert 'reqs_total{state="ok"} 3' in text
    assert "# TYPE depth gauge" in text
    assert "depth 2" in text
    assert 'lat_s_bucket{le="0.1"} 1' in text
    assert 'lat_s_bucket{le="1"} 2' in text
    assert 'lat_s_bucket{le="+Inf"} 2' in text
    assert "lat_s_count 2" in text
    assert "lat_s_sum 0.55" in text


def test_render_prometheus_escapes_label_values():
    reg = MetricsRegistry()
    reg.counter("c", "d").inc(1, msg='a"b\nc\\d')
    text = render_prometheus(reg)
    assert 'msg="a\\"b\\nc\\\\d"' in text


def test_write_prometheus_atomic(tmp_path):
    reg = MetricsRegistry()
    reg.counter("c", "d").inc()
    path = str(tmp_path / "metrics.prom")
    text = write_prometheus(reg, path)
    assert open(path).read() == text
    assert os.listdir(str(tmp_path)) == ["metrics.prom"]  # no tmp leftover


# -- ServeMetrics parity after the registry migration ------------------------


def _drive(m: ServeMetrics):
    m.record_submit()
    m.record_submit()
    m.record_admit(queue_wait_s=0.5)
    m.record_admit(queue_wait_s=1.5)
    m.record_first_token(0.2)
    m.record_finish("done", 2.0)
    m.record_step(active_rows=2, queue_depth=3, new_tokens=5,
                  step_time_s=0.01)
    m.record_step(active_rows=1, queue_depth=3, new_tokens=3,
                  step_time_s=0.03)
    m.record_reject(retry_after_s=0.25)


def test_serve_metrics_attribute_surface_parity():
    m = ServeMetrics(capacity=4, clock=lambda: 0.0)
    _drive(m)
    # the exact pre-migration attribute surface, live values
    assert m.submitted == 2 and isinstance(m.submitted, int)
    assert m.admitted == 2
    assert m.completed == 1
    assert m.rejected == 1
    assert m.cancelled == 0 and m.expired == 0
    assert m.tokens_generated == 8
    assert m.steps == 2 and m.windows == 2
    assert m.queue_wait_s == [0.5, 1.5]
    assert m.ttft_s == [0.2]
    assert m.latency_s == [2.0]
    assert m.step_latency_s == [0.01, 0.03]
    assert m.busy_time_s == pytest.approx(0.04)
    assert m.last_queue_depth == 3
    assert m.last_retry_after_s == 0.25
    assert m.mean_slot_occupancy == pytest.approx(0.375)
    assert m.mean_steps_per_window == 1.0
    assert m.tokens_per_sec == pytest.approx(8 / 0.04)
    assert m.ckpt_load_retries == 0


def test_serve_metrics_snapshot_keys_and_values_parity():
    m = ServeMetrics(capacity=4, clock=lambda: 0.0)
    _drive(m)
    snap = m.snapshot()
    # key set is the pre-migration JSONL contract
    assert set(snap) == {
        "serve_submitted", "serve_rejected", "serve_admitted",
        "serve_completed", "serve_cancelled", "serve_expired",
        "serve_steps", "serve_decode_windows", "serve_steps_per_window",
        "serve_queue_depth", "serve_slot_capacity", "serve_slot_occupancy",
        "serve_tokens_generated", "serve_tokens_per_sec",
        "serve_ckpt_load_retries", "serve_retry_after_hint_s",
        "serve_queue_wait_p50_s", "serve_queue_wait_p95_s",
        "serve_ttft_p50_s", "serve_ttft_p95_s",
        "serve_latency_p50_s", "serve_latency_p95_s",
        "serve_step_latency_p50_s", "serve_step_latency_p95_s",
        "serve_uptime_s",
    }
    # counters serialize as ints (1 not 1.0) — the byte-compat contract
    for k in ("serve_submitted", "serve_admitted", "serve_completed",
              "serve_rejected", "serve_tokens_generated", "serve_steps",
              "serve_decode_windows", "serve_queue_depth",
              "serve_ckpt_load_retries"):
        assert isinstance(snap[k], int), k
    # percentiles are the exact list-based values, not bucket estimates
    assert snap["serve_queue_wait_p50_s"] == percentile([0.5, 1.5], 50)
    assert snap["serve_queue_wait_p95_s"] == percentile([0.5, 1.5], 95)
    assert snap["serve_step_latency_p50_s"] == percentile([0.01, 0.03], 50)
    assert snap["serve_ttft_p50_s"] == 0.2
    assert snap["serve_latency_p95_s"] == 2.0


def test_serve_metrics_empty_percentiles_are_none():
    snap = ServeMetrics(capacity=2).snapshot()
    assert snap["serve_queue_wait_p50_s"] is None
    assert snap["serve_ttft_p95_s"] is None
    assert snap["serve_tokens_per_sec"] is None


def test_serve_metrics_ckpt_load_retries_settable():
    m = ServeMetrics(capacity=2)
    m.ckpt_load_retries = 3  # serve/loader.py assigns this directly
    assert m.ckpt_load_retries == 3
    assert m.snapshot()["serve_ckpt_load_retries"] == 3


def test_serve_metrics_registry_is_queryable():
    m = ServeMetrics(capacity=2)
    _drive(m)
    c = m.registry.counter("serve_requests_total",
                           "request lifecycle events by state")
    assert c.value(state="submitted") == 2
    assert c.value(state="admitted") == 2


def test_serve_metrics_instances_do_not_share_state():
    a, b = ServeMetrics(capacity=2), ServeMetrics(capacity=2)
    a.record_submit()
    assert a.submitted == 1 and b.submitted == 0


# -- StepTimer on the registry ----------------------------------------------


def _fake_clock(monkeypatch, ticks):
    from deeplearning_cfn_tpu.runtime import profiling

    it = iter(ticks)
    monkeypatch.setattr(profiling.time, "perf_counter", lambda: next(it))


def test_step_timer_summary_has_percentiles(monkeypatch):
    from deeplearning_cfn_tpu.runtime.profiling import StepTimer

    _fake_clock(monkeypatch, [0.0, 1.0, 1.0, 2.0, 2.0, 3.5, 3.5, 4.0])
    t = StepTimer(warmup=1)
    for _ in range(4):
        t.start()
        t.stop()
    s = t.summary()
    assert t.steps == 3
    assert s["steps"] == 3
    assert s["mean_step_s"] == pytest.approx(1.0)
    assert s["p50_step_s"] == 1.0
    assert s["p95_step_s"] == pytest.approx(1.45)
    assert s["min_step_s"] == 0.5 and s["max_step_s"] == 1.5


def test_step_timer_feeds_registry_histogram(monkeypatch):
    from deeplearning_cfn_tpu.runtime.profiling import StepTimer

    _fake_clock(monkeypatch, [0.0, 1.0])
    reg = MetricsRegistry()
    t = StepTimer(warmup=0, registry=reg)
    t.start()
    t.stop()
    h = reg.histogram("step_time_s", "synced per-step wall time")
    assert h.count() == 1 and h.samples() == [1.0]


def test_step_timer_empty_summary():
    from deeplearning_cfn_tpu.runtime.profiling import StepTimer

    assert StepTimer().summary() == {"steps": 0}


# -- trace_steps hardening ---------------------------------------------------


def test_trace_steps_body_error_not_masked_by_stop(monkeypatch, tmp_path):
    from deeplearning_cfn_tpu.runtime import profiling

    monkeypatch.setattr(profiling.jax.profiler, "start_trace",
                        lambda d: None)

    def bad_stop():
        raise OSError("flush failed")

    monkeypatch.setattr(profiling.jax.profiler, "stop_trace", bad_stop)
    # body error wins; stop_trace's secondary failure is swallowed
    with pytest.raises(ValueError, match="body"):
        with profiling.trace_steps(str(tmp_path)):
            raise ValueError("body")
    # body succeeded -> stop_trace failure must surface
    with pytest.raises(OSError, match="flush"):
        with profiling.trace_steps(str(tmp_path)):
            pass


# -- lazy MetricsWriter (satellite: no jax at construction) ------------------


def test_metrics_writer_construction_is_side_effect_free(tmp_path):
    path = str(tmp_path / "sub" / "m.jsonl")
    w = MetricsWriter(path, also_stdout=False)
    # no file, no directory until the first write
    assert not os.path.exists(os.path.dirname(path))
    w.write({"a": 1})
    w.close()
    assert json.loads(open(path).read())["a"] == 1


def test_metrics_writer_all_processes_never_asks_jax(tmp_path):
    w = MetricsWriter(str(tmp_path / "m.jsonl"), also_stdout=False,
                      all_processes=True)
    assert w.enabled  # resolved without touching jax.process_index()


# -- run reports over committed fixture logs ---------------------------------


def test_summarize_train_fixture_dir():
    s = summarize(os.path.join(FIXTURES, "train"))
    assert s["source"]["files"] == 2
    assert s["source"]["records"] == 25
    assert s["source"]["skipped_lines"] == 0
    tr = s["train"]
    assert tr["last_step"] == 6
    assert 0.2 < tr["step_time_s"]["p50"] < 0.31
    assert tr["step_time_s"]["p95"] >= tr["step_time_s"]["p50"]
    assert tr["examples_per_sec"]["last"] == pytest.approx(115.15, abs=0.01)
    assert tr["examples_per_sec"]["peak"] == pytest.approx(118.36, abs=0.01)
    assert tr["loss"]["first"] == pytest.approx(2.3026, abs=1e-3)
    assert tr["compile_s"] == pytest.approx(5.258, abs=1e-2)
    assert tr["ckpt_store_retries"] == 0
    assert tr["eval"]["final_eval_accuracy"] == 0.125
    sp = s["spans"]
    assert sp["ckpt.save"]["count"] == 4  # steps 2,4,6 + final forced save
    assert "failed" not in sp["ckpt.save"]  # no failures recorded
    assert sp["train.dispatch"]["count"] == 6
    assert sp["train.realize"]["count"] == 6
    la = s["launch"]
    assert la["attempts"] == 2
    assert la["outcomes"] == ["crash", "ok"]
    assert la["success"] is True and la["restarts"] == 1


def test_summarize_serve_fixture_file():
    s = summarize(os.path.join(FIXTURES, "serve", "metrics.jsonl"))
    assert s["source"]["files"] == 1
    sv = s["serve"]
    assert sv["submitted"] == 4 and sv["admitted"] == 4
    assert sv["completed"] == 4 and sv["rejected"] == 0
    assert sv["tokens_generated"] == 16
    assert sv["tokens_per_sec"] > 0
    assert sv["queue_wait_s"]["p50"] > 0
    assert sv["ttft_s"]["p95"] >= sv["ttft_s"]["p50"]
    assert s["spans"]["serve.decode"]["count"] == 4
    assert s["spans"]["serve.admit"]["count"] == 4
    assert "train" not in s


def test_render_report_is_human_text():
    s = summarize(os.path.join(FIXTURES, "train"))
    text = render_report(s)
    assert "run report:" in text
    assert "last step" in text
    assert "launch:" in text and "crash, ok" in text


def test_summarize_skips_malformed_lines(tmp_path):
    p = tmp_path / "m.jsonl"
    p.write_text('{"step": 1, "loss": 2.0}\nnot json\n{"step": 2}\n')
    s = summarize(str(p))
    assert s["source"]["records"] == 2
    assert s["source"]["skipped_lines"] == 1
    assert s["train"]["last_step"] == 2


def test_summarize_empty_input(tmp_path):
    p = tmp_path / "m.jsonl"
    p.write_text("")
    s = summarize(str(p))
    assert s["source"]["records"] == 0
    assert "no train" in render_report(s)  # renders, no raise


# -- CLI verb ----------------------------------------------------------------


def test_cli_obs_summarize(capsys):
    from deeplearning_cfn_tpu.cli.main import main

    rc = main(["obs", "summarize", os.path.join(FIXTURES, "train")])
    assert rc == 0
    out = capsys.readouterr().out
    assert "run report:" in out and "last step" in out


def test_cli_obs_summarize_json(capsys):
    from deeplearning_cfn_tpu.cli.main import main

    rc = main(["obs", "summarize", "--json",
               os.path.join(FIXTURES, "serve", "metrics.jsonl")])
    assert rc == 0
    s = json.loads(capsys.readouterr().out)
    assert s["serve"]["completed"] == 4


def test_cli_obs_summarize_missing_path(capsys):
    from deeplearning_cfn_tpu.cli.main import main

    assert main(["obs", "summarize", "/nonexistent/m.jsonl"]) == 1
