"""End-to-end trainer over the 8-fake-device mesh — the keystone test
(SURVEY.md §8 Phase 1): sharded pjit-DP step runs, loss decreases on learnable
synthetic data, metrics stream out, checkpoint-resume continues the run.
"""

import glob
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning_cfn_tpu.config import ExperimentConfig, apply_overrides
from deeplearning_cfn_tpu.metrics import read_metrics
from deeplearning_cfn_tpu.parallel import build_mesh
from deeplearning_cfn_tpu.presets import get_preset
from deeplearning_cfn_tpu.train import create_train_state
from deeplearning_cfn_tpu.train.optim import build_optimizer, build_schedule
from deeplearning_cfn_tpu.train.run import run_experiment
from deeplearning_cfn_tpu.train.task import build_task
from deeplearning_cfn_tpu.train.trainer import Trainer


def _tiny_cfg(tmp_workdir, steps=12) -> ExperimentConfig:
    cfg = get_preset("cifar10_resnet20")
    apply_overrides(cfg, [
        f"workdir={tmp_workdir}",
        "train.global_batch=32",
        f"train.steps={steps}",
        "train.log_every_steps=4",
        "train.eval_every_steps=1000000",
        "data.num_train_examples=256",
        "data.num_eval_examples=64",
        "train.eval_batch=32",
        "data.prefetch=0",
        "schedule.name=constant",
        "schedule.base_lr=0.1",
        "schedule.warmup_epochs=0",
        "checkpoint.async_write=false",
    ])
    return cfg


def test_sharded_train_step_runs_and_learns(tmp_workdir, devices):
    cfg = _tiny_cfg(tmp_workdir, steps=32)
    mesh = build_mesh(cfg.mesh)
    assert mesh.shape["data"] == 8
    task = build_task(cfg)
    sched = build_schedule(cfg.schedule, 32, cfg.train.global_batch, 8)
    tx = build_optimizer(cfg.optimizer, sched)
    state = create_train_state(jax.random.PRNGKey(0), task.init, tx, mesh)
    trainer = Trainer(cfg, task.loss_fn, tx, mesh=mesh)

    from deeplearning_cfn_tpu.data import build_pipeline

    pipe = build_pipeline(cfg.data, cfg.train.global_batch, 10, train=True)
    it = pipe.epochs()
    rng = jax.random.PRNGKey(1)

    losses = []
    for _ in range(32):
        batch = trainer.device_batch(next(it))
        # Batch must actually be sharded over the data axis.
        assert batch["image"].addressable_shards[0].data.shape[0] == 4
        state, metrics = trainer.train_step(state, batch, rng)
        losses.append(float(metrics["loss"]))
    assert int(state.step) == 32
    assert np.isfinite(losses).all()
    # Learnable synthetic data: loss should drop clearly.
    assert np.mean(losses[-8:]) < np.mean(losses[:8]) * 0.9, losses


def test_run_experiment_end_to_end(tmp_workdir, devices):
    cfg = _tiny_cfg(tmp_workdir, steps=10)
    final = run_experiment(cfg)
    assert "accuracy" in final and np.isfinite(final["loss"])

    mpath = os.path.join(tmp_workdir, "cifar10_resnet20", "metrics.jsonl")
    records = read_metrics(mpath)
    steps_logged = [r["step"] for r in records if "examples_per_sec" in r]
    assert steps_logged, records
    assert any("final_eval_accuracy" in r for r in records)

    ckpts = glob.glob(os.path.join(tmp_workdir, "cifar10_resnet20", "ckpt",
                                   "step_*", "COMMIT"))
    assert ckpts


def test_resume_continues_from_checkpoint(tmp_workdir, devices):
    cfg = _tiny_cfg(tmp_workdir, steps=6)
    run_experiment(cfg)
    # Second run with more steps must resume (not restart): metrics log shows
    # resumed step numbers > 6.
    cfg2 = _tiny_cfg(tmp_workdir, steps=12)
    run_experiment(cfg2)
    mpath = os.path.join(tmp_workdir, "cifar10_resnet20", "metrics.jsonl")
    steps = [r["step"] for r in read_metrics(mpath) if "loss" in r]
    assert max(steps) >= 12
    # No step was trained twice from scratch: the second run's first logged
    # step is past the first run's last checkpoint.
    assert min(s for s in steps if s > 6) > 6


def test_eval_uses_global_batch(tmp_workdir, devices):
    cfg = _tiny_cfg(tmp_workdir)
    mesh = build_mesh(cfg.mesh)
    task = build_task(cfg)
    sched = build_schedule(cfg.schedule, 4, cfg.train.global_batch, 8)
    tx = build_optimizer(cfg.optimizer, sched)
    state = create_train_state(jax.random.PRNGKey(0), task.init, tx, mesh)
    trainer = Trainer(cfg, task.loss_fn, tx, mesh=mesh)
    from deeplearning_cfn_tpu.data import build_pipeline

    eval_pipe = build_pipeline(cfg.data, cfg.train.global_batch, 10,
                               train=False)
    metrics = trainer.evaluate(state, eval_pipe.one_epoch(), max_steps=2)
    assert set(metrics) >= {"loss", "accuracy", "accuracy_top5"}
    # Top-5 can never be beaten by top-1 and both are proportions.
    assert 0.0 <= metrics["accuracy"] <= metrics["accuracy_top5"] <= 1.0


def test_gradients_identical_across_mesh_layouts(tmp_workdir, devices):
    """DP sharding is numerically transparent: one step on a 8-way data mesh
    equals one step on a 1-way mesh (the correctness claim that replaces
    Horovod's allreduce-equivalence)."""
    cfg = _tiny_cfg(tmp_workdir)
    task = build_task(cfg)
    sched = build_schedule(cfg.schedule, 4, cfg.train.global_batch, 8)
    tx = build_optimizer(cfg.optimizer, sched)

    from deeplearning_cfn_tpu.config import MeshConfig
    from deeplearning_cfn_tpu.data import build_pipeline

    pipe = build_pipeline(cfg.data, cfg.train.global_batch, 10, train=True)
    batch = next(iter(pipe.one_epoch(0)))

    results = []
    for mesh_cfg in [MeshConfig(data=-1), MeshConfig(data=1, model=1)]:
        devs = jax.devices() if mesh_cfg.data == -1 else jax.devices()[:1]
        mesh = build_mesh(mesh_cfg, devices=devs)
        state = create_train_state(jax.random.PRNGKey(0), task.init, tx, mesh)
        trainer = Trainer(cfg, task.loss_fn, tx, mesh=mesh)
        dev_batch = trainer.device_batch(batch)
        state, metrics = trainer.train_step(state, dev_batch,
                                            jax.random.PRNGKey(1))
        results.append((float(metrics["loss"]),
                        np.asarray(jax.tree_util.tree_leaves(state.params)[0])))
    loss_a, w_a = results[0]
    loss_b, w_b = results[1]
    assert loss_a == pytest.approx(loss_b, rel=1e-5)
    np.testing.assert_allclose(w_a, w_b, rtol=1e-5, atol=1e-6)


def test_multi_slice_mesh_matches_single_slice(tmp_workdir, devices):
    """DCN scale-out is numerically transparent: a train step on a 2-slice
    hybrid mesh (dcn_data=2 × data=4) equals the same step on a single-slice
    data=8 mesh — the hierarchical ICI+DCN gradient reduction must sum to
    exactly the flat allreduce."""
    cfg = _tiny_cfg(tmp_workdir)
    task = build_task(cfg)
    sched = build_schedule(cfg.schedule, 4, cfg.train.global_batch, 8)
    tx = build_optimizer(cfg.optimizer, sched)

    from deeplearning_cfn_tpu.config import MeshConfig
    from deeplearning_cfn_tpu.data import build_pipeline

    pipe = build_pipeline(cfg.data, cfg.train.global_batch, 10, train=True)
    batch = next(iter(pipe.one_epoch(0)))

    results = []
    for mesh_cfg in [MeshConfig(data=-1, num_slices=2), MeshConfig(data=-1)]:
        mesh = build_mesh(mesh_cfg)
        state = create_train_state(jax.random.PRNGKey(0), task.init, tx, mesh)
        trainer = Trainer(cfg, task.loss_fn, tx, mesh=mesh)
        dev_batch = trainer.device_batch(batch)
        # The batch must really shard over both data axes on the hybrid mesh.
        assert dev_batch["image"].addressable_shards[0].data.shape[0] == 4
        for _ in range(3):
            state, metrics = trainer.train_step(state, dev_batch,
                                                jax.random.PRNGKey(1))
        results.append((float(metrics["loss"]),
                        np.asarray(jax.tree_util.tree_leaves(state.params)[0])))
    (loss_a, w_a), (loss_b, w_b) = results
    assert loss_a == pytest.approx(loss_b, rel=1e-5)
    np.testing.assert_allclose(w_a, w_b, rtol=1e-5, atol=1e-6)


def test_checkpoint_cadence_decoupled_from_log_cadence(tmp_workdir, devices):
    """Regression: periodic saves must fire even when every_steps is not a
    multiple of log_every_steps (found by driving the surface: only the final
    force-save landed)."""
    cfg = _tiny_cfg(tmp_workdir, steps=10)
    apply_overrides(cfg, ["train.log_every_steps=3",
                          "checkpoint.every_steps=4"])
    run_experiment(cfg)
    ckpts = sorted(
        os.path.basename(os.path.dirname(p)) for p in
        glob.glob(os.path.join(tmp_workdir, "cifar10_resnet20", "ckpt",
                               "step_*", "COMMIT"))
    )
    assert "step_00000004" in ckpts and "step_00000008" in ckpts, ckpts


def test_zero1_opt_state_sharding_matches_replicated(tmp_workdir, devices):
    """ZeRO-1 (train.shard_opt_state): optimizer slots shard over 'data',
    params/grads stay replicated — training must be numerically identical
    to the replicated layout, and the slots must actually be sharded."""
    cfg = _tiny_cfg(tmp_workdir)
    apply_overrides(cfg, ["optimizer.name=adamw"])  # mu/nu mirror slots
    task = build_task(cfg)
    sched = build_schedule(cfg.schedule, 4, cfg.train.global_batch, 8)
    tx = build_optimizer(cfg.optimizer, sched)

    from deeplearning_cfn_tpu.data import build_pipeline

    mesh = build_mesh(cfg.mesh)
    pipe = build_pipeline(cfg.data, cfg.train.global_batch, 10, train=True)
    batch = next(iter(pipe.one_epoch(0)))

    def count_partitioned(tree):
        return sum(
            1 for leaf in jax.tree_util.tree_leaves(tree)
            if hasattr(leaf, "addressable_shards") and leaf.ndim > 0
            and leaf.addressable_shards[0].data.shape != leaf.shape)

    results = []
    for zero1 in (True, False):
        state = create_train_state(jax.random.PRNGKey(0), task.init, tx,
                                   mesh, shard_opt_state=zero1)
        if zero1:
            # At least one mirror slot must really be partitioned: its
            # addressable shard is smaller than the global array.
            assert count_partitioned(state.opt_state) >= 10
        trainer = Trainer(cfg, task.loss_fn, tx, mesh=mesh)
        dev_batch = trainer.device_batch(batch)
        for _ in range(3):
            state, metrics = trainer.train_step(state, dev_batch,
                                                jax.random.PRNGKey(1))
        # Layout stability across steps: params must STAY replicated (no
        # GSPMD leak of the slot sharding through apply_updates) and the
        # slots must STAY sharded.
        assert count_partitioned(state.params) == 0, \
            "params became partitioned after training steps"
        if zero1:
            assert count_partitioned(state.opt_state) >= 10
        results.append((float(metrics["loss"]),
                        np.asarray(jax.tree_util.tree_leaves(state.params)[0])))
    (loss_a, w_a), (loss_b, w_b) = results
    assert loss_a == pytest.approx(loss_b, rel=1e-6)
    np.testing.assert_allclose(w_a, w_b, rtol=1e-6, atol=1e-7)


def test_training_run_deterministic(tmp_workdir, devices):
    """SURVEY §5.3's step-numerics golden test in self-consistent form: two
    fresh runs with the same seed produce bit-identical loss trajectories
    (data order, augmentation, init, and the compiled step are all
    deterministic — the reproducibility the reference never had)."""
    trajectories = []
    for run in ("a", "b"):
        cfg = _tiny_cfg(os.path.join(tmp_workdir, run), steps=8)
        apply_overrides(cfg, ["train.log_every_steps=1"])
        run_experiment(cfg)
        path = os.path.join(tmp_workdir, run, "cifar10_resnet20",
                            "metrics.jsonl")
        trajectories.append([r["loss"] for r in read_metrics(path)
                             if "loss" in r])
    assert len(trajectories[0]) == 8
    assert trajectories[0] == trajectories[1], trajectories


def test_profile_steps_captures_trace(tmp_workdir, devices):
    """train.profile_steps captures a TensorBoard-format profiler trace of
    hot-loop steps into <workdir>/<preset>/profile (SURVEY §6 tracing row
    — the Horovod-timeline role, reachable from config)."""
    cfg = _tiny_cfg(tmp_workdir, steps=4)
    apply_overrides(cfg, ["train.profile_steps=2"])
    run_experiment(cfg)
    trace_root = os.path.join(tmp_workdir, "cifar10_resnet20", "profile")
    files = [os.path.join(dp, f) for dp, _, fs in os.walk(trace_root)
             for f in fs]
    assert files, f"no trace files under {trace_root}"


def test_remat_flag_trains(tmp_workdir, devices):
    cfg = _tiny_cfg(tmp_workdir, steps=2)
    apply_overrides(cfg, ["train.remat=true"])
    final = run_experiment(cfg)
    assert np.isfinite(final["loss"])


def test_exact_eval_counts_every_example(tmp_workdir, devices):
    """The eval set does not divide the eval batch (70 % 32 != 0): with the
    padded-tail pipeline the trainer must still count ALL 70 examples, and
    the weighted accuracy must equal the directly-computed full-set value
    — not a mean of unequal batch means."""
    cfg = _tiny_cfg(tmp_workdir)
    apply_overrides(cfg, ["data.num_eval_examples=70"])
    mesh = build_mesh(cfg.mesh)
    task = build_task(cfg)
    sched = build_schedule(cfg.schedule, 4, cfg.train.global_batch, 8)
    tx = build_optimizer(cfg.optimizer, sched)
    state = create_train_state(jax.random.PRNGKey(0), task.init, tx, mesh)
    trainer = Trainer(cfg, task.loss_fn, tx, mesh=mesh)
    from deeplearning_cfn_tpu.data import build_pipeline

    eval_pipe = build_pipeline(cfg.data, cfg.train.global_batch, 10,
                               train=False, drop_remainder=False)
    metrics = trainer.evaluate(state, eval_pipe.one_epoch())
    assert metrics["examples"] == 70.0

    # Oracle: accuracy over the full set computed directly, one example at
    # a time — batch-size independent.
    correct = 0
    variables = {"params": state.params}
    if state.batch_stats:
        variables["batch_stats"] = state.batch_stats
    for batch in eval_pipe.one_epoch():
        logits = task.model.apply(variables, jnp.asarray(batch["image"]),
                                  train=False)
        pred = np.argmax(np.asarray(logits), -1)
        m = batch["eval_mask"] > 0
        correct += int((pred[m] == batch["label"][m]).sum())
    np.testing.assert_allclose(metrics["accuracy"], correct / 70.0,
                               atol=1e-6)


@pytest.mark.parametrize("unroll_mode", ["scan", "unroll"])
def test_grad_accum_matches_full_batch(devices, unroll_mode):
    """grad_accum_steps=k must give exactly the full-batch update for an
    unweighted mean loss with no BN: mean of k equal-size microbatch
    gradients == the global-batch gradient, and the optimizer runs once.

    Parametrized over BOTH lowerings: 'auto' unrolls on the CPU test
    backend, so without the explicit 'scan' leg the rolled (unroll=1)
    path production TPU runs use would have zero coverage."""
    from deeplearning_cfn_tpu.config import MeshConfig
    import optax

    cfg = _tiny_cfg("/tmp/unused")
    cfg.train.global_batch = 32

    def init_fn(rng):
        return {"params": {"w": jnp.zeros((8,), jnp.float32)}}

    def loss_fn(params, batch_stats, batch, rng, train):
        pred = batch["x"] @ params["w"]
        return jnp.mean((pred - batch["y"]) ** 2), {}

    mesh = build_mesh(MeshConfig(data=-1))
    tx = optax.sgd(0.1)
    x = np.random.RandomState(0).randn(32, 8).astype(np.float32)
    y = np.random.RandomState(1).randn(32).astype(np.float32)
    rng = jax.random.PRNGKey(0)

    results = {}
    for accum in (1, 4):
        cfg.train.grad_accum_steps = accum
        cfg.train.grad_accum_unroll = unroll_mode
        state = create_train_state(jax.random.PRNGKey(0), init_fn, tx, mesh)
        trainer = Trainer(cfg, loss_fn, tx, mesh=mesh)
        batch = trainer.device_batch({"x": x, "y": y})
        new_state, metrics = trainer.train_step(state, batch, rng)
        results[accum] = (np.asarray(new_state.params["w"]),
                          float(metrics["loss"]),
                          float(metrics["grad_norm"]))

    w1, l1, g1 = results[1]
    w4, l4, g4 = results[4]
    # f32 summation order differs (mean-of-4-means vs one mean): allow
    # a few ulps, nothing more.
    np.testing.assert_allclose(w4, w1, rtol=1e-5, atol=1e-8)
    np.testing.assert_allclose(l4, l1, rtol=1e-5)
    np.testing.assert_allclose(g4, g1, rtol=1e-5)


def test_grad_accum_trains_bn_model(tmp_workdir, devices):
    """The accumulation path must also run the full preset machinery
    (BN stats threaded through the scan carry, metrics averaged)."""
    cfg = _tiny_cfg(tmp_workdir, steps=4)
    apply_overrides(cfg, ["train.grad_accum_steps=2"])
    metrics = run_experiment(cfg)
    assert np.isfinite(metrics["loss"])


def test_grad_accum_divisibility_validated(devices):
    cfg = _tiny_cfg("/tmp/unused")
    cfg.train.global_batch = 32  # divisible by the 8 data ways, not by 3
    cfg.train.grad_accum_steps = 3
    mesh = build_mesh(cfg.mesh)

    with pytest.raises(ValueError, match="grad_accum_steps"):
        Trainer(cfg, lambda *a: None, None, mesh=mesh)


def test_plan_window_respects_cadences():
    """Pure window planning: a fused window never straddles a cadence
    multiple or an explicit boundary — they land exactly on window edges."""
    from deeplearning_cfn_tpu.train.trainer import _plan_window

    # Clamp to the next log (3) / hook (4) multiple, whichever is nearer.
    assert _plan_window(0, 100, 8, [3, 4]) == 3
    assert _plan_window(3, 100, 8, [3, 4]) == 1
    assert _plan_window(4, 100, 8, [3, 4]) == 2
    # Tail clamp: never run past num_steps.
    assert _plan_window(98, 100, 8, [100]) == 2
    # Explicit boundaries (trace start/stop) clamp too; past ones don't.
    assert _plan_window(4, 100, 8, [100], boundaries=(6, 10)) == 2
    assert _plan_window(8, 100, 8, [100], boundaries=(6, 10)) == 2
    # Zero/negative cadences are ignored; the floor is one step.
    assert _plan_window(0, 100, 8, [0, -1, 8]) == 8
    assert _plan_window(99, 100, 8, [1]) == 1


@pytest.mark.parametrize("window", [1, 4])
def test_step_window_matches_per_step_loop(tmp_workdir, devices, window):
    """The fused K-step scan (window_step) reproduces the per-step loop's
    loss trajectory and final weights: the scan body is the SAME per-step
    fn, and fold_in(rng, state.step) keyed off the in-carry step counter
    gives every fused step its canonical RNG stream. Tolerance is float-
    level (XLA's loop-body codegen can differ from the straight-line
    program by ~1 ulp), which still catches any RNG- or order-level bug."""
    cfg = _tiny_cfg(tmp_workdir)
    mesh = build_mesh(cfg.mesh)
    sched = build_schedule(cfg.schedule, 16, cfg.train.global_batch, 8)
    tx = build_optimizer(cfg.optimizer, sched)

    def init_fn(rng):
        return {"params": {"w": jnp.zeros((8, 4), jnp.float32)}}

    def loss_fn(params, stats, batch, rng, train):
        logits = batch["x"] @ params["w"]
        if train:
            # RNG inside the loss: parity must hold for stochastic steps.
            logits = logits + 0.01 * jax.random.normal(rng, logits.shape)
        return jnp.mean((logits - batch["y"]) ** 2), {}

    rs = np.random.RandomState(0)
    batches = [{"x": rs.randn(32, 8).astype(np.float32),
                "y": rs.randn(32, 4).astype(np.float32)} for _ in range(8)]
    rng = jax.random.PRNGKey(7)

    def weights(st):
        return np.asarray(jax.tree_util.tree_leaves(st.params)[0])

    state = create_train_state(jax.random.PRNGKey(0), init_fn, tx, mesh)
    trainer = Trainer(cfg, loss_fn, tx, mesh=mesh)
    ref_losses = []
    for b in batches:
        state, m = trainer.train_step(state, trainer.device_batch(b), rng)
        ref_losses.append(float(m["loss"]))
    ref_w = weights(state)

    state = create_train_state(jax.random.PRNGKey(0), init_fn, tx, mesh)
    trainer = Trainer(cfg, loss_fn, tx, mesh=mesh)
    win_losses = []
    for i in range(0, len(batches), window):
        devb = tuple(trainer.device_batch(b)
                     for b in batches[i:i + window])
        state, m = trainer.window_step(state, devb, rng)
        win_losses.extend(np.asarray(m["loss"]).reshape(-1).tolist())
    assert int(state.step) == len(batches)
    np.testing.assert_allclose(win_losses, ref_losses, rtol=1e-5,
                               atol=1e-7)
    np.testing.assert_allclose(weights(state), ref_w, rtol=1e-5,
                               atol=1e-7)


def test_step_window_preserves_cadences(tmp_workdir, devices):
    """Windowed fit keeps every cadence contract: periodic checkpoints
    COMMIT on their exact steps, eval fires on eval_every multiples, the
    watchdog stays beaten (run survives), and the metrics log carries
    compile_s once plus honest post-compile examples_per_sec."""
    cfg = _tiny_cfg(tmp_workdir, steps=8)
    apply_overrides(cfg, [
        "train.step_window=4", "train.log_every_steps=4",
        "checkpoint.every_steps=4", "train.eval_every_steps=4",
        "train.hang_timeout_s=600",
    ])
    final = run_experiment(cfg)
    assert np.isfinite(final["loss"])

    ckpts = sorted(
        os.path.basename(os.path.dirname(p)) for p in
        glob.glob(os.path.join(tmp_workdir, "cifar10_resnet20", "ckpt",
                               "step_*", "COMMIT")))
    assert "step_00000004" in ckpts and "step_00000008" in ckpts, ckpts

    records = read_metrics(
        os.path.join(tmp_workdir, "cifar10_resnet20", "metrics.jsonl"))
    eval_steps = [r["step"] for r in records
                  if any(k.startswith("eval_") for k in r)]
    assert 4 in eval_steps and 8 in eval_steps, records
    train_recs = [r for r in records if "loss" in r]
    # Async realization: windows are logged exactly once each (no
    # duplicate steps), and the final boundary flushes the latest window.
    steps_logged = [r["step"] for r in train_recs]
    assert len(steps_logged) == len(set(steps_logged)), steps_logged
    assert steps_logged[-1] == 8
    assert sum(1 for r in records if "compile_s" in r) == 1
    eps = [r["examples_per_sec"] for r in train_recs
           if "examples_per_sec" in r]
    assert all(v > 0 for v in eps)
