"""loadgen/ subsystem tests: seeded arrival processes, trace-spec
parsing, deterministic schedule generation (class mixes, budgets,
prefix-sharing groups), and the open-loop replay loop against a
scripted router — no JAX, no wall clock anywhere.
"""

import pytest

from deeplearning_cfn_tpu.loadgen import (
    LoadGenerator,
    RequestClass,
    TraceSpec,
    VirtualClock,
    bursty_arrivals,
    diurnal_arrivals,
    parse_trace_spec,
    poisson_arrivals,
    replay,
)
from deeplearning_cfn_tpu.serve.queue import OverloadError


# -- arrival processes -------------------------------------------------------


def test_poisson_arrivals_seeded_and_sorted():
    a = poisson_arrivals(10.0, 5.0, seed=7)
    b = poisson_arrivals(10.0, 5.0, seed=7)
    assert a == b                       # same seed, same draw — exactly
    assert a != poisson_arrivals(10.0, 5.0, seed=8)
    assert all(0.0 <= t < 5.0 for t in a)
    assert a == sorted(a)
    # An exponential(10/s) draw over 5s lands near 50 arrivals; the
    # band is wide on purpose (this is a distribution check, not a
    # regression pin).
    assert 20 <= len(a) <= 90


def test_poisson_arrivals_validation():
    # Zero rate or duration is a legitimate empty schedule; negatives
    # are a caller bug.
    assert poisson_arrivals(0.0, 5.0) == []
    assert poisson_arrivals(10.0, 0.0) == []
    with pytest.raises(ValueError):
        poisson_arrivals(-1.0, 5.0)
    with pytest.raises(ValueError):
        poisson_arrivals(10.0, -1.0)
    with pytest.raises(ValueError):
        bursty_arrivals(5.0, 1.0, 0.0, 0.5, 2.0)    # burst < base
    with pytest.raises(ValueError):
        diurnal_arrivals(5.0, 1.0, 4.0, 4.0)        # peak < trough
    with pytest.raises(ValueError):
        diurnal_arrivals(0.0, 1.0, 0.0, 4.0)        # period <= 0


def test_bursty_arrivals_concentrate_in_window():
    times = bursty_arrivals(base_rps=1.0, burst_rps=100.0,
                            burst_start_s=2.0, burst_s=0.5,
                            duration_s=5.0, seed=3)
    inside = [t for t in times if 2.0 <= t < 2.5]
    outside = [t for t in times if not 2.0 <= t < 2.5]
    assert len(inside) > len(outside)   # 50 expected in vs ~4.5 out
    assert times == bursty_arrivals(1.0, 100.0, 2.0, 0.5, 5.0, seed=3)


def test_diurnal_arrivals_peak_beats_trough():
    # One full period: the middle (peak of the raised cosine) must carry
    # more arrivals than the edges (trough).
    times = diurnal_arrivals(trough_rps=0.5, peak_rps=40.0,
                             period_s=6.0, duration_s=6.0, seed=5)
    mid = [t for t in times if 2.0 <= t < 4.0]
    edges = [t for t in times if t < 2.0 or t >= 4.0]
    assert len(mid) > len(edges)
    assert times == diurnal_arrivals(0.5, 40.0, 6.0, 6.0, seed=5)


# -- spec parsing ------------------------------------------------------------


def test_parse_trace_spec_presets_scale_off_bench_dims():
    spec = parse_trace_spec("burst", src_len=8, max_new_tokens=4,
                            requests=6)
    assert spec.process == "burst"
    assert spec.max_requests == 6
    assert spec.param("burst_s") == 0.1
    assert spec.param("rate") == 2.0 * 6 / 0.1     # oversample then cap
    assert spec.hot_window() == (0.0, pytest.approx(0.1))
    assert len(spec.classes) == 1
    assert spec.classes[0].src_len == 8
    assert spec.classes[0].max_new_tokens == 4


def test_parse_trace_spec_overrides_and_mix():
    spec = parse_trace_spec(
        "poisson:rate=3,duration=10,requests=5,mix=prefill-heavy",
        src_len=9, max_new_tokens=6)
    assert spec.param("rate") == 3.0
    assert spec.duration_s == 10.0
    assert spec.max_requests == 5
    names = [c.name for c in spec.classes]
    assert names == ["adversary", "stream"]
    adversary = spec.classes[0]
    assert adversary.src_len == 9 and adversary.max_new_tokens == 2


def test_parse_trace_spec_prefix_groups():
    spec = parse_trace_spec("poisson:prefix_groups=2", src_len=8)
    cls = spec.classes[0]
    assert cls.prefix_groups == 2
    assert cls.prefix_len == 4          # default src_len // 2


def test_parse_trace_spec_rejects_bad_input():
    with pytest.raises(ValueError):
        parse_trace_spec("")
    with pytest.raises(ValueError):
        parse_trace_spec("lognormal")           # unknown preset
    with pytest.raises(ValueError):
        parse_trace_spec("poisson:peak=3")      # key from another preset
    with pytest.raises(ValueError):
        parse_trace_spec("poisson:rate")        # not key=value
    with pytest.raises(ValueError):
        parse_trace_spec("poisson:rate=fast")   # not a number
    with pytest.raises(ValueError):
        parse_trace_spec("poisson:mix=spicy")   # unknown mix
    with pytest.raises(ValueError):
        parse_trace_spec("poisson:requests=0")


def test_request_class_and_spec_validation():
    with pytest.raises(ValueError):
        RequestClass("c", src_len=0, max_new_tokens=4)
    with pytest.raises(ValueError):
        RequestClass("c", src_len=4, max_new_tokens=4, weight=0.0)
    with pytest.raises(ValueError):
        RequestClass("c", src_len=4, max_new_tokens=4,
                     prefix_groups=2, prefix_len=9)   # > src_len
    with pytest.raises(ValueError):
        TraceSpec(name="x", process="sawtooth", duration_s=1.0,
                  max_requests=1, params=(),
                  classes=(RequestClass("c", 4, 4),))
    with pytest.raises(ValueError):
        TraceSpec(name="x", process="poisson", duration_s=1.0,
                  max_requests=1, params=(("rate", 1.0),), classes=())


# -- schedule generation -----------------------------------------------------


def _spec(**over):
    kw = dict(name="t", process="poisson", duration_s=4.0,
              max_requests=12, params=(("rate", 10.0),),
              classes=(RequestClass("base", src_len=6,
                                    max_new_tokens=3),))
    kw.update(over)
    return TraceSpec(**kw)


def test_schedule_deterministic_and_seed_sensitive():
    a = LoadGenerator(_spec(), seed=1).schedule
    b = LoadGenerator(_spec(), seed=1).schedule
    assert a == b
    assert a != LoadGenerator(_spec(), seed=2).schedule
    assert [s.request_id for s in a] == [f"lg-{i:04d}"
                                         for i in range(len(a))]
    assert all(len(s.src_ids) == 6 and s.max_new_tokens == 3 for s in a)
    # Prompt tokens stay inside the vocab, above the reserved ids.
    assert all(3 <= t < 96 for s in a for t in s.src_ids)


def test_schedule_honors_class_budgets():
    spec = _spec(classes=(
        RequestClass("capped", src_len=4, max_new_tokens=2, budget=2),
        RequestClass("open", src_len=4, max_new_tokens=2),
    ))
    sched = LoadGenerator(spec, seed=0).schedule
    counts = {}
    for s in sched:
        counts[s.cls] = counts.get(s.cls, 0) + 1
    assert counts.get("capped", 0) <= 2
    # When EVERY budget is exhausted the schedule ends early instead of
    # mislabeling arrivals.
    allcapped = _spec(classes=(
        RequestClass("a", src_len=4, max_new_tokens=2, budget=1),
        RequestClass("b", src_len=4, max_new_tokens=2, budget=2),
    ))
    sched = LoadGenerator(allcapped, seed=0).schedule
    assert len(sched) == 3


def test_schedule_prefix_groups_share_prefixes():
    spec = _spec(classes=(RequestClass(
        "base", src_len=8, max_new_tokens=2, prefix_groups=2,
        prefix_len=4),))
    sched = LoadGenerator(spec, seed=0).schedule
    assert len(sched) >= 4
    by_group = {}
    for s in sched:
        by_group.setdefault(s.prefix_group, []).append(s.src_ids[:4])
    assert set(by_group) == {"base/g0", "base/g1"}
    for group, prefixes in by_group.items():
        assert len(set(prefixes)) == 1       # shared within a group
    assert by_group["base/g0"][0] != by_group["base/g1"][0]


def test_schedule_prompt_corpus_replaces_random_prompts():
    corpus = [[10, 11, 12, 13, 14, 15, 16, 17], [20, 21, 22, 23]]
    spec = _spec(classes=(RequestClass("base", src_len=4,
                                       max_new_tokens=2),))
    sched = LoadGenerator(spec, seed=0, prompt_corpus=corpus).schedule
    assert list(sched[0].src_ids) == [10, 11, 12, 13]   # truncated
    assert list(sched[1].src_ids) == [20, 21, 22, 23]
    assert list(sched[2].src_ids) == [10, 11, 12, 13]   # wraps
    with pytest.raises(ValueError):
        LoadGenerator(spec, seed=0, prompt_corpus=[[]])
    with pytest.raises(ValueError):
        LoadGenerator(spec, vocab_size=3)    # vocab <= reserved


# -- virtual clock -----------------------------------------------------------


def test_virtual_clock_only_moves_forward():
    c = VirtualClock()
    assert c.read() == 0.0
    assert c.advance(0.25) == 0.25
    assert c.read() == 0.25
    with pytest.raises(ValueError):
        c.advance(-0.1)


# -- replay against a scripted router ----------------------------------------


class _ScriptedRouter:
    """Router lookalike: admits up to ``capacity`` concurrent requests,
    each finishing after ``work`` steps; rejections carry a fixed
    retry-after hint. Records every submission timestamp via the shared
    clock so the test can assert the hint was honored."""

    def __init__(self, clock, capacity=2, work=1, retry_after=None):
        self.clock = clock
        self.capacity = capacity
        self.work = work
        self.retry_after = retry_after
        self.running = {}
        self.done = set()
        self.ledger = {}
        self.submissions = []

    def submit(self, src_ids, max_new_tokens=None, request_id=None):
        if len(self.running) >= self.capacity:
            raise OverloadError(len(self.running), self.capacity,
                                retry_after_s=self.retry_after)
        self.submissions.append((request_id, self.clock.read()))
        self.running[request_id] = self.work
        self.ledger[request_id] = {"e2e_s": None}
        return request_id

    def step(self):
        for rid in list(self.running):
            self.running[rid] -= 1
            if self.running[rid] <= 0:
                del self.running[rid]
                self.done.add(rid)
        return len(self.done)

    def pending(self):
        return len(self.running)


def test_replay_open_loop_admits_everything_and_stays_virtual():
    spec = _spec(max_requests=6)
    gen = LoadGenerator(spec, seed=0)
    clock = VirtualClock()
    router = _ScriptedRouter(clock, capacity=100)
    report = replay(gen, router, clock, tick_s=0.05)
    assert [rid for rid, _ in router.submissions] == report.rids
    assert report.rejections == 0
    assert all(o["outcome"] == "admitted"
               for o in report.outcomes.values())
    # Open loop: the replay runs to the spec duration even after the
    # work drains, and offered load is schedule/duration — independent
    # of service speed.
    assert report.duration_s >= spec.duration_s
    assert report.offered_load_rps == \
        pytest.approx(len(gen.schedule) / spec.duration_s)
    # Outcomes folded into the router's ledger under "loadgen".
    assert all("loadgen" in router.ledger[rid] for rid in report.rids)


def test_replay_honors_retry_after_hint_and_drops_nothing():
    spec = _spec(max_requests=8)
    gen = LoadGenerator(spec, seed=0)
    clock = VirtualClock()
    router = _ScriptedRouter(clock, capacity=1, work=3,
                             retry_after=0.3)
    report = replay(gen, router, clock, tick_s=0.05)
    assert report.rejections > 0
    assert report.retries_honored > 0
    retried = [o for o in report.outcomes.values() if o["rejections"]]
    assert retried
    assert all(o["outcome"] == "admitted_after_retry" for o in retried)
    assert all(o["retry_after_honored"] for o in retried)
    # Zero-drop: every scheduled request was eventually admitted.
    assert set(rid for rid, _ in router.submissions) == set(report.rids)
    # The hint is real backoff: a rejected request's actual submission
    # comes at least retry_after after its scheduled arrival.
    sub_ts = dict(router.submissions)
    for rid, o in report.outcomes.items():
        if o["rejections"]:
            assert sub_ts[rid] >= o["scheduled_s"] + 0.3 - 1e-9


def test_replay_deterministic_end_to_end():
    def _run():
        gen = LoadGenerator(_spec(max_requests=8), seed=4)
        clock = VirtualClock()
        router = _ScriptedRouter(clock, capacity=1, work=2,
                                 retry_after=0.2)
        report = replay(gen, router, clock, tick_s=0.05)
        return router.submissions, report.outcomes, report.ticks

    assert _run() == _run()


def test_replay_validates_tick():
    gen = LoadGenerator(_spec(max_requests=2), seed=0)
    with pytest.raises(ValueError):
        replay(gen, _ScriptedRouter(VirtualClock()), VirtualClock(),
               tick_s=0.0)
