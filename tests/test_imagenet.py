"""Real ImageNet ingestion tests: shard format round-trip, native/python
augmentation parity (shared RNG contract), pipeline integration, converter
from a JPEG tree, and the feed-rate microbench (SURVEY.md §8 hard-part #2)."""

import json
import os

import numpy as np
import pytest

from deeplearning_cfn_tpu import dataio
from deeplearning_cfn_tpu.config import DataConfig
from deeplearning_cfn_tpu.data.imagenet import (
    IMAGENET_MEAN,
    IMAGENET_STD,
    ShardedImageNetSource,
    _crop_resize_norm_py,
    load_imagenet_source,
    measure_feed_rate,
    prepare_imagenet,
    write_shards,
)
from deeplearning_cfn_tpu.data.pipeline import build_pipeline


def _fixture_shards(tmp_path, n=40, hw=48, num_classes=5, shard_records=16,
                    seed=0):
    rng = np.random.RandomState(seed)
    images = rng.randint(0, 256, (n, hw, hw, 3), dtype=np.uint8)
    labels = rng.randint(0, num_classes, n)
    out = str(tmp_path / "train")
    write_shards(out, images, labels, num_classes,
                 shard_records=shard_records)
    return out, images, labels


def test_shard_roundtrip_multi_shard(tmp_path):
    out, images, labels = _fixture_shards(tmp_path, n=40, shard_records=16)
    with open(os.path.join(out, "index.json")) as fh:
        index = json.load(fh)
    assert len(index["shards"]) == 3  # 16 + 16 + 8
    # Explicit eval-crop contract: the center crop takes
    # EVAL_CROP_RATIO * min(h, w) regardless of shard size. At
    # image_size == round(0.875 * 48) == 42 the resize is identity, so
    # the output must be exactly the normalized central 42² window (the
    # classic resize-256/crop-224 recipe generalized).
    crop = round(0.875 * 48)
    src = ShardedImageNetSource(out, train=False, image_size=crop,
                                native=False)
    assert src.size == 40
    np.testing.assert_array_equal(src._labels, labels.astype(np.int32))
    batch = src.gather_seeded(np.asarray([7]), seed=123)
    lo = (48 - crop) // 2
    window = images[7][lo:lo + crop, lo:lo + crop]
    expect = (window.astype(np.float32) / 255.0 -
              IMAGENET_MEAN) / IMAGENET_STD
    np.testing.assert_allclose(batch["image"][0], expect, atol=1e-4)
    assert batch["label"][0] == labels[7]


def test_gather_deterministic_and_seed_sensitive(tmp_path):
    out, _, _ = _fixture_shards(tmp_path)
    src = ShardedImageNetSource(out, train=True, image_size=32,
                                native=False)
    idx = np.asarray([3, 17, 25])
    a = src.gather_seeded(idx, seed=42)
    b = src.gather_seeded(idx, seed=42)
    c = src.gather_seeded(idx, seed=43)
    np.testing.assert_array_equal(a["image"], b["image"])
    assert np.abs(a["image"] - c["image"]).max() > 1e-3


def test_eval_center_crop_seed_independent(tmp_path):
    out, _, _ = _fixture_shards(tmp_path)
    src = ShardedImageNetSource(out, train=False, image_size=32,
                                native=False)
    idx = np.asarray([1, 2])
    np.testing.assert_array_equal(src.gather_seeded(idx, 1)["image"],
                                  src.gather_seeded(idx, 2)["image"])


@pytest.mark.skipif(not dataio.available(), reason="native dataio not built")
@pytest.mark.parametrize("train", [False, True])
def test_native_python_parity(tmp_path, train):
    """The C++ kernel and the numpy fallback share one RNG contract — same
    seed must give the same crops, flips, and pixels."""
    out, _, _ = _fixture_shards(tmp_path)
    native = ShardedImageNetSource(out, train=train, image_size=32,
                                   native=True)
    assert native._native, "native path did not activate"
    fallback = ShardedImageNetSource(out, train=train, image_size=32,
                                     native=False)
    idx = np.asarray([0, 9, 21, 33])
    a = native.gather_seeded(idx, seed=7)
    b = fallback.gather_seeded(idx, seed=7)
    np.testing.assert_allclose(a["image"], b["image"], atol=1e-4)
    np.testing.assert_array_equal(a["label"], b["label"])


def test_eval_crop_rounding_parity_at_tie_size(tmp_path):
    """0.875 * 44 = 38.5 — a rounding tie. The C++ kernel and the numpy
    fallback must break it identically: the shared rule is floor(x+0.5),
    giving 39. Python's half-to-even round() would give 38 and silently
    diverge from the C++ side, so this size pins the contract."""
    from deeplearning_cfn_tpu import dataio
    from deeplearning_cfn_tpu.data.imagenet import (
        IMAGENET_MEAN,
        IMAGENET_STD,
        _crop_resize_norm_py,
    )

    if dataio.get_lib() is None:
        pytest.skip("native dataio unavailable")
    rng = np.random.RandomState(11)
    img = rng.randint(0, 256, (44, 44, 3), np.uint8)
    img = np.ascontiguousarray(img)
    ptrs = np.asarray([img.ctypes.data], np.uint64)
    a = dataio.crop_resize_norm(ptrs, (44, 44), 32, seed=5, augment=False,
                                mean=IMAGENET_MEAN, std=IMAGENET_STD)
    b = _crop_resize_norm_py([img], 32, seed=5, augment=False)
    np.testing.assert_allclose(a, b, atol=1e-4)


def test_pipeline_integration_epoch_coverage(tmp_path):
    """build_pipeline with a real shard dir: every example appears exactly
    once per epoch across processes (per-host index sharding)."""
    out, _, labels = _fixture_shards(tmp_path, n=40, num_classes=5)
    cfg = DataConfig(name="imagenet", data_dir=str(tmp_path),
                     image_size=32, prefetch=0, use_native_loader=False)
    seen = []
    for pidx in range(2):
        pipe = build_pipeline(cfg, local_batch=4, num_classes=5, seed=0,
                              train=True)
        pipe.pidx, pipe.pcount = pidx, 2
        for batch in pipe.one_epoch(0):
            assert batch["image"].shape == (4, 32, 32, 3)
            seen.extend(batch["label"].tolist())
    assert len(seen) == 40
    # Same multiset of labels as the fixture (global coverage, no dupes).
    assert sorted(seen) == sorted(labels.tolist())


def test_prepare_imagenet_from_jpeg_tree(tmp_path):
    PIL = pytest.importorskip("PIL")
    from PIL import Image

    src_dir = tmp_path / "jpeg"
    rng = np.random.RandomState(0)
    truth = {}
    for cls in ["beagle", "abacus"]:  # sorted: abacus=0, beagle=1
        (src_dir / cls).mkdir(parents=True)
        for i in range(3):
            arr = rng.randint(0, 256, (70, 90, 3), dtype=np.uint8)
            Image.fromarray(arr).save(src_dir / cls / f"img{i}.jpg",
                                      quality=95)
    out_dir = tmp_path / "shards" / "train"
    index = prepare_imagenet(str(src_dir), str(out_dir), size=64,
                             shard_records=4, log_every=0)
    assert index["num_classes"] == 2
    assert sum(s["num_records"] for s in index["shards"]) == 6
    src = ShardedImageNetSource(str(out_dir), train=False, image_size=64,
                                native=False)
    assert src.size == 6
    # Sorted class dirs define labels: abacus → 0 (first 3 records after
    # label-major ordering), beagle → 1.
    assert sorted(src._labels.tolist()) == [0, 0, 0, 1, 1, 1]


def test_load_imagenet_source_requires_index(tmp_path):
    cfg = DataConfig(name="imagenet", data_dir=str(tmp_path))
    with pytest.raises(FileNotFoundError, match="index.json"):
        load_imagenet_source(cfg, train=True)


def test_feed_rate_microbench(tmp_path):
    out, _, _ = _fixture_shards(tmp_path, n=64)
    cfg = DataConfig(name="imagenet", data_dir=str(tmp_path),
                     image_size=32, prefetch=2,
                     use_native_loader=dataio.available())
    pipe = build_pipeline(cfg, local_batch=8, num_classes=5, seed=0,
                          train=True)
    rate = measure_feed_rate(pipe, num_batches=6, warmup=1)
    assert rate["images_per_sec"] > 0
    assert rate["batch_size"] == 8.0
