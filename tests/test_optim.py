"""Optimizer/schedule factories — every recipe the five workloads use."""

import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning_cfn_tpu.config import OptimizerConfig, ScheduleConfig
from deeplearning_cfn_tpu.train.optim import build_optimizer, build_schedule


def test_cosine_with_warmup():
    cfg = ScheduleConfig(name="cosine", base_lr=1.0, warmup_steps=10)
    sched = build_schedule(cfg, total_steps=110, global_batch=128)
    assert float(sched(0)) == pytest.approx(0.0)
    assert float(sched(10)) == pytest.approx(1.0, abs=1e-6)
    assert float(sched(110)) == pytest.approx(0.0, abs=1e-3)


def test_linear_scaling_rule():
    cfg = ScheduleConfig(name="constant", base_lr=0.1, scale_with_batch=True,
                         reference_batch=256)
    sched = build_schedule(cfg, 100, global_batch=1024)
    assert float(sched(50)) == pytest.approx(0.4)


def test_step_schedule_factors():
    cfg = ScheduleConfig(name="step", base_lr=1.0,
                         step_boundaries=(0.5, 0.75),
                         step_factors=(0.1, 0.01))
    sched = build_schedule(cfg, 100, 128)
    assert float(sched(10)) == pytest.approx(1.0)
    assert float(sched(60)) == pytest.approx(0.1)
    assert float(sched(90)) == pytest.approx(0.01)


def test_rsqrt_transformer_schedule():
    cfg = ScheduleConfig(name="rsqrt", base_lr=1.0, warmup_steps=100)
    sched = build_schedule(cfg, 10_000, 128)
    peak = float(sched(99))
    assert float(sched(10)) < peak
    assert float(sched(5000)) < peak


@pytest.mark.parametrize("name", ["sgd", "momentum", "adamw", "adam", "lars",
                                  "lamb", "adafactor"])
def test_optimizers_step(name):
    cfg = OptimizerConfig(name=name, weight_decay=1e-4, grad_clip_norm=1.0)
    sched = build_schedule(ScheduleConfig(name="constant", base_lr=0.1), 10, 8)
    tx = build_optimizer(cfg, sched)
    params = {"w": jnp.ones((4, 4)), "b": jnp.zeros((4,))}
    opt_state = tx.init(params)
    grads = {"w": jnp.ones((4, 4)) * 0.5, "b": jnp.ones((4,))}
    updates, _ = tx.update(grads, opt_state, params)
    new_w = params["w"] + updates["w"]
    assert not np.allclose(np.asarray(new_w), np.asarray(params["w"]))
    assert np.all(np.isfinite(np.asarray(new_w)))


def test_unknown_names_raise():
    with pytest.raises(ValueError):
        build_optimizer(OptimizerConfig(name="bogus"), lambda s: 0.1)
    with pytest.raises(ValueError):
        build_schedule(ScheduleConfig(name="bogus"), 10, 8)


def test_schedules_work_under_jit():
    """Regression: schedules run on a traced step inside the compiled train
    step — no Python branching on tracers allowed."""
    import jax

    for name, kw in [("rsqrt", dict(warmup_steps=10)),
                     ("cosine", dict(warmup_steps=5)),
                     ("step", dict(step_boundaries=(0.5,), step_factors=(0.1,)))]:
        cfg = ScheduleConfig(name=name, base_lr=1.0, **kw)
        sched = build_schedule(cfg, 100, 128)
        val = jax.jit(sched)(jnp.asarray(50, jnp.int32))
        assert np.isfinite(float(val))


def test_step_boundaries_are_fractions_of_total_steps():
    """Boundaries measured against TOTAL steps (incl. warmup), per config."""
    cfg = ScheduleConfig(name="step", base_lr=1.0, warmup_steps=20,
                         step_boundaries=(0.5,), step_factors=(0.1,))
    sched = build_schedule(cfg, 100, 128)
    assert float(sched(45)) == pytest.approx(1.0)
    assert float(sched(55)) == pytest.approx(0.1)
