"""Bench harness: wrapper parsing (stage diagnosis, record contract) and a
tiny real run of the in-package measurement on the CPU backend."""

import importlib.util
import json
import os
import subprocess
import sys

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_wrapper():
    spec = importlib.util.spec_from_file_location(
        "root_bench", os.path.join(REPO_ROOT, "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_wrapper_parses_contract_record():
    w = _load_wrapper()
    out = "\n".join([
        "noise",
        json.dumps({"metric": "m", "value": 1.5, "unit": "u"}),
        "[other] trailing line",
    ])
    rec = w._parse_record(out)
    assert rec == {"metric": "m", "value": 1.5, "unit": "u"}
    assert w._parse_record("no json here") is None
    assert w._parse_record("{broken") is None


def test_wrapper_extracts_last_stage():
    w = _load_wrapper()
    err = ("[bench-stage] t=+0.0s start preset=x\n"
           "[bench-stage] t=+0.1s import_jax\n"
           "some warning\n"
           "[bench-stage] t=+0.2s backend_init\n")
    assert w._last_stage(err) == "t=+0.2s backend_init"
    assert w._last_stage(err.encode()) == "t=+0.2s backend_init"
    assert "no stage marker" in w._last_stage("")
    assert "no stage marker" in w._last_stage(None)


def test_bench_child_measures_on_cpu():
    """The child process measures a tiny preset on the forced-CPU backend,
    prints the contract JSON with measured=true, and emits every stage
    marker through 'done' on stderr."""
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=1")
    proc = subprocess.run(
        [sys.executable, "-m", "deeplearning_cfn_tpu.bench",
         "--preset", "cifar10_resnet20", "--steps", "3", "--warmup", "1",
         "--global-batch", "32"],
        capture_output=True, text=True, timeout=300, cwd=REPO_ROOT, env=env)
    assert proc.returncode == 0, proc.stderr[-2000:]
    rec = json.loads(proc.stdout.strip().splitlines()[-1])
    assert rec["measured"] is True
    assert rec["value"] > 0
    assert rec["unit"] == "images/sec/chip"
    assert rec["global_batch"] == 32
    for name in ("start", "import_jax", "backend_init", "devices_ok",
                 "build", "first_compile", "warmup", "timed", "done"):
        assert f"s {name}" in proc.stderr, (name, proc.stderr[-2000:])
