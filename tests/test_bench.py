"""Bench harness: wrapper parsing (stage diagnosis, record contract) and a
tiny real run of the in-package measurement on the CPU backend."""

import importlib.util
import json
import os
import subprocess
import sys

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_wrapper():
    spec = importlib.util.spec_from_file_location(
        "root_bench", os.path.join(REPO_ROOT, "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_wrapper_parses_contract_record():
    w = _load_wrapper()
    out = "\n".join([
        "noise",
        json.dumps({"metric": "m", "value": 1.5, "unit": "u"}),
        "[other] trailing line",
    ])
    rec = w._parse_record(out)
    assert rec == {"metric": "m", "value": 1.5, "unit": "u"}
    assert w._parse_record("no json here") is None
    assert w._parse_record("{broken") is None


def test_wrapper_extracts_last_stage():
    w = _load_wrapper()
    err = ("[bench-stage] t=+0.0s start preset=x\n"
           "[bench-stage] t=+0.1s import_jax\n"
           "some warning\n"
           "[bench-stage] t=+0.2s backend_init\n")
    assert w._last_stage(err) == "t=+0.2s backend_init"
    assert w._last_stage(err.encode()) == "t=+0.2s backend_init"
    assert "no stage marker" in w._last_stage("")
    assert "no stage marker" in w._last_stage(None)


def test_annotate_record_labels():
    """Fallback + underfill labels (r03 Weak #4/#5): seq-parallel presets on
    a seq=1 mesh are flagged as dense fallbacks; a bench batch below the
    preset's is flagged underfilled; healthy configs stay unlabeled."""
    from deeplearning_cfn_tpu.bench import annotate_record

    r = annotate_record({}, "bert_long_wikipedia", {"data": 1, "seq": 1},
                        gb=8, preset_gb=256)
    assert r["fallback"] is True
    assert "NOT a ring/Ulysses" in r["fallback_note"]
    assert r["batch_underfilled"] is True and r["preset_global_batch"] == 256

    r = annotate_record({}, "gpt_long_lm", {"data": 2, "seq": 4},
                        gb=64, preset_gb=64)
    assert r["fallback"] is False
    assert "fallback_note" not in r and "batch_underfilled" not in r

    r = annotate_record({}, "imagenet_resnet50", {"data": 8}, 512, 8192)
    assert "fallback" not in r
    assert r["batch_underfilled"] is True


def test_pipelined_mfu_uses_dense_twin_flops():
    """The GPipe preset's MFU numerator must come from the dense twin: the
    scanned trunk's own cost analysis under-counts by ~ticks x layers
    (r03 Weak #3). Compare the two counts at tiny matched shapes on CPU."""
    import jax

    from deeplearning_cfn_tpu.bench import _dense_equiv_flops, _flops_of
    from deeplearning_cfn_tpu.config import apply_overrides
    from deeplearning_cfn_tpu.data import build_pipeline
    from deeplearning_cfn_tpu.parallel.mesh import build_mesh, \
        local_batch_size
    from deeplearning_cfn_tpu.config import MeshConfig
    from deeplearning_cfn_tpu.presets import get_preset
    from deeplearning_cfn_tpu.train import create_train_state
    from deeplearning_cfn_tpu.train.optim import build_optimizer, \
        build_schedule
    from deeplearning_cfn_tpu.train.task import build_task
    from deeplearning_cfn_tpu.train.trainer import Trainer

    cfg = get_preset("bert_pipelined_wikipedia")
    cfg.train.global_batch = 8
    cfg.train.grad_accum_steps = 1
    cfg.data.seq_len = 32
    cfg.data.vocab_size = 128
    cfg.model.kwargs.update(hidden_size=32, num_layers=4, num_heads=2,
                            mlp_dim=64, max_len=32, n_microbatches=4)
    apply_overrides(cfg, ["data.prefetch=0", "data.synthetic=true"])
    cfg.data.num_train_examples = 8
    cfg.data.num_eval_examples = 8
    mesh = build_mesh(MeshConfig(data=-1))

    task = build_task(cfg, mesh=mesh)
    tx = build_optimizer(cfg.optimizer, build_schedule(cfg.schedule, 1000,
                                                       8, 100))
    state = create_train_state(jax.random.PRNGKey(0), task.init, tx, mesh,
                               param_rules=getattr(task, "param_rules", ()),
                               shard_opt_state=cfg.train.shard_opt_state)
    trainer = Trainer(cfg, task.loss_fn, tx, mesh=mesh)
    pipe = build_pipeline(cfg.data, local_batch_size(8, mesh),
                          cfg.model.num_classes, seed=0, train=True)
    dev_batch = trainer.device_batch(next(iter(pipe.one_epoch(0))))
    compiled = trainer.train_step.lower(
        state, dev_batch, jax.random.PRNGKey(1)).compile()
    scanned = _flops_of(compiled)
    dense = _dense_equiv_flops("bert_pipelined_wikipedia", cfg, mesh, 8)
    assert dense is not None and scanned is not None
    # The dense twin must count (substantially) more than the scanned
    # program whose trunk body is counted once: 4 layers x (4+S-1) ticks.
    assert dense > 1.5 * scanned, (dense, scanned)


def test_wrapper_red_record_has_null_value(tmp_path):
    """A red (unmeasured) contract record must carry null value/vs_baseline/
    mfu — never 0.0, which an aggregator would average in as a real zero
    (r4 verdict weak #6). Drive the wrapper end-to-end with a preset the
    child rejects so both attempts fail fast."""
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=1",
               DLCFN_BENCH_PRESET="no_such_preset",
               DLCFN_BENCH_TOTAL_BUDGET_S="240",
               DLCFN_BENCH_ARTIFACT_DIR=str(tmp_path))
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "bench.py")],
        capture_output=True, text=True, timeout=300, cwd=REPO_ROOT, env=env)
    assert proc.returncode == 0, proc.stderr[-2000:]
    rec = json.loads(proc.stdout.strip().splitlines()[-1])
    assert rec["measured"] is False
    assert rec["value"] is None
    assert rec["vs_baseline"] is None
    assert rec["mfu"] is None
    assert "no_such_preset" in rec["error"] or "attempt" in rec["error"]


def test_finalize_green_nulls_cpu_fallback(monkeypatch):
    """A child that completed on the silent CPU fallback of a dead
    accelerator plugin must come out measured=false with null value/
    vs_baseline/mfu (raw number preserved as cpu_fallback_value) — a CPU
    throughput against the TPU contract is worse than a fake zero."""
    w = _load_wrapper()
    monkeypatch.delenv("JAX_PLATFORMS", raising=False)
    rec = w._finalize_green(
        {"value": 12.3, "vs_baseline": 0.03, "mfu": 0.01,
         "device_kind": "cpu"},
        alive=False, probe_note="probe: accelerator plugin dead")
    assert rec["measured"] is False
    assert rec["value"] is None and rec["vs_baseline"] is None
    assert rec["mfu"] is None
    assert rec["cpu_fallback_value"] == 12.3

    # Explicitly-requested CPU (tests, operator smoke) stays green.
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    rec = w._finalize_green({"value": 12.3, "device_kind": "cpu"},
                            alive=True, probe_note="probe: cpu alive")
    assert rec["measured"] is True and rec["value"] == 12.3

    # A real chip record with the probe alive is untouched.
    monkeypatch.delenv("JAX_PLATFORMS", raising=False)
    rec = w._finalize_green({"value": 2413.7, "device_kind": "TPU v5e"},
                            alive=True, probe_note="probe: tpu alive")
    assert rec["measured"] is True and rec["value"] == 2413.7


def test_finalize_green_nulls_any_unmeasured_record(monkeypatch):
    """Null-over-zero is not fallback-specific: a child that itself said
    measured=false (for any reason) must not ship numeric value/
    vs_baseline/mfu through the green path — even on a live accelerator
    with no CPU fallback in sight."""
    w = _load_wrapper()
    monkeypatch.delenv("JAX_PLATFORMS", raising=False)
    rec = w._finalize_green(
        {"measured": False, "value": 99.9, "vs_baseline": 0.5, "mfu": 0.4,
         "device_kind": "TPU v5e", "error": "child: warmup diverged"},
        alive=True, probe_note="probe: tpu alive")
    assert rec["measured"] is False
    assert rec["value"] is None
    assert rec["vs_baseline"] is None
    assert rec["mfu"] is None
    # No fake fallback diagnosis was attached — the child's error stands.
    assert rec["error"] == "child: warmup diverged"
    assert "cpu_fallback_value" not in rec


def test_finalize_green_nulls_serving_perf_fields_when_unmeasured(
        monkeypatch):
    """The serving-scenario perf fields (speculation/quantization) follow
    the same null-over-zero rule on measured=false — and are left alone
    on records that never carried them."""
    w = _load_wrapper()
    monkeypatch.delenv("JAX_PLATFORMS", raising=False)
    rec = w._finalize_green(
        {"measured": False, "value": 99.9, "spec_gamma": 2,
         "spec_accept_rate": 0.9, "tokens_per_target_step": 2.5,
         "weight_bytes": 12345, "device_kind": "TPU v5e",
         "error": "child: warmup diverged"},
        alive=True, probe_note="probe: tpu alive")
    for key in ("spec_gamma", "spec_accept_rate",
                "tokens_per_target_step", "weight_bytes"):
        assert rec[key] is None
    rec = w._finalize_green(
        {"measured": False, "value": 1.0, "device_kind": "TPU v5e",
         "error": "x"}, alive=True, probe_note="probe: tpu alive")
    assert "spec_gamma" not in rec  # key set untouched when absent


def test_bench_child_measures_on_cpu():
    """The child process measures a tiny preset on the forced-CPU backend,
    prints the contract JSON with measured=true, and emits every stage
    marker through 'done' on stderr."""
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=1")
    proc = subprocess.run(
        [sys.executable, "-m", "deeplearning_cfn_tpu.bench",
         "--preset", "cifar10_resnet20", "--steps", "3", "--warmup", "1",
         "--global-batch", "32"],
        capture_output=True, text=True, timeout=300, cwd=REPO_ROOT, env=env)
    assert proc.returncode == 0, proc.stderr[-2000:]
    rec = json.loads(proc.stdout.strip().splitlines()[-1])
    assert rec["measured"] is True
    assert rec["value"] > 0
    assert rec["unit"] == "images/sec/chip"
    assert rec["global_batch"] == 32
    for name in ("start", "import_jax", "backend_init", "devices_ok",
                 "build", "first_compile", "warmup", "timed", "done"):
        assert f"s {name}" in proc.stderr, (name, proc.stderr[-2000:])


def test_finalize_green_keeps_forced_cpu_measurement(monkeypatch):
    """A run the wrapper itself forced to JAX_PLATFORMS=cpu (no accelerator
    platform would initialize) is a real, labeled measurement: measured
    stays true with the numeric value, and forced_platform marks that it
    must not be read as a chip number."""
    w = _load_wrapper()
    monkeypatch.delenv("JAX_PLATFORMS", raising=False)
    rec = w._finalize_green(
        {"value": 12.3, "vs_baseline": 0.03, "mfu": 0.0,
         "device_kind": "cpu"},
        alive=False, probe_note="probe: backend_init hung >40s",
        forced_cpu=True)
    assert rec["measured"] is True
    assert rec["value"] == 12.3
    assert rec["forced_platform"] == "cpu"
    assert "cpu_fallback_value" not in rec


@pytest.mark.slow
def test_wrapper_forces_cpu_when_accelerator_dead(tmp_path):
    """End-to-end on a host with no accelerator: the probe reads jax's
    silent CPU fallback as a dead plugin, the cpu probe comes up, and the
    attempts run forced to JAX_PLATFORMS=cpu — a green, labeled CPU
    measurement instead of five rounds of measured=false (r05)."""
    env = dict(os.environ,
               XLA_FLAGS="--xla_force_host_platform_device_count=1",
               DLCFN_BENCH_PRESET="cifar10_resnet20",
               DLCFN_BENCH_STEPS="3", DLCFN_BENCH_WARMUP="1",
               DLCFN_BENCH_GLOBAL_BATCH="32",
               DLCFN_BENCH_TOTAL_BUDGET_S="400",
               DLCFN_BENCH_ARTIFACT_DIR=str(tmp_path))
    env.pop("JAX_PLATFORMS", None)  # accelerator-less: probe must go red
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "bench.py")],
        capture_output=True, text=True, timeout=500, cwd=REPO_ROOT, env=env)
    assert proc.returncode == 0, proc.stderr[-2000:]
    rec = json.loads(proc.stdout.strip().splitlines()[-1])
    assert rec["measured"] is True, rec
    assert rec["forced_platform"] == "cpu"
    assert rec["value"] > 0
    assert rec["device_kind"] == "cpu"
    assert "forced JAX_PLATFORMS=cpu" in rec["probe"]
