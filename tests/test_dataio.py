"""Tests for the native C++ data loader (dataio): build, numerics vs the
Python path, determinism, and pipeline integration."""

import numpy as np
import pytest

from deeplearning_cfn_tpu import dataio
from deeplearning_cfn_tpu.data.pipeline import (
    ArraySource,
    DataPipeline,
    augment_crop_flip,
)

pytestmark = pytest.mark.skipif(not dataio.available(),
                                reason="no C++ toolchain for dataio")


def test_builds_and_loads():
    assert dataio.get_lib() is not None


def test_gather_matches_numpy():
    rng = np.random.RandomState(0)
    src = rng.rand(32, 8, 8, 3).astype(np.float32)
    idx = np.asarray([5, 1, 30, 5], np.int32)
    out = dataio.gather_augment(src, idx, pad=4, seed=7, augment=False)
    np.testing.assert_array_equal(out, src[idx])


def test_gather_rows_matches_numpy():
    rng = np.random.RandomState(0)
    f = rng.rand(16, 10).astype(np.float32)
    i = rng.randint(0, 100, (16, 7)).astype(np.int32)
    idx = np.asarray([3, 3, 0, 15], np.int32)
    np.testing.assert_array_equal(dataio.gather_rows(f, idx), f[idx])
    np.testing.assert_array_equal(dataio.gather_rows(i, idx), i[idx])


def test_augment_deterministic_and_valid():
    rng = np.random.RandomState(1)
    src = rng.rand(8, 16, 16, 3).astype(np.float32)
    idx = np.arange(8, dtype=np.int32)
    a = dataio.gather_augment(src, idx, pad=4, seed=99, augment=True)
    b = dataio.gather_augment(src, idx, pad=4, seed=99, augment=True,
                              nthreads=1)  # thread count must not matter
    np.testing.assert_array_equal(a, b)
    c = dataio.gather_augment(src, idx, pad=4, seed=100, augment=True)
    assert not np.array_equal(a, c)
    # Every output pixel value exists in the source image (crop/flip only
    # rearranges reflect-padded pixels).
    for k in range(8):
        assert np.isin(a[k].ravel(), src[k].ravel()).all()


def test_pipeline_uses_native_path():
    rng = np.random.RandomState(2)
    src = ArraySource({
        "image": rng.rand(64, 8, 8, 3).astype(np.float32),
        "label": rng.randint(0, 10, 64).astype(np.int32),
    })
    pipe = DataPipeline(src, local_batch=16, seed=0,
                        augment=augment_crop_flip, prefetch=0,
                        process_index=0, process_count=1, native=True)
    assert pipe._native
    batches = list(pipe.one_epoch(0))
    assert len(batches) == 4
    assert batches[0]["image"].shape == (16, 8, 8, 3)
    assert batches[0]["label"].dtype == np.int32
    # Same pipeline twice → identical stream (seeded augmentation).
    batches2 = list(pipe.one_epoch(0))
    np.testing.assert_array_equal(batches[0]["image"],
                                  batches2[0]["image"])
    # Python fallback yields the same examples (labels), different aug RNG.
    pipe_py = DataPipeline(src, local_batch=16, seed=0,
                           augment=augment_crop_flip, prefetch=0,
                           process_index=0, process_count=1, native=False)
    np.testing.assert_array_equal(batches[0]["label"],
                                  next(iter(pipe_py.one_epoch(0)))["label"])
