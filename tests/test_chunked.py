"""Chunked prefill (engine --prefill-chunk): the stall-free admission
path must be a pure scheduling change.

The load-bearing guarantee is parity-by-construction: a chunk-completion
tick re-runs the full-width prefill (the partial encodes are
provisional), so chunked output is token-identical to the one-shot
engine for every chunk size, window, cache layout, and search mode. On
top of that ride the scheduling contracts: QoS priority orders the
chunk quota, preemption mid-prefill loses zero tokens, the router's
phase ledger stays honest (queue_wait ends at the first chunk,
prefill_s sums the chunk ticks), and the overload hint covers the
prompt-token backlog.
"""

import math
import os

import jax
import numpy as np
import pytest

from deeplearning_cfn_tpu.data.bpe import NMT_SPECIALS, train_bpe
from deeplearning_cfn_tpu.models import decoding
from deeplearning_cfn_tpu.models.transformer_nmt import transformer_nmt_tiny
from deeplearning_cfn_tpu.serve import (
    Engine,
    OverloadError,
    RequestQueue,
    RequestState,
    ServeMetrics,
)

DATA_DIR = os.path.join(os.path.dirname(__file__), "data")

SRC_LEN = 16
NEW_TOKENS = 8


def _sliver_lines(lang):
    with open(os.path.join(DATA_DIR, f"wmt_sliver.{lang}")) as fh:
        return [ln.strip() for ln in fh if ln.strip()]


@pytest.fixture(scope="module")
def chunk_setup():
    bpe = train_bpe(_sliver_lines("de") + _sliver_lines("en"),
                    vocab_size=300, specials=NMT_SPECIALS)
    model = transformer_nmt_tiny(vocab_size=bpe.vocab_size, hidden_size=32,
                                 num_layers=1, num_heads=2, mlp_dim=64,
                                 max_len=32)
    variables = model.init(
        jax.random.PRNGKey(1), np.zeros((1, SRC_LEN), np.int32),
        np.ones((1, SRC_LEN), np.int32),
        np.zeros((1, SRC_LEN), np.int32), train=False)
    variables = {"params": variables["params"]}
    srcs = []
    for line in _sliver_lines("de")[:5]:
        ids = bpe.encode(line)[:SRC_LEN - 1]
        srcs.append(ids + [decoding.EOS_ID])
    return model, variables, srcs


@pytest.fixture(scope="module")
def unchunked_refs(chunk_setup):
    """One unchunked engine drain per search mode × cache layout — the
    shared reference the whole parity grid compares against (an offline
    decode per grid cell would blow the tier-1 budget)."""
    model, variables, srcs = chunk_setup
    refs = {}
    for beam in (1, 2):
        for kv in (0, 4):
            eng = Engine(model, variables, capacity=4, max_src_len=SRC_LEN,
                         default_max_new_tokens=NEW_TOKENS,
                         kv_block_size=kv)
            reqs = [eng.submit(s, beam_size=beam) for s in srcs]
            eng.run_until_drained()
            refs[(beam, kv)] = [list(eng.poll(r.id).tokens) for r in reqs]
    # The two cache layouts must already agree before chunking enters.
    assert refs[(1, 0)] == refs[(1, 4)]
    assert refs[(2, 0)] == refs[(2, 4)]
    return refs


# -- parity grid -------------------------------------------------------------


@pytest.mark.parametrize("chunk", [3, 8, 32],
                         ids=["chunk3", "chunk8", "chunk-ge-src"])
@pytest.mark.parametrize("window", [1, 4])
@pytest.mark.parametrize("kv", [0, 4], ids=["dense", "paged"])
@pytest.mark.parametrize("beam", [1, 2], ids=["greedy", "beam"])
def test_chunked_prefill_token_parity(chunk_setup, unchunked_refs, chunk,
                                      window, kv, beam):
    """Every grid cell — chunk smaller than, comparable to, and >= the
    source length; fused window on/off; dense and paged KV; greedy and
    beam — produces tokens identical to the one-shot engine."""
    model, variables, srcs = chunk_setup
    eng = Engine(model, variables, capacity=4, max_src_len=SRC_LEN,
                 default_max_new_tokens=NEW_TOKENS, decode_window=window,
                 kv_block_size=kv, prefill_chunk=chunk)
    reqs = [eng.submit(s, beam_size=beam) for s in srcs]
    eng.run_until_drained()
    got = [list(eng.poll(r.id).tokens) for r in reqs]
    assert got == unchunked_refs[(beam, kv)]
    for r, s in zip(reqs, srcs):
        req = eng.poll(r.id)
        assert req.state is RequestState.DONE
        assert req.prefill_chunks == math.ceil(len(s) / chunk)
        assert req.prefill_s is not None and req.prefill_s >= 0.0


def test_chunk_cursor_progress_and_group_parking(chunk_setup):
    """Mid-flight observability of the chunk pipeline: an admitted
    request sits in PREFILLING (counted active, holding rows) until its
    cursor covers the source, then joins the fused decode window."""
    model, variables, srcs = chunk_setup
    eng = Engine(model, variables, capacity=2, max_src_len=SRC_LEN,
                 default_max_new_tokens=NEW_TOKENS, decode_window=1,
                 prefill_chunk=4)
    src = srcs[0]
    ticks = math.ceil(len(src) / 4)
    r = eng.submit(src)
    eng.step()
    assert eng.poll(r.id).state is RequestState.PREFILLING
    assert eng.active_requests == 1 and eng.active_rows == 1
    for _ in range(ticks - 1):
        assert eng.poll(r.id).state is RequestState.PREFILLING
        eng.step()
    assert eng.poll(r.id).state is RequestState.RUNNING
    eng.run_until_drained()
    assert eng.poll(r.id).state is RequestState.DONE
    assert eng.poll(r.id).prefill_chunks == ticks


# -- QoS interaction ---------------------------------------------------------


def test_latency_chunks_outrank_batch_flood(chunk_setup):
    """The chunk quota is a fair-share dimension: a latency-class head
    drains its source ahead of an earlier-admitted batch prompt, so the
    interactive stream reaches decode while the flood is still
    encoding."""
    model, variables, srcs = chunk_setup
    eng = Engine(model, variables, capacity=2, max_src_len=SRC_LEN,
                 default_max_new_tokens=NEW_TOKENS, decode_window=1,
                 prefill_chunk=8)
    batch = eng.submit(srcs[0], tenant="tenant-b", qos_class="batch")
    lat = eng.submit(srcs[1], tenant="tenant-a", qos_class="latency")
    ticks = math.ceil(len(srcs[1]) / 8)
    for _ in range(ticks):
        eng.step()
    # The latency stream got the whole quota first despite FIFO
    # admission order; the batch prompt has not finished encoding.
    assert eng.poll(lat.id).state is RequestState.RUNNING
    assert eng.poll(batch.id).state is RequestState.PREFILLING
    eng.run_until_drained()
    assert eng.poll(batch.id).state is RequestState.DONE
    assert eng.poll(lat.id).state is RequestState.DONE


def test_preempt_mid_prefill_resumes_with_zero_token_loss(chunk_setup,
                                                          unchunked_refs):
    """A half-prefilled batch victim has decoded nothing — eviction
    reclaims its rows and KV commit, the audit trivially balances, and
    the replayed attempt re-chunks from scratch to identical tokens."""
    model, variables, srcs = chunk_setup
    eng = Engine(model, variables, capacity=1, max_src_len=SRC_LEN,
                 default_max_new_tokens=NEW_TOKENS, decode_window=1,
                 prefill_chunk=4)
    batch = eng.submit(srcs[0], tenant="tenant-b", qos_class="batch")
    eng.step()   # admits + first chunk: batch is mid-prefill on row 0
    assert eng.poll(batch.id).state is RequestState.PREFILLING
    lat = eng.submit(srcs[1], max_new_tokens=2, tenant="tenant-a",
                     qos_class="latency")
    eng.run_until_drained()
    assert eng.metrics.preemptions >= 1
    assert eng.metrics.qos_token_loss == 0
    # Nothing was decoded before the eviction, so no replay either.
    assert eng.metrics.preempted_tokens_replayed == 0
    assert eng.poll(lat.id).state is RequestState.DONE
    req = eng.poll(batch.id)
    assert req.state is RequestState.DONE
    assert list(req.tokens) == unchunked_refs[(1, 0)][0]
    # Chunk ticks accumulate across both attempts: one before the
    # eviction plus the full re-encode afterwards.
    assert req.prefill_chunks > math.ceil(len(srcs[0]) / 4)


# -- router phase ledger -----------------------------------------------------


def test_router_ledger_accounts_chunked_phases(chunk_setup):
    """The fleet ledger stays honest under chunking: the phase split
    gains the chunk-tick count, prefill_s covers the accumulated chunk
    time, and queue_wait + prefill + stall + decode still reconstructs
    the e2e latency exactly."""
    from deeplearning_cfn_tpu.fleet import EngineReplica, Router

    model, variables, srcs = chunk_setup
    eng = Engine(model, variables, capacity=2, max_src_len=SRC_LEN,
                 default_max_new_tokens=NEW_TOKENS, decode_window=1,
                 prefill_chunk=4)
    router = Router([EngineReplica("replica-0", eng)],
                    policy="round_robin")
    rid = router.submit(srcs[0], max_new_tokens=NEW_TOKENS)
    router.run_until_drained()
    assert router.result(rid)["state"] == "done"
    entry = router.ledger[rid]
    phases = entry["phases"]
    assert phases["prefill_chunks"] == math.ceil(len(srcs[0]) / 4)
    # queue_wait ended at admission — the same tick the FIRST chunk ran
    # — so the chunk time lives in prefill_s, not in the wait.
    assert phases["prefill_s"] >= 0.0
    assert phases["queue_wait_s"] >= 0.0
    # The phases reconstruct e2e up to the router-submit → engine-submit
    # dispatch gap (sub-ms, owned by no phase). Double-counting the
    # chunk ticks in both queue_wait and prefill_s — the bug this test
    # pins — would be off by the whole multi-tick encode, far past this
    # tolerance.
    total = (phases["queue_wait_s"] + phases["prefill_s"]
             + phases["stall_s"] + phases["decode_s"]
             + phases["emit_s"])
    assert total == pytest.approx(entry["e2e_s"], abs=0.05)
    # Token conservation: every decoded token is goodput (no waste on a
    # clean single-attempt run).
    assert entry["goodput_tokens"] == len(router.result(rid)["tokens"])
    assert entry["wasted_tokens"] == 0


def test_unchunked_ledger_has_no_chunk_phase(chunk_setup):
    """Requests that never chunked keep the exact pre-chunking phase key
    set — the ledger surface is conditional, not a new default."""
    from deeplearning_cfn_tpu.fleet import EngineReplica, Router

    model, variables, srcs = chunk_setup
    eng = Engine(model, variables, capacity=2, max_src_len=SRC_LEN,
                 default_max_new_tokens=NEW_TOKENS)
    router = Router([EngineReplica("replica-0", eng)],
                    policy="round_robin")
    rid = router.submit(srcs[0], max_new_tokens=NEW_TOKENS)
    router.run_until_drained()
    assert "prefill_chunks" not in router.ledger[rid]["phases"]


# -- overload hint -----------------------------------------------------------


def test_retry_after_covers_prefill_chunk_backlog():
    """With chunked prefill armed, a rejection's retry-after includes
    draining the prompt-token backlog (queued + in-flight partial) at
    the chunk quota per tick — a prompt flood yields honestly longer
    hints than a decode-bound queue of equal depth."""
    t = {"now": 0.0}
    q = RequestQueue(max_depth=1, clock=lambda: t["now"])
    q.submit([5] * 6, 4)
    with pytest.raises(OverloadError) as ei:
        q.submit([5, 2], 4)
    base = ei.value.retry_after_s

    q2 = RequestQueue(max_depth=1, clock=lambda: t["now"])
    q2.configure_prefill_chunk(4)
    q2.note_prefill_backlog(10)
    q2.submit([5] * 6, 4)
    with pytest.raises(OverloadError) as ei:
        q2.submit([5, 2], 4)
    # (10 in-flight + 6 queued) tokens / 4 per tick = 4 ticks at the
    # cold-start floor, on top of the base hint.
    floor = RequestQueue.DEFAULT_RETRY_AFTER_FLOOR_S
    assert ei.value.retry_after_s == pytest.approx(base + 4 * floor)
    with pytest.raises(ValueError):
        q2.configure_prefill_chunk(-1)


# -- metrics surface ---------------------------------------------------------


def test_chunk_metrics_surface_is_conditional():
    """serve_chunk_* keys appear only on chunk-configured engines —
    unchunked snapshots keep the exact pre-chunking key set."""
    m = ServeMetrics(capacity=4)
    assert not any(k.startswith("serve_chunk") for k in m.snapshot())
    m.configure_chunked_prefill(8)
    m.record_chunk_tick(chunks=2, tokens=16, partial_rows=1,
                        decode_active=True)
    m.record_chunk_prefill_done(3)
    snap = m.snapshot()
    assert snap["serve_chunk_size"] == 8
    assert snap["serve_chunk_ticks"] == 1
    assert snap["serve_chunk_tokens"] == 16
    assert snap["serve_chunk_partial_rows"] == 1
    assert snap["serve_chunk_stall_ticks_avoided"] == 1
    assert snap["serve_chunk_ticks_per_prefill_p50"] == 3


def test_engine_snapshot_gains_chunk_keys_only_when_armed(chunk_setup):
    model, variables, srcs = chunk_setup
    plain = Engine(model, variables, capacity=2, max_src_len=SRC_LEN,
                   default_max_new_tokens=NEW_TOKENS)
    assert not any(k.startswith("serve_chunk")
                   for k in plain.metrics.snapshot())
    eng = Engine(model, variables, capacity=2, max_src_len=SRC_LEN,
                 default_max_new_tokens=NEW_TOKENS, prefill_chunk=4)
    r = eng.submit(srcs[0])
    eng.run_until_drained()
    assert eng.poll(r.id).state is RequestState.DONE
    snap = eng.metrics.snapshot()
    assert snap["serve_chunk_size"] == 4
    assert snap["serve_chunk_ticks"] >= math.ceil(len(srcs[0]) / 4)
    assert snap["serve_chunk_tokens"] >= len(srcs[0])


# -- validation --------------------------------------------------------------


def test_prefill_chunk_requires_colocated_phase(chunk_setup):
    model, variables, _ = chunk_setup
    for phase in ("prefill", "decode"):
        with pytest.raises(ValueError, match="co-located"):
            Engine(model, variables, capacity=2, max_src_len=SRC_LEN,
                   kv_block_size=4, phase=phase, prefill_chunk=4)
    with pytest.raises(ValueError):
        Engine(model, variables, capacity=2, max_src_len=SRC_LEN,
               prefill_chunk=-1)


def test_fleet_bench_rejects_chunked_disagg():
    from deeplearning_cfn_tpu.fleet.bench import run_fleet_bench

    with pytest.raises(ValueError, match="co-located"):
        run_fleet_bench(smoke=True, prefill_replicas=1, decode_replicas=1,
                        prefill_chunk=4)
