"""Mixture-of-Experts / expert parallelism (models/moe.py, 'expert' axis).

The reference has no MoE (SURVEY.md §3.2 lists EP as absent); these tests
hold the rebuild's extension to the same bar as the other parallelism
strategies: routing math proven against a per-token dense recomputation,
and the expert-parallel mesh proven numerically invisible vs pure DP while
the expert weights are asserted actually sharded.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning_cfn_tpu.config import (
    DataConfig,
    ExperimentConfig,
    MeshConfig,
    ModelConfig,
    OptimizerConfig,
    ScheduleConfig,
    TrainConfig,
)
from deeplearning_cfn_tpu.models.moe import MoeMlp, router_assignment


def test_router_assignment_places_and_drops():
    """Top-1, E=2, C=1: first token claiming each expert keeps its slot,
    later tokens overflowing capacity are dropped."""
    probs = jnp.asarray([[[0.9, 0.1],   # -> expert 0, slot 0
                          [0.8, 0.2],   # -> expert 0, over capacity: drop
                          [0.3, 0.7]]])  # -> expert 1, slot 0
    dispatch, combine = router_assignment(probs, capacity=1, top_k=1)
    assert dispatch.shape == (1, 3, 2, 1)
    np.testing.assert_allclose(dispatch[0, 0, 0, 0], 1.0)
    np.testing.assert_allclose(jnp.sum(dispatch[0, 1]), 0.0)  # dropped
    np.testing.assert_allclose(dispatch[0, 2, 1, 0], 1.0)
    # Top-1 gates renormalize to 1.0 for kept tokens.
    np.testing.assert_allclose(combine[0, 0, 0, 0], 1.0)
    np.testing.assert_allclose(combine[0, 2, 1, 0], 1.0)


def test_router_assignment_top2_priority():
    """First choices claim capacity before any second choice: with E=2, C=2
    and three tokens all preferring expert 0, the third token's FIRST
    choice loses to capacity but its second choice (expert 1) fits."""
    probs = jnp.asarray([[[0.6, 0.4],
                          [0.7, 0.3],
                          [0.8, 0.2]]])
    dispatch, _ = router_assignment(probs, capacity=2, top_k=2)
    per_expert = jnp.sum(dispatch, axis=(1, 3))  # [B, E] kept counts
    assert per_expert[0, 0] == 2  # tokens 0, 1 first-choice slots
    assert per_expert[0, 1] == 2  # capacity 2: tokens 0, 1 second choices
    # Token 2 got nothing: expert 0 full from first choices, expert 1 full
    # from higher-priority second choices of tokens 0 and 1.
    assert jnp.sum(dispatch[0, 2]) == 0


@pytest.mark.parametrize("top_k", [1, 2])
def test_moe_matches_dense_per_token(top_k):
    """With capacity ample enough that nothing drops, MoE output equals the
    dense per-token mixture: y[t] = sum_k gate_k * expert_k_mlp(x[t])."""
    b, s, f, m, e = 2, 8, 16, 32, 4
    moe = MoeMlp(num_experts=e, mlp_dim=m, capacity_factor=float(e),
                 top_k=top_k, dtype=jnp.float32)
    x = jax.random.normal(jax.random.PRNGKey(0), (b, s, f), jnp.float32)
    variables = moe.init(jax.random.PRNGKey(1), x)
    y, aux = moe.apply(variables, x)
    p = variables["params"]

    logits = x @ np.asarray(p["router"]["kernel"])
    probs = jax.nn.softmax(logits, axis=-1)
    w_in = np.asarray(p["w_in"])
    b_in = np.asarray(p["b_in"])
    w_out = np.asarray(p["w_out"])
    b_out = np.asarray(p["b_out"])

    expected = np.zeros((b, s, f), np.float32)
    for bi in range(b):
        for si in range(s):
            pr = np.asarray(probs[bi, si])
            order = np.argsort(-pr)[:top_k]
            gates = pr[order] / pr[order].sum()
            for gate, ei in zip(gates, order):
                h = np.asarray(jax.nn.gelu(
                    x[bi, si] @ w_in[ei] + b_in[ei]))
                expected[bi, si] += gate * (h @ w_out[ei] + b_out[ei])
    np.testing.assert_allclose(np.asarray(y), expected, atol=1e-4)
    assert float(aux["load_balance"]) >= 1.0 - 1e-5  # E*sum(f*p) >= 1
    assert np.isfinite(float(aux["router_z"]))


def _run_bert_moe(mesh_cfg, steps=10):
    from deeplearning_cfn_tpu.data import build_pipeline
    from deeplearning_cfn_tpu.parallel.mesh import build_mesh
    from deeplearning_cfn_tpu.train import create_train_state
    from deeplearning_cfn_tpu.train.optim import build_optimizer, \
        build_schedule
    from deeplearning_cfn_tpu.train.task import build_task
    from deeplearning_cfn_tpu.train.trainer import Trainer

    cfg = ExperimentConfig(
        model=ModelConfig(name="bert_tiny", num_classes=2,
                          kwargs=dict(vocab_size=64, hidden_size=32,
                                      num_layers=2, num_heads=2,
                                      mlp_dim=64, max_len=32,
                                      num_experts=4, moe_every=2)),
        data=DataConfig(name="wikipedia_mlm", seq_len=32, vocab_size=64,
                        num_train_examples=256, prefetch=0),
        train=TrainConfig(global_batch=32, dtype="float32"),
        optimizer=OptimizerConfig(name="adamw", weight_decay=0.01),
        schedule=ScheduleConfig(name="constant", base_lr=3e-3,
                                warmup_steps=0),
        mesh=mesh_cfg,
    )
    mesh = build_mesh(cfg.mesh)
    task = build_task(cfg)
    sched = build_schedule(cfg.schedule, 100, 32, 8)
    tx = build_optimizer(cfg.optimizer, sched)
    state = create_train_state(jax.random.PRNGKey(0), task.init, tx, mesh,
                               param_rules=task.param_rules)
    trainer = Trainer(cfg, task.loss_fn, tx, mesh=mesh, donate=False)
    pipe = build_pipeline(cfg.data, 32, 2, seed=0, train=True)
    it = pipe.epochs()
    losses, metrics = [], {}
    for _ in range(steps):
        batch = trainer.device_batch(next(it))
        state, m = trainer.train_step(state, batch, jax.random.PRNGKey(1))
        losses.append(float(m["loss"]))
        metrics = m
    return state, losses, metrics


def test_expert_parallel_matches_data_parallel(devices):
    """bert_tiny with 4 experts trained 10 steps on a (data=4, expert=2)
    mesh reproduces the pure-DP (data=8) run — same loss trajectory, same
    final params — while the stacked expert weights are actually sharded
    over 'expert'."""
    state_ep, loss_ep, metrics = _run_bert_moe(MeshConfig(data=4, expert=2))
    state_dp, loss_dp, _ = _run_bert_moe(MeshConfig(data=8))

    # Expert weights actually partitioned: local shard dim0 < global E.
    n_sharded = 0
    for leaf in jax.tree_util.tree_leaves(state_ep.params):
        spec = getattr(getattr(leaf, "sharding", None), "spec", None)
        if spec is None or not len(spec):
            continue
        flat = []
        for s in spec:
            flat.extend(s if isinstance(s, tuple) else [s])
        if "expert" in flat:
            n_sharded += 1
            assert leaf.addressable_shards[0].data.shape[0] \
                == leaf.shape[0] // 2
    assert n_sharded >= 4, f"expected >=4 expert-sharded leaves, {n_sharded}"

    np.testing.assert_allclose(loss_ep, loss_dp, rtol=2e-4)
    # Params: atol 1e-3 — the expert einsums reduce in a different order
    # on the (data, expert) mesh, and 10 optimizer steps accumulate that
    # float32 noise; anything semantic (mis-routed tokens, wrong psum)
    # shows up orders of magnitude larger AND in the loss check above.
    for a, b in zip(jax.tree_util.tree_leaves(state_ep.params),
                    jax.tree_util.tree_leaves(state_dp.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-3)

    # The MoE aux metrics surface through the trainer.
    assert "moe_load_balance" in metrics and "moe_router_z" in metrics
    # 10 adamw steps on the tiny task must move the loss.
    assert loss_ep[-1] < loss_ep[0]
