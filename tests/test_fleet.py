"""fleet/ subsystem tests: routing policies, failover, circuit breaking,
process supervision, fleet observability, and the end-to-end zero-drop /
token-parity contract.

The policy suite runs over FAKE replicas (a scripted engine lookalike, no
JAX, no wall-clock) so every routing decision is deterministic and
replayable; the end-to-end tests drive real engines on the tiny NMT model
and pin the fleet's aggregate output token-for-token against a
single-engine run of the same trace — through a mid-stream rolling
upgrade and through a chaos kill.
"""

import io
import itertools
import json
import os
import sys

import pytest

from deeplearning_cfn_tpu.fleet import (
    EngineReplica,
    FleetOverloadError,
    NoReplicasError,
    ReplicaCrashed,
    ReplicaProcSpec,
    ReplicaState,
    ReplicaSupervisor,
    Router,
    rolling_upgrade,
)
from deeplearning_cfn_tpu.runtime.faults import FaultPlan, FaultSpec
from deeplearning_cfn_tpu.serve.queue import (
    OverloadError,
    Request,
    RequestState,
)


# -- fakes -------------------------------------------------------------------


class _FakeQueue:
    def __init__(self, max_depth):
        self.max_depth = max_depth
        self.items = []

    @property
    def depth(self):
        return len(self.items)


class _FakeMetrics:
    def __init__(self):
        self.step_latency_s = []
        self.tokens_generated = 0
        self.last_retry_after_s = None


class FakeEngine:
    """Engine lookalike with scripted behavior: bounded queue, ``capacity``
    slots, every admitted request finishes after ``work`` steps. ``fail_on``
    is a set of step-call indices (1-based) that raise RuntimeError — the
    breaker tests script consecutive-vs-interleaved failures with it."""

    def __init__(self, capacity=2, queue_depth=8, retry_after=None,
                 work=1, fail_on=()):
        self.capacity = capacity
        self.queue = _FakeQueue(queue_depth)
        self.metrics = _FakeMetrics()
        self.retry_after = retry_after
        self.work = work
        self.fail_on = set(fail_on)
        self.step_calls = 0
        self.variables = {"params": "v0"}
        self._running = {}   # request id -> steps remaining
        self._by_id = {}

    @property
    def active_requests(self):
        return len(self._running)

    def submit(self, src_ids, max_new_tokens=None, beam_size=1,
               deadline_s=None, request_id=None, trace_id=None):
        if self.queue.depth >= self.queue.max_depth:
            raise OverloadError(self.queue.depth, self.queue.max_depth,
                                retry_after_s=self.retry_after)
        rid = request_id if request_id is not None \
            else f"fake-{len(self._by_id)}"
        req = Request(id=rid, src_ids=list(src_ids),
                      max_new_tokens=max_new_tokens or 4,
                      beam_size=beam_size, trace_id=trace_id)
        self.queue.items.append(req)
        self._by_id[rid] = req
        return req

    def poll(self, request_id):
        if request_id not in self._by_id:
            raise KeyError(request_id)
        return self._by_id[request_id]

    def cancel(self, request_id):
        req = self.poll(request_id)
        if req.finished:
            return False
        req.state = RequestState.CANCELLED
        if req in self.queue.items:
            self.queue.items.remove(req)
        self._running.pop(req.id, None)
        return True

    def step(self):
        self.step_calls += 1
        if self.step_calls in self.fail_on:
            raise RuntimeError(f"scripted step failure {self.step_calls}")
        while self.queue.items and len(self._running) < self.capacity:
            req = self.queue.items.pop(0)
            if req.finished:
                continue
            req.state = RequestState.RUNNING
            self._running[req.id] = self.work
        decoded = 0
        for rid in list(self._running):
            req = self._by_id[rid]
            self._running[rid] -= 1
            req.tokens.append(1)
            decoded += 1
            self.metrics.tokens_generated += 1
            if self._running[rid] <= 0:
                req.state = RequestState.DONE
                req.finished_at = 0.0
                del self._running[rid]
        return decoded

    def run_until_drained(self, max_steps=1_000_000, **_):
        steps = 0
        while (self.queue.items or self._running) and steps < max_steps:
            self.step()
            steps += 1
        return steps

    def swap_variables(self, variables):
        if self.queue.items or self._running:
            raise RuntimeError("swap_variables requires an idle engine")
        self.variables = variables


def _fake_replica(rid, **kwargs):
    fault_plan = kwargs.pop("fault_plan", None)
    return EngineReplica(rid, FakeEngine(**kwargs), fault_plan=fault_plan)


def _placements(router, rids):
    return [router._requests[rid].replica_id for rid in rids]


# -- policies ----------------------------------------------------------------


def test_round_robin_cycles_in_id_order():
    reps = [_fake_replica(f"replica-{i}", capacity=8, queue_depth=8)
            for i in range(3)]
    router = Router(reps, policy="round_robin")
    rids = [router.submit([5, 4, 3]) for _ in range(6)]
    assert _placements(router, rids) == [
        "replica-0", "replica-1", "replica-2",
        "replica-0", "replica-1", "replica-2"]


def test_round_robin_stable_under_removal_and_readmission():
    reps = {f"replica-{i}": _fake_replica(f"replica-{i}", capacity=8,
                                          queue_depth=8)
            for i in range(3)}
    router = Router(list(reps.values()), policy="round_robin")
    first = router.submit([5, 4, 3])
    assert _placements(router, [first]) == ["replica-0"]
    # The cursor is an id, not an index: with replica-1 gone the rotation
    # resumes at the next id above the cursor, deterministically.
    router.remove("replica-1")
    rids = [router.submit([5, 4, 3]) for _ in range(3)]
    assert _placements(router, rids) == [
        "replica-2", "replica-0", "replica-2"]
    # Re-admission slots it back into the same total order.
    router.add(reps["replica-1"])
    rids = [router.submit([5, 4, 3]) for _ in range(3)]
    assert _placements(router, rids) == [
        "replica-0", "replica-1", "replica-2"]


def test_round_robin_skips_drained_replica():
    reps = [_fake_replica(f"replica-{i}", capacity=8, queue_depth=8)
            for i in range(3)]
    router = Router(reps, policy="round_robin")
    router.drain("replica-1")
    rids = [router.submit([5, 4, 3]) for _ in range(4)]
    assert _placements(router, rids) == [
        "replica-0", "replica-2", "replica-0", "replica-2"]
    # Readmitted: the cursor (at replica-2) wraps, and replica-1 is back
    # in the rotation exactly where its id sorts.
    router.readmit("replica-1")
    rids = [router.submit([5, 4, 3]) for _ in range(3)]
    assert _placements(router, rids) == [
        "replica-0", "replica-1", "replica-2"]


def test_least_loaded_prefers_emptiest_and_ties_break_by_id():
    reps = [_fake_replica(f"replica-{i}", capacity=8, queue_depth=8)
            for i in range(2)]
    router = Router(reps, policy="least_loaded")
    # Tied (both empty) → lowest id wins, deterministically.
    a = router.submit([5, 4, 3])
    assert _placements(router, [a]) == ["replica-0"]
    # replica-0 now carries work → next goes to replica-1; then tied
    # again at one request each → replica-0.
    b = router.submit([5, 4, 3])
    c = router.submit([5, 4, 3])
    assert _placements(router, [b, c]) == ["replica-1", "replica-0"]


def test_least_loaded_ties_break_by_step_latency():
    fast = _fake_replica("replica-0", capacity=8, queue_depth=8)
    slow = _fake_replica("replica-1", capacity=8, queue_depth=8)
    # Equal load, but replica-0 has a slower decode history: the tie
    # goes to the faster replica despite its lower id losing the id
    # tiebreak order (latency sorts before id).
    fast.engine.metrics.step_latency_s = [0.5, 0.5]
    slow.engine.metrics.step_latency_s = [0.01, 0.01]
    router = Router([fast, slow], policy="least_loaded")
    rid = router.submit([5, 4, 3])
    assert _placements(router, [rid]) == ["replica-1"]


def test_round_robin_stable_under_membership_churn():
    """The autoscaler interleaves add()/remove() with live submissions;
    the id-cursor must keep a fair rotation across every churn — never
    double-placing one replica in a window or skipping a live one."""
    reps = {f"replica-{i}": _fake_replica(f"replica-{i}", capacity=8,
                                          queue_depth=64)
            for i in range(3)}
    router = Router([reps["replica-0"], reps["replica-1"]],
                    policy="round_robin")

    def place(n):
        return _placements(router,
                           [router.submit([5, 4, 3]) for _ in range(n)])

    assert place(3) == ["replica-0", "replica-1", "replica-0"]
    # Join mid-rotation (cursor sits at replica-0): the newcomer enters
    # exactly where its id sorts, nobody is double-placed.
    router.add(reps["replica-2"])
    assert place(3) == ["replica-1", "replica-2", "replica-0"]
    # Drain in-flight work so removal is churn, not evacuation.
    router.run_until_drained()
    router.remove("replica-1")
    assert place(3) == ["replica-2", "replica-0", "replica-2"]
    # Re-admission mid-stream: same total order, no skip on the wrap.
    router.run_until_drained()
    router.add(_fake_replica("replica-1", capacity=8, queue_depth=64))
    assert place(4) == ["replica-0", "replica-1", "replica-2",
                        "replica-0"]
    # Removing the replica the cursor points AT: rotation resumes at
    # the next id above the stale cursor, deterministically.
    router.run_until_drained()
    router.remove("replica-0")
    assert place(3) == ["replica-1", "replica-2", "replica-1"]
    assert router.stats()["dropped_requests"] == 0


def test_least_loaded_stable_under_membership_churn():
    """least_loaded under churn: a newcomer (emptiest) wins the next
    placement, and evacuation off a removed member re-places onto the
    emptiest CURRENT member — membership is read live, never cached."""
    reps = {f"replica-{i}": _fake_replica(f"replica-{i}", capacity=8,
                                          queue_depth=64)
            for i in range(3)}
    router = Router([reps["replica-0"], reps["replica-1"]],
                    policy="least_loaded")
    a = router.submit([5, 4, 3])
    b = router.submit([5, 4, 3])
    assert _placements(router, [a, b]) == ["replica-0", "replica-1"]
    router.add(reps["replica-2"])
    c = router.submit([5, 4, 3])         # newcomer is emptiest
    d = router.submit([5, 4, 3])         # tie at 1 each -> lowest id
    assert _placements(router, [c, d]) == ["replica-2", "replica-0"]
    # Remove the newcomer while its request is still queued: the
    # evacuated copy lands on the emptiest survivor (replica-1 at 1,
    # vs replica-0 at 2), not on a stale view that includes replica-2.
    router.remove("replica-2")
    assert _placements(router, [c]) == ["replica-1"]
    router.run_until_drained()
    assert all(router.result(r)["state"] == "done"
               for r in (a, b, c, d))
    assert router.stats()["dropped_requests"] == 0


# -- shedding / overload -----------------------------------------------------


def test_fleet_overload_propagates_max_retry_after():
    reps = [
        _fake_replica("replica-0", capacity=1, queue_depth=1,
                      retry_after=0.5),
        _fake_replica("replica-1", capacity=1, queue_depth=1,
                      retry_after=2.0),
    ]
    router = Router(reps, policy="round_robin")
    router.submit([5, 4, 3])
    router.submit([5, 4, 3])
    with pytest.raises(FleetOverloadError) as ei:
        router.submit([5, 4, 3])
    # Shedding propagates the MAX hint upstream — retrying sooner than
    # the slowest replica's estimate just bounces off the same walls.
    assert ei.value.retry_after_s == 2.0
    assert ei.value.per_replica == {"replica-0": 0.5, "replica-1": 2.0}
    assert isinstance(ei.value, OverloadError)   # existing loops work
    # The rejected request is NOT retained (the caller owns the retry).
    assert router.stats()["requests"] == 2


def test_no_replicas_error_when_nothing_routable():
    reps = [_fake_replica("replica-0")]
    router = Router(reps)
    router.drain("replica-0")
    with pytest.raises(NoReplicasError):
        router.submit([5, 4, 3])


def test_duplicate_request_id_rejected():
    router = Router([_fake_replica("replica-0")])
    router.submit([5, 4, 3], request_id="x")
    with pytest.raises(ValueError):
        router.submit([5, 4, 3], request_id="x")


# -- circuit breaking / crash failover ---------------------------------------


def test_breaker_opens_after_consecutive_failures_then_readmit():
    bad = _fake_replica("replica-0", fail_on=(1, 2))
    good = _fake_replica("replica-1")
    router = Router([bad, good], policy="round_robin",
                    breaker_threshold=2)
    rid = router.submit([5, 4, 3])
    assert _placements(router, [rid]) == ["replica-0"]
    router.step()   # scripted failure 1 — breaker still closed
    assert bad.state is ReplicaState.HEALTHY
    router.step()   # scripted failure 2 — breaker opens
    assert bad.state is ReplicaState.BROKEN
    # The in-flight request was cancelled locally and re-placed on the
    # survivor; it still finishes — nothing dropped.
    assert _placements(router, [rid]) == ["replica-1"]
    router.run_until_drained()
    assert router.result(rid)["state"] == "done"
    assert router.stats()["dropped_requests"] == 0
    # Readmission closes the breaker with a clean failure count.
    router.readmit("replica-0")
    assert bad.state is ReplicaState.HEALTHY and bad.routable


def test_breaker_failure_count_resets_on_success():
    # Failures on calls 1 and 3, success on 2: never two CONSECUTIVE
    # failures, so a threshold of 2 must not open.
    flaky = _fake_replica("replica-0", fail_on=(1, 3), work=5)
    router = Router([flaky], breaker_threshold=2)
    router.submit([5, 4, 3])
    for _ in range(4):
        router.step()
    assert flaky.state is ReplicaState.HEALTHY


def test_crash_failover_resubmits_with_zero_drops():
    # at_calls is 0-based per site: crash on replica-0's FIRST step.
    plan = FaultPlan([FaultSpec(op="step", key="replica-0", kind="crash",
                                at_calls=(0,))])
    victim = _fake_replica("replica-0", fault_plan=plan)
    survivor = _fake_replica("replica-1")
    router = Router([victim, survivor], policy="round_robin")
    a = router.submit([5, 4, 3])
    b = router.submit([6, 5, 4])
    assert _placements(router, [a, b]) == ["replica-0", "replica-1"]
    router.run_until_drained()
    assert victim.state is ReplicaState.DOWN and victim.crashed
    # The victim's request was resubmitted to the survivor and finished.
    assert _placements(router, [a, b]) == ["replica-1", "replica-1"]
    assert router.result(a)["state"] == "done"
    assert router.result(b)["state"] == "done"
    assert router.stats()["dropped_requests"] == 0
    assert router.evacuations == 1
    # A dead replica cannot be readmitted — restart it instead.
    with pytest.raises(ReplicaCrashed):
        router.readmit("replica-0")


def test_crashed_fleet_backlogs_until_capacity_returns():
    plan = FaultPlan([FaultSpec(op="step", key="replica-0", kind="crash",
                                at_calls=(0,))])
    victim = _fake_replica("replica-0", fault_plan=plan)
    router = Router([victim])
    rid = router.submit([5, 4, 3])
    router.step()   # crash; nowhere to evacuate → backlog, not a drop
    assert router.result(rid)["state"] == "backlogged"
    assert router.stats()["backlog"] == 1
    # Capacity returns → the backlog drains on the next tick.
    router.add(_fake_replica("replica-1"))
    router.run_until_drained()
    assert router.result(rid)["state"] == "done"
    assert router.stats()["dropped_requests"] == 0


# -- rolling upgrade (fakes) -------------------------------------------------


def test_rolling_upgrade_drains_swaps_probes_readmits():
    reps = [_fake_replica(f"replica-{i}", work=2) for i in range(2)]
    router = Router(reps, policy="round_robin")
    rids = [router.submit([5, 4, 3]) for _ in range(4)]
    new_vars = {"params": "v1"}
    report = rolling_upgrade(router, new_vars)
    assert report.ok and report.upgraded == ["replica-0", "replica-1"]
    for res in report.results:
        assert res.drained and res.swapped and res.probe_ok \
            and res.readmitted
    for rep in reps:
        assert rep.engine.variables is new_vars
        assert rep.state is ReplicaState.HEALTHY
    router.run_until_drained()
    assert all(router.result(r)["state"] == "done" for r in rids)
    assert router.stats()["dropped_requests"] == 0


def test_rolling_upgrade_skips_replica_crashed_during_drain():
    plan = FaultPlan([FaultSpec(op="step", key="replica-0", kind="crash",
                                at_calls=(1,))])
    victim = _fake_replica("replica-0", fault_plan=plan, work=3)
    healthy = _fake_replica("replica-1", work=1)
    router = Router([victim, healthy], policy="round_robin")
    rids = [router.submit([5, 4, 3]) for _ in range(2)]
    report = rolling_upgrade(router, {"params": "v1"})
    by_id = {r.replica: r for r in report.results}
    assert by_id["replica-0"].skipped == "crashed during drain"
    assert by_id["replica-1"].readmitted
    assert report.ok   # a chaos kill is not an upgrade FAILURE
    router.run_until_drained()
    assert all(router.result(r)["state"] == "done" for r in rids)
    assert router.stats()["dropped_requests"] == 0


# -- process supervision -----------------------------------------------------


def _proc_spec(tmp_path, rid, code):
    return ReplicaProcSpec(replica_id=rid, argv=[sys.executable, "-c", code],
                           run_dir=str(tmp_path / rid))


def _launch_events(tmp_path, rid):
    path = tmp_path / rid / "logs" / "launch.jsonl"
    with open(path) as fh:
        return [json.loads(ln) for ln in fh if ln.strip()]


def test_supervisor_runs_replicas_to_ok(tmp_path):
    specs = [_proc_spec(tmp_path, f"replica-{i}", "print('serving')")
             for i in range(2)]
    sup = ReplicaSupervisor(specs, poll_interval_s=0.02)
    sup.start()
    assert sup.wait(timeout_s=30)
    sup.close()
    assert sup.status_states() == {"replica-0": "ok", "replica-1": "ok"}
    for i in range(2):
        evs = _launch_events(tmp_path, f"replica-{i}")
        assert [e["outcome"] for e in evs
                if e.get("event") == "launch_attempt"] == ["ok"]


def test_supervisor_restarts_crash_within_budget(tmp_path):
    # First run crashes, the restart succeeds: a marker file scripts the
    # state across attempts.
    marker = tmp_path / "attempted"
    code = (f"import os,sys; p=r'{marker}'\n"
            f"sys.exit(0) if os.path.exists(p) else "
            f"(open(p,'w').close(), sys.exit(3))")
    sup = ReplicaSupervisor([_proc_spec(tmp_path, "replica-0", code)],
                            max_restarts=1, poll_interval_s=0.02)
    sup.start()
    assert sup.wait(timeout_s=30)
    sup.close()
    st = sup.status()[0]
    assert st["state"] == "ok" and st["outcomes"] == ["crash", "ok"]
    evs = [e for e in _launch_events(tmp_path, "replica-0")
           if e.get("event") == "launch_attempt"]
    assert [e["outcome"] for e in evs] == ["crash", "ok"]
    assert [e["attempt"] for e in evs] == [0, 1]
    # Each attempt also leaves a launch.attempt span in the same stream,
    # carrying the hang-vs-crash classification as a span attribute.
    spans = [e for e in _launch_events(tmp_path, "replica-0")
             if e.get("span") == "launch.attempt"]
    assert [s["outcome"] for s in spans] == ["crash", "ok"]
    assert [s["ok"] for s in spans] == [False, True]
    assert [s["attempt"] for s in spans] == [0, 1]
    assert all(s["dur_s"] >= 0.0 for s in spans)


def test_supervisor_gives_up_after_restart_budget(tmp_path):
    sup = ReplicaSupervisor(
        [_proc_spec(tmp_path, "replica-0", "import sys; sys.exit(7)")],
        max_restarts=1, poll_interval_s=0.02)
    sup.start()
    assert sup.wait(timeout_s=30) is False
    sup.close()
    st = sup.status()[0]
    assert st["state"] == "failed"
    assert st["outcomes"] == ["crash", "crash"]


def test_supervisor_rejects_duplicate_ids(tmp_path):
    with pytest.raises(ValueError):
        ReplicaSupervisor([
            _proc_spec(tmp_path, "replica-0", "pass"),
            _proc_spec(tmp_path, "replica-0", "pass")])


# -- fleet observability -----------------------------------------------------


def _write_jsonl(path, records):
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as fh:
        for r in records:
            fh.write(json.dumps(r) + "\n")


def _fleet_root(tmp_path):
    _write_jsonl(str(tmp_path / "replica-0" / "metrics.jsonl"), [
        {"serve_submitted": 4, "serve_completed": 4,
         "serve_tokens_per_sec": 100.0, "serve_tokens_generated": 40,
         "serve_latency_p95_s": 0.2, "serve_rejected": 1},
        {"event": "alert", "rule": "p95_latency"},
    ])
    _write_jsonl(str(tmp_path / "replica-0" / "logs" / "launch.jsonl"), [
        {"event": "launch_attempt", "attempt": 0, "outcome": "ok",
         "success": True},
    ])
    _write_jsonl(str(tmp_path / "replica-1" / "metrics.jsonl"), [
        {"serve_submitted": 3, "serve_completed": 2,
         "serve_tokens_per_sec": 50.5, "serve_tokens_generated": 21,
         "serve_latency_p95_s": 0.7, "serve_rejected": 0},
    ])
    _write_jsonl(str(tmp_path / "replica-1" / "logs" / "launch.jsonl"), [
        {"event": "launch_attempt", "attempt": 0, "outcome": "crash",
         "success": False},
        {"event": "launch_attempt", "attempt": 1, "outcome": "ok",
         "success": True},
    ])
    # A non-replica subdir (no jsonl) must be ignored, not summarized.
    os.makedirs(tmp_path / "scratch", exist_ok=True)
    return str(tmp_path)


def test_summarize_fleet_aggregates_across_replicas(tmp_path):
    from deeplearning_cfn_tpu.obs.report import (
        fleet_status_line,
        render_fleet_report,
        summarize_fleet,
    )

    s = summarize_fleet(_fleet_root(tmp_path))
    assert s["source"]["replicas"] == 2
    f = s["fleet"]
    assert f["tokens_per_sec"] == 150.5          # sum across replicas
    assert f["tokens_generated"] == 61
    assert f["worst_latency_p95_s"] == 0.7       # worst, not mean
    assert f["alerts"] == 1
    assert f["submitted"] == 7 and f["completed"] == 6
    assert f["rejected"] == 1
    assert f["launch_attempts"] == 3 and f["launch_restarts"] == 1
    assert f["launch_failed_replicas"] == []
    assert set(s["replicas"]) == {"replica-0", "replica-1"}
    line = fleet_status_line(s)
    assert "fleet 2 replica(s)" in line and "150.5 tok/s" in line
    assert "done 6/7" in line and "alerts 1" in line
    report = render_fleet_report(s)
    assert "replica-0" in report and "replica-1" in report
    assert "launch: 3 attempt(s), 1 restart(s)" in report


def test_summarize_fleet_missing_root_raises(tmp_path):
    from deeplearning_cfn_tpu.obs.report import summarize_fleet

    with pytest.raises(FileNotFoundError):
        summarize_fleet(str(tmp_path / "nope"))


def test_summarize_counts_alert_records(tmp_path):
    from deeplearning_cfn_tpu.obs.report import render_report, summarize

    path = str(tmp_path / "metrics.jsonl")
    _write_jsonl(path, [
        {"step": 1, "loss": 2.0},
        {"event": "alert", "rule": "loss_spike"},
        {"event": "alert", "rule": "p95_latency"},
    ])
    s = summarize(path)
    assert s["alerts"] == {"count": 2, "last_rule": "p95_latency"}
    assert "alerts" in render_report(s)


def test_fleet_tail_renders_aggregate_line(tmp_path):
    from deeplearning_cfn_tpu.obs.tail import tail

    root = _fleet_root(tmp_path)
    out = io.StringIO()
    assert tail(root, once=True, fleet=True, out=out) == 0
    line = out.getvalue().strip().splitlines()[-1]
    assert line.startswith("fleet 2/2 replica(s)")
    assert "150.5 tok/s" in line
    assert "done 6/7" in line
    assert "worst p95 0.7" in line
    assert "alerts 1" in line


def test_fleet_tail_empty_root(tmp_path):
    from deeplearning_cfn_tpu.obs.tail import tail

    out = io.StringIO()
    assert tail(str(tmp_path), once=True, fleet=True, out=out) == 0
    assert "(no records yet)" in out.getvalue()


# -- CLI wiring --------------------------------------------------------------


def test_cli_fleet_parsers_wire_handlers():
    from deeplearning_cfn_tpu.cli.main import build_parser, main

    parser = build_parser()
    up = parser.parse_args(["fleet", "up", "--preset", "p",
                            "--requests", "r.jsonl"])
    assert up.fn.__name__ == "_cmd_fleet_up" and up.replicas == 2
    rt = parser.parse_args(["fleet", "route", "--preset", "p",
                            "--requests", "r.jsonl",
                            "--policy", "round_robin"])
    assert rt.fn.__name__ == "_cmd_fleet_route"
    ro = parser.parse_args(["fleet", "rollout", "--preset", "p",
                            "--requests", "r.jsonl", "--to-step", "5"])
    assert ro.fn.__name__ == "_cmd_fleet_rollout" and ro.to_step == 5
    st = parser.parse_args(["fleet", "status", "/tmp/x", "--json"])
    assert st.fn.__name__ == "_cmd_fleet_status"
    be = parser.parse_args(["bench", "--fleet", "--smoke",
                            "--fleet-replicas", "3"])
    assert be.fleet and be.fleet_replicas == 3
    # --smoke without a serving scenario is still rejected...
    assert main(["bench", "--smoke"]) == 2
    # ...and --fleet refuses to combine with other scenarios.
    assert main(["bench", "--fleet", "--serve"]) == 2
    # Chaos flags are fleet-scenario flags, never silently ignored.
    assert main(["bench", "--serve", "--smoke",
                 "--chaos-plan", "plan.json"]) == 2
    assert main(["bench", "--serve", "--smoke", "--degrade"]) == 2


def test_cli_obs_fleet_flags(tmp_path, capsys):
    from deeplearning_cfn_tpu.cli.main import main

    root = _fleet_root(tmp_path)
    assert main(["obs", "summarize", root, "--fleet"]) == 0
    assert "fleet 2 replica(s)" in capsys.readouterr().out
    assert main(["obs", "summarize", root, "--fleet", "--json"]) == 0
    s = json.loads(capsys.readouterr().out)
    assert s["fleet"]["tokens_per_sec"] == 150.5
    assert main(["obs", "tail", root, "--fleet", "--once"]) == 0
    assert "fleet 2/2" in capsys.readouterr().out
    # --fleet tail needs a directory, not a file.
    assert main(["obs", "tail",
                 os.path.join(root, "replica-0", "metrics.jsonl"),
                 "--fleet", "--once"]) == 2
    assert main(["fleet", "status", root]) == 0
    assert "fleet 2 replica(s)" in capsys.readouterr().out
    assert main(["fleet", "status", str(tmp_path / "scratch")]) == 1


# -- end to end: real engines, zero drops, token parity ----------------------


@pytest.fixture(scope="module")
def tiny_fleet_setup():
    """One tiny NMT init shared by every engine in this module (replicas
    AND the single-engine baseline), a fixed trace, and the baseline's
    per-request token lists."""
    import jax
    import numpy as np

    from deeplearning_cfn_tpu.models.transformer_nmt import (
        transformer_nmt_tiny,
    )
    from deeplearning_cfn_tpu.serve.bench import _fixed_trace
    from deeplearning_cfn_tpu.serve.engine import Engine

    src_len, max_new = 8, 4
    model = transformer_nmt_tiny(vocab_size=96, max_len=64)
    init = model.init(
        jax.random.PRNGKey(0),
        np.zeros((1, src_len), np.int32), np.ones((1, src_len), np.int32),
        np.zeros((1, src_len), np.int32), train=False)
    variables = {"params": init["params"]}
    trace = _fixed_trace(6, src_len, 96, seed=0)

    baseline_engine = Engine(model, variables, capacity=2,
                             max_src_len=src_len, queue_depth=len(trace),
                             default_max_new_tokens=max_new,
                             decode_window=2)
    ids = [baseline_engine.submit(src, max_new_tokens=max_new).id
           for src in trace]
    baseline_engine.run_until_drained()
    baseline = [list(baseline_engine.poll(i).tokens) for i in ids]

    def make_replicas(n, fault_plan=None):
        reps = []
        for i in range(n):
            eng = Engine(model, variables, capacity=2, max_src_len=src_len,
                         queue_depth=len(trace),
                         default_max_new_tokens=max_new, decode_window=2)
            reps.append(EngineReplica(f"replica-{i}", eng,
                                      fault_plan=fault_plan))
        return reps

    return {"variables": variables, "trace": trace, "baseline": baseline,
            "max_new": max_new, "make_replicas": make_replicas}


def _route_all(router, trace, max_new):
    rids = []
    for src in trace:
        while True:
            try:
                rids.append(router.submit(src, max_new_tokens=max_new))
                break
            except OverloadError:
                router.step()
    return rids


def test_e2e_rolling_upgrade_mid_stream_token_parity(tiny_fleet_setup):
    """The acceptance contract: a 2-replica fleet serves the fixed trace
    while every replica is drained, checkpoint-swapped, and re-admitted
    mid-stream — zero drops, aggregate output token-identical to the
    single-engine run."""
    s = tiny_fleet_setup
    router = Router(s["make_replicas"](2), policy="least_loaded")
    half = len(s["trace"]) // 2
    rids = _route_all(router, s["trace"][:half], s["max_new"])
    report = rolling_upgrade(router, s["variables"])
    assert report.ok and len(report.upgraded) == 2
    assert all(r.swapped and r.probe_ok for r in report.results)
    rids += _route_all(router, s["trace"][half:], s["max_new"])
    router.run_until_drained()
    results = [router.result(rid) for rid in rids]
    assert all(r["state"] == "done" for r in results)
    assert router.stats()["dropped_requests"] == 0
    assert [r["tokens"] for r in results] == s["baseline"]
    # Both replicas ended the run back in rotation.
    for rid in router.replica_ids():
        assert router.replica(rid).state is ReplicaState.HEALTHY


def test_e2e_rolling_upgrade_requantizes_int8_fleet(tiny_fleet_setup):
    """Fleet rollout against a --quantize int8 fleet: rolling_upgrade
    hands every replica the fp32 checkpoint, and swap_variables
    re-quantizes it inside the engine — the fleet keeps serving int8
    (identical tokens before and after the swap, int8 params in every
    engine)."""
    import jax
    import numpy as np

    from deeplearning_cfn_tpu.models.transformer_nmt import (
        transformer_nmt_tiny,
    )
    from deeplearning_cfn_tpu.serve.engine import Engine

    s = tiny_fleet_setup
    src_len, max_new = 8, s["max_new"]
    model = transformer_nmt_tiny(vocab_size=96, max_len=64)
    reps = []
    for i in range(2):
        eng = Engine(model, s["variables"], capacity=2,
                     max_src_len=src_len, queue_depth=len(s["trace"]),
                     default_max_new_tokens=max_new, decode_window=2,
                     quantize="int8")
        reps.append(EngineReplica(f"replica-{i}", eng))
    router = Router(reps, policy="least_loaded")
    rids = _route_all(router, s["trace"], max_new)
    router.run_until_drained()
    before = [router.result(rid)["tokens"] for rid in rids]
    report = rolling_upgrade(router, s["variables"])  # fp32 checkpoint in
    assert report.ok and len(report.upgraded) == 2
    for rid in router.replica_ids():
        eng = router.replica(rid).engine
        assert any(np.asarray(l).dtype == np.int8
                   for l in jax.tree_util.tree_leaves(eng.variables))
    rids2 = _route_all(router, s["trace"], max_new)
    router.run_until_drained()
    after = [router.result(rid)["tokens"] for rid in rids2]
    assert after == before  # same weights in → same int8 serving out
    assert router.stats()["dropped_requests"] == 0


def test_e2e_chaos_kill_mid_decode_token_parity(tiny_fleet_setup):
    """The chaos variant: runtime/faults.py kills replica-0 mid-decode;
    its in-flight requests re-run on the survivor and the fleet aggregate
    is STILL token-identical to the single-engine baseline."""
    s = tiny_fleet_setup
    plan = FaultPlan([FaultSpec(op="step", key="replica-0", kind="crash",
                                at_calls=(2,))])
    router = Router(s["make_replicas"](2, fault_plan=plan),
                    policy="least_loaded")
    rids = _route_all(router, s["trace"], s["max_new"])
    router.run_until_drained()
    victim = router.replica("replica-0")
    assert victim.crashed and victim.state is ReplicaState.DOWN
    assert router.evacuations >= 1
    results = [router.result(rid) for rid in rids]
    assert all(r["state"] == "done" for r in results)
    assert router.stats()["dropped_requests"] == 0
    assert [r["tokens"] for r in results] == s["baseline"]
    # Goodput accounting across the kill: every decoded token is either
    # in a DONE result (goodput) or was decoded on the abandoned attempt
    # (waste) — the two sum to the fleet's decoded total, exactly.
    st = router.stats()
    total_decoded = sum(
        router.replica(r).engine.metrics.tokens_generated
        for r in router.replica_ids())
    assert st["goodput_tokens"] + st["wasted_tokens"] == total_decoded
    assert st["goodput_tokens"] == sum(len(r["tokens"]) for r in results)


def test_fleet_bench_smoke_contract_record():
    """`bench --fleet --smoke` record: the BENCH contract shape plus the
    fleet gate fields t1.sh asserts on."""
    from deeplearning_cfn_tpu.fleet.bench import run_fleet_bench

    rec = run_fleet_bench(smoke=True)
    assert rec["metric"] == "fleet_tiny_nmt_tokens_per_sec"
    assert rec["unit"] == "tokens/sec"
    assert rec["measured"] is True
    assert rec["replicas"] == 2
    assert rec["dropped_requests"] == 0
    assert rec["token_identical"] is True
    assert rec["smoke"] is True
    assert len(rec["per_replica"]) == 2
    for row in rec["per_replica"]:
        assert row["state"] == "healthy"
        assert row["routed"] > 0
    assert sum(r["tokens"] for r in rec["per_replica"]) > 0
    # The goodput ledger fields: goodput + waste == decoded, exactly.
    assert rec["goodput_sum_ok"] is True
    total = sum(r["tokens"] for r in rec["per_replica"])
    assert rec["goodput_tokens"] + rec["wasted_tokens"] == total
    assert rec["e2e_latency_p50_s"] is not None
    assert rec["e2e_latency_p95_s"] >= rec["e2e_latency_p50_s"]
    assert rec["goodput_tokens_per_sec"] is not None
    assert rec["goodput_tokens_per_sec"] > 0
    assert json.dumps(rec)   # one JSON line, like every bench record


def test_fleet_bench_autoscale_burst_contract():
    """The acceptance scenario end to end: `bench --fleet --trace burst
    --autoscale` scales up at burst onset, scales down by drain at the
    trough, drops nothing, stays token-identical to a fixed-size fleet,
    and is fully deterministic across runs."""
    from deeplearning_cfn_tpu.fleet.bench import run_fleet_bench

    kw = dict(smoke=True, autoscale=True, trace_spec="burst",
              policy="round_robin")
    rec = run_fleet_bench(**kw)
    assert rec["autoscale"] is True
    assert rec["trace_spec"].startswith("burst")
    assert rec["dropped_requests"] == 0
    assert rec["token_identical"] is True          # vs FIXED max fleet
    assert rec["scale_ups"] >= 1
    first_up = next(e for e in rec["scale_events"]
                    if e["action"] == "scale_up")
    assert first_up["replica"].startswith("auto-")
    assert first_up["reason"]
    assert first_up["signals"]["queue_depth"] is not None
    downs = [e for e in rec["scale_events"]
             if e["action"] == "scale_down"]
    assert downs and all(e["drained"] is True for e in downs)
    # Scale-up at burst onset: well under a virtual second from the
    # first arrival.
    assert rec["time_to_scale_s"] is not None
    assert 0.0 <= rec["time_to_scale_s"] < 1.0
    assert rec["p95_during_burst"] is not None
    assert rec["offered_load_rps"] > 0
    assert rec["replicas_initial"] == rec["min_replicas"] == 1
    assert rec["replicas_final"] == 1              # drained to trough
    assert rec["max_replicas"] >= 2
    # Events are ordered on the virtual clock and phase-consistent.
    ts = [e["ts"] for e in rec["scale_events"]]
    assert ts == sorted(ts)
    assert json.dumps(rec)
    # Determinism: identical arrival schedule AND scale decisions.
    rec2 = run_fleet_bench(**kw)
    assert rec2["arrival_schedule"] == rec["arrival_schedule"]
    assert rec2["scale_events"] == rec["scale_events"]
    assert [r["tokens"] for r in rec2["per_replica"]] == \
        [r["tokens"] for r in rec["per_replica"]]


# -- request tracing & the goodput ledger ------------------------------------


def test_trace_id_stable_across_crash_evacuation():
    """The per-attempt replica request id changes on re-placement (so a
    re-placed copy can never collide with a cancelled one) but the trace
    context — ``Request.trace_id`` == the logical rid — rides along
    unchanged, which is what lets the exporter stitch both attempts into
    one flow."""
    plan = FaultPlan([FaultSpec(op="step", key="replica-0", kind="crash",
                                at_calls=(1,))])
    reps = [_fake_replica("replica-0", work=3, fault_plan=plan),
            _fake_replica("replica-1", work=3)]
    router = Router(reps, policy="round_robin")
    rid = router.submit([5, 4, 3], max_new_tokens=3)
    first = router.poll(rid)
    assert first.id == f"{rid}#a1" and first.trace_id == rid
    router.step()                    # decodes one token on replica-0
    router.step()                    # injected crash -> evacuation
    second = router.poll(rid)
    assert second.id == f"{rid}#a2"  # fresh per-attempt id...
    assert second.trace_id == rid    # ...same trace context
    router.run_until_drained()
    assert router.result(rid)["state"] == "done"
    entry = router.ledger[rid]
    assert entry["replicas"] == ["replica-0", "replica-1"]
    assert entry["attempts"] == 2
    assert entry["goodput_tokens"] == 3 and entry["wasted_tokens"] == 1
    assert set(entry["phases"]) == {"queue_wait_s", "prefill_s",
                                    "decode_s", "stall_s", "emit_s"}
    st = router.stats()
    assert st["goodput_tokens"] == 3 and st["wasted_tokens"] == 1


def test_trace_id_and_waste_across_forced_evacuation():
    """Same contract through the rollout path: drain + evacuate (the
    drain-deadline escape hatch) abandons a half-decoded attempt — its
    tokens are waste, the re-placed copy keeps the trace id, and the
    final result is whole."""
    reps = [_fake_replica("replica-0", work=5),
            _fake_replica("replica-1", work=5)]
    router = Router(reps, policy="round_robin")
    rid = router.submit([5, 4, 3], max_new_tokens=5)
    router.step()                    # one token decoded on replica-0
    router.drain("replica-0")
    router.evacuate("replica-0")
    req = router.poll(rid)
    assert req.id == f"{rid}#a2" and req.trace_id == rid
    router.run_until_drained()
    result = router.result(rid)
    assert result["state"] == "done" and len(result["tokens"]) == 5
    entry = router.ledger[rid]
    assert entry["replicas"] == ["replica-0", "replica-1"]
    assert entry["goodput_tokens"] == 5 and entry["wasted_tokens"] == 1
    st = router.stats()
    assert st["goodput_tokens"] == 5 and st["wasted_tokens"] == 1
    assert st["dropped_requests"] == 0


def test_stall_time_accrues_while_backlogged():
    """A request evacuated with nowhere to go waits in the backlog; the
    gap between losing its replica copy and the re-placement is stall
    time in its phase ledger (deterministic under an injected clock)."""
    ticks = itertools.count()
    reps = [_fake_replica("replica-0", work=3, capacity=2, queue_depth=8),
            _fake_replica("replica-1", work=3, capacity=1, queue_depth=1)]
    router = Router(reps, policy="round_robin",
                    clock=lambda: float(next(ticks)))
    a = router.submit([5, 4, 3], max_new_tokens=3)   # -> replica-0
    b = router.submit([5, 4, 3], max_new_tokens=3)   # -> replica-1 (full)
    router.drain("replica-0")
    router.evacuate("replica-0")     # a: survivor is full -> backlog
    assert router.poll(a) is None    # no live copy anywhere
    router.run_until_drained()
    results = [router.result(r) for r in (a, b)]
    assert all(r["state"] == "done" for r in results)
    entry = router.ledger[a]
    # attempts counts every placement TRY (overload rejections included);
    # the request actually lived on exactly two replicas.
    assert entry["attempts"] >= 2
    assert entry["replicas"] == ["replica-0", "replica-1"]
    assert entry["phases"]["stall_s"] > 0.0
    assert entry["e2e_s"] is not None
    assert router.stats()["dropped_requests"] == 0


def test_fleet_chaos_trace_merges_with_flow_links(tmp_path):
    """The tracing acceptance contract, end to end: a chaos fleet bench
    writes per-process trace shards; `obs export --fleet` merges them
    into ONE valid Perfetto timeline where a single logical request's
    spans appear on the router AND >= 2 replicas, linked by
    cross-process flow events."""
    from deeplearning_cfn_tpu.fleet.bench import run_fleet_bench
    from deeplearning_cfn_tpu.obs.export import export_fleet_trace

    trace_dir = str(tmp_path / "fleet-trace")
    rec = run_fleet_bench(smoke=True, chaos_kill_step=2,
                          trace_dir=trace_dir)
    assert rec["dropped_requests"] == 0
    assert rec["goodput_sum_ok"] is True
    assert rec["trace_dir"] == trace_dir
    assert os.path.exists(os.path.join(trace_dir, "router.jsonl"))
    assert os.path.exists(os.path.join(trace_dir, "signals.jsonl"))

    out = str(tmp_path / "trace.json")
    summary = export_fleet_trace(trace_dir, out)
    assert summary["problems"] == []
    assert summary["shards"] == ["router", "replica-0", "replica-1"]
    assert summary["flow_events"] >= 1

    with open(out) as fh:
        evs = json.load(fh)["traceEvents"]
    pids_by_trace = {}
    for e in evs:
        name = str(e.get("name", ""))
        if e.get("ph") != "X" or not (
                name == "fleet.request" or name.startswith("serve.request")):
            continue
        trace_id = (e.get("args") or {}).get("trace_id")
        if isinstance(trace_id, str):
            pids_by_trace.setdefault(trace_id, set()).add(e["pid"])
    # Every routed request has spans on >= 2 pid blocks (router + the
    # replica that served it); the evacuated ones hop, so at least one
    # request shows on >= 3 (router + both replicas).
    assert pids_by_trace
    assert all(len(p) >= 2 for p in pids_by_trace.values())
    assert any(len(p) >= 3 for p in pids_by_trace.values())
    # Flow events come in s/f pairs sharing an id, each bound to a slice.
    starts = [e for e in evs if e.get("ph") == "s"]
    finishes = [e for e in evs if e.get("ph") == "f"]
    assert {e["id"] for e in starts} == {e["id"] for e in finishes}
    assert all(e.get("bp") == "e" for e in finishes)
    for s, f in zip(sorted(starts, key=lambda e: e["id"]),
                    sorted(finishes, key=lambda e: e["id"])):
        assert s["pid"] != f["pid"]      # cross-process by construction
        assert f["ts"] >= s["ts"]


# -- fleet fault injection (fakes) -------------------------------------------


class _Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def test_transient_submit_fault_routes_to_next_candidate():
    """An injected transient on ``replica.submit`` never lands the
    request there — the router falls through to the next candidate and
    nothing is dropped."""
    plan = FaultPlan([FaultSpec(op="replica.submit", key="replica-0",
                                kind="transient", at_calls=(0,))])
    reps = [_fake_replica("replica-0", fault_plan=plan),
            _fake_replica("replica-1")]
    router = Router(reps, policy="round_robin")
    rid = router.submit([5, 4, 3])
    assert _placements(router, [rid]) == ["replica-1"]
    router.run_until_drained()
    assert router.result(rid)["state"] == "done"
    assert router.stats()["dropped_requests"] == 0
    # The faulted replica is stuck, not dead: the next submit lands.
    rid2 = router.submit([6, 5, 4])
    assert _placements(router, [rid2]) == ["replica-0"]


def test_hang_classified_counted_and_survived():
    """A one-tick step hang is counted apart from crashes, does NOT trip
    a breaker below threshold, and the replica finishes its work."""
    plan = FaultPlan([FaultSpec(op="replica.step", key="replica-0",
                                kind="hang", at_calls=(0,))])
    rep = _fake_replica("replica-0", fault_plan=plan, work=2)
    router = Router([rep], breaker_threshold=3)
    rid = router.submit([5, 4, 3])
    router.step()   # injected hang: no progress, classified + counted
    assert router.stats()["replica_hangs"] == 1
    assert rep.state is ReplicaState.HEALTHY
    router.run_until_drained()
    assert router.result(rid)["state"] == "done"
    assert router.stats()["dropped_requests"] == 0


def test_repeated_hangs_feed_the_breaker():
    """A replica that hangs every tick is as useless as one that
    crashes: consecutive classified hangs open the breaker and the work
    is evacuated to the survivor."""
    plan = FaultPlan([FaultSpec(op="replica.step", key="replica-0",
                                kind="hang")])
    victim = _fake_replica("replica-0", fault_plan=plan)
    survivor = _fake_replica("replica-1")
    router = Router([victim, survivor], policy="round_robin",
                    breaker_threshold=2)
    rid = router.submit([5, 4, 3])
    assert _placements(router, [rid]) == ["replica-0"]
    router.run_until_drained()
    assert victim.state is ReplicaState.BROKEN
    assert router.stats()["replica_hangs"] >= 2
    assert router.result(rid)["state"] == "done"
    assert _placements(router, [rid]) == ["replica-1"]
    assert router.stats()["dropped_requests"] == 0


def test_crash_mid_tick_wastes_partial_progress():
    """``crash_mid`` lets the step RUN before the replica dies — the
    tick's tokens exist on a dead replica, so they are ledgered as
    waste and re-decoded on the survivor (torn state, zero drops)."""
    plan = FaultPlan([FaultSpec(op="replica.step", key="replica-0",
                                kind="crash_mid", at_calls=(0,))])
    victim = _fake_replica("replica-0", fault_plan=plan, work=3)
    survivor = _fake_replica("replica-1", work=3)
    router = Router([victim, survivor], policy="round_robin")
    rid = router.submit([5, 4, 3])
    router.step()
    assert victim.crashed
    st = router.stats()
    assert st["wasted_tokens"] >= 1    # the mid-crash tick's token
    router.run_until_drained()
    assert router.result(rid)["state"] == "done"
    assert router.stats()["dropped_requests"] == 0


def test_latency_fault_injects_slow_tick():
    """``latency`` slows the tick through the replica's injectable
    sleep — no exception, no waste, just a slow replica."""
    plan = FaultPlan([FaultSpec(op="replica.step", key="replica-0",
                                kind="latency", latency_s=0.25,
                                at_calls=(0,))])
    slept = []
    rep = EngineReplica("replica-0", FakeEngine(work=2),
                        fault_plan=plan, sleep=slept.append)
    router = Router([rep])
    rid = router.submit([5, 4, 3])
    router.run_until_drained()
    assert slept == [0.25]
    assert router.result(rid)["state"] == "done"
    assert router.stats()["wasted_tokens"] == 0


def test_fault_plan_counts_what_fired():
    """``fired_counts`` proves the plan actually bit — a chaos run whose
    plan never fires passes every contract vacuously."""
    plan = FaultPlan([
        FaultSpec(op="replica.step", key="replica-0", kind="hang",
                  at_calls=(0,)),
        FaultSpec(op="replica.step", key="replica-0", kind="crash_mid",
                  at_calls=(1,)),
    ])
    victim = _fake_replica("replica-0", fault_plan=plan, work=4)
    survivor = _fake_replica("replica-1", work=4)
    router = Router([victim, survivor], policy="round_robin",
                    breaker_threshold=5)
    rid = router.submit([5, 4, 3])
    router.run_until_drained()
    assert plan.fired_counts == {"hang": 1, "crash_mid": 1}
    assert router.result(rid)["state"] == "done"
    assert router.stats()["dropped_requests"] == 0


# -- backlog retry backoff ---------------------------------------------------


def _backlogged_router(clock, deadline_s=None):
    """One crashed replica, one request stranded in the backlog."""
    plan = FaultPlan([FaultSpec(op="step", key="replica-0", kind="crash",
                                at_calls=(0,))])
    router = Router([_fake_replica("replica-0", fault_plan=plan)],
                    clock=clock)
    rid = router.submit([5, 4, 3], deadline_s=deadline_s)
    router.step()   # crash → nowhere to evacuate → backlog
    assert router.result(rid)["state"] == "backlogged"
    return router, rid


def test_backlog_retry_backs_off_between_attempts():
    """Backlog retries are paced by the ckpt-store RetryPolicy, not
    hammered every tick: with a frozen clock the second attempt waits
    out the deterministic-jitter delay."""
    clock = _Clock()
    router, rid = _backlogged_router(clock)
    router.step()   # retry 1: NoReplicasError → backoff state armed
    assert router.stats()["router_backlog_retries"] == 1
    for _ in range(5):
        router.step()   # frozen clock: still backing off, no attempts
    assert router.stats()["router_backlog_retries"] == 1
    clock.advance(10.0)  # past any jittered delay
    router.step()
    assert router.stats()["router_backlog_retries"] == 2
    # Capacity returns → the next due retry places; nothing dropped.
    router.add(_fake_replica("replica-1"))
    clock.advance(10.0)
    router.run_until_drained()
    assert router.result(rid)["state"] == "done"
    assert router.stats()["dropped_requests"] == 0


def test_backlog_retry_pacing_is_deterministic():
    """Same scenario, two runs: identical retry counts at every tick —
    the jitter is salted by request id, never wall-clock."""

    def trace():
        clock = _Clock()
        router, rid = _backlogged_router(clock)
        seen = []
        for _ in range(8):
            clock.advance(0.013)
            router.step()
            seen.append(router.stats()["router_backlog_retries"])
        return seen

    assert trace() == trace()


# -- deadline honesty --------------------------------------------------------


def test_expired_backlog_entry_cancelled_not_replaced():
    """Deadline honesty in the backlog: an entry whose deadline passes
    while it waits is finalized terminal-EXPIRED, never re-placed —
    resolved, not dropped."""
    clock = _Clock()
    router, rid = _backlogged_router(clock, deadline_s=5.0)
    router.add(_fake_replica("replica-1"))   # capacity returns...
    clock.advance(6.0)                        # ...but too late
    router.step()
    assert router.finished(rid)
    res = router.result(rid)
    assert res["state"] == "expired" and res["tokens"] == []
    st = router.stats()
    assert st["deadline_cancelled"] == 1
    assert st["dropped_requests"] == 0
    assert router.ledger[rid]["state"] == "expired"
    assert router.ledger[rid]["goodput_tokens"] == 0


def test_expired_at_evacuation_cancelled_with_waste_ledgered():
    """A crash that strands an already-expired request must not re-place
    it: the abandoned attempt's tokens are waste, the request is
    terminal EXPIRED."""
    clock = _Clock()
    plan = FaultPlan([FaultSpec(op="step", key="replica-0", kind="crash",
                                at_calls=(1,))])
    router = Router([_fake_replica("replica-0", fault_plan=plan, work=3),
                     _fake_replica("replica-1", work=3)],
                    policy="round_robin", clock=clock)
    rid = router.submit([5, 4, 3], deadline_s=5.0)
    router.step()        # decodes one token on replica-0
    clock.advance(6.0)   # the promise lapses mid-flight
    router.step()        # crash → evacuation finds it expired
    res = router.result(rid)
    assert res["state"] == "expired"
    st = router.stats()
    assert st["deadline_cancelled"] == 1
    assert st["wasted_tokens"] >= 1      # the abandoned attempt's token
    assert st["dropped_requests"] == 0
    assert router.ledger[rid]["wasted_tokens"] >= 1


def test_router_cancel_fault_defers_then_applies():
    """An injected ``router.cancel`` fault defers the cancellation one
    consult — the next attempt goes through."""
    clock = _Clock()
    plan_cancel = FaultSpec(op="router.cancel", kind="transient",
                            at_calls=(0,))
    plan = FaultPlan([FaultSpec(op="step", key="replica-0", kind="crash",
                                at_calls=(0,)), plan_cancel])
    router = Router([_fake_replica("replica-0", fault_plan=plan)],
                    clock=clock, fault_plan=plan)
    rid = router.submit([5, 4, 3])
    router.step()
    assert router.result(rid)["state"] == "backlogged"
    assert router.cancel(rid) is False    # deferred by the fault
    assert router.cancel(rid) is True     # retry lands
    assert router.result(rid)["state"] == "cancelled"
    assert router.stats()["dropped_requests"] == 0


# -- brownout graceful degradation (fakes) -----------------------------------


def _degrade_rig(n=2, policy=None):
    from deeplearning_cfn_tpu.fleet.degrade import (
        DegradeController, DegradePolicy,
    )
    from deeplearning_cfn_tpu.obs.signals import SignalBus

    reps = [_fake_replica(f"replica-{i}", queue_depth=64)
            for i in range(n)]
    router = Router(reps, policy="round_robin")
    bus = SignalBus(names=[r.id for r in reps])
    clock = _Clock()
    ctrl = DegradeController(
        router, bus,
        policy=policy or DegradePolicy(up_stable_ticks=1,
                                       down_stable_ticks=1,
                                       cooldown_ticks=0),
        clock=clock)
    router.degrade = ctrl

    def feed(depth_per_replica):
        clock.advance(0.01)
        for r in reps:
            bus.observe(r.id, {"serve_queue_depth": depth_per_replica},
                        ts=clock())
    return router, reps, ctrl, feed


def test_degrade_steps_up_one_level_at_a_time_and_applies_knobs():
    """Pressure walks the fleet down the brownout ladder one audited
    level per tick: no_spec → window_cap → shed_batch — and each
    level's knobs land on every member engine."""
    router, reps, ctrl, feed = _degrade_rig()
    for expect_level, name in ((1, "no_spec"), (2, "window_cap"),
                               (3, "shed_batch")):
        feed(100)       # way past up_queue_depth * routable
        ctrl.tick()
        assert ctrl.level == expect_level
        assert ctrl.level_name == name
    for r in reps:
        assert r.engine._degrade_no_spec is True
        assert r.engine._degrade_window_cap == ctrl.policy.window_cap
        assert r.engine.queue.shed_classes == {"batch"}
    # Ratcheted at the top: more pressure cannot push past MAX_LEVEL.
    feed(100)
    ctrl.tick()
    assert ctrl.level == 3
    assert [e["action"] for e in ctrl.events] == ["degrade"] * 3
    assert all(e["event"] == "degrade_event" for e in ctrl.events)
    assert ctrl.transitions == 3


def test_degrade_recovers_hysteretically_and_clears_knobs():
    router, reps, ctrl, feed = _degrade_rig()
    for _ in range(3):
        feed(100)
        ctrl.tick()
    assert ctrl.level == 3
    for _ in range(3):
        feed(0)         # calm: walk back up one level per tick
        ctrl.tick()
    assert ctrl.level == 0 and ctrl.level_name == "normal"
    for r in reps:
        assert r.engine._degrade_no_spec is False
        assert r.engine._degrade_window_cap is None
        assert r.engine.queue.shed_classes == set()
    acts = [e["action"] for e in ctrl.events]
    assert acts == ["degrade"] * 3 + ["recover"] * 3
    assert ctrl.transitions == 6


def test_degrade_hysteresis_streaks_and_cooldown_block_flapping():
    from deeplearning_cfn_tpu.fleet.degrade import DegradePolicy

    router, reps, ctrl, feed = _degrade_rig(
        policy=DegradePolicy(up_stable_ticks=2, down_stable_ticks=2,
                             cooldown_ticks=2))
    feed(100)
    ctrl.tick()
    assert ctrl.level == 0      # hot for 1 tick < up_stable_ticks
    feed(0)
    ctrl.tick()
    assert ctrl.level == 0      # the streak reset — no flap
    feed(100); ctrl.tick()
    feed(100); ctrl.tick()
    assert ctrl.level == 1      # two consecutive hot ticks
    feed(100); ctrl.tick()
    feed(100); ctrl.tick()
    assert ctrl.level == 1      # cooldown holds the next step back
    feed(100); ctrl.tick()
    assert ctrl.level == 2


def test_degrade_policy_rejects_inverted_hysteresis():
    from deeplearning_cfn_tpu.fleet.degrade import DegradePolicy

    with pytest.raises(ValueError, match="hysteresis"):
        DegradePolicy(up_queue_depth=1.0, down_queue_depth=2.0)
    with pytest.raises(ValueError, match="cooldown"):
        DegradePolicy(cooldown_ticks=-1)


def test_degraded_overload_hint_adds_recovery_horizon():
    """While browned out, FleetOverloadError.retry_after_s folds in the
    level's expected recovery horizon so clients back off long enough
    for the fleet to step back up."""
    router, reps, ctrl, feed = _degrade_rig(n=1)
    for r in reps:
        r.engine.queue.max_depth = 0    # every submit overflows
    with pytest.raises(FleetOverloadError) as e0:
        router.submit([5, 4, 3])
    base_hint = e0.value.retry_after_s or 0.0
    for _ in range(2):
        feed(100)
        ctrl.tick()
    assert ctrl.level == 2
    with pytest.raises(FleetOverloadError) as e1:
        router.submit([5, 4, 3])
    horizon = ctrl.recovery_horizon_s()
    assert horizon == 2 * ctrl.policy.level_recovery_s > 0
    assert (e1.value.retry_after_s or 0.0) >= base_hint + horizon


def test_degrade_shed_only_rejects_batch_class():
    """Level 3 sheds throughput-tier admissions; the controller itself
    never touches latency-class traffic or anything in flight."""
    from deeplearning_cfn_tpu.serve.queue import RequestQueue

    router, reps, ctrl, feed = _degrade_rig(n=1)
    # Swap the fake's list-queue for a real RequestQueue so shed
    # semantics (OverloadError on shed classes) are the production ones.
    q = RequestQueue(max_depth=8)
    reps[0].engine.queue = q
    for _ in range(3):
        feed(100)
        ctrl.tick()
    assert ctrl.level == 3 and q.shed_classes == {"batch"}
    with pytest.raises(OverloadError):
        q.submit([5, 4, 3], 4, tenant="t", qos_class="batch")
    req = q.submit([5, 4, 3], 4, tenant="t", qos_class="latency")
    assert req.qos_class == "latency"


# -- deadline + handoff seams (real engines) ---------------------------------


SRC_LEN_CHAOS = 8
MAX_NEW_CHAOS = 4


@pytest.fixture(scope="module")
def tiny_chaos_setup():
    """One tiny paged NMT init for the fleet-chaos seam tests: engines
    with injectable clocks so deadline decisions replay without
    wall-clock."""
    import jax
    import numpy as np

    from deeplearning_cfn_tpu.models.transformer_nmt import (
        transformer_nmt_tiny,
    )
    from deeplearning_cfn_tpu.serve.bench import _fixed_trace
    from deeplearning_cfn_tpu.serve.engine import Engine

    model = transformer_nmt_tiny(vocab_size=96, max_len=64)
    init = model.init(
        jax.random.PRNGKey(0),
        np.zeros((1, SRC_LEN_CHAOS), np.int32),
        np.ones((1, SRC_LEN_CHAOS), np.int32),
        np.zeros((1, SRC_LEN_CHAOS), np.int32), train=False)
    variables = {"params": init["params"]}
    trace = _fixed_trace(4, SRC_LEN_CHAOS, 96, seed=0)

    def make_engine(phase, **kw):
        kw.setdefault("kv_block_size", 4)
        kw.setdefault("capacity", 2)
        kw.setdefault("decode_window", 2)
        return Engine(model, variables,
                      max_src_len=SRC_LEN_CHAOS, queue_depth=8,
                      default_max_new_tokens=MAX_NEW_CHAOS,
                      phase=phase, **kw)

    baseline_engine = make_engine("both")
    ids = [baseline_engine.submit(src, max_new_tokens=MAX_NEW_CHAOS).id
           for src in trace]
    baseline_engine.run_until_drained()
    baseline = [list(baseline_engine.poll(i).tokens) for i in ids]
    return {"trace": trace, "baseline": baseline,
            "make_engine": make_engine}


@pytest.mark.chaos
@pytest.mark.parametrize("kind,counter", [
    ("corrupt", "handoff_corrupt_rejects"),
    ("drop", "handoff_lost_rejects"),
], ids=["corrupt", "lost"])
def test_handoff_fault_detected_rejected_and_retried(tiny_chaos_setup,
                                                     kind, counter):
    """An injected handoff artifact fault (bit-flip / loss in the store)
    is DETECTED and REJECTED by the importer; the exporter stays parked
    and the retried hop lands token-identical — corruption costs
    latency, never tokens."""
    s = tiny_chaos_setup
    plan = FaultPlan([FaultSpec(op="handoff.export", kind=kind,
                                at_calls=(0,))])
    router = Router(
        [EngineReplica("prefill-0", s["make_engine"]("prefill")),
         EngineReplica("decode-0", s["make_engine"]("decode"))],
        policy="least_loaded", fault_plan=plan)
    rid = router.submit(s["trace"][0], max_new_tokens=MAX_NEW_CHAOS)
    router.run_until_drained()
    st = router.stats()
    assert st[counter] == 1
    assert plan.fired_counts == {kind: 1}
    assert st["handoffs"] == 1          # the retry landed
    assert st["dropped_requests"] == 0
    res = router.result(rid)
    assert res["state"] == "done"
    assert res["tokens"] == s["baseline"][0]


@pytest.mark.chaos
def test_import_handoff_refuses_expired_stream_pre_commit(
        tiny_chaos_setup):
    """Deadline honesty across the handoff seam: a stream whose budget
    lapsed in transit is refused BEFORE any decode-side state commits —
    rows, blocks, and the queue stay untouched for the next import."""
    from deeplearning_cfn_tpu.serve.queue import DeadlineExceededError

    s = tiny_chaos_setup
    clock = _Clock()
    pre = s["make_engine"]("prefill", clock=clock)
    dec = s["make_engine"]("decode", clock=clock)
    req = pre.submit(s["trace"][0], max_new_tokens=MAX_NEW_CHAOS,
                     deadline_s=5.0)
    pre.run_until_drained()
    assert pre.handoff_ready(req.id)
    art = pre.export_handoff(req.id)
    rows_free = len(dec._free_rows())
    blocks_free = dec.allocator.free_blocks
    clock.advance(10.0)     # the promise lapses in transit
    with pytest.raises(DeadlineExceededError):
        dec.import_handoff(art, request_id=req.id + "#a1")
    assert len(dec._free_rows()) == rows_free
    assert dec.allocator.free_blocks == blocks_free
    assert dec.active_requests == 0 and dec.queue.depth == 0
    # The refusal is not a black hole: a live stream still imports.
    req2 = pre.submit(s["trace"][1], max_new_tokens=MAX_NEW_CHAOS)
    pre.run_until_drained()
    new = dec.import_handoff(pre.export_handoff(req2.id),
                             request_id=req2.id + "#a1")
    dec.run_until_drained()
    assert list(dec.poll(new.id).tokens) == s["baseline"][1]


@pytest.mark.chaos
def test_deadline_expires_in_flight_after_handoff_import(
        tiny_chaos_setup):
    """The full seam through the router: the stream hops prefill→decode
    inside budget, then expires mid-decode on the IMPORTING replica —
    terminal EXPIRED, waste in the ``deadline`` bucket, zero drops."""
    s = tiny_chaos_setup
    clock = _Clock()
    pre_eng = s["make_engine"]("prefill", clock=clock)
    dec_eng = s["make_engine"]("decode", clock=clock)
    router = Router([EngineReplica("prefill-0", pre_eng),
                     EngineReplica("decode-0", dec_eng)],
                    policy="least_loaded", clock=clock)
    rid = router.submit(s["trace"][0], max_new_tokens=MAX_NEW_CHAOS,
                        deadline_s=5.0)
    router.step()           # prefill + park + hop (all inside budget)
    assert router.stats()["handoffs"] == 1
    router.step()           # the decode side emits inside budget...
    assert len(router.poll(rid).tokens) >= 1
    clock.advance(10.0)     # ...then the promise lapses mid-decode
    for _ in range(10):
        router.step()
        if router.finished(rid):
            break
    res = router.result(rid)
    assert res["state"] == "expired"
    assert router.stats()["dropped_requests"] == 0
    # The prefill token the decode side re-decoded plus anything it got
    # to emit are deadline waste, ledgered on the expiring engine.
    assert dec_eng.metrics.deadline_wasted_tokens >= 1
    snap = dec_eng.metrics.snapshot()
    assert snap["serve_deadline_wasted_tokens"] \
        == dec_eng.metrics.deadline_wasted_tokens


@pytest.mark.chaos
def test_deadline_expires_after_preemption_resume(tiny_chaos_setup):
    """Deadline honesty across the QoS seam: a batch stream preempted by
    a latency arrival, resumed, then expired mid-redecode splits its
    waste across the ``preempted`` and ``deadline`` buckets — and the
    ledger still balances to the token."""
    from deeplearning_cfn_tpu.serve.queue import RequestState

    s = tiny_chaos_setup
    clock = _Clock()
    eng = s["make_engine"]("both", capacity=1, decode_window=1,
                           clock=clock)
    r1 = eng.submit(s["trace"][0], max_new_tokens=6, deadline_s=50.0,
                    tenant="tenant-b", qos_class="batch")
    for _ in range(3):
        eng.step()          # r1 prefills and decodes a little
    assert len(r1.tokens) >= 1
    r3 = eng.submit(s["trace"][1], max_new_tokens=2, tenant="tenant-a",
                    qos_class="latency")
    for _ in range(20):
        eng.step()          # latency arrival preempts, runs, finishes
        if eng.poll(r3.id).state is RequestState.DONE:
            break
    assert eng.poll(r3.id).state is RequestState.DONE
    assert eng.metrics.preemptions >= 1
    preempted_waste = eng.metrics.preempted_wasted_tokens
    assert preempted_waste >= 1
    for _ in range(20):     # r1 resumes and re-decodes (still in budget)
        if eng.poll(r1.id).state is RequestState.RUNNING \
                and len(r1.tokens) >= 1:
            break
        eng.step()
    assert eng.poll(r1.id).state is RequestState.RUNNING
    clock.advance(100.0)    # the deadline passes mid-redecode
    eng.step()
    assert eng.poll(r1.id).state is RequestState.EXPIRED
    assert eng.metrics.deadline_wasted_tokens >= 1
    # Wasted buckets stay apart AND the whole ledger balances:
    # goodput + wasted == decoded, with both reasons accounted.
    snap = eng.metrics.snapshot()
    assert snap["serve_goodput_tokens"] + snap["serve_wasted_tokens"] \
        == snap["serve_tokens_generated"]
    assert snap["serve_wasted_tokens"] \
        >= preempted_waste + eng.metrics.deadline_wasted_tokens
    assert eng.metrics.preempted_wasted_tokens == preempted_waste


# -- brownout observability + bench record contract --------------------------


_DEGRADE_EVENTS = [
    {"event": "degrade_event", "action": "degrade", "ts": 1.0,
     "level": 1, "level_name": "no_spec", "reason": "queue_depth 9 > 6"},
    {"event": "degrade_event", "action": "degrade", "ts": 2.0,
     "level": 2, "level_name": "window_cap",
     "reason": "queue_depth 9 > 6"},
    {"event": "degrade_event", "action": "recover", "ts": 3.0,
     "level": 1, "level_name": "no_spec",
     "reason": "queue_depth 0 <= 2"},
]


def test_summarize_fleet_folds_degrade_events(tmp_path):
    from deeplearning_cfn_tpu.obs.report import (
        fleet_status_line,
        render_fleet_report,
        summarize_fleet,
    )

    root = _fleet_root(tmp_path)
    _write_jsonl(str(tmp_path / "degrade.jsonl"), _DEGRADE_EVENTS)
    s = summarize_fleet(root)
    d = s["degrade"]
    assert d["events"] == 3
    assert d["degrades"] == 2 and d["recovers"] == 1
    assert d["level"] == 1 and d["level_name"] == "no_spec"
    assert d["last_action"] == "recover"
    assert "brownout L1 (no_spec)" in fleet_status_line(s)
    report = render_fleet_report(s)
    assert "brownout: level 1 (no_spec)" in report
    assert "2 degrade(s) / 1 recover(s)" in report


def test_summarize_fleet_without_degrade_stays_legacy(tmp_path):
    from deeplearning_cfn_tpu.obs.report import (
        fleet_status_line,
        summarize_fleet,
    )

    s = summarize_fleet(_fleet_root(tmp_path))
    assert "degrade" not in s
    assert "brownout" not in fleet_status_line(s)


def test_fleet_tail_surfaces_brownout(tmp_path):
    from deeplearning_cfn_tpu.obs.tail import tail

    root = _fleet_root(tmp_path)
    _write_jsonl(str(tmp_path / "degrade.jsonl"), _DEGRADE_EVENTS)
    out = io.StringIO()
    assert tail(root, once=True, fleet=True, out=out) == 0
    line = out.getvalue().strip().splitlines()[-1]
    assert "brownout L1 (no_spec, 3 transition(s))" in line


@pytest.mark.chaos
def test_fleet_bench_chaos_plan_record_contract():
    """`bench --fleet --chaos-plan`: the plan fires, the record proves
    it, and every chaos contract holds — zero drops, token parity, a
    balanced goodput ledger."""
    from deeplearning_cfn_tpu.fleet.bench import run_fleet_bench

    plan = {"specs": [
        {"op": "replica.step", "key": "replica-0", "kind": "hang",
         "at_calls": [0]},
        {"op": "replica.step", "key": "replica-0", "kind": "crash_mid",
         "at_calls": [1]},
    ]}
    rec = run_fleet_bench(smoke=True, chaos_plan=plan)
    assert rec["chaos_plan"] == "inline"
    assert rec["faults_injected"]["hang"] == 1
    assert rec["faults_injected"]["crash_mid"] == 1
    assert rec["dropped_requests"] == 0
    assert rec["token_identical"] is True
    assert rec["goodput_sum_ok"] is True
    assert rec["deadline_wasted_tokens"] == 0
    assert rec["degrade_transitions"] is None
    assert rec["degrade_events"] is None
    assert json.dumps(rec)


@pytest.mark.chaos
def test_fleet_bench_degrade_record_contract():
    """`bench --fleet --degrade`: brownout wiring changes nothing the
    contract pins (levels are token-preserving) and the record carries
    the transition audit."""
    from deeplearning_cfn_tpu.fleet.bench import run_fleet_bench

    rec = run_fleet_bench(smoke=True, degrade=True)
    assert isinstance(rec["degrade_transitions"], int)
    assert isinstance(rec["degrade_events"], list)
    assert rec["degrade_transitions"] == len(rec["degrade_events"])
    assert rec["deadline_wasted_tokens"] == 0
    assert rec["chaos_plan"] is None
    assert rec["faults_injected"] is None
    assert rec["dropped_requests"] == 0
    assert rec["token_identical"] is True
    assert rec["goodput_sum_ok"] is True
