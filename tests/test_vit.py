"""Vision Transformer: shapes, train-mode dropout, convergence through
ClassificationTask, and TP kernel sharding."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning_cfn_tpu.config import (
    DataConfig,
    ExperimentConfig,
    MeshConfig,
    ModelConfig,
    OptimizerConfig,
    ScheduleConfig,
    TrainConfig,
)
from deeplearning_cfn_tpu.metrics import read_metrics
from deeplearning_cfn_tpu.models import build_model
from deeplearning_cfn_tpu.train.run import run_experiment


def test_vit_shapes_and_params():
    model = build_model("vit_s16", num_classes=1000, dtype=jnp.bfloat16)
    x = jnp.zeros((2, 224, 224, 3))
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    n = sum(int(np.prod(p.shape)) for p in
            jax.tree_util.tree_leaves(variables["params"]))
    assert 20e6 < n < 24e6, n  # ViT-S/16 ≈ 22M
    logits = model.apply(variables, x, train=False)
    assert logits.shape == (2, 1000)
    assert logits.dtype == jnp.float32

    with pytest.raises(ValueError, match="divisible"):
        model.apply(variables, jnp.zeros((1, 100, 100, 3)), train=False)


def test_vit_dropout_active_in_train_mode():
    """The stats-free train path must run a REAL train-mode forward:
    dropout noise varies with the rng (the silent train=False fallback
    this change removed would make these identical)."""
    from deeplearning_cfn_tpu.train.task import ClassificationTask

    cfg = ExperimentConfig(
        model=ModelConfig(name="vit_tiny", num_classes=10,
                          kwargs=dict(dropout_rate=0.5)),
        data=DataConfig(name="cifar10", image_size=32),
        train=TrainConfig(dtype="float32"),
    )
    task = ClassificationTask(cfg)
    variables = task.init(jax.random.PRNGKey(0))
    # The head kernel is zero-init (logits would be constant and hide the
    # dropout noise) — randomize it for this test.
    params = jax.tree_util.tree_map(lambda x: x, variables["params"])
    params["head"]["kernel"] = jax.random.normal(
        jax.random.PRNGKey(3), params["head"]["kernel"].shape) * 0.1
    variables = {"params": params}
    batch = {"image": jnp.ones((4, 32, 32, 3)),
             "label": jnp.zeros((4,), jnp.int32)}
    l1, _ = task.loss_fn(variables["params"], {}, batch,
                         jax.random.PRNGKey(1), True)
    l2, _ = task.loss_fn(variables["params"], {}, batch,
                         jax.random.PRNGKey(2), True)
    l_eval1, _ = task.loss_fn(variables["params"], {}, batch, None, False)
    l_eval2, _ = task.loss_fn(variables["params"], {}, batch, None, False)
    assert float(l1) != float(l2)  # dropout noise differs by rng
    assert float(l_eval1) == float(l_eval2)  # eval is deterministic


def test_vit_trains_end_to_end(tmp_workdir, devices):
    cfg = ExperimentConfig(
        model=ModelConfig(name="vit_tiny", num_classes=10,
                          kwargs=dict(dropout_rate=0.0)),
        data=DataConfig(name="cifar10", image_size=32,
                        num_train_examples=256, num_eval_examples=64,
                        prefetch=0),
        train=TrainConfig(global_batch=32, dtype="float32", eval_batch=32,
                          steps=40, log_every_steps=5),
        optimizer=OptimizerConfig(name="adamw", weight_decay=0.01,
                                  grad_clip_norm=1.0),
        schedule=ScheduleConfig(name="constant", base_lr=1e-3,
                                warmup_steps=5),
        mesh=MeshConfig(data=-1),
    )
    cfg.workdir = os.path.join(tmp_workdir, "work")
    cfg.checkpoint.async_write = False
    final = run_experiment(cfg)
    records = [r for r in read_metrics(
        os.path.join(cfg.workdir, "vit_tiny", "metrics.jsonl"))
        if "loss" in r]
    assert records[-1]["loss"] < records[0]["loss"] - 0.3, \
        (records[0], records[-1])
    assert {"accuracy", "accuracy_top5"} <= set(final)


def test_vit_tensor_parallel_shards_kernels(devices):
    from deeplearning_cfn_tpu.parallel import build_mesh
    from deeplearning_cfn_tpu.train import create_train_state
    from deeplearning_cfn_tpu.train.optim import build_optimizer, build_schedule
    from deeplearning_cfn_tpu.train.task import build_task

    cfg = ExperimentConfig(
        model=ModelConfig(name="vit_tiny", num_classes=10),
        data=DataConfig(name="cifar10", image_size=32),
        train=TrainConfig(global_batch=16, dtype="float32"),
        mesh=MeshConfig(data=4, model=2),
    )
    mesh = build_mesh(cfg.mesh)
    task = build_task(cfg)
    sched = build_schedule(cfg.schedule, 4, 16, 4)
    tx = build_optimizer(cfg.optimizer, sched)
    state = create_train_state(jax.random.PRNGKey(0), task.init, tx, mesh,
                               param_rules=task.param_rules)
    n_sharded = sum(
        1 for leaf in jax.tree_util.tree_leaves(state.params)
        if (spec := getattr(leaf.sharding, "spec", None))
        and any(ax == "model" for ax in spec if ax))
    assert n_sharded >= 6, n_sharded
