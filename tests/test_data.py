"""Data pipelines: sharding-across-processes, determinism, augmentation."""

import numpy as np
import pytest

from deeplearning_cfn_tpu.config import DataConfig
from deeplearning_cfn_tpu.data import build_pipeline
from deeplearning_cfn_tpu.data.pipeline import (
    ArraySource,
    DataPipeline,
    augment_crop_flip,
    synthetic_image_source,
)


def test_synthetic_source_learnable_structure():
    src = synthetic_image_source(256, 32, 10, seed=0)
    assert src.arrays["image"].shape == (256, 32, 32, 3)
    assert src.arrays["label"].min() >= 0
    assert src.arrays["label"].max() <= 9
    # Same-class images correlate more than cross-class ones.
    labels = src.arrays["label"]
    imgs = src.arrays["image"].reshape(256, -1)
    cls = labels[0]
    same = imgs[labels == cls]
    other = imgs[labels != cls]
    same_d = np.linalg.norm(same[0] - same[1])
    cross_d = np.linalg.norm(same[0] - other[0])
    assert same_d < cross_d


def test_pipeline_batches_and_epoch_coverage():
    src = ArraySource({"x": np.arange(64, dtype=np.float32),
                       "label": np.zeros(64, np.int32)})
    pipe = DataPipeline(src, local_batch=8, prefetch=0, process_index=0,
                        process_count=1)
    assert pipe.steps_per_epoch == 8
    seen = []
    for batch in pipe.one_epoch(0):
        assert batch["x"].shape == (8,)
        seen.extend(batch["x"].tolist())
    assert sorted(seen) == list(range(64))


def test_process_sharding_disjoint_and_complete():
    src = ArraySource({"x": np.arange(64, dtype=np.float32)})
    shards = []
    for pidx in range(4):
        pipe = DataPipeline(src, local_batch=4, prefetch=0,
                            process_index=pidx, process_count=4, seed=3)
        vals = [v for b in pipe.one_epoch(0) for v in b["x"].tolist()]
        shards.append(set(vals))
    union = set().union(*shards)
    assert len(union) == 64  # complete
    for i in range(4):
        for j in range(i + 1, 4):
            assert not (shards[i] & shards[j])  # disjoint


def test_epoch_shuffle_deterministic_and_varies():
    src = ArraySource({"x": np.arange(32, dtype=np.float32)})
    pipe = DataPipeline(src, local_batch=32, prefetch=0, process_index=0,
                        process_count=1, seed=5)
    e0a = next(iter(pipe.one_epoch(0)))["x"]
    e0b = next(iter(pipe.one_epoch(0)))["x"]
    e1 = next(iter(pipe.one_epoch(1)))["x"]
    np.testing.assert_array_equal(e0a, e0b)
    assert not np.array_equal(e0a, e1)


def test_augmentation_preserves_shape_and_changes_pixels():
    rng = np.random.RandomState(0)
    batch = {"image": np.random.rand(4, 32, 32, 3).astype(np.float32),
             "label": np.zeros(4, np.int32)}
    out = augment_crop_flip(batch, rng)
    assert out["image"].shape == batch["image"].shape
    assert not np.allclose(out["image"], batch["image"])


def test_prefetch_thread_yields_all():
    src = ArraySource({"x": np.arange(16, dtype=np.float32)})
    pipe = DataPipeline(src, local_batch=4, prefetch=2, process_index=0,
                        process_count=1)
    it = pipe.epochs()
    batches = [next(it) for _ in range(8)]  # 2 epochs worth
    assert all(b["x"].shape == (4,) for b in batches)


def test_factory_synthetic_fallback():
    cfg = DataConfig(name="cifar10", num_train_examples=128)
    pipe = build_pipeline(cfg, local_batch=16, num_classes=10)
    batch = next(iter(pipe.one_epoch(0)))
    assert batch["image"].shape == (16, 32, 32, 3)
    assert batch["label"].dtype == np.int32


def test_factory_unknown_raises():
    with pytest.raises(KeyError):
        build_pipeline(DataConfig(name="bogus"), 8, 10)


def test_prefetch_propagates_worker_errors():
    class BoomSource(ArraySource):
        def gather(self, idx):
            raise RuntimeError("disk on fire")

    src = BoomSource.__new__(BoomSource)
    src.arrays = {"x": np.arange(16, dtype=np.float32)}
    src.size = 16
    pipe = DataPipeline(src, local_batch=4, prefetch=2, process_index=0,
                        process_count=1)
    with pytest.raises(RuntimeError, match="worker crashed"):
        next(pipe.epochs())


def test_mid_epoch_resume_skips_consumed_batches():
    src = ArraySource({"x": np.arange(32, dtype=np.float32)})
    pipe = DataPipeline(src, local_batch=4, prefetch=0, process_index=0,
                        process_count=1, seed=9)
    full = [b["x"].tolist() for b in pipe.one_epoch(0)]
    resumed_it = pipe.epochs(start_epoch=0, skip_batches=3)
    resumed_first = next(resumed_it)["x"].tolist()
    assert resumed_first == full[3]


def test_padded_eval_tail_single_process():
    """drop_remainder=False: every example appears once; the final batch is
    padded with eval_mask zeros (exact-set evaluation)."""
    src = ArraySource({"x": np.arange(70, dtype=np.float32)})
    pipe = DataPipeline(src, local_batch=32, prefetch=0, shuffle=False,
                        drop_remainder=False, process_index=0,
                        process_count=1)
    batches = list(pipe.one_epoch(0))
    assert pipe.steps_per_epoch == 3 and len(batches) == 3
    masks = np.concatenate([b["eval_mask"] for b in batches])
    assert masks.sum() == 70
    xs = np.concatenate([b["x"] for b in batches])
    assert sorted(xs[masks > 0].tolist()) == list(range(70))
    # Shapes stay static even on the padded tail.
    assert all(b["x"].shape == (32,) for b in batches)


def test_padded_eval_tail_multi_process():
    """Ceil chunking: processes cover the whole set between them and run
    the SAME number of steps (collective lockstep), padding where short."""
    src = ArraySource({"x": np.arange(70, dtype=np.float32)})
    seen = []
    steps = []
    for pidx in range(3):
        pipe = DataPipeline(src, local_batch=16, prefetch=0, shuffle=False,
                            drop_remainder=False, process_index=pidx,
                            process_count=3)
        batches = list(pipe.one_epoch(0))
        steps.append(len(batches))
        for b in batches:
            seen.extend(b["x"][b["eval_mask"] > 0].tolist())
    assert len(set(steps)) == 1  # lockstep
    assert sorted(seen) == list(range(70))


def test_device_prefetcher_yields_all_in_order():
    """DevicePrefetcher is order-preserving and runs its transform on the
    worker thread (the device_batch role in the train fast path)."""
    import threading

    from deeplearning_cfn_tpu.data.pipeline import DevicePrefetcher

    src = (({"x": np.full((2,), i, np.float32)}) for i in range(20))
    worker_ids = set()

    def transform(b):
        worker_ids.add(threading.get_ident())
        return {"x": b["x"] + 1}

    pf = DevicePrefetcher(src, transform, depth=2)
    got = [int(b["x"][0]) for b in pf]
    assert got == [i + 1 for i in range(20)]
    assert worker_ids and threading.get_ident() not in worker_ids
    pf.close()
    assert not pf._thread.is_alive()


def test_device_prefetcher_close_unblocks_full_queue():
    """close() mid-stream: the worker may be blocked on a full queue and
    the wrapped generator mid-next — both must shut down cleanly (no
    daemon thread left staging batches for the rest of the process), and
    the generator's close() must run."""
    import threading

    from deeplearning_cfn_tpu.data.pipeline import DevicePrefetcher

    closed = threading.Event()

    def gen():
        try:
            i = 0
            while True:
                yield {"x": np.full((2,), i, np.float32)}
                i += 1
        finally:
            closed.set()

    pf = DevicePrefetcher(gen(), lambda b: b, depth=1)
    assert int(next(pf)["x"][0]) == 0  # worker is running and producing
    pf.close()  # queue is full again by now; worker blocked in put()
    assert not pf._thread.is_alive()
    assert closed.wait(timeout=5.0)
    # Idempotent: a second close (e.g. fit's finally after an explicit
    # close) must not raise.
    pf.close()


def test_device_prefetcher_propagates_transform_errors():
    from deeplearning_cfn_tpu.data.pipeline import DevicePrefetcher

    src = iter([{"x": np.zeros(2)}])

    def bad(b):
        raise ValueError("staging exploded")

    pf = DevicePrefetcher(src, bad, depth=2)
    with pytest.raises(RuntimeError, match="device prefetch worker"):
        next(pf)
    pf.close()
    assert not pf._thread.is_alive()
