"""Mesh/topology math over the 8-fake-device harness."""

import jax
import numpy as np
import pytest

from deeplearning_cfn_tpu.config import MeshConfig
from deeplearning_cfn_tpu.parallel import (
    MeshSpec,
    batch_sharding,
    build_mesh,
    param_sharding_tree,
    replicated,
    shard_params,
)
from deeplearning_cfn_tpu.parallel.mesh import (
    describe,
    hosts_for_slice,
    slice_chip_count,
    validate_batch,
)
from jax.sharding import PartitionSpec as P


def test_meshspec_resolve_auto_data(devices):
    spec = MeshSpec.resolve(MeshConfig(data=-1, model=2), 8)
    assert spec.data == 4 and spec.model == 2 and spec.num_devices == 8


def test_meshspec_resolve_rejects_bad_shapes():
    with pytest.raises(ValueError):
        MeshSpec.resolve(MeshConfig(data=3, model=2), 8)
    with pytest.raises(ValueError):
        MeshSpec.resolve(MeshConfig(model=3), 8)


def test_build_mesh_axes(devices):
    mesh = build_mesh(MeshConfig(data=-1, model=2, spatial=2))
    assert mesh.shape == {"dcn_data": 1, "pipe": 1, "data": 2, "expert": 1,
                          "spatial": 2, "seq": 1, "model": 2}
    assert mesh.devices.size == 8
    assert "mesh[" in describe(mesh)


def test_batch_sharding_places_batch_dim(devices):
    mesh = build_mesh(MeshConfig(data=-1))
    x = np.zeros((16, 4, 4, 3), np.float32)
    sharded = jax.device_put(x, batch_sharding(mesh, x.ndim))
    # Each of the 8 devices should hold 2 rows of the batch.
    assert sharded.addressable_shards[0].data.shape == (2, 4, 4, 3)


def test_spatial_sharding(devices):
    mesh = build_mesh(MeshConfig(data=-1, spatial=2))
    x = np.zeros((8, 16, 16, 3), np.float32)
    sharded = jax.device_put(x, batch_sharding(mesh, x.ndim, spatial_dim=1))
    assert sharded.addressable_shards[0].data.shape == (2, 8, 16, 3)


def test_param_rules_and_replication(devices):
    mesh = build_mesh(MeshConfig(data=-1, model=2))
    params = {
        "dense": {"kernel": np.zeros((16, 8), np.float32),
                  "bias": np.zeros((8,), np.float32)},
        "head": {"kernel": np.zeros((8, 4), np.float32)},
    }
    rules = [(r"dense/kernel", P(None, "model"))]
    tree = param_sharding_tree(params, mesh, rules)
    assert tree["dense"]["kernel"].spec == P(None, "model")
    assert tree["dense"]["bias"].spec == P()
    placed = shard_params(params, mesh, rules)
    assert placed["dense"]["kernel"].addressable_shards[0].data.shape == (16, 4)


def test_meshspec_resolve_multi_slice():
    spec = MeshSpec.resolve(MeshConfig(data=-1, num_slices=2), 8)
    assert spec.dcn_data == 2 and spec.data == 4 and spec.num_devices == 8
    spec = MeshSpec.resolve(MeshConfig(data=-1, model=2, num_slices=2), 8)
    assert spec.dcn_data == 2 and spec.data == 2 and spec.model == 2
    with pytest.raises(ValueError):
        MeshSpec.resolve(MeshConfig(num_slices=3), 8)  # 3 ∤ 8
    with pytest.raises(ValueError):
        # per-slice devices (8) not divisible by model*spatial (3)
        MeshSpec.resolve(MeshConfig(model=3, num_slices=2), 16)


def test_build_mesh_multi_slice(devices):
    """2 simulated slices × 4 chips: the outer dcn_data axis spans slice
    boundaries and the batch dim shards over both data axes jointly."""
    mesh = build_mesh(MeshConfig(data=-1, num_slices=2))
    assert mesh.shape == {"dcn_data": 2, "pipe": 1, "data": 4, "expert": 1,
                          "spatial": 1, "seq": 1, "model": 1}
    sh = batch_sharding(mesh, 2)
    assert sh.spec == P(("dcn_data", "data"), None)
    x = np.zeros((16, 4), np.float32)
    sharded = jax.device_put(x, sh)
    # 8 total data-parallel ways → 2 rows per device.
    assert sharded.addressable_shards[0].data.shape == (2, 4)
    # Params stay replicated across slices (full copy on every device).
    tree = param_sharding_tree({"w": np.zeros((4, 4), np.float32)}, mesh)
    assert tree["w"].spec == P()


def test_validate_batch(devices):
    mesh = build_mesh(MeshConfig(data=-1))
    validate_batch(16, mesh)
    with pytest.raises(ValueError):
        validate_batch(11, mesh)


def test_slice_math():
    assert slice_chip_count("v5p-256") == 256
    assert hosts_for_slice("v5p-8") == 2
    assert hosts_for_slice("v5p-256") == 64
    with pytest.raises(ValueError):
        slice_chip_count("bogus")


def test_replicated_spec(devices):
    mesh = build_mesh(MeshConfig())
    assert replicated(mesh).spec == P()
