"""net/ tests: the wire codec under fuzz/truncation, lossless typed
overload round-trips, an in-process loopback server↔client exchange,
and — marked slow — REAL child-process fleets: cross-process token
parity vs the in-process fleet (greedy + beam) and the zero-drop
contract across a SIGKILL'd replica mid-stream.

The contract under test everywhere: promoting replicas from in-process
objects to socket-backed processes must be invisible in outputs —
token-identical on the same seeded trace — while zero requests drop.
"""

import os
import random
import struct
import threading

import pytest

from deeplearning_cfn_tpu.fleet.router import (
    FleetOverloadError,
    NoReplicasError,
)
from deeplearning_cfn_tpu.net.codec import (
    MAX_FRAME_BYTES,
    CodecError,
    CorruptFrame,
    FrameReader,
    FrameTooLarge,
    FrameType,
    VersionMismatch,
    encode_frame,
    error_header,
    raise_error_header,
    read_frames,
)
from deeplearning_cfn_tpu.serve.handoff import HandoffCorruptError
from deeplearning_cfn_tpu.serve.queue import (
    DeadlineExceededError,
    OverloadError,
    RateLimitError,
)

# -- codec: round trip, truncation, fuzz -------------------------------------


def test_frame_round_trip_all_types():
    frames = [
        (FrameType.SUBMIT, {"rid": "r-1", "src_ids": [3, 7, 11]}, b""),
        (FrameType.TOKENS, {"req": {"id": "a", "tokens": [1, 2]}}, b""),
        (FrameType.HANDOFF_EXPORT_OK, {"rid": "r-2"}, b"\x00\x01npz"),
        (FrameType.HEALTH_OK, {"rid": "r-3", "queue_depth": 0}, b""),
    ]
    blob = b"".join(encode_frame(t, h, b) for t, h, b in frames)
    decoded, consumed = read_frames(blob)
    assert consumed == len(blob)
    assert [(f.ftype, f.header, f.body) for f in decoded] == frames


def test_partial_frame_is_silence_not_error():
    blob = encode_frame(FrameType.SUBMIT, {"rid": "r", "src_ids": [1]})
    reader = FrameReader()
    for cut in range(len(blob)):
        r = FrameReader()
        r.feed(blob[:cut])
        assert r.next() is None, f"phantom frame at truncation {cut}"
    # Byte-at-a-time delivery reassembles exactly one frame.
    for i in range(len(blob)):
        reader.feed(blob[i:i + 1])
    frames = list(reader)
    assert len(frames) == 1 and frames[0].header["rid"] == "r"
    assert reader.buffered == 0


def test_oversized_frame_rejected_before_buffering():
    reader = FrameReader()
    reader.feed(struct.pack(">I", MAX_FRAME_BYTES + 1))
    with pytest.raises(FrameTooLarge):
        reader.next()
    # The reader is poisoned: a framing-desync stream can't resync.
    with pytest.raises(CodecError):
        reader.feed(b"x")
        reader.next()


def test_version_mismatch_rejected():
    blob = bytearray(encode_frame(FrameType.HEALTH, {"rid": "r"}))
    blob[4] ^= 0x7F   # the version byte lives right after the prefix
    reader = FrameReader()
    reader.feed(bytes(blob))
    with pytest.raises(VersionMismatch):
        reader.next()


def test_garbage_bytes_rejected():
    reader = FrameReader()
    # A plausible length prefix followed by garbage: bad version or a
    # corrupt header, never a parsed frame.
    reader.feed(struct.pack(">I", 64) + b"\xde\xad" * 32)
    with pytest.raises(CodecError):
        reader.next()


def test_fuzz_random_garbage_never_yields_frames():
    rng = random.Random(0)
    for _ in range(200):
        reader = FrameReader()
        reader.feed(bytes(rng.randrange(256)
                          for _ in range(rng.randrange(1, 80))))
        try:
            frame = reader.next()
        except CodecError:
            continue
        # Not rejected means incomplete: silence, never a phantom frame.
        assert frame is None


def test_fuzz_corrupted_valid_frame():
    base = encode_frame(FrameType.SUBMIT,
                        {"rid": "r", "src_ids": list(range(16))},
                        b"body-bytes")
    rng = random.Random(1)
    for _ in range(200):
        blob = bytearray(base)
        for _ in range(rng.randrange(1, 4)):
            blob[rng.randrange(len(blob))] ^= 1 << rng.randrange(8)
        reader = FrameReader()
        reader.feed(bytes(blob))
        try:
            frame = reader.next()
        except CodecError:
            continue
        if frame is not None:
            # Flips confined to header values/body can still parse —
            # but the frame must be structurally whole, and the stream
            # must stay in sync for the next frame.
            assert isinstance(frame.header, dict)
            reader.feed(encode_frame(FrameType.HEALTH, {"rid": "h"}))
            follow = reader.next()
            assert follow is not None and follow.header["rid"] == "h"


# -- typed overload round trips ----------------------------------------------


def test_fleet_overload_round_trips_losslessly():
    exc = FleetOverloadError(7, 8, 0.25,
                             per_replica={"r0": 0.25, "r1": None})
    h = error_header(exc, rid="rid-1", recovery_horizon_s=1.5)
    assert h["code"] == "fleet_overload"
    with pytest.raises(FleetOverloadError) as ei:
        raise_error_header(h)
    back = ei.value
    assert (back.depth, back.max_depth, back.retry_after_s) == (7, 8, 0.25)
    assert back.per_replica == {"r0": 0.25, "r1": None}
    assert back.recovery_horizon_s == 1.5
    assert back.rid == "rid-1"
    assert isinstance(back, OverloadError)


def test_rate_limit_round_trips_losslessly():
    exc = RateLimitError("latency", "tenant-a", 0.75, 3, 4)
    h = error_header(exc)
    assert h["code"] == "rate_limit"
    with pytest.raises(RateLimitError) as ei:
        raise_error_header(h)
    back = ei.value
    assert back.qos_class == "latency"
    assert back.tenant == "tenant-a"
    assert back.retry_after_s == 0.75
    assert (back.depth, back.max_depth) == (3, 4)


def test_overload_and_draining_round_trip():
    h = error_header(OverloadError(2, 2, retry_after_s=0.05))
    assert h["code"] == "overload"
    with pytest.raises(OverloadError) as ei:
        raise_error_header(h)
    assert ei.value.retry_after_s == 0.05
    # "draining" means exactly "try the next candidate" — a plain
    # OverloadError, so mid-placement routers need no special case.
    with pytest.raises(OverloadError):
        raise_error_header({"code": "draining", "message": "draining"})


def test_remaining_error_codes_round_trip():
    cases = [
        (DeadlineExceededError("too late"), DeadlineExceededError),
        (KeyError("nope"), KeyError),
        (HandoffCorruptError("bad npz"), HandoffCorruptError),
        (ValueError("bad submit"), ValueError),
        (RuntimeError("boom"), RuntimeError),
    ]
    for exc, klass in cases:
        with pytest.raises(klass):
            raise_error_header(error_header(exc))
    with pytest.raises(NoReplicasError):
        raise_error_header({"code": "no_replicas", "message": "none"})
    # handoff_corrupt must NOT degrade to the generic "invalid" even
    # though HandoffCorruptError IS-A ValueError.
    assert error_header(HandoffCorruptError("x"))["code"] \
        == "handoff_corrupt"


# -- in-process loopback: server thread ↔ RemoteReplica ----------------------


@pytest.fixture(scope="module")
def loopback(tmp_path_factory):
    """One tiny-engine ReplicaServer on a unix socket in a daemon
    thread, plus a connected RemoteReplica. Module-scoped: one jax
    model build for every loopback test."""
    import jax
    import numpy as np

    from deeplearning_cfn_tpu.models.transformer_nmt import (
        transformer_nmt_tiny,
    )
    from deeplearning_cfn_tpu.net.client import RemoteReplica
    from deeplearning_cfn_tpu.net.server import ReplicaServer
    from deeplearning_cfn_tpu.serve.engine import Engine

    model = transformer_nmt_tiny(vocab_size=96, max_len=64)
    init = model.init(jax.random.PRNGKey(0),
                      np.zeros((1, 8), np.int32),
                      np.ones((1, 8), np.int32),
                      np.zeros((1, 8), np.int32), train=False)
    engine = Engine(model, {"params": init["params"]}, capacity=2,
                    max_src_len=8, queue_depth=4,
                    default_max_new_tokens=4, decode_window=4)
    addr = f"unix://{tmp_path_factory.mktemp('net')}/replica.sock"
    server = ReplicaServer(engine, addr, replica_id="loop")
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    replica = RemoteReplica("loop", addr,
                            connect_retry_deadline_s=30.0).connect()
    yield replica
    replica.drain()
    replica.close()
    thread.join(timeout=10)


def test_loopback_submit_stream_and_result(loopback):
    req = loopback.submit([5, 9, 13, 2], max_new_tokens=4,
                          request_id="loop-1")
    assert req.id == "loop-1"
    deadline = 100
    while req.state.value not in ("done", "cancelled", "expired") \
            and deadline:
        loopback.step()
        deadline -= 1
    assert req.state.value == "done"
    assert len(req.tokens) >= 1
    assert req.ttft_s is not None


def test_loopback_health_and_unknown_cancel(loopback):
    h = loopback.health()
    assert h["replica"] == "loop"
    assert h["queue_max_depth"] == 4
    # Same duck type as EngineReplica: unknown-id cancel is a KeyError,
    # round-tripped over the wire as the typed unknown_request frame.
    with pytest.raises(KeyError):
        loopback.cancel("never-submitted")


# -- real child processes (slow) ---------------------------------------------


def _spawn(tmp_path, phases, **kwargs):
    from deeplearning_cfn_tpu.net.bench import spawn_process_fleet

    defaults = dict(slots=2, src_len=8, max_new_tokens=4,
                    queue_depth=16, decode_window=4, seed=0)
    defaults.update(kwargs)
    return spawn_process_fleet(str(tmp_path), phases, **defaults)


def _drive(router, trace, max_new_tokens, beam_size=1, prefix="q"):
    rids = []
    for i, src in enumerate(trace):
        while True:
            try:
                rids.append(router.submit(
                    src, max_new_tokens=max_new_tokens,
                    beam_size=beam_size, request_id=f"{prefix}{i}"))
                break
            except (OverloadError, NoReplicasError):
                router.step()
    router.run_until_drained(idle_timeout_s=60.0)
    return {rid: list(router.result(rid)["tokens"]) for rid in rids}


@pytest.mark.slow
def test_cross_process_token_parity_greedy_and_beam(tmp_path):
    from deeplearning_cfn_tpu.net.bench import (
        _reference_tokens,
        _teardown,
    )
    from deeplearning_cfn_tpu.net.router import NetRouter
    from deeplearning_cfn_tpu.serve.bench import _fixed_trace

    trace = _fixed_trace(4, 8, 96, seed=0)
    warm = trace[0]
    sup, remotes = _spawn(tmp_path, ["both", "both"], warmup_src=warm)
    try:
        rt = NetRouter(remotes, supervisor=sup)
        got_greedy = _drive(rt, trace, 4, beam_size=1, prefix="g")
        got_beam = _drive(rt, trace, 4, beam_size=2, prefix="b")
        assert rt.dropped_requests == 0
    finally:
        _teardown(sup, remotes)
    for beam, got, prefix in ((1, got_greedy, "g"), (2, got_beam, "b")):
        # The reference helper submits with request ids q0..qN in trace
        # order; match by index.
        ref = _reference_tokens(trace, 4, beam, slots=2, src_len=8,
                                queue_depth=16, decode_window=4, seed=0)
        for i in range(len(trace)):
            assert got[f"{prefix}{i}"] == ref[f"q{i}"], \
                f"beam={beam} request {i} parity broken"


@pytest.mark.slow
def test_sigkill_mid_stream_zero_drops(tmp_path):
    from deeplearning_cfn_tpu.net.bench import _teardown
    from deeplearning_cfn_tpu.net.router import NetRouter
    from deeplearning_cfn_tpu.serve.bench import _fixed_trace

    trace = _fixed_trace(6, 8, 96, seed=0)
    sup, remotes = _spawn(tmp_path, ["both", "both"],
                          warmup_src=trace[0], max_restarts=1)
    try:
        rt = NetRouter(remotes, supervisor=sup)
        rids = []
        for i, src in enumerate(trace):
            while True:
                try:
                    rids.append(rt.submit(src, max_new_tokens=8,
                                          request_id=f"k{i}"))
                    break
                except (OverloadError, NoReplicasError):
                    rt.step()
        # SIGKILL one replica while its streams are mid-decode: the
        # router must evacuate and replay them elsewhere, zero drops.
        victim = sup._replicas[1].handle._procs[0].proc
        victim.kill()
        rt.run_until_drained(idle_timeout_s=60.0)
        assert rt.dropped_requests == 0
        results = [rt.result(rid) for rid in rids]
        assert all(r["state"] == "done" for r in results)
        assert all(len(r["tokens"]) >= 1 for r in results)
        assert rt.evacuations >= 1 or rt.reconnects >= 1
    finally:
        _teardown(sup, remotes)
