"""Test harness: 8 fake CPU devices (SURVEY.md §5 strategy #2).

The reference had no multi-node test harness at all; ours simulates every
mesh/pjit/collective path single-process by forcing the CPU backend with 8
virtual devices. Must run before jax initializes its backends, hence env
setup at conftest import time.
"""

import os

os.environ.setdefault("JAX_ENABLE_X64", "0")

# This image's sitecustomize pre-registers a TPU PJRT plugin before conftest
# runs, so the env var alone is too late — the shared helper also switches
# the platform in-process, before any backend initializes.
from deeplearning_cfn_tpu.runtime.platform import force_cpu_platform  # noqa: E402

force_cpu_platform(8)

import jax  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 fake devices, got {devs}"
    return devs


@pytest.fixture()
def tmp_workdir(tmp_path):
    return str(tmp_path)
