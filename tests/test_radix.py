"""Radix token-prefix KV cache tests.

Three layers, mirroring the feature's own: the RadixCache structure over
a bare BlockAllocator (lookup/insert/LRU eviction/tenant isolation — no
JAX), the engine integration on the tiny NMT model (the token-parity
contract: a radix engine's output must be byte-identical to a cold-cache
engine across repeated sources, divergent budgets, instant completes and
pool-pressure eviction, with refcount conservation throughout), and the
fleet's prefix-affinity routing (rendezvous placement stability under
membership churn, and cache locality through a real two-replica router).
"""

import jax
import numpy as np
import pytest

from deeplearning_cfn_tpu.fleet import EngineReplica, Router
from deeplearning_cfn_tpu.fleet.router import PrefixAffinityPolicy
from deeplearning_cfn_tpu.models import decoding
from deeplearning_cfn_tpu.models.transformer_nmt import transformer_nmt_tiny
from deeplearning_cfn_tpu.serve.blockpool import BlockAllocator
from deeplearning_cfn_tpu.serve.engine import Engine
from deeplearning_cfn_tpu.serve.metrics import ServeMetrics
from deeplearning_cfn_tpu.serve.queue import OverloadError
from deeplearning_cfn_tpu.serve.radix import RadixCache

# -- RadixCache over a bare allocator (no JAX) -------------------------------

BS = 4


def _chain(cache, alloc, src, tokens, tenant=None, now=0.0):
    """Allocate fully-written blocks for ``tokens`` (a multiple of BS)
    and insert them, the way the engine does on a DONE stream."""
    blocks = [alloc.alloc() for _ in range(len(tokens) // BS)]
    cache.insert(tuple(src), list(tokens), blocks, alloc, now,
                 tenant=tenant)
    # The finished stream retires: its own references go away and the
    # tree's survive, exactly the engine's release order.
    for b in blocks:
        alloc.free(b)
    return blocks


def test_radix_lookup_miss_then_insert_roundtrip():
    alloc = BlockAllocator(num_blocks=9, block_size=BS)
    cache = RadixCache(BS)
    assert cache.lookup((1, 2), now=0.0) == ([], [])
    toks = list(range(10, 18))            # two full blocks
    blocks = _chain(cache, alloc, (1, 2), toks, now=1.0)
    assert cache.source_count == 1
    assert cache.node_count == 2 and cache.block_count == 2
    got_t, got_b = cache.lookup((1, 2), now=2.0)
    assert got_t == toks and got_b == blocks
    # A different source shares nothing.
    assert cache.lookup((9, 9), now=2.0) == ([], [])
    # Tree-exclusive accounting: the chain is held only by the tree.
    assert cache.tree_exclusive_blocks(alloc) == 2
    for b in blocks:
        assert alloc.refcount(b) == 1


def test_radix_insert_existing_segments_not_double_referenced():
    """Re-inserting a chain (a concurrent same-source finisher) touches
    existing nodes instead of creating duplicates or leaking refs — the
    duplicate blocks stay owned by their finisher."""
    alloc = BlockAllocator(num_blocks=9, block_size=BS)
    cache = RadixCache(BS)
    toks = list(range(20, 28))
    blocks = _chain(cache, alloc, (5,), toks, now=1.0)
    dup = [alloc.alloc() for _ in range(2)]
    created = cache.insert((5,), toks, dup, alloc, now=2.0)
    assert created == 0
    assert cache.node_count == 2
    # The duplicates were NOT referenced by the tree; freeing them (as
    # their finisher would) must empty them out of the pool.
    for b in dup:
        assert alloc.refcount(b) == 1
        alloc.free(b)
    for b in blocks:
        assert alloc.refcount(b) == 1


def test_radix_ensure_free_evicts_lru_exclusive_leaves():
    alloc = BlockAllocator(num_blocks=7, block_size=BS)  # 6 usable
    cache = RadixCache(BS)
    _chain(cache, alloc, (1,), list(range(8)), now=1.0)   # cold chain
    _chain(cache, alloc, (2,), list(range(8)), now=5.0)   # hot chain
    assert cache.tree_exclusive_blocks(alloc) == 4
    # Committing 4 blocks needs 4 + tree(4) <= 6 → evict 2, coldest
    # leaves first (deepest node of the LRU chain goes before its
    # parent).
    evicted = cache.ensure_free(alloc, need=4)
    assert evicted == {"pressure": 2}
    assert cache.source_count == 1
    assert cache.lookup((1,), now=6.0) == ([], [])         # cold gone
    assert len(cache.lookup((2,), now=6.0)[1]) == 2        # hot intact
    assert alloc.committed_blocks + 4 \
        + cache.tree_exclusive_blocks(alloc) <= alloc.usable_blocks


def test_radix_ensure_free_prefers_own_tenant_then_crosses():
    alloc = BlockAllocator(num_blocks=5, block_size=BS)  # 4 usable
    cache = RadixCache(BS)
    # tenant-b's chain is COLDER, but tenant-a's pressure must consume
    # tenant-a's own leaf first.
    _chain(cache, alloc, (1,), list(range(4)), tenant="b", now=1.0)
    _chain(cache, alloc, (2,), list(range(4)), tenant="a", now=9.0)
    ev1 = cache.ensure_free(alloc, need=3, tenant="a")
    assert ev1 == {"pressure": 1}
    assert cache.lookup((2,), now=10.0) == ([], [])   # a's own went
    assert len(cache.lookup((1,), now=10.0)[1]) == 1  # b's survived
    # Only cross-tenant leaves remain — last resort, labeled as such.
    ev2 = cache.ensure_free(alloc, need=4, tenant="a")
    assert ev2 == {"cross_tenant_pressure": 1}
    assert cache.source_count == 0
    assert cache.evictions == {"pressure": 1, "cross_tenant_pressure": 1}


def test_radix_never_evicts_blocks_referenced_by_running_streams():
    alloc = BlockAllocator(num_blocks=3, block_size=BS)  # 2 usable
    cache = RadixCache(BS)
    blocks = _chain(cache, alloc, (1,), list(range(8)), now=1.0)
    # A running stream holds the chain (the engine's resume path refs
    # every matched block).
    for b in blocks:
        alloc.ref(b)
    assert cache.tree_exclusive_blocks(alloc) == 0
    evicted = cache.ensure_free(alloc, need=2)
    assert evicted == {}                  # nothing evictable — pinned
    assert cache.node_count == 2
    for b in blocks:
        assert alloc.refcount(b) == 2


def test_radix_reset_releases_every_tree_reference():
    alloc = BlockAllocator(num_blocks=9, block_size=BS)
    cache = RadixCache(BS)
    _chain(cache, alloc, (1,), list(range(8)), now=1.0)
    _chain(cache, alloc, (2,), list(range(4)), now=2.0)
    assert alloc.blocks_in_use == 3
    dropped = cache.reset(alloc)
    assert dropped == 3
    assert cache.source_count == 0 and cache.node_count == 0
    assert alloc.blocks_in_use == 0
    assert cache.evictions["reset"] == 3


def test_radix_metrics_keys_are_conditional():
    """An unconfigured ServeMetrics snapshot has NO serve_radix_ keys
    (the pinned obs contract); configure_radix adds the whole surface."""

    class _Clock:
        def __call__(self):
            return 0.0

    base = ServeMetrics(capacity=2, clock=_Clock())
    assert not any(k.startswith("serve_radix_") for k in base.snapshot())
    m = ServeMetrics(capacity=2, clock=_Clock())
    m.configure_radix()
    m.record_radix_lookup("miss", 0)
    m.record_radix_lookup("hit", 8)
    m.record_radix_lookup("instant", 4)
    m.record_radix_blocks(2, 3)
    m.record_radix_evictions("pressure", 2)
    m.set_radix_size(nodes=5, blocks=5)
    snap = m.snapshot()
    assert snap["serve_radix_nodes"] == 5
    assert snap["serve_radix_blocks"] == 5
    assert snap["serve_radix_hits"] == 2          # hit + instant
    assert snap["serve_radix_misses"] == 1
    assert snap["serve_radix_hit_rate"] == pytest.approx(2 / 3)
    assert snap["serve_radix_instant_completes"] == 1
    assert snap["serve_radix_hit_tokens"] == 12
    assert snap["serve_radix_shared_blocks"] == 2
    assert snap["serve_radix_shared_block_ratio"] == pytest.approx(2 / 3)
    assert snap["serve_radix_evictions"] == 2
    assert snap["serve_radix_evictions_by_cause"] == {"pressure": 2}


# -- engine integration: the token-parity contract ---------------------------

SCHED_VOCAB = 64
SCHED_SRC_LEN = 8


@pytest.fixture(scope="module")
def sched_model():
    model = transformer_nmt_tiny(vocab_size=SCHED_VOCAB, hidden_size=32,
                                 num_layers=1, num_heads=2, mlp_dim=64,
                                 max_len=32)
    variables = model.init(
        jax.random.PRNGKey(0), np.zeros((1, SCHED_SRC_LEN), np.int32),
        np.ones((1, SCHED_SRC_LEN), np.int32),
        np.zeros((1, SCHED_SRC_LEN), np.int32), train=False)
    return model, {"params": variables["params"]}


def _mk_engine(sched_model, radix=True, **kw):
    model, variables = sched_model
    kw.setdefault("capacity", 2)
    kw.setdefault("max_src_len", SCHED_SRC_LEN)
    kw.setdefault("queue_depth", 32)
    kw.setdefault("kv_block_size", 4)
    return Engine(model, variables, radix_cache=radix, **kw)


def _src(seed, n=5):
    rng = np.random.RandomState(seed)
    return [int(t) for t in rng.randint(3, SCHED_VOCAB, size=n - 1)] + \
        [decoding.EOS_ID]


def _decode_all(eng, trace):
    """Submit (src, budget, beam) triples with backpressure, drain, and
    return the per-trace-index token lists."""
    ids = []
    for src, budget, beam in trace:
        while True:
            try:
                ids.append(eng.submit(src, max_new_tokens=budget,
                                      beam_size=beam).id)
                break
            except OverloadError:
                eng.step()
    eng.run_until_drained()
    return [list(eng.poll(i).tokens) for i in ids]


# The divergent-budget trace: repeats of two sources at budgets shorter
# than, equal to, and longer than what the cache holds — instant
# completes, block-boundary resumes, and the copy-on-write tail all in
# one pass.
def _parity_trace():
    s0, s1 = _src(1), _src(2)
    return [(s0, 8, 1), (s1, 6, 1), (s0, 4, 1), (s0, 8, 1),
            (s1, 6, 1), (s0, 12, 1), (s1, 3, 1), (s0, 8, 1)]


@pytest.mark.parametrize("kv_quant", ["", "int8"])
def test_radix_token_parity_vs_cold_cache(sched_model, kv_quant):
    trace = _parity_trace()
    cold = _decode_all(
        _mk_engine(sched_model, radix=False, kv_quant=kv_quant), trace)
    eng = _mk_engine(sched_model, kv_quant=kv_quant)
    warm = _decode_all(eng, trace)
    assert warm == cold
    snap = eng.metrics.snapshot()
    assert snap["serve_radix_hits"] > 0
    assert snap["serve_radix_instant_completes"] > 0
    assert snap["serve_radix_hit_tokens"] > 0
    assert eng.metrics.radix_hit_rate > 0


def test_radix_beam_requests_bypass_the_tree(sched_model):
    """Beam groups neither read nor populate the tree (their block
    tables fork), but greedy traffic around them still shares — and
    every token matches the cold engine. Driven one request at a time so
    the hit/miss ledger is deterministic (concurrent same-source misses
    are legal but unpredictable)."""
    s0 = _src(3)
    trace = [(s0, 6, 2), (s0, 6, 1), (s0, 6, 1), (s0, 6, 2)]

    def _sequential(engine):
        out = []
        for src, budget, beam in trace:
            rid = engine.submit(src, max_new_tokens=budget,
                                beam_size=beam).id
            engine.run_until_drained()
            out.append(list(engine.poll(rid).tokens))
        return out

    cold = _sequential(_mk_engine(sched_model, radix=False))
    eng = _mk_engine(sched_model)
    warm = _sequential(eng)
    assert warm == cold
    snap = eng.metrics.snapshot()
    # Two greedy admissions: one miss (inserts), one cached reuse.
    assert snap["serve_radix_hits"] == 1
    assert snap["serve_radix_misses"] == 1
    assert eng.radix.source_count == 1


def test_radix_engine_requires_paged_colocated(sched_model):
    model, variables = sched_model
    with pytest.raises(ValueError):
        Engine(model, variables, capacity=2, max_src_len=SCHED_SRC_LEN,
               radix_cache=True, kv_block_size=0)
    with pytest.raises(ValueError):
        Engine(model, variables, capacity=2, max_src_len=SCHED_SRC_LEN,
               radix_cache=True, kv_block_size=4, phase="prefill")


def test_radix_eviction_under_pool_pressure(sched_model):
    """A pool too small for the whole working set forces ensure_free to
    evict cold chains at admission — and decoding stays correct and
    complete throughout (no drops, token parity, invariant holds)."""
    trace = [(_src(10 + i), 8, 1) for i in range(5)]
    cold = _decode_all(
        _mk_engine(sched_model, radix=False, capacity=1, kv_blocks=8),
        trace)
    eng = _mk_engine(sched_model, capacity=1, kv_blocks=8)
    warm = _decode_all(eng, trace)
    assert warm == cold
    assert eng.radix.evictions.get("pressure", 0) > 0
    alloc = eng.allocator
    assert (alloc.committed_blocks + eng.radix.tree_exclusive_blocks(alloc)
            <= alloc.usable_blocks)
    assert eng.metrics.snapshot()["serve_radix_evictions"] > 0


def test_radix_refcount_conservation_and_reset(sched_model):
    """After drain, every live pool block is a tree block with exactly
    one reference (no leaks, no double-refs); reset returns the pool to
    empty."""
    eng = _mk_engine(sched_model)
    _decode_all(eng, _parity_trace())
    alloc = eng.allocator
    refs = alloc.refcounts()
    assert len(refs) == eng.radix.block_count
    assert all(c == 1 for c in refs.values())
    assert eng.radix.tree_exclusive_blocks(alloc) == eng.radix.block_count
    dropped = eng.reset_radix_cache()
    assert dropped > 0
    assert alloc.blocks_in_use == 0
    assert eng.radix.source_count == 0
    snap = eng.metrics.snapshot()
    assert snap["serve_radix_evictions_by_cause"]["reset"] == dropped
    assert snap["serve_radix_nodes"] == 0 and snap["serve_radix_blocks"] == 0


def test_radix_cache_is_dropped_on_weight_swap(sched_model):
    """swap_variables invalidates every cached stream — the old weights'
    tokens are not prefixes of the new weights' decodes."""
    model, variables = sched_model
    eng = _mk_engine(sched_model)
    _decode_all(eng, [(_src(1), 8, 1)])
    assert eng.radix.source_count == 1
    eng.swap_variables(variables)
    assert eng.radix.source_count == 0
    assert eng.allocator.blocks_in_use == 0


# -- prefix-affinity routing -------------------------------------------------


def _cands(ids):
    return [(rid, {}) for rid in sorted(ids)]


def test_prefix_affinity_is_deterministic_and_key_sticky():
    pol = PrefixAffinityPolicy()
    ids = [f"replica-{i}" for i in range(3)]
    first = pol.order_for(_cands(ids), "grp-0")
    assert sorted(first) == ids
    for _ in range(3):
        assert pol.order_for(_cands(ids), "grp-0") == first
    # Keyless requests fall back to the load order untouched.
    assert pol.order_for(_cands(ids), None) == pol.order(_cands(ids))


def test_prefix_affinity_churn_remaps_only_the_removed_replicas_keys():
    """Rendezvous hashing's stability contract: removing one replica
    remaps ONLY the keys that preferred it — every other key's placement
    survives the membership change (no thundering re-hash)."""
    pol = PrefixAffinityPolicy()
    ids = [f"replica-{i}" for i in range(3)]
    keys = [f"grp-{i}" for i in range(30)]
    before = {k: pol.order_for(_cands(ids), k)[0] for k in keys}
    assert set(before.values()) == set(ids)   # all replicas drew keys
    survivors = [r for r in ids if r != "replica-1"]
    after = {k: pol.order_for(_cands(survivors), k)[0] for k in keys}
    for k in keys:
        if before[k] == "replica-1":
            assert after[k] in survivors
        else:
            assert after[k] == before[k]


def test_router_prefix_affinity_colocates_groups_on_real_engines(
        sched_model):
    """End to end: same affinity key → same replica → radix reuse on
    that replica; and keyless same-source requests derive a token-based
    key that colocates them just the same."""
    # capacity=1 so same-source requests admit one at a time — the
    # hit ledger is then exactly one cold miss + three reuses.
    reps = [EngineReplica(f"replica-{i}", _mk_engine(sched_model,
                                                     capacity=1))
            for i in range(2)]
    router = Router(reps, policy="prefix_affinity")
    s = _src(7)
    rids = [router.submit(s, max_new_tokens=4, affinity_key="grp-0")
            for _ in range(4)]
    router.run_until_drained()
    results = [router.result(r) for r in rids]
    assert all(r["state"] == "done" for r in results)
    assert len({tuple(r["tokens"]) for r in results}) == 1
    placed = {router._requests[r].replica_id for r in rids}
    assert len(placed) == 1
    rep = next(rp for rp in reps if rp.id in placed)
    # One cold decode, three cached reuses — all on the one replica.
    assert rep.engine.metrics.radix_hits == 3
    # Keyless: the router derives the affinity key from the leading
    # source tokens, so bare repeats of one prompt still colocate.
    s2 = _src(8)
    rids2 = [router.submit(s2, max_new_tokens=4) for _ in range(3)]
    router.run_until_drained()
    assert len({router._requests[r].replica_id for r in rids2}) == 1
