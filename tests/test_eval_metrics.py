"""Acceptance-metric layer: BLEU, COCO mAP, and the decoding searchers.

These are the reference workloads' own yardsticks (BASELINE.md rows 5-6:
box/mask mAP for Mask R-CNN, BLEU for Sockeye NMT). The searchers are
verified against brute-force Python implementations on a tiny random model —
beam bookkeeping (gather order, done-freezing, length normalization) is
exactly the kind of code that is wrong until executed.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning_cfn_tpu.metrics.bleu import corpus_bleu
from deeplearning_cfn_tpu.metrics.coco_map import (
    DetectionAccumulator,
    box_iou_np,
    mask_iou_np,
    paste_mask,
)
from deeplearning_cfn_tpu.models.decoding import (
    BOS_ID,
    EOS_ID,
    PAD_ID,
    beam_decode,
    beam_decode_cached,
    greedy_decode,
    greedy_decode_cached,
    strip_special,
)
from deeplearning_cfn_tpu.models.transformer_nmt import TransformerNMT


# -- BLEU -------------------------------------------------------------------


def test_bleu_perfect_match():
    refs = [[3, 4, 5, 6, 7], [8, 9, 10, 11]]
    assert corpus_bleu(refs, refs) == pytest.approx(1.0)


def test_bleu_zero_on_disjoint():
    assert corpus_bleu([[3, 4, 5, 6]], [[7, 8, 9, 10]]) == 0.0


def test_bleu_brevity_penalty():
    # Hypothesis is a perfect prefix, half the reference length:
    # precisions are 1.0, so BLEU = BP = exp(1 - ref/hyp) = exp(-1).
    ref = [3, 4, 5, 6, 7, 8, 9, 10]
    hyp = ref[:4]
    assert corpus_bleu([hyp], [ref]) == pytest.approx(np.exp(1 - 8 / 4))


def test_bleu_clipping():
    # "the the the ..." pathology: 1-gram matches are clipped to the
    # reference count (2), not len(hyp).
    hyp = [3] * 6
    ref = [3, 4, 3, 5, 6, 7]
    # Only 1-grams match (no repeated bigrams in ref) → BLEU 0 unsmoothed.
    assert corpus_bleu([hyp], [ref]) == 0.0
    # Smoothed: 1-gram precision must reflect clipping = 2/6.
    smoothed = corpus_bleu([hyp], [ref], smooth=True)
    assert 0.0 < smoothed < 2 / 6


def test_bleu_smoothing_does_not_reward_impossible_orders():
    # 2-token hypothesis has zero 3/4-grams; smoothing must not grant those
    # orders 1/1 precision. Effective order here is {1,2}-grams:
    # p1 = 1/2, p2 smoothed = 1/2 → BLEU = 0.5 (NOT sqrt(0.5) ≈ 0.707).
    assert corpus_bleu([[3, 9]], [[3, 4]], smooth=True) == pytest.approx(0.5)
    # And a perfect-but-short pair scores 1.0 under effective order.
    assert corpus_bleu([[3, 4, 5]], [[3, 4, 5]]) == pytest.approx(1.0)


def test_bleu_corpus_level_not_mean_of_sentences():
    # One perfect long pair + one disjoint short pair: corpus BLEU pools
    # counts, so the result is strictly between 0 and 1 (a mean of
    # sentence BLEUs with zero 4-gram matches would be 0.5 or 0).
    hyps = [[3, 4, 5, 6, 7, 8, 9, 10], [20, 21]]
    refs = [[3, 4, 5, 6, 7, 8, 9, 10], [30, 31]]
    score = corpus_bleu(hyps, refs)
    assert 0.0 < score < 1.0


# -- COCO mAP ---------------------------------------------------------------


def _square(y0, x0, size):
    return [y0, x0, y0 + size, x0 + size]


def test_box_iou_np():
    a = np.array([_square(0, 0, 10)], np.float64)
    b = np.array([_square(0, 0, 10), _square(0, 5, 10), _square(20, 20, 5)],
                 np.float64)
    iou = box_iou_np(a, b)
    assert iou[0, 0] == pytest.approx(1.0)
    assert iou[0, 1] == pytest.approx(50 / 150)
    assert iou[0, 2] == 0.0


def test_paste_mask_full_box():
    m = np.ones((28, 28), np.float32)
    out = paste_mask(m, np.array([2.0, 2.0, 6.0, 6.0]), 8, 8)
    expect = np.zeros((8, 8), bool)
    expect[2:6, 2:6] = True
    assert (out == expect).all()


def test_mask_iou_np_identity_and_disjoint():
    a = np.zeros((8, 8), bool)
    a[0:4] = True
    b = ~a
    assert mask_iou_np([a], [a])[0, 0] == pytest.approx(1.0)
    assert mask_iou_np([a], [b])[0, 0] == 0.0


def _add_perfect_image(acc, with_masks=True):
    gt_boxes = np.array([_square(2, 2, 10), _square(20, 20, 8)], np.float64)
    gt_labels = np.array([1, 2], np.int32)
    masks = np.ones((2, 28, 28), np.float32)
    acc.add_image(
        gt_boxes, np.array([0.9, 0.8]), gt_labels, gt_boxes, gt_labels,
        pred_masks=masks if with_masks else None,
        gt_masks=masks if with_masks else None,
        image_hw=(40, 40))


def test_map_perfect_detections():
    acc = DetectionAccumulator()
    _add_perfect_image(acc)
    out = acc.compute(with_masks=True)
    assert out["map"] == pytest.approx(1.0)
    assert out["map50"] == pytest.approx(1.0)
    assert out["mask_map"] == pytest.approx(1.0)


def test_map_known_precision_recall():
    # 2 GT of class 1; detections: one TP (score .9) and one far-away FP
    # (score .8). p(r)=1 for r<=0.5, 0 beyond → 101-point AP = 51/101.
    acc = DetectionAccumulator(iou_thresholds=np.array([0.5]))
    gt_boxes = np.array([_square(0, 0, 10), _square(30, 30, 10)], np.float64)
    gt_labels = np.array([1, 1], np.int32)
    pred_boxes = np.array([_square(0, 0, 10), _square(60, 60, 10)],
                          np.float64)
    acc.add_image(pred_boxes, np.array([0.9, 0.8]), np.array([1, 1]),
                  gt_boxes, gt_labels)
    out = acc.compute()
    assert out["map50"] == pytest.approx(51 / 101)


def test_map_one_detection_per_gt():
    # Two identical detections on one GT: the second must count as FP.
    acc = DetectionAccumulator(iou_thresholds=np.array([0.5]))
    box = np.array([_square(0, 0, 10)], np.float64)
    acc.add_image(np.repeat(box, 2, 0), np.array([0.9, 0.8]),
                  np.array([1, 1]), box, np.array([1], np.int32))
    out = acc.compute()
    # AP: recall hits 1.0 at precision 1.0 (first det), envelope keeps
    # p=1.0 through r=1.0 → AP 1.0 — matching cocoeval (the FP comes after
    # full recall so it never lowers the envelope at any grid point).
    assert out["map50"] == pytest.approx(1.0)


def test_map_class_zero_predictions_ignored():
    acc = DetectionAccumulator(iou_thresholds=np.array([0.5]))
    box = np.array([_square(0, 0, 10)], np.float64)
    acc.add_image(box, np.array([0.9]), np.array([0]),  # class 0 = padding
                  box, np.array([1], np.int32))
    out = acc.compute()
    assert out["map50"] == 0.0  # no usable detection, GT present


# -- decoding searchers vs brute force --------------------------------------


VOCAB = 12
MAXLEN = 6


@pytest.fixture(scope="module")
def tiny_nmt():
    model = TransformerNMT(vocab_size=VOCAB, hidden_size=16, num_layers=1,
                           num_heads=2, mlp_dim=32, max_len=MAXLEN + 1,
                           dtype=jnp.float32)
    rng = jax.random.PRNGKey(7)
    src = jnp.zeros((1, 4), jnp.int32)
    variables = model.init(rng, src, jnp.ones((1, 4), jnp.int32),
                           jnp.zeros((1, MAXLEN + 1), jnp.int32)[:, :-1],
                           train=False)
    return model, variables


def _stepwise_logp(model, variables, src, src_mask, prefix):
    """Log-probs over the vocab for the next token after `prefix` (list of
    ids starting with BOS) — the brute-force oracle the searchers must
    match. Uses the same encode/decode apply path."""
    enc = model.apply(variables, src, src_mask, method=TransformerNMT.encode)
    t = len(prefix) - 1
    tokens = np.full((1, MAXLEN), PAD_ID, np.int32)
    tokens[0, :len(prefix)] = prefix
    logits = model.apply(variables, jnp.asarray(tokens), enc, src_mask,
                         method=TransformerNMT.decode)
    return np.asarray(
        jax.nn.log_softmax(logits[0, t, :].astype(jnp.float32)))


def _brute_greedy(model, variables, src, src_mask):
    prefix = [BOS_ID]
    out = []
    done = False
    for _ in range(MAXLEN):
        if done:
            out.append(PAD_ID)
            continue
        logp = _stepwise_logp(model, variables, src, src_mask, prefix)
        nxt = int(np.argmax(logp))
        out.append(nxt)
        prefix.append(nxt)
        done = nxt == EOS_ID
    return out


def _brute_beam(model, variables, src, src_mask, w, alpha):
    beams = [([BOS_ID], 0.0, False)]
    for _ in range(MAXLEN):
        cands = []
        for toks, score, done in beams:
            if done:
                cands.append((toks + [PAD_ID], score, True))
                continue
            logp = _stepwise_logp(model, variables, src, src_mask, toks)
            for v in range(VOCAB):
                cands.append((toks + [v], score + float(logp[v]),
                              v == EOS_ID))
        cands.sort(key=lambda c: -c[1])
        beams = cands[:w]

    def norm_score(toks, score):
        length = sum(1 for t in toks[1:] if t != PAD_ID)
        return score / (((5.0 + length) / 6.0) ** alpha)

    best = max(beams, key=lambda b: norm_score(b[0], b[1]))
    return best[0][1:], best[1]


@pytest.fixture(scope="module")
def tiny_src():
    rng = np.random.RandomState(3)
    src = rng.randint(3, VOCAB, (2, 4)).astype(np.int32)
    mask = np.ones((2, 4), np.int32)
    return jnp.asarray(src), jnp.asarray(mask)


def test_greedy_matches_brute_force(tiny_nmt, tiny_src):
    model, variables = tiny_nmt
    src, mask = tiny_src
    got = np.asarray(greedy_decode(model, variables, src, mask, MAXLEN))
    for i in range(src.shape[0]):
        expect = _brute_greedy(model, variables, src[i:i + 1],
                               mask[i:i + 1])
        assert got[i].tolist() == expect, (i, got[i], expect)


@pytest.mark.parametrize("w", [2, 3])
def test_beam_matches_brute_force(tiny_nmt, tiny_src, w):
    model, variables = tiny_nmt
    src, mask = tiny_src
    toks, scores = beam_decode(model, variables, src, mask, MAXLEN,
                               beam_size=w, length_penalty=0.6)
    toks, scores = np.asarray(toks), np.asarray(scores)
    for i in range(src.shape[0]):
        e_toks, e_score = _brute_beam(model, variables, src[i:i + 1],
                                      mask[i:i + 1], w, 0.6)
        assert toks[i].tolist() == e_toks, (i, toks[i], e_toks)
        assert scores[i] == pytest.approx(e_score, abs=1e-4)


def test_cached_greedy_matches_recompute(tiny_nmt, tiny_src):
    """The KV-cached decode path must produce bit-identical token streams
    to the full-recompute path — same params, same inputs."""
    model, variables = tiny_nmt
    src, mask = tiny_src
    a = np.asarray(greedy_decode(model, variables, src, mask, MAXLEN))
    b = np.asarray(greedy_decode_cached(model, variables, src, mask,
                                        MAXLEN))
    np.testing.assert_array_equal(a, b)


@pytest.mark.parametrize("w", [2, 3])
def test_cached_beam_matches_recompute(tiny_nmt, tiny_src, w):
    """Beam + cache: the per-step cache reorder must track surviving beams
    exactly — any ancestry mix-up shows up as diverging tokens/scores."""
    model, variables = tiny_nmt
    src, mask = tiny_src
    t_a, s_a = beam_decode(model, variables, src, mask, MAXLEN,
                           beam_size=w, length_penalty=0.6)
    t_b, s_b = beam_decode_cached(model, variables, src, mask, MAXLEN,
                                  beam_size=w, length_penalty=0.6)
    np.testing.assert_array_equal(np.asarray(t_a), np.asarray(t_b))
    np.testing.assert_allclose(np.asarray(s_a), np.asarray(s_b),
                               rtol=1e-5, atol=1e-5)


def test_strip_special():
    assert strip_special([BOS_ID, 5, 6, EOS_ID, 7, PAD_ID]) == [5, 6]
    assert strip_special([5, PAD_ID, 6]) == [5, 6]
    assert strip_special([EOS_ID]) == []
