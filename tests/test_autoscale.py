"""fleet/autoscale.py tests: pool-signal folding, policy validation,
hysteresis streaks + cooldown, phase-aware pools, victim selection, the
zero-drop drain contract, decision determinism, and the supervised
spawner — all over scripted engines on an injected clock, no JAX and no
wall time anywhere.
"""

import sys

import pytest

from deeplearning_cfn_tpu.fleet import (
    AutoscalePolicy,
    Autoscaler,
    EngineReplica,
    ReplicaProcSpec,
    Router,
    SupervisedSpawner,
    pool_signals,
)
from deeplearning_cfn_tpu.obs.signals import SignalBus
from deeplearning_cfn_tpu.serve.queue import (
    OverloadError,
    Request,
    RequestState,
)


# -- fakes (scripted engine, same shape as tests/test_fleet.py) --------------


class _FakeQueue:
    def __init__(self, max_depth):
        self.max_depth = max_depth
        self.items = []

    @property
    def depth(self):
        return len(self.items)


class _FakeMetrics:
    def __init__(self):
        self.step_latency_s = []
        self.tokens_generated = 0
        self.last_retry_after_s = None


class FakeEngine:
    def __init__(self, capacity=2, queue_depth=8, work=1, phase="both"):
        self.capacity = capacity
        self.queue = _FakeQueue(queue_depth)
        self.metrics = _FakeMetrics()
        self.work = work
        self.phase = phase
        self.variables = {"params": "v0"}
        self._running = {}
        self._by_id = {}

    @property
    def active_requests(self):
        return len(self._running)

    def submit(self, src_ids, max_new_tokens=None, beam_size=1,
               deadline_s=None, request_id=None, trace_id=None):
        if self.queue.depth >= self.queue.max_depth:
            raise OverloadError(self.queue.depth, self.queue.max_depth)
        rid = request_id if request_id is not None \
            else f"fake-{len(self._by_id)}"
        req = Request(id=rid, src_ids=list(src_ids),
                      max_new_tokens=max_new_tokens or 4,
                      beam_size=beam_size, trace_id=trace_id)
        self.queue.items.append(req)
        self._by_id[rid] = req
        return req

    def poll(self, request_id):
        if request_id not in self._by_id:
            raise KeyError(request_id)
        return self._by_id[request_id]

    def cancel(self, request_id):
        req = self.poll(request_id)
        if req.finished:
            return False
        req.state = RequestState.CANCELLED
        if req in self.queue.items:
            self.queue.items.remove(req)
        self._running.pop(req.id, None)
        return True

    def step(self):
        while self.queue.items and len(self._running) < self.capacity:
            req = self.queue.items.pop(0)
            if req.finished:
                continue
            req.state = RequestState.RUNNING
            self._running[req.id] = self.work
        decoded = 0
        for rid in list(self._running):
            req = self._by_id[rid]
            self._running[rid] -= 1
            req.tokens.append(1)
            decoded += 1
            self.metrics.tokens_generated += 1
            if self._running[rid] <= 0:
                req.state = RequestState.DONE
                req.finished_at = 0.0
                del self._running[rid]
        return decoded


def _replica(rid, **kw):
    return EngineReplica(rid, FakeEngine(**kw))


class _Clock:
    def __init__(self):
        self.now = 0.0

    def read(self):
        return self.now


class _Spawner:
    """Callable spawner that also records retire() calls."""

    def __init__(self, **engine_kw):
        self.engine_kw = engine_kw
        self.spawned = []
        self.retired = []

    def spawn(self, phase, rid):
        self.spawned.append(rid)
        return _replica(rid, phase=phase, **self.engine_kw)

    def retire(self, rid):
        self.retired.append(rid)


def _feed(bus, router, depths):
    """Push one queue-depth observation per replica into the bus."""
    for rid, depth in depths.items():
        if rid in router.replica_ids():
            bus.observe(rid, {"serve_queue_depth": depth})


def _scaler(replicas=1, policy=None, **kw):
    reps = [_replica(f"replica-{i}", queue_depth=64)
            for i in range(replicas)]
    router = Router(reps, policy="round_robin")
    bus = SignalBus(names=[r.id for r in reps])
    clock = _Clock()
    spawner = _Spawner(queue_depth=64)
    scaler = Autoscaler(router, bus, spawner,
                        policy=policy or AutoscalePolicy(**kw),
                        clock=clock.read)
    return scaler, router, bus, clock, spawner


# -- pool signals ------------------------------------------------------------


def test_pool_signals_null_over_zero_and_extrema():
    bus = SignalBus(names=["a", "b", "c"])
    bus.observe("a", {"serve_queue_depth": 3,
                      "serve_latency_p95_s": 0.2,
                      "serve_spec_accept_rate": 0.9})
    bus.observe("b", {"serve_queue_depth": 1,
                      "serve_latency_p95_s": 0.7,
                      "serve_retry_after_hint_s": 0.4,
                      "serve_spec_accept_rate": 0.5})
    sig = pool_signals(bus, ["a", "b", "c"])
    assert sig["members_reporting"] == 3
    assert sig["queue_depth"] == 4               # sum
    assert sig["worst_latency_p95_s"] == 0.7     # max
    assert sig["retry_after_pressure_s"] == 0.4  # max of reporters
    assert sig["spec_accept_rate_min"] == 0.5    # min
    # A pool nobody reported into is all-None, never all-zero.
    empty = pool_signals(bus, ["nope"])
    assert empty["members_reporting"] == 0
    assert empty["queue_depth"] is None
    # Pool slicing: a's signals only.
    assert pool_signals(bus, ["a"])["queue_depth"] == 3


def test_policy_validation():
    with pytest.raises(ValueError):
        AutoscalePolicy(min_replicas=0)
    with pytest.raises(ValueError):
        AutoscalePolicy(min_replicas=3, max_replicas=2)
    with pytest.raises(ValueError):
        AutoscalePolicy(up_queue_depth=0.5, down_queue_depth=0.5)
    with pytest.raises(ValueError):
        AutoscalePolicy(up_stable_ticks=0)
    with pytest.raises(ValueError):
        AutoscalePolicy(cooldown_s=-1.0)
    with pytest.raises(ValueError):
        AutoscalePolicy(drain_grace_ticks=0)


# -- hysteresis / cooldown ---------------------------------------------------


def test_scale_up_needs_a_breach_streak():
    scaler, router, bus, clock, _ = _scaler(
        up_stable_ticks=2, cooldown_s=0.0)
    # One spiky tick does not scale...
    _feed(bus, router, {"replica-0": 10})
    assert scaler.tick() == []
    # ...a calm tick resets the streak...
    _feed(bus, router, {"replica-0": 0})
    assert scaler.tick() == []
    _feed(bus, router, {"replica-0": 10})
    assert scaler.tick() == []
    # ...two consecutive breaches fire exactly one scale-up.
    _feed(bus, router, {"replica-0": 10})
    evs = scaler.tick()
    assert [e["action"] for e in evs] == ["scale_up"]
    assert evs[0]["replica"] == "auto-both-0"
    assert "queue_depth" in evs[0]["reason"]
    assert "auto-both-0" in router.replica_ids()
    assert scaler.state() == "scaling-up"


def test_cooldown_blocks_consecutive_actions():
    scaler, router, bus, clock, _ = _scaler(
        up_stable_ticks=1, cooldown_s=5.0, max_replicas=4)
    _feed(bus, router, {"replica-0": 50})
    assert len(scaler.tick()) == 1
    # Still breaching, but inside the cooldown window: no action.
    for _ in range(3):
        clock.now += 1.0
        _feed(bus, router, {r: 50 for r in router.replica_ids()})
        assert scaler.tick() == []
    clock.now += 5.0             # past the cooldown -> next action fires
    _feed(bus, router, {r: 50 for r in router.replica_ids()})
    assert [e["action"] for e in scaler.tick()] == ["scale_up"]


def test_scale_up_respects_max_replicas():
    scaler, router, bus, clock, _ = _scaler(
        up_stable_ticks=1, cooldown_s=0.0, max_replicas=2)
    _feed(bus, router, {"replica-0": 50})
    assert len(scaler.tick()) == 1
    clock.now += 1.0
    _feed(bus, router, {r: 50 for r in router.replica_ids()})
    assert scaler.tick() == []          # at the ceiling
    assert len(router.replica_ids()) == 2


def test_drain_based_scale_down_zero_drop():
    scaler, router, bus, clock, spawner = _scaler(
        up_stable_ticks=1, down_stable_ticks=2, cooldown_s=0.0)
    _feed(bus, router, {"replica-0": 50})
    scaler.tick()
    assert "auto-both-0" in router.replica_ids()
    # Put live work on the spawned replica, then go calm: the drain
    # begins but removal waits for idleness.
    rid = router.submit([5, 4, 3], max_new_tokens=3)
    while router._requests[rid].replica_id != "auto-both-0":
        rid = router.submit([5, 4, 3], max_new_tokens=3)
    for _ in range(2):
        clock.now += 0.1
        _feed(bus, router, {r: 0 for r in router.replica_ids()})
        evs = scaler.tick()
    assert [e["action"] for e in evs] == ["drain_begin"]
    assert evs[0]["replica"] == "auto-both-0"
    assert scaler.state() == "draining"
    assert scaler.draining == ["auto-both-0"]
    # Busy victim: tick after tick, still a member.
    clock.now += 0.1
    assert scaler.tick() == []
    assert "auto-both-0" in router.replica_ids()
    # Let the work finish, then the drain completes as a removal.
    router.run_until_drained()
    clock.now += 0.1
    evs = scaler.tick()
    assert [e["action"] for e in evs] == ["scale_down"]
    assert evs[0]["drained"] is True
    assert "auto-both-0" not in router.replica_ids()
    assert spawner.retired == ["auto-both-0"]
    assert scaler.state() == "steady"
    # Zero-drop, and every submitted request completed whole.
    assert router.stats()["dropped_requests"] == 0
    assert router.result(rid)["state"] == "done"


def test_drain_grace_expiry_evacuates_not_drops():
    scaler, router, bus, clock, _ = _scaler(
        up_stable_ticks=1, down_stable_ticks=1, cooldown_s=0.0,
        drain_grace_ticks=2)
    _feed(bus, router, {"replica-0": 50})
    scaler.tick()
    # Pin unfinished work on the victim (never stepped to completion).
    rid = router.submit([5, 4, 3], max_new_tokens=50)
    while router._requests[rid].replica_id != "auto-both-0":
        rid = router.submit([5, 4, 3], max_new_tokens=50)
    clock.now += 1.0
    _feed(bus, router, {r: 0 for r in router.replica_ids()})
    evs = scaler.tick()
    assert [e["action"] for e in evs] == ["drain_begin"]
    # Grace of 2 ticks expires with the victim still busy: the work is
    # evacuated to survivors, the removal records drained=False.
    down = []
    for _ in range(3):
        clock.now += 0.1
        down.extend(e for e in scaler.tick()
                    if e["action"] == "scale_down")
    assert len(down) == 1
    assert down[0]["drained"] is False
    assert "evacuated" in down[0]["reason"]
    assert "auto-both-0" not in router.replica_ids()
    assert router.stats()["dropped_requests"] == 0
    # The evacuated request lives on and completes elsewhere.
    router.run_until_drained()
    assert router.result(rid)["state"] == "done"


def test_scale_down_respects_min_replicas():
    scaler, router, bus, clock, _ = _scaler(
        replicas=1, down_stable_ticks=1, cooldown_s=0.0)
    for _ in range(5):
        clock.now += 1.0
        _feed(bus, router, {"replica-0": 0})
        assert scaler.tick() == []      # already at min_replicas=1
    assert router.replica_ids() == ["replica-0"]


def test_victim_selection_prefers_newest_spawned():
    scaler, router, bus, clock, _ = _scaler(
        up_stable_ticks=1, down_stable_ticks=1, cooldown_s=0.0,
        max_replicas=3)
    for _ in range(2):
        clock.now += 1.0
        _feed(bus, router, {r: 50 for r in router.replica_ids()})
        scaler.tick()
    assert sorted(router.replica_ids()) == [
        "auto-both-0", "auto-both-1", "replica-0"]
    clock.now += 1.0
    _feed(bus, router, {r: 0 for r in router.replica_ids()})
    evs = scaler.tick()
    # LIFO: the NEWEST spawn drains first; the operator's seed replica
    # is never chosen while a spawned one remains.
    assert evs[0]["action"] == "drain_begin"
    assert evs[0]["replica"] == "auto-both-1"


# -- phase-aware pools -------------------------------------------------------


def test_pools_scale_independently_by_phase():
    reps = [EngineReplica("prefill-0",
                          FakeEngine(queue_depth=64, phase="prefill")),
            EngineReplica("decode-0",
                          FakeEngine(queue_depth=64, phase="decode"))]
    router = Router(reps, policy="round_robin")
    bus = SignalBus(names=[r.id for r in reps])
    clock = _Clock()
    spawner = _Spawner(queue_depth=64)
    scaler = Autoscaler(router, bus, spawner,
                        policy=AutoscalePolicy(up_stable_ticks=1,
                                               cooldown_s=0.0),
                        clock=clock.read)
    assert scaler.phases() == ["decode", "prefill"]
    # Pressure ONLY on the prefill pool.
    bus.observe("prefill-0", {"serve_queue_depth": 50})
    bus.observe("decode-0", {"serve_queue_depth": 0})
    evs = scaler.tick()
    assert [(e["action"], e["phase"]) for e in evs] == [
        ("scale_up", "prefill")]
    assert evs[0]["replica"] == "auto-prefill-0"
    assert router.replica("auto-prefill-0").phase == "prefill"
    assert scaler.pool_members("decode") == ["decode-0"]
    # Per-phase state: prefill scaling-up, decode steady.
    assert scaler.state("prefill") == "scaling-up"
    assert scaler.state("decode") == "steady"


# -- determinism -------------------------------------------------------------


def test_decision_sequence_is_deterministic():
    def _run():
        scaler, router, bus, clock, _ = _scaler(
            up_stable_ticks=2, down_stable_ticks=3, cooldown_s=0.5)
        script = [8, 8, 8, 8, 0, 0, 0, 0, 0, 0, 6, 6, 6]
        for depth in script:
            clock.now += 0.25
            _feed(bus, router, {r: depth for r in router.replica_ids()})
            scaler.tick()
            router.step()
        return scaler.events

    a, b = _run(), _run()
    assert a == b
    assert [e["action"] for e in a].count("scale_up") >= 1


# -- supervised spawner ------------------------------------------------------


def test_supervised_spawner_runs_one_supervisor_per_spawn(tmp_path):
    def spec_factory(phase, rid):
        return ReplicaProcSpec(
            replica_id=rid,
            argv=[sys.executable, "-c", "import time; time.sleep(60)"],
            run_dir=str(tmp_path / rid))

    spawner = SupervisedSpawner(spec_factory,
                                lambda phase, rid: _replica(
                                    rid, phase=phase))
    rep = spawner.spawn("both", "auto-both-0")
    assert rep.id == "auto-both-0"
    sup = spawner.supervisors["auto-both-0"]
    assert [row["replica"] for row in sup.status()] == ["auto-both-0"]
    # Retire terminates and forgets the supervisor; idempotent.
    spawner.retire("auto-both-0")
    assert spawner.supervisors == {}
    spawner.retire("auto-both-0")
    # close() retires whatever is left.
    spawner.spawn("both", "auto-both-1")
    spawner.close()
    assert spawner.supervisors == {}
