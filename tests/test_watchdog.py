"""Hang watchdog: the mechanism (timer/beat/stop semantics) and its
trainer wiring. The real on_hang action is os._exit(89) — tests inject a
recording action instead; the launcher-restart integration is covered by
the launch tests' death-watch path (any nonzero exit restarts the job).
"""

import time

import numpy as np
import pytest

from deeplearning_cfn_tpu.runtime.watchdog import HANG_EXIT_CODE, StepWatchdog


def _make(timeout_s, grace=0.0, poll=0.02):
    fired = []
    wd = StepWatchdog(timeout_s, first_beat_grace_s=grace,
                      on_hang=fired.append, poll_interval_s=poll)
    return wd, fired


def test_beats_keep_it_alive():
    wd, fired = _make(0.15)
    try:
        for _ in range(5):
            time.sleep(0.05)
            wd.beat()
        assert not fired
    finally:
        wd.stop()


def test_fires_on_stall():
    wd, fired = _make(0.1)
    try:
        deadline = time.time() + 5.0
        while not fired and time.time() < deadline:
            time.sleep(0.02)
        assert fired, "watchdog never fired on a stalled loop"
        assert fired[0] >= 0.1  # reported stall covers at least the limit
    finally:
        wd.stop()


def test_stop_prevents_firing():
    wd, fired = _make(0.1)
    wd.stop()
    time.sleep(0.3)
    assert not fired


def test_first_beat_grace_extends_initial_deadline():
    # grace 0.3 + timeout 0.1: must NOT fire in the first ~0.25s even
    # without any beat (compile headroom), then fire once it lapses.
    wd, fired = _make(0.1, grace=0.3)
    try:
        time.sleep(0.2)
        assert not fired
        deadline = time.time() + 5.0
        while not fired and time.time() < deadline:
            time.sleep(0.02)
        assert fired
    finally:
        wd.stop()


def test_rejects_nonpositive_timeout():
    with pytest.raises(ValueError):
        StepWatchdog(0.0)


def test_exit_code_is_distinctive():
    assert HANG_EXIT_CODE == 89


def test_trainer_wires_watchdog(tmp_workdir, devices, monkeypatch):
    """fit() with train.hang_timeout_s: the watchdog is created, beaten at
    sync points (run survives, no fire), and stopped at loop end."""
    import deeplearning_cfn_tpu.runtime.watchdog as wd_mod

    created = []
    real = wd_mod.StepWatchdog

    class Recording(real):
        def __init__(self, *a, **kw):
            kw["on_hang"] = lambda s: created.append(("FIRED", s))
            super().__init__(*a, **kw)
            created.append(self)

    monkeypatch.setattr(wd_mod, "StepWatchdog", Recording)

    from deeplearning_cfn_tpu.config import apply_overrides
    from deeplearning_cfn_tpu.presets import get_preset
    from deeplearning_cfn_tpu.train.run import run_experiment

    cfg = get_preset("cifar10_resnet20")
    apply_overrides(cfg, [
        f"workdir={tmp_workdir}", "train.global_batch=32",
        "train.steps=6", "train.log_every_steps=2",
        "train.hang_timeout_s=600", "data.num_train_examples=64",
        "data.num_eval_examples=32", "train.eval_batch=32",
        "schedule.name=constant", "schedule.warmup_epochs=0",
        "checkpoint.async_write=false",
    ])
    metrics = run_experiment(cfg)
    assert np.isfinite(metrics["loss"])
    instances = [c for c in created if isinstance(c, Recording)]
    fires = [c for c in created if isinstance(c, tuple)]
    assert len(instances) == 1
    assert not fires  # beats kept it alive through the whole run
    assert instances[0]._stopped.is_set()  # stopped when fit returned
