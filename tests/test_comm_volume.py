"""Comm-volume analysis (parallel/comm_volume.py): HLO parsing and the
structural contract of the sequence-parallel strategies (r03 verdict,
Next #9 — the table a pod profile is checked against)."""

import pytest

from deeplearning_cfn_tpu.config import MeshConfig
from deeplearning_cfn_tpu.parallel.comm_volume import (
    comm_volume,
    compile_detection_step,
    compile_train_step,
)


def test_comm_volume_parses_hlo_text():
    """Parser unit contract: plain ops, async -start/-done pairs (payload
    counted once), and the all-reduce combiner's tuple-with-index-comments
    line (the r04 parser bug: '/*index=N*/' contains '=')."""
    hlo = """
HloModule m
  %x = bf16[2,4]{1,0} parameter(0)
  %p = bf16[2,4]{1,0} collective-permute(%x), channel_id=1
  %ag-start = (f32[8]{0}, f32[16]{0}) all-gather-start(%x), dim=0
  %ag-done = f32[16]{0} all-gather-done(%ag-start)
  %big = (f32[32]{0}, f32[32,32]{1,0}, /*index=2*/f32[4]{0}) all-reduce(%a, %b, %c), channel_id=2
  %gte = f32[32]{0} get-tuple-element(%big), index=0
  %a2a = f32[16]{0} all-to-all(%x), dim=0
  %cps = (u32[2,4]{1,0}, u32[2,4]{1,0}, u32[], u32[]) collective-permute-start(%i), channel_id=3
"""
    vol = comm_volume(hlo)
    # Sync permute + the async -start form (whose (in, out, ctx, ctx)
    # tuple must count the output once, not in+out+ctx).
    assert vol["collective-permute"] == {"count": 2,
                                         "bytes": 2 * 4 * 2 + 2 * 4 * 4}
    # Async all-gather-start: (input alias f32[8], output f32[16]) — the
    # payload is the 64-byte output, not the 96-byte tuple.
    assert vol["all-gather"] == {"count": 1, "bytes": 64}
    # Sync combiner tuple: every member IS output — summed.
    assert vol["all-reduce"] == {"count": 1,
                                 "bytes": 4 * (32 + 32 * 32 + 4)}
    assert vol["all-to-all"] == {"count": 1, "bytes": 64}
    assert vol["total"]["count"] == 5


def test_comm_volume_rejects_unknown_dtype():
    with pytest.raises(ValueError, match="unknown dtype"):
        comm_volume("  %q = f8e4m3fn[8]{0} all-reduce(%x)\n")


@pytest.mark.skipif(
    tuple(map(int, __import__("jax").__version__.split(".")[:2])) < (0, 5),
    reason="jaxlib 0.4.x XLA SPMD partitioner lowers the ring strategy's "
           "shard_map ppermute with extra all-to-all ops (observed: 7 where "
           "the contract demands 0), so the signature assertions cannot hold "
           "on this toolchain. Environmental — see PARITY.md (tier-1 triage).")
def test_seq_parallel_comm_structure(devices):
    """The strategies' collective SIGNATURES: ring moves K/V by ppermute
    (no all-to-all), Ulysses by all-to-all (no ppermute), byte-identical
    at equal shapes; pure DP has only the grad all-reduce. Compiled from
    the real train step on the fake-device mesh."""
    ring = comm_volume(compile_train_step(
        "bert_long", MeshConfig(data=2, seq=4), seq_impl="ring"))
    uly = comm_volume(compile_train_step(
        "bert_long", MeshConfig(data=2, seq=4), seq_impl="ulysses"))
    dp = comm_volume(compile_train_step(
        "bert_long", MeshConfig(data=8), seq_impl="ring"))

    assert ring["collective-permute"]["count"] > 0
    assert ring["all-to-all"]["count"] == 0
    assert uly["all-to-all"]["count"] > 0
    assert uly["collective-permute"]["count"] == 0
    # The textbook trade: same bytes moved, different op kind (ring rides
    # neighbor links, Ulysses needs full bisection).
    assert ring["collective-permute"]["bytes"] == uly["all-to-all"]["bytes"]
    # Pure DP: grad all-reduce only — no seq-axis movement of any kind.
    assert dp["collective-permute"]["count"] == 0
    assert dp["all-to-all"]["count"] == 0
    assert dp["all-gather"]["count"] == 0
    assert dp["all-reduce"]["count"] >= 1
    # Grad all-reduce bytes must cover the full param tuple (not just the
    # loss scalar — the r04 parser bug made it 4 bytes).
    assert dp["all-reduce"]["bytes"] > 50_000


def test_spatial_shard_halo_structure(devices):
    """The data+spatial detection step (SURVEY §3.2's one beyond-DP
    requirement) must move conv halos over 'spatial' — visible as
    collective-permute/all-gather traffic that the pure-DP compile of the
    same model does not have."""
    sp = comm_volume(compile_detection_step(MeshConfig(data=4, spatial=2)))
    dp = comm_volume(compile_detection_step(MeshConfig(data=8)))
    sp_moves = sp["collective-permute"]["count"] + sp["all-gather"]["count"]
    dp_moves = dp["collective-permute"]["count"] + dp["all-gather"]["count"]
    assert sp_moves > dp_moves, (sp, dp)
    assert sp["total"]["bytes"] > dp["total"]["bytes"], (sp, dp)
