"""The README's five-minute demo must actually run — docs are the product
surface (the reference's README WAS its API; SURVEY.md §3.1), so the demo
commands are executed verbatim from the file. If someone edits the README
without updating the CLI (or vice versa), this fails.
"""

import os
import re
import shlex

import pytest

from deeplearning_cfn_tpu.cli.main import main

README = os.path.join(os.path.dirname(__file__), "..", "README.md")


def _bash_blocks():
    text = open(README).read()
    return re.findall(r"```bash\n(.*?)```", text, re.DOTALL)


def _commands(block):
    """Join continuation lines, drop comments, keep dlcfn-tpu commands."""
    joined = block.replace("\\\n", " ")
    cmds = []
    for line in joined.splitlines():
        line = line.strip()
        if line.startswith("dlcfn-tpu "):
            cmds.append(shlex.split(line.split("#")[0])[1:])
    return cmds


def test_readme_five_minute_demo(tmp_path, capsys, monkeypatch):
    blocks = _bash_blocks()
    assert blocks, "README lost its bash blocks"
    demo_cmds = [c for b in blocks[:3] for c in _commands(b)]
    # Expect at least: doctor, first train, resume train, ckpt list/rollback.
    assert any(c[0] == "doctor" for c in demo_cmds), demo_cmds
    trains = [c for c in demo_cmds if c[0] == "train"]
    assert len(trains) >= 2, "README demo should train then resume"

    # Shrink the documented step counts but KEEP them distinct (30→4,
    # 60→8): the resume leg must really train 4 more steps (not restore
    # and no-op), and the two committed checkpoints {4, 8} give the
    # rollback command something real to delete.
    step_map = {}

    def relocate(cmd):
        # Point the documented /tmp/demo paths into the test's tmp dir and
        # shrink the step counts (the commands stay otherwise verbatim).
        out = []
        for a in cmd:
            a = a.replace("/tmp/demo", str(tmp_path))

            def shrink(m):
                orig = int(m.group(0).split("=")[1])
                step_map.setdefault(orig, 4 * (len(step_map) + 1))
                return f"train.steps={step_map[orig]}"

            a = re.sub(r"train\.steps=\d+", shrink, a)
            out.append(a)
        return out

    ran = 0
    for cmd in demo_cmds:
        if cmd[0] == "doctor":
            assert main(["doctor", "--skip-backend"]) == 0
            ran += 1
        elif cmd[0] == "train":
            assert main(relocate(cmd)) == 0, cmd
            ran += 1
        elif cmd[0] == "ckpt":
            args = relocate(cmd)
            if args[1] == "rollback" and "--step" in args:
                # The documented rollback step may exceed the shrunk runs'
                # steps; roll back to the earliest committed step instead
                # (authoritative list, not a dir glob — COMMIT markers
                # define "committed").
                from deeplearning_cfn_tpu.ckpt import committed_steps

                steps = committed_steps(args[2])
                assert len(steps) >= 2, \
                    f"demo should have left >=2 checkpoints, got {steps}"
                args[args.index("--step") + 1] = str(steps[0])
            assert main(args) == 0, args
            ran += 1
    assert ran >= 4, f"only ran {ran} demo commands: {demo_cmds}"
    out = capsys.readouterr().out
    assert "resumed from step" in out, \
        "the README's resume claim did not reproduce"
    # The resume leg genuinely trained past the first run's endpoint.
    assert re.search(r'"step": 8', out) or "step': 8" in out, \
        "resume leg did not reach step 8"
