"""Input pipelines.

The reference fed GPUs from EFS-mounted datasets via each framework's loader
(MXNet ImageRecordIter, TF tf.data, TensorPack dataflow — SURVEY.md §3.1).
The rebuild's contract: a pipeline yields per-process numpy batches
``{"image"/..., "label"/...}`` of the *local* batch size; the Trainer stitches
them into globally-sharded arrays. In no-network environments every dataset
has a deterministic synthetic fallback so all five configs smoke-test
anywhere; real data paths read standard binary formats via the native C++
loader (:mod:`deeplearning_cfn_tpu.data.native`) when built.
"""

from .pipeline import build_pipeline, DataPipeline  # noqa: F401
