"""Dataset pipelines with deterministic synthetic fallbacks.

Design: datasets are in-memory or file-backed numpy sources; a
:class:`DataPipeline` handles per-process sharding (each host reads only its
slice — the reference's "each rank reads its own shard" contract), shuffling,
augmentation, batching, and background prefetch. Heavy decode paths go
through the native C++ loader when available.
"""

from __future__ import annotations

import os
import pickle
import queue
import threading
from typing import Any, Callable, Dict, Iterator, Optional

import jax
import numpy as np

from ..config import DataConfig

Batch = Dict[str, np.ndarray]


# ---------------------------------------------------------------------------
# Sources
# ---------------------------------------------------------------------------


class ArraySource:
    """An in-memory (features, labels) source."""

    def __init__(self, arrays: Dict[str, np.ndarray]):
        sizes = {k: len(v) for k, v in arrays.items()}
        if len(set(sizes.values())) != 1:
            raise ValueError(f"ragged source: {sizes}")
        self.arrays = arrays
        self.size = next(iter(sizes.values()))

    def gather(self, idx: np.ndarray) -> Batch:
        return {k: v[idx] for k, v in self.arrays.items()}


def synthetic_image_source(
    num_examples: int, image_size: int, num_classes: int, seed: int,
    channels: int = 3,
) -> ArraySource:
    """Learnable synthetic image data: each class has a fixed random mean
    image; examples are mean + noise. A ResNet reaches high accuracy on this
    in a few steps, which is what convergence smoke tests need (the
    reference's CIFAR smoke role, network-free)."""
    rng = np.random.RandomState(seed)
    means = rng.normal(0.0, 1.0, (num_classes, 8, 8, channels)).astype(np.float32)
    labels = rng.randint(0, num_classes, num_examples).astype(np.int32)
    noise = rng.normal(0.0, 0.25, (num_examples, image_size, image_size,
                                   channels)).astype(np.float32)
    # Upsample the 8x8 class mean to the image size (nearest) — keeps memory
    # small for ImageNet-sized synthetic data.
    reps = image_size // 8
    mean_imgs = np.repeat(np.repeat(means, reps, axis=1), reps, axis=2)
    images = mean_imgs[labels] + noise
    return ArraySource({"image": images, "label": labels})


def load_cifar10(data_dir: str, train: bool) -> ArraySource:
    """Read the standard ``cifar-10-batches-py`` pickled format."""
    names = [f"data_batch_{i}" for i in range(1, 6)] if train else ["test_batch"]
    xs, ys = [], []
    for name in names:
        with open(os.path.join(data_dir, name), "rb") as fh:
            d = pickle.load(fh, encoding="bytes")
        xs.append(d[b"data"])
        ys.append(np.asarray(d[b"labels"], np.int32))
    x = np.concatenate(xs).reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
    mean = np.array([0.4914, 0.4822, 0.4465], np.float32) * 255
    std = np.array([0.2470, 0.2435, 0.2616], np.float32) * 255
    x = (x.astype(np.float32) - mean) / std
    return ArraySource({"image": x, "label": np.concatenate(ys)})


# ---------------------------------------------------------------------------
# Augmentation (numpy, vectorized — host-side, overlapped via prefetch)
# ---------------------------------------------------------------------------


def augment_crop_flip(batch: Batch, rng: np.random.RandomState,
                      pad: int = 4) -> Batch:
    """Random crop (with padding) + horizontal flip — the standard CIFAR
    augmentation the reference's MXNet script applied on-the-fly."""
    x = batch["image"]
    n, h, w, c = x.shape
    padded = np.pad(x, ((0, 0), (pad, pad), (pad, pad), (0, 0)), mode="reflect")
    out = np.empty_like(x)
    ys = rng.randint(0, 2 * pad + 1, n)
    xs = rng.randint(0, 2 * pad + 1, n)
    flips = rng.rand(n) < 0.5
    for i in range(n):
        img = padded[i, ys[i]:ys[i] + h, xs[i]:xs[i] + w]
        out[i] = img[:, ::-1] if flips[i] else img
    return {**batch, "image": out}


# ---------------------------------------------------------------------------
# Pipeline
# ---------------------------------------------------------------------------


class DataPipeline:
    """Shards, shuffles, batches, augments, prefetches.

    ``local_batch`` is the per-process batch; indices are sharded by
    ``process_index/process_count`` with a per-epoch shuffle from a shared
    seed, so across processes every example appears exactly once per epoch
    (the hostfile-era equivalent was MXNet's per-worker record partitioning).
    """

    def __init__(
        self,
        source: ArraySource,
        local_batch: int,
        seed: int = 0,
        shuffle: bool = True,
        augment: Optional[Callable[[Batch, np.random.RandomState], Batch]] = None,
        drop_remainder: bool = True,
        prefetch: int = 2,
        process_index: Optional[int] = None,
        process_count: Optional[int] = None,
        native: bool = True,
        num_workers: int = 4,
    ):
        self.source = source
        self.local_batch = local_batch
        self.seed = seed
        self.shuffle = shuffle
        self.augment = augment
        self.prefetch = prefetch
        self.pidx = jax.process_index() if process_index is None else process_index
        self.pcount = jax.process_count() if process_count is None else process_count
        self.num_workers = max(1, num_workers)
        # Sources exposing gather_seeded (ImageNet shards) do their own
        # augmentation/decode — the pipeline just hands them a seed.
        self._seeded = hasattr(source, "gather_seeded") and augment is None
        # Native path handles the plain and crop/flip cases; anything else
        # (custom augment fns, sources overriding gather) stays in Python.
        self._native = False
        if not self._seeded and native \
                and (augment is None or augment is augment_crop_flip) \
                and isinstance(source, ArraySource) \
                and type(source).gather is ArraySource.gather:
            from .. import dataio

            self._native = dataio.available()
        # Static shapes always hold; drop_remainder=False keeps the tail by
        # PADDING the final batch (repeated indices) and attaching an
        # "eval_mask" key (1=real, 0=pad) every batch — exact-set evaluation
        # ("75.9% top-1" means exactly 50 000 images, not 49 920).
        self.drop_remainder = drop_remainder

    @property
    def _per_proc(self) -> int:
        if self.drop_remainder:
            return self.source.size // self.pcount
        return -(-self.source.size // self.pcount)  # ceil

    @property
    def steps_per_epoch(self) -> int:
        if self.drop_remainder:
            return self._per_proc // self.local_batch
        return -(-self._per_proc // self.local_batch)  # ceil

    def _epoch_indices(self, epoch: int) -> np.ndarray:
        idx = np.arange(self.source.size)
        if self.shuffle:
            np.random.RandomState(self.seed + epoch).shuffle(idx)
        per_proc = self._per_proc
        return idx[self.pidx * per_proc:(self.pidx + 1) * per_proc]

    def _gather_native(self, idx: np.ndarray, epoch: int, start: int
                       ) -> Batch:
        """GIL-free threaded gather (+ crop/flip) through dataio. The seed
        mixes (pipeline seed, epoch, batch offset, process) so augmentation
        is deterministic regardless of thread scheduling."""
        from .. import dataio

        seed = ((self.seed + 1) * 7919 + epoch * 2654435761 + start * 31 +
                self.pidx) & (2**64 - 1)
        out: Batch = {}
        for k, v in self.source.arrays.items():
            if (k == "image" and v.ndim == 4 and v.dtype == np.float32):
                out[k] = dataio.gather_augment(
                    v, idx, pad=4, seed=seed,
                    augment=self.augment is augment_crop_flip,
                    nthreads=self.num_workers)
            else:
                out[k] = dataio.gather_rows(v, idx,
                                            nthreads=self.num_workers)
        return out

    def _epoch_batches(self, epoch: int, start_batch: int = 0
                       ) -> Iterator[Batch]:
        rng = np.random.RandomState(
            (self.seed + 1) * 7919 + epoch * 31 + self.pidx
        )
        idx = self._epoch_indices(epoch)
        for start in range(start_batch * self.local_batch,
                           self.steps_per_epoch * self.local_batch,
                           self.local_batch):
            batch_idx = idx[start:start + self.local_batch]
            eval_mask = None
            if not self.drop_remainder:
                real = len(batch_idx)
                eval_mask = np.zeros(self.local_batch, np.float32)
                eval_mask[:real] = 1.0
                if real < self.local_batch:
                    # Pad with wrapped indices — shapes stay static, the
                    # mask zeroes their metric contribution.
                    pad = np.resize(idx[:max(real, 1)],
                                    self.local_batch - real)
                    batch_idx = np.concatenate([batch_idx, pad])
            if self._seeded:
                # Seeded-gather sources (ImageNet shards) own their
                # augmentation; the (seed, epoch, offset, process) mix makes
                # it deterministic and resume-stable.
                seed = ((self.seed + 1) * 7919 + epoch * 2654435761 +
                        start * 31 + self.pidx) & (2**64 - 1)
                batch = self.source.gather_seeded(
                    np.asarray(batch_idx, np.int64), seed)
            elif self._native:
                batch = self._gather_native(np.asarray(batch_idx, np.int32),
                                            epoch, start)
            else:
                batch = self.source.gather(batch_idx)
                if self.augment is not None:
                    batch = self.augment(batch, rng)
            if eval_mask is not None:
                batch = {**batch, "eval_mask": eval_mask}
            yield batch

    def epochs(self, start_epoch: int = 0, skip_batches: int = 0
               ) -> Iterator[Batch]:
        """Infinite stream across epochs, optionally prefetched on a thread.

        ``skip_batches`` fast-forwards within the first epoch (mid-epoch
        checkpoint resume: the stream must continue where training stopped,
        not replay the epoch head)."""
        def gen():
            epoch = start_epoch
            skip = skip_batches
            while True:
                yield from self._epoch_batches(epoch, start_batch=skip)
                skip = 0
                epoch += 1

        if self.prefetch > 0:
            return _thread_prefetch(gen(), self.prefetch)
        return gen()

    def one_epoch(self, epoch: int = 0) -> Iterator[Batch]:
        return self._epoch_batches(epoch)


def _thread_prefetch(it: Iterator[Batch], depth: int) -> Iterator[Batch]:
    """Background-thread prefetch with a shutdown path: closing (or
    abandoning + GC'ing) the returned generator stops the worker and drains
    the queue, so no thread is left blocked on a full queue pinning
    ``depth + 1`` materialized batches for the rest of the process."""
    q: "queue.Queue" = queue.Queue(maxsize=depth)
    _SENTINEL = object()
    stop = threading.Event()

    def put(item) -> bool:
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def worker():
        try:
            for item in it:
                if not put(item):
                    return
            put(_SENTINEL)
        except BaseException as e:  # propagate loader crashes to consumer
            put(("__prefetch_error__", e))

    t = threading.Thread(target=worker, daemon=True)
    t.start()
    try:
        while True:
            item = q.get()
            if item is _SENTINEL:
                return
            if isinstance(item, tuple) and len(item) == 2 and \
                    item[0] == "__prefetch_error__":
                raise RuntimeError("data pipeline worker crashed") \
                    from item[1]
            yield item
    finally:
        stop.set()
        try:
            while True:
                q.get_nowait()
        except queue.Empty:
            pass


class DevicePrefetcher:
    """Double-buffered host→device staging on a background thread.

    Layered on :func:`_thread_prefetch`'s host-side pipeline: ``transform``
    (typically ``Trainer.device_batch``) runs on the worker thread, so the
    host→device transfer of batch N+1 overlaps the device compute of batch
    N and the consuming step loop never blocks on ``device_put``. ``depth``
    bounds how many device-resident batches are staged ahead (2 = classic
    double buffering; deeper pins more HBM for no extra overlap).

    ``close()`` (also the iterator-abandon path via ``__del__``) stops the
    worker even when it is blocked on a full queue, joins it, then closes
    the wrapped iterator — no thread is left pinning staged batches for the
    rest of the process.
    """

    def __init__(self, it: Iterator[Batch], transform: Callable[[Batch], Any],
                 depth: int = 2):
        self._it = it
        self._transform = transform
        self._q: "queue.Queue" = queue.Queue(maxsize=max(1, depth))
        self._stop = threading.Event()
        self._sentinel = object()
        self._thread = threading.Thread(target=self._work, daemon=True)
        self._thread.start()

    def _put(self, item) -> bool:
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def _work(self):
        try:
            for item in self._it:
                if self._stop.is_set():
                    return
                if not self._put(self._transform(item)):
                    return
            self._put(self._sentinel)
        except BaseException as e:  # propagate staging crashes to consumer
            self._put(("__prefetch_error__", e))

    def __iter__(self):
        return self

    def __next__(self):
        item = self._q.get()
        if item is self._sentinel:
            raise StopIteration
        if isinstance(item, tuple) and len(item) == 2 and \
                item[0] == "__prefetch_error__":
            raise RuntimeError("device prefetch worker crashed") from item[1]
        return item

    def close(self):
        self._stop.set()
        # Unblock a worker stuck in put(); it re-checks the event and exits.
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass
        # Join BEFORE closing the wrapped iterator: generator.close() on a
        # generator mid-next() in another thread raises ValueError.
        self._thread.join(timeout=10.0)
        close = getattr(self._it, "close", None)
        if close is not None:
            try:
                close()
            except ValueError:  # worker outlived the join timeout
                pass

    def __del__(self):
        self._stop.set()


# ---------------------------------------------------------------------------
# Factory
# ---------------------------------------------------------------------------


def build_pipeline(
    cfg: DataConfig, local_batch: int, num_classes: int, seed: int = 0,
    train: bool = True, drop_remainder: bool = True,
) -> DataPipeline:
    name = cfg.name
    want_real = bool(cfg.data_dir) and not cfg.synthetic

    if name == "cifar10":
        if want_real and os.path.isdir(cfg.data_dir):
            source = load_cifar10(cfg.data_dir, train)
        else:
            n = cfg.num_train_examples or (50_000 if train else 10_000)
            if not train and cfg.num_eval_examples:
                n = cfg.num_eval_examples
            source = synthetic_image_source(n, cfg.image_size, num_classes,
                                            seed=17 if train else 23)
        return DataPipeline(
            source, local_batch, seed=seed, shuffle=train,
            augment=augment_crop_flip if train else None,
            prefetch=cfg.prefetch, native=cfg.use_native_loader,
            num_workers=cfg.num_workers, drop_remainder=drop_remainder,
        )

    if name == "imagenet":
        if want_real and os.path.isdir(cfg.data_dir):
            from .imagenet import load_imagenet_source

            source = load_imagenet_source(cfg, train)
        else:
            n = cfg.num_train_examples or (8192 if train else 1024)
            if not train and cfg.num_eval_examples:
                n = cfg.num_eval_examples
            source = synthetic_image_source(n, cfg.image_size, num_classes,
                                            seed=29 if train else 31)
        return DataPipeline(
            source, local_batch, seed=seed, shuffle=train,
            augment=None, prefetch=cfg.prefetch,
            native=cfg.use_native_loader, num_workers=cfg.num_workers,
            drop_remainder=drop_remainder,
        )

    if name in ("wikipedia_mlm", "wmt_en_de", "lm_text", "coco"):
        from .text import build_text_source
        from .detection import build_detection_source

        if name == "coco":
            source = build_detection_source(cfg, train,
                                            num_classes=num_classes,
                                            max_boxes=cfg.max_boxes)
        else:
            source = build_text_source(cfg, train)
        return DataPipeline(source, local_batch, seed=seed, shuffle=train,
                            prefetch=cfg.prefetch,
                            native=cfg.use_native_loader,
                            num_workers=cfg.num_workers,
                            drop_remainder=drop_remainder)

    raise KeyError(f"unknown dataset {name!r}")
