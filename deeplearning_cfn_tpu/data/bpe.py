"""Offline byte-level BPE: train, encode, decode, vocab files.

The reference's text workloads owned their vocab/tokenization step — BERT's
create_pretraining_data.py assumed a WordPiece vocab, Sockeye's
prepare-data ran (shared-vocab) BPE over the WMT bitext. This module is
that step for the rebuild, fully offline (no vocab download): a
deterministic byte-level BPE trained from the corpus itself.

Design:

- **Byte-level base**: the initial alphabet is the 256 byte values, so any
  input encodes with zero OOV and the trained vocab is language-agnostic
  (the WMT En-De pair shares one vocab, Sockeye-style).
- **Whitespace pre-tokenization with a space end-of-word marker**: the
  corpus is split on whitespace and each word is encoded as its bytes plus
  one trailing space byte. Merges never cross word boundaries (the classic
  BPE constraint that keeps the merge table small and meaningful).
  Decoding concatenates token bytes — whitespace runs are normalized to
  single spaces, the standard lossy-but-reversible-enough contract for
  MT/MLM corpora.
- **Deterministic training**: ties in pair frequency break on the pair's
  byte strings (lexicographic), so the same corpus + vocab size always
  yields the same merge table on any platform.
- **Reserved specials first**: ids [0, reserved) are the task's special
  tokens ([PAD]/[CLS]/[SEP]/[MASK] for MLM, [PAD]/[BOS]/[EOS] for NMT);
  ids [reserved, reserved+256) are the raw bytes; merge products follow.

Vocab file: JSON {"reserved": [names...], "merges": [[hexA, hexB], ...]}.
"""

from __future__ import annotations

import json
from collections import Counter
from typing import Dict, Iterable, List, Sequence, Tuple

MLM_SPECIALS = ("[PAD]", "[CLS]", "[SEP]", "[MASK]")
NMT_SPECIALS = ("[PAD]", "[BOS]", "[EOS]")


def _words(lines: Iterable[str]) -> Counter:
    """Corpus → {word bytes (incl. trailing space): count}."""
    counts: Counter = Counter()
    for line in lines:
        for w in line.split():
            counts[w.encode("utf-8") + b" "] += 1
    return counts


class Bpe:
    """A trained BPE: merge table + id mapping, encode/decode."""

    def __init__(self, merges: Sequence[Tuple[bytes, bytes]],
                 specials: Sequence[str]):
        self.specials = tuple(specials)
        self.merges = [tuple(m) for m in merges]
        self.rank = {m: i for i, m in enumerate(self.merges)}
        r = len(self.specials)
        # id table: specials, the 256 bytes, then merge products in order.
        self.id_of: Dict[bytes, int] = {
            bytes([b]): r + b for b in range(256)}
        for i, (a, b) in enumerate(self.merges):
            self.id_of[a + b] = r + 256 + i
        self.bytes_of: Dict[int, bytes] = {
            v: k for k, v in self.id_of.items()}
        self._cache: Dict[bytes, List[int]] = {}

    @property
    def vocab_size(self) -> int:
        return len(self.specials) + 256 + len(self.merges)

    # -- encode/decode ------------------------------------------------------

    def _encode_word(self, word: bytes) -> List[int]:
        cached = self._cache.get(word)
        if cached is not None:
            return cached
        syms = [bytes([b]) for b in word]
        # Classic BPE encode: repeatedly apply the lowest-rank adjacent
        # merge until none applies.
        while len(syms) > 1:
            best_i, best_r = -1, len(self.rank)
            for i in range(len(syms) - 1):
                r = self.rank.get((syms[i], syms[i + 1]), best_r)
                if r < best_r:
                    best_i, best_r = i, r
            if best_i < 0:
                break
            syms[best_i:best_i + 2] = [syms[best_i] + syms[best_i + 1]]
        ids = [self.id_of[s] for s in syms]
        if len(self._cache) < 1_000_000:
            self._cache[word] = ids
        return ids

    def encode(self, text: str) -> List[int]:
        """Text → token ids (no specials added — callers own framing)."""
        out: List[int] = []
        for w in text.split():
            out.extend(self._encode_word(w.encode("utf-8") + b" "))
        return out

    def decode(self, ids: Iterable[int]) -> str:
        """Token ids → text. Special ids render as their bracketed names;
        unknown ids are skipped. Trailing word-space is stripped."""
        parts: List[bytes] = []
        for i in ids:
            i = int(i)
            if 0 <= i < len(self.specials):
                parts.append(b" " + self.specials[i].encode() + b" ")
            elif i in self.bytes_of:
                parts.append(self.bytes_of[i])
        return b"".join(parts).decode("utf-8", "replace").strip()

    # -- persistence --------------------------------------------------------

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump({
                "reserved": list(self.specials),
                "merges": [[a.hex(), b.hex()] for a, b in self.merges],
            }, f)

    @classmethod
    def load(cls, path: str) -> "Bpe":
        with open(path) as f:
            d = json.load(f)
        merges = [(bytes.fromhex(a), bytes.fromhex(b))
                  for a, b in d["merges"]]
        return cls(merges, d["reserved"])


def train_bpe(lines: Iterable[str], vocab_size: int,
              specials: Sequence[str] = MLM_SPECIALS) -> Bpe:
    """Train a byte-level BPE to ``vocab_size`` total ids (specials + 256
    bytes + merges). Deterministic: most-frequent pair first, frequency
    ties broken lexicographically on the pair's bytes.

    Incremental: pair counts are maintained exactly across merges and each
    merge rescans only the unique words indexed as containing the merged
    pair — O(corpus + merges·affected), not O(merges·corpus), which is the
    difference between minutes and days at the default vocab 8192 on a
    real Wikipedia-scale corpus.
    """
    n_merges = vocab_size - len(specials) - 256
    if n_merges < 0:
        raise ValueError(
            f"vocab_size={vocab_size} smaller than the "
            f"{len(specials)}+256 reserved+byte base")
    word_counts = _words(lines)
    # Working state: per unique word, its current symbol list + count.
    words: List[Tuple[List[bytes], int]] = [
        ([bytes([b]) for b in w], c) for w, c in word_counts.items()]

    pair_counts: Counter = Counter()
    # pair → indices of words that contained it when last scanned. Entries
    # go stale when later merges rewrite a word; stale indices are handled
    # at use (re-scan finds no occurrence → net-zero update).
    pair_words: Dict[Tuple[bytes, bytes], set] = {}
    for wi, (syms, c) in enumerate(words):
        for i in range(len(syms) - 1):
            p = (syms[i], syms[i + 1])
            pair_counts[p] += c
            pair_words.setdefault(p, set()).add(wi)

    merges: List[Tuple[bytes, bytes]] = []
    for _ in range(n_merges):
        if not pair_counts:
            break
        best = min(pair_counts.items(), key=lambda kv: (-kv[1], kv[0]))[0]
        if pair_counts[best] < 2:
            break  # nothing left worth merging
        merges.append(best)
        a, b = best
        ab = a + b
        # sorted() for determinism: the rewrite order doesn't affect counts
        # (each word's contribution is removed then re-added atomically),
        # but iterating a set would make any future tie-sensitive change
        # platform-dependent.
        for wi in sorted(pair_words.pop(best, ())):
            syms, c = words[wi]
            if len(syms) < 2:
                continue
            # Remove this word's contribution entirely, rewrite, re-add —
            # exact counts even for overlapping repeats (e.g. b"aaa").
            for i in range(len(syms) - 1):
                p = (syms[i], syms[i + 1])
                pair_counts[p] -= c
                if pair_counts[p] <= 0:
                    del pair_counts[p]
            i = 0
            while i < len(syms) - 1:
                if syms[i] == a and syms[i + 1] == b:
                    syms[i:i + 2] = [ab]
                else:
                    i += 1
            for i in range(len(syms) - 1):
                p = (syms[i], syms[i + 1])
                pair_counts[p] += c
                pair_words.setdefault(p, set()).add(wi)
    return Bpe(merges, specials)
