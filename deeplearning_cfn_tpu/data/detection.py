"""Placeholder — detection source lands with the Mask R-CNN milestone."""


def build_detection_source(cfg, train):
    raise NotImplementedError
