"""Detection source: COCO-style batches with static shapes.

Replaces the reference Mask R-CNN's COCO data layer (TensorPack image/anno
loading). Two paths, same contract as the other sources:

- **Real data**: ``<data_dir>/<split>.npz`` with the keys below (COCO
  converted offline; masks stored box-aligned at 28×28 — the mask-head
  target resolution, which is also how the TPU reference implementations
  shipped their targets).
- **Synthetic**: deterministic scenes of colored ellipses/rectangles on
  noise; class = shape×color. Learnable: the RPN can localize the shapes
  and the heads can classify/segment them, so detection losses fall fast
  enough for convergence smoke tests.

Batch contract (all static; label 0 = padding, classes are 1-based):
  image [H, W, 3] f32 | boxes [N, 4] f32 (y0,x0,y1,x1 pixels)
  labels [N] i32     | masks [N, 28, 28] f32 (box-aligned)
"""

from __future__ import annotations

import os

import numpy as np

from ..config import DataConfig
from .pipeline import ArraySource

MASK_SIZE = 28
_KEYS = ("image", "boxes", "labels", "masks")


def make_detection_source(num_examples: int, image_size: int,
                          num_classes: int, max_boxes: int,
                          seed: int) -> ArraySource:
    rng = np.random.RandomState(seed)
    # class = 1 + shape * n_colors + color; shape 0 = rectangle, 1 = ellipse.
    n_fg = max(2, num_classes - 1)
    n_colors = max(1, n_fg // 2)
    palette = rng.rand(n_colors, 3).astype(np.float32) * 0.8 + 0.2

    images = rng.normal(0.0, 0.05, (num_examples, image_size, image_size, 3)
                        ).astype(np.float32)
    boxes = np.zeros((num_examples, max_boxes, 4), np.float32)
    labels = np.zeros((num_examples, max_boxes), np.int32)
    masks = np.zeros((num_examples, max_boxes, MASK_SIZE, MASK_SIZE),
                     np.float32)

    yy, xx = np.mgrid[0:MASK_SIZE, 0:MASK_SIZE]
    unit_y = (yy + 0.5) / MASK_SIZE * 2 - 1  # [-1, 1] box coords
    unit_x = (xx + 0.5) / MASK_SIZE * 2 - 1

    min_sz = max(6, image_size // 8)
    max_sz = max(min_sz + 2, image_size // 3)
    for i in range(num_examples):
        n_obj = rng.randint(1, min(max_boxes, 4) + 1)
        for j in range(n_obj):
            h = rng.randint(min_sz, max_sz)
            w = rng.randint(min_sz, max_sz)
            y0 = rng.randint(0, image_size - h)
            x0 = rng.randint(0, image_size - w)
            shape = rng.randint(0, 2)
            color = rng.randint(0, n_colors)
            cls = 1 + (shape * n_colors + color) % n_fg
            if shape == 0:
                mask28 = np.ones((MASK_SIZE, MASK_SIZE), np.float32)
            else:
                mask28 = ((unit_y ** 2 + unit_x ** 2) <= 1.0) \
                    .astype(np.float32)
            # Paint the object into the image at box resolution.
            obj_y = np.clip((np.arange(h) + 0.5) / h * MASK_SIZE - 0.5,
                            0, MASK_SIZE - 1).astype(int)
            obj_x = np.clip((np.arange(w) + 0.5) / w * MASK_SIZE - 0.5,
                            0, MASK_SIZE - 1).astype(int)
            stamp = mask28[np.ix_(obj_y, obj_x)][:, :, None] * palette[color]
            region = images[i, y0:y0 + h, x0:x0 + w]
            images[i, y0:y0 + h, x0:x0 + w] = np.where(
                stamp.sum(-1, keepdims=True) > 0, stamp, region)
            boxes[i, j] = [y0, x0, y0 + h, x0 + w]
            labels[i, j] = cls
            masks[i, j] = mask28
    return ArraySource({"image": images, "boxes": boxes, "labels": labels,
                        "masks": masks})


def _load_npz(data_dir: str, split: str) -> ArraySource:
    path = os.path.join(data_dir, f"{split}.npz")
    if not os.path.exists(path):
        raise FileNotFoundError(
            f"{path} not found; expected an .npz with keys {list(_KEYS)} "
            "(COCO converted offline, masks box-aligned 28x28)"
        )
    with np.load(path) as z:
        missing = [k for k in _KEYS if k not in z]
        if missing:
            raise KeyError(f"{path} missing keys {missing}")
        arrays = {k: np.asarray(z[k]) for k in _KEYS}
    # data prepare-coco stores images uint8 (4x smaller on disk); the batch
    # contract is f32 in [0, 1].
    if arrays["image"].dtype == np.uint8:
        arrays["image"] = arrays["image"].astype(np.float32) / 255.0
    return ArraySource(arrays)


def build_detection_source(cfg: DataConfig, train: bool,
                           num_classes: int = 91,
                           max_boxes: int = 16) -> ArraySource:
    if cfg.data_dir and not cfg.synthetic:
        return _load_npz(cfg.data_dir, "train" if train else "eval")
    n = cfg.num_train_examples or 512
    if not train:
        n = cfg.num_eval_examples or max(64, n // 8)
    return make_detection_source(n, cfg.image_size, num_classes, max_boxes,
                                 seed=47 if train else 53)
