"""Placeholder — text sources land with the BERT/NMT milestones."""


def build_text_source(cfg, train):
    raise NotImplementedError
