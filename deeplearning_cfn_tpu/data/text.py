"""Text sources: BERT MLM+NSP and NMT seq2seq batches.

Replaces the data layers of the reference's BERT (TF records of pre-masked
Wikipedia examples) and Sockeye (tokenized WMT bitext) workloads with two
paths:

- **Real data**: a directory of ``.npz`` files with pre-tokenized arrays
  (documented per builder below) — the offline-friendly stand-in for the
  TFRecord/bitext formats.
- **Synthetic**: deterministic, *learnable* generators, so convergence smoke
  tests have signal (same philosophy as pipeline.synthetic_image_source):
  MLM tokens follow a fixed Markov chain (masked tokens are predictable from
  context); NMT targets are a deterministic transform of the source.

All shapes are static: fixed seq_len, fixed max_predictions_per_seq —
the TPU constraint BERT's TF scripts also honored.
"""

from __future__ import annotations

import os
from typing import Dict

import numpy as np

from ..config import DataConfig
from .pipeline import ArraySource

MASK_RATE = 0.15
MAX_PRED_FRACTION = 0.2  # max_predictions_per_seq = fraction * seq_len


def _markov_tokens(rng: np.random.RandomState, n: int, seq_len: int,
                   vocab: int, reserved: int = 4) -> np.ndarray:
    """Token sequences from a sparse, fixed-transition Markov chain over the
    non-reserved vocab — structured enough that an MLM head can beat unigram
    entropy within a few hundred CPU steps."""
    usable = vocab - reserved
    # Each state deterministically prefers 2 successors (chosen per-seed).
    succ = np.stack([
        (np.arange(usable) * 7 + 3) % usable,
        (np.arange(usable) * 11 + 5) % usable,
    ], axis=1)
    tokens = np.empty((n, seq_len), np.int32)
    state = rng.randint(0, usable, n)
    for t in range(seq_len):
        tokens[:, t] = state + reserved
        pick = succ[state, rng.randint(0, 2, n)]
        noise = rng.rand(n) < 0.05
        state = np.where(noise, rng.randint(0, usable, n), pick)
    return tokens


def _build_mlm_examples(tokens: np.ndarray, vocab_size: int,
                        rng: np.random.RandomState) -> Dict[str, np.ndarray]:
    """Frame content token windows ``[N, seq_len-2]`` into the pre-masked
    MLM+NSP example contract (shared by the synthetic Markov source and the
    real-corpus BPE converter).

    Special ids: 0=[PAD], 1=[CLS], 2=[SEP], 3=[MASK].
    """
    num_examples, content = tokens.shape
    seq_len = content + 2
    max_pred = max(1, int(seq_len * MAX_PRED_FRACTION))

    input_ids = np.zeros((num_examples, seq_len), np.int32)
    input_ids[:, 0] = 1  # [CLS]
    input_ids[:, 1:-1] = tokens
    input_ids[:, -1] = 2  # [SEP]
    input_mask = np.ones((num_examples, seq_len), np.int32)
    # Two "segments" split at a random midpoint; NSP label = whether the
    # second half was swapped with another example's (learnable because
    # swapped halves break the Markov transitions at the boundary).
    split = seq_len // 2
    segment_ids = np.zeros((num_examples, seq_len), np.int32)
    segment_ids[:, split:] = 1
    nsp_label = rng.randint(0, 2, num_examples).astype(np.int32)
    swap = np.where(nsp_label == 1)[0]
    if len(swap) > 1:
        input_ids[swap[:, None], np.arange(split, seq_len)[None, :]] = \
            input_ids[np.roll(swap, 1)[:, None],
                      np.arange(split, seq_len)[None, :]]
    else:
        # A lone positive can't swap with anyone — relabel it negative
        # rather than train NSP on a contiguous "swapped" example.
        nsp_label[swap] = 0

    mlm_positions = np.zeros((num_examples, max_pred), np.int32)
    mlm_ids = np.zeros((num_examples, max_pred), np.int32)
    mlm_weights = np.zeros((num_examples, max_pred), np.float32)
    n_mask = max(1, int((seq_len - 2) * MASK_RATE))
    n_mask = min(n_mask, max_pred)
    for i in range(num_examples):
        pos = rng.choice(np.arange(1, seq_len - 1), n_mask, replace=False)
        pos.sort()
        mlm_positions[i, :n_mask] = pos
        mlm_ids[i, :n_mask] = input_ids[i, pos]
        mlm_weights[i, :n_mask] = 1.0
        # 80% [MASK], 10% random, 10% keep — the BERT masking recipe.
        r = rng.rand(n_mask)
        masked = input_ids[i, pos].copy()
        masked[r < 0.8] = 3
        rand_sel = (r >= 0.8) & (r < 0.9)
        masked[rand_sel] = rng.randint(4, vocab_size, rand_sel.sum())
        input_ids[i, pos] = masked

    return {
        "input_ids": input_ids, "input_mask": input_mask,
        "segment_ids": segment_ids, "mlm_positions": mlm_positions,
        "mlm_ids": mlm_ids, "mlm_weights": mlm_weights,
        "nsp_label": nsp_label,
    }


def make_mlm_source(num_examples: int, seq_len: int, vocab_size: int,
                    seed: int) -> ArraySource:
    """Pre-masked MLM+NSP examples (the reference pipeline also pre-masked
    offline via create_pretraining_data.py), from the synthetic Markov
    chain. Framing/masking shared with the real-corpus path
    (``_build_mlm_examples``)."""
    rng = np.random.RandomState(seed)
    tokens = _markov_tokens(rng, num_examples, seq_len - 2, vocab_size)
    return ArraySource(_build_mlm_examples(tokens, vocab_size, rng))


def make_nmt_source(num_examples: int, seq_len: int, vocab_size: int,
                    seed: int) -> ArraySource:
    """Seq2seq pairs where the target is a deterministic transform of the
    source (reverse + fixed offset) — a transformer-base learns it to
    near-zero loss, giving convergence tests real signal.

    Special ids: 0=[PAD], 1=[BOS], 2=[EOS].
    """
    rng = np.random.RandomState(seed)
    reserved = 3
    usable = vocab_size - reserved
    lengths = rng.randint(max(2, seq_len // 2), seq_len - 1, num_examples)

    src_ids = np.zeros((num_examples, seq_len), np.int32)
    src_mask = np.zeros((num_examples, seq_len), np.int32)
    tgt_in = np.zeros((num_examples, seq_len), np.int32)
    tgt_out = np.zeros((num_examples, seq_len), np.int32)
    tgt_mask = np.zeros((num_examples, seq_len), np.float32)
    for i in range(num_examples):
        n = lengths[i]
        src = rng.randint(0, usable, n)
        tgt = (src[::-1] + 7) % usable
        src_ids[i, :n] = src + reserved
        src_ids[i, n] = 2  # EOS
        src_mask[i, :n + 1] = 1
        tgt_in[i, 0] = 1  # BOS
        tgt_in[i, 1:n + 1] = tgt + reserved
        tgt_out[i, :n] = tgt + reserved
        tgt_out[i, n] = 2  # EOS
        tgt_mask[i, :n + 1] = 1.0
    return ArraySource({
        "src_ids": src_ids, "src_mask": src_mask, "tgt_in_ids": tgt_in,
        "tgt_out_ids": tgt_out, "tgt_mask": tgt_mask,
    })


_MLM_KEYS = ("input_ids", "input_mask", "segment_ids", "mlm_positions",
             "mlm_ids", "mlm_weights", "nsp_label")
_NMT_KEYS = ("src_ids", "src_mask", "tgt_in_ids", "tgt_out_ids", "tgt_mask")


_LM_KEYS = ("tokens", "loss_mask")


def make_lm_source(num_examples: int, seq_len: int, vocab_size: int,
                   seed: int) -> ArraySource:
    """Causal-LM examples: ``tokens [N, seq_len+1]`` (model consumes
    tokens[:, :-1], predicts tokens[:, 1:]) + ``loss_mask [N, seq_len]``
    over the predicted positions. Synthetic tokens follow the same fixed
    Markov chain as the MLM source, so next-token loss falls fast below
    unigram entropy — a learnable convergence signal."""
    rng = np.random.RandomState(seed)
    tokens = _markov_tokens(rng, num_examples, seq_len + 1, vocab_size)
    return ArraySource({
        "tokens": tokens.astype(np.int32),
        "loss_mask": np.ones((num_examples, seq_len), np.float32),
    })


def prepare_lm_text(src_path: str, out_dir: str, seq_len: int,
                    eval_fraction: float = 0.05) -> Dict[str, int]:
    """Tokenize a raw text/bytes file into the ``lm_text`` npz contract.

    Byte-level vocabulary (the fully-offline tokenizer: 256 byte values
    shifted past the 4 reserved special ids → ``data.vocab_size=260``),
    chunked into non-overlapping ``seq_len + 1`` windows, split into
    ``train.npz`` / ``eval.npz`` under ``out_dir``. Returns counts.
    The reference's text workloads assumed an offline tokenization step
    too (create_pretraining_data.py, Sockeye's prepare-data); this is
    that step for the LM family, with no vocab download required.
    """
    if not 0.0 < eval_fraction < 1.0:
        raise ValueError(
            f"eval_fraction must be in (0, 1), got {eval_fraction}")
    with open(src_path, "rb") as f:
        raw = np.frombuffer(f.read(), np.uint8)
    window = seq_len + 1
    n = len(raw) // window
    if n < 2:
        raise ValueError(
            f"{src_path}: need at least {2 * window} bytes for one train "
            f"and one eval window of seq_len+1={window}, got {len(raw)}")
    tokens = raw[:n * window].reshape(n, window).astype(np.int32) + 4
    n_eval = min(max(1, int(n * eval_fraction)), n - 1)
    os.makedirs(out_dir, exist_ok=True)
    splits = {"train": tokens[:-n_eval], "eval": tokens[-n_eval:]}
    for split, toks in splits.items():
        np.savez(os.path.join(out_dir, f"{split}.npz"), tokens=toks,
                 loss_mask=np.ones((len(toks), seq_len), np.float32))
    return {"train_examples": n - n_eval, "eval_examples": n_eval,
            "vocab_size": 260, "seq_len": seq_len}


def _read_lines(path: str):
    with open(path, encoding="utf-8", errors="replace") as f:
        return f.read().splitlines()


def _train_or_load_bpe(lines, vocab_size: int, specials, out_dir: str,
                       vocab_path: str = ""):
    """Load an existing vocab file, or train one from ``lines`` and save it
    to ``<out_dir>/vocab.json`` (reusable across splits and at decode time
    via the CLI's --vocab)."""
    from .bpe import Bpe, train_bpe

    if vocab_path:
        bpe = Bpe.load(vocab_path)
        if bpe.specials != tuple(specials):
            raise ValueError(
                f"{vocab_path} was trained with specials "
                f"{list(bpe.specials)} but this converter needs "
                f"{list(specials)} — reusing it would shift every byte id "
                f"and silently corrupt the shards. Train a fresh vocab for "
                f"this task (omit --vocab).")
        return bpe
    bpe = train_bpe(lines, vocab_size, specials)
    os.makedirs(out_dir, exist_ok=True)
    bpe.save(os.path.join(out_dir, "vocab.json"))
    return bpe


def prepare_mlm_text(src_path: str, out_dir: str, seq_len: int,
                     vocab_size: int = 8192, eval_fraction: float = 0.05,
                     vocab_path: str = "", seed: int = 0) -> Dict[str, int]:
    """Real corpus → the ``wikipedia_mlm`` npz contract, via byte-level BPE
    (data/bpe.py) — the rebuild's create_pretraining_data.py: train (or
    load) the vocab, encode the corpus, cut into ``seq_len-2`` content
    windows, and frame/mask with the same recipe as the synthetic source
    (``_build_mlm_examples``: CLS/SEP framing, midpoint segments, NSP by
    second-half swap, 15% masking at 80/10/10)."""
    from .bpe import MLM_SPECIALS

    if not 0.0 < eval_fraction < 1.0:
        raise ValueError(
            f"eval_fraction must be in (0, 1), got {eval_fraction}")
    lines = _read_lines(src_path)
    bpe = _train_or_load_bpe(lines, vocab_size, MLM_SPECIALS, out_dir,
                             vocab_path)
    stream: list = []
    for line in lines:
        stream.extend(bpe.encode(line))
    content = seq_len - 2
    n = len(stream) // content
    if n < 2:
        raise ValueError(
            f"{src_path}: corpus encodes to {len(stream)} tokens; need at "
            f"least 2 windows of seq_len-2={content}")
    tokens = np.asarray(stream[:n * content], np.int32).reshape(n, content)
    examples = _build_mlm_examples(tokens, bpe.vocab_size,
                                   np.random.RandomState(seed))
    n_eval = min(max(1, int(n * eval_fraction)), n - 1)
    os.makedirs(out_dir, exist_ok=True)
    for split, sl in (("train", slice(None, n - n_eval)),
                      ("eval", slice(n - n_eval, None))):
        np.savez(os.path.join(out_dir, f"{split}.npz"),
                 **{k: v[sl] for k, v in examples.items()})
    return {"train_examples": n - n_eval, "eval_examples": n_eval,
            "vocab_size": bpe.vocab_size, "seq_len": seq_len}


def prepare_nmt_text(src_path: str, tgt_path: str, out_dir: str,
                     seq_len: int, vocab_size: int = 8192,
                     eval_fraction: float = 0.05,
                     vocab_path: str = "") -> Dict[str, int]:
    """Parallel line files → the ``wmt_en_de`` npz contract, with ONE
    shared byte-level BPE over both sides (Sockeye's shared-vocab
    prepare-data convention). Pairs whose encoded source or target exceeds
    ``seq_len - 1`` (room for EOS) are dropped and counted, Sockeye's
    max-length filter behavior."""
    from .bpe import NMT_SPECIALS

    if not 0.0 < eval_fraction < 1.0:
        raise ValueError(
            f"eval_fraction must be in (0, 1), got {eval_fraction}")
    src_lines = _read_lines(src_path)
    tgt_lines = _read_lines(tgt_path)
    if len(src_lines) != len(tgt_lines):
        raise ValueError(
            f"parallel files differ in length: {len(src_lines)} src vs "
            f"{len(tgt_lines)} tgt lines")
    bpe = _train_or_load_bpe(src_lines + tgt_lines, vocab_size,
                             NMT_SPECIALS, out_dir, vocab_path)
    pairs = []
    skipped = 0
    for s_line, t_line in zip(src_lines, tgt_lines):
        s, t = bpe.encode(s_line), bpe.encode(t_line)
        if not s or not t or len(s) > seq_len - 1 or len(t) > seq_len - 1:
            skipped += 1
            continue
        pairs.append((s, t))
    n = len(pairs)
    if n < 2:
        raise ValueError(
            f"only {n} usable pairs (skipped {skipped}); need at least 2 — "
            f"raise seq_len or check the files are parallel")
    src_ids = np.zeros((n, seq_len), np.int32)
    src_mask = np.zeros((n, seq_len), np.int32)
    tgt_in = np.zeros((n, seq_len), np.int32)
    tgt_out = np.zeros((n, seq_len), np.int32)
    tgt_mask = np.zeros((n, seq_len), np.float32)
    for i, (s, t) in enumerate(pairs):
        src_ids[i, :len(s)] = s
        src_ids[i, len(s)] = 2  # EOS
        src_mask[i, :len(s) + 1] = 1
        tgt_in[i, 0] = 1  # BOS
        tgt_in[i, 1:len(t) + 1] = t
        tgt_out[i, :len(t)] = t
        tgt_out[i, len(t)] = 2  # EOS
        tgt_mask[i, :len(t) + 1] = 1.0
    n_eval = min(max(1, int(n * eval_fraction)), n - 1)
    arrays = {"src_ids": src_ids, "src_mask": src_mask,
              "tgt_in_ids": tgt_in, "tgt_out_ids": tgt_out,
              "tgt_mask": tgt_mask}
    os.makedirs(out_dir, exist_ok=True)
    for split, sl in (("train", slice(None, n - n_eval)),
                      ("eval", slice(n - n_eval, None))):
        np.savez(os.path.join(out_dir, f"{split}.npz"),
                 **{k: v[sl] for k, v in arrays.items()})
    return {"train_examples": n - n_eval, "eval_examples": n_eval,
            "skipped_pairs": skipped, "vocab_size": bpe.vocab_size,
            "seq_len": seq_len}


def _load_npz_dir(data_dir: str, split: str, keys) -> ArraySource:
    """Real-data path: ``<data_dir>/<split>.npz`` holding the listed keys."""
    path = os.path.join(data_dir, f"{split}.npz")
    if not os.path.exists(path):
        raise FileNotFoundError(
            f"{path} not found; expected an .npz with keys {list(keys)}"
        )
    with np.load(path) as z:
        missing = [k for k in keys if k not in z]
        if missing:
            raise KeyError(f"{path} missing keys {missing}")
        return ArraySource({k: np.asarray(z[k]) for k in keys})


def build_text_source(cfg: DataConfig, train: bool) -> ArraySource:
    split = "train" if train else "eval"
    keys = {"wikipedia_mlm": _MLM_KEYS, "lm_text": _LM_KEYS} \
        .get(cfg.name, _NMT_KEYS)
    if cfg.data_dir and not cfg.synthetic:
        return _load_npz_dir(cfg.data_dir, split, keys)
    n = cfg.num_train_examples or 4096
    if not train:
        n = cfg.num_eval_examples or max(256, n // 8)
    seed = 41 if train else 43
    if cfg.name == "wikipedia_mlm":
        return make_mlm_source(n, cfg.seq_len, cfg.vocab_size, seed)
    if cfg.name == "wmt_en_de":
        return make_nmt_source(n, cfg.seq_len, cfg.vocab_size, seed)
    if cfg.name == "lm_text":
        return make_lm_source(n, cfg.seq_len, cfg.vocab_size, seed)
    raise KeyError(f"unknown text dataset {cfg.name!r}")
