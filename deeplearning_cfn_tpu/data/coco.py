"""Real COCO ingestion: annotation JSON + image dir → detection npz.

The reference's Mask R-CNN workload (TensorPack — SURVEY.md §3.1) consumed
COCO's instances_*.json + JPEG directories directly, with dynamic-shape
per-image annotation lists. This converter runs that ingestion ONCE
offline and writes the rebuild's static-shape detection contract
(data/detection.py): square f32 images, boxes padded to ``max_boxes``,
labels (0 = padding, COCO category ids kept 1-based as-is — the
maskrcnn_coco preset's num_classes=91 covers the sparse id space), and
GT masks stored **box-aligned at 28×28** — the mask-head target
resolution, sampled with the same box-frame convention
metrics/coco_map.py's PastedMask pastes back with.

Geometry: aspect-preserving resize by ``image_size / max(H, W)`` with
bottom/right zero padding (boxes/polygons scale by one factor — no
distortion). iscrowd annotations are skipped (standard training practice;
RLE crowds are eval-only in the reference too). Objects beyond
``max_boxes`` are dropped largest-first-kept and counted.

Scale note: npz holds the whole split in one array — right for the
fixture-scale and fine-tuning datasets this repo can test offline
(convert at a reduced ``--image-size`` or ``--limit`` for smoke runs);
pod-scale COCO would use the same converter sharded per file-range, one
npz per shard.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List

import numpy as np

MASK_SIZE = 28
_SUPERSAMPLE = 2  # rasterize polygons at 2x then average-pool to 28


def _polygons_to_box_mask(polys: List[List[float]], y0: float, x0: float,
                          bh: float, bw: float) -> np.ndarray:
    """COCO polygons (image coords, [x1,y1,x2,y2,...] flat lists) → one
    box-aligned [28, 28] float mask. Drawn with PIL at 2× supersample and
    average-pooled, so partial-coverage cells get fractional values the
    bilinear paste-back reproduces smoothly."""
    from PIL import Image, ImageDraw

    s = MASK_SIZE * _SUPERSAMPLE
    canvas = Image.new("L", (s, s), 0)
    draw = ImageDraw.Draw(canvas)
    drew = False
    for poly in polys:
        if len(poly) < 6:
            continue
        pts = [
            (
                (poly[i] - x0) / max(bw, 1e-3) * s,
                (poly[i + 1] - y0) / max(bh, 1e-3) * s,
            )
            for i in range(0, len(poly) - 1, 2)
        ]
        draw.polygon(pts, fill=255)
        drew = True
    if not drew:
        return np.zeros((MASK_SIZE, MASK_SIZE), np.float32)
    arr = np.asarray(canvas, np.float32) / 255.0
    return arr.reshape(MASK_SIZE, _SUPERSAMPLE, MASK_SIZE,
                       _SUPERSAMPLE).mean((1, 3))


def prepare_coco(annotations_path: str, images_dir: str, out_dir: str,
                 split: str, image_size: int = 1024, max_boxes: int = 100,
                 limit: int = 0) -> Dict[str, int]:
    """instances_*.json + image dir → ``<out_dir>/<split>.npz`` in the
    detection contract. Returns counts (images, objects, skipped_crowd,
    dropped_over_max)."""
    from PIL import Image

    if split not in ("train", "eval"):
        raise ValueError(f"split must be 'train' or 'eval', got {split!r}")
    with open(annotations_path) as f:
        coco = json.load(f)
    by_image: Dict[int, List[dict]] = {}
    skipped_crowd = 0
    for ann in coco.get("annotations", []):
        if ann.get("iscrowd", 0):
            skipped_crowd += 1
            continue
        by_image.setdefault(ann["image_id"], []).append(ann)

    images_meta = coco.get("images", [])
    if limit:
        images_meta = images_meta[:limit]
    n = len(images_meta)
    if n == 0:
        raise ValueError(f"{annotations_path}: no images listed")
    est_gib = n * image_size * image_size * 3 / 2 ** 30
    if est_gib > 8.0:
        raise ValueError(
            f"{n} images at {image_size}² is ~{est_gib:.0f} GiB in one npz "
            f"— beyond the single-file contract. Convert a subset "
            f"(--limit), reduce --image-size, or run per file-range shard "
            f"(one npz each) for pod-scale COCO.")

    images = np.zeros((n, image_size, image_size, 3), np.uint8)
    boxes = np.zeros((n, max_boxes, 4), np.float32)
    labels = np.zeros((n, max_boxes), np.int32)
    masks = np.zeros((n, max_boxes, MASK_SIZE, MASK_SIZE), np.float32)
    total_objects = 0
    dropped = 0
    skipped_degenerate = 0

    for i, meta in enumerate(images_meta):
        path = os.path.join(images_dir, meta["file_name"])
        with Image.open(path) as im:
            im = im.convert("RGB")
            w0, h0 = im.size
            scale = image_size / max(w0, h0)
            nw, nh = max(1, round(w0 * scale)), max(1, round(h0 * scale))
            im = im.resize((nw, nh), Image.BILINEAR)
            images[i, :nh, :nw] = np.asarray(im, np.uint8)

        anns = by_image.get(meta["id"], [])
        # Degenerate (sub-pixel after scaling) boxes go first, BEFORE the
        # cap — a dud must never consume a slot a real object needed.
        scaled = []
        for ann in anns:
            x, y, bw, bh = [float(v) * scale for v in ann["bbox"]]
            y1 = min(y + bh, image_size)
            x1 = min(x + bw, image_size)
            if y1 - y < 1.0 or x1 - x < 1.0:
                skipped_degenerate += 1
                continue
            scaled.append((ann, (y, x, y1, x1)))
        # Largest objects first: when the cap bites, small instances are
        # the standard sacrifice (they are also the least learnable).
        scaled.sort(key=lambda p: -float(p[0].get("area", 0.0)))
        if len(scaled) > max_boxes:
            dropped += len(scaled) - max_boxes
            scaled = scaled[:max_boxes]
        for j, (ann, (y0, x0, y1, x1)) in enumerate(scaled):
            boxes[i, j] = (y0, x0, y1, x1)
            labels[i, j] = int(ann["category_id"])
            seg = ann.get("segmentation")
            if isinstance(seg, list) and seg:
                polys = [[v * scale for v in poly] for poly in seg]
                masks[i, j] = _polygons_to_box_mask(
                    polys, y0, x0, y1 - y0, x1 - x0)
            else:
                # No polygon (or RLE on a non-crowd, rare): whole-box mask.
                masks[i, j] = 1.0
            total_objects += 1

    os.makedirs(out_dir, exist_ok=True)
    np.savez(os.path.join(out_dir, f"{split}.npz"), image=images,
             boxes=boxes, labels=labels, masks=masks)
    return {"images": n, "objects": total_objects,
            "skipped_crowd": skipped_crowd,
            "skipped_degenerate": skipped_degenerate,
            "dropped_over_max": dropped,
            "image_size": image_size, "max_boxes": max_boxes}
