"""Placeholder — real ImageNet file loader lands with Phase 3."""


def load_imagenet_source(cfg, train):
    raise NotImplementedError("real ImageNet loading lands with Phase 3; use synthetic")
