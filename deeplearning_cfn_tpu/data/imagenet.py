"""Real ImageNet ingestion: pre-decoded binary shards + native hot path.

The reference's flagship workload (BASELINE.json configs[1] — ImageNet
ResNet-50 via TF+Horovod) consumed TFRecords with on-the-fly JPEG decode on
host CPUs. At TPU feed rates JPEG decode is the classic host bottleneck
(SURVEY.md §8 hard-part #2), so the rebuild splits ingestion in two:

1. **Preparation** (one-off, ``prepare_imagenet`` / the
   ``dlcfn-tpu data prepare-imagenet`` CLI): decode JPEGs (PIL), resize the
   short side to ``size`` (default 256), center-crop to square u8 RGB, and
   write fixed-record binary shards. This is the FFCV-style trade: pay
   decode once, stream bytes forever after.
2. **Runtime** (:class:`ShardedImageNetSource`): mmap the shards, and per
   batch do random-resized-crop → bilinear resize to the train resolution →
   flip → per-channel normalize, in the native C++ loader
   (``dataio.dlcfn_crop_resize_norm``, threaded, GIL-free) with a numpy
   fallback that replicates the C++ RNG draw-for-draw.

Shard format (``dlcfn-imagenet-shards-v1``)::

    <split_dir>/index.json
      {"format": "dlcfn-imagenet-shards-v1",
       "image_hw": [H, W],           # stored (pre-decoded) resolution
       "record_bytes": 4 + H*W*3,
       "num_classes": C,
       "shards": [{"file": "shard-00000.bin", "num_records": N0}, ...]}
    <split_dir>/shard-XXXXX.bin
      num_records consecutive records, each:
        int32 (little-endian) label | uint8[H*W*3] RGB, HWC

Per-host sharding happens at the index level (DataPipeline hands each
process its slice of the global shuffled index), so any number of hosts can
share one shard set — the GCS/EFS "shared data store" role from SURVEY.md §6.
"""

from __future__ import annotations

import json
import math
import os
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..config import DataConfig

IMAGENET_MEAN = np.array([0.485, 0.456, 0.406], np.float32)
IMAGENET_STD = np.array([0.229, 0.224, 0.225], np.float32)

FORMAT_NAME = "dlcfn-imagenet-shards-v1"
_GOLDEN = 0x9E3779B97F4A7C15
_MASK64 = (1 << 64) - 1


# ---------------------------------------------------------------------------
# RNG — SplitMix64, bit-identical to dataio.cpp
# ---------------------------------------------------------------------------


def _splitmix64(x: int) -> int:
    x = (x + _GOLDEN) & _MASK64
    x = ((x ^ (x >> 30)) * 0xBF58476D1CE4E5B9) & _MASK64
    x = ((x ^ (x >> 27)) * 0x94D049BB133111EB) & _MASK64
    return x ^ (x >> 31)


class _Rng:
    def __init__(self, seed: int):
        self.state = seed & _MASK64

    def next(self) -> int:
        self.state = _splitmix64(self.state)
        return self.state

    def below(self, bound: int) -> int:
        return self.next() % bound

    def uniform01(self) -> float:
        return (self.next() >> 11) * (1.0 / 9007199254740992.0)


# Eval center-crop field of view: crop EVAL_CROP_RATIO*min(h,w), then
# resize — with 256² stored sources exactly the classic resize-256 /
# center-crop-224 recipe, and the same field of view at any other shard
# size. Must match kEvalCropRatio in dataio.cpp (same contract style as
# the shared RNG).
EVAL_CROP_RATIO = 0.875


def _crop_params(rng: "_Rng", h: int, w: int, augment: bool
                 ) -> Tuple[int, int, int, int, bool]:
    """(y0, x0, crop_h, crop_w, flip) — the draw order is the contract
    shared with crop_resize_one in dataio.cpp."""
    if augment:
        area = float(h * w)
        for _ in range(10):
            target_area = (0.08 + rng.uniform01() * 0.92) * area
            log_lo, log_hi = math.log(3.0 / 4.0), math.log(4.0 / 3.0)
            ar = math.exp(log_lo + rng.uniform01() * (log_hi - log_lo))
            w_c = int(math.floor(math.sqrt(target_area * ar) + 0.5))
            h_c = int(math.floor(math.sqrt(target_area / ar) + 0.5))
            if 0 < w_c <= w and 0 < h_c <= h:
                y0 = rng.below(h - h_c + 1)
                x0 = rng.below(w - w_c + 1)
                return y0, x0, h_c, w_c, bool(rng.next() & 1)
        side = min(h, w)
        return (h - side) // 2, (w - side) // 2, side, side, \
            bool(rng.next() & 1)
    # floor(x + 0.5): the one tie-breaking rule both implementations use
    # (Python round() is half-to-even and would diverge from C++ lround).
    side = max(1, int(EVAL_CROP_RATIO * min(h, w) + 0.5))
    return (h - side) // 2, (w - side) // 2, side, side, False


def _crop_resize_norm_py(
    images: Sequence[np.ndarray], out_size: int, seed: int, augment: bool,
    mean: np.ndarray = IMAGENET_MEAN, std: np.ndarray = IMAGENET_STD,
) -> np.ndarray:
    """Numpy fallback for dataio.dlcfn_crop_resize_norm — same RNG, same
    sampling formula, same normalization (parity-tested)."""
    b = len(images)
    out = np.empty((b, out_size, out_size, 3), np.float32)
    s = out_size
    for i, img in enumerate(images):
        h, w = img.shape[:2]
        rng = _Rng(_splitmix64(seed ^ (((i + 1) * _GOLDEN) & _MASK64)))
        y0, x0, ch, cw, flip = _crop_params(rng, h, w, augment)
        fy = y0 + (np.arange(s, dtype=np.float64) + 0.5) * ch / s - 0.5
        cols = np.arange(s)
        if flip:
            cols = s - 1 - cols
        fx = x0 + (cols.astype(np.float64) + 0.5) * cw / s - 0.5
        yi = np.floor(fy).astype(np.int64)
        xi = np.floor(fx).astype(np.int64)
        wy1 = (fy - yi).astype(np.float32)[:, None, None]
        wx1 = (fx - xi).astype(np.float32)[None, :, None]
        y0i = np.clip(yi, 0, h - 1)
        y1i = np.clip(yi + 1, 0, h - 1)
        x0i = np.clip(xi, 0, w - 1)
        x1i = np.clip(xi + 1, 0, w - 1)
        fimg = img.astype(np.float32)
        v00 = fimg[y0i[:, None], x0i[None, :]]
        v01 = fimg[y0i[:, None], x1i[None, :]]
        v10 = fimg[y1i[:, None], x0i[None, :]]
        v11 = fimg[y1i[:, None], x1i[None, :]]
        top = v00 + (v01 - v00) * wx1
        bot = v10 + (v11 - v10) * wx1
        v = top + (bot - top) * wy1
        out[i] = (v * (1.0 / 255.0) - mean) / std
    return out


# ---------------------------------------------------------------------------
# Shard writing
# ---------------------------------------------------------------------------


class ShardWriter:
    """Streaming writer for dlcfn-imagenet-shards-v1 — the single place
    that knows the record layout and index schema (write_shards and
    prepare_imagenet both go through it)."""

    def __init__(self, out_dir: str, image_hw: Tuple[int, int],
                 shard_records: int, prefix: str = "shard"):
        os.makedirs(out_dir, exist_ok=True)
        self.out_dir = out_dir
        self.image_hw = tuple(image_hw)
        self.shard_records = shard_records
        self.prefix = prefix
        self.shards: List[Dict] = []
        self._fh = None
        self._in_shard = 0

    def add(self, image_u8: np.ndarray, label: int) -> None:
        h, w = self.image_hw
        img = np.ascontiguousarray(image_u8, np.uint8)
        assert img.shape == (h, w, 3), (
            f"record shape {img.shape} != {(h, w, 3)}")
        if self._fh is None:
            fname = f"{self.prefix}-{len(self.shards):05d}.bin"
            self.shards.append({"file": fname, "num_records": 0})
            self._fh = open(os.path.join(self.out_dir, fname), "wb")
            self._in_shard = 0
        self._fh.write(np.int32(label).tobytes())
        self._fh.write(img.tobytes())
        self._in_shard += 1
        self.shards[-1]["num_records"] = self._in_shard
        if self._in_shard >= self.shard_records:
            self._fh.close()
            self._fh = None

    def finish(self, num_classes: int) -> Dict:
        if self._fh is not None:
            self._fh.close()
            self._fh = None
        h, w = self.image_hw
        index = {
            "format": FORMAT_NAME,
            "image_hw": [h, w],
            "record_bytes": 4 + h * w * 3,
            "num_classes": int(num_classes),
            "shards": self.shards,
        }
        with open(os.path.join(self.out_dir, "index.json"), "w") as fh:
            json.dump(index, fh, indent=1)
        return index


def write_shards(
    out_dir: str,
    images_u8,
    labels: Sequence[int],
    num_classes: int,
    shard_records: int = 1024,
    prefix: str = "shard",
) -> Dict:
    """Write u8 HWC images + labels as dlcfn-imagenet-shards-v1.

    ``images_u8`` is any sequence of equal-shape [H,W,3] u8 arrays (list or
    [N,H,W,3] array). Returns the index dict (also written to index.json).
    """
    n = len(images_u8)
    assert n == len(labels) and n > 0
    writer = ShardWriter(out_dir, images_u8[0].shape[:2], shard_records,
                         prefix=prefix)
    for img, lab in zip(images_u8, labels):
        writer.add(img, int(lab))
    return writer.finish(num_classes)


def prepare_imagenet(
    src_dir: str,
    out_dir: str,
    size: int = 256,
    shard_records: int = 8192,
    limit: Optional[int] = None,
    log_every: int = 5000,
) -> Dict:
    """Convert a class-per-directory JPEG tree (the torchvision ImageFolder
    layout the reference's scripts also consumed) into binary shards.

    ``src_dir`` holds one subdirectory per class; sorted subdirectory names
    define the label ids. Each image is decoded with PIL, short-side resized
    to ``size``, center-cropped square. Run once per split::

        dlcfn-tpu data prepare-imagenet --src train/ --out shards/train
    """
    try:
        from PIL import Image
    except ImportError as e:  # pragma: no cover
        raise RuntimeError(
            "prepare_imagenet needs PIL for JPEG decode; install pillow or "
            "produce shards with write_shards() from pre-decoded arrays"
        ) from e

    classes = sorted(
        d for d in os.listdir(src_dir)
        if os.path.isdir(os.path.join(src_dir, d)))
    if not classes:
        raise ValueError(f"no class directories under {src_dir}")
    files: List[Tuple[str, int]] = []
    for label, cls in enumerate(classes):
        cdir = os.path.join(src_dir, cls)
        for fname in sorted(os.listdir(cdir)):
            if fname.lower().endswith((".jpg", ".jpeg", ".png")):
                files.append((os.path.join(cdir, fname), label))
    if limit:
        files = files[:limit]
    if not files:
        raise ValueError(f"no images found under {src_dir}")

    def decode(path: str) -> np.ndarray:
        img = Image.open(path).convert("RGB")
        w, h = img.size
        scale = size / min(w, h)
        img = img.resize((max(size, round(w * scale)),
                          max(size, round(h * scale))), Image.BILINEAR)
        w, h = img.size
        left, top = (w - size) // 2, (h - size) // 2
        return np.asarray(img.crop((left, top, left + size, top + size)),
                          np.uint8)

    writer = ShardWriter(out_dir, (size, size), shard_records)
    for i, (path, label) in enumerate(files):
        writer.add(decode(path), label)
        if log_every and (i + 1) % log_every == 0:
            print(f"[prepare-imagenet] {i + 1}/{len(files)} images")
    return writer.finish(len(classes))


# ---------------------------------------------------------------------------
# Runtime source
# ---------------------------------------------------------------------------


class ShardedImageNetSource:
    """mmap-backed source over dlcfn-imagenet-shards-v1.

    Exposes the seeded-gather protocol (``gather_seeded``) DataPipeline
    prefers: augmentation randomness comes from the pipeline's
    (seed, epoch, offset, process) mix, so results are deterministic and
    resume-stable. Labels are read once at load (4 bytes/record); image
    payloads stay on disk until gathered (the OS page cache is the prefetch
    buffer, as with the reference's RecordIO/TFRecord readers).
    """

    def __init__(self, split_dir: str, train: bool, image_size: int = 224,
                 native: bool = True, num_workers: int = 4):
        index_path = os.path.join(split_dir, "index.json")
        if not os.path.exists(index_path):
            raise FileNotFoundError(
                f"no index.json under {split_dir}; build shards with "
                "`dlcfn-tpu data prepare-imagenet`")
        with open(index_path) as fh:
            self.index = json.load(fh)
        if self.index.get("format") != FORMAT_NAME:
            raise ValueError(
                f"unsupported shard format {self.index.get('format')!r}")
        self.split_dir = split_dir
        self.train = train
        self.image_size = image_size
        self.num_workers = num_workers
        self.image_hw = tuple(self.index["image_hw"])
        self.record_bytes = int(self.index["record_bytes"])
        self.num_classes = int(self.index["num_classes"])

        self._mmaps: List[np.ndarray] = []
        counts = []
        for shard in self.index["shards"]:
            path = os.path.join(split_dir, shard["file"])
            mm = np.memmap(path, dtype=np.uint8, mode="r")
            expect = shard["num_records"] * self.record_bytes
            if mm.size != expect:
                raise ValueError(
                    f"{path}: {mm.size} bytes, expected {expect}")
            self._mmaps.append(mm)
            counts.append(shard["num_records"])
        self._cum = np.concatenate([[0], np.cumsum(counts)])
        self.size = int(self._cum[-1])

        # Labels up front: one int32 per record at each record head.
        labels = np.empty(self.size, np.int32)
        for s, mm in enumerate(self._mmaps):
            n = counts[s]
            recs = mm[:n * self.record_bytes].reshape(n, self.record_bytes)
            labels[self._cum[s]:self._cum[s + 1]] = (
                recs[:, :4].copy().view(np.int32).ravel())
        self._labels = labels

        self._native = False
        if native:
            from .. import dataio

            self._native = dataio.available()

    def _payload_ptr(self, example: int) -> int:
        shard = int(np.searchsorted(self._cum, example, side="right")) - 1
        rec = int(example - self._cum[shard])
        mm = self._mmaps[shard]
        return mm.ctypes.data + rec * self.record_bytes + 4

    def _payload_view(self, example: int) -> np.ndarray:
        shard = int(np.searchsorted(self._cum, example, side="right")) - 1
        rec = int(example - self._cum[shard])
        mm = self._mmaps[shard]
        start = rec * self.record_bytes + 4
        h, w = self.image_hw
        return mm[start:start + h * w * 3].reshape(h, w, 3)

    def gather_seeded(self, idx: np.ndarray, seed: int
                      ) -> Dict[str, np.ndarray]:
        labels = self._labels[idx]
        if self._native:
            from .. import dataio

            ptrs = np.fromiter((self._payload_ptr(int(e)) for e in idx),
                               np.uint64, count=len(idx))
            images = dataio.crop_resize_norm(
                ptrs, self.image_hw, self.image_size, seed,
                augment=self.train, mean=IMAGENET_MEAN, std=IMAGENET_STD,
                nthreads=self.num_workers)
        else:
            views = [self._payload_view(int(e)) for e in idx]
            images = _crop_resize_norm_py(views, self.image_size, seed,
                                          augment=self.train)
        return {"image": images, "label": np.asarray(labels, np.int32)}

    # DataPipeline's unseeded path (eval under custom wrappers) — center
    # crop is draw-free, so seed 0 is exact.
    def gather(self, idx: np.ndarray) -> Dict[str, np.ndarray]:
        return self.gather_seeded(idx, 0)


def load_imagenet_source(cfg: DataConfig, train: bool
                         ) -> ShardedImageNetSource:
    """Factory used by build_pipeline for the real-data path: expects
    ``cfg.data_dir/{train,val}/index.json``."""
    split = "train" if train else "val"
    return ShardedImageNetSource(
        os.path.join(cfg.data_dir, split), train=train,
        image_size=cfg.image_size, native=cfg.use_native_loader,
        num_workers=cfg.num_workers)


# ---------------------------------------------------------------------------
# Feed-rate measurement (SURVEY.md §8 hard-part #2 acceptance)
# ---------------------------------------------------------------------------


def measure_feed_rate(pipeline, num_batches: int = 30,
                      warmup: int = 3) -> Dict[str, float]:
    """Host-side images/sec the pipeline can sustain (no device in the
    loop) — must exceed one chip's training consumption rate for input and
    compute to overlap cleanly."""
    import time

    it = pipeline.epochs()
    batch = None
    for _ in range(warmup + 1):
        batch = next(it)
    per_batch = len(next(iter(batch.values())))
    t0 = time.perf_counter()
    for _ in range(num_batches):
        next(it)
    dt = time.perf_counter() - t0
    return {
        "images_per_sec": per_batch * num_batches / dt,
        "batch_size": float(per_batch),
        "batches": float(num_batches),
        "seconds": dt,
    }
