"""Socket transport for the network serving plane.

Unix-domain sockets by default (``unix:///path/to.sock`` — one replica
per path, zero port arithmetic, and the path lives in the replica's run
dir next to its logs), TCP optional (``tcp://host:port``; port 0 binds
an ephemeral port and :func:`listen` returns the resolved address).

The transport is deliberately thin: blocking sockets with explicit
timeouts, a :class:`Connection` wrapper that distinguishes the three
things a read can mean (bytes / not-yet / peer-gone), and a connect
retry loop that doubles as the fleet's readiness barrier — a replica
server binds its listen socket only AFTER its engine is built and
warmed, so the first successful connect IS the readiness signal.

Failure model (docs/OPERATIONS.md "socket failure model"):

- connect timeout / refused → the replica is not up (yet); retry until
  ``retry_deadline_s``, then raise — the caller decides whether that is
  fatal (bench startup) or a DOWN replica (router reconnect).
- recv timeout → no data, nothing wrong; return ``None``.
- EOF / ECONNRESET / EPIPE → the peer is GONE: raise
  :class:`ConnectionClosed`. The client maps this to ReplicaCrashed —
  a dead socket is a dead replica, same as SIGKILL.
"""

from __future__ import annotations

import os
import select
import socket
import time
from typing import Optional, Tuple


class ConnectionClosed(ConnectionError):
    """The peer closed (or reset) the connection — distinct from a
    timeout, which only means "no bytes yet"."""


def parse_address(address: str) -> Tuple[str, object]:
    """``unix:///path`` → ("unix", path); ``tcp://host:port`` →
    ("tcp", (host, port)). A bare path is taken as a unix socket."""
    if address.startswith("unix://"):
        path = address[len("unix://"):]
        if not path:
            raise ValueError(f"empty unix socket path in {address!r}")
        return "unix", path
    if address.startswith("tcp://"):
        rest = address[len("tcp://"):]
        host, sep, port = rest.rpartition(":")
        if not sep or not host:
            raise ValueError(
                f"tcp address must be tcp://host:port, got {address!r}")
        return "tcp", (host, int(port))
    if address.startswith("/") or address.startswith("./"):
        return "unix", address
    raise ValueError(f"unsupported address {address!r} "
                     "(use unix:///path or tcp://host:port)")


def format_address(scheme: str, target) -> str:
    if scheme == "unix":
        return f"unix://{target}"
    host, port = target
    return f"tcp://{host}:{port}"


def listen(address: str, backlog: int = 16) -> Tuple[socket.socket, str]:
    """Bind + listen; returns ``(socket, resolved_address)``.

    Unix: a stale path from a previous (killed) server is unlinked
    before binding — the supervisor restarts replicas in place, and the
    restarted process must be able to reclaim its address. TCP with
    port 0 resolves to the kernel-assigned ephemeral port."""
    scheme, target = parse_address(address)
    if scheme == "unix":
        if os.path.exists(target):
            os.unlink(target)
        os.makedirs(os.path.dirname(target) or ".", exist_ok=True)
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        sock.bind(target)
        resolved = format_address("unix", target)
    else:
        host, port = target
        sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        sock.bind((host, port))
        resolved = format_address("tcp", (host, sock.getsockname()[1]))
    sock.listen(backlog)
    sock.setblocking(False)
    return sock, resolved


def connect(address: str, timeout_s: float = 5.0,
            retry_deadline_s: float = 0.0) -> "Connection":
    """Connect with a per-attempt timeout, retrying refusal/absence
    until ``retry_deadline_s`` wall seconds have passed (0 = a single
    attempt). Raises the last error when the deadline expires."""
    scheme, target = parse_address(address)
    deadline = time.monotonic() + retry_deadline_s
    while True:
        sock = socket.socket(
            socket.AF_UNIX if scheme == "unix" else socket.AF_INET,
            socket.SOCK_STREAM)
        sock.settimeout(timeout_s)
        try:
            sock.connect(target if scheme == "unix" else tuple(target))
            sock.settimeout(None)
            return Connection(sock, name=address)
        except (ConnectionRefusedError, FileNotFoundError,
                socket.timeout, TimeoutError, OSError):
            sock.close()
            if time.monotonic() >= deadline:
                raise
            time.sleep(min(0.05, max(deadline - time.monotonic(), 0.0)))


class Connection:
    """One established stream socket with explicit-timeout reads."""

    RECV_CHUNK = 1 << 16

    def __init__(self, sock: socket.socket, name: str = "?"):
        self._sock = sock
        self.name = name
        self.closed = False

    def fileno(self) -> int:
        return self._sock.fileno()

    def send(self, data: bytes) -> None:
        if self.closed:
            raise ConnectionClosed(f"{self.name}: connection closed")
        try:
            self._sock.sendall(data)
        except (BrokenPipeError, ConnectionResetError, OSError) as e:
            self.close()
            raise ConnectionClosed(f"{self.name}: send failed: {e}") from e

    def recv(self, timeout_s: Optional[float] = None) -> Optional[bytes]:
        """One read: bytes, ``None`` on timeout (no data — not an
        error), :class:`ConnectionClosed` on EOF or reset."""
        if self.closed:
            raise ConnectionClosed(f"{self.name}: connection closed")
        if timeout_s is not None and not self.poll(timeout_s):
            return None
        try:
            data = self._sock.recv(self.RECV_CHUNK)
        except (BlockingIOError, socket.timeout):
            return None
        except (ConnectionResetError, OSError) as e:
            self.close()
            raise ConnectionClosed(f"{self.name}: recv failed: {e}") from e
        if data == b"":
            self.close()
            raise ConnectionClosed(f"{self.name}: peer closed")
        return data

    def poll(self, timeout_s: float = 0.0) -> bool:
        """True when a read would return immediately (data or EOF)."""
        if self.closed:
            return False
        try:
            ready, _, _ = select.select([self._sock], [], [],
                                        max(timeout_s, 0.0))
        except (ValueError, OSError):
            return False
        return bool(ready)

    def close(self) -> None:
        if not self.closed:
            self.closed = True
            try:
                self._sock.close()
            except OSError:
                pass
