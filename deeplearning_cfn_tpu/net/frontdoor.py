"""Async front door: many client connections, one fleet, token streams.

The front door is the fleet's public socket. It multiplexes concurrent
client connections (loadgen drives it open-loop over real sockets) onto
the router, and streams tokens back as they decode — same frame
vocabulary as the per-replica servers, so one codec serves both planes:

    client ──SUBMIT──▶ front door ──router.submit──▶ replica sockets
    client ◀─TOKENS──  front door ◀──router.step───  (autonomous)

Concurrency model — one thread touches the router, ever:

- An asyncio event loop runs in a background thread. Connection
  handlers AND the driver task are coroutines on that loop, so every
  router call happens loop-thread-only; no locks.
- The driver task ticks ``router.step()`` continuously and publishes
  request snapshots to subscribed connections on change.
- Backpressure: each connection has a BOUNDED outbound queue. When a
  slow client fills it, intermediate snapshots are SKIPPED — every
  TOKENS frame carries the full cumulative token list, so dropping an
  intermediate frame loses granularity, never tokens.
- Overload is a first-class reply: ``FleetOverloadError`` /
  ``RateLimitError`` from ``router.submit`` become typed ERROR frames
  carrying ``retry_after_s`` and — while the fleet is browned out —
  the degradation controller's ``recovery_horizon_s``, round-tripped
  losslessly by :func:`~.codec.raise_error_header` client-side.
"""

from __future__ import annotations

import asyncio
import threading
from typing import Dict, Optional, Tuple

from .codec import FrameReader, FrameType, CodecError, encode_frame, \
    error_header
from .transport import parse_address
from ..fleet.router import FleetOverloadError, NoReplicasError
from ..serve.queue import OverloadError


class _ClientConn:
    __slots__ = ("writer", "reader", "outbox", "streams")

    def __init__(self, writer, max_queue: int):
        self.writer = writer
        self.reader = FrameReader()
        self.outbox: asyncio.Queue = asyncio.Queue(maxsize=max_queue)
        # logical rid → last published (state, n_tokens)
        self.streams: Dict[str, Tuple] = {}


class FrontDoor:
    """Serve the fleet on one listening socket until :meth:`stop`."""

    def __init__(self, router, address: str, max_queue: int = 64,
                 tick_interval_s: float = 0.002, on_tick=None):
        self.router = router
        self._requested_address = address
        self.address: Optional[str] = None   # resolved after start()
        self.max_queue = max_queue
        self.tick_interval_s = tick_interval_s
        self.on_tick = on_tick
        self.skipped_publishes = 0           # backpressure drops (frames)
        self.overload_rejects = 0
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._thread: Optional[threading.Thread] = None
        self._server = None
        self._conns: Dict[int, _ClientConn] = {}
        self._stopping = threading.Event()
        self._ready = threading.Event()
        self._startup_error: Optional[BaseException] = None

    # -- lifecycle -----------------------------------------------------------

    def start(self, timeout_s: float = 30.0) -> str:
        self._thread = threading.Thread(
            target=self._thread_main, name="net-frontdoor", daemon=True)
        self._thread.start()
        if not self._ready.wait(timeout_s):
            raise TimeoutError("front door failed to start in time")
        if self._startup_error is not None:
            raise self._startup_error
        return self.address

    def stop(self, timeout_s: float = 10.0) -> None:
        if self._loop is None:
            return
        self._stopping.set()
        if self._thread is not None:
            self._thread.join(timeout_s)

    def call(self, fn, timeout_s: float = 30.0):
        """Run ``fn(router)`` ON the loop thread (the only thread
        allowed to touch the router) and return its result."""
        async def _run():
            return fn(self.router)
        fut = asyncio.run_coroutine_threadsafe(_run(), self._loop)
        return fut.result(timeout_s)

    # -- loop thread ---------------------------------------------------------

    def _thread_main(self) -> None:
        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        self._loop = loop
        try:
            loop.run_until_complete(self._main())
        finally:
            loop.close()

    async def _main(self) -> None:
        try:
            scheme, target = parse_address(self._requested_address)
            if scheme == "unix":
                import os
                if os.path.exists(target):
                    os.unlink(target)
                os.makedirs(os.path.dirname(target) or ".", exist_ok=True)
                self._server = await asyncio.start_unix_server(
                    self._handle_conn, path=target)
                self.address = f"unix://{target}"
            else:
                host, port = target
                self._server = await asyncio.start_server(
                    self._handle_conn, host=host, port=port)
                port = self._server.sockets[0].getsockname()[1]
                self.address = f"tcp://{host}:{port}"
        except BaseException as e:  # surface bind errors to start()
            self._startup_error = e
            self._ready.set()
            return
        self._ready.set()
        driver = asyncio.ensure_future(self._drive())
        try:
            await driver
        finally:
            self._server.close()
            for conn in list(self._conns.values()):
                conn.writer.close()

    async def _drive(self) -> None:
        """The router's only caller: tick, publish, yield."""
        while not self._stopping.is_set():
            if self.on_tick is not None:
                self.on_tick(self.router)
            progress = self.router.step()
            self._publish()
            # Zero observed progress means the replica processes are
            # computing — sleep a tick instead of spinning the pumps.
            await asyncio.sleep(0 if progress > 0 else self.tick_interval_s)

    # -- per-connection handling ---------------------------------------------

    async def _handle_conn(self, reader, writer) -> None:
        conn = _ClientConn(writer, self.max_queue)
        self._conns[id(conn)] = conn
        sender = asyncio.ensure_future(self._send_loop(conn))
        try:
            while not self._stopping.is_set():
                data = await reader.read(1 << 16)
                if not data:
                    break
                conn.reader.feed(data)
                for frame in conn.reader:
                    self._dispatch(conn, frame)
        except (ConnectionError, CodecError, asyncio.CancelledError):
            pass
        finally:
            self._conns.pop(id(conn), None)
            sender.cancel()
            try:
                writer.close()
            except Exception:
                pass

    async def _send_loop(self, conn: _ClientConn) -> None:
        try:
            while True:
                data = await conn.outbox.get()
                conn.writer.write(data)
                await conn.writer.drain()
        except (ConnectionError, asyncio.CancelledError):
            pass

    def _enqueue(self, conn: _ClientConn, data: bytes,
                 must: bool = False) -> None:
        """Reply frames (``must``) always land — the queue is only
        bounded against runaway token streams; TOKENS publishes are
        skipped when the client reads too slowly."""
        try:
            conn.outbox.put_nowait(data)
        except asyncio.QueueFull:
            if must:
                # Evict one streamed snapshot to make room for a reply.
                try:
                    conn.outbox.get_nowait()
                except asyncio.QueueEmpty:
                    pass
                try:
                    conn.outbox.put_nowait(data)
                    return
                except asyncio.QueueFull:
                    pass
            self.skipped_publishes += 1

    # -- frame dispatch (loop thread — router access is safe) ----------------

    def _dispatch(self, conn: _ClientConn, frame) -> None:
        h = frame.header
        rid = h.get("rid")
        try:
            if frame.ftype == FrameType.SUBMIT:
                self._on_submit(conn, h, rid)
            elif frame.ftype == FrameType.CANCEL:
                ok = self.router.cancel(h["request_id"])
                self._enqueue(conn, encode_frame(
                    FrameType.CANCEL_OK, {"rid": rid, "ok": bool(ok)}),
                    must=True)
            elif frame.ftype == FrameType.HEALTH:
                self._enqueue(conn, encode_frame(
                    FrameType.HEALTH_OK,
                    {"rid": rid, "health": self.router.stats()}),
                    must=True)
            else:
                self._enqueue(conn, encode_frame(FrameType.ERROR,
                              error_header(ValueError(
                                  f"unexpected frame {frame.name}"),
                                  rid=rid)), must=True)
        except Exception as e:  # noqa: BLE001 — protocol boundary
            self._enqueue(conn, encode_frame(
                FrameType.ERROR, self._error_header(e, rid)), must=True)

    def _error_header(self, exc: BaseException, rid) -> Dict:
        horizon = None
        degrade = getattr(self.router, "degrade", None)
        if isinstance(exc, (OverloadError, FleetOverloadError,
                            NoReplicasError)) \
                and degrade is not None and degrade.level > 0:
            # Brownout honesty at the front door: tell the client how
            # long until the fleet expects to step back up, not just
            # how long until a queue drains.
            horizon = degrade.recovery_horizon_s()
        if isinstance(exc, OverloadError):
            self.overload_rejects += 1
        h = error_header(exc, rid=rid, recovery_horizon_s=horizon)
        if isinstance(exc, NoReplicasError):
            h["code"] = "no_replicas"
        return h

    def _on_submit(self, conn: _ClientConn, h: Dict, rid) -> None:
        kwargs = {k: h[k] for k in
                  ("max_new_tokens", "beam_size", "deadline_s",
                   "request_id", "tenant", "qos_class", "affinity_key")
                  if h.get(k) is not None}
        logical = self.router.submit(
            [int(t) for t in h["src_ids"]], **kwargs)
        conn.streams[logical] = ()
        self._enqueue(conn, encode_frame(
            FrameType.SUBMIT_OK,
            {"rid": rid, "req": {"id": logical, "state": "queued",
                                 "tokens": []}}), must=True)

    # -- publishing ----------------------------------------------------------

    def _publish(self) -> None:
        for conn in list(self._conns.values()):
            for logical in list(conn.streams):
                self._publish_one(conn, logical)

    def _publish_one(self, conn: _ClientConn, logical: str) -> None:
        try:
            snap = self.router.result(logical)
        except KeyError:
            conn.streams.pop(logical, None)
            return
        snap["id"] = logical
        key = (snap.get("state"), len(snap.get("tokens") or ()))
        if key == conn.streams.get(logical):
            return
        terminal = snap.get("state") in ("done", "cancelled", "expired")
        conn.streams[logical] = key
        self._enqueue(conn, encode_frame(
            FrameType.TOKENS, {"req": snap}), must=terminal)
        if terminal:
            conn.streams.pop(logical, None)


class FrontDoorClient:
    """Blocking client for the front door — what loadgen's open-loop
    driver threads (and the tests) speak. One socket, any number of
    in-flight streams; TTFB is observed CLIENT-side (submit send →
    first TOKENS frame with a token), which is the only honest place
    to measure it: it includes the wire, the front-door queue, routing,
    and the replica round-trip."""

    def __init__(self, address: str, connect_timeout_s: float = 5.0,
                 retry_deadline_s: float = 30.0, clock=None):
        import time as _time

        from .transport import connect
        self.clock = clock or _time.monotonic
        self._conn = connect(address, timeout_s=connect_timeout_s,
                             retry_deadline_s=retry_deadline_s)
        self._reader = FrameReader()
        self._rid = 0
        self._results: Dict[str, Dict] = {}    # logical id → last snapshot
        self.ttfb_s: Dict[str, float] = {}     # logical id → observed TTFB
        self._sent_at: Dict[str, float] = {}

    def close(self) -> None:
        self._conn.close()

    def _next_rid(self) -> str:
        self._rid += 1
        return f"fd-{self._rid}"

    def _pump(self, timeout_s: float) -> list:
        frames = []
        data = self._conn.recv(timeout_s=timeout_s)
        if data is not None:
            self._reader.feed(data)
            while self._conn.poll(0.0):
                more = self._conn.recv(timeout_s=0.0)
                if more is None:
                    break
                self._reader.feed(more)
        for frame in self._reader:
            if frame.ftype == FrameType.TOKENS:
                self._absorb(frame.header.get("req") or {})
            else:
                frames.append(frame)
        return frames

    def _absorb(self, snap: Dict) -> None:
        logical = snap.get("id")
        if logical is None:
            return
        self._results[logical] = snap
        if logical not in self.ttfb_s and snap.get("tokens") \
                and logical in self._sent_at:
            self.ttfb_s[logical] = \
                max(self.clock() - self._sent_at[logical], 0.0)

    def _rpc(self, ftype: int, header: Dict, timeout_s: float = 30.0):
        rid = self._next_rid()
        header = dict(header)
        header["rid"] = rid
        self._conn.send(encode_frame(ftype, header))
        deadline = self.clock() + timeout_s
        while True:
            remaining = deadline - self.clock()
            if remaining <= 0:
                raise TimeoutError(
                    f"front door: no reply to {FrameType.name(ftype)}")
            for frame in self._pump(min(remaining, 0.05)):
                if frame.header.get("rid") == rid:
                    if frame.ftype == FrameType.ERROR:
                        from .codec import raise_error_header
                        raise_error_header(frame.header)
                    return frame

    def submit(self, src_ids, **kwargs) -> str:
        """Submit one request; returns its logical id. Raises the exact
        overload exception the router raised (FleetOverloadError /
        RateLimitError / NoReplicasError) with ``retry_after_s`` and —
        under brownout — ``recovery_horizon_s`` intact."""
        header = {"src_ids": [int(t) for t in src_ids]}
        for key in ("max_new_tokens", "beam_size", "deadline_s",
                    "request_id", "tenant", "qos_class", "affinity_key"):
            if kwargs.get(key) is not None:
                header[key] = kwargs[key]
        sent = self.clock()
        reply = self._rpc(FrameType.SUBMIT, header)
        logical = reply.header["req"]["id"]
        self._sent_at[logical] = sent
        return logical

    def cancel(self, logical: str) -> bool:
        reply = self._rpc(FrameType.CANCEL, {"request_id": logical})
        return bool(reply.header.get("ok"))

    def health(self) -> Dict:
        reply = self._rpc(FrameType.HEALTH, {})
        return reply.header.get("health") or {}

    def result(self, logical: str) -> Optional[Dict]:
        return self._results.get(logical)

    def finished(self, logical: str) -> bool:
        snap = self._results.get(logical)
        return snap is not None and snap.get("state") in (
            "done", "cancelled", "expired")

    def wait(self, logicals, timeout_s: float = 120.0) -> Dict[str, Dict]:
        """Pump the stream until every id in ``logicals`` is terminal
        (or the deadline passes); returns id → final snapshot."""
        deadline = self.clock() + timeout_s
        pending = [l for l in logicals if not self.finished(l)]
        while pending and self.clock() < deadline:
            self._pump(0.05)
            pending = [l for l in pending if not self.finished(l)]
        return {l: self._results.get(l) for l in logicals}
