"""net/: the network serving plane — real processes, real sockets.

Promotes the in-process fleet simulation to a multi-process system:

- :mod:`.codec` — length-prefixed wire frames (submit / token stream /
  cancel / typed overload with ``retry_after_s`` / health / KV handoff)
- :mod:`.transport` — unix-domain (default) or TCP sockets with
  explicit timeouts and a connect-retry readiness barrier
- :mod:`.server` — one serve engine per child process, spawned through
  the launch/ supervisor (``python -m deeplearning_cfn_tpu.net.server``)
- :mod:`.client` — :class:`~.client.RemoteReplica`, the socket-backed
  EngineReplica duck type the unchanged fleet router drives
- :mod:`.router` — :class:`~.router.NetRouter`: reconnection tending,
  KV-handoff bytes over sockets, wall-clock drain
- :mod:`.frontdoor` — async front door multiplexing client connections
  with token streaming and bounded-queue backpressure
- :mod:`.bench` — the first wall-clock fleet bench record
"""

from .client import RemoteReplica
from .codec import FrameReader, FrameType, encode_frame
from .frontdoor import FrontDoor, FrontDoorClient
from .router import NetRouter
from .server import ReplicaServer
from .transport import Connection, ConnectionClosed, connect, listen

__all__ = [
    "Connection", "ConnectionClosed", "connect", "listen",
    "FrameReader", "FrameType", "encode_frame",
    "RemoteReplica", "NetRouter", "ReplicaServer",
    "FrontDoor", "FrontDoorClient",
]
