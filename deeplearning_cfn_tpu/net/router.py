"""NetRouter: the fleet router running over real processes.

Subclasses ``fleet.router.Router`` and overrides exactly three things —
everything else (policies, circuit breaker, backlog retry with jittered
backoff, QoS, brownout, evacuation, the phase ledger) runs unchanged
over :class:`~.client.RemoteReplica` objects, because they wear the
EngineReplica duck type:

- :meth:`step` first tends reconnections (a supervisor-restarted child
  re-binds its socket; a rate-limited ``try_connect`` readmits it) and
  polls the supervisor so hang-vs-crash classification and bounded
  restart happen on the fleet tick cadence;
- :meth:`_hand_off` moves the KV artifact as BYTES over the sockets
  (prefill export → decode import, no store round-trip — the wire IS
  the store here, and the npz member CRC still rejects corruption);
- :meth:`run_until_drained` replaces the base class's zero-progress
  wedge test with a WALL-CLOCK idle timeout: remote replicas compute
  between router ticks, so a tick that observed no tokens is normal,
  not a wedge. Only a continuous stretch of no progress, no placeable
  backlog, and no reconnectable replica counts as wedged.
"""

from __future__ import annotations

import time
from typing import Optional

from .client import RemoteReplica
from ..fleet.replica import ReplicaCrashed, ReplicaState
from ..fleet.router import Router
from ..serve.handoff import HandoffCorruptError
from ..serve.queue import DeadlineExceededError, OverloadError


class NetRouter(Router):
    """Router over socket-backed replicas (plus an optional supervisor
    whose ``poll()`` is driven from the fleet tick)."""

    def __init__(self, replicas, supervisor=None, sleep=time.sleep,
                 idle_probe_interval_s: float = 1.0, **kwargs):
        super().__init__(replicas, **kwargs)
        self.supervisor = supervisor
        self._sleep = sleep
        self.reconnects = 0
        self.idle_probe_interval_s = idle_probe_interval_s
        self._last_probe: dict = {}

    # -- stepping ------------------------------------------------------------

    def step(self) -> int:
        if self.supervisor is not None:
            # Reap/classify/restart dead children first, so a replica
            # the supervisor just restarted can be readmitted (and
            # receive backlog) within this same tick.
            self.supervisor.poll()
        self._tend_reconnections()
        return super().step()

    def _tend_reconnections(self) -> None:
        now = time.monotonic()
        for r in self._replicas.values():
            if not isinstance(r, RemoteReplica):
                continue
            if r.state is ReplicaState.HEALTHY and not r.busy:
                # Idle liveness probe. A busy replica's own RPC traffic
                # detects a dead child immediately, but the base router
                # never steps an idle replica — so a child that dies
                # (and is restarted on a fresh socket) behind an idle
                # connection would stay stale-HEALTHY forever, invisible
                # until the next placement lands on it. health() does a
                # live RPC whose failure flips the client state machine
                # to DOWN, which is exactly the trigger tending needs;
                # rate-limited so the probe doesn't turn the hot drain
                # loop into a health storm.
                if now - self._last_probe.get(r.id, 0.0) \
                        >= self.idle_probe_interval_s:
                    self._last_probe[r.id] = now
                    r.health()
            if r.state is ReplicaState.DOWN:
                # Reconnecting CLEARS the client's mirror table, so
                # settle the books first: requests that FINISHED on the
                # replica before it died keep pointing at their mirror
                # (evacuation deliberately skips them) — detach their
                # results now or they become unreadable. And a crash
                # first observed inside a swallowing path (health()
                # answers from cache so stats() always works) never
                # reached _mark_down — evacuate stragglers before
                # their mirrors vanish too. _mark_down is a no-op when
                # the evacuation already ran.
                self._absorb_finished(r)
                self._mark_down(r)
                if r.try_connect():
                    self.reconnects += 1

    def _absorb_finished(self, r: RemoteReplica) -> None:
        for lr in self._requests.values():
            if lr.replica_id != r.id or lr.replica_rid is None \
                    or lr.rid in self._detached:
                continue
            try:
                req = r.poll(lr.replica_rid)
            except (KeyError, ReplicaCrashed):
                continue
            if req is None or not req.finished:
                continue
            self._finalize(lr, req)
            out = req.to_dict()
            out["id"] = lr.rid
            out["replica"] = lr.replica_id
            self._detached[lr.rid] = out

    # -- disaggregated handoff: bytes over sockets ---------------------------

    def _hand_off(self, lr, rep) -> int:
        """One prefill→decode hop across the process boundary: export
        the parked stream's packed KV bytes from the prefill server,
        import them on the best decode server. Same bookkeeping as the
        in-process hop (handoffs, bytes, latencies, phase_prefix
        snapshot BEFORE release); the store round-trip is gone because
        the bytes already crossed a real wire."""
        t0 = self._clock()
        old_rid = lr.replica_rid
        try:
            prefill_req = rep.poll(old_rid)
            data = rep.export_handoff_bytes(old_rid)
        except ReplicaCrashed:
            self._mark_down(rep)
            return 0
        except (TimeoutError, KeyError):
            self.handoff_deferred += 1
            return 0
        if self._fault_plan is not None:
            for spec in self._fault_plan.consult("handoff.export", lr.rid):
                if spec.kind == "corrupt":
                    # Bit-flip mid-wire: the decode side's npz CRC
                    # rejects it — detect-and-reject, stream stays
                    # parked, re-exported next tick.
                    raw = bytearray(data)
                    raw[len(raw) // 2] ^= 0xFF
                    data = bytes(raw)
                elif spec.kind == "drop":
                    self.handoff_lost_rejects += 1
                    return 0
                else:
                    self.handoff_deferred += 1
                    return 0
        nbytes = len(data)
        candidates = [r for r in self._routable()
                      if getattr(r, "phase", "both") in ("decode", "both")]
        ordered = self.policy.order_for(
            [(r.id, r.health()) for r in candidates],
            self._affinity_for(lr))
        for rep_id in ordered:
            d = self._replicas[rep_id]
            lr.attempts += 1
            new_rid = f"{lr.rid}#a{lr.attempts}"
            qos_kwargs = {k: lr.spec[k] for k in ("tenant", "qos_class")
                          if lr.spec.get(k) is not None}
            if self._fault_plan is not None and any(
                    self._fault_plan.consult("handoff.import", rep_id)):
                self.handoff_deferred += 1
                continue
            try:
                d.import_handoff_bytes(data, request_id=new_rid,
                                       trace_id=lr.rid, **qos_kwargs)
            except HandoffCorruptError:
                self.handoff_corrupt_rejects += 1
                return 0
            except DeadlineExceededError:
                self.deadline_rejects += 1
                return 0
            except (OverloadError, TimeoutError):
                continue
            except ReplicaCrashed:
                self._mark_down(d)
                continue
            t_sub, t_adm = (prefill_req.submitted_at,
                            prefill_req.admitted_at)
            lr.phase_prefix = {
                "queue_wait_s": max(t_adm - t_sub, 0.0)
                if t_adm is not None else None,
                "prefill_s": prefill_req.prefill_s,
            }
            try:
                rep.release_handoff(old_rid)
            except ReplicaCrashed:
                self._mark_down(rep)
            except TimeoutError:
                pass
            lr.replica_id = rep_id
            lr.replica_rid = new_rid
            lr.hops.append(rep_id)
            dt = max(self._clock() - t0, 0.0)
            lr.handoff_s = (lr.handoff_s or 0.0) + dt
            lr.handoff_bytes = nbytes
            self.handoffs += 1
            self.handoff_bytes_total += nbytes
            self.handoff_latencies.append(dt)
            self.policy.note_routed(rep_id)
            self.routed[rep_id] = self.routed.get(rep_id, 0) + 1
            return 1
        return 0

    # -- draining ------------------------------------------------------------

    def run_until_drained(self, max_steps: int = 1_000_000,
                          idle_timeout_s: float = 30.0,
                          poll=None) -> int:
        """Step until every logical request is terminal. The wedge test
        is wall-clock: remote replicas decode between ticks, so only
        ``idle_timeout_s`` continuous seconds with zero observed
        progress AND nothing placeable AND nothing reconnecting counts
        as wedged. ``poll`` (optional) runs every tick — the bench
        threads burst submission through it."""
        steps = 0
        idle_since: Optional[float] = None
        while self.pending() and steps < max_steps:
            if poll is not None:
                poll()
            progress = self.step()
            steps += 1
            if progress > 0 or self._backlog_can_move():
                # A supervisor restart produces no progress for a few
                # seconds, then readmission + re-placed backlog resets
                # the timer — idle_timeout_s just has to outlast one
                # restart, NOT be immune to a permanently dead child.
                idle_since = None
                continue
            now = self._clock()
            if idle_since is None:
                idle_since = now
            elif now - idle_since >= idle_timeout_s:
                break
            # Zero observed progress: the children are computing. Yield
            # the core instead of spinning the RPC pump hot.
            self._sleep(0.002)
        leftover = self.pending()
        if leftover:
            self.dropped_requests += len(leftover)
        return steps

    def close(self) -> None:
        for r in self._replicas.values():
            if isinstance(r, RemoteReplica):
                r.close()
