"""Length-prefixed wire codec for the network serving plane.

One frame on the wire:

    u32 BE  payload length (bytes after this prefix)
    u8      protocol version (PROTOCOL_VERSION)
    u8      frame type (FrameType)
    u32 BE  header length
    bytes   JSON header (UTF-8)
    bytes   binary body (payload length - 6 - header length)

The header carries everything structured (request snapshots, health,
error details); the body carries bulk binary (the KV-handoff artifact,
packed with :func:`pack_artifact`). JSON over msgpack: the repo already
speaks JSONL everywhere (metrics, traces, ckpt manifests), the framed
binary body covers the one payload JSON would butcher, and a
reader can inspect a captured stream with nothing but stdlib.

Failure taxonomy, decided at the frame boundary so every caller agrees:

- **truncation is not an error** — :meth:`FrameReader.next` returns
  ``None`` until the bytes arrive (a half-open TCP stream looks exactly
  like a slow one until the transport says otherwise);
- :class:`FrameTooLarge` — the length prefix promises more than
  ``max_frame_bytes``; refused BEFORE buffering, so a corrupt or
  malicious prefix cannot balloon memory;
- :class:`VersionMismatch` — wrong protocol version; refuse, never
  guess;
- :class:`CorruptFrame` — the inner lengths disagree with the outer, or
  the header is not valid JSON: the stream is unusable from here on.

Typed error frames (:func:`error_header`/:func:`raise_error_header`)
round-trip the serve/fleet backpressure exceptions losslessly: a client
catching ``OverloadError`` sees the same ``retry_after_s``, the same
``FleetOverloadError.per_replica`` hint map, and the brownout
``recovery_horizon_s`` the router folded in — the wire changes the
transport, never the contract.
"""

from __future__ import annotations

import io
import json
import struct
from typing import Dict, Iterator, Optional, Tuple

import numpy as np

from ..serve.handoff import HandoffCorruptError, _decode_extension_dtypes, \
    _encode_extension_dtypes, validate_artifact
from ..serve.queue import DeadlineExceededError, OverloadError, \
    RateLimitError

PROTOCOL_VERSION = 1

#: Refuse frames above this size before buffering them. Generous: the
#: largest real payload is a KV-handoff artifact (tens of KB at bench
#: scale), so 64 MiB flags corruption, not legitimate traffic.
MAX_FRAME_BYTES = 64 * 1024 * 1024

_PREFIX = struct.Struct(">I")        # payload length
_INNER = struct.Struct(">BBI")       # version, ftype, header length


class FrameType:
    """Wire frame types. Requests carry a client-minted correlation id
    (``rid`` in the header); the matching ``*_OK`` (or ERROR) response
    echoes it. TOKENS frames are server-initiated pushes — no ``rid``."""

    SUBMIT = 1
    SUBMIT_OK = 2
    TOKENS = 3               # server push: request snapshot (token stream)
    CANCEL = 4
    CANCEL_OK = 5
    HEALTH = 6
    HEALTH_OK = 7
    ERROR = 8                # typed failure (overload, rate limit, ...)
    HANDOFF_EXPORT = 9       # body of the _OK: packed artifact bytes
    HANDOFF_EXPORT_OK = 10
    HANDOFF_IMPORT = 11      # body: packed artifact bytes
    HANDOFF_IMPORT_OK = 12
    HANDOFF_RELEASE = 13
    HANDOFF_RELEASE_OK = 14
    DRAIN = 15               # graceful: refuse new submits, finish in-flight
    DRAIN_OK = 16

    _NAMES = None

    @classmethod
    def name(cls, ftype: int) -> str:
        if cls._NAMES is None:
            cls._NAMES = {v: k for k, v in vars(cls).items()
                          if isinstance(v, int)}
        return cls._NAMES.get(ftype, f"type-{ftype}")


_VALID_TYPES = frozenset(
    v for k, v in vars(FrameType).items()
    if isinstance(v, int) and not k.startswith("_"))


class CodecError(ValueError):
    """Base class for wire-level failures."""


class FrameTooLarge(CodecError):
    def __init__(self, length: int, limit: int):
        super().__init__(
            f"frame of {length} bytes exceeds the {limit}-byte limit")
        self.length = length
        self.limit = limit


class VersionMismatch(CodecError):
    def __init__(self, got: int):
        super().__init__(
            f"protocol version {got} != {PROTOCOL_VERSION}")
        self.got = got


class CorruptFrame(CodecError):
    """The frame's internal structure is inconsistent — the stream
    cannot be trusted past this point."""


class Frame:
    __slots__ = ("ftype", "header", "body")

    def __init__(self, ftype: int, header: Dict, body: bytes = b""):
        self.ftype = ftype
        self.header = header
        self.body = body

    @property
    def name(self) -> str:
        return FrameType.name(self.ftype)

    def __repr__(self):
        return (f"Frame({self.name}, header={self.header!r}, "
                f"body={len(self.body)}B)")


def encode_frame(ftype: int, header: Dict, body: bytes = b"") -> bytes:
    """Serialize one frame, length prefix included."""
    if ftype not in _VALID_TYPES:
        raise CodecError(f"unknown frame type {ftype}")
    hdr = json.dumps(header, separators=(",", ":")).encode("utf-8")
    payload_len = _INNER.size + len(hdr) + len(body)
    return b"".join((
        _PREFIX.pack(payload_len),
        _INNER.pack(PROTOCOL_VERSION, ftype, len(hdr)),
        hdr, body))


def decode_payload(payload: bytes) -> Frame:
    """Decode one frame's payload (the bytes AFTER the length prefix)."""
    if len(payload) < _INNER.size:
        raise CorruptFrame(
            f"payload of {len(payload)} bytes is shorter than the "
            f"{_INNER.size}-byte frame header")
    version, ftype, hdr_len = _INNER.unpack_from(payload)
    if version != PROTOCOL_VERSION:
        raise VersionMismatch(version)
    if ftype not in _VALID_TYPES:
        raise CorruptFrame(f"unknown frame type {ftype}")
    if _INNER.size + hdr_len > len(payload):
        raise CorruptFrame(
            f"header length {hdr_len} overruns the "
            f"{len(payload)}-byte payload")
    hdr_bytes = payload[_INNER.size:_INNER.size + hdr_len]
    try:
        header = json.loads(hdr_bytes.decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise CorruptFrame(f"header is not valid JSON: {e}") from e
    if not isinstance(header, dict):
        raise CorruptFrame(
            f"header must be a JSON object, got {type(header).__name__}")
    return Frame(ftype, header, payload[_INNER.size + hdr_len:])


class FrameReader:
    """Incremental frame parser over an arbitrary byte stream.

    Feed it whatever the socket produced; :meth:`next` yields complete
    frames and returns ``None`` on a partial one (truncation is a
    transport condition, not a codec error). Structural failures raise
    and poison the reader — after a :class:`CodecError` the stream
    framing is lost, so the connection must be dropped.
    """

    def __init__(self, max_frame_bytes: int = MAX_FRAME_BYTES):
        self.max_frame_bytes = max_frame_bytes
        self._buf = bytearray()
        self._dead = False

    @property
    def buffered(self) -> int:
        return len(self._buf)

    def feed(self, data: bytes) -> None:
        if data:
            self._buf.extend(data)

    def next(self) -> Optional[Frame]:
        if self._dead:
            raise CorruptFrame("frame stream already failed")
        if len(self._buf) < _PREFIX.size:
            return None
        (payload_len,) = _PREFIX.unpack_from(self._buf)
        if payload_len > self.max_frame_bytes:
            self._dead = True
            raise FrameTooLarge(payload_len, self.max_frame_bytes)
        if len(self._buf) < _PREFIX.size + payload_len:
            return None
        payload = bytes(self._buf[_PREFIX.size:_PREFIX.size + payload_len])
        del self._buf[:_PREFIX.size + payload_len]
        try:
            return decode_payload(payload)
        except CodecError:
            self._dead = True
            raise

    def __iter__(self) -> Iterator[Frame]:
        while True:
            frame = self.next()
            if frame is None:
                return
            yield frame


# -- KV-handoff artifact body ------------------------------------------------


def pack_artifact(artifact: Dict[str, np.ndarray]) -> bytes:
    """Artifact dict → npz bytes for a frame body. Same codec the ckpt
    store uses (validate + extension-dtype byte views + npz with
    per-member CRC32), so corruption on the wire is detected exactly
    like corruption in the store."""
    validate_artifact(artifact)
    buf = io.BytesIO()
    np.savez(buf, **_encode_extension_dtypes(artifact))
    return buf.getvalue()


def unpack_artifact(data: bytes) -> Dict[str, np.ndarray]:
    """npz bytes → validated artifact dict. Any decode or validation
    failure raises :class:`~..serve.handoff.HandoffCorruptError` — the
    importer rejects, the exporter stays parked, the hop retries."""
    try:
        with np.load(io.BytesIO(data)) as npz:
            raw = {k: npz[k] for k in npz.files}
        artifact = _decode_extension_dtypes(raw)
        validate_artifact(artifact)
    except Exception as e:
        raise HandoffCorruptError(
            f"handoff artifact bytes are corrupt: {e}") from e
    return artifact


# -- typed error frames ------------------------------------------------------

#: header ``code`` values an ERROR frame may carry.
ERROR_CODES = ("rate_limit", "fleet_overload", "overload", "deadline",
               "draining", "no_replicas", "unknown_request",
               "handoff_corrupt", "invalid", "internal")


def error_header(exc: BaseException, rid: Optional[str] = None,
                 recovery_horizon_s: Optional[float] = None) -> Dict:
    """Map a server/router-side exception onto the typed ERROR header.

    The overload family is encoded losslessly — depth, max_depth,
    retry_after_s, the per-replica hint map, the rate-limited class and
    tenant — so :func:`raise_error_header` can rebuild the exact
    exception client-side. ``recovery_horizon_s`` threads the brownout
    controller's estimate through (None when the fleet is not
    degraded)."""
    h: Dict = {"message": str(exc)}
    if rid is not None:
        h["rid"] = rid
    if recovery_horizon_s is not None:
        h["recovery_horizon_s"] = recovery_horizon_s
    if isinstance(exc, RateLimitError):
        h.update(code="rate_limit", qos_class=exc.qos_class,
                 tenant=exc.tenant, retry_after_s=exc.retry_after_s,
                 depth=exc.depth, max_depth=exc.max_depth)
    elif isinstance(exc, OverloadError):
        per = getattr(exc, "per_replica", None)
        h.update(code="fleet_overload" if per is not None else "overload",
                 retry_after_s=exc.retry_after_s, depth=exc.depth,
                 max_depth=exc.max_depth)
        if per is not None:
            h["per_replica"] = per
    elif isinstance(exc, DeadlineExceededError):
        h["code"] = "deadline"
    elif isinstance(exc, KeyError):
        h["code"] = "unknown_request"
    elif isinstance(exc, HandoffCorruptError):
        # Before ValueError: a corrupt-artifact reject must come back
        # as HandoffCorruptError so the exporter stays parked and the
        # hop retries, same as an in-process corrupt reject.
        h["code"] = "handoff_corrupt"
    elif isinstance(exc, ValueError):
        h["code"] = "invalid"
    else:
        h["code"] = "internal"
    return h


def raise_error_header(h: Dict):
    """Rebuild and raise the exception an ERROR header encodes.

    The overload family comes back as the same class with the same
    attributes (the lossless round-trip the backpressure loops depend
    on); ``recovery_horizon_s``/``rid`` are attached as attributes when
    present. ``draining`` raises a plain OverloadError — to a router
    mid-placement it means exactly "try the next candidate"."""
    from ..fleet.router import FleetOverloadError, NoReplicasError

    code = h.get("code", "internal")
    msg = h.get("message", "")
    if code == "rate_limit":
        exc: BaseException = RateLimitError(
            h.get("qos_class", "standard"), h.get("tenant"),
            h.get("retry_after_s") or 0.0,
            h.get("depth", 0), h.get("max_depth", 0))
    elif code == "fleet_overload":
        exc = FleetOverloadError(
            h.get("depth", 0), h.get("max_depth", 0),
            h.get("retry_after_s"), per_replica=h.get("per_replica"))
    elif code in ("overload", "draining"):
        exc = OverloadError(h.get("depth", 0), h.get("max_depth", 0),
                            retry_after_s=h.get("retry_after_s"))
    elif code == "deadline":
        exc = DeadlineExceededError(msg)
    elif code == "no_replicas":
        exc = NoReplicasError(msg)
    elif code == "unknown_request":
        exc = KeyError(msg)
    elif code == "handoff_corrupt":
        exc = HandoffCorruptError(msg)
    elif code == "invalid":
        exc = ValueError(msg)
    else:
        exc = RuntimeError(msg or f"remote error ({code})")
    if h.get("recovery_horizon_s") is not None:
        exc.recovery_horizon_s = h["recovery_horizon_s"]
    if h.get("rid") is not None:
        exc.rid = h["rid"]
    raise exc


def read_frames(data: bytes) -> Tuple[list, int]:
    """Convenience for tests/tools: parse as many complete frames as
    ``data`` holds; returns (frames, bytes_consumed)."""
    reader = FrameReader()
    reader.feed(data)
    frames = list(reader)
    return frames, len(data) - reader.buffered
