"""The first REAL fleet bench: replicas are processes, wires are sockets.

Everything the in-process fleet bench measured on one thread and one
clock is re-measured here with actual process parallelism:

- ``net_decode_p95_colocated`` / ``net_decode_p95_disagg`` — the
  deferred PR 12 comparison, now wall-clock honest: prefill and decode
  really overlap across processes, and the KV artifact really crosses
  a socket.
- ``net_stream_ttfb_p50/p95`` — time-to-first-byte observed CLIENT-side
  through the async front door (wire + queue + routing + replica RTT).
- ``autoscale_time_to_scale_s`` — burst arrives, the fleet overloads,
  ``SupervisedSpawner`` forks a new replica server, and the clock runs
  until that replica is connected and routable. Real seconds: process
  spawn + jax import + model build + warmup + socket accept.

Honesty rules carried over from the in-process bench: parity against
the same seeded trace (greedy decode on bit-identical weights — every
server re-derives the weights from the same ``PRNGKey(seed)``), zero
dropped requests as a hard assertion, and every unmeasured record
field is ``None``, never 0.
"""

from __future__ import annotations

import os
import sys
import time
from typing import Dict, List, Optional, Tuple

from .client import RemoteReplica
from .router import NetRouter
from ..fleet.replica import ReplicaProcSpec, ReplicaSupervisor
from ..fleet.router import FleetOverloadError, NoReplicasError
from ..serve.queue import OverloadError

METRIC = "net_fleet_tiny_nmt_tokens_per_sec"
UNIT = "tokens/sec"

#: Record fields that must be null (never 0) when unmeasured — root
#: bench.py's ``_finalize_green`` nulls these on red/unmeasured runs.
NULLABLE_FIELDS = ("net_decode_p95_disagg", "net_decode_p95_colocated",
                   "autoscale_time_to_scale_s", "net_stream_ttfb_p50",
                   "net_stream_ttfb_p95")


def _percentile(values, pct: float) -> Optional[float]:
    vals = sorted(v for v in values if v is not None)
    if not vals:
        return None
    k = max(0, min(len(vals) - 1, int(round((pct / 100.0) * (len(vals) - 1)))))
    return float(vals[k])


def _child_env() -> Dict[str, str]:
    """The replica child inherits the parent's platform pin — the
    image's TPU plugin hangs in backend init, so an unpinned child
    would wedge the whole fleet at warmup."""
    env = {"JAX_PLATFORMS": os.environ.get("JAX_PLATFORMS", "cpu"),
           "JAX_ENABLE_X64": os.environ.get("JAX_ENABLE_X64", "0")}
    if os.environ.get("DLCFN_OBS_OFF"):
        env["DLCFN_OBS_OFF"] = os.environ["DLCFN_OBS_OFF"]
    return env


def make_server_spec(replica_id: str, run_dir: str, phase: str = "both",
                     slots: int = 2, src_len: int = 8,
                     max_new_tokens: int = 4, queue_depth: int = 16,
                     decode_window: int = 4, kv_block_size: int = 0,
                     seed: int = 0, warmup_src=(),
                     trace: bool = False) -> Tuple[ReplicaProcSpec, str]:
    """Build the (spec, address) pair for one replica server child.
    Unix socket in the replica's run dir: zero port arithmetic, and a
    supervisor-restarted child reclaims the same address."""
    address = f"unix://{os.path.join(run_dir, 'replica.sock')}"
    argv = [sys.executable, "-m", "deeplearning_cfn_tpu.net.server",
            "--listen", address,
            "--replica-id", replica_id,
            "--slots", str(slots),
            "--src-len", str(src_len),
            "--max-new-tokens", str(max_new_tokens),
            "--queue-depth", str(queue_depth),
            "--decode-window", str(decode_window),
            "--kv-block-size", str(kv_block_size),
            "--phase", phase,
            "--seed", str(seed)]
    if warmup_src:
        argv += ["--warmup-src", ",".join(str(int(t)) for t in warmup_src)]
    if trace:
        argv += ["--run-dir", run_dir]
    return ReplicaProcSpec(replica_id, argv, run_dir,
                           env=_child_env()), address


def spawn_process_fleet(run_root: str, phases: List[str],
                        connect_deadline_s: float = 180.0,
                        max_restarts: int = 1, trace: bool = False,
                        **engine_kwargs
                        ) -> Tuple[ReplicaSupervisor, List[RemoteReplica]]:
    """Spawn one server process per phase entry and connect to all.
    Children build + warm in PARALLEL; connect order doesn't matter
    because the first successful connect per child is its readiness
    barrier."""
    specs, addrs = [], []
    for i, phase in enumerate(phases):
        rid = f"r{i}"
        run_dir = os.path.join(run_root, rid)
        os.makedirs(run_dir, exist_ok=True)
        spec, addr = make_server_spec(rid, run_dir, phase=phase,
                                      trace=trace, **engine_kwargs)
        specs.append(spec)
        addrs.append((rid, addr, phase))
    sup = ReplicaSupervisor(specs, max_restarts=max_restarts)
    sup.start()
    replicas = []
    try:
        for rid, addr, phase in addrs:
            replicas.append(RemoteReplica(
                rid, addr, phase=phase,
                connect_retry_deadline_s=connect_deadline_s).connect())
    except BaseException:
        for r in replicas:
            r.close()
        sup.terminate()
        raise
    return sup, replicas


def _submit_all(router, trace, max_new_tokens: int, beam_size: int,
                sup=None) -> List[str]:
    """Submit the whole seeded trace with the fleet-bench retry loop:
    overload → tick the fleet (draining queues) → retry."""
    rids = []
    for i, src in enumerate(trace):
        while True:
            try:
                rids.append(router.submit(
                    src, max_new_tokens=max_new_tokens,
                    beam_size=beam_size, request_id=f"q{i}"))
                break
            except (FleetOverloadError, OverloadError, NoReplicasError):
                if sup is not None:
                    sup.poll()
                router.step()
                time.sleep(0.01)
    return rids


def _decode_p95(router, rids) -> Optional[float]:
    vals = []
    for rid in rids:
        entry = router.ledger.get(rid)
        if entry is None:
            continue
        decode = (entry.get("phases") or {}).get("decode_s")
        if decode is not None:
            vals.append(decode)
    return _percentile(vals, 95)


def _reference_tokens(trace, max_new_tokens: int, beam_size: int,
                      slots: int, src_len: int, queue_depth: int,
                      decode_window: int, seed: int) -> Dict[str, List[int]]:
    """In-process fleet on the SAME seeded trace — the parity baseline.
    Same model geometry, same ``PRNGKey(seed)`` init the server children
    use, run through the plain in-process Router."""
    import jax
    import numpy as np

    from ..fleet.replica import EngineReplica
    from ..fleet.router import Router
    from ..models.transformer_nmt import transformer_nmt_tiny
    from ..runtime.platform import enable_partitionable_rng
    from ..serve.engine import Engine

    # The server children run under honor_env_platform(), which pins
    # layout-invariant RNG — model.init derives DIFFERENT bits under
    # the two threefry modes, so the parity reference must pin the same
    # mode or "identical weights by construction" silently breaks.
    enable_partitionable_rng()
    model = transformer_nmt_tiny(vocab_size=96, max_len=64)
    init = model.init(jax.random.PRNGKey(seed),
                      np.zeros((1, src_len), np.int32),
                      np.ones((1, src_len), np.int32),
                      np.zeros((1, src_len), np.int32), train=False)
    variables = {"params": init["params"]}

    def _engine():
        return Engine(model, variables, capacity=slots,
                      max_src_len=src_len, queue_depth=queue_depth,
                      default_max_new_tokens=max_new_tokens,
                      decode_window=decode_window)

    replicas = [EngineReplica(f"ref{i}", _engine()) for i in range(2)]
    rt = Router(replicas)
    rids = _submit_all(rt, trace, max_new_tokens, beam_size)
    rt.run_until_drained()
    return {rid: list(rt.result(rid)["tokens"]) for rid in rids}


def _tokens_identical(router, rids, expected: Dict[str, List[int]]) -> bool:
    for rid in rids:
        if list(router.result(rid)["tokens"]) != expected.get(rid):
            return False
    return True


def run_net_fleet_bench(run_root: str, smoke: bool = True,
                        replicas: int = 2, num_requests: int = 6,
                        slots: int = 2, max_new_tokens: int = 4,
                        src_len: int = 8, queue_depth: int = 16,
                        decode_window: int = 4, beam_size: int = 1,
                        policy: str = "least_loaded",
                        disagg: bool = True, chaos_kill: bool = False,
                        autoscale: bool = False, seed: int = 0,
                        trace_dir: str = "",
                        idle_timeout_s: float = 60.0) -> Dict:
    """The ``bench --fleet --net`` record. Phases:

    1. in-process reference run (parity baseline),
    2. co-located process fleet driven through the async front door
       (→ throughput, ``net_decode_p95_colocated``, client-side TTFB,
       optional mid-stream SIGKILL),
    3. disaggregated process fleet, KV bytes over sockets
       (→ ``net_decode_p95_disagg``),
    4. optional burst autoscale (→ ``autoscale_time_to_scale_s``).
    """
    from ..serve.bench import _fixed_trace

    if smoke:
        replicas = 2
        num_requests = min(num_requests, 6)
        slots = min(slots, 2)
        max_new_tokens = min(max_new_tokens, 4)
        src_len = min(src_len, 8)
    trace = _fixed_trace(num_requests, src_len, 96, seed=seed)
    engine_kwargs = dict(slots=slots, src_len=src_len,
                         max_new_tokens=max_new_tokens,
                         queue_depth=queue_depth,
                         decode_window=decode_window, seed=seed,
                         warmup_src=trace[0])
    expected = _reference_tokens(trace, max_new_tokens, beam_size, slots,
                                 src_len, queue_depth, decode_window, seed)

    record: Dict = {
        "metric": METRIC, "value": None, "unit": UNIT,
        "vs_baseline": None, "mfu": None, "measured": True,
        "net": True, "transport": "unix", "smoke": bool(smoke),
        "replicas": replicas, "policy": policy,
        "requests": num_requests, "slots": slots,
        "max_new_tokens": max_new_tokens, "src_len": src_len,
        "decode_window": decode_window, "beam_size": beam_size,
        "dropped_requests": 0, "evacuations": 0, "reconnects": 0,
        "chaos_kills": 0, "token_identical": None,
        "token_identical_disagg": None,
        "handoffs": None, "handoff_bytes": None,
        "handoff_latency_p50_s": None, "handoff_latency_p95_s": None,
        "trace_dir": trace_dir or None, "flow_events": None,
    }
    for field in NULLABLE_FIELDS:
        record[field] = None

    # -- phase 2: co-located fleet behind the front door ---------------------
    colo_root = os.path.join(run_root, "colocated")
    sup, remotes = spawn_process_fleet(
        colo_root, ["both"] * replicas, trace=bool(trace_dir),
        **engine_kwargs)
    try:
        record.update(_run_colocated(
            sup, remotes, trace, expected, record, colo_root,
            max_new_tokens=max_new_tokens, beam_size=beam_size,
            policy=policy, chaos_kill=chaos_kill, trace_dir=trace_dir,
            idle_timeout_s=idle_timeout_s))
    finally:
        _teardown(sup, remotes)

    # -- phase 3: disaggregated fleet, KV bytes over sockets -----------------
    if disagg:
        disagg_root = os.path.join(run_root, "disagg")
        dk = dict(engine_kwargs)
        dk["kv_block_size"] = 4
        sup, remotes = spawn_process_fleet(
            disagg_root, ["prefill"] + ["decode"] * (replicas - 1), **dk)
        try:
            rt = NetRouter(remotes, supervisor=sup, policy=policy)
            rids = _submit_all(rt, trace, max_new_tokens, beam_size, sup)
            rt.run_until_drained(idle_timeout_s=idle_timeout_s)
            record["dropped_requests"] += rt.dropped_requests
            record["token_identical_disagg"] = \
                _tokens_identical(rt, rids, expected)
            record["net_decode_p95_disagg"] = _decode_p95(rt, rids)
            record["handoffs"] = rt.handoffs
            record["handoff_bytes"] = rt.handoff_bytes_total or None
            record["handoff_latency_p50_s"] = \
                _percentile(rt.handoff_latencies, 50)
            record["handoff_latency_p95_s"] = \
                _percentile(rt.handoff_latencies, 95)
        finally:
            _teardown(sup, remotes)

    # -- phase 4: burst autoscale (real wall-clock time-to-scale) ------------
    if autoscale:
        record["autoscale_time_to_scale_s"] = _run_autoscale(
            os.path.join(run_root, "autoscale"), trace, record,
            max_new_tokens=max_new_tokens, beam_size=beam_size,
            policy=policy, idle_timeout_s=idle_timeout_s,
            engine_kwargs=engine_kwargs)

    if trace_dir:
        from ..obs.export import export_fleet_trace
        os.makedirs(trace_dir, exist_ok=True)
        summary = export_fleet_trace(
            colo_root, os.path.join(trace_dir, "net_fleet_trace.json"))
        record["flow_events"] = summary.get("flow_events")
        record["trace_dir"] = trace_dir

    try:
        import jax
        record["device"] = jax.default_backend()
    except Exception:
        record["device"] = None
    return record


def _run_colocated(sup, remotes, trace, expected, record, run_root,
                   max_new_tokens: int, beam_size: int, policy: str,
                   chaos_kill: bool, trace_dir: str,
                   idle_timeout_s: float) -> Dict:
    from .frontdoor import FrontDoor, FrontDoorClient
    from ..metrics.jsonl import MetricsWriter
    from ..obs.sinks import JsonlSink

    rt = NetRouter(remotes, supervisor=sup, policy=policy)
    router_writer = None
    if trace_dir:
        # Parent-side shard: fleet.request spans land in router.jsonl
        # at the run root; each child's serve.request spans land in its
        # own <rid>/metrics.jsonl — the merged Perfetto export links
        # them by trace_id ACROSS pids.
        router_writer = MetricsWriter(
            os.path.join(run_root, "router.jsonl"), also_stdout=False)
        rt.trace_sink = JsonlSink(router_writer)
        for r in remotes:
            client_writer = MetricsWriter(
                os.path.join(run_root, r.id, "client.jsonl"),
                also_stdout=False)
            r.trace_sink = JsonlSink(client_writer)

    fd = FrontDoor(rt, f"unix://{os.path.join(run_root, 'frontdoor.sock')}")
    out: Dict = {}
    killed = 0
    t0 = time.monotonic()
    try:
        fd.start()
        client = FrontDoorClient(fd.address)
        try:
            logicals = []
            for i, src in enumerate(trace):
                while True:
                    try:
                        logicals.append(client.submit(
                            src, max_new_tokens=max_new_tokens,
                            beam_size=beam_size, request_id=f"q{i}"))
                        break
                    except (FleetOverloadError, OverloadError,
                            NoReplicasError) as e:
                        time.sleep(min(getattr(e, "retry_after_s", None)
                                       or 0.02, 0.2))
            if chaos_kill and len(remotes) > 1:
                # SIGKILL a replica process mid-stream: the dead socket
                # marks it DOWN, the router evacuates, the supervisor
                # restarts it, and the zero-drop contract still holds.
                client.wait(logicals[:1], timeout_s=60.0)
                sup._replicas[1].handle._procs[0].proc.kill()
                killed = 1
            results = client.wait(logicals, timeout_s=300.0)
            wall = max(time.monotonic() - t0, 1e-9)
            goodput = sum(len((r or {}).get("tokens") or ())
                          for r in results.values())
            unfinished = [l for l, r in results.items()
                          if r is None or r.get("state") != "done"]
            stats = fd.call(lambda router: router.stats())
            ledger = fd.call(lambda router: {
                rid: dict(router.ledger.get(rid) or {})
                for rid in logicals})
            tokens = fd.call(lambda router: {
                rid: list(router.result(rid)["tokens"])
                for rid in logicals})
            out["value"] = goodput / wall
            out["net_decode_p95_colocated"] = _percentile(
                [(e.get("phases") or {}).get("decode_s")
                 for e in ledger.values()], 95)
            ttfbs = [client.ttfb_s.get(l) for l in logicals]
            out["net_stream_ttfb_p50"] = _percentile(ttfbs, 50)
            out["net_stream_ttfb_p95"] = _percentile(ttfbs, 95)
            out["token_identical"] = all(
                tokens.get(rid) == expected.get(rid) for rid in logicals)
            out["dropped_requests"] = record["dropped_requests"] \
                + stats["dropped_requests"] + len(unfinished)
            out["evacuations"] = stats["evacuations"]
            out["reconnects"] = fd.call(
                lambda router: getattr(router, "reconnects", 0))
            out["chaos_kills"] = killed
            out["goodput_tokens"] = goodput
        finally:
            client.close()
    finally:
        fd.stop()
        if router_writer is not None:
            router_writer.close()
    return out


def _run_autoscale(run_root: str, trace, record, max_new_tokens: int,
                   beam_size: int, policy: str, idle_timeout_s: float,
                   engine_kwargs: Dict) -> Optional[float]:
    """Start ONE replica, submit the burst until it overloads, then
    spawn a second through SupervisedSpawner and measure wall-clock
    burst-start → new-replica-routable. This is the number the
    in-process autoscaler could only simulate: it includes process
    fork, jax import, model build, warmup, and the socket accept."""
    from ..fleet.autoscale import SupervisedSpawner

    os.makedirs(run_root, exist_ok=True)
    # The burst must actually overload one replica or there is nothing
    # to scale from: tight queue (2 slots + 2 queued → the 5th
    # concurrent submit trips FleetOverloadError), a 4x-repeated trace,
    # and a heavier decode budget so the single replica cannot simply
    # outrun the submission loop.
    burst_tokens = max(int(max_new_tokens) * 4, 16)
    # decode_window=1: the server answers RPCs once per engine-step
    # loop, so each routed submit (health + submit RPC) lets it advance
    # ~2 steps. At window 4 a 16-token request drains in 4 steps — one
    # request per submit, the queue never fills. At window 1 it takes
    # 16 steps, the burst genuinely outruns the replica.
    engine_kwargs = dict(engine_kwargs, queue_depth=2,
                         max_new_tokens=burst_tokens, decode_window=1)
    burst = [src for _ in range(4) for src in trace]
    sup, remotes = spawn_process_fleet(run_root, ["both"],
                                       **engine_kwargs)
    spawner = None
    extra: List[RemoteReplica] = []
    try:
        rt = NetRouter(remotes, supervisor=sup, policy=policy)

        def spec_factory(phase, replica_id):
            run_dir = os.path.join(run_root, replica_id)
            os.makedirs(run_dir, exist_ok=True)
            spec, _ = make_server_spec(
                replica_id, run_dir, phase=phase, **engine_kwargs)
            return spec

        def replica_factory(phase, replica_id):
            addr = f"unix://{os.path.join(run_root, replica_id, 'replica.sock')}"
            return RemoteReplica(replica_id, addr, phase=phase,
                                 connect_retry_deadline_s=180.0)

        spawner = SupervisedSpawner(spec_factory, replica_factory)
        burst_t0 = time.monotonic()
        time_to_scale = None
        rids = []
        for i, src in enumerate(burst):
            while True:
                try:
                    rids.append(rt.submit(
                        src, max_new_tokens=burst_tokens,
                        beam_size=beam_size, request_id=f"b{i}"))
                    break
                except (FleetOverloadError, OverloadError):
                    if time_to_scale is None:
                        # First overload under the burst: scale out.
                        new = spawner.spawn("both", "r-scale")
                        new.connect()   # blocks until built + warm
                        rt.add(new)
                        extra.append(new)
                        time_to_scale = time.monotonic() - burst_t0
                    rt.step()
                    time.sleep(0.01)
                except NoReplicasError:
                    rt.step()
                    time.sleep(0.01)
        rt.run_until_drained(idle_timeout_s=idle_timeout_s)
        record["dropped_requests"] += rt.dropped_requests
        record["replicas_initial"] = 1
        record["replicas_final"] = 1 + len(extra)
        return time_to_scale
    finally:
        for r in extra:
            r.close()
        if spawner is not None:
            spawner.close()
        _teardown(sup, remotes)


def _teardown(sup, remotes) -> None:
    for r in remotes:
        try:
            r.drain()
        except Exception:
            pass
        r.close()
    try:
        sup.wait(timeout_s=10.0)
    except Exception:
        pass
    sup.terminate()
    sup.close()
