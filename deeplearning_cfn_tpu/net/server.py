"""Per-replica network server: one serve engine behind a socket.

Runs as a child process (``python -m deeplearning_cfn_tpu.net.server``)
spawned through the launch/ Transport + ReplicaSupervisor machinery —
the supervisor's hang-vs-crash classification and bounded restart apply
to it unchanged, because from the launcher's point of view this is just
another single-host job.

Lifecycle (the readiness barrier):

1. pin the jax platform from the environment (the image's TPU plugin
   hangs in backend init; the parent pins ``JAX_PLATFORMS``),
2. build the tiny NMT engine EXACTLY as fleet/bench.py does (same
   ``model.init`` seed → bit-identical weights → cross-process token
   parity is by construction),
3. warm it (submit one full-budget request, drain, release a parked
   prefill) so every fused decode shape is compiled OUTSIDE any timed
   window,
4. only THEN bind the listen socket. A client's first successful
   connect therefore means "engine ready" — no separate readiness RPC.

The serve loop is autonomous: the server steps its own engine whenever
it has work, which is the entire point of the net/ subsystem — N
replicas really do decode in parallel, one process each, instead of
taking turns inside one router thread. Clients observe progress through
TOKENS push frames (full request snapshot per update; budgets are tens
of tokens, so full-list is simpler than deltas and cannot drift).

Shutdown is deadline-honest: SIGTERM (or a DRAIN frame) stops new
admissions — submits are refused with a typed ``draining`` error —
while in-flight streams finish; the process exits 0 when idle or when
``--drain-grace-s`` expires, whichever is first.
"""

from __future__ import annotations

import argparse
import os
import signal
import sys
import time
from typing import Dict, List, Optional, Tuple

from .codec import FrameReader, FrameType, CodecError, encode_frame, \
    error_header, pack_artifact, unpack_artifact
from .transport import Connection, ConnectionClosed, listen
from ..serve.handoff import HandoffCorruptError
from ..serve.queue import DeadlineExceededError, OverloadError


class _Watch:
    """One client connection and the request streams it subscribed to."""

    def __init__(self, conn: Connection):
        self.conn = conn
        self.reader = FrameReader()
        # request_id → last published (state, n_tokens, preemptions,
        # prefill_chunks) so only actual progress crosses the wire.
        self.streams: Dict[str, Tuple] = {}


class ReplicaServer:
    """Serve one engine over a listening socket until drained."""

    def __init__(self, engine, address: str, replica_id: str = "replica",
                 drain_grace_s: float = 30.0, idle_wait_s: float = 0.01,
                 clock=time.monotonic):
        self.engine = engine
        self.replica_id = replica_id
        self.drain_grace_s = drain_grace_s
        self.idle_wait_s = idle_wait_s
        self.clock = clock
        self.steps = 0
        self._draining = False
        self._drain_deadline: Optional[float] = None
        self._watches: List[_Watch] = []
        # Bind LAST (see module docstring): the engine behind this
        # server is already built and warm when listen() succeeds.
        self._listen_sock, self.address = listen(address)

    # -- lifecycle ----------------------------------------------------------

    def install_signal_handlers(self) -> None:
        for sig in (signal.SIGTERM, signal.SIGINT):
            signal.signal(sig, lambda *_: self.request_drain())

    def request_drain(self) -> None:
        if not self._draining:
            self._draining = True
            self._drain_deadline = self.clock() + self.drain_grace_s

    def _busy(self) -> bool:
        """Work the engine can make progress on THIS tick. Parked
        handoffs deliberately excluded: stepping an engine whose only
        work is parked streams is a hot no-op."""
        return self.engine.queue.depth > 0 \
            or self.engine.active_requests > 0

    def _drained(self) -> bool:
        """Drain-exit: nothing running, queued, OR parked — a parked
        stream's KV blocks must stay alive until the router moves it."""
        return not self._busy() \
            and getattr(self.engine, "handoff_pending", 0) == 0

    def serve_forever(self) -> int:
        """The replica loop; returns the process exit code."""
        try:
            while True:
                if self._draining:
                    if self._drained():
                        return 0
                    if self.clock() >= self._drain_deadline:
                        return 0
                busy = self._busy()
                self._pump(0.0 if busy else self.idle_wait_s)
                if self._busy():
                    self.engine.step()
                    self.steps += 1
                self._publish()
        finally:
            self._close()

    def tick(self) -> None:
        """One loop iteration (tests drive the server in-process)."""
        self._pump(0.0)
        if self._busy():
            self.engine.step()
            self.steps += 1
        self._publish()

    def _close(self) -> None:
        for w in self._watches:
            w.conn.close()
        self._watches = []
        try:
            self._listen_sock.close()
        except OSError:
            pass

    # -- socket pump --------------------------------------------------------

    def _pump(self, wait_s: float) -> None:
        import select

        socks = [self._listen_sock] + [w.conn for w in self._watches
                                       if not w.conn.closed]
        try:
            ready, _, _ = select.select(socks, [], [], wait_s)
        except (ValueError, OSError):
            ready = []
        for sock in ready:
            if sock is self._listen_sock:
                self._accept()
            else:
                self._read(sock)
        self._watches = [w for w in self._watches if not w.conn.closed]

    def _accept(self) -> None:
        try:
            raw, _ = self._listen_sock.accept()
        except (BlockingIOError, OSError):
            return
        raw.setblocking(True)
        self._watches.append(
            _Watch(Connection(raw, name=f"{self.replica_id}-client")))

    def _read(self, conn: Connection) -> None:
        watch = next((w for w in self._watches if w.conn is conn), None)
        if watch is None:
            return
        try:
            while conn.poll(0.0):
                data = conn.recv()
                if data is None:
                    break
                watch.reader.feed(data)
            for frame in watch.reader:
                self._dispatch(watch, frame)
        except ConnectionClosed:
            # Client gone. Its in-flight streams keep decoding — the
            # router owns retry/evacuation policy, not this server.
            conn.close()
        except CodecError:
            # Framing lost (corrupt/oversized frame): the stream cannot
            # be re-synchronized — drop the connection.
            conn.close()

    def _send(self, watch: _Watch, data: bytes) -> None:
        try:
            watch.conn.send(data)
        except ConnectionClosed:
            pass

    def _error(self, watch: _Watch, exc: BaseException,
               rid: Optional[str]) -> None:
        self._send(watch, encode_frame(
            FrameType.ERROR, error_header(exc, rid=rid)))

    # -- frame dispatch ------------------------------------------------------

    def _dispatch(self, watch: _Watch, frame) -> None:
        h = frame.header
        rid = h.get("rid")
        try:
            if frame.ftype == FrameType.SUBMIT:
                self._on_submit(watch, h, rid)
            elif frame.ftype == FrameType.CANCEL:
                ok = self.engine.cancel(h["request_id"])
                self._send(watch, encode_frame(
                    FrameType.CANCEL_OK, {"rid": rid, "ok": bool(ok)}))
            elif frame.ftype == FrameType.HEALTH:
                self._send(watch, encode_frame(
                    FrameType.HEALTH_OK,
                    {"rid": rid, "health": self.health()}))
            elif frame.ftype == FrameType.HANDOFF_EXPORT:
                artifact = self.engine.export_handoff(h["request_id"])
                self._send(watch, encode_frame(
                    FrameType.HANDOFF_EXPORT_OK, {"rid": rid},
                    body=pack_artifact(artifact)))
            elif frame.ftype == FrameType.HANDOFF_IMPORT:
                artifact = unpack_artifact(frame.body)
                req = self.engine.import_handoff(
                    artifact, h["request_id"],
                    trace_id=h.get("trace_id"),
                    **{k: h[k] for k in ("tenant", "qos_class")
                       if h.get(k) is not None})
                watch.streams.setdefault(req.id, ())
                self._send(watch, encode_frame(
                    FrameType.HANDOFF_IMPORT_OK,
                    {"rid": rid, "req": self._snapshot(req)}))
            elif frame.ftype == FrameType.HANDOFF_RELEASE:
                self.engine.release_handoff(h["request_id"])
                self._send(watch, encode_frame(
                    FrameType.HANDOFF_RELEASE_OK, {"rid": rid}))
            elif frame.ftype == FrameType.DRAIN:
                self.request_drain()
                self._send(watch, encode_frame(
                    FrameType.DRAIN_OK, {"rid": rid}))
            else:
                self._error(watch, ValueError(
                    f"unexpected frame {frame.name}"), rid)
        except (OverloadError, DeadlineExceededError, KeyError,
                HandoffCorruptError, ValueError) as e:
            self._error(watch, e, rid)
        except Exception as e:  # noqa: BLE001 — protocol boundary
            self._error(watch, e, rid)

    def _on_submit(self, watch: _Watch, h: Dict,
                   rid: Optional[str]) -> None:
        if self._draining:
            # Typed refusal: to a router mid-placement this means "try
            # the next candidate" — OverloadError semantics, surfaced
            # with its own code so operators can tell drain from load.
            eh = error_header(
                OverloadError(self.engine.queue.depth,
                              self.engine.queue.max_depth), rid=rid)
            eh["code"] = "draining"
            eh["message"] = f"replica {self.replica_id} is draining"
            self._send(watch, encode_frame(FrameType.ERROR, eh))
            return
        kwargs = {k: h[k] for k in
                  ("max_new_tokens", "beam_size", "deadline_s",
                   "request_id", "trace_id", "tenant", "qos_class")
                  if h.get(k) is not None}
        req = self.engine.submit(list(h["src_ids"]), **kwargs)
        watch.streams.setdefault(req.id, ())
        self._send(watch, encode_frame(
            FrameType.SUBMIT_OK, {"rid": rid, "req": self._snapshot(req)}))

    # -- token streaming -----------------------------------------------------

    @staticmethod
    def _snapshot(req) -> Dict:
        """Full request snapshot: the tokens AND the lifecycle
        timestamps. CLOCK_MONOTONIC is system-wide on Linux, so these
        timestamps and the parent router's clock share one timeline —
        the phase ledger stays valid across the process boundary."""
        return {
            "id": req.id,
            "state": req.state.value,
            "tokens": [int(t) for t in req.tokens],
            "submitted_at": req.submitted_at,
            "admitted_at": req.admitted_at,
            "first_token_at": req.first_token_at,
            "finished_at": req.finished_at,
            "prefill_s": req.prefill_s,
            "prefill_chunks": req.prefill_chunks,
            "preemptions": req.preemptions,
            "preempted_s": req.preempted_s,
            "beam_size": req.beam_size,
            "max_new_tokens": req.max_new_tokens,
            "deadline": req.deadline,
            "tenant": req.tenant,
            "qos_class": req.qos_class,
            "trace_id": req.trace_id,
        }

    def _publish(self) -> None:
        for watch in self._watches:
            if watch.conn.closed:
                continue
            for req_id in list(watch.streams):
                self._publish_one(watch, req_id)

    def _publish_one(self, watch: _Watch, req_id: str) -> None:
        try:
            req = self.engine.poll(req_id)
        except KeyError:
            watch.streams.pop(req_id, None)
            return
        key = (req.state.value, len(req.tokens), req.preemptions,
               req.prefill_chunks)
        if key == watch.streams.get(req_id):
            return
        watch.streams[req_id] = key
        self._send(watch, encode_frame(
            FrameType.TOKENS, {"req": self._snapshot(req)}))
        if req.finished:
            watch.streams.pop(req_id, None)

    def health(self) -> Dict:
        m = self.engine.metrics
        from ..serve.metrics import percentile
        return {
            "replica": self.replica_id,
            "state": "draining" if self._draining else "healthy",
            "phase": getattr(self.engine, "phase", "both"),
            "queue_depth": self.engine.queue.depth,
            "queue_max_depth": self.engine.queue.max_depth,
            "active_requests": self.engine.active_requests,
            "handoff_pending": getattr(self.engine, "handoff_pending", 0),
            "capacity": self.engine.capacity,
            "step_latency_p50_s": percentile(m.step_latency_s, 50),
            "tokens_generated": m.tokens_generated,
            "retry_after_hint_s": m.last_retry_after_s,
            "steps": self.steps,
            "pid": os.getpid(),
        }


# -- child-process entry point -----------------------------------------------

# The seeded bench-recipe geometry every server child builds; CLI
# callers validate request token ids against TINY_VOCAB.
TINY_VOCAB = 96
TINY_MAX_LEN = 64


def _build_tiny_engine(args):
    """The fleet bench engine, bit-for-bit: same tiny NMT model, same
    ``model.init`` call under the same seed — every server process
    derives IDENTICAL weights, so greedy cross-process token parity
    with the in-process fleet holds by construction."""
    import jax
    import numpy as np

    from ..models.transformer_nmt import transformer_nmt_tiny
    from ..serve.engine import Engine

    model = transformer_nmt_tiny(vocab_size=TINY_VOCAB,
                                 max_len=TINY_MAX_LEN)
    init = model.init(
        jax.random.PRNGKey(args.seed),
        np.zeros((1, args.src_len), np.int32),
        np.ones((1, args.src_len), np.int32),
        np.zeros((1, args.src_len), np.int32), train=False)
    variables = {"params": init["params"]}
    return Engine(model, variables, capacity=args.slots,
                  max_src_len=args.src_len,
                  queue_depth=args.queue_depth,
                  default_max_new_tokens=args.max_new_tokens,
                  decode_window=args.decode_window,
                  kv_block_size=args.kv_block_size,
                  phase=args.phase)


def _warmup(engine, args) -> None:
    """Compile every shape the timed run decodes through, before the
    listen socket exists (see the readiness barrier)."""
    src = [int(t) for t in args.warmup_src.split(",") if t.strip()] \
        if args.warmup_src else [5, 4, 3]
    req = engine.submit(src[:args.src_len],
                        max_new_tokens=args.max_new_tokens)
    engine.run_until_drained()
    if args.phase == "prefill" and engine.handoff_ready(req.id):
        engine.release_handoff(req.id)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="deeplearning_cfn_tpu.net.server",
        description="one tiny-NMT serve engine behind a socket")
    ap.add_argument("--listen", required=True,
                    help="unix:///path.sock or tcp://host:port "
                         "(tcp port 0 = ephemeral)")
    ap.add_argument("--replica-id", default="replica")
    ap.add_argument("--slots", type=int, default=2)
    ap.add_argument("--src-len", type=int, default=8)
    ap.add_argument("--max-new-tokens", type=int, default=4)
    ap.add_argument("--queue-depth", type=int, default=16)
    ap.add_argument("--decode-window", type=int, default=4)
    ap.add_argument("--kv-block-size", type=int, default=0)
    ap.add_argument("--phase", default="both",
                    choices=["both", "prefill", "decode"])
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--warmup-src", default="",
                    help="comma-separated warmup token ids")
    ap.add_argument("--drain-grace-s", type=float, default=30.0)
    ap.add_argument("--run-dir", default="",
                    help="write this replica's span shard to "
                         "<run-dir>/metrics.jsonl")
    ap.add_argument("--address-file", default="",
                    help="write the resolved listen address here after "
                         "binding (ephemeral-port discovery)")
    args = ap.parse_args(argv)

    # The env var alone is too late on this image — the TPU plugin is
    # pre-registered; switch the platform in-process before jax
    # initializes any backend.
    from ..runtime.platform import honor_env_platform
    honor_env_platform()

    writer = None
    if args.run_dir:
        from ..metrics.jsonl import MetricsWriter
        from ..obs.sinks import JsonlSink
        from ..obs.trace import get_tracer

        os.makedirs(args.run_dir, exist_ok=True)
        # Append-mode writer: a supervisor-restarted replica continues
        # the same shard instead of truncating its predecessor's spans.
        writer = MetricsWriter(
            os.path.join(args.run_dir, "metrics.jsonl"),
            also_stdout=False, all_processes=True)
        get_tracer().add_sink(JsonlSink(writer))

    engine = _build_tiny_engine(args)
    _warmup(engine, args)
    server = ReplicaServer(engine, args.listen,
                           replica_id=args.replica_id,
                           drain_grace_s=args.drain_grace_s)
    if args.address_file:
        tmp = args.address_file + ".tmp"
        with open(tmp, "w") as fh:
            fh.write(server.address)
        os.replace(tmp, args.address_file)
    print(f"[net.server] {args.replica_id} ready on {server.address} "
          f"(pid {os.getpid()})", flush=True)
    server.install_signal_handlers()
    rc = server.serve_forever()
    if writer is not None:
        engine.metrics.emit(writer, replica=args.replica_id,
                            phase=args.phase)
        writer.close()
    return rc


if __name__ == "__main__":
    sys.exit(main())
