"""Client side of the network serving plane: RemoteReplica.

A :class:`RemoteReplica` wears the exact duck type of
``fleet.replica.EngineReplica`` — id/state/phase/crashed/steps, the
routable/steppable/busy properties, submit/poll/cancel/step, the
handoff quartet, ``record_evacuation`` and ``health()`` — so
``fleet/router.py`` (policies, circuit breaker, backlog retry, brownout,
disagg handoff orchestration) runs over sockets UNCHANGED. The router
never learns the replica is a different process.

Three impedance mismatches are absorbed here:

- **step() is a pump, not a compute tick.** The server steps its own
  engine autonomously (that is the whole point — real parallelism).
  The client's ``step()`` drains pending TOKENS pushes, applies them to
  client-side mirror ``Request`` objects, and returns the token
  progress it OBSERVED, which is all the router's wedge/progress
  accounting needs.
- **poll() reads a mirror.** Every TOKENS push carries a full request
  snapshot (tokens + lifecycle timestamps). CLOCK_MONOTONIC is
  system-wide on Linux, so the server-stamped ``admitted_at``/
  ``finished_at``/``prefill_s`` land directly in the router's phase
  ledger without translation.
- **a dead socket is a dead replica.** ConnectionClosed anywhere maps
  to ``ReplicaCrashed`` → the router marks the replica DOWN and
  evacuates, exactly as for an in-process injected crash. When the
  supervisor restarts the child, :meth:`try_connect` readmits it:
  state back to HEALTHY, mirrors cleared (the router already re-placed
  them), fresh framing.
"""

from __future__ import annotations

import itertools
import time
from typing import Dict, Optional

from .codec import FrameReader, FrameType, CodecError, encode_frame, \
    pack_artifact, raise_error_header, unpack_artifact
from .transport import Connection, ConnectionClosed, connect
from ..fleet.replica import ReplicaCrashed, ReplicaState
from ..obs.trace import get_tracer, obs_enabled
from ..serve.queue import Request, RequestState

_RID_COUNTER = itertools.count(1)


class _RemoteQueueView:
    """Backed by the last HEALTH_OK snapshot — Router._place reads
    ``r.engine.queue.depth``/``.max_depth`` directly."""

    def __init__(self, replica: "RemoteReplica"):
        self._replica = replica

    @property
    def depth(self) -> int:
        return int(self._replica.last_health.get("queue_depth", 0))

    @property
    def max_depth(self) -> int:
        return int(self._replica.last_health.get("queue_max_depth", 1))


class _RemoteEngineView:
    """The slice of the Engine surface the router actually touches,
    served from the cached health snapshot."""

    def __init__(self, replica: "RemoteReplica"):
        self._replica = replica
        self.queue = _RemoteQueueView(replica)

    @property
    def capacity(self) -> int:
        return int(self._replica.last_health.get("capacity", 1))

    @property
    def active_requests(self) -> int:
        return int(self._replica.last_health.get("active_requests", 0))

    @property
    def handoff_pending(self) -> int:
        return int(self._replica.last_health.get("handoff_pending", 0))

    @property
    def phase(self) -> str:
        return self._replica.phase


class RemoteReplica:
    """One replica-server connection, duck-typed as EngineReplica."""

    def __init__(self, replica_id: str, address: str, phase: str = "both",
                 connect_timeout_s: float = 5.0,
                 connect_retry_deadline_s: float = 60.0,
                 rpc_timeout_s: float = 30.0,
                 step_wait_s: float = 0.02,
                 reconnect_interval_s: float = 0.25,
                 clock=time.monotonic):
        self.id = replica_id
        self.address = address
        self.phase = phase
        self.state = ReplicaState.DOWN      # until connect() succeeds
        self.crashed = False
        self.steps = 0
        self.trace_sink = None              # router-side shard (evacuations)
        self.clock = clock
        self.connect_timeout_s = connect_timeout_s
        self.connect_retry_deadline_s = connect_retry_deadline_s
        self.rpc_timeout_s = rpc_timeout_s
        self.step_wait_s = step_wait_s
        self.reconnect_interval_s = reconnect_interval_s
        self.last_health: Dict = {}
        self.engine = _RemoteEngineView(self)
        self._conn: Optional[Connection] = None
        self._reader = FrameReader()
        self._mirrors: Dict[str, Request] = {}
        self._orphan_snaps: Dict[str, Dict] = {}
        self._last_reconnect = 0.0

    # -- connection lifecycle ------------------------------------------------

    def connect(self) -> "RemoteReplica":
        """Block until the replica server accepts — the readiness
        barrier: the server binds only after engine build + warmup, so
        the first successful connect means "warm and ready"."""
        self._conn = connect(self.address, timeout_s=self.connect_timeout_s,
                             retry_deadline_s=self.connect_retry_deadline_s)
        self._reader = FrameReader()
        self.crashed = False
        self.state = ReplicaState.HEALTHY
        self.health()                       # prime the engine/queue view
        return self

    def try_connect(self) -> bool:
        """One cheap reconnect attempt (rate-limited by the caller via
        ``reconnect_interval_s``) — readmits a supervisor-restarted
        child. Mirrors are dropped: the router evacuated those requests
        when the socket died; this process has no copy of them."""
        now = self.clock()
        if now - self._last_reconnect < self.reconnect_interval_s:
            return False
        self._last_reconnect = now
        try:
            conn = connect(self.address, timeout_s=self.connect_timeout_s,
                           retry_deadline_s=0.0)
        except OSError:
            return False
        if self._conn is not None:
            self._conn.close()
        self._conn = conn
        self._reader = FrameReader()
        self._mirrors = {}
        self._orphan_snaps = {}
        self.crashed = False
        self.state = ReplicaState.HEALTHY
        # Raw HEALTH round-trip — NOT self.health(), which swallows
        # RPC failures by design (stats must always render). True must
        # mean "verified round-trip": without this, a reconnect could
        # be counted while the very RPC that probed it flipped the
        # state machine back to DOWN.
        try:
            reply = self._rpc(FrameType.HEALTH, {},
                              timeout_s=min(self.rpc_timeout_s, 5.0))
            self.last_health = dict(reply.header.get("health") or {})
        except (ReplicaCrashed, TimeoutError):
            # Not readmitted. Close the socket and leave the state
            # machine DOWN so the router keeps tending this replica —
            # a half-ready connection must not look routable, and a
            # late reply on the next attempt's fresh stream would
            # desync the reader.
            if self._conn is not None:
                self._conn.close()
            self.crashed = True
            self.state = ReplicaState.DOWN
            return False
        return True

    def close(self) -> None:
        if self._conn is not None:
            self._conn.close()

    def _on_lost(self, why: str) -> ReplicaCrashed:
        """A dead socket is a dead replica — same observable effect as
        SIGKILL, because usually it IS SIGKILL."""
        if self._conn is not None:
            self._conn.close()
        self.crashed = True
        self.state = ReplicaState.DOWN
        return ReplicaCrashed(
            f"replica {self.id} lost ({why}) at {self.address}")

    # -- routing surface (EngineReplica duck type) ---------------------------

    @property
    def routable(self) -> bool:
        return self.state is ReplicaState.HEALTHY and not self.crashed

    @property
    def steppable(self) -> bool:
        return self.state in (ReplicaState.HEALTHY, ReplicaState.DRAINING) \
            and not self.crashed

    @property
    def busy(self) -> bool:
        # Any unfinished mirror — including parked PREFILLED streams
        # (released mirrors are dropped, so a completed handoff does
        # not pin this replica busy forever).
        return any(not r.finished for r in self._mirrors.values())

    # -- wire plumbing -------------------------------------------------------

    def _pump(self, timeout_s: float) -> int:
        """Read whatever the socket has, apply TOKENS pushes, queue the
        rest; returns observed token/state progress."""
        if self._conn is None or self._conn.closed:
            raise self._on_lost("not connected")
        progress = 0
        try:
            data = self._conn.recv(timeout_s=timeout_s)
            while data is not None:
                self._reader.feed(data)
                data = self._conn.recv(timeout_s=0.0) \
                    if self._conn.poll(0.0) else None
            for frame in self._drain_frames():
                progress += self._handle_push(frame)
        except ConnectionClosed as e:
            raise self._on_lost(str(e)) from e
        except CodecError as e:
            raise self._on_lost(f"corrupt stream: {e}") from e
        return progress

    def _drain_frames(self):
        frames = []
        while True:
            frame = self._reader.next()
            if frame is None:
                return frames
            frames.append(frame)

    def _handle_push(self, frame) -> int:
        if frame.ftype == FrameType.TOKENS:
            return self._apply(frame.header.get("req") or {})
        # Non-TOKENS frame outside an RPC wait: a straggler reply from
        # an RPC that timed out. Drop it — its rid matches nothing.
        return 0

    def _apply(self, snap: Dict) -> int:
        req = self._mirrors.get(snap.get("id"))
        if req is None:
            # A push can race ahead of its mirror: the server sends
            # SUBMIT_OK and may step the request to DONE (one fused
            # window can cover the whole budget) before this process is
            # scheduled again, so the terminal snapshot arrives in the
            # same batch as the reply — BEFORE _mirror() runs. Stash it;
            # _mirror() replays the latest stashed snapshot. Dropping it
            # would wedge the request forever: terminal snapshots are
            # sent exactly once.
            self._orphan_snaps[snap.get("id")] = snap
            return 0
        before = (len(req.tokens), req.state)
        req.state = RequestState(snap["state"])
        req.tokens = [int(t) for t in snap["tokens"]]
        req.submitted_at = snap["submitted_at"]
        req.admitted_at = snap["admitted_at"]
        req.first_token_at = snap["first_token_at"]
        req.finished_at = snap["finished_at"]
        req.prefill_s = snap.get("prefill_s")
        req.prefill_chunks = int(snap.get("prefill_chunks") or 0)
        req.preemptions = int(snap.get("preemptions") or 0)
        req.preempted_s = float(snap.get("preempted_s") or 0.0)
        delta = len(req.tokens) - before[0]
        # State transitions with no new tokens (→PREFILLED, →DONE on an
        # empty stream) still count as progress for wedge detection.
        return max(delta, 0) + (1 if req.state is not before[1] else 0)

    def _mirror(self, snap: Dict, src_ids=()) -> Request:
        req = Request(id=snap["id"], src_ids=list(src_ids),
                      max_new_tokens=int(snap.get("max_new_tokens") or 0),
                      beam_size=int(snap.get("beam_size") or 1),
                      deadline=snap.get("deadline"),
                      trace_id=snap.get("trace_id"))
        if snap.get("tenant") is not None:
            req.tenant = snap["tenant"]
        if snap.get("qos_class"):
            req.qos_class = snap["qos_class"]
        self._mirrors[req.id] = req
        self._apply(snap)
        # Snapshots are full-state, so the latest stashed push (if any
        # raced ahead of this mirror — see _apply) supersedes the reply
        # snapshot wholesale.
        orphan = self._orphan_snaps.pop(req.id, None)
        if orphan is not None:
            self._apply(orphan)
        return req

    def _rpc(self, ftype: int, header: Dict, body: bytes = b"",
             timeout_s: Optional[float] = None):
        """Send one request frame, pump until its reply arrives.
        TOKENS pushes interleaved with the reply are applied on the
        way. ERROR replies re-raise the server's typed exception."""
        if self._conn is None or self._conn.closed:
            raise self._on_lost("not connected")
        rid = f"{self.id}-{next(_RID_COUNTER)}"
        header = dict(header)
        header["rid"] = rid
        deadline = self.clock() + (timeout_s if timeout_s is not None
                                   else self.rpc_timeout_s)
        try:
            self._conn.send(encode_frame(ftype, header, body))
        except ConnectionClosed as e:
            raise self._on_lost(str(e)) from e
        while True:
            remaining = deadline - self.clock()
            if remaining <= 0:
                raise TimeoutError(
                    f"replica {self.id}: no reply to "
                    f"{FrameType.name(ftype)} within "
                    f"{timeout_s or self.rpc_timeout_s:.1f}s")
            try:
                data = self._conn.recv(timeout_s=min(remaining, 0.05))
                if data is not None:
                    self._reader.feed(data)
                # Apply EVERY non-reply frame before returning: a batch
                # can carry TOKENS pushes BEHIND the reply, and a
                # terminal snapshot is sent exactly once — returning
                # early would drop it on the floor and the mirror would
                # never finish.
                reply = None
                for frame in self._drain_frames():
                    if reply is None and frame.header.get("rid") == rid:
                        reply = frame
                    else:
                        self._handle_push(frame)
                if reply is not None:
                    if reply.ftype == FrameType.ERROR:
                        raise_error_header(reply.header)
                    return reply
            except ConnectionClosed as e:
                raise self._on_lost(str(e)) from e
            except CodecError as e:
                raise self._on_lost(f"corrupt stream: {e}") from e

    # -- request lifecycle ---------------------------------------------------

    def submit(self, src_ids, **kwargs):
        if self.crashed:
            raise ReplicaCrashed(f"replica {self.id} is down")
        header = {"src_ids": [int(t) for t in src_ids]}
        for key in ("max_new_tokens", "beam_size", "deadline_s",
                    "request_id", "trace_id", "tenant", "qos_class"):
            if kwargs.get(key) is not None:
                header[key] = kwargs[key]
        reply = self._rpc(FrameType.SUBMIT, header)
        return self._mirror(reply.header["req"], src_ids=src_ids)

    def poll(self, request_id: str) -> Request:
        return self._mirrors[request_id]

    def cancel(self, request_id: str) -> bool:
        if self.crashed:
            return False
        try:
            reply = self._rpc(FrameType.CANCEL, {"request_id": request_id})
        except (ReplicaCrashed, TimeoutError):
            return False
        return bool(reply.header.get("ok"))

    def step(self) -> int:
        """Pump the token stream. Waits up to ``step_wait_s`` for the
        first bytes (the server computes in parallel — a short poll
        keeps the router tick from spinning hot), then drains whatever
        arrived without waiting again."""
        if self.crashed:
            raise ReplicaCrashed(f"replica {self.id} is down")
        progress = self._pump(self.step_wait_s if self.busy else 0.0)
        self.steps += 1
        return progress

    # -- KV handoff (bytes travel the wire, no store round-trip) -------------

    def handoff_ready(self, request_id: str) -> bool:
        if self.crashed:
            return False
        req = self._mirrors.get(request_id)
        return req is not None and req.state is RequestState.PREFILLED

    def export_handoff_bytes(self, request_id: str) -> bytes:
        if self.crashed:
            raise ReplicaCrashed(f"replica {self.id} is down")
        reply = self._rpc(FrameType.HANDOFF_EXPORT,
                          {"request_id": request_id})
        return reply.body

    def export_handoff(self, request_id: str):
        return unpack_artifact(self.export_handoff_bytes(request_id))

    def import_handoff_bytes(self, data: bytes, request_id: str,
                             trace_id=None, **qos_kwargs) -> Request:
        if self.crashed:
            raise ReplicaCrashed(f"replica {self.id} is down")
        header = {"request_id": request_id}
        if trace_id is not None:
            header["trace_id"] = trace_id
        for key in ("tenant", "qos_class"):
            if qos_kwargs.get(key) is not None:
                header[key] = qos_kwargs[key]
        reply = self._rpc(FrameType.HANDOFF_IMPORT, header, body=data)
        return self._mirror(reply.header["req"])

    def import_handoff(self, artifact, request_id: str, trace_id=None,
                       **qos_kwargs) -> Request:
        return self.import_handoff_bytes(
            pack_artifact(artifact), request_id, trace_id=trace_id,
            **qos_kwargs)

    def release_handoff(self, request_id: str) -> None:
        if self.crashed:
            raise ReplicaCrashed(f"replica {self.id} is down")
        self._rpc(FrameType.HANDOFF_RELEASE, {"request_id": request_id})
        # Drop the mirror: the parked stream is gone server-side, and a
        # retained PREFILLED mirror would pin this replica busy forever.
        self._mirrors.pop(request_id, None)
        self._orphan_snaps.pop(request_id, None)

    # -- health / drain / observability --------------------------------------

    def health(self) -> Dict:
        """Live health RPC; falls back to the last snapshot when the
        replica is down (EngineReplica.health always answers — it reads
        a local engine — and Router.stats() relies on that), so a
        SIGKILL'd replica reports its final observed load, marked with
        the CLIENT-side state machine's DOWN."""
        if not self.crashed and self._conn is not None \
                and not self._conn.closed:
            try:
                reply = self._rpc(FrameType.HEALTH, {},
                                  timeout_s=min(self.rpc_timeout_s, 5.0))
                self.last_health = dict(reply.header.get("health") or {})
            except (ReplicaCrashed, TimeoutError):
                pass
        h = dict(self.last_health)
        # The router's policies key on the CLIENT-side state machine
        # (HEALTHY/DRAINING/...), not the server's self-report.
        h["state"] = self.state.value
        h["replica"] = self.id
        h.setdefault("queue_depth", 0)
        h.setdefault("active_requests", 0)
        h.setdefault("tokens_generated", 0)
        self.last_health = h
        return h

    def drain(self) -> None:
        """Ask the server to refuse new submits and exit when idle."""
        self._rpc(FrameType.DRAIN, {})

    def record_evacuation(self, req, now: float) -> None:
        """Same retroactive ``serve.request`` span EngineReplica writes,
        into the router-side sink for this replica's shard — the dead
        child can't write it, and the merged timeline still must show
        the abandoned attempt."""
        if not obs_enabled():
            return
        t0 = getattr(req, "submitted_at", None)
        if not isinstance(t0, (int, float)):
            return
        tracer = get_tracer()
        if self.trace_sink is not None:
            tracer.add_sink(self.trace_sink)
        try:
            tracer.record_span(
                "serve.request", t0, max(now - t0, 0.0), ok=False,
                request_id=getattr(req, "id", None),
                trace_id=getattr(req, "trace_id", None)
                or getattr(req, "id", None),
                state="evacuated", replica=self.id,
                tokens=len(getattr(req, "tokens", ()) or ()))
        finally:
            if self.trace_sink is not None:
                tracer.remove_sink(self.trace_sink)
