"""Live run follower: ``dlcfn-tpu obs tail <run_dir>``.

Follows a run's JSONL streams as they grow and renders a one-line status
(step, step time, examples/sec, loss | queue depth, tokens/sec | alert
count) every time something changes — the "is it healthy right now"
glance `obs summarize` can only give post-hoc.

The follower is **truncation-tolerant** by construction:

- a trailing partial line (the writer is mid-``write()``, or the process
  crashed mid-line) is buffered until its newline arrives and is never
  parsed early — so a torn line can only delay one record, not corrupt
  the stream;
- unparseable complete lines are counted and skipped, same as
  ``obs summarize``;
- a file that shrinks (rotation, restart from scratch) resets the read
  offset to zero instead of erroring;
- files that don't exist yet (``logs/launch.jsonl`` before the first
  attempt finishes) are silently retried each poll.

Optionally evaluates SLO rules live (``--rules``): alerts print as their
own lines above the status, so a degrading run is visible the moment the
rule fires, not at the postmortem.
"""

from __future__ import annotations

import json
import os
import sys
import time
from typing import Any, Dict, List, Optional


class JsonlFollower:
    """Incremental reader of one JSONL file; ``poll()`` returns the
    complete records appended since the previous call."""

    def __init__(self, path: str):
        self.path = path
        self._pos = 0
        self._buf = ""
        self.skipped = 0

    def poll(self) -> List[Dict[str, Any]]:
        try:
            size = os.path.getsize(self.path)
        except OSError:
            return []
        if size < self._pos:        # truncated/rotated: start over
            self._pos = 0
            self._buf = ""
        if size == self._pos:
            return []
        try:
            with open(self.path, "r") as fh:
                fh.seek(self._pos)
                chunk = fh.read()
                self._pos = fh.tell()
        except OSError:
            return []
        self._buf += chunk
        # Everything before the last newline is complete; the remainder
        # is a partial line held for the next poll.
        if "\n" in self._buf:
            complete, self._buf = self._buf.rsplit("\n", 1)
            lines = complete.split("\n")
        else:
            return []
        records = []
        for line in lines:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                self.skipped += 1
                continue
            if isinstance(rec, dict):
                records.append(rec)
            else:
                self.skipped += 1
        return records


class TailState:
    """Folds the record stream into the current one-line status."""

    def __init__(self):
        self.step: Optional[Any] = None
        self.step_time_s: Optional[float] = None
        self.examples_per_sec: Optional[float] = None
        self.loss: Optional[float] = None
        self.queue_depth: Optional[Any] = None
        self.tokens_per_sec: Optional[float] = None
        self.latency_p95_s: Optional[float] = None
        self.completed: Optional[Any] = None
        self.submitted: Optional[Any] = None
        self.preemptions: Optional[Any] = None
        self.radix_hits: Optional[Any] = None
        self.radix_hit_rate: Optional[Any] = None
        self.chunk_ticks: Optional[Any] = None
        self.chunk_partial: Optional[Any] = None
        self.alerts = 0
        self.last_alert: Optional[str] = None
        self.launch_outcome: Optional[str] = None
        self.span_failures = 0
        self.records = 0

    def update(self, r: Dict[str, Any]) -> None:
        self.records += 1
        if r.get("event") == "alert":
            self.alerts += 1
            self.last_alert = str(r.get("rule", "?"))
            return
        if r.get("event") == "launch_attempt":
            self.launch_outcome = str(r.get("outcome", "?"))
            return
        if "span" in r:
            if r.get("ok") is False:
                self.span_failures += 1
            return
        if any(k.startswith("serve_") for k in r):
            for attr, key in (("queue_depth", "serve_queue_depth"),
                              ("tokens_per_sec", "serve_tokens_per_sec"),
                              ("latency_p95_s", "serve_latency_p95_s"),
                              ("completed", "serve_completed"),
                              ("submitted", "serve_submitted"),
                              ("preemptions", "serve_preemptions"),
                              ("radix_hits", "serve_radix_hits"),
                              ("radix_hit_rate", "serve_radix_hit_rate"),
                              ("chunk_ticks", "serve_chunk_ticks"),
                              ("chunk_partial",
                               "serve_chunk_partial_rows")):
                if key in r:
                    setattr(self, attr, r[key])
            return
        for key in ("step", "step_time_s", "examples_per_sec", "loss"):
            if key in r:
                setattr(self, key, r[key])

    def status_line(self) -> str:
        def _f(v: Any) -> str:
            if v is None:
                return "-"
            if isinstance(v, float):
                return f"{v:.4g}"
            return str(v)

        parts = []
        if self.step is not None or self.loss is not None:
            sps = None
            if isinstance(self.step_time_s, (int, float)) \
                    and self.step_time_s > 0:
                sps = 1.0 / self.step_time_s
            parts.append(f"step {_f(self.step)} "
                         f"({_f(sps)} steps/s, "
                         f"{_f(self.examples_per_sec)} ex/s) "
                         f"loss {_f(self.loss)}")
        if self.submitted is not None or self.queue_depth is not None:
            serve = (f"serve q={_f(self.queue_depth)} "
                     f"{_f(self.tokens_per_sec)} tok/s "
                     f"done {_f(self.completed)}/{_f(self.submitted)}")
            if self.preemptions is not None:
                # Only QoS-active engines emit serve_preemptions —
                # single-tenant status lines stay byte-identical.
                serve += f" preempt {_f(self.preemptions)}"
            if self.radix_hits is not None:
                # Only --radix-cache engines emit serve_radix_* — other
                # configurations' status lines stay byte-identical.
                serve += (f" radix {_f(self.radix_hits)}"
                          f"@{_f(self.radix_hit_rate)}")
            if self.chunk_ticks is not None:
                # Only --prefill-chunk engines emit serve_chunk_* —
                # unchunked status lines stay byte-identical.
                serve += (f" chunk {_f(self.chunk_ticks)}"
                          f"~{_f(self.chunk_partial)}p")
            parts.append(serve)
        if self.launch_outcome is not None:
            parts.append(f"launch {self.launch_outcome}")
        alerts = f"alerts {self.alerts}"
        if self.last_alert:
            alerts += f" (last: {self.last_alert})"
        if self.span_failures:
            alerts += f" span-failures {self.span_failures}"
        parts.append(alerts)
        if not parts:
            return "(no records yet)"
        return " | ".join(parts)


class FleetTailState:
    """A :class:`~.signals.SignalBus` folded live into ONE fleet status
    line: total tokens/sec and queue depth across replicas, aggregate
    done/submitted, the WORST per-replica latency p95, total alerts —
    the same aggregate `obs summarize --fleet` reports, because both
    read the identical bus fold."""

    def __init__(self, names: List[str]):
        from .signals import SignalBus

        # "#"-prefixed names are control streams (the fleet root's own
        # autoscale.jsonl), not replicas — they feed the scale fold
        # below, never the bus.
        self.bus = SignalBus(names=[n for n in names
                                    if not n.startswith("#")])
        # Live membership + autoscale fold. ``members`` maps replica →
        # phase and tracks scale events as they stream in: a fleet's
        # membership is no longer fixed for the life of one `fleet up`.
        self.members: Dict[str, Optional[str]] = {
            n: None for n in names if not n.startswith("#")}
        self.scale_ups = 0
        self.scale_downs = 0
        self.last_scale: Optional[Dict[str, Any]] = None
        self._open_drains: set = set()
        self._scale_seen = False
        # Per-replica preemption counters (QoS fleets only — the key is
        # absent from single-tenant snapshots).
        self._preemptions: Dict[str, int] = {}
        # Per-replica radix hit counters (--radix-cache fleets only).
        self._radix_hits: Dict[str, int] = {}
        # Per-replica chunk tick counters (--prefill-chunk fleets only).
        self._chunk_ticks: Dict[str, int] = {}
        # Brownout fold (--degrade fleets only): the last degrade_event
        # carries the current level.
        self.last_degrade: Optional[Dict[str, Any]] = None
        self.degrade_transitions = 0

    def update(self, name: str, rec: Dict[str, Any]) -> None:
        if rec.get("event") == "degrade_event":
            self.degrade_transitions += 1
            self.last_degrade = rec
            return
        if rec.get("event") == "scale_event":
            self._scale_seen = True
            action = rec.get("action")
            replica = rec.get("replica")
            phase = rec.get("phase")
            if action == "scale_up":
                self.scale_ups += 1
                self.members[replica] = phase
                self._open_drains.discard(replica)
            elif action == "drain_begin":
                self.members.setdefault(replica, phase)
                self._open_drains.add(replica)
            elif action == "scale_down":
                self.scale_downs += 1
                self._open_drains.discard(replica)
                self.members.pop(replica, None)
            self.last_scale = rec
            return
        if name.startswith("#"):
            return
        if name not in self.members:
            self.members[name] = rec.get("phase")
        elif self.members[name] is None and rec.get("phase"):
            self.members[name] = rec.get("phase")
        if isinstance(rec.get("serve_preemptions"), (int, float)):
            self._preemptions[name] = int(rec["serve_preemptions"])
        if isinstance(rec.get("serve_radix_hits"), (int, float)):
            self._radix_hits[name] = int(rec["serve_radix_hits"])
        if isinstance(rec.get("serve_chunk_ticks"), (int, float)):
            self._chunk_ticks[name] = int(rec["serve_chunk_ticks"])
        self.bus.observe(name, rec)

    def scale_state(self) -> str:
        if self._open_drains:
            return "draining"
        if self.last_scale is not None \
                and self.last_scale.get("action") == "scale_up":
            return "scaling-up"
        return "steady"

    def status_line(self) -> str:
        def _f(v: Any) -> str:
            if v is None:
                return "-"
            if isinstance(v, float):
                return f"{v:.4g}"
            return str(v)

        f = self.bus.fleet()
        if f["replicas_live"] == 0:
            return f"fleet {f['replicas']} replica(s) | (no records yet)"
        parts = [f"fleet {f['replicas_live']}/{f['replicas']} replica(s)",
                 f"q={_f(f['queue_depth'])} "
                 f"{_f(f['tokens_per_sec'])} tok/s",
                 f"done {_f(f['completed'])}/{_f(f['submitted'])}",
                 f"worst p95 {_f(f['worst_latency_p95_s'])}",
                 f"alerts {f['alerts']}"]
        if self._preemptions:
            parts.insert(3, f"preempt {sum(self._preemptions.values())}")
        if self._radix_hits:
            parts.insert(3, f"radix {sum(self._radix_hits.values())}")
        if self._chunk_ticks:
            parts.insert(3, f"chunk {sum(self._chunk_ticks.values())}")
        fails = {n: s.launch_outcome
                 for n, s in self.bus.replicas.items()
                 if s.launch_outcome not in (None, "ok")}
        if fails:
            parts.append("launch " + ",".join(
                f"{n}:{o}" for n, o in sorted(fails.items())))
        if self._scale_seen:
            # Autoscaled fleet: surface live membership (with phase)
            # and the controller state + last event reason. Fixed
            # fleets never see a scale_event, so the legacy line is
            # unchanged byte for byte.
            parts.append("members " + ",".join(
                f"{n}:{self.members[n] or '?'}"
                for n in sorted(self.members)))
            last = self.last_scale or {}
            why = f" — {last['reason']}" if last.get("reason") else ""
            parts.append(
                f"scale {self.scale_state()} "
                f"(last: {last.get('action')} {last.get('replica')}"
                f"{why})")
        if self.last_degrade is not None:
            # Browning-out fleet: surface the live level. Fleets that
            # never degrade see no degrade_event, so the legacy line
            # stays byte-identical.
            d = self.last_degrade
            parts.append(f"brownout L{d.get('level')} "
                         f"({d.get('level_name')}, "
                         f"{self.degrade_transitions} transition(s))")
        return " | ".join(parts)


def _follow_paths(path: str) -> List[str]:
    if os.path.isdir(path):
        return [os.path.join(path, "metrics.jsonl"),
                os.path.join(path, "logs", "launch.jsonl")]
    return [path]


def _fleet_followers(root: str) -> List[tuple]:
    """[(replica_name, JsonlFollower)] over every per-replica run dir
    under ``root`` (the same filter ``obs summarize --fleet`` uses),
    plus the ``#autoscale`` control stream (``<root>/autoscale.jsonl``,
    which may not exist yet — the follower retries silently). The tail
    loop re-runs this discovery every poll: an autoscaled fleet grows
    new replica dirs mid-follow."""
    from .report import fleet_replica_dirs

    pairs = []
    for name, sub in fleet_replica_dirs(root):
        for p in _follow_paths(sub):
            pairs.append((name, JsonlFollower(p)))
    pairs.append(("#autoscale",
                  JsonlFollower(os.path.join(root, "autoscale.jsonl"))))
    pairs.append(("#degrade",
                  JsonlFollower(os.path.join(root, "degrade.jsonl"))))
    return pairs


def tail(path: str, interval_s: float = 1.0,
         max_seconds: Optional[float] = None, once: bool = False,
         slo_engine=None, out=None, fleet: bool = False) -> int:
    """Follow ``path`` (a run dir or one JSONL file), printing the status
    line whenever it changes. ``once`` renders current state and returns
    (tests and scripts); ``max_seconds`` bounds a follow. ``fleet``
    treats ``path`` as a directory of per-replica run dirs and renders
    ONE aggregated fleet status line. Returns 0."""
    out = out if out is not None else sys.stdout
    if fleet:
        pairs = _fleet_followers(path)
        fstate = FleetTailState([n for n, _ in pairs])
    else:
        pairs = [(None, JsonlFollower(p)) for p in _follow_paths(path)]
        state = TailState()
    deadline = (time.monotonic() + max_seconds
                if max_seconds is not None else None)
    last_line = None
    while True:
        if fleet:
            # Membership can change under a live follow (autoscale):
            # pick up newly created replica dirs each poll.
            known = {f.path for _, f in pairs}
            for name, f in _fleet_followers(path):
                if f.path not in known:
                    pairs.append((name, f))
        for name, f in pairs:
            for rec in f.poll():
                def _fold(r):
                    if fleet:
                        fstate.update(name, r)
                    else:
                        state.update(r)
                if slo_engine is not None and rec.get("event") != "alert":
                    for alert in slo_engine.observe(rec):
                        _fold(alert)
                        print(f"ALERT {alert['rule']}: "
                              f"{alert.get('detail', '')}", file=out)
                _fold(rec)
        line = fstate.status_line() if fleet else state.status_line()
        if line != last_line:
            print(line, file=out)
            try:
                out.flush()
            except (AttributeError, OSError):
                pass
            last_line = line
        if once:
            return 0
        if deadline is not None and time.monotonic() >= deadline:
            return 0
        time.sleep(interval_s)
