"""Chrome/Perfetto trace-event export: span JSONL → ``trace.json``.

``dlcfn-tpu obs export <run_dir>`` turns the run's JSONL streams into one
Trace Event Format file (the ``{"traceEvents": [...]}`` JSON object both
``chrome://tracing`` and https://ui.perfetto.dev load directly), so a
run's timeline — train dispatch/realize spans, checkpoint saves, serve
admission ticks and per-request lifecycles, launcher attempts — becomes a
zoomable flame view instead of grep output.

Mapping:

- **span records** (``{"span", "span_id", "parent_id", "t0_s", "dur_s",
  "ok", ...}``) become ``"X"`` complete events. Nesting is preserved by
  construction: every span lineage (a root span plus all descendants via
  ``parent_id``) is placed on one Perfetto track (``tid``), children
  clamped inside their parent's interval so rounding in the 6-decimal
  JSONL fields can never break the viewer's stack discipline. Root
  lineages share tracks greedily when they don't overlap. Per-request
  ``serve.request*`` lineages get their own process group so request
  gantt rows don't interleave with engine ticks.
- **launcher attempt events** (``{"event": "launch_attempt", ...}``) and
  **SLO alert events** (``{"event": "alert", ...}``, obs/slo.py) become
  ``"i"`` instant events.
- **numeric series** (train ``loss``/``examples_per_sec``, serve
  ``serve_queue_depth``/``serve_tokens_per_sec``/...) become ``"C"``
  counter events, one track each.

Timeline alignment: span ``t0_s`` is monotonic seconds since tracer
creation while every JSONL record's ``ts`` is wall clock, so the exporter
estimates the tracer's wall epoch as ``min(ts - dur_s - t0_s)`` over
spans carrying both (the write happens at span close, so each candidate
over-estimates by at most the write latency and min is tightest). All
event timestamps are microseconds relative to the earliest event.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional, Tuple

from .report import collect

# Counter keys exported as "C" tracks when present in non-span records.
COUNTER_KEYS = (
    "loss",
    "examples_per_sec",
    "step_time_s",
    "serve_queue_depth",
    "serve_tokens_per_sec",
    "serve_slot_occupancy",
    "serve_kv_blocks_in_use",
)

_PID_SPANS = 1
_PID_REQUESTS = 2
_PID_COUNTERS = 3

# Spans whose lineage belongs on the per-request process group.
_REQUEST_PREFIX = "serve.request"


class _SpanNode:
    __slots__ = ("rec", "start", "end", "tid", "children")

    def __init__(self, rec: Dict[str, Any]):
        self.rec = rec
        self.start = 0.0
        self.end = 0.0
        self.tid: Optional[int] = None
        self.children: List["_SpanNode"] = []


def _wall_epoch(spans: List[Dict[str, Any]],
                others: List[Dict[str, Any]]) -> float:
    """Wall-clock value of the tracer's monotonic epoch (t0_s == 0)."""
    candidates = [
        r["ts"] - float(r.get("dur_s") or 0.0) - float(r["t0_s"])
        for r in spans
        if isinstance(r.get("ts"), (int, float))
        and isinstance(r.get("t0_s"), (int, float))
    ]
    if candidates:
        return min(candidates)
    # No span carries wall clock (MemorySink records): anchor the span
    # timeline at the earliest wall ts seen, or zero.
    ts = [r["ts"] for r in others if isinstance(r.get("ts"), (int, float))]
    return min(ts) if ts else 0.0


def _shard_events(records: List[Dict[str, Any]], pid_spans: int,
                  pid_requests: int, pid_counters: int
                  ) -> Tuple[List[Dict[str, Any]], Dict[int, List[float]],
                             List[float]]:
    """Layout core shared by the single-run and fleet builders: records
    → (events, pools, t_base candidates), with ``ts``/``dur`` in
    ABSOLUTE wall-clock seconds (the caller rebases to relative µs —
    the fleet builder needs one GLOBAL base across shards, so rebasing
    cannot happen per shard)."""
    spans = [r for r in records if "span" in r
             and isinstance(r.get("t0_s"), (int, float))
             and isinstance(r.get("dur_s"), (int, float))]
    others = [r for r in records if "span" not in r]
    epoch = _wall_epoch(spans, others)

    nodes: Dict[int, _SpanNode] = {}
    anon: List[_SpanNode] = []   # spans without a usable span_id
    for r in spans:
        n = _SpanNode(r)
        n.start = epoch + float(r["t0_s"])
        n.end = n.start + max(float(r["dur_s"]), 0.0)
        sid = r.get("span_id")
        if isinstance(sid, int) and sid not in nodes:
            nodes[sid] = n
        else:
            anon.append(n)

    # Lineage: children under parents; unknown parents make roots.
    roots: List[_SpanNode] = list(anon)
    for sid, n in nodes.items():
        pid = n.rec.get("parent_id")
        parent = nodes.get(pid) if isinstance(pid, int) else None
        if parent is not None and parent is not n:
            parent.children.append(n)
        else:
            roots.append(n)

    # Track (tid) assignment: one tid per lineage; non-overlapping root
    # lineages reuse tracks greedily so the view stays compact.
    pools: Dict[int, List[float]] = {pid_spans: [], pid_requests: []}

    def _lineage_end(n: _SpanNode) -> float:
        return max([n.end] + [_lineage_end(c) for c in n.children])

    events: List[Dict[str, Any]] = []
    placed: List[Tuple[int, _SpanNode]] = []   # (pid, node)

    for root in sorted(roots, key=lambda n: (n.start, -n.end)):
        pid = (pid_requests
               if str(root.rec.get("span", "")).startswith(_REQUEST_PREFIX)
               or root.rec.get("span") == "fleet.request"
               else pid_spans)
        pool = pools[pid]
        end = _lineage_end(root)
        for tid, last_end in enumerate(pool):
            if last_end <= root.start + 1e-9:
                pool[tid] = end
                break
        else:
            tid = len(pool)
            pool.append(end)
        stack = [(root, None)]
        while stack:
            n, parent = stack.pop()
            n.tid = tid
            if parent is not None:
                # Clamp into the parent so 6-decimal rounding in the
                # JSONL can never produce viewer-visible mis-nesting.
                n.start = min(max(n.start, parent.start), parent.end)
                n.end = min(max(n.end, n.start), parent.end)
            placed.append((pid, n))
            for c in sorted(n.children, key=lambda c: (c.start, -c.end)):
                stack.append((c, n))

    times = [n.start for _, n in placed]
    times += [r["ts"] for r in others
              if isinstance(r.get("ts"), (int, float))]

    for pid, n in placed:
        r = n.rec
        args = {k: v for k, v in r.items()
                if k not in ("span", "t0_s", "dur_s", "ts")}
        events.append({
            "name": r["span"], "ph": "X", "pid": pid, "tid": n.tid,
            "ts": n.start, "dur": n.end - n.start,
            "cat": str(r["span"]).split(".")[0],
            "args": args,
        })

    for r in others:
        ts = r.get("ts")
        if not isinstance(ts, (int, float)):
            continue
        ev = r.get("event")
        if ev in ("launch_attempt", "alert"):
            name = (f"launch_attempt:{r.get('outcome', '?')}"
                    if ev == "launch_attempt"
                    else f"alert:{r.get('rule', '?')}")
            events.append({
                "name": name, "ph": "i", "s": "g",
                "pid": pid_spans, "tid": 0, "ts": ts,
                "args": {k: v for k, v in r.items() if k != "ts"},
            })
            continue
        for key in COUNTER_KEYS:
            v = r.get(key)
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                events.append({
                    "name": key, "ph": "C", "pid": pid_counters,
                    "ts": ts, "args": {key: v},
                })

    return events, pools, times


def _rebase(events: List[Dict[str, Any]], t_base: float
            ) -> List[Dict[str, Any]]:
    """Absolute seconds → relative microseconds, in place."""
    for e in events:
        e["ts"] = round((e["ts"] - t_base) * 1e6, 3)
        if e["ph"] == "X":
            e["dur"] = round(e["dur"] * 1e6, 3)
    return events


def _meta_events(names: Dict[int, str], pools: Dict[int, List[float]],
                 used_pids) -> List[Dict[str, Any]]:
    meta: List[Dict[str, Any]] = []
    for pid in sorted(used_pids):
        meta.append({"name": "process_name", "ph": "M", "pid": pid,
                     "args": {"name": names.get(pid, f"pid {pid}")}})
    for pid in sorted(pools):
        for tid in range(len(pools[pid])):
            meta.append({"name": "thread_name", "ph": "M", "pid": pid,
                         "tid": tid, "args": {"name": f"track {tid}"}})
    return meta


def build_trace(records: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Records (any mix of spans / train / serve / launch / alert lines)
    → a Trace Event Format object. Pure function of its input: no clock
    reads, so identical records yield an identical trace."""
    events, pools, times = _shard_events(
        records, _PID_SPANS, _PID_REQUESTS, _PID_COUNTERS)
    t_base = min(times) if times else 0.0
    events = _rebase(events, t_base)
    names = {_PID_SPANS: "process spans", _PID_REQUESTS: "serve requests",
             _PID_COUNTERS: "metrics"}
    meta = _meta_events(names, pools, {e["pid"] for e in events})
    events.sort(key=lambda e: (e.get("ts", 0.0), -e.get("dur", 0.0)))
    return {"traceEvents": meta + events, "displayTimeUnit": "ms"}


def _flow_events(events: List[Dict[str, Any]], start_id: int = 1
                 ) -> List[Dict[str, Any]]:
    """Cross-process flow arrows stitching one distributed request's
    spans into a chain: for every ``trace_id`` carried by a request-level
    X event (the router's ``fleet.request``, each replica's
    ``serve.request`` attempt), consecutive spans on DIFFERENT pids get
    an ``s``→``f`` pair — the Perfetto arrow from router submit to first
    attempt, and from an evacuated attempt to its re-placement."""
    by_trace: Dict[str, List[Dict[str, Any]]] = {}
    for e in events:
        if e.get("ph") != "X":
            continue
        name = str(e.get("name", ""))
        if name != "fleet.request" \
                and not name.startswith(_REQUEST_PREFIX):
            continue
        trace_id = (e.get("args") or {}).get("trace_id")
        if isinstance(trace_id, str):
            by_trace.setdefault(trace_id, []).append(e)
    flows: List[Dict[str, Any]] = []
    fid = start_id
    for trace_id in sorted(by_trace):
        chain = sorted(by_trace[trace_id],
                       key=lambda e: (e["ts"], e["pid"], e["tid"]))
        for a, b in zip(chain, chain[1:]):
            if a["pid"] == b["pid"]:
                continue
            common = {"name": f"trace/{trace_id}", "cat": "flow",
                      "id": fid}
            flows.append(dict(common, ph="s", pid=a["pid"],
                              tid=a["tid"], ts=a["ts"]))
            flows.append(dict(common, ph="f", bp="e", pid=b["pid"],
                              tid=b["tid"], ts=b["ts"]))
            fid += 1
    return flows


def build_fleet_trace(shards: List[Tuple[str, List[Dict[str, Any]]]]
                      ) -> Dict[str, Any]:
    """Merge per-process record shards — ``[(name, records)]``, one per
    router/replica — into ONE Trace Event Format object. Each shard gets
    its own pid block (spans / requests / counters) named after it; all
    shards share one time base (every in-process clock is the same
    ``time.monotonic``, and wall ``ts`` stamps anchor cross-process
    shards), so one request's hops line up on a single zoomable
    timeline, linked by flow arrows (:func:`_flow_events`)."""
    all_events: List[Dict[str, Any]] = []
    all_pools: Dict[int, List[float]] = {}
    names: Dict[int, str] = {}
    times: List[float] = []
    for i, (name, records) in enumerate(shards):
        base = 3 * i
        events, pools, ts = _shard_events(
            records, base + _PID_SPANS, base + _PID_REQUESTS,
            base + _PID_COUNTERS)
        all_events.extend(events)
        all_pools.update(pools)
        names[base + _PID_SPANS] = f"{name} spans"
        names[base + _PID_REQUESTS] = f"{name} requests"
        names[base + _PID_COUNTERS] = f"{name} metrics"
        times.extend(ts)
    t_base = min(times) if times else 0.0
    all_events = _rebase(all_events, t_base)
    flows = _flow_events(all_events)
    meta = _meta_events(names, all_pools,
                        {e["pid"] for e in all_events})
    all_events.extend(flows)
    all_events.sort(key=lambda e: (e.get("ts", 0.0), -e.get("dur", 0.0)))
    return {"traceEvents": meta + all_events, "displayTimeUnit": "ms"}


def validate_trace(trace: Any) -> List[str]:
    """Structural check of a Trace Event Format object; returns a list of
    problems (empty == valid). The cheap no-viewer gate the bench smoke
    and tests run: JSON shape, required fields, non-negative times, and
    per-track stack discipline for complete events."""
    problems: List[str] = []
    if not isinstance(trace, dict) or not isinstance(
            trace.get("traceEvents"), list):
        return ["not a {'traceEvents': [...]} object"]
    try:
        json.dumps(trace)
    except (TypeError, ValueError) as e:
        problems.append(f"not JSON-serializable: {e}")
    tracks: Dict[Tuple[Any, Any], List[Tuple[float, float]]] = {}
    for i, e in enumerate(trace["traceEvents"]):
        if not isinstance(e, dict) or "ph" not in e or "name" not in e:
            problems.append(f"event {i}: missing ph/name")
            continue
        if e["ph"] == "M":
            continue
        ts = e.get("ts")
        if not isinstance(ts, (int, float)) or ts < 0:
            problems.append(f"event {i} ({e['name']}): bad ts {ts!r}")
            continue
        if e["ph"] == "X":
            dur = e.get("dur")
            if not isinstance(dur, (int, float)) or dur < 0:
                problems.append(
                    f"event {i} ({e['name']}): bad dur {dur!r}")
                continue
            tracks.setdefault((e.get("pid"), e.get("tid")), []).append(
                (float(ts), float(ts) + float(dur)))
    # Abutting sibling spans (queue → prefill → decode share their
    # boundary timestamps) have t0_s and dur_s rounded independently to
    # 6 decimals in the JSONL, so their rendered edges can disagree by
    # up to ~1.5 µs without any real mis-nesting.
    eps = 2.0
    for key, ivals in tracks.items():
        ivals.sort(key=lambda p: (p[0], -p[1]))
        stack: List[float] = []
        for start, end in ivals:
            while stack and stack[-1] <= start + eps:
                stack.pop()
            if stack and end > stack[-1] + eps:
                problems.append(
                    f"track pid={key[0]} tid={key[1]}: event "
                    f"[{start},{end}] overlaps span ending {stack[-1]}")
                break
            stack.append(end)
    return problems


def export_trace(path: str, out_path: str) -> Dict[str, Any]:
    """Read a run (file or directory, via report.collect), write
    ``out_path``, return a summary dict (events/spans/records counts plus
    any validation problems)."""
    records, files, skipped = collect(path)
    trace = build_trace(records)
    problems = validate_trace(trace)
    with open(out_path, "w") as fh:
        json.dump(trace, fh)
    n_spans = sum(1 for e in trace["traceEvents"] if e.get("ph") == "X")
    return {
        "out": out_path,
        "records": len(records),
        "files": len(files),
        "skipped_lines": skipped,
        "events": len(trace["traceEvents"]),
        "spans": n_spans,
        "problems": problems,
    }


def fleet_trace_shards(root: str
                       ) -> Tuple[List[Tuple[str, List[Dict[str, Any]]]],
                                  List[str], int]:
    """Discover a fleet run's trace shards: ``*.jsonl`` files directly
    at ``root`` form the ``router`` shard (the router's fleet.request
    spans and signal snapshots live at the fleet root, owning no
    replica), and every per-replica run dir is its own shard. Returns
    (shards, files, skipped_lines)."""
    from .report import fleet_replica_dirs

    if not os.path.isdir(root):
        raise FileNotFoundError(f"no fleet run directory at {root}")
    shards: List[Tuple[str, List[Dict[str, Any]]]] = []
    files: List[str] = []
    skipped = 0
    router_records: List[Dict[str, Any]] = []
    for f in sorted(os.listdir(root)):
        if not f.endswith(".jsonl"):
            continue
        recs, fs, sk = collect(os.path.join(root, f))
        router_records.extend(recs)
        files.extend(fs)
        skipped += sk
    if router_records:
        shards.append(("router", router_records))
    for name, sub in fleet_replica_dirs(root):
        recs, fs, sk = collect(sub)
        shards.append((name, recs))
        files.extend(fs)
        skipped += sk
    return shards, files, skipped


def export_fleet_trace(root: str, out_path: str) -> Dict[str, Any]:
    """Merge every shard under a fleet root into one ``trace.json``
    (see :func:`build_fleet_trace`); returns the summary dict with the
    per-shard breakdown and the cross-process ``flow_events`` count the
    smoke gate asserts on."""
    shards, files, skipped = fleet_trace_shards(root)
    trace = build_fleet_trace(shards)
    problems = validate_trace(trace)
    with open(out_path, "w") as fh:
        json.dump(trace, fh)
    n_spans = sum(1 for e in trace["traceEvents"] if e.get("ph") == "X")
    n_flows = sum(1 for e in trace["traceEvents"] if e.get("ph") == "s")
    return {
        "out": out_path,
        "shards": [name for name, _ in shards],
        "records": sum(len(recs) for _, recs in shards),
        "files": len(files),
        "skipped_lines": skipped,
        "events": len(trace["traceEvents"]),
        "spans": n_spans,
        "flow_events": n_flows,
        "problems": problems,
    }
