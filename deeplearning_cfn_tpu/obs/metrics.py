"""Typed metric instruments and the registry that owns them.

Three instrument kinds, deliberately the Prometheus trio:

- :class:`Counter` — monotonically non-decreasing (``inc`` rejects negative
  deltas). Lifecycle events: requests admitted, store retries, steps run.
- :class:`Gauge` — a value that goes both ways (``set``/``inc``): queue
  depth, slot occupancy, last retry-after hint.
- :class:`Histogram` — observations bucketed into *fixed exponential
  bounds* for export, **plus** the raw samples, because the repo's
  pre-existing p50/p95 numbers (serve queue wait, TTFT, decode latency)
  are exact :func:`percentile` values over raw series and must stay
  byte-identical after the migration. Buckets serve Prometheus; samples
  serve parity.

Every instrument supports per-instrument labels: call ``labels(k=v)`` to
get a child bound to one label-set; series are keyed by the sorted label
items, so ``labels(op="save")`` and ``labels(op="load")`` are independent
series under one registered name.

The registry is get-or-create (``registry.counter("x")`` twice returns the
same object; re-registering a name as a different kind raises) and
thread-safe, because serve's admission path and the trainer's checkpoint
hook thread both record into it.
"""

from __future__ import annotations

import random
import threading
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

LabelKey = Tuple[Tuple[str, str], ...]


def percentile(xs: Sequence[float], q: float) -> Optional[float]:
    """Nearest-rank-with-interpolation percentile; None on empty input
    (matching the bench contract's null-over-zero convention)."""
    if not xs:
        return None
    s = sorted(xs)
    if len(s) == 1:
        return s[0]
    rank = (len(s) - 1) * (q / 100.0)
    lo = int(rank)
    hi = min(lo + 1, len(s) - 1)
    frac = rank - lo
    return s[lo] * (1.0 - frac) + s[hi] * frac


def exponential_buckets(start: float = 1e-4, factor: float = 2.0,
                        count: int = 20) -> Tuple[float, ...]:
    """Fixed exponential bucket upper bounds: start, start*factor, ...
    The default spans 100µs → ~52s, wide enough for step times and
    checkpoint I/O alike."""
    if start <= 0 or factor <= 1 or count < 1:
        raise ValueError("need start > 0, factor > 1, count >= 1")
    out, b = [], start
    for _ in range(count):
        out.append(b)
        b *= factor
    return tuple(out)


DEFAULT_BUCKETS = exponential_buckets()


def _label_key(labels: Dict[str, str]) -> LabelKey:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class _Instrument:
    """Shared label plumbing. A bound child shares the parent's series
    table; only the bound label-set differs."""

    kind = "untyped"

    def __init__(self, name: str, help: str = "", *, lock: threading.Lock,
                 _parent: Optional["_Instrument"] = None,
                 _bound: LabelKey = ()):
        self.name = name
        self.help = help
        self._lock = lock
        self._parent = _parent
        self._bound = _bound

    def labels(self, **labels: str) -> "_Instrument":
        key = _label_key({**dict(self._bound), **labels})
        child = type(self).__new__(type(self))
        _Instrument.__init__(child, self.name, self.help, lock=self._lock,
                             _parent=self._root(), _bound=key)
        return child

    def _root(self) -> "_Instrument":
        return self._parent if self._parent is not None else self


class Counter(_Instrument):
    """Monotonic counter. ``inc(n)`` with n >= 0."""

    kind = "counter"

    def __init__(self, name: str, help: str = "", *, lock: threading.Lock,
                 _parent=None, _bound=()):
        super().__init__(name, help, lock=lock, _parent=_parent,
                         _bound=_bound)
        self._values: Dict[LabelKey, float] = {}

    def inc(self, n: float = 1.0, **labels: str) -> None:
        if n < 0:
            raise ValueError(f"counter {self.name} cannot decrease")
        key = _label_key({**dict(self._bound), **labels})
        root = self._root()
        with self._lock:
            root._values[key] = root._values.get(key, 0.0) + n

    def value(self, **labels: str) -> float:
        key = _label_key({**dict(self._bound), **labels})
        with self._lock:
            return self._root()._values.get(key, 0.0)

    def series(self) -> Dict[LabelKey, float]:
        with self._lock:
            return dict(self._root()._values)


class Gauge(_Instrument):
    """Last-write-wins value; inc/dec allowed."""

    kind = "gauge"

    def __init__(self, name: str, help: str = "", *, lock: threading.Lock,
                 _parent=None, _bound=()):
        super().__init__(name, help, lock=lock, _parent=_parent,
                         _bound=_bound)
        self._values: Dict[LabelKey, float] = {}

    def set(self, v: float, **labels: str) -> None:
        key = _label_key({**dict(self._bound), **labels})
        with self._lock:
            self._root()._values[key] = float(v)

    def inc(self, n: float = 1.0, **labels: str) -> None:
        key = _label_key({**dict(self._bound), **labels})
        root = self._root()
        with self._lock:
            root._values[key] = root._values.get(key, 0.0) + n

    def value(self, **labels: str) -> Optional[float]:
        key = _label_key({**dict(self._bound), **labels})
        with self._lock:
            return self._root()._values.get(key)

    def series(self) -> Dict[LabelKey, float]:
        with self._lock:
            return dict(self._root()._values)


# Raw-sample retention bound per histogram series: exact percentiles up
# to this many observations; beyond it, uniform reservoir sampling keeps
# memory flat (a multi-day serve run observes unboundedly many latencies).
DEFAULT_MAX_SAMPLES = 8192

# Fixed reservoir seed — sampling must be deterministic across runs, per
# the repo rule that nothing in the metrics path reads wall-clock
# randomness (reproducible runs, assertable tests).
_RESERVOIR_SEED = 0x5EED


class _HistSeries:
    __slots__ = ("bucket_counts", "count", "total", "samples", "rng",
                 "first_ts", "last_ts")

    def __init__(self, n_buckets: int):
        self.bucket_counts = [0] * (n_buckets + 1)  # +1 for +Inf
        self.count = 0
        self.total = 0.0
        self.samples: List[float] = []
        self.rng: Optional[random.Random] = None  # created at first evict
        # Observation window bounds — set only from caller-supplied
        # timestamps (observe(ts=...)); the metrics path itself never
        # reads a clock, per the determinism rule above.
        self.first_ts: Optional[float] = None
        self.last_ts: Optional[float] = None


class Histogram(_Instrument):
    """Observations into fixed exponential buckets + retained raw samples.

    ``keep_samples=False`` drops raw retention for genuinely hot series
    where only the bucketed export matters; percentiles then return None.

    Retention is bounded: the first ``max_samples`` observations are kept
    verbatim (percentiles exact — short runs see identical behavior to
    unbounded retention), after which uniform reservoir sampling
    (Algorithm R, deterministic seed) keeps a fixed-size representative
    subset, so percentiles degrade to an unbiased approximation instead
    of memory growing without bound. ``count``/``sum`` stay exact always.
    """

    kind = "histogram"

    def __init__(self, name: str, help: str = "", *,
                 buckets: Sequence[float] = DEFAULT_BUCKETS,
                 keep_samples: bool = True,
                 max_samples: int = DEFAULT_MAX_SAMPLES,
                 lock: threading.Lock,
                 _parent=None, _bound=()):
        super().__init__(name, help, lock=lock, _parent=_parent,
                         _bound=_bound)
        if _parent is None:
            bs = tuple(sorted(float(b) for b in buckets))
            if not bs:
                raise ValueError("histogram needs at least one bucket")
            if max_samples < 1:
                raise ValueError("max_samples must be >= 1")
            self.buckets = bs
            self.keep_samples = keep_samples
            self.max_samples = max_samples
            self._series: Dict[LabelKey, _HistSeries] = {}

    def observe(self, v: float, ts: Optional[float] = None,
                **labels: str) -> None:
        """Record one observation. ``ts`` (optional, caller-supplied —
        never read from a clock here) stamps the series' observation
        window so windowed percentiles can report how much wall time
        backs them."""
        key = _label_key({**dict(self._bound), **labels})
        root = self._root()
        v = float(v)
        with self._lock:
            s = root._series.get(key)
            if s is None:
                s = root._series[key] = _HistSeries(len(root.buckets))
            if ts is not None:
                ts = float(ts)
                if s.first_ts is None or ts < s.first_ts:
                    s.first_ts = ts
                if s.last_ts is None or ts > s.last_ts:
                    s.last_ts = ts
            i = 0
            for i, b in enumerate(root.buckets):
                if v <= b:
                    break
            else:
                i = len(root.buckets)  # +Inf bucket
            s.bucket_counts[i] += 1
            s.count += 1
            s.total += v
            if root.keep_samples:
                if len(s.samples) < root.max_samples:
                    s.samples.append(v)
                else:
                    if s.rng is None:
                        s.rng = random.Random(_RESERVOIR_SEED)
                    j = s.rng.randrange(s.count)
                    if j < root.max_samples:
                        s.samples[j] = v

    def _get(self, labels: Dict[str, str]) -> Optional[_HistSeries]:
        key = _label_key({**dict(self._bound), **labels})
        return self._root()._series.get(key)

    def count(self, **labels: str) -> int:
        with self._lock:
            s = self._get(labels)
            return s.count if s else 0

    def sum(self, **labels: str) -> float:
        with self._lock:
            s = self._get(labels)
            return s.total if s else 0.0

    def samples(self, **labels: str) -> List[float]:
        """The raw series (copy); empty if keep_samples=False or no data."""
        with self._lock:
            s = self._get(labels)
            return list(s.samples) if s else []

    def percentile(self, q: float, **labels: str) -> Optional[float]:
        """Exact percentile over retained samples — the same math (and so
        the same value) as the pre-registry list-based code paths."""
        return percentile(self.samples(**labels), q)

    def mean(self, **labels: str) -> Optional[float]:
        with self._lock:
            s = self._get(labels)
            if not s or s.count == 0:
                return None
            return s.total / s.count

    def series(self) -> Dict[LabelKey, _HistSeries]:
        with self._lock:
            return dict(self._root()._series)


class MetricsRegistry:
    """Get-or-create home for instruments; one per process is typical
    (``obs.trace.get_tracer().registry``), but tests build their own."""

    def __init__(self):
        self._lock = threading.Lock()
        self._instruments: Dict[str, _Instrument] = {}

    def _get_or_create(self, cls, name: str, help: str, **kwargs):
        with self._lock:
            inst = self._instruments.get(name)
            if inst is not None:
                if not isinstance(inst, cls):
                    raise TypeError(
                        f"metric {name!r} already registered as {inst.kind}")
                return inst
            inst = cls(name, help, lock=threading.Lock(), **kwargs)
            self._instruments[name] = inst
            return inst

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get_or_create(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get_or_create(Gauge, name, help)

    def histogram(self, name: str, help: str = "", *,
                  buckets: Sequence[float] = DEFAULT_BUCKETS,
                  keep_samples: bool = True,
                  max_samples: int = DEFAULT_MAX_SAMPLES) -> Histogram:
        return self._get_or_create(Histogram, name, help, buckets=buckets,
                                   keep_samples=keep_samples,
                                   max_samples=max_samples)

    def instruments(self) -> Iterable[_Instrument]:
        with self._lock:
            return list(self._instruments.values())

    def snapshot(self) -> Dict[str, Dict]:
        """Flat, JSON-able view: {name: {kind, series: {label_str: ...}}}.
        Histogram series carry count/sum/p50/p95 (percentiles None when
        samples are not retained)."""
        out: Dict[str, Dict] = {}
        for inst in self.instruments():
            if isinstance(inst, (Counter, Gauge)):
                series = {_fmt_labels(k): v for k, v in inst.series().items()}
            else:
                series = {}
                for k, s in inst.series().items():
                    series[_fmt_labels(k)] = {
                        "count": s.count,
                        "sum": s.total,
                        "p50": percentile(s.samples, 50),
                        "p95": percentile(s.samples, 95),
                        # Honesty fields: how many raw samples actually
                        # back the percentiles (== count until the
                        # reservoir cap bites) and the observation
                        # window they were taken over (None when the
                        # caller supplied no timestamps).
                        "samples_retained": len(s.samples),
                        "window_start_ts": s.first_ts,
                        "window_end_ts": s.last_ts,
                    }
            out[inst.name] = {"kind": inst.kind, "series": series}
        return out


def _fmt_labels(key: LabelKey) -> str:
    if not key:
        return ""
    return ",".join(f"{k}={v}" for k, v in key)
