"""Run reports: turn a metrics.jsonl (or a run directory) into answers.

``dlcfn-tpu obs summarize <metrics.jsonl|dir>`` is the "what happened in
this run" verb the JSONL stream never had — before this, the answer was
hand-grepping. The summarizer is intentionally forgiving: it takes any
mix of train records, serve snapshots, span records, and launcher attempt
events in one stream (or across ``*.jsonl`` files in a directory),
skips torn/partial lines (a crash mid-write must not kill the post-mortem
tool), and reports only the sections it has data for.

Sections:

- **train** — steps reached, step-time p50/p95 (from the additive
  ``step_time_s`` boundary key), examples/sec (last + peak), compile
  time, eval/final-eval metrics, checkpoint store retries.
- **serve** — from the last ``serve_*`` snapshot: tokens/sec, queue
  wait / TTFT / latency / step-latency percentiles, admission counters.
- **spans** — per-name count and duration p50/p95 from span records
  (ckpt.save latency lives here).
- **launch** — per-attempt outcomes (``ok``/``hang``/``crash``) from
  launcher attempt events, mirroring ``JobResult.attempt_outcomes``.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional, Tuple

from .metrics import percentile


def _iter_records(path: str) -> Tuple[List[Dict[str, Any]], int]:
    """Lenient JSONL parse: (records, skipped_line_count)."""
    records, skipped = [], 0
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                skipped += 1
                continue
            if isinstance(rec, dict):
                records.append(rec)
            else:
                skipped += 1
    return records, skipped


def collect(path: str) -> Tuple[List[Dict[str, Any]], List[str], int]:
    """Load records from a file, or every ``*.jsonl`` under a directory
    (one level, plus ``logs/``). Returns (records, files, skipped).

    A nonexistent path raises :class:`FileNotFoundError` with a usable
    message; an unreadable individual file inside a directory is skipped
    (a half-deleted run must still summarize), and an existing-but-empty
    directory yields zero records rather than an exception.
    """
    if os.path.isdir(path):
        files = []
        for sub in ("", "logs"):
            d = os.path.join(path, sub) if sub else path
            if os.path.isdir(d):
                files.extend(
                    os.path.join(d, f) for f in sorted(os.listdir(d))
                    if f.endswith(".jsonl"))
        records, skipped = [], 0
        for f in files:
            try:
                rs, sk = _iter_records(f)
            except OSError:
                continue
            records.extend(rs)
            skipped += sk
        return records, files, skipped
    if not os.path.exists(path):
        raise FileNotFoundError(
            f"no metrics file or run directory at {path}")
    records, skipped = _iter_records(path)
    return records, [path], skipped


def _pct_pair(xs: List[float]) -> Dict[str, Optional[float]]:
    return {"p50": percentile(xs, 50), "p95": percentile(xs, 95)}


def summarize(path: str,
              since_step: Optional[int] = None) -> Dict[str, Any]:
    """Build the run-report dict. Always includes ``source``; train /
    serve / spans / launch sections appear only when present.

    ``since_step`` drops every record carrying a numeric ``step`` below
    it (train records and step-tagged spans alike); step-less records
    (serve snapshots, launch events) always pass — the filter narrows
    the timeline, it doesn't hide subsystems."""
    records, files, skipped = collect(path)
    if since_step is not None:
        records = [r for r in records
                   if not (isinstance(r.get("step"), (int, float))
                           and r["step"] < since_step)]
    out: Dict[str, Any] = {
        "source": {"path": path, "files": len(files),
                   "records": len(records), "skipped_lines": skipped},
    }
    if since_step is not None:
        out["source"]["since_step"] = since_step

    train = [r for r in records if "step" in r and "span" not in r
             and not any(k.startswith("serve_") for k in r)]
    serve = [r for r in records
             if any(k.startswith("serve_") for k in r)]
    spans = [r for r in records if "span" in r]
    launch = [r for r in records if r.get("event") == "launch_attempt"]
    alerts = [r for r in records if r.get("event") == "alert"]

    if train:
        steps = [r["step"] for r in train
                 if isinstance(r.get("step"), (int, float))]
        step_times = [r["step_time_s"] for r in train
                      if isinstance(r.get("step_time_s"), (int, float))]
        eps = [r["examples_per_sec"] for r in train
               if isinstance(r.get("examples_per_sec"), (int, float))]
        losses = [r["loss"] for r in train
                  if isinstance(r.get("loss"), (int, float))]
        compile_s = next(
            (r["compile_s"] for r in train
             if isinstance(r.get("compile_s"), (int, float))), None)
        retries = [r["ckpt_store_retries"] for r in train
                   if isinstance(r.get("ckpt_store_retries"), (int, float))]
        evals = {}
        for r in train:
            for k, v in r.items():
                if k.startswith(("eval_", "final_eval_")):
                    evals[k] = v
        out["train"] = {
            "last_step": max(steps) if steps else None,
            "records": len(train),
            "step_time_s": _pct_pair(step_times),
            "examples_per_sec": {
                "last": eps[-1] if eps else None,
                "peak": max(eps) if eps else None,
            },
            "loss": {
                "first": losses[0] if losses else None,
                "last": losses[-1] if losses else None,
            },
            "compile_s": compile_s,
            "ckpt_store_retries": retries[-1] if retries else None,
            "eval": evals or None,
        }

    if serve:
        last = serve[-1]
        out["serve"] = {
            "records": len(serve),
            # Disaggregated fleets tag each replica's emission with its
            # phase role; co-located snapshots carry no tag.
            "phase": last.get("phase"),
            "queue_depth": last.get("serve_queue_depth"),
            "submitted": last.get("serve_submitted"),
            "admitted": last.get("serve_admitted"),
            "completed": last.get("serve_completed"),
            "rejected": last.get("serve_rejected"),
            "cancelled": last.get("serve_cancelled"),
            "expired": last.get("serve_expired"),
            "tokens_generated": last.get("serve_tokens_generated"),
            "tokens_per_sec": last.get("serve_tokens_per_sec"),
            "slot_occupancy": last.get("serve_slot_occupancy"),
            "steps_per_window": last.get("serve_steps_per_window"),
            "ckpt_load_retries": last.get("serve_ckpt_load_retries"),
            "queue_wait_s": {
                "p50": last.get("serve_queue_wait_p50_s"),
                "p95": last.get("serve_queue_wait_p95_s"),
            },
            "ttft_s": {
                "p50": last.get("serve_ttft_p50_s"),
                "p95": last.get("serve_ttft_p95_s"),
            },
            "latency_s": {
                "p50": last.get("serve_latency_p50_s"),
                "p95": last.get("serve_latency_p95_s"),
            },
            "step_latency_s": {
                "p50": last.get("serve_step_latency_p50_s"),
                "p95": last.get("serve_step_latency_p95_s"),
            },
        }
        # Per-tenant QoS section — only when the snapshot carries the
        # QoS surface (single-tenant runs keep the exact pre-QoS keys).
        if last.get("serve_qos_by_class") is not None:
            out["serve"]["qos"] = {
                "by_class": last.get("serve_qos_by_class"),
                "preemptions": last.get("serve_preemptions"),
                "preempted_tokens_replayed":
                    last.get("serve_preempted_tokens_replayed"),
                "token_loss": last.get("serve_qos_token_loss"),
                "fair_share_violation_max":
                    last.get("serve_fair_share_violation_max"),
            }
        # Chunked-prefill section — only when the snapshot carries the
        # chunk surface (--prefill-chunk runs).
        if last.get("serve_chunk_size") is not None:
            out["serve"]["chunked_prefill"] = {
                "chunk_size": last.get("serve_chunk_size"),
                "chunk_ticks": last.get("serve_chunk_ticks"),
                "chunk_tokens": last.get("serve_chunk_tokens"),
                "chunks_per_tick": {
                    "p50": last.get("serve_chunks_per_tick_p50"),
                    "p95": last.get("serve_chunks_per_tick_p95"),
                },
                "partial_rows": last.get("serve_chunk_partial_rows"),
                "stall_ticks_avoided":
                    last.get("serve_chunk_stall_ticks_avoided"),
                "ticks_per_prefill": {
                    "p50": last.get("serve_chunk_ticks_per_prefill_p50"),
                    "p95": last.get("serve_chunk_ticks_per_prefill_p95"),
                },
            }
        # Radix token-prefix KV cache section — only when the snapshot
        # carries the radix surface (--radix-cache runs).
        if last.get("serve_radix_nodes") is not None:
            out["serve"]["radix"] = {
                "nodes": last.get("serve_radix_nodes"),
                "blocks": last.get("serve_radix_blocks"),
                "hits": last.get("serve_radix_hits"),
                "misses": last.get("serve_radix_misses"),
                "hit_rate": last.get("serve_radix_hit_rate"),
                "instant_completes":
                    last.get("serve_radix_instant_completes"),
                "hit_tokens": last.get("serve_radix_hit_tokens"),
                "shared_block_ratio":
                    last.get("serve_radix_shared_block_ratio"),
                "evictions": last.get("serve_radix_evictions"),
                "evictions_by_cause":
                    last.get("serve_radix_evictions_by_cause"),
            }

    if spans:
        by_name: Dict[str, List[float]] = {}
        fails: Dict[str, int] = {}
        for r in spans:
            name = r["span"]
            if isinstance(r.get("dur_s"), (int, float)):
                by_name.setdefault(name, []).append(r["dur_s"])
            if r.get("ok") is False:
                fails[name] = fails.get(name, 0) + 1
        out["spans"] = {
            name: {"count": len(durs), **_pct_pair(durs),
                   **({"failed": fails[name]} if name in fails else {})}
            for name, durs in sorted(by_name.items())
        }

    if launch:
        outcomes = [r.get("outcome") for r in launch]
        out["launch"] = {
            "attempts": len(launch),
            "outcomes": outcomes,
            "success": bool(launch[-1].get("success",
                                           outcomes[-1] == "ok")),
            "restarts": max(0, len(launch) - 1),
        }

    if alerts:
        out["alerts"] = {
            "count": len(alerts),
            "last_rule": str(alerts[-1].get("rule", "?")),
        }

    return out


def _fmt(v: Any, unit: str = "") -> str:
    if v is None:
        return "-"
    if isinstance(v, bool):
        return "yes" if v else "no"
    if isinstance(v, float):
        if v != 0 and abs(v) < 0.001:
            return f"{v:.2e}{unit}"
        return f"{v:.4g}{unit}"
    return f"{v}{unit}"


def render_report(summary: Dict[str, Any]) -> str:
    """Human-readable text rendering of :func:`summarize` output."""
    L: List[str] = []
    src = summary["source"]
    L.append(f"run report: {src['path']}")
    L.append(f"  files={src['files']} records={src['records']}"
             + (f" skipped_lines={src['skipped_lines']}"
                if src["skipped_lines"] else ""))

    t = summary.get("train")
    if t:
        L.append("train:")
        L.append(f"  last step           {_fmt(t['last_step'])}")
        st = t["step_time_s"]
        L.append(f"  step time p50/p95   {_fmt(st['p50'], 's')} / "
                 f"{_fmt(st['p95'], 's')}")
        e = t["examples_per_sec"]
        L.append(f"  examples/sec        last {_fmt(e['last'])}  "
                 f"peak {_fmt(e['peak'])}")
        lo = t["loss"]
        L.append(f"  loss                {_fmt(lo['first'])} -> "
                 f"{_fmt(lo['last'])}")
        L.append(f"  compile             {_fmt(t['compile_s'], 's')}")
        L.append(f"  ckpt store retries  {_fmt(t['ckpt_store_retries'])}")
        if t["eval"]:
            for k, v in sorted(t["eval"].items()):
                L.append(f"  {k:<19} {_fmt(v)}")

    s = summary.get("serve")
    if s:
        L.append("serve:")
        L.append(f"  submitted/admitted/completed  "
                 f"{_fmt(s['submitted'])}/{_fmt(s['admitted'])}/"
                 f"{_fmt(s['completed'])}")
        L.append(f"  rejected/cancelled/expired    "
                 f"{_fmt(s['rejected'])}/{_fmt(s['cancelled'])}/"
                 f"{_fmt(s['expired'])}")
        L.append(f"  tokens/sec          {_fmt(s['tokens_per_sec'])}  "
                 f"(total {_fmt(s['tokens_generated'])})")
        L.append(f"  slot occupancy      {_fmt(s['slot_occupancy'])}")
        L.append(f"  steps/window        {_fmt(s['steps_per_window'])}")
        L.append(f"  ckpt load retries   {_fmt(s['ckpt_load_retries'])}")
        for key, label in (("queue_wait_s", "queue wait"),
                           ("ttft_s", "ttft"),
                           ("latency_s", "latency"),
                           ("step_latency_s", "step latency")):
            p = s[key]
            L.append(f"  {label:<19} p50 {_fmt(p['p50'], 's')}  "
                     f"p95 {_fmt(p['p95'], 's')}")
        q = s.get("qos")
        if q:
            L.append(f"  preemptions         {_fmt(q['preemptions'])}  "
                     f"(replayed {_fmt(q['preempted_tokens_replayed'])}, "
                     f"lost {_fmt(q['token_loss'])})")
            L.append(f"  fair-share viol.    "
                     f"{_fmt(q['fair_share_violation_max'])}")
            for cls, v in sorted((q.get("by_class") or {}).items()):
                L.append(f"  qos {cls:<15} n={_fmt(v.get('completed')):<5} "
                         f"p50 {_fmt(v.get('latency_p50_s'), 's')}  "
                         f"p95 {_fmt(v.get('latency_p95_s'), 's')}")
        ck = s.get("chunked_prefill")
        if ck:
            tp = ck.get("ticks_per_prefill") or {}
            L.append(f"  chunked prefill     chunk {_fmt(ck['chunk_size'])} "
                     f"tok/tick  {_fmt(ck['chunk_ticks'])} ticks / "
                     f"{_fmt(ck['chunk_tokens'])} tokens")
            L.append(f"  chunk interleave    "
                     f"{_fmt(ck['stall_ticks_avoided'])} stall ticks "
                     f"avoided, ticks/prefill p50 {_fmt(tp.get('p50'))}  "
                     f"p95 {_fmt(tp.get('p95'))}")
        rx = s.get("radix")
        if rx:
            L.append(f"  radix cache         {_fmt(rx['nodes'])} nodes / "
                     f"{_fmt(rx['blocks'])} blocks  "
                     f"hit rate {_fmt(rx['hit_rate'])}")
            L.append(f"  radix reuse         {_fmt(rx['hits'])} hits "
                     f"({_fmt(rx['instant_completes'])} instant), "
                     f"{_fmt(rx['hit_tokens'])} tokens, "
                     f"shared-block ratio "
                     f"{_fmt(rx['shared_block_ratio'])}")
            causes = rx.get("evictions_by_cause") or {}
            cause_txt = ", ".join(f"{c}={n}"
                                  for c, n in sorted(causes.items()))
            L.append(f"  radix evictions     {_fmt(rx['evictions'])}"
                     + (f"  ({cause_txt})" if cause_txt else ""))

    sp = summary.get("spans")
    if sp:
        L.append("spans:")
        for name, v in sp.items():
            extra = f"  failed {v['failed']}" if "failed" in v else ""
            L.append(f"  {name:<19} n={v['count']:<5} "
                     f"p50 {_fmt(v['p50'], 's')}  "
                     f"p95 {_fmt(v['p95'], 's')}{extra}")

    la = summary.get("launch")
    if la:
        L.append("launch:")
        L.append(f"  attempts            {la['attempts']} "
                 f"({', '.join(str(o) for o in la['outcomes'])})")
        L.append(f"  success             {_fmt(la['success'])}  "
                 f"restarts {la['restarts']}")

    al = summary.get("alerts")
    if al:
        L.append("alerts:")
        L.append(f"  count               {al['count']} "
                 f"(last: {al['last_rule']})")

    if len(L) == 2:
        L.append("(no train, serve, span, or launch records found)")
    return "\n".join(L)


# -- fleet aggregate ---------------------------------------------------------


def fleet_replica_dirs(root: str) -> List[Tuple[str, str]]:
    """The per-replica run dirs under a fleet root: immediate
    subdirectories that contain any ``*.jsonl`` (top level or
    ``logs/``), sorted by name. Returns [(name, path)]."""
    if not os.path.isdir(root):
        raise FileNotFoundError(f"no fleet run directory at {root}")
    found = []
    for name in sorted(os.listdir(root)):
        sub = os.path.join(root, name)
        if not os.path.isdir(sub):
            continue
        has_jsonl = any(
            f.endswith(".jsonl") for f in os.listdir(sub)) or (
            os.path.isdir(os.path.join(sub, "logs"))
            and any(f.endswith(".jsonl")
                    for f in os.listdir(os.path.join(sub, "logs"))))
        if has_jsonl:
            found.append((name, sub))
    return found


def _autoscale_events(root: str,
                      event: str = "scale_event") -> List[Dict[str, Any]]:
    """Every ``event``-typed record (``scale_event`` by default; the
    brownout fold passes ``degrade_event``) in the fleet root's own
    top-level ``*.jsonl`` shards (the bench writes them to
    ``<root>/autoscale.jsonl`` / ``<root>/degrade.jsonl``), in
    record-time order."""
    events: List[Dict[str, Any]] = []
    for f in sorted(os.listdir(root)):
        p = os.path.join(root, f)
        if not f.endswith(".jsonl") or not os.path.isfile(p):
            continue
        try:
            recs, _ = _iter_records(p)
        except OSError:
            continue
        events.extend(r for r in recs if r.get("event") == event)
    events.sort(key=lambda r: r["ts"]
                if isinstance(r.get("ts"), (int, float)) else 0.0)
    return events


def fold_autoscale(events: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Fold a scale-event stream into the autoscale summary: counters,
    the derived controller state (steady / scaling-up / draining), and
    the last event's what/why — the same fold ``obs tail --fleet``
    applies live."""
    ups = sum(1 for e in events if e.get("action") == "scale_up")
    downs = sum(1 for e in events if e.get("action") == "scale_down")
    drained = sum(1 for e in events
                  if e.get("action") == "scale_down" and e.get("drained"))
    open_drains = set()
    for e in events:
        if e.get("action") == "drain_begin":
            open_drains.add(e.get("replica"))
        elif e.get("action") == "scale_down":
            open_drains.discard(e.get("replica"))
    if open_drains:
        state = "draining"
    elif events and events[-1].get("action") == "scale_up":
        state = "scaling-up"
    else:
        state = "steady"
    last = events[-1] if events else {}
    return {
        "events": len(events),
        "scale_ups": ups,
        "scale_downs": downs,
        "drained_scale_downs": drained,
        "state": state,
        "last_action": last.get("action"),
        "last_replica": last.get("replica"),
        "last_phase": last.get("phase"),
        "last_reason": last.get("reason"),
    }


def fold_degrade(events: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Fold a degrade-event stream into the brownout summary: the
    current level (the last transition's), transition counters, and
    the last what/why — the same fold ``obs tail --fleet`` applies
    live."""
    degrades = sum(1 for e in events if e.get("action") == "degrade")
    recovers = sum(1 for e in events if e.get("action") == "recover")
    last = events[-1] if events else {}
    return {
        "events": len(events),
        "degrades": degrades,
        "recovers": recovers,
        "level": last.get("level", 0),
        "level_name": last.get("level_name", "normal"),
        "last_action": last.get("action"),
        "last_reason": last.get("reason"),
    }


def summarize_fleet(root: str) -> Dict[str, Any]:
    """Fleet-wide report over a directory of per-replica run dirs (the
    ReplicaSupervisor layout: ``<root>/replica-<i>/``). Per-replica
    sections are full :func:`summarize` outputs; the ``fleet`` section
    is the aggregate an operator triages from — total tokens/sec across
    replicas, the WORST p95 request latency (the fleet is as slow as
    its slowest replica), and the total alert count.

    The aggregate is computed by replaying every replica's records
    through the :class:`~.signals.SignalBus` — the same fold
    ``obs tail --fleet`` and the autoscale controller consume — so the
    live and post-hoc views can never drift apart. The full
    signal-snapshot rides along under ``"signals"``."""
    from .signals import SignalBus

    dirs = fleet_replica_dirs(root)
    replicas: Dict[str, Any] = {}
    total_records = 0
    bus = SignalBus(names=[name for name, _ in dirs])
    for name, path in dirs:
        s = summarize(path)
        replicas[name] = s
        total_records += s["source"]["records"]
        records, _, _ = collect(path)
        for rec in records:
            bus.observe(name, rec)
    agg = bus.fleet()
    # Per-phase queue depth: a starved decode pool must be visible as
    # its own number, not folded into the fleet aggregate. Co-located
    # replicas (no phase tag) fold under "both".
    queue_by_phase: Dict[str, int] = {}
    for name, s in replicas.items():
        sv = s.get("serve") or {}
        phase = sv.get("phase") or "both"
        qd = sv.get("queue_depth")
        if isinstance(qd, (int, float)):
            queue_by_phase[phase] = \
                queue_by_phase.get(phase, 0) + int(qd)
    out: Dict[str, Any] = {
        "source": {"path": root, "replicas": len(dirs),
                   "records": total_records},
        "fleet": {
            "tokens_per_sec": round(agg["tokens_per_sec"], 2)
            if isinstance(agg["tokens_per_sec"], (int, float)) else None,
            "tokens_generated": agg["tokens_generated"] or None,
            "worst_latency_p95_s": agg["worst_latency_p95_s"],
            "alerts": agg["alerts"],
            "submitted": agg["submitted"] or None,
            "completed": agg["completed"] or None,
            "rejected": agg["rejected"] or None,
            "launch_attempts": agg["launch_attempts"] or None,
            "launch_restarts": agg["launch_restarts"],
            "launch_failed_replicas": agg["launch_failed_replicas"],
            "queue_depth_by_phase": queue_by_phase or None,
        },
        "signals": bus.snapshot(),
        "replicas": replicas,
    }
    # Autoscale section only when the run actually scaled — legacy
    # fixed-membership layouts summarize byte-identically.
    events = _autoscale_events(root)
    if events:
        out["autoscale"] = fold_autoscale(events)
    # Brownout section under the same rule: only when transitions were
    # actually audited.
    degrade_events = _autoscale_events(root, event="degrade_event")
    if degrade_events:
        out["degrade"] = fold_degrade(degrade_events)
    return out


def fleet_status_line(summary: Dict[str, Any]) -> str:
    """The one-line fleet status (`dlcfn-tpu fleet status`)."""
    f = summary["fleet"]
    n = summary["source"]["replicas"]
    line = (f"fleet {n} replica(s) | {_fmt(f['tokens_per_sec'])} tok/s | "
            f"done {_fmt(f['completed'])}/{_fmt(f['submitted'])} | "
            f"worst p95 {_fmt(f['worst_latency_p95_s'], 's')} | "
            f"alerts {f['alerts']}")
    a = summary.get("autoscale")
    if a:
        line += (f" | scale {a['state']} "
                 f"+{a['scale_ups']}/-{a['scale_downs']}")
    d = summary.get("degrade")
    if d:
        line += f" | brownout L{d['level']} ({d['level_name']})"
    return line


def render_fleet_report(summary: Dict[str, Any]) -> str:
    """Human rendering of :func:`summarize_fleet`: the aggregate line,
    then one compact line per replica."""
    L: List[str] = []
    src = summary["source"]
    L.append(f"fleet report: {src['path']}")
    L.append(f"  {fleet_status_line(summary)}")
    f = summary["fleet"]
    if f["launch_attempts"]:
        failed = (f" FAILED: {', '.join(f['launch_failed_replicas'])}"
                  if f["launch_failed_replicas"] else "")
        L.append(f"  launch: {f['launch_attempts']} attempt(s), "
                 f"{f['launch_restarts']} restart(s){failed}")
    a = summary.get("autoscale")
    if a:
        why = f" — {a['last_reason']}" if a.get("last_reason") else ""
        L.append(f"  autoscale: {a['state']} | "
                 f"+{a['scale_ups']} up / -{a['scale_downs']} down "
                 f"({a['drained_scale_downs']} drained) | last: "
                 f"{a['last_action']} {a['last_replica']}{why}")
    d = summary.get("degrade")
    if d:
        dwhy = f" — {d['last_reason']}" if d.get("last_reason") else ""
        L.append(f"  brownout: level {d['level']} ({d['level_name']}) | "
                 f"{d['degrades']} degrade(s) / {d['recovers']} "
                 f"recover(s) | last: {d['last_action']}{dwhy}")
    qbp = f.get("queue_depth_by_phase")
    if qbp and set(qbp) != {"both"}:
        L.append("  queue depth by phase: " + "  ".join(
            f"{phase}={qbp[phase]}" for phase in sorted(qbp)))
    for name, s in summary["replicas"].items():
        sv = s.get("serve") or {}
        la = s.get("launch") or {}
        al = s.get("alerts") or {}
        lat = sv.get("latency_s") or {}
        bits = [f"{_fmt(sv.get('tokens_per_sec'))} tok/s",
                f"done {_fmt(sv.get('completed'))}/"
                f"{_fmt(sv.get('submitted'))}",
                f"p95 {_fmt(lat.get('p95'), 's')}"]
        if sv.get("phase"):
            bits.insert(0, f"phase {sv['phase']} "
                           f"(q {_fmt(sv.get('queue_depth'))})")
        if la:
            bits.append(
                f"launch {','.join(str(o) for o in la['outcomes'])}")
        if al:
            bits.append(f"alerts {al['count']}")
        L.append(f"  {name:<16} " + " | ".join(bits))
    if not summary["replicas"]:
        L.append("  (no replica run dirs with records found)")
    return "\n".join(L)
