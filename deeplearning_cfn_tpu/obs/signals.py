"""The fleet signal bus: one rolling, time-windowed aggregator that
`obs tail --fleet`, `obs summarize --fleet`, and the (future) autoscale
controller all read from.

Before this module, the fleet aggregate was computed twice — once
post-hoc in :func:`~.report.summarize_fleet` and once live in
``obs tail`` — with separately maintained semantics. The bus is the
single fold: feed it the per-replica record streams (serve snapshots,
alert events, launch attempts, spans) in arrival order and it maintains

- **last-value state** per replica (queue depth, tokens/sec, admission
  counters, latency p95, retry-after hint, spec accept rate, slot
  occupancy) — exactly the values the old aggregations used, so the
  reported numbers are unchanged by construction;
- **rolling windows** over the headline series (latency p95, queue
  depth, tokens/sec), pruned to ``window_s`` of record time, each
  window honest about how many samples back it and what time span they
  cover;
- the **fleet aggregate** (sum tokens/sec, worst p95, done/submitted,
  alert count, launch health) consumed by the status line and report.

Determinism: the bus never reads a clock. Record time comes from the
record's own ``ts`` field (stamped by :class:`~..metrics.jsonl
.MetricsWriter` at write time); records without one advance a
monotonic per-bus sequence counter instead, so replaying the same
shards always yields the same snapshot.

``snapshot()`` returns the signal-snapshot dict (JSON-able; one per
line in a ``signals.jsonl`` stream) documented in
docs/OBSERVABILITY.md — the wire format the autoscaler reads.
"""

from __future__ import annotations

from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from .metrics import percentile

DEFAULT_WINDOW_S = 60.0

# serve_* snapshot keys folded into last-value replica state, keyed by
# the signal name they surface as.
_LAST_VALUE_KEYS: Tuple[Tuple[str, str], ...] = (
    ("queue_depth", "serve_queue_depth"),
    ("tokens_per_sec", "serve_tokens_per_sec"),
    ("tokens_generated", "serve_tokens_generated"),
    ("latency_p95_s", "serve_latency_p95_s"),
    ("completed", "serve_completed"),
    ("submitted", "serve_submitted"),
    ("rejected", "serve_rejected"),
    ("retry_after_hint_s", "serve_retry_after_hint_s"),
    ("spec_accept_rate", "serve_spec_accept_rate"),
    ("utilization", "serve_slot_occupancy"),
)

# Series that additionally get a rolling window.
_WINDOWED = ("latency_p95_s", "queue_depth", "tokens_per_sec")


class RollingWindow:
    """(ts, value) pairs pruned to the trailing ``window_s`` of record
    time. Percentiles are exact over the surviving samples; the
    snapshot always says how many samples and what time span back
    them."""

    def __init__(self, window_s: float = DEFAULT_WINDOW_S):
        if window_s <= 0:
            raise ValueError("window_s must be > 0")
        self.window_s = float(window_s)
        self._items: deque = deque()

    def add(self, ts: float, value: float) -> None:
        self._items.append((float(ts), float(value)))
        self._prune(ts)

    def _prune(self, now: float) -> None:
        cutoff = float(now) - self.window_s
        items = self._items
        while items and items[0][0] < cutoff:
            items.popleft()

    def values(self) -> List[float]:
        return [v for _, v in self._items]

    def count(self) -> int:
        return len(self._items)

    def last(self) -> Optional[float]:
        return self._items[-1][1] if self._items else None

    def percentile(self, q: float) -> Optional[float]:
        return percentile(self.values(), q)

    def bounds(self) -> Tuple[Optional[float], Optional[float]]:
        if not self._items:
            return None, None
        return self._items[0][0], self._items[-1][0]

    def snapshot(self) -> Dict[str, Any]:
        start, end = self.bounds()
        return {
            "p50": self.percentile(50),
            "p95": self.percentile(95),
            "last": self.last(),
            "samples": self.count(),
            "window_start_ts": start,
            "window_end_ts": end,
        }


class ReplicaSignal:
    """One replica's folded state: last values, rolling windows, alert
    and launch health. The fold rules are byte-compatible with the old
    ``TailState`` serve handling and ``summarize``'s last-snapshot
    semantics."""

    def __init__(self, window_s: float = DEFAULT_WINDOW_S):
        self.records = 0
        self.last: Dict[str, Any] = {name: None for name, _ in
                                     _LAST_VALUE_KEYS}
        self.windows: Dict[str, RollingWindow] = {
            name: RollingWindow(window_s) for name in _WINDOWED}
        self.alerts = 0
        self.last_alert: Optional[str] = None
        self.span_failures = 0
        self.launch_attempts = 0
        self.launch_outcomes: List[str] = []
        self.launch_outcome: Optional[str] = None
        self.launch_success: Optional[bool] = None

    def observe(self, rec: Dict[str, Any], ts: float) -> None:
        self.records += 1
        if rec.get("event") == "alert":
            self.alerts += 1
            self.last_alert = str(rec.get("rule", "?"))
            return
        if rec.get("event") == "launch_attempt":
            outcome = str(rec.get("outcome", "?"))
            self.launch_attempts += 1
            self.launch_outcomes.append(outcome)
            self.launch_outcome = outcome
            self.launch_success = bool(rec.get("success", outcome == "ok"))
            return
        if "span" in rec:
            if rec.get("ok") is False:
                self.span_failures += 1
            return
        if any(k.startswith("serve_") for k in rec):
            for name, key in _LAST_VALUE_KEYS:
                if key in rec:
                    self.last[name] = rec[key]
            for name in _WINDOWED:
                key = _key_of(name)
                if isinstance(rec.get(key), (int, float)):
                    self.windows[name].add(ts, rec[key])

    @property
    def launch_restarts(self) -> int:
        return max(0, self.launch_attempts - 1)

    def snapshot(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {"records": self.records, **self.last}
        out["windowed"] = {name: w.snapshot()
                          for name, w in self.windows.items()}
        out["alerts"] = self.alerts
        if self.last_alert is not None:
            out["last_alert"] = self.last_alert
        if self.span_failures:
            out["span_failures"] = self.span_failures
        if self.launch_attempts:
            out["launch"] = {
                "attempts": self.launch_attempts,
                "outcomes": list(self.launch_outcomes),
                "outcome": self.launch_outcome,
                "success": self.launch_success,
                "restarts": self.launch_restarts,
            }
        return out


def _key_of(name: str) -> str:
    for n, key in _LAST_VALUE_KEYS:
        if n == name:
            return key
    raise KeyError(name)


class SignalBus:
    """Fold per-replica record streams into the fleet aggregate.

    ``observe(replica, record)`` routes one record; ``fleet()`` is the
    aggregate dict; ``snapshot()`` is the serialized signal-snapshot
    (``{"event": "signal_snapshot", ...}``)."""

    def __init__(self, window_s: float = DEFAULT_WINDOW_S,
                 names: Optional[List[str]] = None):
        self.window_s = float(window_s)
        self.replicas: Dict[str, ReplicaSignal] = {}
        self._seq = 0
        for n in names or []:
            self.replica(n)

    def replica(self, name: str) -> ReplicaSignal:
        sig = self.replicas.get(name)
        if sig is None:
            sig = self.replicas[name] = ReplicaSignal(self.window_s)
        return sig

    def observe(self, replica: str, rec: Dict[str, Any],
                ts: Optional[float] = None) -> None:
        self._seq += 1
        if ts is None:
            ts = rec.get("ts")
        if not isinstance(ts, (int, float)):
            ts = float(self._seq)
        self.replica(replica).observe(rec, float(ts))

    # -- aggregate ---------------------------------------------------------

    def fleet(self) -> Dict[str, Any]:
        """The fleet aggregate. Sums/extrema are over replicas' last
        values (the semantics `summarize --fleet` and the status line
        always had); ``None`` means "no replica reported it", matching
        the null-over-zero convention."""

        def _vals(name):
            return [s.last[name] for s in self.replicas.values()
                    if isinstance(s.last[name], (int, float))]

        def _sum(name):
            vals = _vals(name)
            return sum(vals) if vals else None

        p95s = _vals("latency_p95_s")
        hints = _vals("retry_after_hint_s")
        accept = _vals("spec_accept_rate")
        util = _vals("utilization")
        launch_attempts = sum(s.launch_attempts
                              for s in self.replicas.values())
        failed = sorted(n for n, s in self.replicas.items()
                        if s.launch_attempts and not s.launch_success)
        return {
            "replicas": len(self.replicas),
            "replicas_live": sum(1 for s in self.replicas.values()
                                 if s.records),
            "queue_depth": _sum("queue_depth"),
            "tokens_per_sec": _sum("tokens_per_sec"),
            "tokens_generated": _sum("tokens_generated"),
            "submitted": _sum("submitted"),
            "completed": _sum("completed"),
            "rejected": _sum("rejected"),
            "worst_latency_p95_s": max(p95s) if p95s else None,
            "retry_after_pressure_s": max(hints) if hints else None,
            "spec_accept_rate_min": min(accept) if accept else None,
            "utilization_mean": (sum(util) / len(util)) if util else None,
            "alerts": sum(s.alerts for s in self.replicas.values()),
            "launch_attempts": launch_attempts,
            "launch_restarts": sum(s.launch_restarts
                                   for s in self.replicas.values()),
            "launch_failed_replicas": failed,
        }

    def snapshot(self) -> Dict[str, Any]:
        """One signal-snapshot record (JSON-able): the autoscaler wire
        format, also what ``bench --fleet`` serializes to
        ``signals.jsonl``."""
        return {
            "event": "signal_snapshot",
            "seq": self._seq,
            "window_s": self.window_s,
            "fleet": self.fleet(),
            "replicas": {n: s.snapshot()
                         for n, s in sorted(self.replicas.items())},
        }
