"""Export sinks: where instruments and spans leave the process.

Three, per the subsystem contract:

- :class:`JsonlSink` — appends span/metric records to the run's existing
  ``metrics.jsonl`` through :class:`~..metrics.jsonl.MetricsWriter`, so
  one stream still tells the whole story. Purely additive: old keys keep
  their bytes; span records are new lines with a ``"span"`` key that
  ``dlcfn-tpu metrics`` and the bench harness already ignore.
- :func:`write_prometheus` — renders a :class:`MetricsRegistry` snapshot
  in Prometheus text exposition format (version 0.0.4) to a file,
  atomically (tmp + rename), for scrape-by-file setups (node_exporter
  textfile collector — no server dependency, same as the rest of the
  repo's no-new-deps posture).
- :class:`MemorySink` — a list, for tests.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List

from .metrics import Counter, Gauge, Histogram, MetricsRegistry


class MemorySink:
    """Collects records in memory; tests assert on ``.records``."""

    def __init__(self):
        self.records: List[Dict[str, Any]] = []

    def write(self, record: Dict[str, Any]) -> None:
        self.records.append(dict(record))

    def by_span(self, name: str) -> List[Dict[str, Any]]:
        return [r for r in self.records if r.get("span") == name]


class JsonlSink:
    """Adapts a MetricsWriter (or anything with ``write(dict)``) as a span
    sink. ``also_stdout`` should stay False for span streams — spans are
    high-rate and the stdout stream is the human one."""

    def __init__(self, writer):
        self._writer = writer

    def write(self, record: Dict[str, Any]) -> None:
        self._writer.write(record)

    def close(self) -> None:
        close = getattr(self._writer, "close", None)
        if close is not None:
            close()


def _prom_name(name: str) -> str:
    out = []
    for ch in name:
        out.append(ch if (ch.isalnum() or ch in "_:") else "_")
    s = "".join(out)
    if s and s[0].isdigit():
        s = "_" + s
    return s


def _prom_labels(key) -> str:
    if not key:
        return ""
    inner = ",".join(
        '%s="%s"' % (_prom_name(k), str(v).replace("\\", "\\\\")
                     .replace('"', '\\"').replace("\n", "\\n"))
        for k, v in key
    )
    return "{" + inner + "}"


def _prom_num(v: float) -> str:
    if v == float("inf"):
        return "+Inf"
    f = float(v)
    return repr(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


def render_prometheus(registry: MetricsRegistry) -> str:
    """Registry → Prometheus text exposition format (one snapshot)."""
    lines: List[str] = []
    for inst in registry.instruments():
        name = _prom_name(inst.name)
        if inst.help:
            lines.append(f"# HELP {name} {inst.help}")
        lines.append(f"# TYPE {name} {inst.kind}")
        if isinstance(inst, (Counter, Gauge)):
            for key, v in sorted(inst.series().items()):
                lines.append(f"{name}{_prom_labels(key)} {_prom_num(v)}")
        elif isinstance(inst, Histogram):
            for key, s in sorted(inst.series().items()):
                cum = 0
                for b, c in zip(inst.buckets, s.bucket_counts):
                    cum += c
                    le = _prom_labels(key + (("le", _prom_num(b)),))
                    lines.append(f"{name}_bucket{le} {cum}")
                cum += s.bucket_counts[-1]
                le = _prom_labels(key + (("le", "+Inf"),))
                lines.append(f"{name}_bucket{le} {cum}")
                lines.append(f"{name}_sum{_prom_labels(key)} "
                             f"{_prom_num(s.total)}")
                lines.append(f"{name}_count{_prom_labels(key)} {s.count}")
    return "\n".join(lines) + ("\n" if lines else "")


def write_prometheus(registry: MetricsRegistry, path: str) -> str:
    """Atomic snapshot write (tmp + rename — a scraper never sees a torn
    file). Returns the rendered text."""
    text = render_prometheus(registry)
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w") as fh:
        fh.write(text)
    os.replace(tmp, path)
    return text
