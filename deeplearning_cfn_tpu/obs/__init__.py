"""Unified observability subsystem (SURVEY.md §6, grown up).

The reference stack's observability was "stdout prints + CloudWatch agent";
the rebuild had grown three disjoint substitutes (train JSONL, serve's
ad-hoc dict, profiling timers) with no shared naming and no spans. ``obs``
is the one telemetry layer under all of them:

- :mod:`obs.metrics` — a :class:`MetricsRegistry` of typed instruments
  (:class:`Counter`, :class:`Gauge`, :class:`Histogram` with fixed
  exponential buckets), per-instrument labels, and the shared
  :func:`percentile` math every p50/p95 in the repo goes through.
- :mod:`obs.trace` — a low-overhead span tracer: ``with span("ckpt.save",
  step=N):`` produces deterministic monotonic-clock span records with
  parent/child nesting (ids from a counter, never wall-clock-randomized).
  ``DLCFN_OBS_OFF=1`` turns every span into a no-op.
- :mod:`obs.sinks` — pluggable exporters: the existing JSONL event stream
  (byte-compatible for old keys — span records are purely additive), a
  Prometheus text-format snapshot file, and an in-memory sink for tests.
- :mod:`obs.report` — ``dlcfn-tpu obs summarize <metrics.jsonl|dir>``:
  a run report (step-time p50/p95, tokens/sec, checkpoint latency +
  retries, queue wait, per-attempt launch outcomes) for train and serve
  runs alike.
- :mod:`obs.export` — ``dlcfn-tpu obs export``: span/metric JSONL →
  Chrome/Perfetto trace-event ``trace.json`` (the run as a flame view).
- :mod:`obs.slo` — ``dlcfn-tpu obs check``: declarative SLO rules
  (threshold / percentile / drop) streamed over the record stream,
  emitting ``alert`` events and a CI-gateable exit code.
- :mod:`obs.diff` — ``dlcfn-tpu obs diff``: align two runs' metric
  series, report p50/p95 deltas, flag direction-aware regressions.
- :mod:`obs.tail` — ``dlcfn-tpu obs tail``: truncation-tolerant live
  follower rendering a one-line train/serve status as the JSONL grows.
- :mod:`obs.signals` — the fleet signal bus: per-replica rolling-window
  aggregates (windowed p50/p95 latency, queue depth, tokens/sec, spec
  accept rate, retry-after pressure) folded from the same JSONL streams,
  serialized as ``signal_snapshot`` records — the one fold
  ``obs tail --fleet``, ``obs summarize --fleet`` and an autoscale
  controller all consume.

See docs/OBSERVABILITY.md for instrument/span naming conventions.
"""

from .diff import diff_runs, render_diff  # noqa: F401
from .export import (  # noqa: F401
    build_fleet_trace,
    build_trace,
    export_fleet_trace,
    export_trace,
    validate_trace,
)
from .signals import RollingWindow, SignalBus  # noqa: F401
from .metrics import (  # noqa: F401
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    exponential_buckets,
    percentile,
)
from .report import render_report, summarize  # noqa: F401
from .slo import AlertingWriter, SloEngine, check_run, load_rules  # noqa: F401
from .tail import JsonlFollower, TailState, tail  # noqa: F401
from .sinks import (  # noqa: F401
    JsonlSink,
    MemorySink,
    render_prometheus,
    write_prometheus,
)
from .trace import (  # noqa: F401
    Tracer,
    configured,
    get_tracer,
    obs_enabled,
    set_enabled,
    span,
)

__all__ = [
    "AlertingWriter",
    "JsonlFollower",
    "RollingWindow",
    "SignalBus",
    "SloEngine",
    "TailState",
    "build_fleet_trace",
    "build_trace",
    "check_run",
    "diff_runs",
    "export_fleet_trace",
    "export_trace",
    "load_rules",
    "render_diff",
    "tail",
    "validate_trace",
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "exponential_buckets",
    "percentile",
    "JsonlSink",
    "MemorySink",
    "render_prometheus",
    "write_prometheus",
    "render_report",
    "summarize",
    "Tracer",
    "configured",
    "get_tracer",
    "obs_enabled",
    "set_enabled",
    "span",
]
