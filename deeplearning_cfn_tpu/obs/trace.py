"""Low-overhead span tracer.

``with span("ckpt.save", step=N):`` brackets one operation and produces a
span record when it closes:

    {"span": "ckpt.save", "span_id": 7, "parent_id": 3,
     "t0_s": 1.0234, "dur_s": 0.112, "ok": true, "step": 400}

Design constraints, in order:

- **Deterministic ids.** ``span_id`` is a process-local monotonic counter
  — never wall-clock, never random — so two runs of the same code produce
  the same id sequence and tests can assert on it.
- **Monotonic clock.** ``t0_s`` is seconds since the tracer was created
  (``time.monotonic`` deltas); durations cannot go negative across NTP
  steps.
- **Nesting.** A thread-local stack links children to parents
  (``parent_id``); concurrent threads (checkpoint async writer, serve
  admission) each get their own stack, so cross-thread spans never
  corrupt each other's lineage.
- **Near-zero cost when off.** ``DLCFN_OBS_OFF=1`` (or ``set_enabled(False)``)
  makes ``span(...)`` return a shared no-op context manager: no clock
  read, no allocation beyond the call itself. The train hot loop pays
  one truthiness check.

Span durations also feed a per-name :class:`~.metrics.Histogram`
(``span_dur_s{name=...}``) in the tracer's registry, so ``obs summarize``
and the Prometheus snapshot see latency distributions without re-parsing
the JSONL stream.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional

from .metrics import MetricsRegistry

_OFF_ENV = "DLCFN_OBS_OFF"


def obs_enabled() -> bool:
    """Env gate, read per call so subprocess workers and in-process bench
    toggles both behave; `set_enabled` overrides it."""
    if _FORCED is not None:
        return _FORCED
    return os.environ.get(_OFF_ENV, "") != "1"


_FORCED: Optional[bool] = None


def set_enabled(on: Optional[bool]) -> None:
    """Programmatic override of the env gate (None restores env control).
    The bench overhead smoke flips this to measure on-vs-off in one
    process."""
    global _FORCED
    _FORCED = on


class _NullSpan:
    """Shared do-nothing context manager for the disabled path."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def annotate(self, **attrs) -> None:
        pass


_NULL_SPAN = _NullSpan()


class _Span:
    __slots__ = ("_tracer", "name", "span_id", "parent_id", "_t0",
                 "attrs")

    def __init__(self, tracer: "Tracer", name: str, span_id: int,
                 parent_id: Optional[int], attrs: Dict):
        self._tracer = tracer
        self.name = name
        self.span_id = span_id
        self.parent_id = parent_id
        self.attrs = attrs
        self._t0 = time.monotonic()

    def annotate(self, **attrs) -> None:
        """Attach attributes discovered mid-span (e.g. retry counts)."""
        self.attrs.update(attrs)

    def __enter__(self):
        self._tracer._push(self)
        return self

    def __exit__(self, exc_type, exc, tb):
        dur = time.monotonic() - self._t0
        self._tracer._pop(self, dur, ok=exc_type is None)
        return False


class Tracer:
    """Owns the id counter, the per-thread span stacks, the sinks, and a
    :class:`MetricsRegistry` fed with span durations."""

    def __init__(self, registry: Optional[MetricsRegistry] = None):
        self.registry = registry or MetricsRegistry()
        self._sinks: List = []
        self._next_id = 1
        self._id_lock = threading.Lock()
        self._local = threading.local()
        self._epoch = time.monotonic()
        self._dur_hist = self.registry.histogram(
            "span_dur_s", "span durations by name")

    # -- configuration -----------------------------------------------------

    def add_sink(self, sink) -> None:
        """``sink`` is anything with ``write(record: dict)``."""
        self._sinks.append(sink)

    def remove_sink(self, sink) -> None:
        try:
            self._sinks.remove(sink)
        except ValueError:
            pass

    # -- span lifecycle ----------------------------------------------------

    def span(self, name: str, **attrs):
        if not obs_enabled():
            return _NULL_SPAN
        with self._id_lock:
            sid = self._next_id
            self._next_id += 1
        stack = self._stack()
        parent = stack[-1].span_id if stack else None
        return _Span(self, name, sid, parent, attrs)

    def record_span(self, name: str, t0_monotonic: float, dur_s: float,
                    parent_id: Optional[int] = None, ok: bool = True,
                    **attrs) -> Optional[int]:
        """Emit a span retroactively from timestamps the caller already
        holds (``time.monotonic`` values on this tracer's clock). The
        serving engine uses this for per-request lifecycle spans — a
        request's queue wait and decode phases are only known at finish,
        long after a ``with span(...)`` block could have bracketed them.

        Returns the allocated span_id (so callers can parent children on
        it), or None when tracing is disabled."""
        if not obs_enabled():
            return None
        with self._id_lock:
            sid = self._next_id
            self._next_id += 1
        dur_s = max(float(dur_s), 0.0)
        self._dur_hist.observe(dur_s, name=name)
        if self._sinks:
            record = {
                "span": name,
                "span_id": sid,
                "parent_id": parent_id,
                "t0_s": round(t0_monotonic - self._epoch, 6),
                "dur_s": round(dur_s, 6),
                "ok": ok,
                **attrs,
            }
            for sink in list(self._sinks):
                sink.write(record)
        return sid

    def _stack(self) -> List[_Span]:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    def _push(self, s: _Span) -> None:
        self._stack().append(s)

    def _pop(self, s: _Span, dur_s: float, ok: bool) -> None:
        stack = self._stack()
        if stack and stack[-1] is s:
            stack.pop()
        else:  # out-of-order exit (generator misuse); drop if present
            try:
                stack.remove(s)
            except ValueError:
                pass
        self._dur_hist.observe(dur_s, name=s.name)
        if not self._sinks:
            return
        record = {
            "span": s.name,
            "span_id": s.span_id,
            "parent_id": s.parent_id,
            "t0_s": round(s._t0 - self._epoch, 6),
            "dur_s": round(dur_s, 6),
            "ok": ok,
            **s.attrs,
        }
        for sink in list(self._sinks):
            sink.write(record)


_DEFAULT: Optional[Tracer] = None
_DEFAULT_LOCK = threading.Lock()


def get_tracer() -> Tracer:
    """The process-wide default tracer (created on first use)."""
    global _DEFAULT
    if _DEFAULT is None:
        with _DEFAULT_LOCK:
            if _DEFAULT is None:
                _DEFAULT = Tracer()
    return _DEFAULT


def configured(tracer: Optional[Tracer]) -> None:
    """Swap the process default — tests install a fresh tracer so span ids
    restart at 1 and sinks don't leak across cases."""
    global _DEFAULT
    _DEFAULT = tracer


def span(name: str, **attrs):
    """Module-level convenience over the default tracer — the call sites
    in trainer/ckpt/serve/launcher all use this."""
    if not obs_enabled():
        return _NULL_SPAN
    return get_tracer().span(name, **attrs)
