"""Cross-run regression diff: ``dlcfn-tpu obs diff <run_a> <run_b>``.

Aligns the metric series two runs share and reports per-metric p50/p95
deltas, flagging **regressions** — deltas in the bad direction beyond a
relative tolerance. Direction is metric-aware: throughputs
(``*_per_sec``, tokens/sec) regress when they fall, times/latencies
(``*_s`` series, span durations) and loss regress when they rise;
anything else is reported informationally and never gates. Comparing a
run against itself yields zero deltas and no regressions by construction
— the tier-1 self-diff smoke pins that.

The same comparator gates bench records: root ``bench.py`` calls
:func:`diff_bench_records` when ``DLCFN_BENCH_DIFF_AGAINST`` points at a
prior contract JSON, attaching the verdict to the new record.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional

from .metrics import percentile
from .report import collect

DEFAULT_TOLERANCE = 0.10

# Metrics where larger is better (everything matching LOWER_SUFFIXES is
# smaller-is-better; the rest is informational).
_HIGHER = ("examples_per_sec", "serve_tokens_per_sec", "value", "mfu",
           "serve_slot_occupancy", "serve_steps_per_window",
           "serve_prefix_hit_rate")
_LOWER = ("loss", "mean_step_s", "compile_s")
_LOWER_SUFFIXES = ("_time_s", "_wait_s", "_latency_s", "_ttft_s",
                   "_dur_s", "_step_s", "_p50_s", "_p95_s")


def direction(metric: str) -> Optional[str]:
    """'higher' | 'lower' (better) | None (informational)."""
    base = metric.split(":", 1)[-1]
    if base in _HIGHER or base.endswith("_per_sec"):
        return "higher"
    if base in _LOWER or base.endswith(_LOWER_SUFFIXES):
        return "lower"
    if base.startswith("span:"):
        return "lower"
    if metric.startswith("span:"):
        return "lower"
    return None


def run_series(records: List[Dict[str, Any]]) -> Dict[str, List[float]]:
    """Extract the comparable series from one run's records:

    - train series: ``step_time_s``, ``examples_per_sec``, ``loss``,
      ``compile_s`` from step records;
    - span durations: ``span:<name>`` per span name;
    - serve counters: every numeric ``serve_*`` key from the LAST
      snapshot (cumulative snapshots — the last one is the run total).
    """
    out: Dict[str, List[float]] = {}

    def _num(v: Any) -> bool:
        return isinstance(v, (int, float)) and not isinstance(v, bool)

    last_serve: Optional[Dict[str, Any]] = None
    for r in records:
        if "span" in r:
            if _num(r.get("dur_s")):
                out.setdefault(f"span:{r['span']}", []).append(
                    float(r["dur_s"]))
            continue
        if any(k.startswith("serve_") for k in r):
            last_serve = r
            continue
        for key in ("step_time_s", "examples_per_sec", "loss",
                    "compile_s"):
            if _num(r.get(key)):
                out.setdefault(key, []).append(float(r[key]))
    if last_serve is not None:
        for k, v in last_serve.items():
            if k.startswith("serve_") and _num(v):
                out.setdefault(k, []).append(float(v))
    return out


def _stats(xs: List[float]) -> Dict[str, Optional[float]]:
    return {"n": len(xs), "p50": percentile(xs, 50),
            "p95": percentile(xs, 95)}


def _rel(a: Optional[float], b: Optional[float]) -> Optional[float]:
    if a is None or b is None:
        return None
    if a == b:
        return 0.0
    if a == 0:
        return None
    return (b - a) / abs(a)


def diff_runs(path_a: str, path_b: str,
              tolerance: float = DEFAULT_TOLERANCE) -> Dict[str, Any]:
    """Full report dict for two recorded runs (files or directories)."""
    recs_a, _, _ = collect(path_a)
    recs_b, _, _ = collect(path_b)
    series_a = run_series(recs_a)
    series_b = run_series(recs_b)
    metrics: Dict[str, Dict[str, Any]] = {}
    regressions: List[str] = []
    for name in sorted(set(series_a) & set(series_b)):
        a, b = _stats(series_a[name]), _stats(series_b[name])
        rel50, rel95 = _rel(a["p50"], b["p50"]), _rel(a["p95"], b["p95"])
        d = direction(name)
        regressed = False
        if d == "lower":
            regressed = any(r is not None and r > tolerance
                            for r in (rel50, rel95))
        elif d == "higher":
            regressed = any(r is not None and r < -tolerance
                            for r in (rel50, rel95))
        metrics[name] = {
            "a": a, "b": b,
            "delta_p50": (None if a["p50"] is None or b["p50"] is None
                          else b["p50"] - a["p50"]),
            "delta_p95": (None if a["p95"] is None or b["p95"] is None
                          else b["p95"] - a["p95"]),
            "rel_p50": rel50, "rel_p95": rel95,
            "direction": d, "regressed": regressed,
        }
        if regressed:
            regressions.append(name)
    return {
        "run_a": path_a, "run_b": path_b, "tolerance": tolerance,
        "common_metrics": len(metrics),
        "only_a": sorted(set(series_a) - set(series_b)),
        "only_b": sorted(set(series_b) - set(series_a)),
        "metrics": metrics,
        "regressions": regressions,
        "ok": not regressions,
    }


def render_diff(report: Dict[str, Any]) -> str:
    """Human-readable rendering of :func:`diff_runs` output."""
    L: List[str] = []
    L.append(f"run diff: {report['run_a']}  vs  {report['run_b']}  "
             f"(tolerance {report['tolerance'] * 100:g}%)")

    def _f(v: Optional[float]) -> str:
        if v is None:
            return "-"
        return f"{v:.4g}"

    def _p(v: Optional[float]) -> str:
        if v is None:
            return "-"
        return f"{v * 100:+.1f}%"

    for name, m in report["metrics"].items():
        mark = "  << REGRESSED" if m["regressed"] else ""
        L.append(f"  {name:<28} p50 {_f(m['a']['p50'])} -> "
                 f"{_f(m['b']['p50'])} ({_p(m['rel_p50'])})   "
                 f"p95 {_f(m['a']['p95'])} -> {_f(m['b']['p95'])} "
                 f"({_p(m['rel_p95'])}){mark}")
    if report["only_a"]:
        L.append(f"  only in A: {', '.join(report['only_a'])}")
    if report["only_b"]:
        L.append(f"  only in B: {', '.join(report['only_b'])}")
    if not report["metrics"]:
        L.append("  (no common metric series)")
    L.append(f"regressions: {len(report['regressions'])}"
             + (f" ({', '.join(report['regressions'])})"
                if report["regressions"] else ""))
    return "\n".join(L)


# -- bench record gating -----------------------------------------------------

_BENCH_KEYS = ("value", "mean_step_s", "mfu", "value_with_input",
               "mean_step_s_with_input")


def diff_bench_records(prior: Dict[str, Any], current: Dict[str, Any],
                       tolerance: float = DEFAULT_TOLERANCE
                       ) -> Dict[str, Any]:
    """Compare two bench contract records key-by-key; same direction
    rules as the run diff. Unmeasured records never gate."""
    out: Dict[str, Any] = {"tolerance": tolerance, "regressions": [],
                           "metrics": {}}
    if not prior.get("measured", True) or not current.get("measured",
                                                          True):
        out["skipped"] = "one of the records is measured=false"
        out["ok"] = True
        return out
    for key in _BENCH_KEYS:
        a, b = prior.get(key), current.get(key)
        if not isinstance(a, (int, float)) or isinstance(a, bool) \
                or not isinstance(b, (int, float)) or isinstance(b, bool):
            continue
        rel = _rel(float(a), float(b))
        d = direction(key)
        regressed = (rel is not None
                     and ((d == "lower" and rel > tolerance)
                          or (d == "higher" and rel < -tolerance)))
        out["metrics"][key] = {"prior": a, "current": b, "rel": rel,
                               "direction": d, "regressed": regressed}
        if regressed:
            out["regressions"].append(key)
    out["ok"] = not out["regressions"]
    return out


def load_bench_record(path: str) -> Optional[Dict[str, Any]]:
    """Read a prior bench contract record: a JSON file holding one
    record, or a JSONL file whose last parseable line with a "metric"
    key wins."""
    if not os.path.exists(path):
        return None
    with open(path) as fh:
        text = fh.read()
    try:
        doc = json.loads(text)
        if isinstance(doc, dict):
            return doc
    except json.JSONDecodeError:
        pass
    for line in reversed(text.strip().splitlines()):
        try:
            doc = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(doc, dict) and "metric" in doc:
            return doc
    return None
